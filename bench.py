"""Benchmark driver — prints ONE JSON line with the headline metric.

Metric (BASELINE.json:2): dense block-MatMul TFLOPS/chip, measured on the
4k×4k BlockMatrix multiply config (BASELINE.md row 1) through the full
framework stack (BlockMatrix → IR → planner → jitted strategy).

vs_baseline: ratio against the self-measured CPU reference (numpy BLAS on
this host, standing in for the reference's local[*] Spark config —
BASELINE.md "the build must fill in the CPU reference itself"). The CPU
number is measured once and cached in cpu_baseline.json.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

N = 4096
DTYPE = "bfloat16"
REPEATS = 40
CPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "cpu_baseline.json")


def flops(n: int) -> float:
    return 2.0 * n * n * n


def measure_cpu_baseline() -> float:
    """numpy (BLAS) matmul TFLOPS on this host — the local[*] stand-in."""
    a = np.random.default_rng(0).standard_normal((N, N)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((N, N)).astype(np.float32)
    a @ b  # warm up BLAS threads
    t0 = time.perf_counter()
    a @ b
    dt = time.perf_counter() - t0
    return flops(N) / dt / 1e12


def cpu_baseline() -> float:
    if os.path.exists(CPU_CACHE):
        with open(CPU_CACHE) as f:
            return json.load(f)["tflops"]
    v = measure_cpu_baseline()
    with open(CPU_CACHE, "w") as f:
        json.dump({"tflops": v, "n": N, "dtype": "float32"}, f)
    return v


def measure_tpu() -> float:
    """Marginal per-multiply time through the framework's compiled plan.

    The axon relay acks dispatches before execution completes
    (block_until_ready is unreliable), so: chain each multiply on the
    previous result (real data dependency), force completion with a scalar
    fetch, and take the MARGINAL time between two repeat counts to cancel
    the fixed relay round-trip latency (~60ms on this host).
    """
    import jax
    import jax.numpy as jnp
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.executor import compile_expr

    set_default_config(MatrelConfig())
    mesh = mesh_lib.make_mesh()
    A = BlockMatrix.random((N, N), mesh=mesh, seed=0, dtype=DTYPE)
    B = BlockMatrix.random((N, N), mesh=mesh, seed=1, dtype=DTYPE)
    plan = compile_expr(A.expr().multiply(B.expr()), mesh)
    a_leaf = plan.leaf_order[0]
    # bound_runner: the framework's iterative-execution fast path (leaf
    # layout resolved once; raw padded arrays in/out)
    step = plan.bound_runner(rebind_uids=(a_leaf.uid,))
    fetch = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    def chained(reps: int) -> float:
        # keep_input_dtype keeps the chain bf16×bf16 with f32 accumulation
        cur = step(A.data)  # C = A·B
        for _ in range(reps - 1):
            cur = step(cur)  # C ← C·B
        np.asarray(fetch(cur))
        return 0.0

    chained(2)  # warm both programs
    lo, hi = 5, 5 + REPEATS
    dts = []
    for _ in range(5):
        t0 = time.perf_counter()
        chained(lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        chained(hi)
        t_hi = time.perf_counter() - t0
        dts.append(max((t_hi - t_lo) / (hi - lo), 1e-9))
    dt = sorted(dts)[len(dts) // 2]
    n_chips = max(1, len(mesh.devices.ravel()))
    return flops(N) / dt / 1e12 / n_chips


def main() -> None:
    base = cpu_baseline()
    tpu = measure_tpu()
    print(json.dumps({
        "metric": "dense_blockmatmul_tflops_per_chip",
        "value": round(tpu, 3),
        "unit": "TFLOPS",
        "vs_baseline": round(tpu / base, 2),
    }))


if __name__ == "__main__":
    main()
