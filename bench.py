"""Benchmark driver — prints ONE JSON line with the headline metric.

Metric (BASELINE.json:2): dense block-MatMul TFLOPS/chip, measured on the
4k×4k BlockMatrix multiply config (BASELINE.md row 1) through the full
framework stack (BlockMatrix → IR → planner → jitted strategy).

vs_baseline: ratio against the self-measured CPU reference (numpy BLAS on
this host, standing in for the reference's local[*] Spark config —
BASELINE.md "the build must fill in the CPU reference itself"). The CPU
number is measured once and cached in cpu_baseline.json.

Resilience (round-2 hardening): the axon relay is known to wedge — backend
init can raise UNAVAILABLE *or* hang for 30+ minutes (docs/INTERNALS.md).
So the TPU work runs in SUBPROCESSES under hard timeouts:
  1. a tiny probe matmul (fast fail/hang detection),
  2. the real measurement,
with bounded retries + backoff between attempts. On final failure this
script still prints ONE parseable JSON line ({"value": null, "error": ...,
"last_known_good": ...}) and exits 0, instead of a stack trace with rc=1.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


N = _env_int("MATREL_BENCH_N", 4096)
DTYPE = "bfloat16"
REPEATS = _env_int("MATREL_BENCH_REPEATS", 40)
_HERE = os.path.dirname(os.path.abspath(__file__))
# path overrides exist for the dry-batch fire-drill (tools/tpu_batch.sh
# --dry): a toy-scale CPU run must not clobber the real CPU baseline or
# the last-known-good on-chip record
CPU_CACHE = os.environ.get("MATREL_BENCH_CPU_CACHE",
                           os.path.join(_HERE, "cpu_baseline.json"))
LAST_GOOD = os.environ.get("MATREL_BENCH_LAST_GOOD",
                           os.path.join(_HERE, "bench_last_good.json"))
# Weak #5 (round 5): sub-5-ms rows showed a 4.6x run-to-run band. For
# any per-multiply time under this threshold, measure_tpu RAISES the
# chained-rep count until the marginal-time band half-width is under
# BAND_TARGET of the median (or the escalation cap is hit) and records
# the interval in the bench JSON either way.
BAND_ROW_THRESHOLD_S = 5e-3
BAND_TARGET = 0.15
BAND_MAX_DOUBLINGS = _env_int("MATREL_BENCH_BAND_DOUBLINGS", 4)

PROBE_TIMEOUT_S = _env_int("MATREL_BENCH_PROBE_TIMEOUT", 180)
MEASURE_TIMEOUT_S = _env_int("MATREL_BENCH_MEASURE_TIMEOUT", 900)
# total wall-clock budget for the retry ladder: the structured error
# JSON must reach stdout BEFORE any outer (driver) timeout kills us —
# a full 4-attempt ladder with backoffs would otherwise take ~19 min
DEADLINE_S = _env_int("MATREL_BENCH_DEADLINE", 540)
# sleeps between the 4 attempts; relay wedges clear on their own eventually
try:
    BACKOFFS_S = tuple(
        int(x) for x in
        os.environ.get("MATREL_BENCH_BACKOFFS", "60,120,240").split(",")
        if x.strip())
except ValueError:
    BACKOFFS_S = (60, 120, 240)


def flops(n: int) -> float:
    return 2.0 * n * n * n


def bf16_safe_chain_step(A, B):
    """The ONE overflow-guarded chained bench step, shared by every row
    that feeds a product back into the next multiply (the headline row,
    the --precision tier rows): (C·B)·(2/N), NOT C·B.

    With uniform[0,1) entries the bare product grows ~N/2× per multiply
    (Perron eigenvalue N·mean), overflowing bf16 to inf well before the
    45th repeat and turning the forced fetch into nan (round-2 VERDICT
    weakness 4). The rescale fuses into the matmul epilogue (N² FLOPs
    vs 2N³ — timing unaffected) and makes the step's dominant
    eigenvalue 2·mean(B) ≈ 1, so the chain converges along the Perron
    direction with O(1) entries and the fetch doubles as a correctness
    canary (``check_chain_canary``). A and B are BlockMatrix; B must be
    square (the chain feeds C back in as A)."""
    n = B.shape[0]
    return A.expr().multiply(B.expr()).multiply_scalar(2.0 / n)


def check_chain_canary(canary) -> None:
    """The guard's other half: mean|entry| of the final chain product
    must be finite and O(1). inf/nan (overflow, garbage results) or a
    collapsed/exploded scale means the multiply chain computed wrong
    values and the timing is meaningless — fail the measure child
    loudly so the harness reports a structured error, not a silent
    wrong number."""
    if not (np.isfinite(canary) and 1e-3 < canary < 1e3):
        raise RuntimeError(
            f"chain correctness canary out of band: mean|C| = {canary!r}")


def measure_cpu_baseline() -> float:
    """numpy (BLAS) matmul TFLOPS on this host — the local[*] stand-in."""
    a = np.random.default_rng(0).standard_normal((N, N)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((N, N)).astype(np.float32)
    a @ b  # warm up BLAS threads
    t0 = time.perf_counter()
    a @ b
    dt = time.perf_counter() - t0
    return flops(N) / dt / 1e12


def cpu_baseline() -> float:
    try:
        with open(CPU_CACHE) as f:
            cached = json.load(f)
        if cached.get("n") == N:
            return float(cached["tflops"])
    except (OSError, ValueError, KeyError, TypeError):
        pass  # missing/corrupt/mismatched cache → re-measure
    v = measure_cpu_baseline()
    try:
        tmp = CPU_CACHE + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"tflops": v, "n": N, "dtype": "float32"}, f)
        os.replace(tmp, CPU_CACHE)
    except OSError:
        pass
    return v


def probe_tpu() -> None:
    """Tiny matmul proving the backend is alive. Raises on failure."""
    import jax
    import jax.numpy as jnp
    del jax  # imported for backend registration side effect
    x = jnp.ones((256, 256), dtype=jnp.bfloat16)
    val = float(jnp.sum((x @ x).astype(jnp.float32)))
    assert abs(val - 256.0 ** 3) < 1e-3 * 256.0 ** 3, val


def measure_tpu() -> dict:
    """Marginal per-multiply time through the framework's compiled plan.
    Returns ``{"tflops": float, "phases": {...}}`` — per-phase
    wall-clock for the obs/ bench event.

    The axon relay acks dispatches before execution completes
    (block_until_ready is unreliable), so: chain each multiply on the
    previous result (real data dependency), force completion with a scalar
    fetch, and take the MARGINAL time between two repeat counts to cancel
    the fixed relay round-trip latency (~60ms on this host).
    """
    import jax
    import jax.numpy as jnp
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.executor import compile_expr

    # obs_level="off" is the bench contract: the query hot path must
    # carry zero instrumentation syncs. Phase timings below are taken
    # by THIS harness around whole phases, not inside them.
    set_default_config(MatrelConfig(obs_level="off"))
    phases: dict = {}
    t_phase = time.perf_counter()
    mesh = mesh_lib.make_mesh()
    A = BlockMatrix.random((N, N), mesh=mesh, seed=0, dtype=DTYPE)
    B = BlockMatrix.random((N, N), mesh=mesh, seed=1, dtype=DTYPE)
    phases["setup_s"] = round(time.perf_counter() - t_phase, 3)
    # the ONE overflow-guarded chained step (bf16_safe_chain_step):
    # rescaled so repeated accumulation cannot overflow bf16 to inf
    step_expr = bf16_safe_chain_step(A, B)
    t_phase = time.perf_counter()
    plan = compile_expr(step_expr, mesh)
    a_leaf = plan.leaf_order[0]
    # bound_runner: the framework's iterative-execution fast path (leaf
    # layout resolved once; raw padded arrays in/out)
    step = plan.bound_runner(rebind_uids=(a_leaf.uid,))
    fetch = jax.jit(lambda x: jnp.mean(jnp.abs(x.astype(jnp.float32))))
    phases["compile_s"] = round(time.perf_counter() - t_phase, 3)
    phases["optimize_ms"] = (plan.meta or {}).get("optimize_ms")

    def chained(reps: int) -> float:
        cur = step(A.data)  # C = A·B·(2/N)
        for _ in range(reps - 1):
            cur = step(cur)  # C ← C·B·(2/N)
        return float(np.asarray(fetch(cur)))

    t_phase = time.perf_counter()
    chained(2)  # warm both programs
    phases["warmup_s"] = round(time.perf_counter() - t_phase, 3)
    t_phase = time.perf_counter()
    reps = REPEATS
    escalations = 0
    while True:
        lo, hi = 5, 5 + reps
        dts = []
        canary = None
        for _ in range(5):
            t0 = time.perf_counter()
            chained(lo)
            t_lo = time.perf_counter() - t0
            t0 = time.perf_counter()
            canary = chained(hi)
            t_hi = time.perf_counter() - t0
            dts.append(max((t_hi - t_lo) / (hi - lo), 1e-9))
        dt = sorted(dts)[len(dts) // 2]
        half_width = (max(dts) - min(dts)) / 2
        # latency-bound rows (sub-5-ms per multiply — BASELINE row 2
        # class, VERDICT r5 Weak #5) drown the marginal in dispatch
        # jitter: escalate the chained-rep count until the band
        # half-width is inside BAND_TARGET of the median, so
        # regressions at this size stop hiding in a 4.6x spread.
        # Bounded doublings: a noisy host must still report (with its
        # interval on record) rather than spin past the harness
        # deadline.
        if (dt >= BAND_ROW_THRESHOLD_S
                or half_width <= BAND_TARGET * dt
                or escalations >= BAND_MAX_DOUBLINGS):
            break
        reps *= 2
        escalations += 1
    check_chain_canary(canary)   # shared guard: see bf16_safe_chain_step
    phases["measure_s"] = round(time.perf_counter() - t_phase, 3)
    n_chips = max(1, len(mesh.devices.ravel()))
    interval = {
        "median_ms": round(dt * 1e3, 4),
        "half_width_ms": round(half_width * 1e3, 4),
        "half_width_frac": round(half_width / dt, 4),
        "reps": reps,
        "escalations": escalations,
        "band_target": BAND_TARGET,
    }
    return {"tflops": flops(N) / dt / 1e12 / n_chips, "phases": phases,
            "interval": interval}


def measure_spgemm() -> dict:
    """SpGEMM (S×S) bench row — the tile-intersection kernel at
    BASELINE row-4 scale (100k×100k, 1% block density, 512 tiles) plus
    the executor-dispatch crossover comparison vs the densify fallback
    at a reduced scale where the densified operand actually fits.

    Two measurements on purpose: at full scale the densify path's
    100k×100k dense intermediate (~20 GB bf16) exceeds a v5e chip's
    HBM — that infeasibility IS the headline win — so the full-scale
    number times the sparse-result kernel alone (``ops/spgemm.spgemm``,
    nothing dense ever materialises), and the dispatch-vs-densify
    ratio is taken at ``MATREL_SPGEMM_CMP_N`` where both paths run.
    Single-run medians with forced fetches (the sub-ms kernel is
    relay-latency-bound on chip — same caveat as BASELINE row 2)."""
    import jax
    import jax.numpy as jnp
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu import executor as executor_lib
    from matrel_tpu.ops import spgemm as spgemm_lib

    set_default_config(MatrelConfig(obs_level="off"))
    cfg = MatrelConfig(obs_level="off")
    mesh = mesh_lib.make_mesh()
    bs = 512
    n = _env_int("MATREL_SPGEMM_N", 100_352)          # 196 tile grid
    n_cmp = _env_int("MATREL_SPGEMM_CMP_N", 32_768)   # densify fits
    dtype = os.environ.get("MATREL_SPGEMM_DTYPE", "bfloat16")
    fetch = jax.jit(lambda t: jnp.sum(t.astype(jnp.float32)))

    def median_ms(fn, reps=5):
        return _median_s(fn, reps=reps) * 1e3   # warm once, median

    out: dict = {"block_size": bs, "dtype": dtype}
    # -- full scale: sparse-result kernel only --------------------------
    S1 = BlockSparseMatrix.random((n, n), block_density=0.01,
                                  block_size=bs, mesh=mesh, seed=0,
                                  dtype=dtype)
    S2 = BlockSparseMatrix.random((n, n), block_density=0.01,
                                  block_size=bs, mesh=mesh, seed=1,
                                  dtype=dtype)

    def run_full():
        C = spgemm_lib.spgemm(S1, S2, cfg)
        float(np.asarray(fetch(C.blocks)))

    out["n"] = n
    out["spgemm_full_ms"] = round(median_ms(run_full), 3)
    pairs = spgemm_lib.pair_structure(
        np.asarray(S1.block_rows), np.asarray(S1.block_cols),
        np.asarray(S2.block_rows), np.asarray(S2.block_cols),
        S2.grid[1])[0].size
    out["pairs"] = int(pairs)
    fl = 2.0 * pairs * bs ** 3
    out["effective_tflops"] = round(
        fl / (out["spgemm_full_ms"] / 1e3) / 1e12, 3)
    # -- reduced scale: executor dispatch vs densify fallback -----------
    T1 = BlockSparseMatrix.random((n_cmp, n_cmp), block_density=0.01,
                                  block_size=bs, mesh=mesh, seed=2,
                                  dtype=dtype)
    T2 = BlockSparseMatrix.random((n_cmp, n_cmp), block_density=0.01,
                                  block_size=bs, mesh=mesh, seed=3,
                                  dtype=dtype)
    expr = T1.multiply(T2)
    assert executor_lib._spgemm_dispatch(expr, cfg), \
        "comparison config must sit below the SpGEMM crossover"
    plan_sp = executor_lib.compile_expr(expr, mesh, cfg)
    cfg_dense = MatrelConfig(obs_level="off",
                             spgemm_density_threshold=0.0)
    plan_dn = executor_lib.compile_expr(T1.multiply(T2), mesh,
                                        cfg_dense)

    def run_plan(plan):
        def go():
            float(np.asarray(fetch(plan.run().data)))
        return go

    out["cmp_n"] = n_cmp
    out["cmp_spgemm_ms"] = round(median_ms(run_plan(plan_sp), reps=3), 3)
    out["cmp_densify_ms"] = round(median_ms(run_plan(plan_dn), reps=3),
                                  3)
    out["cmp_speedup"] = round(
        out["cmp_densify_ms"] / max(out["cmp_spgemm_ms"], 1e-9), 2)
    return out


def measure_sparse_kernels() -> dict:
    """Structure-specialized SpGEMM kernel sweep (ROADMAP item 5, the
    round-11 acceptance row): for each structure class, a synthetic
    operand pair EXHIBITING it (the registry's own generator, so the
    measured population is the one the classifier bins) is multiplied
    through every relevant registered kernel with the registry choice
    pinned, reporting per-kernel ms median + half-width against the
    pre-registry fixed Pallas kernel (``pallas_generic``) as baseline.
    CPU interpret mode is acceptable (the wedge-safe dry harness): the
    grouped variants' grid-step reduction shows in interpret wall
    clock just as on-chip. The row also closes the autotune loop
    in-process: the winner for one (shape, structure) class is
    measured, PERSISTED, the in-process caches dropped, and the
    persisted winner replayed — the cross-session proof."""
    import tempfile
    import jax
    import jax.numpy as jnp
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.ops import kernel_registry as kr
    from matrel_tpu.ops import spgemm as spgemm_lib
    from matrel_tpu.parallel import autotune

    n = _env_int("MATREL_SPK_N", 100_352)
    bs = _env_int("MATREL_SPK_BS", 512)
    reps = _env_int("MATREL_SPK_REPEATS", 5)
    interp = jax.default_backend() not in ("tpu", "axon")
    cfg = MatrelConfig(obs_level="off", pallas_interpret=interp)
    set_default_config(cfg)
    mesh = mesh_lib.make_mesh()
    fetch = jax.jit(lambda t: jnp.sum(t.astype(jnp.float32)))

    def timed(fn) -> dict:
        fn()                                   # compile + warm
        ts = []
        for _ in range(max(reps, 2)):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med = ts[len(ts) // 2]
        return {"ms": round(med * 1e3, 3),
                "half_width_ms": round((ts[-1] - ts[0]) / 2 * 1e3, 3)}

    rows = []
    best_speedup = 0.0
    for structure in ("row_band", "clustered_tile", "powerlaw_coo"):
        A = kr.synthesize_structure(structure, n, bs, mesh, seed=0)
        B = kr.synthesize_structure(structure, n, bs, mesh, seed=1)
        npairs = int(spgemm_lib._pair_structure_cached(A, B)[0].size)
        kernels: dict = {}
        for kid in kr.kernel_ids():
            spec = kr.get_kernel(kid)
            if not (spec.universal or structure in spec.structures):
                continue
            if not kr.admissible(kid, bs, npairs, cfg):
                continue

            def go(_k=kid):
                tiles, _, _ = spgemm_lib.spgemm_tiles(A, B, cfg,
                                                      kernel=_k)
                float(np.asarray(fetch(tiles)))

            kernels[kid] = timed(go)
        base = kernels.get("pallas_generic", {}).get("ms")
        specialized = next(
            (kid for kid in kernels
             if structure in kr.get_kernel(kid).structures), None)
        speedup = None
        if base and specialized and kernels[specialized]["ms"] > 0:
            speedup = round(base / kernels[specialized]["ms"], 2)
            best_speedup = max(best_speedup, speedup)
        rows.append({
            "structure": structure,
            "classified": kr.structure_of_matrix(A),
            "n": A.shape[0], "bs": bs, "nnzb": A.nnzb,
            "pairs": npairs, "kernels": kernels,
            "specialized": specialized,
            "speedup_vs_generic": speedup,
        })

    # autotune persist + replay across "sessions" (fresh caches) — a
    # bounded probe side so the loop also runs at flagship-n configs
    aside = min(n, _env_int("MATREL_SPK_AUTOTUNE_SIDE", 2048))
    table = os.environ.get("MATREL_SPK_TABLE", "") or os.path.join(
        tempfile.gettempdir(), f"matrel_spk_autotune_{os.getpid()}.json")
    acfg = cfg.replace(autotune=True, autotune_table_path=table)
    winner = autotune.lookup_or_measure_spgemm(aside, "row_band", bs,
                                               mesh, acfg)
    key = autotune._spgemm_key(
        aside, "row_band", bs, *mesh_lib.mesh_grid_shape(mesh),
        mesh_lib.axis_weights(mesh, acfg))
    persisted = key in autotune.load_table(table)
    autotune._SPGEMM_CACHE.clear()
    autotune._TABLE_CACHE.clear()
    replay = autotune.lookup_or_measure_spgemm(aside, "row_band", bs,
                                               mesh, acfg)
    classified_ok = all(r["classified"] == r["structure"] for r in rows)
    return {
        "n": n, "bs": bs, "repeats": reps,
        "backend": jax.default_backend(), "interpret": interp,
        "baseline_kernel": "pallas_generic",
        "rows": rows, "best_speedup": round(best_speedup, 2),
        "autotune": {"side": aside, "winner": winner,
                     "persisted": persisted,
                     "replayed": replay == winner, "key": key},
        "ok": (classified_ok and best_speedup >= 1.3
               and persisted and replay == winner),
    }


def measure_fusion() -> dict:
    """Whole-plan fusion sweep (ROADMAP item 3, the round-12
    acceptance row): the PageRank-step and linreg-epilogue chains
    emitted BOTH ways through the executor's unit-program seam —
    ``compile_staged_units`` (one jitted program per physical op: a
    dispatch and an HBM round-trip per plan edge, the per-op floor)
    vs ``compile_region_units`` (one jitted program per fused region —
    XLA sees the whole segment). Reports ms median + half-width and
    the DISPATCH COUNTS for both forms per chain; the acceptance
    number is fused >= 1.3x over staged at bench scale with the
    dispatch count reduced. CPU backend is acceptable (the wedge-safe
    dry harness): the win IS the per-edge dispatch + HBM round-trip
    elimination, which the CPU pays like the TPU does. Outputs of the
    two forms are asserted equal (same member lowerings, one program
    boundary apart)."""
    import jax
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu import executor as executor_lib
    from matrel_tpu.ir import fusion as fusion_lib

    n = _env_int("MATREL_FUSION_N", 512)
    k = _env_int("MATREL_FUSION_K", 128)
    reps = _env_int("MATREL_FUSION_REPEATS", 9)
    inner = _env_int("MATREL_FUSION_INNER", 8)
    cfg_off = MatrelConfig(obs_level="off")
    cfg_on = cfg_off.replace(fusion_enable=True)
    set_default_config(cfg_off)
    mesh = mesh_lib.make_mesh()
    rng = np.random.default_rng(0)

    def timed(units) -> dict:
        """Median ms per EXECUTION over ``reps`` samples of ``inner``
        back-to-back runs each (amortises per-sample host jitter on a
        shared box — the per-program dispatch cost under measure is
        paid identically in every inner run)."""
        import jax

        def sample():
            out = None
            for _ in range(max(inner, 1)):
                out = units.run()
            jax.block_until_ready(out)

        sample()                               # compile + warm
        ts = []
        for _ in range(max(reps, 2)):
            t0 = time.perf_counter()
            sample()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        scale = 1e3 / max(inner, 1)
        return {"ms": round(ts[len(ts) // 2] * scale, 3),
                "half_width_ms": round((ts[-1] - ts[0]) / 2 * scale,
                                       3)}

    def pagerank_step_expr():
        # r' = α·(Âᵀ·(w∘r) + 1·(dangling·r)/n) + (1-α)/n — the whole
        # per-round update as ONE fusable region anchored on the
        # matvec (prologue w∘r below the anchor, epilogue above)
        a = rng.random((n, n), dtype=np.float32)
        r = rng.random((n, 1), dtype=np.float32)
        w = rng.random((n, 1), dtype=np.float32)
        dang = (rng.random((n, 1)) < 0.05).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh)
        R = BlockMatrix.from_numpy(r, mesh=mesh)
        W = BlockMatrix.from_numpy(w, mesh=mesh)
        D = BlockMatrix.from_numpy(dang, mesh=mesh)
        alpha = 0.85
        contrib = A.expr().t().multiply(
            W.expr().elem_multiply(R.expr()))
        dmass = D.expr().elem_multiply(R.expr()).sum() \
            .multiply_scalar(1.0 / n)
        return contrib.add(dmass).multiply_scalar(alpha) \
            .add_scalar((1.0 - alpha) / n)

    def linreg_epilogue_expr():
        # ridge-normalised Gram + row-mean diagnostic:
        # rowsum((XᵀX)·(1/n) + λ·I)·(1/k) — the BASELINE row-3
        # epilogue chain fused into the producing contraction
        x = rng.random((n, k), dtype=np.float32)
        eye = np.eye(k, dtype=np.float32)
        X = BlockMatrix.from_numpy(x, mesh=mesh)
        I = BlockMatrix.from_numpy(eye, mesh=mesh)
        return X.expr().t().multiply(X.expr()) \
            .multiply_scalar(1.0 / n) \
            .add(I.expr().multiply_scalar(0.1)) \
            .row_sum().multiply_scalar(1.0 / k)

    rows = []
    all_ok = True
    for name, make in (("pagerank_step", pagerank_step_expr),
                       ("linreg_epilogue", linreg_epilogue_expr)):
        e = make()
        staged = executor_lib.compile_staged_units(e, mesh, cfg_off)
        fused = executor_lib.compile_region_units(e, mesh, cfg_on)
        regions = sum(1 for _n, _f, _i, nm in fused.units if nm > 1)
        got_s = np.asarray(jax.block_until_ready(staged.run()))
        got_f = np.asarray(jax.block_until_ready(fused.run()))
        scale = max(float(np.abs(got_s).max()), 1.0)
        agree = bool(np.allclose(got_f / scale, got_s / scale,
                                 atol=1e-5))
        t_staged = timed(staged)
        t_fused = timed(fused)
        speedup = (round(t_staged["ms"] / t_fused["ms"], 2)
                   if t_fused["ms"] > 0 else None)
        ok = (agree and speedup is not None and speedup >= 1.3
              and fused.dispatches < staged.dispatches)
        all_ok = all_ok and ok
        rows.append({
            "chain": name,
            "staged_ms": t_staged["ms"],
            "staged_half_width_ms": t_staged["half_width_ms"],
            "fused_ms": t_fused["ms"],
            "fused_half_width_ms": t_fused["half_width_ms"],
            "staged_dispatches": staged.dispatches,
            "fused_dispatches": fused.dispatches,
            "regions": regions,
            "speedup": speedup,
            "outputs_agree": agree,
            "ok": ok,
        })
    # the default-path contract rides the row: fusion off constructs
    # ZERO region objects and MV111 is quiet on a fresh fused plan
    before = fusion_lib._CONSTRUCTED["count"]
    executor_lib.compile_expr(linreg_epilogue_expr(), mesh, cfg_off)
    off_clean = fusion_lib._CONSTRUCTED["count"] == before
    from matrel_tpu import analysis
    plan_on = executor_lib.compile_expr(linreg_epilogue_expr(), mesh,
                                        cfg_on)
    mv111 = [d.render() for d in analysis.verify_plan(
        plan_on.optimized, mesh, cfg_on) if d.code == "MV111"]
    return {"n": n, "k": k, "repeats": reps,
            "backend": jax.default_backend(),
            "rows": rows,
            "off_constructs_nothing": off_clean,
            "mv111_quiet": not mv111,
            "mv111": mv111[:4],
            "ok": bool(all_ok and off_clean and not mv111)}


def measure_fleet() -> dict:
    """Multi-slice serving-fleet scale-out row (docs/FLEET.md;
    ROADMAP item 1): a repeated-traffic stream of distinct queries
    whose WORKING SET exceeds one slice's result-cache budget but
    fits the fleet's aggregate — the distributed-cache capacity
    story, measured. ``fleet_slices=1`` thrashes its LRU on every
    replay (cyclic access over a 0.6x-capacity set: every consult
    misses and recomputes); ``fleet_slices=2`` splits ownership
    across slices, the global directory routes every replay to its
    owning slice's cache, and the stream answers without recompute —
    the acceptance number is the aggregate-QPS ratio going 1 -> 2
    virtual slices, with a directory hit on a NON-owning slice
    proven recompute-free.

    Phase three is the failover drill: a 2-slice fleet serving the
    stream has slice 0 killed mid-stream; the stream must complete
    with ZERO wrong answers (each future's result checked against
    the numpy oracle) and only typed failures.

    Single-query admission (``serve_max_batch=1``) in every config so
    the ratio measures CACHE CAPACITY, not MultiPlan composition
    churn (the traffic-harness precedent on CPU hosts)."""
    import jax  # noqa: F401  (backend registration)
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.resilience.errors import ResilienceError
    from matrel_tpu.session import MatrelSession

    set_default_config(MatrelConfig(obs_level="off"))
    mesh = mesh_lib.make_mesh()
    # ODD stream length: round-robin placement then lands each
    # replay's asks on alternating slices relative to ownership, so
    # the row PROVES the remote-hit path (an even count parity-aligns
    # placement with ownership and never exercises it)
    n = _env_int("MATREL_FLEET_N", 512)
    n_q = _env_int("MATREL_FLEET_QUERIES", 13)
    replays = _env_int("MATREL_FLEET_REPLAYS", 3)
    rng = np.random.default_rng(7)
    A_np = rng.standard_normal((n, n)).astype(np.float32)
    B_np = rng.standard_normal((n, n)).astype(np.float32)
    # per-slice budget: 60% of the working set — one slice thrashes,
    # two slices (each owning ~half the stream) hold their share
    budget = int(0.6 * n_q * n * n * 4)

    def build_session(slices: int) -> MatrelSession:
        cfg = MatrelConfig(obs_level="off", fleet_slices=slices,
                           result_cache_max_bytes=budget,
                           serve_max_batch=1)
        sess = MatrelSession(mesh=mesh, config=cfg)
        sess.register("A", sess.from_numpy(A_np))
        sess.register("B", sess.from_numpy(B_np))
        return sess

    def stream_exprs(sess):
        base = sess.table("A").expr().multiply(
            sess.table("B").expr())
        return [base.multiply_scalar(1.0 + 0.5 * i)
                for i in range(n_q)]

    def replay(sess, qs):
        futs = [sess.submit(q) for q in qs]
        outs = [f.result(timeout=600) for f in futs]
        for o in outs:
            o.data.block_until_ready()

    def run_config(slices: int) -> dict:
        sess = build_session(slices)
        qs = stream_exprs(sess)
        replay(sess, qs)      # warm: compiles + populates the caches
        sess.serve_drain()
        info0 = sess.fleet_info()
        sub0 = sum(sl["submitted"] for sl in info0["slices"])
        ts = []
        for _ in range(replays):
            t0 = time.perf_counter()
            replay(sess, qs)
            ts.append(time.perf_counter() - t0)
        sess.serve_drain()
        info = sess.fleet_info()
        sub1 = sum(sl["submitted"] for sl in info["slices"])
        ts.sort()
        med = ts[len(ts) // 2]
        half = (ts[-1] - ts[0]) / 2
        row = {"qps": round(n_q / med, 2),
               "median_ms": round(med * 1e3, 3),
               "half_width_ms": round(half * 1e3, 3),
               "replays": replays,
               "directory": info["directory"],
               "placed": info["placed"],
               # "answered without recompute": the measured replays
               # never re-entered a slice pipeline — every answer
               # came from the directory's front door
               "recompute_free_replays": sub1 == sub0}
        sess.serve_close()
        return row

    def kill_drill() -> dict:
        sess = build_session(2)
        qs = stream_exprs(sess)
        oracle = A_np @ B_np
        futs = []
        for r in range(3):
            for i, q in enumerate(qs):
                futs.append((i, sess.submit(q)))
                if r == 1 and i == n_q // 2:
                    sess._fleet.kill_slice(0)
        try:
            sess.serve_drain(timeout=600)
        except ResilienceError:
            pass          # a wedged drain still counts below, typed
        completed = wrong = typed = untyped = 0
        for i, f in futs:
            try:
                o = f.result(timeout=600)
                got = np.asarray(o.to_numpy())
                want = oracle * (1.0 + 0.5 * i)
                if np.allclose(got, want, rtol=2e-3, atol=2e-3):
                    completed += 1
                else:
                    wrong += 1
            except ResilienceError:
                typed += 1
            except Exception:
                untyped += 1
        info = sess.fleet_info()
        out = {"submitted": len(futs), "completed": completed,
               "wrong": wrong, "typed_failures": typed,
               "untyped_failures": untyped,
               "failovers": info["failovers"],
               "requeued": info["requeued"]}
        sess.serve_close()
        return out

    out: dict = {"n": n, "queries": n_q, "replays": replays,
                 "cache_budget_bytes": budget, "configs": {}}
    out["configs"]["slices1"] = run_config(1)
    out["configs"]["slices2"] = run_config(2)
    q1 = out["configs"]["slices1"]["qps"]
    q2 = out["configs"]["slices2"]["qps"]
    out["slices1_qps"] = q1
    out["slices2_qps"] = q2
    out["speedup"] = round(q2 / q1, 2) if q1 else None
    d2 = out["configs"]["slices2"]["directory"]
    out["remote_hit_no_recompute"] = bool(
        d2["remote_hits"] >= 1
        and out["configs"]["slices2"]["recompute_free_replays"])
    out["kill"] = kill_drill()
    return out


def measure_stream() -> dict:
    """Streaming IVM sweep (ROADMAP item 2, the round-14 acceptance
    row): the sliding-window streaming-graph dashboard
    (workloads/streaming.py) run through BOTH maintenance modes over
    the same seeded stream — delta-patch (``register_delta``: cached
    entries patched in place, repeats answer from the cache) vs full
    recompute (a plain rebind per tick: transitive kill, every repeat
    recompiles and re-executes). Reports steady-state per-update
    latency (median ± half-width over the measured ticks, the first
    patch-mode tick excluded — it compiles the patch plans the steady
    state reuses) and the speedup; the acceptance number is
    delta-patch >= 3x on the small-delta stream, with MV113's dynamic
    check proving every surviving patched entry within its stamped
    bound and ZERO wrong answers (integer queries bit-exact) in both
    modes. CPU backend is acceptable: the win is algebraic work
    avoided plus compiles avoided, which the CPU pays like the TPU."""
    import jax
    from matrel_tpu.analysis import delta_pass
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.session import MatrelSession
    from matrel_tpu.workloads.streaming import StreamingGraph

    n = _env_int("MATREL_STREAM_N", 1024)
    edges = _env_int("MATREL_STREAM_EDGES", 16)
    window = _env_int("MATREL_STREAM_WINDOW", 6)
    updates = _env_int("MATREL_STREAM_UPDATES", 5)
    feat_k = _env_int("MATREL_STREAM_K", 32)
    seed = _env_int("MATREL_STREAM_SEED", 0)
    cfg = MatrelConfig(obs_level="off",
                       result_cache_max_bytes=1 << 30)
    set_default_config(cfg)
    mesh = mesh_lib.make_mesh()

    def check(g) -> float:
        got = g.run_all()
        want = g.oracle()
        worst = 0.0
        for k, v in got.items():
            w = np.asarray(want[k], np.float32).reshape(v.shape)
            err = float(np.abs(v - w).max())
            if k != "feature_product" and err != 0.0:
                raise AssertionError(
                    f"integer query {k} not bit-exact: {err}")
            worst = max(worst, err / max(float(np.abs(w).max()), 1.0))
        return worst

    def run_mode(mode: str) -> dict:
        sess = MatrelSession(mesh=mesh, config=cfg)
        g = StreamingGraph(sess, n=n, batch_edges=edges,
                           window=window, feature_k=feat_k, seed=seed)
        g.run_all()                              # cold dashboard
        if mode == "patch":
            t0 = time.perf_counter()
            g.step_delta()                       # tick 0 compiles the
            g.run_all()                          # patch plans — warm,
            warm_ms = (time.perf_counter() - t0) * 1e3   # reported
        else:                                    # separately
            warm_ms = None
        ts = []
        worst = 0.0
        summaries = []
        for _ in range(max(updates, 2)):
            t0 = time.perf_counter()
            s = (g.step_delta() if mode == "patch"
                 else g.step_rebind())
            g.run_all()
            ts.append((time.perf_counter() - t0) * 1e3)
            summaries.append(s)
            worst = max(worst, check(g))
        ts.sort()
        out = {"median_ms": round(ts[len(ts) // 2], 3),
               "half_width_ms": round((ts[-1] - ts[0]) / 2, 3),
               "updates": len(ts), "worst_rel_err": worst}
        if mode == "patch":
            out["warm_ms"] = round(warm_ms, 3)
            out["patched_per_update"] = summaries[-1]["patched"]
            out["killed_per_update"] = summaries[-1]["killed"]
            out["reused_plans"] = summaries[-1]["reused_plans"]
            out["est_saved_flops"] = summaries[-1]["est_saved_flops"]
            out["mv113"] = [d.render()[:160] for d in
                            delta_pass.verify_patched_entries(sess)]
            out["rc"] = {k: v for k, v in
                         sess.result_cache_info().items()
                         if k in ("entries", "hits", "patched",
                                  "rekeyed", "invalidated")}
        return out

    patch = run_mode("patch")
    recompute = run_mode("recompute")
    speedup = (round(recompute["median_ms"] / patch["median_ms"], 2)
               if patch["median_ms"] > 0 else None)
    ok = (speedup is not None and speedup >= 3.0
          and not patch["mv113"]
          and patch["reused_plans"] > 0
          and patch["patched_per_update"] > 0)
    return {"n": n, "edges_per_update": edges, "window": window,
            "backend": jax.default_backend(),
            "patch": patch, "recompute": recompute,
            "speedup": speedup,
            "value": speedup, "unit": "x recompute",
            "ok": bool(ok)}


def measure_precision() -> dict:
    """Precision-tier sweep (the ROADMAP item-3 acceptance row): the
    dense flagship multiply at f32 vs bf16×1 vs bf16×3 vs int32, each
    through the FULL stack under its explicit-dtype SLA, with a
    measured max-abs-error column against an f64 numpy oracle and the
    documented per-tier bound (planner.tier_error_bound) asserted
    alongside. On CPU the MXU-rate win cannot show in wall-clock — the
    row instead proves the SLA chooser picks tiers the cost model says
    it should ("fast"→bf16x1, "high"→bf16x3, "exact"+integral→int32)
    and that every tier's error sits inside its documented bound; the
    TPU TFLOPS column lands via the staged tools/tpu_batch.sh step.

    Float tiers time the SAME overflow-guarded chained step as the
    headline row (bf16_safe_chain_step + check_chain_canary — the one
    shared guard); the int32 tier times independent runs (an integer
    chain cannot carry the 2/N rescale without leaving the integer
    domain, and unrescaled integer products overflow int32 by design).
    """
    import jax
    import jax.numpy as jnp
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.executor import compile_expr
    from matrel_tpu.parallel import planner

    set_default_config(MatrelConfig(obs_level="off"))
    mesh = mesh_lib.make_mesh()
    n = _env_int("MATREL_PRECISION_N", 2048)
    reps = _env_int("MATREL_PRECISION_REPEATS", 8)
    n_chips = max(1, len(mesh.devices.ravel()))
    rng = np.random.default_rng(0)
    a = rng.random((n, n), dtype=np.float32)
    b = rng.random((n, n), dtype=np.float32)
    ai = rng.integers(0, 4, (n, n)).astype(np.float32)
    bi = rng.integers(0, 4, (n, n)).astype(np.float32)
    A = BlockMatrix.from_numpy(a, mesh=mesh)
    B = BlockMatrix.from_numpy(b, mesh=mesh)
    Ai = BlockMatrix.from_numpy(ai, mesh=mesh, integral=True)
    Bi = BlockMatrix.from_numpy(bi, mesh=mesh, integral=True)
    oracle = a.astype(np.float64) @ b.astype(np.float64)
    oracle_i = ai.astype(np.int64) @ bi.astype(np.int64)
    fetch = jax.jit(lambda x: jnp.mean(jnp.abs(x.astype(jnp.float32))))

    def tier_error(cfg, Pa, Pb, want):
        plan = compile_expr(Pa.expr().multiply(Pb.expr()), mesh, cfg)
        got = plan.run().to_numpy().astype(np.float64)
        stamped = plan.optimized.attrs.get("precision_tier")
        return float(np.abs(got - want).max()), stamped

    def time_chained(cfg):
        plan = compile_expr(bf16_safe_chain_step(A, B), mesh, cfg)
        a_leaf = plan.leaf_order[0]
        step = plan.bound_runner(rebind_uids=(a_leaf.uid,))

        def chained(r):
            cur = step(A.data)
            for _ in range(r - 1):
                cur = step(cur)
            return float(np.asarray(fetch(cur)))

        chained(2)                       # warm both programs
        lo, hi = 3, 3 + reps
        ests = []
        canary = None
        for _ in range(3):
            t0 = time.perf_counter()
            chained(lo)
            t_lo = time.perf_counter() - t0
            t0 = time.perf_counter()
            canary = chained(hi)
            t_hi = time.perf_counter() - t0
            ests.append(max((t_hi - t_lo) / (hi - lo), 1e-9))
        check_chain_canary(canary)       # the shared overflow guard
        return sorted(ests)[1]

    rows = []
    all_ok = True
    for tier, sla in (("f32", "float32"), ("bf16x1", "bfloat16"),
                      ("bf16x3", "bf16x3"), ("int32", "int32")):
        cfg = MatrelConfig(obs_level="off", precision_sla=sla)
        integer = tier == "int32"
        Pa, Pb = (Ai, Bi) if integer else (A, B)
        want = oracle_i.astype(np.float64) if integer else oracle
        amax = float(np.abs(ai if integer else a).max())
        bmax = float(np.abs(bi if integer else b).max())
        err, stamped = tier_error(cfg, Pa, Pb, want)
        if integer:
            plan = compile_expr(Ai.expr().multiply(Bi.expr()), mesh,
                                cfg)

            def run_once(p=plan):
                float(np.asarray(fetch(p.run().data)))

            dt = _median_s(run_once, reps=3)
        else:
            dt = time_chained(cfg)
        bound = planner.tier_error_bound(tier, n, amax, bmax)
        # int tiers are EXACT: the bound is literal zero
        ok = err <= bound if bound > 0 else err == 0.0
        all_ok = all_ok and ok
        rows.append({
            "tier": tier, "sla": sla, "stamped_tier": stamped,
            "est_passes": planner.TIER_PASSES[tier],
            "median_ms": round(dt * 1e3, 3),
            "tflops_per_chip": round(flops(n) / dt / 1e12 / n_chips,
                                     3),
            "max_abs_err": err,
            "err_bound": bound,
            "within_bound": ok,
        })
    # the SLA chooser's picks on the flagship shape — the CPU-visible
    # half of the acceptance: the cost model must route each named SLA
    # to the tier its pass/byte billing says is cheapest-satisfying
    choices = {}
    for sla, Pa, Pb in (("exact", A, B), ("high", A, B),
                        ("fast", A, B), ("exact_int", Ai, Bi)):
        cfg = MatrelConfig(obs_level="off",
                           precision_sla=sla.replace("_int", ""))
        ann = planner.annotate_strategies(
            Pa.expr().multiply(Pb.expr()), mesh, cfg)
        choices[sla] = ann.attrs.get("precision_tier")
    chooser_ok = (choices.get("exact") == "f32"
                  and choices.get("high") == "bf16x3"
                  and choices.get("fast") == "bf16x1"
                  and choices.get("exact_int") == "int32")
    return {"n": n, "rows": rows, "sla_choices": choices,
            "chooser_ok": chooser_ok, "all_within_bound": all_ok}


def measure_serve() -> dict:
    """Repeated-traffic serving QPS (the serve-layer headline): a mixed
    query stream — PageRank-style step, normal-equations linreg, a
    reordered chain (two scalar variants each, six distinct queries) —
    replayed round-robin, measured under four configs: {result cache
    off, on} × {sequential session.run loop, micro-batched
    session.run_many}. The speedup of cached+batched over today's
    sequential uncached loop is the acceptance number (the MatFast
    persist/RDD-cache amortization, measured end to end).

    Interval methodology matches the bench discipline: each config's
    stream is replayed ``MATREL_SERVE_MEAS`` times after a warm-up
    replay (which also populates the caches — steady-state serving is
    the thing being measured), and the row records the median wall per
    replay with its half-width. Whole streams are the repeat unit (the
    chained-reps analogue: every query's dispatch depends on the
    session state the previous one left), and every replay force-
    fetches its results before the clock stops."""
    import jax  # noqa: F401  (backend registration)
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.session import MatrelSession

    set_default_config(MatrelConfig(obs_level="off"))
    mesh = mesh_lib.make_mesh()
    n = _env_int("MATREL_SERVE_N", 1024)
    k = _env_int("MATREL_SERVE_K", 128)
    n_q = _env_int("MATREL_SERVE_QUERIES", 36)
    meas = _env_int("MATREL_SERVE_MEAS", 5)
    batch = _env_int("MATREL_SERVE_BATCH", 6)

    M = BlockMatrix.random((n, n), mesh=mesh, seed=0)
    r = BlockMatrix.random((n, 1), mesh=mesh, seed=1)
    X = BlockMatrix.random((n, k), mesh=mesh, seed=2)
    y = BlockMatrix.random((n, 1), mesh=mesh, seed=3)
    A = BlockMatrix.random((n, k), mesh=mesh, seed=4)
    B = BlockMatrix.random((k, n), mesh=mesh, seed=5)
    C = BlockMatrix.random((n, k), mesh=mesh, seed=6)

    def templates():
        # distinct expression OBJECTS reused across the stream — the
        # dashboard-traffic shape: identical structural keys recur
        pr = M.expr().multiply(r.expr()).multiply_scalar(0.85)
        xt = X.expr().t()
        linreg = xt.multiply(X.expr()).solve(xt.multiply(y.expr()))
        chain = A.expr().multiply(B.expr().multiply(C.expr()))
        return [pr, pr.add_scalar(0.15 / n),
                linreg, linreg.multiply_scalar(2.0),
                chain, chain.multiply_scalar(0.5)]

    qs = templates()
    stream = [qs[i % len(qs)] for i in range(n_q)]

    def run_config(cache_on: bool, batched: bool) -> dict:
        cfg = MatrelConfig(
            obs_level="off",
            result_cache_max_bytes=(1 << 30) if cache_on else 0)
        sess = MatrelSession(mesh=mesh, config=cfg)

        def replay():
            if batched:
                outs = []
                for j in range(0, len(stream), batch):
                    outs.extend(sess.run_many(stream[j:j + batch]))
            else:
                outs = [sess.run(q) for q in stream]
            for o in outs:
                o.data.block_until_ready()

        replay()           # warm: compiles, populates plan/result caches
        ts = []
        for _ in range(meas):
            t0 = time.perf_counter()
            replay()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med = ts[len(ts) // 2]
        half = (ts[-1] - ts[0]) / 2
        return {"qps": round(n_q / med, 2),
                "median_ms": round(med * 1e3, 3),
                "half_width_ms": round(half * 1e3, 3),
                "half_width_frac": round(half / med, 4) if med else None,
                "replays": meas}

    out: dict = {"n": n, "k": k, "queries": n_q, "batch": batch,
                 "configs": {}}
    for name, cache_on, batched in (
            ("seq_uncached", False, False),
            ("seq_cached", True, False),
            ("batched_uncached", False, True),
            ("batched_cached", True, True)):
        out["configs"][name] = run_config(cache_on, batched)
    base = out["configs"]["seq_uncached"]["qps"]
    best = out["configs"]["batched_cached"]["qps"]
    out["seq_uncached_qps"] = base
    out["batched_cached_qps"] = best
    out["speedup"] = round(best / base, 2) if base else None
    return out


def measure_cse() -> dict:
    """Shared-interior batch row (the multi-query-optimization
    acceptance number, serve/mqo.py; docs/SERVING.md): a batch of
    ``MATREL_CSE_VARIANTS`` dashboard variants over ONE Gram interior
    — Xᵀ·X scaled per variant, the identical-subplan shape dashboard
    traffic produces — admitted through ``session.run_many`` with
    ``cse_enable`` off vs on, FRESH session each trial so the measured
    wall is first contact (optimize + trace + execute, nothing
    amortized by the plan or result caches). CSE-on hoists the Gram
    once and feeds every variant the computed leaf; the off/on median
    ratio is the row's speedup.

    A steady-state coda replays a structurally-identical batch over a
    REBOUND leaf (a different X) on the warm CSE session: the
    plan-template path must answer it by rebinding leaves into the
    compiled MultiPlan (``mqo_info`` template-hit delta >= the batch),
    paying zero optimize/trace — the event-verified half lives in
    tests/test_cse.py. Interval methodology matches the bench
    discipline: median over ``MATREL_CSE_MEAS`` fresh-session trials
    with the min/max half-width; exactness is asserted by comparing
    the two paths' answers bit-for-bit (zero wrong answers is part of
    the row, not a separate check)."""
    import jax  # noqa: F401  (backend registration)
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.session import MatrelSession

    set_default_config(MatrelConfig(obs_level="off"))
    mesh = mesh_lib.make_mesh()
    n = _env_int("MATREL_CSE_N", 2048)
    cols = _env_int("MATREL_CSE_COLS", 512)
    k = _env_int("MATREL_CSE_VARIANTS", 8)
    meas = _env_int("MATREL_CSE_MEAS", 3)

    X = BlockMatrix.random((n, cols), mesh=mesh, seed=0)
    X2 = BlockMatrix.random((n, cols), mesh=mesh, seed=1)

    def batch(M):
        # shared interior: a cubic polynomial over the Gram (the
        # graph-analytics A³ shape) — 4 matmuls every variant repays
        # without CSE, one hoisted compute-once node with it
        g = M.expr().t().multiply(M.expr())
        h = g.multiply(g).multiply(g)
        return [h.multiply_scalar(1.0 + 0.25 * i) for i in range(k)]

    def first_contact(cse_on: bool):
        ts, last = [], None
        sess = None
        for _ in range(meas):
            sess = MatrelSession(mesh=mesh, config=MatrelConfig(
                obs_level="off", cse_enable=cse_on))
            qs = batch(X)
            t0 = time.perf_counter()
            outs = sess.run_many(qs)
            for o in outs:
                o.data.block_until_ready()
            ts.append(time.perf_counter() - t0)
            last = outs
        ts.sort()
        med = ts[len(ts) // 2]
        row = {"median_ms": round(med * 1e3, 3),
               "half_width_ms": round((ts[-1] - ts[0]) / 2 * 1e3, 3),
               "trials": meas}
        return row, med, last, sess

    off_row, off_med, off_outs, _ = first_contact(False)
    on_row, on_med, on_outs, on_sess = first_contact(True)

    # zero wrong answers IS the row: both paths bit-identical
    diff = max(float(np.abs(a.to_numpy().astype(np.float64)
                            - b.to_numpy().astype(np.float64)).max())
               for a, b in zip(off_outs, on_outs))
    info = on_sess.mqo_info()

    # steady state: structurally identical batch, REBOUND leaf — the
    # template path answers by rebinding, zero optimize/trace
    before = info["template_hits"]
    qs2 = batch(X2)
    t0 = time.perf_counter()
    outs2 = on_sess.run_many(qs2)
    for o in outs2:
        o.data.block_until_ready()
    steady_ms = (time.perf_counter() - t0) * 1e3
    info2 = on_sess.mqo_info()
    ref = X2.to_numpy().astype(np.float64)
    g2 = ref.T @ ref
    h2 = g2 @ g2 @ g2
    scale = float(np.abs(h2).max())
    exact2 = all(
        float(np.abs(o.to_numpy().astype(np.float64)
                     - h2 * (1.0 + 0.25 * i)).max()) / scale < 1e-4
        for i, o in enumerate(outs2))

    return {"n": n, "cols": cols, "variants": k,
            "configs": {"cse_off": off_row, "cse_on": on_row},
            "cse_off_ms": off_row["median_ms"],
            "cse_on_ms": on_row["median_ms"],
            "speedup": round(off_med / on_med, 2) if on_med else None,
            "exact": diff == 0.0,
            "hoisted_per_batch": int(info["cse_hoisted"]
                                     / max(info["cse_batches"], 1)),
            "steady": {
                "rebind_ms": round(steady_ms, 3),
                "template_hits_delta": info2["template_hits"] - before,
                "templates": info2["templates"],
                "exact": bool(exact2)}}


def measure_coeffs() -> dict:
    """Calibrated-vs-analytic planner row (the cost-model loop's
    acceptance number, parallel/coeffs.py; docs/COST_MODEL.md): for
    each workload, run every strategy FORCED (``strategy_override`` —
    the ground truth the closed loop is supposed to learn), convert
    the steady-state wall times into drift samples at the workloads'
    OWN matmul shapes, and persist them through the auditor's
    calibrate/update_table writers — a measured coefficient table
    built the way live traffic builds it. Then run the chain /
    PageRank-step / linreg-epilogue workloads on fresh sessions with
    ``coeff_planner_enable`` off (analytic closed forms) vs on
    (measured ms ranking against that table), steady state (warm plan
    cache: the strategy choice is what differs, and execution is
    where it pays). The three workloads land in three DISTINCT shape
    classes (side n, 2n, rows 4n), so each ranking consults rows
    calibrated on its own class. The row reports per-workload
    medians, the strategies each ranking picked and the ``cost``
    provenance stamps; answers from the two paths are asserted close
    (zero wrong answers is part of the row). Acceptance: every
    covered workload class (all decisions stamped ``measured``) runs
    no slower than analytic beyond host noise — and strictly faster
    wherever the closed forms mispick."""
    import tempfile

    import jax
    from matrel_tpu import executor as executor_lib
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.obs import drift
    from matrel_tpu.parallel import strategies as strategies_lib
    from matrel_tpu.session import MatrelSession

    n = _env_int("MATREL_COEFFS_N", 512)
    k = _env_int("MATREL_COEFFS_K", 128)
    meas = _env_int("MATREL_COEFFS_MEAS", 5)
    inner = _env_int("MATREL_COEFFS_INNER", 8)

    table = os.path.join(tempfile.mkdtemp(prefix="matrel_coeffs_"),
                         "drift.json")
    cfg_analytic = MatrelConfig(obs_level="off",
                                drift_table_path=table)
    cfg_measured = cfg_analytic.replace(coeff_planner_enable=True,
                                        coeff_min_samples=2)
    set_default_config(cfg_analytic)
    mesh = mesh_lib.make_mesh()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    backend = jax.default_backend()
    rng = np.random.default_rng(0)

    # three workloads, three DISTINCT shape classes (shape_class
    # buckets on the max dim): chain at side n, PageRank at side 2n,
    # linreg Gram over 4n rows
    n2, n4 = 2 * n, 4 * n
    C1 = BlockMatrix.random((n, n), mesh=mesh, seed=2)
    C2 = BlockMatrix.random((n, n), mesh=mesh, seed=3)
    C3 = BlockMatrix.random((n, n), mesh=mesh, seed=4)
    P = BlockMatrix.random((n2, n2), mesh=mesh, seed=5)
    R = BlockMatrix.from_numpy(
        rng.random((n2, 1), dtype=np.float32), mesh=mesh)
    W = BlockMatrix.from_numpy(
        rng.random((n2, 1), dtype=np.float32), mesh=mesh)
    X = BlockMatrix.from_numpy(
        rng.random((n4, k), dtype=np.float32), mesh=mesh)
    I_k = BlockMatrix.from_numpy(np.eye(k, dtype=np.float32),
                                 mesh=mesh)

    def chain_expr():
        return C1.expr().multiply(C2.expr()).multiply(C3.expr())

    def pagerank_expr():
        return P.expr().t() \
            .multiply(W.expr().elem_multiply(R.expr())) \
            .multiply_scalar(0.85).add_scalar(0.15 / n2)

    def linreg_expr():
        return X.expr().t().multiply(X.expr()) \
            .multiply_scalar(1.0 / n4) \
            .add(I_k.expr().multiply_scalar(0.1))

    workloads = (("chain", chain_expr),
                 ("pagerank_step", pagerank_expr),
                 ("linreg_epilogue", linreg_expr))

    def bench_one(make, cfg):
        """Steady-state median over ``meas`` samples of ``inner``
        back-to-back runs each (the measure_fusion discipline: these
        workloads execute in ~1 ms, a single run is host-jitter, not
        signal), plus the plan's per-matmul decision records."""
        sess = MatrelSession(mesh=mesh, config=cfg)
        out = sess.run(make())
        out.data.block_until_ready()        # compile + warm

        def sample():
            o = None
            for _ in range(max(inner, 1)):
                o = sess.run(make())
            o.data.block_until_ready()

        ts = []
        for _ in range(max(meas, 2)):
            t0 = time.perf_counter()
            sample()
            ts.append((time.perf_counter() - t0) / max(inner, 1))
        ts.sort()
        plan = executor_lib.compile_expr(make(), mesh, cfg)
        decs = executor_lib.plan_matmul_decisions(plan)
        return {"ms": round(ts[len(ts) // 2] * 1e3, 3),
                "half_width_ms": round((ts[-1] - ts[0]) / 2 * 1e3, 3),
                "ts": ts,
                "decisions": decs,
                "strategies": [d.get("strategy") for d in decs],
                "cost": [d.get("cost", "analytic") for d in decs],
                }, out

    # phase 1: calibrate — every strategy forced per workload, the
    # per-rep wall attributed across the plan's matmuls by flops share
    # (the per-op exclusive-ms discipline), one drift sample per rep
    # so the persisted count clears coeff_min_samples
    samples = []
    for _name, make in workloads:
        for s in strategies_lib.STRATEGIES:
            if s == "summa" and gx != gy:
                continue
            try:
                row, _ = bench_one(make, cfg_analytic.replace(
                    strategy_override=s))
            except Exception:  # matlint: disable=ML007 probe loop — a strategy failing to compile on this backend drops out of the table (the autotune idiom)
                continue
            decs = [d for d in row["decisions"]
                    if isinstance(d.get("flops"), (int, float))
                    and d.get("flops") > 0]
            total_gf = sum(d["flops"] for d in decs)
            if not decs or total_gf <= 0:
                continue
            for t in row["ts"]:
                for d in decs:
                    share = d["flops"] / total_gf
                    samples.append({
                        "strategy": d.get("strategy", s),
                        "class": drift.shape_class(
                            tuple(d.get("dims") or ())),
                        "backend": backend, "tier": "",
                        "flops": float(d["flops"]),
                        "est_bytes": float(
                            d.get("est_ici_bytes") or 0.0),
                        "ms": t * 1e3 * share, "source": "bench"})
    drift.update_table(table, drift.calibrate(samples))

    # phase 2: analytic vs calibrated ranking, fresh sessions
    rows = []
    all_ok = True
    for name, make in workloads:
        a_row, a_out = bench_one(make, cfg_analytic)
        m_row, m_out = bench_one(make, cfg_measured)
        ref = a_out.to_numpy().astype(np.float64)
        got = m_out.to_numpy().astype(np.float64)
        scale = max(float(np.abs(ref).max()), 1.0)
        agree = bool(np.allclose(got / scale, ref / scale, atol=1e-5))
        covered = bool(m_row["cost"]) and all(
            c == "measured" for c in m_row["cost"])
        speedup = (round(a_row["ms"] / m_row["ms"], 2)
                   if m_row["ms"] > 0 else None)
        # "no slower beyond host noise": identical strategy picks mean
        # identical plans — any ratio off 1.0 is pure host jitter, not
        # a planner regression; when the rankings DIVERGE the
        # calibrated pick must hold 0.9 (the shared-box guard band)
        same_plan = (m_row["strategies"] == a_row["strategies"])
        ok = (agree and covered and speedup is not None
              and (same_plan or speedup >= 0.9))
        all_ok = all_ok and ok
        rows.append({"workload": name,
                     "analytic_ms": a_row["ms"],
                     "calibrated_ms": m_row["ms"],
                     "half_width_ms": max(a_row["half_width_ms"],
                                          m_row["half_width_ms"]),
                     "speedup": speedup,
                     "analytic_strategies": a_row["strategies"],
                     "calibrated_strategies": m_row["strategies"],
                     "cost_sources": m_row["cost"],
                     "covered": covered,
                     "outputs_agree": agree,
                     "ok": ok})
    return {"n": n, "k": k, "backend": backend,
            "classes": sorted({s["class"] for s in samples}),
            "table_strategies": sorted({s["strategy"]
                                        for s in samples}),
            "trials": meas,
            "rows": rows,
            "ok": bool(all_ok)}


def measure_reshard() -> dict:
    """Flagship-shape src→dst reshard sweep (the reshard-planner row,
    ROADMAP item 2): for each layout move, time the PLANNED staged
    step sequence (parallel/reshard.py: per-axis all_to_all chains,
    ordered gather stages) against the NAIVE one-shot sharding
    constraint (whatever collective XLA emits), and record both with
    the plan's modelled {bytes moved, peak bytes} next to the one-shot
    model's — the numbers the drift auditor calibrates
    ``reshard:<kind>`` ms/MiB rows from. Median + half-width over
    ``MATREL_RESHARD_REPEATS`` timed runs per lowering (the bench
    interval discipline); every run force-fetches through
    block_until_ready."""
    import jax
    from jax.sharding import NamedSharding
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.parallel import reshard as reshard_lib

    set_default_config(MatrelConfig(obs_level="off"))
    mesh = mesh_lib.make_mesh()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    p = max(gx * gy, 1)
    n = _env_int("MATREL_RESHARD_N", 4096)
    reps = _env_int("MATREL_RESHARD_REPEATS", 5)
    n = max(p, -(-n // p) * p)          # divisible by every state
    nbytes = float(n) * n * 4
    wts = mesh_lib.axis_weights(mesh)
    rng = np.random.default_rng(0)
    host = rng.standard_normal((n, n)).astype(np.float32)

    def timed(f, x) -> dict:
        f(x).block_until_ready()        # compile + warm
        ts = []
        for _ in range(max(reps, 2)):
            t0 = time.perf_counter()
            f(x).block_until_ready()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        med = ts[len(ts) // 2]
        half = (ts[-1] - ts[0]) / 2
        return {"ms": round(med * 1e3, 3),
                "half_width_ms": round(half * 1e3, 3)}

    rows = []
    for src, dst in (("row", "col"), ("col", "row"),
                     ("row", "2d"), ("2d", "rep")):
        # the budget that forces the bounded decomposition: four
        # shards — staged cross moves fit (peak 2·B/p), one-shot
        # full-gather transients do not
        budget = 4.0 * nbytes / p
        plan = reshard_lib.compile_reshard(src, dst, nbytes, gx, gy,
                                           wts, peak_budget=budget)
        unb = reshard_lib.compile_reshard(src, dst, nbytes, gx, gy,
                                          wts)
        x = jax.device_put(
            host, NamedSharding(mesh,
                                reshard_lib._state_spec(src, mesh)))
        dst_sh = NamedSharding(mesh,
                               reshard_lib._state_spec(dst, mesh))
        naive = jax.jit(
            lambda v, _sh=dst_sh: jax.lax.with_sharding_constraint(
                v, _sh))
        staged = jax.jit(
            lambda v, _p=plan: reshard_lib.apply_staged(v, _p, mesh))
        t_naive = timed(naive, x)
        t_staged = timed(staged, x)
        kinds = [k for k in plan.step_kinds if k != "slice"]
        cross = {src, dst} == {"row", "col"}
        rows.append({
            "pair": f"{src}->{dst}", "n": n, "cross": cross,
            "kind": kinds[0] if kinds else "slice",
            "steps": list(plan.step_kinds),
            "staged_ms": t_staged["ms"],
            "staged_half_width_ms": t_staged["half_width_ms"],
            "naive_ms": t_naive["ms"],
            "naive_half_width_ms": t_naive["half_width_ms"],
            "staged_bytes": plan.bytes_x + plan.bytes_y,
            "naive_bytes": unb.bytes_x + unb.bytes_y,
            "peak_bytes": plan.peak_bytes,
            "naive_peak_bytes": plan.naive_peak_bytes,
            "peak_ratio": round(
                plan.naive_peak_bytes / plan.peak_bytes, 2)
            if plan.peak_bytes else None,
        })
    # the peak-improvement claim holds for the CROSS moves (the staged
    # all_to_all chain vs the modelled one-shot full gather); gathers
    # to "rep" end replicated either way — their win is axis ORDER on
    # a weighted mesh, not peak
    ok = all(r["staged_ms"] > 0 and r["naive_ms"] > 0
             and (not r["cross"]
                  or r["peak_bytes"] <= r["naive_peak_bytes"])
             for r in rows)
    return {"n": n, "grid": f"{gx}x{gy}", "repeats": reps,
            "backend": jax.default_backend(), "rows": rows, "ok": ok}


# ---------------------------------------------------------------------------
# CPU reference rows (BASELINE rows 2-6) — VERDICT r5 "Missing #2".
# Pure numpy/scipy on the HOST: nothing here imports jax, so this path
# cannot touch (or hang on) the axon relay and is runnable with the
# relay down. Full-scale where host memory/time allow; rows 3 and 6 use
# a reduced config with an EXPLICIT, recorded extrapolation (linear in
# streamed rows for the Gram; cubic in n for the dense chain).
# ---------------------------------------------------------------------------


def _median_s(fn, reps: int = 3, warm: int = 1) -> float:
    for _ in range(warm):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _cpu_row_chain() -> dict:                               # row 2
    rng = np.random.default_rng(0)
    n, mid = 10_000, 100
    A = rng.standard_normal((n, mid)).astype(np.float32)
    B = rng.standard_normal((mid, n)).astype(np.float32)
    C = rng.standard_normal((n, mid)).astype(np.float32)
    dt = _median_s(lambda: A @ (B @ C), reps=5)
    return {"metric": "chain_abc_10k_skewed_wallclock", "unit": "ms",
            "value": round(dt * 1e3, 3),
            "config": "full scale, optimal order A·(B·C), numpy BLAS"}


def _cpu_row_linreg() -> dict:                              # row 3
    n_full, k, panel = 10_000_000, 1000, 250_000
    n_meas = 1_000_000
    rng = np.random.default_rng(1)
    G = np.zeros((k, k), np.float32)
    b = np.zeros((k, 1), np.float32)

    def run():
        G[:] = 0
        b[:] = 0
        for _ in range(n_meas // panel):
            Xp = rng.standard_normal((panel, k)).astype(np.float32)
            yp = Xp @ np.ones((k, 1), np.float32)
            G[:, :] += Xp.T @ Xp       # item-assign: G/b stay closure
            b[:, :] += Xp.T @ yp       # vars (+= on the name rebinds)
        np.linalg.solve(G.astype(np.float64), b.astype(np.float64))

    dt = _median_s(run, reps=1, warm=0)
    scale = n_full / n_meas
    return {"metric": "linreg_normal_eq_10Mx1k_wallclock", "unit": "s",
            "value": round(dt * scale, 3),
            "config": f"measured at {n_meas}x{k} panel-streamed Gram, "
                      f"extrapolated x{scale:.0f} (linear in rows; "
                      "generator included, as in the TPU row)"}


def _cpu_row_spmm() -> dict:                                # row 4
    n, bs, m = 100_352, 512, 512
    gr = gc = n // bs                                       # 196
    rng = np.random.default_rng(2)
    nnzb = max(1, int(round(gr * gc * 0.01)))               # 384
    flat = rng.choice(gr * gc, size=nnzb, replace=False)
    rows, cols = flat // gc, flat % gc
    tiles = rng.standard_normal((nnzb, bs, bs)).astype(np.float32)
    D = rng.standard_normal((n, m)).astype(np.float32)
    out = np.zeros((n, m), np.float32)

    def run():
        out[:] = 0
        for t in range(nnzb):
            out[rows[t] * bs:(rows[t] + 1) * bs] += (
                tiles[t] @ D[cols[t] * bs:(cols[t] + 1) * bs])

    dt = _median_s(run)
    fl = 2.0 * nnzb * bs * bs * m
    return {"metric": "blocksparse_spmm_100k_1pct_wallclock",
            "unit": "ms", "value": round(dt * 1e3, 2), "nnzb": nnzb,
            "effective_tflops": round(fl / dt / 1e12, 4),
            "config": "full scale, blocked numpy BLAS"}


def _cpu_row_pagerank() -> dict:                            # row 5
    import scipy.sparse as sp
    n, n_edges, rounds = 1_000_000, 10_000_000, 5
    rng = np.random.default_rng(3)
    src = rng.integers(0, n, n_edges, dtype=np.int64)
    dst = rng.integers(0, n, n_edges, dtype=np.int64)
    M = sp.csr_matrix(
        (np.ones(n_edges, np.float32), (dst, src)), shape=(n, n))
    x = np.full(n, 1.0 / n, np.float32)

    def run():
        y = x
        for _ in range(rounds):
            y = 0.85 * (M @ y) + 0.15 / n
        float(y[0])

    dt = _median_s(run)
    return {"metric": "pagerank_1M_30rounds_wallclock_per_round",
            "unit": "ms/round", "value": round(dt / rounds * 1e3, 2),
            "config": f"full scale, scipy CSR, {rounds} rounds timed"}


def _cpu_row_north_star() -> dict:                          # row 6
    n_full, n_meas = 65_536, 8_192
    rng = np.random.default_rng(4)
    A = rng.standard_normal((n_meas, n_meas)).astype(np.float32)
    B = rng.standard_normal((n_meas, n_meas)).astype(np.float32)
    C = rng.standard_normal((n_meas, n_meas)).astype(np.float32)
    dt = _median_s(lambda: (A @ B) @ C, reps=1)
    scale = (n_full / n_meas) ** 3
    return {"metric": "north_star_65k_chain_wallclock", "unit": "s",
            "value": round(dt * scale, 1),
            "config": f"measured at {n_meas} (full 65k needs ~17 GB/"
                      f"operand and hours of host BLAS), extrapolated "
                      f"x{scale:.0f} (cubic in n)"}


def _cpu_row_spgemm() -> dict:          # new SpGEMM row (CPU reference)
    n, bs = 100_352, 512
    gr = gc = n // bs
    nnzb = max(1, int(round(gr * gc * 0.01)))

    def sample(seed):
        r = np.random.default_rng(seed)
        flat = np.sort(r.choice(gr * gc, size=nnzb, replace=False))
        return (flat // gc, flat % gc,
                r.standard_normal((nnzb, bs, bs)).astype(np.float32))

    ar, ac, at = sample(10)
    br, bc, bt = sample(11)
    order = np.argsort(br, kind="stable")
    brs = br[order]

    def run():
        acc: dict = {}
        starts = np.searchsorted(brs, ac, side="left")
        ends = np.searchsorted(brs, ac, side="right")
        for i in range(nnzb):
            for j0 in range(starts[i], ends[i]):
                j = order[j0]
                k = (int(ar[i]), int(bc[j]))
                p = at[i] @ bt[j]
                if k in acc:
                    acc[k] += p
                else:
                    acc[k] = p
        return len(acc)

    dt = _median_s(run, reps=3, warm=1)
    return {"metric": "blocksparse_spgemm_100k_1pct_wallclock",
            "unit": "ms", "value": round(dt * 1e3, 2), "nnzb": nnzb,
            "config": "full scale, tile-intersection blocked numpy "
                      "BLAS (the ops/spgemm.py algorithm on host)"}


#: BASELINE row number → measurement fn ("spgemm" is the staged row).
CPU_ROWS = {
    "2": _cpu_row_chain,
    "3": _cpu_row_linreg,
    "4": _cpu_row_spmm,
    "5": _cpu_row_pagerank,
    "6": _cpu_row_north_star,
    "spgemm": _cpu_row_spgemm,
}


def cpu_rows() -> dict:
    """Measure every missing CPU reference row and merge the results
    into cpu_baseline.json under "rows" (the row-1 top-level schema is
    untouched — bench.cpu_baseline() keeps reading it)."""
    results = {}
    for row, fn in CPU_ROWS.items():
        t0 = time.perf_counter()
        try:
            rec = fn()
        except Exception as e:            # one broken row must not
            rec = {"error": repr(e)}      # lose the others
        rec["measure_s"] = round(time.perf_counter() - t0, 1)
        results[row] = rec
        print(json.dumps({"row": row, **rec}), flush=True)
    try:
        with open(CPU_CACHE) as f:
            cached = json.load(f)
    except (OSError, ValueError):
        cached = {}
    cached["rows"] = results
    cached["rows_measured"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = CPU_CACHE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cached, f, indent=1)
    os.replace(tmp, CPU_CACHE)
    return results


def measure_spill() -> dict:
    """Spill-hierarchy row (docs/DURABILITY.md): a working set
    deliberately larger than ``result_cache_max_bytes`` cycles
    through the HBM/host/disk tiers under sustained repeats (every
    repeat answers from a lower tier — recompute count is the
    regression signal), then the same fleet restarts COLD (fresh
    process state, first query pays compile + execute) vs THAWED
    (``save_state()`` → ``restore()``, first query pays only the
    priced disk_read + h2d legs) — restart-to-first-hit is the
    headline pair. Per-leg transfer timings land in ``rows``
    (``{"leg","n","bytes","ms"}``), the seed calibration the drift
    auditor ingests as ``spill:<leg>`` coefficient rows (the
    reshard_sweep precedent). Zero wrong answers is part of the row:
    every served repeat and both restart paths are asserted close to
    the fresh-execution oracle."""
    import shutil
    import tempfile

    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.session import MatrelSession

    n = _env_int("MATREL_SPILL_N", 512)
    m = _env_int("MATREL_SPILL_MATS", 6)
    reps = _env_int("MATREL_SPILL_REPEATS", 3)

    state_dir = tempfile.mkdtemp(prefix="matrel_spill_")
    entry_bytes = n * n * 4
    # the budget holds ~2 entries; the working set is m of them, so
    # sustained repeats MUST serve from the lower tiers to avoid
    # recompute (the proof the acceptance criteria ask for)
    budget = int(2.5 * entry_bytes)
    cfg = MatrelConfig(obs_level="off", spill_enable=True,
                       result_cache_max_bytes=budget,
                       result_cache_max_entries=m + 2,
                       spill_host_max_bytes=2 * entry_bytes,
                       spill_disk_hits=0,
                       state_dir=state_dir)
    set_default_config(cfg)
    mesh = mesh_lib.make_mesh()

    def build(sess) -> dict:
        exprs = {}
        for i in range(m):
            name = f"spill_{i}"
            mat = BlockMatrix.random((n, n), mesh=mesh, seed=100 + i)
            sess.register(name, mat)
            exprs[name] = mat.expr().t().multiply(mat.expr())
        return exprs

    rows: list = []

    def collect(rec: dict) -> None:
        for leg in rec.get("legs") or ():
            if isinstance(leg, dict) and leg.get("ms"):
                rows.append({"leg": leg["leg"], "n": n,
                             "bytes": leg["bytes"], "ms": leg["ms"]})

    sess = MatrelSession(mesh=mesh, config=cfg)
    sess._spill.emit = collect
    exprs = build(sess)
    oracle = {}
    for name, e in exprs.items():
        oracle[name] = np.asarray(sess.run(e).data)

    wrong = 0
    sustained_ms = []
    for _ in range(max(reps, 1)):
        for name, e in exprs.items():
            t0 = time.perf_counter()
            out = np.asarray(sess.run(e).data)
            sustained_ms.append((time.perf_counter() - t0) * 1e3)
            if not np.allclose(out, oracle[name], rtol=1e-4,
                               atol=1e-4):
                wrong += 1
    sustained_ms.sort()
    spill_info = sess.result_cache_info().get("spill") or {}

    t0 = time.perf_counter()
    save = sess.save_state()
    save_ms = (time.perf_counter() - t0) * 1e3

    first = next(iter(exprs))

    # COLD restart: a fresh session, no snapshot — first answer pays
    # plan compile + full execution
    cold = MatrelSession(mesh=mesh, config=cfg)
    cold_exprs = build(cold)
    t0 = time.perf_counter()
    out = np.asarray(cold.run(cold_exprs[first]).data)
    cold_ms = (time.perf_counter() - t0) * 1e3
    if not np.allclose(out, oracle[first], rtol=1e-4, atol=1e-4):
        wrong += 1

    # THAWED restart: restore() the snapshot — the first answer thaws
    # a restored disk entry through the priced legs, recomputing
    # nothing
    warm = MatrelSession(mesh=mesh, config=cfg)
    warm._spill.emit = collect
    t0 = time.perf_counter()
    restore = warm.restore()
    restore_ms = (time.perf_counter() - t0) * 1e3
    mat = warm.catalog[first]
    t0 = time.perf_counter()
    out = np.asarray(warm.run(
        mat.expr().t().multiply(mat.expr())).data)
    thawed_ms = (time.perf_counter() - t0) * 1e3
    if not np.allclose(out, oracle[first], rtol=1e-4, atol=1e-4):
        wrong += 1
    thawed = (warm.result_cache_info().get("spill") or {}).get(
        "thawed_restored", 0)

    shutil.rmtree(state_dir, ignore_errors=True)
    return {
        "n": n, "mats": m, "entry_bytes": entry_bytes,
        "hbm_budget_bytes": budget,
        "working_set_bytes": m * entry_bytes,
        "working_set_over_budget": bool(m * entry_bytes > budget),
        "sustained": {
            "queries": len(sustained_ms),
            "ms_p50": round(
                sustained_ms[len(sustained_ms) // 2], 3),
            "promoted": spill_info.get("promoted", 0),
            "demoted_host": spill_info.get("demoted_host", 0),
            "demoted_disk": spill_info.get("demoted_disk", 0),
        },
        "restart": {
            "save_ms": round(save_ms, 3),
            "restore_ms": round(restore_ms, 3),
            "restored_entries": restore.get("rc_entries", 0),
            "cold_first_hit_ms": round(cold_ms, 3),
            "thawed_first_hit_ms": round(thawed_ms, 3),
            "thawed_served_from_snapshot": bool(thawed),
        },
        "wrong": wrong,
        "rows": rows,
    }


# ---------------------------------------------------------------------------
# Subprocess harness: the relay can HANG (not just error), so both the probe
# and the measurement run as child processes under hard timeouts.
# ---------------------------------------------------------------------------

def _child_env() -> dict:
    env = dict(os.environ)
    parts = [p for p in (_HERE, "/root/.axon_site") if os.path.isdir(p)]
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(parts + ([prev] if prev else []))
    return env


def _run_child(mode: str, timeout_s: int) -> tuple[bool, object]:
    """Run `bench.py --_<mode>` in a subprocess. Returns (ok, payload).

    payload = parsed JSON from the child's last stdout line on success,
    else a short error string.

    Output goes to temp FILES (not pipes) and the child runs in its own
    session killed via killpg on timeout: a hung relay helper process
    that inherited a stdout pipe would otherwise keep communicate()
    blocked forever after the direct child dies, re-creating the very
    hang this harness exists to bound.
    """
    import signal
    import tempfile
    with tempfile.TemporaryFile(mode="w+") as out, \
            tempfile.TemporaryFile(mode="w+") as err:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), f"--_{mode}"],
            stdout=out, stderr=err, text=True,
            env=_child_env(), cwd=_HERE, start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
            err.seek(0)
            tail = " | ".join(err.read().strip().splitlines()[-3:])[:300]
            return False, (f"{mode} timed out after {timeout_s}s (relay "
                           f"wedge?)" + (f"; child stderr: {tail}" if tail else ""))
        out.seek(0)
        err.seek(0)
        stdout, stderr = out.read(), err.read()
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    if rc != 0 or not lines:
        tail = (stderr or stdout or "").strip().splitlines()
        return False, f"{mode} rc={rc}: " + " | ".join(tail[-3:])[:500]
    try:
        return True, json.loads(lines[-1])
    except json.JSONDecodeError:
        return False, f"{mode} emitted unparseable output: {lines[-1][:200]}"


def _load_last_good() -> dict | None:
    try:
        with open(LAST_GOOD) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _store_last_good(tflops: float) -> None:
    try:
        tmp = LAST_GOOD + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"tflops": round(tflops, 3), "n": N, "dtype": DTYPE,
                       "when": time.strftime("%Y-%m-%dT%H:%M:%S")}, f)
        os.replace(tmp, LAST_GOOD)
    except OSError:
        pass


def _emit_obs_event(kind: str, record: dict) -> None:
    """Append one record to the obs/ event log (the same JSONL file
    the session's query records land in). Harness-level: runs in the
    PARENT process after measurement, so it cannot perturb the
    measured hot path. obs/events.py is loaded by FILE PATH — importing
    the matrel_tpu package would pull jax into this parent, which is
    deliberately kept backend-free (relay-wedge safety). Never fails
    the bench."""
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_matrel_obs_events",
            os.path.join(_HERE, "matrel_tpu", "obs", "events.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.emit_tool_event(kind, record, anchor_dir=_HERE)
    except Exception as e:  # obs must never fail the bench
        print(f"# {kind} event not logged: {e}", file=sys.stderr)


def _emit_bench_event(record: dict) -> None:
    """One "bench" record per successful run, so BENCH_*.json
    trajectories gain per-phase breakdowns via
    `python -m matrel_tpu history --summary`."""
    _emit_obs_event("bench", record)


def _emit_bench_error(metric: str, error: str, extra: dict = None,
                      last_good: dict = None) -> None:
    """Final-failure trail: a DISTINCT ``bench_error`` event carrying
    the error tail and the last-known-good record, so `history
    --summary` surfaces the failure per metric — today it lives only
    in the BENCH_*.json tail string (the relay-wedge null-row class)."""
    record = {"metric": metric, "error": error[-500:],
              "last_known_good": last_good}
    record.update(extra or {})
    _emit_obs_event("bench_error", record)


def main() -> None:
    base = cpu_baseline()
    t_start = time.monotonic()
    errors: list[str] = []
    tpu: float | None = None
    phases: dict | None = None
    interval: dict | None = None
    for attempt in range(1 + len(BACKOFFS_S)):
        if attempt > 0:
            delay = BACKOFFS_S[attempt - 1]
            remaining = DEADLINE_S - (time.monotonic() - t_start)
            # a retry needs its backoff + at least one probe window
            if delay + PROBE_TIMEOUT_S > remaining:
                errors.append(
                    f"deadline ({DEADLINE_S}s) reached after "
                    f"{attempt} attempt(s)")
                break
            print(f"# attempt {attempt} failed ({errors[-1]}); "
                  f"retrying in {delay}s", file=sys.stderr)
            time.sleep(delay)
        ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
        if not ok:
            errors.append(str(payload))
            continue
        # clamp the measure window to the remaining budget (120 s
        # floor: a healthy measure runs ~60-90 s incl. compile) so a
        # mid-run wedge still reports near the deadline instead of
        # holding the JSON for the full MEASURE_TIMEOUT_S
        remaining = DEADLINE_S - (time.monotonic() - t_start)
        measure_timeout = min(MEASURE_TIMEOUT_S,
                              max(120, int(remaining)))
        ok, payload = _run_child("measure", measure_timeout)
        if not ok:
            errors.append(str(payload))
            continue
        try:
            tpu = float(payload["tflops"])
            phases = payload.get("phases")
            interval = payload.get("interval")
            break
        except (KeyError, TypeError, ValueError):
            errors.append(f"measure returned unexpected payload: "
                          f"{str(payload)[:200]}")
            continue

    if tpu is not None:
        _store_last_good(tpu)
        _emit_bench_event({
            "metric": "dense_blockmatmul_tflops_per_chip",
            "value": round(tpu, 3), "n": N, "dtype": DTYPE,
            "attempts": 1 + len(errors), "phases": phases,
            "interval": interval,
            "wall_s": round(time.monotonic() - t_start, 1)})
        print(json.dumps({
            "metric": "dense_blockmatmul_tflops_per_chip",
            "value": round(tpu, 3),
            "unit": "TFLOPS",
            "vs_baseline": round(tpu / base, 2),
            "interval": interval,
        }))
        return

    # Final failure: still one parseable JSON line, rc 0 — the harness
    # records the structured error instead of a stack trace, and a
    # DISTINCT bench_error event (error tail + last-known-good) so the
    # history roll-up shows the failure per metric.
    last = _load_last_good()
    _emit_bench_error(
        "dense_blockmatmul_tflops_per_chip", "; ".join(errors),
        extra={"n": N, "dtype": DTYPE, "attempts": 1 + len(errors),
               "wall_s": round(time.monotonic() - t_start, 1)},
        last_good=last)
    print(json.dumps({
        "metric": "dense_blockmatmul_tflops_per_chip",
        "value": None,
        "unit": "TFLOPS",
        "vs_baseline": None,
        "error": "; ".join(errors)[-1000:],
        "last_known_good": last,
    }))


def main_serve() -> None:
    """Wedge-safe serving-QPS row capture (tools/tpu_batch.sh step):
    probe, then the measurement child under a hard timeout; one
    parseable JSON line either way, rc 0 — same contract as the
    headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("serve", MEASURE_TIMEOUT_S)
    record = {"metric": "serve_repeated_traffic_qps"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_cse() -> None:
    """Wedge-safe shared-interior CSE/template row capture
    (tools/tpu_batch.sh step): probe, then the measurement child under
    a hard timeout; one parseable JSON line either way, rc 0 — same
    contract as the headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("cse", MEASURE_TIMEOUT_S)
    record = {"metric": "cse_shared_interior_batch"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_coeffs() -> None:
    """Wedge-safe calibrated-vs-analytic planner row capture
    (tools/tpu_batch.sh step): probe, then the measurement child under
    a hard timeout; one parseable JSON line either way, rc 0 — same
    contract as the headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("coeffs", MEASURE_TIMEOUT_S)
    record = {"metric": "coeff_planner_sweep"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_precision() -> None:
    """Wedge-safe precision-tier row capture (tools/tpu_batch.sh step):
    probe, then the measurement child under a hard timeout; one
    parseable JSON line either way, rc 0 — same contract as the
    headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("precision", MEASURE_TIMEOUT_S)
    record = {"metric": "precision_tier_sweep"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_reshard() -> None:
    """Wedge-safe reshard-sweep row capture (tools/tpu_batch.sh step):
    probe, then the measurement child under a hard timeout; one
    parseable JSON line either way, rc 0 — same contract as the
    headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("reshard", MEASURE_TIMEOUT_S)
    record = {"metric": "reshard_sweep"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_sparse_kernels() -> None:
    """Wedge-safe structure-specialized kernel sweep capture
    (tools/tpu_batch.sh step): probe, then the measurement child under
    a hard timeout; one parseable JSON line either way, rc 0 — same
    contract as the headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("sparse_kernels", MEASURE_TIMEOUT_S)
    record = {"metric": "sparse_kernel_sweep"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_fusion() -> None:
    """Wedge-safe fused-vs-staged fusion sweep capture
    (tools/tpu_batch.sh step): probe, then the measurement child under
    a hard timeout; one parseable JSON line either way, rc 0 — same
    contract as the headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("fusion", MEASURE_TIMEOUT_S)
    record = {"metric": "fusion_region_sweep"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_fleet() -> None:
    """Wedge-safe multi-slice fleet scale-out row capture
    (tools/tpu_batch.sh step): probe, then the measurement child under
    a hard timeout; one parseable JSON line either way, rc 0 — same
    contract as the headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("fleet", MEASURE_TIMEOUT_S)
    record = {"metric": "fleet_scaleout_qps"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_spill() -> None:
    """Wedge-safe spill-hierarchy row capture (tools/tpu_batch.sh
    step): probe, then the measurement child under a hard timeout;
    one parseable JSON line either way, rc 0 — same contract as the
    headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("spill", MEASURE_TIMEOUT_S)
    record = {"metric": "spill_sweep"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_stream() -> None:
    """Wedge-safe streaming-IVM row capture (tools/tpu_batch.sh step):
    probe, then the measurement child under a hard timeout; one
    parseable JSON line either way, rc 0 — same contract as the
    headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("stream", MEASURE_TIMEOUT_S)
    record = {"metric": "stream_update_latency"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


def main_spgemm() -> None:
    """Wedge-safe SpGEMM row capture (tools/tpu_batch.sh step): probe,
    then the measurement child under a hard timeout; one parseable JSON
    line either way, rc 0 — same contract as the headline metric."""
    ok, payload = _run_child("probe", PROBE_TIMEOUT_S)
    if ok:
        ok, payload = _run_child("spgemm", MEASURE_TIMEOUT_S)
    record = {"metric": "blocksparse_spgemm_100k_1pct"}
    if ok and isinstance(payload, dict):
        record.update(payload)
        _emit_bench_event(dict(record))
    else:
        record.update({"value": None, "error": str(payload)[:500]})
        _emit_bench_error(record["metric"], str(payload))
    print(json.dumps(record))


if __name__ == "__main__":
    if "--_probe" in sys.argv:
        probe_tpu()
        print(json.dumps({"probe": "ok"}))
    elif "--_measure" in sys.argv:
        print(json.dumps(measure_tpu()))
    elif "--_spgemm" in sys.argv:
        print(json.dumps(measure_spgemm()))
    elif "--_serve" in sys.argv:
        print(json.dumps(measure_serve()))
    elif "--_cse" in sys.argv:
        print(json.dumps(measure_cse()))
    elif "--_precision" in sys.argv:
        print(json.dumps(measure_precision()))
    elif "--_coeffs" in sys.argv:
        print(json.dumps(measure_coeffs()))
    elif "--_reshard" in sys.argv:
        print(json.dumps(measure_reshard()))
    elif "--_sparse_kernels" in sys.argv:
        print(json.dumps(measure_sparse_kernels()))
    elif "--_fusion" in sys.argv:
        print(json.dumps(measure_fusion()))
    elif "--_stream" in sys.argv:
        print(json.dumps(measure_stream()))
    elif "--_fleet" in sys.argv:
        print(json.dumps(measure_fleet()))
    elif "--_spill" in sys.argv:
        print(json.dumps(measure_spill()))
    elif "--spill" in sys.argv:
        main_spill()
    elif "--fleet" in sys.argv:
        main_fleet()
    elif "--stream" in sys.argv:
        main_stream()
    elif "--fusion" in sys.argv:
        main_fusion()
    elif "--sparse-kernels" in sys.argv:
        main_sparse_kernels()
    elif "--reshard" in sys.argv:
        main_reshard()
    elif "--spgemm" in sys.argv:
        main_spgemm()
    elif "--serve" in sys.argv:
        main_serve()
    elif "--cse" in sys.argv:
        main_cse()
    elif "--precision" in sys.argv:
        main_precision()
    elif "--coeffs" in sys.argv:
        main_coeffs()
    elif "--cpu-rows" in sys.argv:
        # host-only (no jax, relay-safe): BASELINE rows 2-6 + the
        # SpGEMM row's CPU reference column, cached in cpu_baseline.json
        cpu_rows()
    else:
        main()
