"""Reshard planner (parallel/reshard.py; docs/RESHARD.md): plan
compilation + accounting, bit-identical cost fidelity vs the legacy
closed forms, peak-bounded staging, staged execution equivalence,
MV109, MV105's hint, obs/drift/autotune wiring, and the
default-config constructs-nothing contract."""

import json

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.parallel import planner, reshard as reshard_lib

GRIDS = ((2, 4), (4, 2), (2, 2), (1, 8), (8, 1))
PAIRS = (("row", "2d"), ("2d", "row"), ("col", "2d"), ("2d", "col"),
         ("row", "col"), ("col", "row"), ("2d", "rep"), ("row", "rep"),
         ("col", "rep"), ("rep", "row"), ("rep", "col"), ("rep", "2d"))


def _cfg(**kw):
    return MatrelConfig(obs_level="off", **kw)


class TestCompile:
    def test_steps_chain_src_to_dst(self):
        for gx, gy in GRIDS:
            for src, dst in PAIRS:
                plan = reshard_lib.compile_reshard(src, dst, 1e6, gx,
                                                   gy)
                state = src
                for s in plan.steps:
                    assert s.src_state == state, (src, dst, plan.steps)
                    state = s.dst_state
                    assert s.kind in reshard_lib.STEP_KINDS
                assert state == dst or not plan.steps and src == dst \
                    or gx * gy == 1

    def test_identity_and_single_device_empty(self):
        assert reshard_lib.compile_reshard("row", "row", 1e6, 2,
                                           4).steps == ()
        assert reshard_lib.compile_reshard("row", "col", 1e6, 1,
                                           1).steps == ()

    def test_rep_source_is_free_slice(self):
        plan = reshard_lib.compile_reshard("rep", "col", 1e6, 2, 4)
        assert plan.step_kinds == ("slice",)
        assert plan.weighted_cost == 0.0
        assert plan.bytes_x == plan.bytes_y == 0.0

    def test_unknown_layout_raises(self):
        with pytest.raises(ValueError):
            reshard_lib.compile_reshard("diag", "2d", 1e6, 2, 4)

    def test_other_normalises_to_2d(self):
        a = reshard_lib.compile_reshard("other", "row", 1e6, 2, 4)
        b = reshard_lib.compile_reshard("2d", "row", 1e6, 2, 4)
        assert a.weighted_cost == b.weighted_cost
        assert a.step_kinds == b.step_kinds

    def test_cost_bit_identical_to_closed_forms_uniform(self):
        """The acceptance equality: an UNCONSTRAINED plan's cost equals
        the legacy closed form bit-for-bit on uniform meshes, for every
        pair in the vocabulary, across grids and sizes."""
        for gx, gy in GRIDS:
            for B in (4096.0, 1e6, 12345678.0):
                for lay in ("row", "col"):
                    got = reshard_lib.compile_reshard(
                        lay, "2d", B, gx, gy).weighted_cost
                    assert got == planner._to_2d_reshard(B, lay, gx, gy)
                for lay, axis in (("2d", "row"), ("2d", "col"),
                                  ("row", "col"), ("col", "row"),
                                  ("rep", "row")):
                    got = reshard_lib.compile_reshard(
                        lay, axis, B, gx, gy).weighted_cost
                    assert got == planner._reshard_to_axis(
                        B, lay, axis, gx, gy)
                for lay in ("2d", "row", "col"):
                    got = reshard_lib.compile_reshard(
                        lay, "rep", B, gx, gy).weighted_cost
                    assert got == planner._split_full_mesh(
                        B, gx, gy, 1.0, 1.0)[0]

    def test_cost_bit_identical_weighted(self):
        for wts in ((8.0, 1.0), (1.0, 8.0), (2.5, 1.5)):
            for gx, gy in ((2, 4), (4, 2)):
                B = 1e6
                got = reshard_lib.compile_reshard(
                    "2d", "rep", B, gx, gy, wts).weighted_cost
                assert got == planner._split_full_mesh(B, gx, gy,
                                                       *wts)[0]
                got = reshard_lib.compile_reshard(
                    "row", "col", B, gx, gy, wts).weighted_cost
                assert got == planner._reshard_to_axis(
                    B, "row", "col", gx, gy, weights=wts)

    def test_weighted_mesh_picks_cheaper_axis_order(self):
        """Acceptance: a weighted mesh provably orders the gather
        stages cheaper than the naive (y-first) sequence — the
        expensive axis rides the small first stage."""
        gx, gy, B = 2, 4, 1e6
        p = gx * gy
        plan = reshard_lib.compile_reshard("2d", "rep", B, gx, gy,
                                           (8.0, 1.0))
        naive_y_first = 8.0 * B * (gx - 1) / gx + 1.0 * B * (gy - 1) / p
        assert plan.weighted_cost < naive_y_first
        # x-first: the expensive x stage moved while shards were small
        assert plan.steps[0].axis == "x"
        assert plan.steps[1].axis == "y"

    def test_budget_forces_staged_cross_move(self):
        gx, gy, B = 2, 4, 1e6
        p = gx * gy
        unb = reshard_lib.compile_reshard("row", "col", B, gx, gy)
        assert unb.step_kinds == ("oneshot",)
        assert unb.peak_bytes > B              # the full-gather model
        bounded = reshard_lib.compile_reshard("row", "col", B, gx, gy,
                                              peak_budget=4 * B / p)
        assert bounded.step_kinds == ("all_to_all", "all_to_all")
        assert bounded.peak_bytes == 2 * B / p
        assert bounded.fits(4 * B / p)
        # honest pricing: the bounded plan moves MORE bytes
        assert bounded.weighted_cost > unb.weighted_cost
        assert bounded.naive_peak_bytes == unb.peak_bytes

    def test_unfittable_budget_returns_min_peak_unfit_plan(self):
        gx, gy, B = 2, 4, 1e6
        plan = reshard_lib.compile_reshard("row", "col", B, gx, gy,
                                           peak_budget=B / gx / gy)
        assert not plan.fits(B / gx / gy)
        assert plan.step_kinds == ("all_to_all", "all_to_all")

    def test_to_dict_roundtrip_fields(self):
        plan = reshard_lib.compile_reshard("row", "col", 1e6, 2, 4,
                                           peak_budget=1e6)
        d = plan.to_dict()
        assert d["src"] == "row" and d["dst"] == "col"
        assert d["steps"] == list(plan.step_kinds)
        assert d["bytes_by_axis"] == [plan.bytes_x, plan.bytes_y]
        assert d["peak_bytes"] == plan.peak_bytes


class TestPlannerPricing:
    def test_reshard_to_axis_plan_path_matches_closed_forms(self):
        """With the budget on but not binding, the plan-priced
        `_reshard_to_axis` equals the closed forms bit-identically
        (single-axis moves share the exact float expressions)."""
        cfg = _cfg(reshard_peak_budget_bytes=1 << 40)
        for gx, gy in GRIDS:
            for B in (4096.0, 1e6):
                for lay, axis in (("2d", "row"), ("2d", "col"),
                                  ("row", "col"), ("col", "row"),
                                  ("rep", "col"), ("row", "row")):
                    assert planner._reshard_to_axis(
                        B, lay, axis, gx, gy, config=cfg) == \
                        planner._reshard_to_axis(B, lay, axis, gx, gy)

    def test_tight_budget_prices_the_staged_bill(self):
        gx, gy, B = 2, 4, 1e6
        cfg = _cfg(reshard_peak_budget_bytes=int(4 * B / (gx * gy)))
        staged = planner._reshard_to_axis(B, "row", "col", gx, gy,
                                          config=cfg)
        closed = planner._reshard_to_axis(B, "row", "col", gx, gy)
        assert staged > closed

    def test_default_config_constructs_no_plans(self, mesh8,
                                                monkeypatch):
        """The bit-identity contract: with the default budget (0), a
        full compile+run constructs ZERO ReshardPlan objects."""
        def _poisoned(*a, **k):
            raise AssertionError("ReshardPlan constructed under the "
                                 "default config")
        monkeypatch.setattr(reshard_lib, "compile_reshard", _poisoned)
        from matrel_tpu import executor
        A = BlockMatrix.random((64, 32), mesh=mesh8, seed=0)
        B = BlockMatrix.random((32, 48), mesh=mesh8, seed=1)
        e = A.expr().multiply(B.expr())
        out = executor.execute(e, mesh8, _cfg())
        np.testing.assert_allclose(
            out.to_numpy(), A.to_numpy() @ B.to_numpy(), rtol=2e-4,
            atol=2e-4)
        # and matmul_decisions (the obs read path) builds none either
        plan = executor.compile_expr(e, mesh8, _cfg())
        recs = executor.plan_matmul_decisions(plan)
        assert all("reshard" not in r for r in recs)


class TestStagedExecution:
    @pytest.mark.parametrize("src,dst", [("row", "col"), ("col", "row"),
                                         ("row", "2d"), ("2d", "rep")])
    def test_staged_equals_naive_values(self, mesh8, src, dst):
        import jax
        from jax.sharding import NamedSharding
        n = 64
        gx, gy = mesh_lib.mesh_grid_shape(mesh8)
        p = gx * gy
        x = np.random.default_rng(3).standard_normal(
            (n, n)).astype(np.float32)
        xd = jax.device_put(
            x, NamedSharding(mesh8,
                             reshard_lib._state_spec(src, mesh8)))
        plan = reshard_lib.compile_reshard(
            src, dst, float(n) * n * 4, gx, gy,
            peak_budget=4.0 * n * n * 4 / p)
        staged = jax.jit(
            lambda v: reshard_lib.apply_staged(v, plan, mesh8))
        naive = jax.jit(lambda v: jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh8,
                             reshard_lib._state_spec(dst, mesh8))))
        np.testing.assert_array_equal(np.asarray(staged(xd)),
                                      np.asarray(naive(xd)))
        np.testing.assert_array_equal(np.asarray(staged(xd)), x)

    def test_staged_cross_move_hlo_is_pure_all_to_all(self, mesh8):
        """The peak claim made structural: the staged row→col chain
        compiles to all-to-alls — no all-gather, hence no full-array
        transient — while carrying one reshard annotate per step."""
        import jax
        from jax.sharding import NamedSharding
        n = 64
        gx, gy = mesh_lib.mesh_grid_shape(mesh8)
        xd = jax.device_put(
            np.zeros((n, n), np.float32),
            NamedSharding(mesh8, reshard_lib._state_spec("row",
                                                         mesh8)))
        plan = reshard_lib.compile_reshard(
            "row", "col", float(n) * n * 4, gx, gy,
            peak_budget=4.0 * n * n * 4 / (gx * gy))
        staged = jax.jit(
            lambda v: reshard_lib.apply_staged(v, plan, mesh8))
        hlo = staged.lower(xd).compile().as_text()
        assert "all-to-all" in hlo
        assert "all-gather" not in hlo

    def test_end_to_end_staged_matmul_matches_oracle(self, mesh8):
        """A bmm_left whose RIGHT operand arrives row-sharded (the
        opposite-1D cross move): under the budget the lowering stages
        the re-lay and the result still matches numpy exactly-ish."""
        from jax.sharding import PartitionSpec as P
        from matrel_tpu import executor
        x, y = mesh8.axis_names
        A = BlockMatrix.random((16, 64), mesh=mesh8, seed=0)
        Bm = BlockMatrix.random((64, 64), mesh=mesh8, seed=1,
                                spec=P((x, y), None))
        e = A.expr().multiply(Bm.expr())
        n, k = 16, 64
        p = 8
        budget = int(4 * 64 * 64 * 4 / p) + 1
        cfg = _cfg(strategy_override="bmm_left",
                   reshard_peak_budget_bytes=budget)
        out = executor.execute(e, mesh8, cfg)
        np.testing.assert_allclose(
            out.to_numpy(), A.to_numpy() @ Bm.to_numpy(), rtol=2e-4,
            atol=2e-4)
        # the decision record carries the staged move's accounting
        plan = executor.compile_expr(e, mesh8, cfg)
        recs = executor.plan_matmul_decisions(plan)
        (rec,) = recs
        assert rec["reshard"]["steps"] == ["all_to_all", "all_to_all"]
        assert rec["reshard"]["moves"] == [
            {"operand": 1, "src": "row", "dst": "col"}]
        assert rec["reshard"]["peak_bytes"] <= budget

    def test_budgeted_suite_numerics_unchanged(self, mesh8):
        """Ordinary canonical-layout queries under the budget run
        bit-equal to the default config (no staged moves trigger —
        everything is already where its strategy wants it)."""
        from matrel_tpu import executor
        A = BlockMatrix.random((64, 32), mesh=mesh8, seed=5)
        B = BlockMatrix.random((32, 48), mesh=mesh8, seed=6)
        e = A.expr().multiply(B.expr()).add_scalar(1.0)
        base = executor.execute(e, mesh8, _cfg()).to_numpy()
        staged = executor.execute(
            e, mesh8, _cfg(reshard_peak_budget_bytes=1 << 30)
        ).to_numpy()
        np.testing.assert_array_equal(base, staged)


class TestMV109:
    def _planned(self, mesh, cfg):
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.ir import rules
        x, y = mesh.axis_names
        A = BlockMatrix.random((16, 64), mesh=mesh, seed=0)
        Bm = BlockMatrix.random((64, 64), mesh=mesh, seed=1,
                                spec=P((x, y), None))
        e = A.expr().multiply(Bm.expr())
        opt = rules.optimize(e, cfg,
                             grid=mesh_lib.mesh_grid_shape(mesh),
                             mesh=mesh)
        return planner.annotate_strategies(opt, mesh, cfg)

    def test_clean_under_generous_budget(self, mesh8):
        from matrel_tpu import analysis
        cfg = _cfg(strategy_override="bmm_left",
                   reshard_peak_budget_bytes=1 << 30)
        diags = analysis.verify_plan(self._planned(mesh8, cfg), mesh8,
                                     cfg)
        assert [d for d in diags if d.code == "MV109"] == []

    def test_unfittable_budget_is_an_error(self, mesh8):
        from matrel_tpu import analysis
        # below 2·B/p for the 64x64 f32 operand: no decomposition fits
        cfg = _cfg(strategy_override="bmm_left",
                   reshard_peak_budget_bytes=1024)
        diags = [d for d in analysis.verify_plan(
            self._planned(mesh8, cfg), mesh8, cfg)
            if d.code == "MV109"]
        assert diags and diags[0].severity == "error"
        assert "no decomposition" in diags[0].message
        assert "reshard_peak_budget_bytes" in diags[0].fix_hint

    def test_hand_stamped_over_peak_plan_flagged(self, mesh8):
        """The acceptance fixture: a hand-stamped reshard record whose
        claimed peak understates the recompiled plan's is an error."""
        from matrel_tpu import analysis
        cfg = _cfg(reshard_peak_budget_bytes=1 << 20)
        A = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        B = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
        e = planner.annotate_strategies(
            A.expr().multiply(B.expr()), mesh8, cfg)
        stamped = e.with_attrs(reshard={
            "src": "row", "dst": "col", "nbytes": float(1 << 26),
            "steps": ["all_to_all", "all_to_all"],
            "peak_bytes": 8.0})           # wildly understated
        diags = [d for d in analysis.verify_plan(stamped, mesh8, cfg)
                 if d.code == "MV109"]
        assert diags and all(d.severity == "error" for d in diags)
        assert any("understates" in d.message for d in diags)
        # over the verifying budget too: both findings fire
        assert any("no decomposition" in d.message for d in diags)

    def test_bad_stamp_vocabulary_flagged(self, mesh8):
        from matrel_tpu import analysis
        cfg = _cfg(reshard_peak_budget_bytes=1 << 20)
        A = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        B = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
        e = planner.annotate_strategies(
            A.expr().multiply(B.expr()), mesh8, cfg)
        stamped = e.with_attrs(reshard={"src": "diag", "dst": "2d",
                                        "nbytes": 1.0})
        diags = [d for d in analysis.verify_plan(stamped, mesh8, cfg)
                 if d.code == "MV109"]
        assert diags and "vocabulary" in diags[0].message

    def test_root_relay_over_budget_is_an_error(self, mesh8):
        """Review r9: a root whose canonical re-lay cannot fit the
        budget must be flagged — previously MV109 only walked operand
        moves, so a plan verified clean could still run an over-peak
        root move."""
        from jax.sharding import PartitionSpec as P
        from matrel_tpu import analysis
        x, y = mesh8.axis_names
        A = BlockMatrix.random((16, 64), mesh=mesh8, seed=0)
        # B already col-sharded: bmm_left's operand move is free, but
        # the bmm_left ROOT emits "col" and pays the col->2d re-lay
        Bm = BlockMatrix.random((64, 64), mesh=mesh8, seed=1,
                                spec=P(None, (x, y)))
        cfg = _cfg(strategy_override="bmm_left",
                   reshard_peak_budget_bytes=64)
        e = planner.annotate_strategies(
            A.expr().multiply(Bm.expr()), mesh8, cfg)
        diags = [d for d in analysis.verify_plan(e, mesh8, cfg)
                 if d.code == "MV109"]
        assert diags, "root re-lay over budget must flag"
        assert any("root canonical re-lay" in d.message for d in diags)
        # a generous budget clears it
        cfg_ok = _cfg(strategy_override="bmm_left",
                      reshard_peak_budget_bytes=1 << 30)
        e2 = planner.annotate_strategies(
            A.expr().multiply(Bm.expr()), mesh8, cfg_ok)
        assert [d for d in analysis.verify_plan(e2, mesh8, cfg_ok)
                if d.code == "MV109"] == []

    def test_stamp_without_nbytes_flagged(self, mesh8):
        """Review r9: a stamp missing (or zeroing) 'nbytes' would
        recompile as a 0-byte move and bypass both checks — it must be
        an error like bad vocabulary."""
        from matrel_tpu import analysis
        cfg = _cfg(reshard_peak_budget_bytes=1 << 20)
        A = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        B = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
        base = planner.annotate_strategies(
            A.expr().multiply(B.expr()), mesh8, cfg)
        for bad in ({"src": "row", "dst": "col", "peak_bytes": 8.0},
                    {"src": "row", "dst": "col", "nbytes": 0.0},
                    {"src": "row", "dst": "col", "nbytes": "big"}):
            diags = [d for d in analysis.verify_plan(
                base.with_attrs(reshard=bad), mesh8, cfg)
                if d.code == "MV109"]
            assert diags and diags[0].severity == "error", bad
            assert "nbytes" in diags[0].message, bad

    def test_default_budget_pass_silent(self, mesh8):
        from matrel_tpu import analysis
        cfg = _cfg()
        A = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        B = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
        e = planner.annotate_strategies(
            A.expr().multiply(B.expr()), mesh8, cfg)
        assert [d for d in analysis.verify_plan(e, mesh8, cfg)
                if d.code == "MV109"] == []


class TestMV105Hint:
    def _over_budget_plan(self, mesh):
        """An rmm hand-stamp whose working set exceeds a tiny HBM
        budget while cpmm would fit — the refusal MV105 can now hint
        out of."""
        A = BlockMatrix.random((64, 64), mesh=mesh, seed=0)
        B = BlockMatrix.random((64, 64), mesh=mesh, seed=1)
        e = A.expr().multiply(B.expr())
        return e.with_attrs(strategy="rmm", strategy_source="override")

    def test_refusal_hints_the_reshard_knob(self, mesh8):
        from matrel_tpu import analysis
        # rmm working set: a/gx + b/gy + c/p = 64*64*4*(1/2+1/4+1/8)
        # = 14336 B; cpmm: a/p + b/gy + c/gx = 64*64*4*(1/8+1/4+1/2)
        # = 14336 B... use a skewed shape so they separate
        A = BlockMatrix.random((64, 512), mesh=mesh8, seed=0)
        B = BlockMatrix.random((512, 64), mesh=mesh8, seed=1)
        e = A.expr().multiply(B.expr()).with_attrs(
            strategy="rmm", strategy_source="override")
        # rmm: a/gx + b/gy + c/p; cpmm: a/p + b/gy + c/gx — with the
        # fat contraction dim rmm replicates far more
        need_rmm = planner.strategy_hbm_bytes("rmm", 64, 512, 64, 2, 4)
        need_cpmm = planner.strategy_hbm_bytes("cpmm", 64, 512, 64, 2,
                                               4)
        budget = int((need_rmm + need_cpmm) / 2)
        assert need_cpmm < budget < need_rmm
        cfg = _cfg(hbm_budget_bytes=budget)
        diags = [d for d in analysis.verify_plan(e, mesh8, cfg)
                 if d.code == "MV105"]
        assert diags, "MV105 must fire on the over-budget rmm stamp"
        assert "reshard_peak_budget_bytes" in diags[0].fix_hint

    def test_hinted_config_actually_runs_it(self, mesh8):
        """The refused operand MOVES under the hinted config: the
        planner routes to a budget-fitting strategy and the staged
        reshard lowering executes to the oracle."""
        from matrel_tpu import executor
        need_rmm = planner.strategy_hbm_bytes("rmm", 64, 512, 64, 2, 4)
        need_cpmm = planner.strategy_hbm_bytes("cpmm", 64, 512, 64, 2,
                                               4)
        budget = int((need_rmm + need_cpmm) / 2)
        cfg = _cfg(hbm_budget_bytes=budget,
                   # bmm broadcasts would blow the same budget
                   broadcast_threshold_bytes=1,
                   reshard_peak_budget_bytes=1 << 20,
                   verify_plans="error")
        A = BlockMatrix.random((64, 512), mesh=mesh8, seed=0)
        B = BlockMatrix.random((512, 64), mesh=mesh8, seed=1)
        e = A.expr().multiply(B.expr())
        plan = executor.compile_expr(e, mesh8, cfg)
        strat = plan.optimized.attrs["strategy"]
        assert strat != "rmm"
        out = plan.run().to_numpy()
        np.testing.assert_allclose(out, A.to_numpy() @ B.to_numpy(),
                                   rtol=2e-4, atol=2e-4)


class TestChainDegrade:
    def test_budget_degrades_native_to_python_dp(self, mesh8,
                                                 monkeypatch):
        from matrel_tpu.ir import chain
        from matrel_tpu.utils import native

        def _boom(*a, **k):
            raise AssertionError("native DP consulted under a reshard "
                                 "budget — must degrade to Python")
        monkeypatch.setattr(native, "chain_dp", _boom)
        ops = [BlockMatrix.random((32, 64), mesh=mesh8, seed=0).expr(),
               BlockMatrix.random((64, 16), mesh=mesh8, seed=1).expr(),
               BlockMatrix.random((16, 48), mesh=mesh8, seed=2).expr()]
        cfg = _cfg(reshard_peak_budget_bytes=1 << 20)
        e, cost = chain.optimal_order(ops, grid=(2, 4), mesh=mesh8,
                                      config=cfg)
        assert cost >= 0.0

    def test_budget_zero_matches_native_pricing(self, mesh8):
        """Native-mirror hygiene: at budget 0 the plan-derived costs
        the Python DP would use ARE the closed forms the native mirror
        implements — cross-checked per leg across random shapes."""
        rng = np.random.default_rng(7)
        for _ in range(25):
            B = float(rng.integers(1 << 10, 1 << 24))
            gx, gy = GRIDS[rng.integers(0, len(GRIDS))]
            for lay in ("row", "col"):
                assert reshard_lib.compile_reshard(
                    lay, "2d", B, gx, gy).weighted_cost == \
                    planner._to_2d_reshard(B, lay, gx, gy)
            wts = (float(rng.integers(1, 9)), float(rng.integers(1, 9)))
            assert reshard_lib.compile_reshard(
                "2d", "rep", B, gx, gy, wts).weighted_cost == \
                planner._split_full_mesh(B, gx, gy, *wts)[0]


class TestObsRollups:
    def _events_with_reshard(self):
        return [{"kind": "query", "query_id": "q1", "cache": "miss",
                 "matmuls": [{"uid": 1, "dims": [64, 64, 64],
                              "strategy": "bmm_left",
                              "source": "override",
                              "flops": 2.0 * 64 ** 3,
                              "est_ici_bytes": 100.0,
                              "reshard": {
                                  "steps": ["all_to_all",
                                            "all_to_all"],
                                  "bytes_by_axis": [1024.0, 2048.0],
                                  "peak_bytes": 4096.0,
                                  "moves": [{"operand": 1,
                                             "src": "row",
                                             "dst": "col"}]}}]}]

    def test_history_summary_reshard_line(self):
        from matrel_tpu.obs import history
        s = history.summarize(self._events_with_reshard())
        rsh = s["reshards"]
        assert rsh["matmuls"] == 1
        assert rsh["steps"] == {"all_to_all": 2}
        assert rsh["bytes_x"] == 1024.0 and rsh["bytes_y"] == 2048.0
        assert rsh["peak_bytes"] == 4096.0
        text = history.render_summary(self._events_with_reshard())
        assert "reshards: 1 staged matmul move(s)" in text
        assert "all_to_all=2" in text

    def test_no_reshards_no_line(self):
        from matrel_tpu.obs import history
        events = [{"kind": "query", "cache": "miss", "matmuls": []}]
        assert history.summarize(events)["reshards"] is None
        assert "reshards:" not in history.render_summary(events)

    def test_drift_reshard_rows_and_flag(self, tmp_path):
        """Seeded miscalibration: the model prefers the one-shot
        (fewer est bytes) but it measured 2x slower — the reshard
        DRIFT flag fires and reshard:<kind> calibration rows exist."""
        from matrel_tpu.obs import drift
        events = [{"kind": "bench", "metric": "reshard_sweep",
                   "backend": "cpu",
                   "rows": [{"pair": "row->col", "n": 1024,
                             "kind": "all_to_all",
                             "staged_ms": 1.0, "naive_ms": 2.0,
                             "staged_bytes": 4096.0,
                             "naive_bytes": 2048.0,
                             "peak_bytes": 10.0,
                             "naive_peak_bytes": 100.0}]}]
        samples = list(drift.iter_samples(events))
        assert {s["strategy"] for s in samples} == {
            "reshard:all_to_all", "reshard:oneshot"}
        calib = drift.calibrate(samples)
        assert any(r["strategy"] == "reshard:all_to_all"
                   and r["ms_per_est_mib"] is not None
                   for r in calib.values())
        flags = drift.rank_flags(samples)
        assert any(f["model_prefers"] == "reshard:oneshot"
                   and f["measured_prefers"] == "reshard:all_to_all"
                   for f in flags)
        report = drift.report(events,
                              table_path_str=str(tmp_path / "d.json"))
        assert "reshard:" in report and "DRIFT" in report

    def test_drift_ignores_malformed_rows(self):
        from matrel_tpu.obs import drift
        events = [{"kind": "bench", "metric": "reshard_sweep",
                   "rows": [{"pair": "x", "staged_ms": 0,
                             "naive_ms": None}, "junk"]}]
        assert list(drift.iter_samples(events)) == []


class TestAutotuneReshard:
    def test_key_format_accepted_and_legacy_pruned(self):
        from matrel_tpu.parallel import autotune
        assert autotune._current_key_format(
            "reshard|row>col|4096|2x4|cpu")
        assert autotune._current_key_format(
            "reshard|row>col|4096|2x4|cpu|w1x8")
        assert not autotune._current_key_format("reshard|row>col|4096")

    def test_lookup_measures_persists_and_caches(self, mesh8,
                                                 monkeypatch,
                                                 tmp_path):
        from matrel_tpu.parallel import autotune
        table = tmp_path / "at.json"
        cfg = _cfg(autotune=True, autotune_table_path=str(table))
        gx, gy = mesh_lib.mesh_grid_shape(mesh8)
        plan = reshard_lib.compile_reshard(
            "row", "col", 256.0 * 256 * 4, gx, gy,
            peak_budget=4.0 * 256 * 256 * 4 / 8)
        times = {"staged": 0.001, "naive": 0.005}
        monkeypatch.setattr(
            autotune, "measure_reshard_variant",
            lambda v, p, m, c=None, n_times=5: times[v])
        autotune._RESHARD_CACHE.clear()
        assert autotune.lookup_or_measure_reshard(plan, mesh8,
                                                  cfg) == "staged"
        persisted = json.loads(table.read_text())
        key = [k for k in persisted if k.startswith("reshard|")]
        assert key and persisted[key[0]]["best"] == "staged"
        # second call answers from cache: poison the measurer
        monkeypatch.setattr(
            autotune, "measure_reshard_variant",
            lambda *a, **k: (_ for _ in ()).throw(AssertionError()))
        assert autotune.lookup_or_measure_reshard(plan, mesh8,
                                                  cfg) == "staged"

    def test_single_step_plans_never_measured(self, mesh8):
        from matrel_tpu.parallel import autotune
        gx, gy = mesh_lib.mesh_grid_shape(mesh8)
        plan = reshard_lib.compile_reshard("row", "2d",
                                           256.0 * 256 * 4, gx, gy)
        assert autotune.lookup_or_measure_reshard(
            plan, mesh8, _cfg(autotune=True)) is None

    def test_measured_naive_winner_skips_staging(self, mesh8,
                                                 monkeypatch):
        from jax.sharding import PartitionSpec as P
        from matrel_tpu import executor
        from matrel_tpu.parallel import autotune
        monkeypatch.setattr(autotune, "lookup_or_measure_reshard",
                            lambda *a, **k: "naive")
        x, y = mesh8.axis_names
        A = BlockMatrix.random((16, 64), mesh=mesh8, seed=0)
        Bm = BlockMatrix.random((64, 64), mesh=mesh8, seed=1,
                                spec=P((x, y), None))
        cfg = _cfg(strategy_override="bmm_left", autotune=True,
                   reshard_peak_budget_bytes=1 << 20)
        low = executor.Lowerer(mesh8, cfg)
        e = planner.annotate_strategies(
            A.expr().multiply(Bm.expr()), mesh8, cfg)
        a, b = A.data, Bm.data
        a2, b2 = low._stage_matmul_operands(e, a, b)
        assert a2 is a and b2 is b     # winner says: keep the one-shot

    def test_real_measure_smoke(self, mesh8):
        """One real (tiny) measurement through both lowerings."""
        from matrel_tpu.parallel import autotune
        gx, gy = mesh_lib.mesh_grid_shape(mesh8)
        plan = reshard_lib.compile_reshard(
            "row", "col", 64.0 * 64 * 4, gx, gy,
            peak_budget=4.0 * 64 * 64 * 4 / 8)
        for v in autotune.RESHARD_VARIANTS:
            t = autotune.measure_reshard_variant(v, plan, mesh8,
                                                 _cfg(), n_times=1)
            assert t > 0.0


class TestConfig:
    def test_negative_budget_rejected(self):
        with pytest.raises(ValueError):
            MatrelConfig(reshard_peak_budget_bytes=-1)

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("MATREL_RESHARD_PEAK_BUDGET_BYTES", "4096")
        cfg = MatrelConfig.from_env()
        assert cfg.reshard_peak_budget_bytes == 4096
