"""Workload acceptance tests (SURVEY.md §7.5, BASELINE.md rows 2/3/5):
linreg, chain reorder, PageRank — numerics vs host oracles on the 8-device
mesh."""

import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.workloads import chain_bench, linreg, pagerank


class TestLinreg:
    def _data(self, rng, n=256, k=8):
        x = rng.standard_normal((n, k)).astype(np.float32)
        theta_true = rng.standard_normal((k, 1)).astype(np.float32)
        y = x @ theta_true + 0.01 * rng.standard_normal((n, 1)).astype(np.float32)
        return x, y, theta_true

    def test_fit_matches_lstsq(self, mesh8, rng):
        x, y, _ = self._data(rng)
        X = BlockMatrix.from_numpy(x, mesh=mesh8)
        Y = BlockMatrix.from_numpy(y, mesh=mesh8)
        theta = np.asarray(linreg.fit(X, Y))
        oracle = np.linalg.lstsq(x, y, rcond=None)[0]
        np.testing.assert_allclose(theta, oracle, rtol=1e-2, atol=1e-3)

    def test_fit_fused_matches(self, mesh8, rng):
        x, y, _ = self._data(rng)
        from jax.sharding import PartitionSpec as P
        X = BlockMatrix.from_numpy(x, mesh=mesh8, spec=P(("x", "y"), None))
        Y = BlockMatrix.from_numpy(y, mesh=mesh8, spec=P(("x", "y"), None))
        theta = np.asarray(linreg.fit_fused(X, Y))
        oracle = np.linalg.lstsq(x, y, rcond=None)[0]
        np.testing.assert_allclose(theta, oracle, rtol=1e-2, atol=1e-3)

    def test_ridge_shrinks(self, mesh8, rng):
        x, y, _ = self._data(rng)
        X = BlockMatrix.from_numpy(x, mesh=mesh8)
        Y = BlockMatrix.from_numpy(y, mesh=mesh8)
        t0 = np.asarray(linreg.fit(X, Y, l2=0.0))
        t1 = np.asarray(linreg.fit(X, Y, l2=100.0))
        assert np.linalg.norm(t1) < np.linalg.norm(t0)


class TestChain:
    def test_skewed_chain_picks_cheap_order(self, mesh8):
        mats = chain_bench.skewed_abc(mesh8, n=256, mid=8)
        plan, paren, cost = chain_bench.compile_chain(mats)
        assert paren == "((A·B)·C)" or paren == "(A·(B·C))"
        # for n >> mid, (A·B)·C costs n*mid*n + n*n*mid vs A·(B·C): both
        # orders share no term; optimal is A·(B·C): mid·n·mid twice
        assert paren == "(A·(B·C))"

    def test_chain_numerics(self, mesh8, rng):
        a = rng.standard_normal((24, 4)).astype(np.float32)
        b = rng.standard_normal((4, 24)).astype(np.float32)
        c = rng.standard_normal((24, 4)).astype(np.float32)
        mats = [BlockMatrix.from_numpy(m, mesh=mesh8) for m in (a, b, c)]
        plan, _, _ = chain_bench.compile_chain(mats)
        out = plan.run()
        np.testing.assert_allclose(out.to_numpy(), a @ b @ c,
                                   rtol=1e-4, atol=1e-4)


class TestPageRank:
    def test_matches_oracle(self, mesh8, rng):
        n = 50
        a = (rng.random((n, n)) < 0.1).astype(np.float32)
        np.fill_diagonal(a, 0)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        r = np.asarray(pagerank.pagerank(A, rounds=30))
        oracle = pagerank.pagerank_numpy_oracle(a, rounds=30)
        np.testing.assert_allclose(r, oracle, rtol=1e-3, atol=1e-6)
        assert r.sum() == pytest.approx(1.0, rel=1e-3)

    def test_dangling_nodes_conserve_mass(self, mesh8):
        # node 2 has no out-edges
        a = np.array([[0, 1, 1], [1, 0, 0], [0, 0, 0]], dtype=np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        r = np.asarray(pagerank.pagerank(A, rounds=50))
        assert r.sum() == pytest.approx(1.0, rel=1e-4)
        oracle = pagerank.pagerank_numpy_oracle(a, rounds=50)
        np.testing.assert_allclose(r, oracle, rtol=1e-3, atol=1e-6)


class TestStreamingLinreg:
    def test_streaming_matches_dense(self, mesh8):
        import jax
        import jax.numpy as jnp
        from matrel_tpu.workloads.linreg import fit_streaming
        k, n, panel = 8, 512, 128
        theta_true = jnp.arange(1.0, k + 1.0).reshape(k, 1)

        def panel_fn(p):
            key = jax.random.fold_in(jax.random.PRNGKey(0), p)
            xp = jax.random.normal(key, (panel, k), jnp.float32)
            yp = xp @ theta_true
            return xp, yp

        theta = np.asarray(fit_streaming(n, k, panel_fn, panel_rows=panel,
                                         mesh=mesh8))
        np.testing.assert_allclose(theta, np.asarray(theta_true),
                                   rtol=1e-3, atol=1e-3)

    def test_streaming_high_symmetric_matches_oracle(self, mesh8):
        # round-3: precision="high" on f32 panels takes the SYMMETRIC
        # 2-pass bf16 split; theta must still recover to f32-level
        # accuracy and agree with the "highest" path closely
        import jax
        import jax.numpy as jnp
        from matrel_tpu.workloads.linreg import fit_streaming
        k, n, panel = 16, 1024, 256
        theta_true = jnp.linspace(-2.0, 2.0, k).reshape(k, 1)

        def panel_fn(p):
            key = jax.random.fold_in(jax.random.PRNGKey(3), p)
            xp = jax.random.normal(key, (panel, k), jnp.float32)
            yp = xp @ theta_true
            return xp, yp

        th_high = np.asarray(fit_streaming(n, k, panel_fn,
                                           panel_rows=panel, mesh=mesh8,
                                           precision="high"))
        th_highest = np.asarray(fit_streaming(n, k, panel_fn,
                                              panel_rows=panel,
                                              mesh=mesh8,
                                              precision="highest"))
        np.testing.assert_allclose(th_high, np.asarray(theta_true),
                                   rtol=5e-3, atol=5e-3)
        np.testing.assert_allclose(th_high, th_highest, rtol=5e-3,
                                   atol=5e-3)

    def test_symmetric_gram_term_equivalence(self):
        # the 2-pass identity itself: HiHi + HiLo + HiLo^T equals the
        # generic 3-term split HiHi + HiLo + LoHi exactly
        import jax.numpy as jnp
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((64, 8)), jnp.float32)
        hi = x.astype(jnp.bfloat16)
        lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        d = lambda a, b: jnp.einsum("nk,nj->kj", a, b,
                                    preferred_element_type=jnp.float32)
        sym = d(hi, hi) + d(hi, lo) + d(hi, lo).T
        generic = d(hi, hi) + d(hi, lo) + d(lo, hi)
        np.testing.assert_allclose(np.asarray(sym), np.asarray(generic),
                                   rtol=0, atol=0)



class TestEdgePageRank:
    def test_edges_matches_dense_oracle(self, mesh8, rng):
        from matrel_tpu.workloads.pagerank import pagerank_edges
        n = 60
        a = (rng.random((n, n)) < 0.08).astype(np.float32)
        np.fill_diagonal(a, 0)
        src, dst = np.nonzero(a)
        r = np.asarray(pagerank_edges(src, dst, n, rounds=30))
        oracle = pagerank.pagerank_numpy_oracle(a, rounds=30).ravel()
        np.testing.assert_allclose(r, oracle, rtol=1e-3, atol=1e-7)
        assert r.sum() == pytest.approx(1.0, rel=1e-3)

    def test_csr_matches_edges(self, mesh8, rng):
        from matrel_tpu.workloads.pagerank import pagerank_csr, pagerank_edges
        n = 80
        a = (rng.random((n, n)) < 0.1).astype(np.float32)
        np.fill_diagonal(a, 0)
        src, dst = np.nonzero(a)
        r_csr = np.asarray(pagerank_csr(src, dst, n, rounds=20))
        r_seg = np.asarray(pagerank_edges(src, dst, n, rounds=20))
        np.testing.assert_allclose(r_csr, r_seg, rtol=1e-4, atol=1e-8)

    def test_csr_fallback_on_hub(self, rng):
        from matrel_tpu.workloads import pagerank as pr
        n = 50
        # hub graph: every node points at node 0 (in-degree 49 >> mean 1)
        src = np.arange(1, n, dtype=np.int32)
        dst = np.zeros(n - 1, dtype=np.int32)
        r = np.asarray(pr.pagerank_csr(src, dst, n, rounds=10))
        assert r.shape == (n,) and abs(r.sum() - 1.0) < 1e-3


class TestStreamingBigChain:
    def test_streaming_chain_matches_numpy(self, mesh8):
        import jax.numpy as jnp
        from matrel_tpu.workloads.big_chain import streaming_chain, default_gen
        n, tile, panel = 64, 8, 16
        gens = tuple(default_gen(s, tile, jnp.float32, 0.05) for s in (1, 2, 3))
        got = float(streaming_chain(n, *gens, tile=tile, panel=panel,
                                    dtype=jnp.float32))
        kt = n // tile
        full = [np.block([[np.asarray(g(jnp.int32(i), jnp.int32(j)))
                           for j in range(kt)] for i in range(kt)])
                for g in gens]
        oracle = float(((full[0] @ full[1] @ full[2]) ** 2).sum())
        assert got == pytest.approx(oracle, rel=1e-4)

    def test_rejects_misaligned(self):
        from matrel_tpu.workloads.big_chain import streaming_chain, default_gen
        g = default_gen(0, 8)
        with pytest.raises(ValueError):
            streaming_chain(60, g, g, g, tile=8, panel=16)

    @pytest.mark.parametrize("mk", ["default_gen", "cheap_gen"])
    def test_slab_matches_numpy_and_accum(self, mk):
        import jax.numpy as jnp
        from matrel_tpu.workloads import big_chain
        gen_factory = getattr(big_chain, mk)
        n, tile, panel = 64, 8, 16
        gens = tuple(gen_factory(s, tile, jnp.float32, 0.05)
                     for s in (1, 2, 3))
        slab = float(big_chain.streaming_chain_slab(
            n, *gens, tile=tile, panel=panel, dtype=jnp.float32))
        accum = float(big_chain.streaming_chain(
            n, *gens, tile=tile, panel=panel, dtype=jnp.float32))
        full = [np.asarray(g.slab(0, 0, (n, n)), dtype=np.float64)
                for g in gens]
        oracle = float(((full[0] @ full[1] @ full[2]) ** 2).sum())
        assert slab == pytest.approx(accum, rel=1e-5)
        assert slab == pytest.approx(oracle, rel=1e-4)

    def test_slab_gen_consistency(self):
        # .slab(r0, c0) must produce exactly the tiles gen(bi, bj) does
        import jax.numpy as jnp
        from matrel_tpu.workloads.big_chain import default_gen, cheap_gen
        for mk in (default_gen, cheap_gen):
            g = mk(3, 8, jnp.float32, 0.05)
            tile_11 = np.asarray(g(1, 2))
            slab = np.asarray(g.slab(8, 16, (8, 8)))
            np.testing.assert_allclose(slab, tile_11, atol=2e-7)

    def test_slab_requires_capable_gens(self):
        from matrel_tpu.workloads.big_chain import streaming_chain_slab
        with pytest.raises(ValueError, match="slab"):
            streaming_chain_slab(64, lambda i, j: None, lambda i, j: None,
                                 lambda i, j: None, tile=8, panel=16)

    def test_sharded_matches_single(self, mesh8):
        import jax.numpy as jnp
        from matrel_tpu.workloads.big_chain import (
            streaming_chain, streaming_chain_sharded, default_gen)
        n, tile, panel = 128, 8, 16  # 8 panels = 1 per device
        gens = tuple(default_gen(s, tile, jnp.float32, 0.05) for s in (1, 2, 3))
        single = float(streaming_chain(n, *gens, tile=tile, panel=panel,
                                       dtype=jnp.float32))
        sharded = float(streaming_chain_sharded(n, *gens, mesh=mesh8,
                                                tile=tile, panel=panel,
                                                dtype=jnp.float32))
        assert sharded == pytest.approx(single, rel=1e-5)


class TestBlockSparsePageRank:
    def test_matches_dense_oracle(self, mesh8, rng):
        from matrel_tpu.core.sparse import BlockSparseMatrix
        from matrel_tpu.workloads.pagerank import (
            pagerank_block_sparse, pagerank_numpy_oracle)
        n, bs = 32, 8
        # clustered adjacency: a few dense blocks
        a = np.zeros((n, n), dtype=np.float32)
        a[0:8, 8:16] = (rng.random((8, 8)) < 0.6)
        a[8:16, 0:8] = (rng.random((8, 8)) < 0.6)
        a[16:24, 24:32] = (rng.random((8, 8)) < 0.6)
        np.fill_diagonal(a, 0)
        S = BlockSparseMatrix.from_numpy(a, block_size=bs, mesh=mesh8)
        from matrel_tpu.config import MatrelConfig
        r = np.asarray(pagerank_block_sparse(S, rounds=20,
                                             config=MatrelConfig(use_pallas=False)))
        oracle = pagerank_numpy_oracle(a, rounds=20)
        np.testing.assert_allclose(r, oracle, rtol=1e-3, atol=1e-6)

    def test_weighted_adjacency_small_row_sums(self, mesh8, rng):
        # Row sums < 1 (weighted graph): the inverse-degree floor must be
        # an epsilon, not 1.0, or ranks skew silently (regression).
        from matrel_tpu.core.sparse import BlockSparseMatrix
        from matrel_tpu.workloads.pagerank import (
            pagerank_block_sparse, pagerank_numpy_oracle)
        from matrel_tpu.config import MatrelConfig
        n, bs = 32, 8
        a = np.zeros((n, n), dtype=np.float32)
        a[0:8, 8:16] = 0.1 * (rng.random((8, 8)) < 0.6)
        a[8:16, 16:24] = 0.1 * (rng.random((8, 8)) < 0.6)
        a[16:24, 0:8] = 0.1 * (rng.random((8, 8)) < 0.6)
        np.fill_diagonal(a, 0)
        S = BlockSparseMatrix.from_numpy(a, block_size=bs, mesh=mesh8)
        r = np.asarray(pagerank_block_sparse(
            S, rounds=20, config=MatrelConfig(use_pallas=False)))
        oracle = pagerank_numpy_oracle(a, rounds=20)
        np.testing.assert_allclose(r, oracle, rtol=1e-3, atol=1e-6)


class TestTriangleCount:
    def test_matches_numpy_oracle(self, mesh8, rng):
        from matrel_tpu.workloads import triangles as T
        n = 40
        a = (rng.random((n, n)) < 0.2).astype(np.float32)
        a = np.triu(a, 1)
        a = a + a.T                       # symmetric, zero diagonal
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        got = T.triangle_count(A)
        assert got == pytest.approx(T.triangles_numpy_oracle(a), rel=1e-4)

    def test_known_small_graph(self, mesh8):
        from matrel_tpu.workloads import triangles as T
        # K4 has C(4,3) = 4 triangles
        a = (np.ones((4, 4)) - np.eye(4)).astype(np.float32)
        assert T.triangle_count(
            BlockMatrix.from_numpy(a, mesh=mesh8)) == pytest.approx(4.0)

    def test_via_sql(self, mesh8, rng):
        from matrel_tpu.session import MatrelSession
        from matrel_tpu.workloads import triangles as T
        n = 24
        a = (rng.random((n, n)) < 0.3).astype(np.float32)
        a = np.triu(a, 1); a = a + a.T
        s = MatrelSession(mesh=mesh8)
        s.register("A", s.from_numpy(a))
        got = s.compute(s.sql("trace(A * A * A)")).to_numpy()[0, 0] / 6.0
        assert got == pytest.approx(T.triangles_numpy_oracle(a), rel=1e-4)

    def test_rejects_nonsquare(self, mesh8, rng):
        from matrel_tpu.workloads import triangles as T
        A = BlockMatrix.from_numpy(
            rng.standard_normal((4, 6)).astype(np.float32), mesh=mesh8)
        with pytest.raises(ValueError):
            T.triangle_count_expr(A)


class TestCosineSimilarity:
    def test_matches_numpy_oracle(self, mesh8, rng):
        from matrel_tpu.workloads import similarity as S
        x = rng.standard_normal((20, 12)).astype(np.float32) + 0.1
        X = BlockMatrix.from_numpy(x, mesh=mesh8)
        got = S.cosine_similarity(X)
        np.testing.assert_allclose(
            got, S.cosine_similarity_numpy_oracle(x), rtol=2e-3, atol=2e-3)

    def test_diagonal_is_one(self, mesh8, rng):
        from matrel_tpu.workloads import similarity as S
        x = rng.standard_normal((16, 8)).astype(np.float32) + 0.2
        got = S.cosine_similarity(BlockMatrix.from_numpy(x, mesh=mesh8))
        np.testing.assert_allclose(np.diagonal(got), 1.0, atol=1e-3)

    def test_gram_path_engaged_under_high_precision(self, mesh8, rng,
                                                    monkeypatch):
        # the X·Xᵀ core must route through the symmetric 2-pass split
        import jax.numpy as jnp
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.executor import execute
        from matrel_tpu.parallel import strategies
        from matrel_tpu.workloads import similarity as S
        calls = []
        real = strategies.run_matmul

        def spy(strategy, p, q, mesh, config=None, **kw):
            calls.append((p.dtype, q.dtype))
            return real(strategy, p, q, mesh, config, **kw)

        monkeypatch.setattr(strategies, "run_matmul", spy)
        x = rng.standard_normal((24, 12)).astype(np.float32) + 0.1
        X = BlockMatrix.from_numpy(x, mesh=mesh8)
        out = execute(S.cosine_similarity_expr(X), mesh8,
                      MatrelConfig(matmul_precision="high")).to_numpy()
        assert [c for c in calls
                if c == (jnp.bfloat16, jnp.bfloat16)], calls
        np.testing.assert_allclose(
            out, S.cosine_similarity_numpy_oracle(x), rtol=5e-3, atol=5e-3)


def test_fit_fused_honors_high_precision(mesh8, rng):
    # round-3: fit_fused with precision="high" takes the symmetric
    # 2-pass Gram and still recovers theta
    import jax.numpy as jnp
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.workloads.linreg import fit_fused
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from jax.sharding import PartitionSpec as P
    x = rng.standard_normal((256, 8)).astype(np.float32)
    tt = np.linspace(1, 2, 8).reshape(8, 1).astype(np.float32)
    y = x @ tt
    X = BlockMatrix.from_numpy(x, mesh=mesh8, spec=P(("x", "y"), None))
    Y = BlockMatrix.from_numpy(y, mesh=mesh8, spec=P(("x", "y"), None))
    th = np.asarray(fit_fused(X, Y,
                              config=MatrelConfig(matmul_precision="high")))
    np.testing.assert_allclose(th, tt, rtol=5e-3, atol=5e-3)


class TestPowerIteration:
    def test_dominant_eigenpair_symmetric(self, mesh8, rng):
        from matrel_tpu.workloads import eigen
        n = 24
        q = rng.standard_normal((n, n)).astype(np.float32)
        a = (q + q.T) / 2                       # symmetric: real spectrum
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        lam, v = eigen.power_iteration(A, rounds=200)
        assert abs(abs(lam) - eigen.eig_numpy_oracle(a)) < 1e-2
        # v is an eigenvector: A v ≈ λ v
        resid = np.linalg.norm(a @ np.asarray(v) - lam * np.asarray(v))
        assert resid < 1e-2 * abs(lam)

    def test_spectral_norm_matches_svd(self, mesh8, rng):
        from matrel_tpu.workloads import eigen
        a = rng.standard_normal((20, 12)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        got = eigen.spectral_norm(A, rounds=200)
        want = float(np.linalg.svd(a, compute_uv=False)[0])
        assert got == pytest.approx(want, rel=1e-3)

    def test_rejects_nonsquare(self, mesh8, rng):
        from matrel_tpu.workloads import eigen
        A = BlockMatrix.from_numpy(
            rng.standard_normal((4, 6)).astype(np.float32), mesh=mesh8)
        with pytest.raises(ValueError):
            eigen.power_iteration(A)

    def test_accepts_expression(self, mesh8, rng):
        from matrel_tpu.workloads import eigen
        a = rng.standard_normal((12, 12)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        # spectral norm of a lazy expression (2·A): compiles then iterates
        got = eigen.spectral_norm(A.expr().multiply_scalar(2.0),
                                  rounds=200)
        want = 2 * float(np.linalg.svd(a, compute_uv=False)[0])
        assert got == pytest.approx(want, rel=1e-3)

    def test_coo_power_iteration_matches_dense(self, mesh8, rng):
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.workloads import eigen
        n = 64
        a = (rng.random((n, n)) < 0.12).astype(np.float32)
        a = np.maximum(a, a.T)                 # symmetric 0/1 adjacency
        np.fill_diagonal(a, 0)
        r, c = np.nonzero(a)
        coo = COOMatrix.from_edges(r, c, a[r, c], shape=(n, n))
        lam, v = eigen.power_iteration_coo(coo, rounds=300)
        assert abs(lam) == pytest.approx(eigen.eig_numpy_oracle(a),
                                         rel=1e-2)
        resid = np.linalg.norm(a @ np.asarray(v) - lam * np.asarray(v))
        assert resid < 2e-2 * abs(lam)


class TestConjugateGradient:
    def test_spd_solve_matches_numpy(self, mesh8, rng):
        from matrel_tpu.workloads import cg
        n = 24
        q = rng.standard_normal((n, n)).astype(np.float32)
        a = q @ q.T + n * np.eye(n, dtype=np.float32)   # SPD
        b = rng.standard_normal(n).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        x, it = cg.cg_solve(A, b, tol=1e-6)
        assert 0 < it < 1000
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(a, b),
                                   rtol=1e-3, atol=1e-3)

    def test_least_squares_matches_lstsq(self, mesh8, rng):
        from matrel_tpu.workloads import cg
        x_np = rng.standard_normal((96, 8)).astype(np.float32)
        tt = np.linspace(-1, 1, 8).astype(np.float32)
        y = x_np @ tt
        X = BlockMatrix.from_numpy(x_np, mesh=mesh8)
        theta, it = cg.cg_least_squares(X, y, tol=1e-7)
        np.testing.assert_allclose(np.asarray(theta), tt, rtol=1e-2,
                                   atol=1e-2)

    def test_linop_form_with_planned_spmv(self, mesh8, rng):
        # SPD operator from a sparse graph Laplacian via the SpMV plan
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.ops import spmv as spmv_lib
        from matrel_tpu.workloads import cg
        n = 48
        adj = (rng.random((n, n)) < 0.15).astype(np.float32)
        adj = np.maximum(adj, adj.T); np.fill_diagonal(adj, 0)
        lap = np.diag(adj.sum(1)) - adj + np.eye(n, dtype=np.float32)
        r, c = np.nonzero(lap)
        coo = COOMatrix.from_edges(r, c, lap[r, c], shape=(n, n))
        plan = coo._get_plan()
        static = (plan.n_rows, plan.n_cols, plan.block)
        arrays = plan.arrays()
        b = rng.standard_normal(plan.n_cols).astype(np.float32)
        b[n:] = 0.0
        x, it = cg.cg_solve_linop(
            lambda v: spmv_lib.spmv_apply(static, arrays, v),
            b, tol=1e-6)
        np.testing.assert_allclose(
            np.asarray(x)[:n], np.linalg.solve(lap, b[:n]), rtol=1e-3,
            atol=1e-3)

    def test_rejects_nonsquare(self, mesh8, rng):
        from matrel_tpu.workloads import cg
        A = BlockMatrix.from_numpy(
            rng.standard_normal((4, 6)).astype(np.float32), mesh=mesh8)
        with pytest.raises(ValueError):
            cg.cg_solve(A, np.zeros(4))
