"""Optimizer metamorphic fuzzing: for random expression trees, the
optimized plan must produce the same numbers as the unoptimized one, and
both must match a numpy evaluation of the tree. This is the strongest
correctness net over the rewrite rules + chain DP + planner + executor
stack (SURVEY.md §4: numerics vs oracles, extended to generated plans)."""

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.executor import compile_expr
from matrel_tpu.ir import expr as E


def np_eval(e, env):
    """Reference evaluation of a MatExpr over numpy leaf values."""
    k = e.kind
    if k in ("leaf", "sparse_leaf", "coo_leaf"):
        return env[e.uid]
    if k == "transpose":
        return np_eval(e.children[0], env).T
    if k == "matmul":
        return np_eval(e.children[0], env) @ np_eval(e.children[1], env)
    if k == "elemwise":
        a, b = (np_eval(c, env) for c in e.children)
        op = e.attrs["op"]
        if op == "add":
            return a + b
        if op == "sub":
            return a - b
        if op == "mul":
            return a * b
        if op == "div":
            return np.where(b == 0, 0.0, a / np.where(b == 0, 1.0, b))
        raise NotImplementedError(op)
    if k == "scalar":
        x = np_eval(e.children[0], env)
        op, v = e.attrs["op"], e.attrs["value"]
        if op == "add":
            return x + v
        if op == "mul":
            return x * v
        return np.power(x, v)
    if k == "agg":
        x = np_eval(e.children[0], env)
        kind, axis = e.attrs["agg"], e.attrs["axis"]
        if kind == "sum":
            if axis == "row":
                return x.sum(1, keepdims=True)
            if axis == "col":
                return x.sum(0, keepdims=True)
            if axis == "all":
                return x.sum().reshape(1, 1)
            return np.trace(x).reshape(1, 1)
        raise NotImplementedError(kind)
    if k == "vec":
        x = np_eval(e.children[0], env)
        return x.T.reshape(-1, 1)
    if k == "rank1":
        a, u, v = (np_eval(c, env) for c in e.children)
        return a + u @ v.T
    if k == "solve":
        a, b = (np_eval(c, env) for c in e.children)
        return np.linalg.solve(a, b).astype(np.float32)
    if k == "inverse":
        return np.linalg.inv(np_eval(e.children[0], env)).astype(np.float32)
    if k == "select_value":
        x = np_eval(e.children[0], env)
        pred, fill = e.attrs["predicate"], e.attrs["fill"]
        return np.where(np.asarray(pred(x)), x, fill).astype(np.float32)
    if k == "join_index":
        a, b = (np_eval(c, env) for c in e.children)
        return np.asarray(e.attrs["merge"](a, b), dtype=np.float32)
    if k == "join_value":
        a, b = (np_eval(c, env) for c in e.children)
        va = a.T.reshape(-1)
        vb = b.T.reshape(-1)
        P = np.asarray(e.attrs["merge"](va[:, None], vb[None, :]))
        if e.attrs["predicate"] is not None:
            mask = np.asarray(e.attrs["predicate"](va[:, None],
                                                   vb[None, :]))
            P = np.where(mask, P, 0.0)
        return P.astype(np.float32)
    if k == "select_index":
        x = np_eval(e.children[0], env).copy()
        rows, cols = e.attrs["rows"], e.attrs["cols"]
        if rows is not None:
            keep = np.asarray(rows(np.arange(x.shape[0])))
            x[~keep, :] = 0
        if cols is not None:
            keep = np.asarray(cols(np.arange(x.shape[1])))
            x[:, ~keep] = 0
        return x
    raise NotImplementedError(k)


def _rand_spec(rng, shape):
    """A random leaf PartitionSpec: canonical (None), 1D row/col over
    all devices, replicated, or a partial sharding. Size-1 dims stay
    canonical (they are never padded, so 1D specs cannot divide)."""
    from jax.sharding import PartitionSpec as P
    if shape[0] <= 1 or shape[1] <= 1:
        return None
    pool = [None, P(("x", "y"), None), P(None, ("x", "y")),
            P(None, None), P("x", None), P(None, "y")]
    return pool[int(rng.integers(len(pool)))]


def gen_expr(rng, env, mesh, depth, shape=None, leaf_kinds=("dense",),
             dtype_pop=("float32",), structured_join=False,
             rand_specs=False):
    """Random expression with consistent shapes; fills env[uid] for leaves.
    ``leaf_kinds``: population for leaf flavors — "dense" (BlockMatrix),
    "sparse" (BlockSparseMatrix tile stack), "coo" (element-sparse plan);
    all three enter the same IR and must agree with the numpy oracle.
    ``dtype_pop``: device dtypes for dense leaves (the numpy oracle env
    always stores exact f32 — mixed-dtype callers compare dtypes, not
    numerics). ``structured_join``: use structured string merges for
    join_index (dtype-inferable) instead of a callable."""
    def leaf_of(shape):
        a = rng.standard_normal(shape).astype(np.float32)
        kind = str(rng.choice(leaf_kinds))
        if kind == "sparse":
            a = a * (rng.random(shape) < 0.6)
            from matrel_tpu.core.sparse import BlockSparseMatrix
            l = BlockSparseMatrix.from_numpy(a, block_size=4,
                                             mesh=mesh).expr()
        elif kind == "coo":
            from matrel_tpu.core.coo import COOMatrix
            a = a * (rng.random(shape) < 0.6)
            r, c = np.nonzero(a)
            l = COOMatrix.from_edges(r, c, a[r, c], shape=shape).expr()
        else:
            spec = _rand_spec(rng, shape) if rand_specs else None
            l = E.leaf(BlockMatrix.from_numpy(
                a, mesh=mesh, dtype=str(rng.choice(dtype_pop)),
                spec=spec))
        env[l.uid] = a
        return l

    dims = [1, 3, 5, 8, 13]
    if shape is None:
        shape = (int(rng.choice(dims[1:])), int(rng.choice(dims[1:])))
    if depth <= 0:
        return leaf_of(shape)
    choice = rng.choice(
        ["matmul", "elemwise", "scalar", "transpose", "agg_chain",
         "select", "select_value", "join_index", "join_value", "rank1",
         "solve", "gram", "leaf"])
    if choice == "gram" and shape[0] == shape[1]:
        # AᵀA / AAᵀ with a SHARED operand node — under
        # matmul_precision="high" this takes the symmetric 2-pass
        # lowering (executor gram path); under other precisions the
        # generic path. Both must track the oracle.
        k = int(rng.choice(dims[1:]))
        if rng.random() < 0.5:
            x = gen_expr(rng, env, mesh, depth - 1, (k, shape[0]),
                         leaf_kinds, dtype_pop, structured_join, rand_specs)
            return E.matmul(E.transpose(x), x)
        x = gen_expr(rng, env, mesh, depth - 1, (shape[0], k),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        return E.matmul(x, E.transpose(x))
    if choice == "matmul":
        k = int(rng.choice(dims[1:]))
        a = gen_expr(rng, env, mesh, depth - 1, (shape[0], k),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        b = gen_expr(rng, env, mesh, depth - 1, (k, shape[1]),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        return E.matmul(a, b)
    if choice == "elemwise":
        op = str(rng.choice(["add", "sub", "mul"]))
        a = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        b = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        return E.elemwise(op, a, b)
    if choice == "scalar":
        op = str(rng.choice(["add", "mul"]))
        c = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        return E.scalar_op(op, c, float(rng.uniform(-2, 2)))
    if choice == "transpose":
        c = gen_expr(rng, env, mesh, depth - 1, (shape[1], shape[0]),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        return E.transpose(c)
    if choice == "agg_chain":
        # produce shape via aggregation of a larger operand when possible
        if shape[1] == 1 and shape[0] > 1:
            inner = gen_expr(rng, env, mesh, depth - 1,
                             (shape[0], int(rng.choice(dims[1:]))),
                             leaf_kinds, dtype_pop, structured_join, rand_specs)
            return E.agg(inner, "sum", "row")
        if shape == (1, 1):
            inner = gen_expr(rng, env, mesh, depth - 1,
                             (int(rng.choice(dims[1:])),) * 2, leaf_kinds,
                             dtype_pop, structured_join, rand_specs)
            return E.agg(inner, "sum", "all")
        return leaf_of(shape)
    if choice == "select":
        c = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        m = int(rng.integers(2, 5))
        return E.select_index(c, rows=lambda i, m=m: i % m != 0)
    if choice == "select_value":
        c = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        t = float(rng.uniform(-0.5, 0.5))
        return E.select_value(c, lambda v, t=t: v > t)
    if choice == "join_index":
        a = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        b = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        if structured_join:
            return E.join_on_index(
                a, b, str(rng.choice(["left", "right", "add", "mul"])))
        return E.join_on_index(a, b, lambda x, y: x * y + x)
    if choice == "join_value":
        # pair matrix shaped (s0, s1) from column-vector operands; a
        # parent agg triggers the streaming lowering, otherwise the
        # capped materialisation runs — both fuzzed here
        a = gen_expr(rng, env, mesh, depth - 1, (shape[0], 1),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        b = gen_expr(rng, env, mesh, depth - 1, (shape[1], 1),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        merge = str(rng.choice(["left", "right", "add", "mul"]))
        pred = str(rng.choice(["eq", "lt", "le", "gt", "ge"]))
        return E.join_on_value(a, b, merge, pred)
    if choice == "solve":
        # well-conditioned lhs: a random leaf shifted to diagonal
        # dominance, so the numpy oracle and the LU solve both stay
        # far from singularity across all seeds
        n = shape[0]
        m_np = rng.standard_normal((n, n)).astype(np.float32)
        m_np = (m_np @ m_np.T / n + 2.0 * np.eye(n, dtype=np.float32))
        l = E.leaf(BlockMatrix.from_numpy(
            m_np, mesh=mesh,
            spec=_rand_spec(rng, (n, n)) if rand_specs else None))
        env[l.uid] = m_np
        b = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        if rng.random() < 0.5:
            return E.solve(l, b)
        return E.matmul(E.inverse(l), b)   # exercises the R7 fusion
    if choice == "rank1":
        a = gen_expr(rng, env, mesh, depth - 1, shape, leaf_kinds,
                     dtype_pop, structured_join, rand_specs)
        u = gen_expr(rng, env, mesh, depth - 1, (shape[0], 1),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        v = gen_expr(rng, env, mesh, depth - 1, (shape[1], 1),
                     leaf_kinds, dtype_pop, structured_join, rand_specs)
        return E.rank_one_update(a, u, v)
    return leaf_of(shape)


@pytest.mark.parametrize("seed", range(20))
def test_optimized_matches_unoptimized_and_numpy(seed, mesh8):
    rng = np.random.default_rng(seed)
    env = {}
    e = gen_expr(rng, env, mesh8, depth=int(rng.integers(2, 5)))
    oracle = np_eval(e, env)

    plan_opt = compile_expr(e, mesh8, MatrelConfig())
    got_opt = plan_opt.run().to_numpy()
    plan_raw = compile_expr(
        e, mesh8, MatrelConfig(rewrite_rules=False, chain_opt=False))
    got_raw = plan_raw.run().to_numpy()

    np.testing.assert_allclose(got_raw, oracle, rtol=2e-3, atol=2e-3,
                               err_msg=f"unoptimized != numpy (seed {seed})")
    np.testing.assert_allclose(got_opt, oracle, rtol=2e-3, atol=2e-3,
                               err_msg=f"optimized != numpy (seed {seed})")


@pytest.mark.parametrize("seed", range(40, 55))
def test_fuzz_mixed_leaf_kinds(seed, mesh8):
    """Dense, block-sparse and element-sparse leaves mixed in one tree:
    every lowering path (strategy matmuls, SpMM, one-hot SpMV, densify
    fallbacks) must produce the oracle numbers, optimized or not."""
    rng = np.random.default_rng(seed)
    env = {}
    e = gen_expr(rng, env, mesh8, depth=int(rng.integers(2, 4)),
                 leaf_kinds=("dense", "dense", "sparse", "coo"))
    oracle = np_eval(e, env)
    got_opt = compile_expr(e, mesh8, MatrelConfig()).run().to_numpy()
    got_raw = compile_expr(
        e, mesh8, MatrelConfig(rewrite_rules=False,
                               chain_opt=False)).run().to_numpy()
    np.testing.assert_allclose(got_raw, oracle, rtol=2e-3, atol=2e-3,
                               err_msg=f"unoptimized != numpy (seed {seed})")
    np.testing.assert_allclose(got_opt, oracle, rtol=2e-3, atol=2e-3,
                               err_msg=f"optimized != numpy (seed {seed})")


@pytest.mark.parametrize("seed", range(20, 28))
def test_fuzz_on_square_mesh(seed, mesh_square):
    rng = np.random.default_rng(seed)
    env = {}
    e = gen_expr(rng, env, mesh_square, depth=3)
    oracle = np_eval(e, env)
    got = compile_expr(e, mesh_square, MatrelConfig()).run().to_numpy()
    np.testing.assert_allclose(got, oracle, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("seed", range(60, 75))
def test_fuzz_value_join_streaming_vs_pair_matrix(seed, mesh8):
    """The streaming agg(join_on_value) lowerings (sort-based and
    chunked) must equal the materialised pair matrix aggregated with
    the dense rules, across random shapes, predicates, merges, zero/
    duplicate-heavy values, aggregate kinds and axes."""
    rng = np.random.default_rng(seed)
    pool = np.array([-2.0, -1.0, -1.0, 0.0, 0.0, 0.5, 1.0, 1.0, 3.0],
                    np.float32)
    sa = (int(rng.integers(2, 7)), int(rng.integers(2, 7)))
    sb = (int(rng.integers(2, 7)), int(rng.integers(2, 7)))
    a = rng.choice(pool, sa).astype(np.float32)
    b = rng.choice(pool, sb).astype(np.float32)
    A = E.leaf(BlockMatrix.from_numpy(a, mesh=mesh8))
    B = E.leaf(BlockMatrix.from_numpy(b, mesh=mesh8))

    structured = bool(rng.random() < 0.7)
    if structured:
        pred = str(rng.choice(["eq", "lt", "le", "gt", "ge"]))
        merge = str(rng.choice(["left", "right", "add", "mul"]))
        pred_np = {"eq": np.equal, "lt": np.less, "le": np.less_equal,
                   "gt": np.greater, "ge": np.greater_equal}[pred]
        merge_np = {"left": lambda x, y: x + 0 * y,
                    "right": lambda x, y: y + 0 * x,
                    "add": np.add, "mul": np.multiply}[merge]
    else:
        pred = pred_np = lambda x, y: x + y > 0.25
        merge = merge_np = lambda x, y: x * y - x
    kind = str(rng.choice(["sum", "count", "avg", "max", "min"]))
    axis = str(rng.choice(["row", "col", "all"]))

    va, vb = a.T.reshape(-1), b.T.reshape(-1)
    P = merge_np(va[:, None].astype(np.float64), vb[None, :])
    P = np.where(pred_np(va[:, None], vb[None, :]), P, 0.0)
    ax = {"row": 1, "col": 0, "all": None}[axis]
    if kind == "sum":
        want = P.sum(axis=ax)
    elif kind == "count":
        want = (P != 0).sum(axis=ax).astype(np.float64)
    elif kind == "avg":
        s, c = P.sum(axis=ax), (P != 0).sum(axis=ax)
        want = np.where(c > 0, s / np.maximum(c, 1), 0.0)
    else:
        want = (np.max if kind == "max" else np.min)(P, axis=ax)

    expr = E.agg(E.join_on_value(A, B, merge, pred), kind, axis)
    out = compile_expr(expr, mesh8, MatrelConfig()).run().to_numpy()
    got = {"row": out[:, 0], "col": out[0], "all": out[0, 0]}[axis]
    np.testing.assert_allclose(
        got, want, rtol=1e-4, atol=1e-4,
        err_msg=f"seed {seed}: {pred}/{merge}/{kind}/{axis} "
                f"structured={structured}")


@pytest.mark.parametrize("seed", range(80, 92))
def test_fuzz_gram_high_precision(seed, mesh8):
    """Forced AᵀA/AAᵀ roots over random sub-trees under
    matmul_precision="high": the symmetric 2-pass bf16 lowering must
    track the f32 oracle at bf16x3-class tolerance, with and without
    the optimizer."""
    rng = np.random.default_rng(seed)
    env = {}
    n = int(rng.integers(3, 9))
    k = int(rng.integers(2, 9))
    if rng.random() < 0.5:
        x = gen_expr(rng, env, mesh8, depth=int(rng.integers(1, 3)),
                     shape=(k, n))
        e = E.matmul(E.transpose(x), x)
    else:
        x = gen_expr(rng, env, mesh8, depth=int(rng.integers(1, 3)),
                     shape=(n, k))
        e = E.matmul(x, E.transpose(x))
    if rng.random() < 0.5:
        e = E.agg(e, "sum", str(rng.choice(["row", "all", "diag"])))
    oracle = np_eval(e, env)
    cfg = MatrelConfig(matmul_precision="high")
    got = compile_expr(e, mesh8, cfg).run().to_numpy()
    got_raw = compile_expr(
        e, mesh8, cfg.replace(rewrite_rules=False,
                              chain_opt=False)).run().to_numpy()
    tol = dict(rtol=1e-2, atol=1e-2 * max(1.0, np.abs(oracle).max()))
    np.testing.assert_allclose(got, oracle, **tol,
                               err_msg=f"optimized (seed {seed})")
    np.testing.assert_allclose(got_raw, oracle, **tol,
                               err_msg=f"unoptimized (seed {seed})")


def test_fuzz_infer_dtype_matches_executed_dtype(mesh8):
    """planner.infer_dtype models the Lowerer's dtype behaviour; this
    fuzz pins them together (round 4): for random mixed bf16/f32
    expression trees over the SHARED gen_expr generator (all node
    kinds), whenever infer_dtype makes a prediction it must equal the
    dtype the compiled program actually produces — drift between the
    planner model and the executor would silently mis-key the autotune
    table. Callable-merge joins legitimately predict None; at least
    half the seeds must produce a prediction so the assertion has
    teeth."""
    from matrel_tpu import executor as executor_lib
    from matrel_tpu.parallel.planner import infer_dtype

    cfg = MatrelConfig()
    predicted_count = 0
    n_seeds = 24
    for seed in range(n_seeds):
        rng = np.random.default_rng(4000 + seed)
        env = {}
        e = gen_expr(rng, env, mesh8, depth=int(rng.integers(2, 4)),
                     dtype_pop=("float32", "bfloat16"),
                     structured_join=True)
        predicted = infer_dtype(e, cfg)
        got = executor_lib.execute(e, mesh8, cfg).data.dtype
        if predicted is not None:
            predicted_count += 1
            assert np.dtype(predicted) == np.dtype(got), (
                f"seed {seed}: predicted {predicted}, executed {got}")
    assert predicted_count >= n_seeds // 2, predicted_count


@pytest.mark.parametrize("seed", range(60, 75))
def test_fuzz_random_leaf_layouts(seed, mesh8):
    # round-5 layout net: random leaf PartitionSpecs through random
    # trees — infer_layout's claims steer strategy/join-scheme/root
    # charges, and none of it may move the numbers
    rng = np.random.default_rng(seed)
    env = {}
    e = gen_expr(rng, env, mesh8, depth=int(rng.integers(2, 5)),
                 rand_specs=True)
    oracle = np_eval(e, env)
    got = compile_expr(e, mesh8, MatrelConfig()).run().to_numpy()
    np.testing.assert_allclose(got, oracle, rtol=2e-3, atol=2e-3,
                               err_msg=f"layout fuzz (seed {seed})")
