"""Live telemetry plane (round 15, docs/OBSERVABILITY.md tier 3):
per-tenant SLO burn-rate monitors (obs/slo.py), the in-process
metrics endpoint (obs/export.py), the `top` operator console
(obs/top.py), the history alert roll-up + --check gate, and the
default-config structural-zero contract."""

import json
import re
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig, parse_slo_targets
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.obs import slo as slo_lib
from matrel_tpu.obs.events import EventLog, read_events
from matrel_tpu.session import MatrelSession


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _mat(rng, n, m, mesh):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh)


#: Small windows so monitor tests run in wall-clock milliseconds with
#: the injected clock.
SLO_CFG = dict(slo_targets="gold:avail=0.9,p95_ms=50;bronze:avail=0.9",
               slo_fast_window_s=1.0, slo_slow_window_s=4.0,
               slo_burn_threshold=3.0, slo_burn_exit=1.0)


def _plane(emit=None, clock=None, **over):
    cfg = MatrelConfig(**{**SLO_CFG, **over})
    return slo_lib.SLOPlane(cfg, emit=emit, clock=clock)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


class TestSLOConfig:
    def test_parse_targets(self):
        t = parse_slo_targets(
            "gold:p95_ms=50,avail=0.999; bronze:avail=0.99")
        assert t == {"gold": {"p95_ms": 50.0, "avail": 0.999},
                     "bronze": {"avail": 0.99}}
        assert parse_slo_targets("") == {}
        assert parse_slo_targets(None) == {}

    @pytest.mark.parametrize("spec", [
        "gold",                       # no objectives
        "gold:p95_ms",                # no target
        "gold:p77_ms=5",              # unknown objective
        "gold:avail=1.5",             # avail outside (0,1)
        "gold:avail=0",               # avail outside (0,1)
        "gold:p95_ms=-3",             # non-positive latency
        "gold:p95_ms=x",              # not a number
        "gold:avail=0.9;gold:avail=0.8",          # duplicate tenant
        "gold:avail=0.9,avail=0.99",  # duplicate objective
        ";",                          # no tenants
    ])
    def test_parse_targets_rejects(self, spec):
        with pytest.raises(ValueError):
            parse_slo_targets(spec)

    def test_config_validates_at_construction(self):
        with pytest.raises(ValueError, match="slo_targets"):
            MatrelConfig(slo_targets="gold:p77_ms=5")
        with pytest.raises(ValueError, match="slo windows"):
            MatrelConfig(slo_fast_window_s=60.0,
                         slo_slow_window_s=60.0)
        with pytest.raises(ValueError, match="hysteresis"):
            MatrelConfig(slo_burn_exit=20.0)   # >= threshold
        with pytest.raises(ValueError, match="obs_metrics_port"):
            MatrelConfig(obs_metrics_port=-1)
        with pytest.raises(ValueError, match="obs_metrics_port"):
            MatrelConfig(obs_metrics_port=70000)

    def test_defaults_are_off(self):
        cfg = MatrelConfig()
        assert cfg.obs_metrics_port == 0
        assert cfg.slo_targets == ""


# ---------------------------------------------------------------------------
# burn-rate monitors (deterministic injected clock)
# ---------------------------------------------------------------------------


class TestBurnRateMonitor:
    def _clocked_plane(self, emit=None, **over):
        t = [1000.0]
        plane = _plane(emit=emit, clock=lambda: t[0], **over)
        return plane, t

    def test_fires_on_sustained_burn_and_emits_transition(self):
        alerts = []
        plane, t = self._clocked_plane(emit=alerts.append)
        # budget 0.1 (avail=0.9), threshold 3 => bad fraction >= 0.3
        # over BOTH windows fires
        for _ in range(10):
            plane.record_shed("gold")
        st = plane.snapshot()["tenants"]["gold"]["objectives"]["avail"]
        assert st["state"] == "firing"
        assert st["burn_fast"] >= 3.0 and st["burn_slow"] >= 3.0
        (fire,) = [a for a in alerts if a["state"] == "firing"]
        assert fire["tenant"] == "gold"
        assert fire["objective"] == "avail"
        assert fire["burn_fast"] >= 3.0
        assert fire["attainment"] == 0.0
        assert fire["window_fast_s"] == 1.0

    def test_slow_window_dilution_blocks_one_bad_second(self):
        # a long healthy history inside the slow window keeps
        # burn_slow below threshold: a short burst must NOT page —
        # the multi-window point (fast detects, slow confirms)
        plane, t = self._clocked_plane()
        for _ in range(200):
            plane.record_ok("gold", latency_ms=1.0)
        t[0] += 2.0        # past the fast window, inside the slow one
        for _ in range(10):
            plane.record_shed("gold")
        st = plane.snapshot()["tenants"]["gold"]["objectives"]["avail"]
        assert st["burn_fast"] >= 3.0       # fast window is all-bad
        assert st["burn_slow"] < 3.0        # diluted by history
        assert st["state"] == "ok"

    def test_clears_when_fast_window_slides_past(self):
        alerts = []
        plane, t = self._clocked_plane(emit=alerts.append)
        for _ in range(10):
            plane.record_shed("gold")
        assert plane.snapshot()["alerts_active"] == 1
        t[0] += 1.5                          # fast window now empty
        plane.tick()
        assert plane.snapshot()["alerts_active"] == 0
        states = [a["state"] for a in alerts]
        assert states == ["firing", "clear"]

    def test_exit_hysteresis_holds_between_exit_and_threshold(self):
        # burn between exit (1.0) and threshold (3.0) HOLDS the alert:
        # neither re-fires nor clears — the separated-threshold band.
        # The bad events stay INSIDE the fast window while good
        # traffic dilutes the fraction into the band (an emptied
        # window would legally clear).
        plane, t = self._clocked_plane()
        for _ in range(10):
            plane.record_shed("gold")
        assert plane.snapshot()["alerts_active"] == 1
        t[0] += 0.5        # half the fast window: bad still inside
        # 10 bad / 57 good -> fraction ~0.149 -> burn ~1.49, inside
        # (exit, threshold)
        for _ in range(57):
            plane.record_ok("gold", latency_ms=1.0)
        st = plane.snapshot()["tenants"]["gold"]["objectives"]["avail"]
        assert 1.0 <= st["burn_fast"] < 3.0
        assert st["state"] == "firing"       # held, not cleared
        t[0] += 0.7        # bad events age out -> burn under exit
        plane.tick()
        st = plane.snapshot()["tenants"]["gold"]["objectives"]["avail"]
        assert st["state"] == "ok"

    def test_latency_objective_counts_slow_queries(self):
        plane, t = self._clocked_plane()
        # p95_ms=50, budget 0.05: >= 15% slow queries burns at >= 3x
        for _ in range(8):
            plane.record_ok("gold", latency_ms=10.0)
        for _ in range(2):
            plane.record_ok("gold", latency_ms=500.0)
        st = plane.snapshot()["tenants"]["gold"]["objectives"]
        assert st["p95_ms"]["state"] == "firing"
        assert st["avail"]["state"] == "ok"   # all queries SERVED

    def test_sheds_do_not_pollute_latency_objectives(self):
        plane, t = self._clocked_plane()
        for _ in range(50):
            plane.record_shed("gold")
        st = plane.snapshot()["tenants"]["gold"]["objectives"]
        assert st["avail"]["state"] == "firing"
        assert st["p95_ms"]["burn_fast"] == 0.0   # never resolved

    def test_undeclared_tenant_costs_and_counts_nothing(self):
        plane, t = self._clocked_plane()
        plane.record_shed("nobody")
        plane.record_ok("nobody", latency_ms=1.0)
        snap = plane.snapshot()
        assert "nobody" not in snap["tenants"]
        assert snap["alerts_active"] == 0

    def test_from_config_off_returns_none(self):
        assert slo_lib.from_config(MatrelConfig()) is None


# ---------------------------------------------------------------------------
# serve-plane wiring (real session)
# ---------------------------------------------------------------------------


def _sess(mesh, tmp_path=None, **cfg):
    if tmp_path is not None:
        cfg.setdefault("obs_event_log", str(tmp_path / "ev.jsonl"))
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


class TestServeWiring:
    def test_ok_latency_and_counters_flow(self, mesh8, rng):
        sess = _sess(mesh8, **SLO_CFG)
        A = _mat(rng, 32, 32, mesh8)
        an = A.to_numpy()
        futs = [sess.submit(A.expr().multiply_scalar(2.0),
                            tenant="gold") for _ in range(4)]
        for f in futs:
            np.testing.assert_allclose(
                f.result(timeout=60).to_numpy(), an * 2.0,
                rtol=1e-5, atol=1e-5)
        sess.serve_drain(timeout=60)
        time.sleep(0.1)
        snap = sess._slo.snapshot()
        gold = snap["tenants"]["gold"]
        assert gold["counts"]["ok"] == 4
        assert gold["latency_ms"]["count"] == 4
        assert gold["latency_ms"]["p95"] > 0

    def test_quota_shed_burns_availability(self, mesh8, rng):
        # tenant quota 1 + a slow stream: excess submissions shed
        # typed AND burn the tenant's availability budget
        sess = _sess(mesh8, serve_tenant_weights="gold:2,bronze:1",
                     serve_tenant_queue_max=1, **SLO_CFG)
        from matrel_tpu.resilience.errors import AdmissionShed
        A = _mat(rng, 32, 32, mesh8)
        sheds = 0
        for i in range(40):
            try:
                sess.submit(A.expr().multiply_scalar(float(i % 7)),
                            tenant="bronze")
            except AdmissionShed:
                sheds += 1
        sess.serve_drain(timeout=60)
        assert sheds > 0
        snap = sess._slo.snapshot()
        assert snap["tenants"]["bronze"]["counts"]["shed"] == sheds

    def test_deadline_miss_burns_availability(self, mesh8, rng):
        sess = _sess(mesh8, **SLO_CFG)
        A = _mat(rng, 32, 32, mesh8)
        fut = sess.submit(A.expr().multiply(A.expr()), tenant="gold",
                          deadline_ms=0.0001)
        with pytest.raises(Exception):
            fut.result(timeout=60)
        sess.serve_drain(timeout=60)
        time.sleep(0.1)
        assert sess._slo.snapshot()["tenants"]["gold"]["counts"][
            "miss"] >= 1

    def test_register_delta_feeds_ivm_pseudo_tenant(self, mesh8, rng):
        sess = _sess(mesh8, slo_targets="ivm:p95_ms=60000",
                     slo_fast_window_s=1.0, slo_slow_window_s=4.0,
                     slo_burn_threshold=3.0, slo_burn_exit=1.0)
        an = (rng.random((32, 32)) < 0.2).astype(np.float32)
        sess.register("A", sess.from_numpy(an))
        out = sess.register_delta(
            "A", (np.array([1, 2]), np.array([3, 4])), kind="coo")
        assert isinstance(out["ms"], float)
        lat = sess._slo.snapshot()["tenants"]["ivm"]["latency_ms"]
        assert lat["count"] == 1

    def test_overload_event_carries_slo_snapshot(self, mesh8, rng,
                                                 tmp_path):
        sess = _sess(mesh8, tmp_path, obs_level="on", **SLO_CFG)
        A = _mat(rng, 32, 32, mesh8)
        sess.submit(A.expr().multiply_scalar(2.0),
                    tenant="gold").result(timeout=60)
        sess.serve_drain(timeout=60)
        time.sleep(0.1)
        ov = read_events(sess.config.obs_event_log,
                         kinds=("overload",))
        assert ov, "slo-active pipeline must emit overload cycles"
        assert "slo" in ov[-1]
        assert "gold" in ov[-1]["slo"]["tenants"]


class TestAlertEventContract:
    def test_alert_lands_in_event_log_when_obs_on(self, mesh8,
                                                  tmp_path):
        sess = _sess(mesh8, tmp_path, obs_level="on", **SLO_CFG)
        for _ in range(10):
            sess._slo.record_shed("gold")
        al = read_events(sess.config.obs_event_log, kinds=("alert",))
        assert [e["state"] for e in al] == ["firing"]
        assert al[0]["tenant"] == "gold"
        assert al[0]["objective"] == "avail"

    def test_alert_lands_in_flight_ring_regardless_of_obs_level(
            self, mesh8, tmp_path):
        # THE tier-3 contract: obs_level OFF, flight recorder on —
        # alert transitions still enter the post-mortem ring
        sess = _sess(mesh8, tmp_path, obs_level="off",
                     obs_flight_recorder=64, **SLO_CFG)
        for _ in range(10):
            sess._slo.record_shed("gold")
        kinds = [r.get("kind") for r in sess._flight.snapshot()]
        assert "alert" in kinds
        # and nothing was written to the event log (obs off)
        assert read_events(sess.config.obs_event_log,
                           kinds=("alert",)) == []

    def test_alert_metrics_counters(self, mesh8, tmp_path):
        from matrel_tpu.obs.metrics import REGISTRY
        REGISTRY.reset()
        sess = _sess(mesh8, tmp_path, obs_level="on", **SLO_CFG)
        t0 = time.time()
        for _ in range(10):
            sess._slo.record_shed("gold")
        while (REGISTRY.counter("slo.alerts.cleared").value < 1
               and time.time() - t0 < 10):
            time.sleep(0.2)
            sess._slo.tick()
        # >= — the registry is process-global and earlier tests'
        # still-ticking sessions may clear their own alerts into it
        assert REGISTRY.counter("slo.alerts.fired").value >= 1
        assert REGISTRY.counter("slo.alerts.cleared").value >= 1


# ---------------------------------------------------------------------------
# metrics endpoint
# ---------------------------------------------------------------------------

#: Strict Prometheus text-format line grammar (the traffic harness
#: applies the same check on every poll).
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?\s"
    r"[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|NaN|[Ii]nf)$")


def _prom_ok(text: str) -> bool:
    saw = False
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if not re.match(r"^# (TYPE|HELP) [a-zA-Z_:]", line):
                return False
            continue
        if not _PROM_SAMPLE.match(line):
            return False
        saw = True
    return saw


class TestMetricsEndpoint:
    @pytest.fixture
    def served(self, mesh8, rng, tmp_path):
        port = _free_port()
        sess = _sess(mesh8, tmp_path, obs_level="on",
                     obs_metrics_port=port,
                     result_cache_max_bytes=1 << 20, **SLO_CFG)
        A = _mat(rng, 32, 32, mesh8)
        for _ in range(3):
            sess.submit(A.expr().multiply_scalar(2.0),
                        tenant="gold").result(timeout=60)
        sess.serve_drain(timeout=60)
        time.sleep(0.1)
        yield sess, port
        sess._exporter.stop()

    def test_prometheus_endpoint_parses_strict(self, served):
        sess, port = served
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics",
            timeout=10).read().decode()
        assert _prom_ok(txt), txt[:600]
        assert "matrel_query_count" in txt
        assert 'matrel_slo_burn_rate{tenant="gold"' in txt
        assert "matrel_serve_queue_depth" in txt

    def test_json_endpoint_sections(self, served):
        sess, port = served
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/json",
            timeout=10).read().decode())
        assert snap["slo"]["tenants"]["gold"]["counts"]["ok"] == 3
        assert snap["metrics"]["counters"]["query.count"] >= 3
        # the repeated query hits the result cache after its first
        # execution, so only the real runs land in the histogram
        h = snap["metrics"]["histograms"]["query.execute_ms"]
        assert h["count"] >= 1 and h["p95"] is not None
        assert snap["plan_cache"]["plans"] >= 1
        assert snap["result_cache"]["entries"] >= 0
        assert snap["serve"]["queue_depth"] == 0
        # drift section present (obs on) even when no flags fire
        assert snap["drift"] is None or "flag_count" in snap["drift"]

    def test_unknown_path_404(self, served):
        sess, port = served
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
        assert ei.value.code == 404

    def test_exporter_thread_named_and_stoppable(self, mesh8,
                                                 tmp_path):
        port = _free_port()
        sess = _sess(mesh8, tmp_path, obs_metrics_port=port)
        names = [t.name for t in threading.enumerate()]
        assert "matrel-metrics" in names
        sess._exporter.stop()
        time.sleep(0.1)
        names = [t.name for t in threading.enumerate()]
        assert "matrel-metrics" not in names

    def test_bind_conflict_raises_at_construction(self, mesh8,
                                                  tmp_path):
        port = _free_port()
        blocker = socket.socket()
        blocker.bind(("127.0.0.1", port))
        blocker.listen(1)
        try:
            with pytest.raises(OSError):
                _sess(mesh8, tmp_path, obs_metrics_port=port)
        finally:
            blocker.close()

    def test_serve_close_stops_exporter(self, mesh8, rng, tmp_path):
        # review fix: "done serving" frees the port deterministically
        port = _free_port()
        sess = _sess(mesh8, tmp_path, obs_metrics_port=port)
        A = _mat(rng, 32, 32, mesh8)
        sess.submit(A.expr().multiply_scalar(2.0)).result(timeout=60)
        sess.serve_close(timeout=60)
        time.sleep(0.1)
        assert "matrel-metrics" not in {
            t.name for t in threading.enumerate()}
        # the port is reusable immediately — no EADDRINUSE leak
        s2 = _sess(mesh8, tmp_path, obs_metrics_port=port)
        s2._exporter.stop()

    def test_dropped_session_frees_port_via_finalizer(self, mesh8,
                                                      tmp_path):
        # review fix: a session that is simply dropped (no serve
        # traffic, so no worker thread roots it) must not pin its
        # bound port for process lifetime
        import gc
        port = _free_port()
        sess = _sess(mesh8, tmp_path, obs_metrics_port=port)
        del sess
        gc.collect()
        time.sleep(0.2)
        assert "matrel-metrics" not in {
            t.name for t in threading.enumerate()}
        s2 = _sess(mesh8, tmp_path, obs_metrics_port=port)
        s2._exporter.stop()

    def test_events_tail_bytes_reads_only_the_tail(self, tmp_path):
        # review fix: live readers (scrape drift view, `top` frames)
        # cost O(tail), not O(history) — and the cut-off first line
        # is dropped, not mis-parsed
        log = EventLog(str(tmp_path / "big.jsonl"))
        for i in range(200):
            log.emit("query", {"i": i})
        full = read_events(log.path)
        assert len(full) == 200
        tail = read_events(log.path, tail_bytes=2000)
        assert 0 < len(tail) < 200
        assert tail[-1]["i"] == 199            # newest records kept
        assert [e["i"] for e in tail] == sorted(
            e["i"] for e in tail)              # contiguous tail
        # a bound larger than the file reads everything
        assert len(read_events(log.path, tail_bytes=1 << 30)) == 200

    def test_render_prometheus_escapes_labels(self):
        from matrel_tpu.obs.export import render_prometheus
        snap = {"metrics": {"counters": {}, "gauges": {},
                            "histograms": {}},
                "serve": {"queue_depth": 1,
                          "tenant_depths": {'we"ird\nname': 2},
                          "inflight": 0}}
        txt = render_prometheus(snap)
        assert _prom_ok(txt), txt
        assert r'tenant="we\"ird\nname"' in txt


# ---------------------------------------------------------------------------
# top — the operator console
# ---------------------------------------------------------------------------


class TestTopConsole:
    def test_render_from_live_endpoint(self, mesh8, rng, tmp_path):
        from matrel_tpu.obs import top
        port = _free_port()
        sess = _sess(mesh8, tmp_path, obs_level="on",
                     obs_metrics_port=port, **SLO_CFG)
        try:
            A = _mat(rng, 32, 32, mesh8)
            sess.submit(A.expr().multiply_scalar(2.0),
                        tenant="gold").result(timeout=60)
            sess.serve_drain(timeout=60)
            time.sleep(0.1)
            snap = top.snapshot_from_url(f"http://127.0.0.1:{port}")
            frame = top.render(snap)
            assert "gold" in frame
            assert "qps" in frame and "p95" in frame
            assert "active alerts" in frame
        finally:
            sess._exporter.stop()

    def test_render_from_log(self, tmp_path):
        from matrel_tpu.obs import top
        log = EventLog(str(tmp_path / "ev.jsonl"))
        log.emit("overload", {
            "rung": 2, "rung_label": "stale-serve",
            "queue_depth": 7, "tenant_depths": {"gold": 3},
            "admitted": {"gold": 4, "bronze": 1},
            "tenant_waits_ms": {"gold": [5.0, 9.0], "bronze": [80.0]},
            "sheds": {"bronze": 3}})
        log.emit("alert", {"tenant": "bronze", "objective": "avail",
                           "state": "firing", "burn_fast": 9.0})
        snap = top.snapshot_from_log(log.path)
        frame = top.render(snap)
        assert "stale-serve" in frame
        assert "bronze" in frame and "FIRING:avail" in frame
        assert "gold" in frame

    def test_log_mode_alert_reconciliation(self, tmp_path):
        # an alert CLEAR newer than the last overload record's slo
        # snapshot must win — the header can never show a stale FIRING
        from matrel_tpu.obs import top
        log = EventLog(str(tmp_path / "ev.jsonl"))
        log.emit("overload", {
            "rung": 0, "queue_depth": 0, "admitted": {"gold": 1},
            "tenant_waits_ms": {"gold": [2.0]},
            "slo": {"tenants": {"gold": {"objectives": {
                "avail": {"state": "firing", "burn_fast": 9.0}},
                "latency_ms": {}, "qps": 1.0, "shed_rate": 0.0,
                "counts": {}}},
                "alerts_active": 1, "alerts_fired": 1,
                "alerts_cleared": 0}})
        log.emit("alert", {"tenant": "gold", "objective": "avail",
                           "state": "clear", "burn_fast": 0.0})
        snap = top.snapshot_from_log(log.path)
        assert snap["slo"]["alerts_active"] == 0
        st = snap["slo"]["tenants"]["gold"]["objectives"]["avail"]
        assert st["state"] == "ok"

    def test_cli_once_against_log(self, tmp_path, capsys):
        import argparse
        from matrel_tpu.obs import top
        log = EventLog(str(tmp_path / "ev.jsonl"))
        log.emit("overload", {"rung": 0, "queue_depth": 0,
                              "admitted": {"gold": 2},
                              "tenant_waits_ms": {"gold": [1.0]}})
        args = argparse.Namespace(url=None, port=None, log=log.path,
                                  interval=0.1, once=True,
                                  iterations=None)
        assert top.main(args) == 0
        out = capsys.readouterr().out
        assert "matrel_tpu top" in out and "gold" in out

    def test_cli_unreachable_endpoint_exits_nonzero(self, capsys):
        import argparse
        from matrel_tpu.obs import top
        args = argparse.Namespace(url=None, port=_free_port(),
                                  log=None, interval=0.1, once=True,
                                  iterations=None)
        assert top.main(args) == 1


# ---------------------------------------------------------------------------
# history: alert roll-up + --check gate
# ---------------------------------------------------------------------------


class TestHistoryAlertRollup:
    def _seed(self, tmp_path, cleared=True):
        log = EventLog(str(tmp_path / "ev.jsonl"))
        log.emit("overload", {
            "rung": 1, "queue_depth": 5,
            "admitted": {"gold": 10, "bronze": 4},
            "tenant_waits_ms": {"gold": [3.0], "bronze": [50.0]},
            "sheds": {"bronze": 6}})
        log.emit("alert", {"tenant": "bronze", "objective": "avail",
                           "state": "firing", "burn_fast": 8.0,
                           "attainment": 0.41})
        if cleared:
            log.emit("alert", {"tenant": "bronze",
                               "objective": "avail",
                               "state": "clear", "burn_fast": 0.2,
                               "attainment": 0.77})
        return log.path

    def test_summarize_alert_counts_and_attainment(self, tmp_path):
        from matrel_tpu.obs.history import summarize
        s = summarize(read_events(self._seed(tmp_path)))
        al = s["alerts"]
        assert al["fired"] == 1 and al["cleared"] == 1
        assert al["uncleared"] == []
        assert al["tenants"]["bronze"]["attainment"] == 0.77
        assert al["tenants"]["bronze"]["fired"] == 1

    def test_no_alert_events_summarize_none(self, tmp_path):
        from matrel_tpu.obs.history import summarize
        log = EventLog(str(tmp_path / "e2.jsonl"))
        log.emit("query", {"query_id": "q", "cache": "miss",
                           "execute_ms": 1.0, "out_shape": [1, 1],
                           "plan_cache": {}, "matmuls": []})
        assert summarize(read_events(log.path))["alerts"] is None

    def test_render_has_slo_columns_and_line(self, tmp_path):
        from matrel_tpu.obs.history import render_summary
        out = render_summary(read_events(self._seed(tmp_path)))
        assert "slo attain" in out and "alerts" in out
        assert "slo alerts: 1 fired / 1 cleared" in out
        # bronze row carries its attainment + alert count
        row = [ln for ln in out.splitlines()
               if ln.startswith("bronze")][0]
        assert "0.7700" in row

    def test_render_flags_uncleared(self, tmp_path):
        from matrel_tpu.obs.history import render_summary
        out = render_summary(
            read_events(self._seed(tmp_path, cleared=False)))
        assert "UNCLEARED: bronze:avail" in out

    def _args(self, path, check):
        import argparse
        return argparse.Namespace(log=path, summary=True, last=None,
                                  drift=False, check=check,
                                  drift_table=None, no_save=True)

    def test_check_exits_zero_when_cleared(self, tmp_path, capsys):
        from matrel_tpu.obs import history
        assert history.main(
            self._args(self._seed(tmp_path), True)) == 0

    def test_check_exits_nonzero_on_uncleared(self, tmp_path,
                                              capsys):
        from matrel_tpu.obs import history
        rc = history.main(
            self._args(self._seed(tmp_path, cleared=False), True))
        assert rc == 1
        assert "SLO CHECK FAILED" in capsys.readouterr().out

    def test_no_check_ignores_uncleared(self, tmp_path, capsys):
        from matrel_tpu.obs import history
        assert history.main(
            self._args(self._seed(tmp_path, cleared=False),
                       False)) == 0


# ---------------------------------------------------------------------------
# default-config structural zero (the PR 6 idiom)
# ---------------------------------------------------------------------------


class TestZeroOverheadContract:
    def test_default_session_owns_no_telemetry_objects(self, mesh8):
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        assert sess._slo is None
        assert sess._exporter is None

    def test_default_path_constructs_no_sketch_monitor_exporter(
            self, mesh8, rng, monkeypatch):
        # the poisoned-__init__ idiom: a default-config session over
        # REAL serve traffic (submit + run + drain) must never build a
        # sketch, a monitor, a plane or an exporter — the query path
        # is structurally identical to round 14
        from matrel_tpu.obs.export import MetricsExporter
        from matrel_tpu.obs.metrics import QuantileSketch
        from matrel_tpu.obs.slo import SLOMonitor, SLOPlane, _Window

        def poisoned(self, *a, **k):
            raise AssertionError(
                "telemetry object built on the default path")
        for cls in (QuantileSketch, SLOMonitor, SLOPlane, _Window,
                    MetricsExporter):
            monkeypatch.setattr(cls, "__init__", poisoned)
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        A = _mat(rng, 32, 32, mesh8)
        an = A.to_numpy()
        fut = sess.submit(A.expr().multiply_scalar(2.0))
        np.testing.assert_allclose(fut.result(timeout=60).to_numpy(),
                                   an * 2.0, rtol=1e-6, atol=1e-6)
        sess.compute(A.expr().multiply(A.expr()))
        sess.serve_drain(timeout=60)

    def test_default_session_starts_no_exporter_thread(self, mesh8):
        before = {t.name for t in threading.enumerate()}
        MatrelSession(mesh=mesh8, config=MatrelConfig())
        after = {t.name for t in threading.enumerate()}
        assert "matrel-metrics" not in after - before
