"""core/coo.py — element-sparse COOMatrix over the one-hot SpMV plans."""

import numpy as np
import pytest
import scipy.sparse as sp


from matrel_tpu import COOMatrix


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def random_coo(rng, n_r, n_c, m):
    return (rng.integers(0, n_r, m), rng.integers(0, n_c, m),
            rng.standard_normal(m).astype(np.float32))


class TestConstruction:
    def test_from_edges_and_scipy_agree(self, rng):
        r, c, v = random_coo(rng, 500, 300, 4000)
        a = COOMatrix.from_edges(r, c, v, shape=(500, 300))
        b = COOMatrix.from_scipy(
            sp.coo_matrix((v, (r, c)), shape=(500, 300)))
        np.testing.assert_allclose(a.to_dense(), b.to_dense())
        assert a.shape == b.shape == (500, 300)
        assert a.nnz == 4000

    def test_default_values_and_shape_inference(self):
        a = COOMatrix.from_edges([0, 2], [1, 3])
        assert a.shape == (3, 4)
        assert a.to_dense()[2, 3] == 1.0

    def test_bounds_and_length_validation(self):
        with pytest.raises(ValueError, match="out of bounds"):
            COOMatrix.from_edges([5], [0], shape=(3, 3))
        with pytest.raises(ValueError, match="mismatch"):
            COOMatrix.from_edges([1, 2], [0])
        with pytest.raises(ValueError, match="vals"):
            COOMatrix.from_edges([1], [0], vals=[1.0, 2.0])


class TestOps:
    def test_matvec_vs_scipy(self, rng):
        r, c, v = random_coo(rng, 2000, 1500, 30_000)
        A = COOMatrix.from_edges(r, c, v, shape=(2000, 1500))
        S = sp.coo_matrix((v, (r, c)), shape=(2000, 1500)).tocsr()
        x = rng.standard_normal(1500).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.matvec(x)), S @ x,
                                   rtol=3e-4, atol=3e-4)

    def test_rmatvec_and_T_vs_scipy(self, rng):
        r, c, v = random_coo(rng, 800, 1200, 10_000)
        A = COOMatrix.from_edges(r, c, v, shape=(800, 1200))
        S = sp.coo_matrix((v, (r, c)), shape=(800, 1200)).tocsr()
        y = rng.standard_normal(800).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.rmatvec(y)), S.T @ y,
                                   rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(np.asarray(A.T.matvec(y)), S.T @ y,
                                   rtol=3e-4, atol=3e-4)

    def test_matmat_vs_scipy(self, rng):
        r, c, v = random_coo(rng, 600, 400, 5_000)
        A = COOMatrix.from_edges(r, c, v, shape=(600, 400))
        S = sp.coo_matrix((v, (r, c)), shape=(600, 400)).tocsr()
        X = rng.standard_normal((400, 5)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.matmat(X)), S @ X,
                                   rtol=3e-4, atol=3e-4)

    def test_matvec_shape_errors(self, rng):
        A = COOMatrix.from_edges([0], [0], shape=(4, 6))
        with pytest.raises(ValueError, match="columns"):
            A.matvec(np.ones(4))
        with pytest.raises(ValueError, match="rows"):
            A.rmatvec(np.ones(6))
        with pytest.raises(ValueError, match="k"):
            A.matmat(np.ones((4, 2)))

    def test_duplicate_coordinates_accumulate(self):
        A = COOMatrix.from_edges([1, 1, 1], [2, 2, 0],
                                 vals=[1.0, 2.0, 5.0], shape=(3, 3))
        x = np.array([1.0, 0.0, 10.0], np.float32)
        got = np.asarray(A.matvec(x))
        np.testing.assert_allclose(got, [0.0, 35.0, 0.0])

    def test_segment_fallback_on_refused_plan(self):
        # one edge per 512-block over a huge row space -> plan refused;
        # matvec must still be correct through the segment path
        n_r = 512 * 20_000
        rows = np.arange(20_000, dtype=np.int64) * 512
        cols = np.arange(20_000, dtype=np.int64) % 64
        A = COOMatrix.from_edges(rows, cols, shape=(n_r, 64))
        assert A._get_plan() is None
        x = np.ones(64, np.float32)
        got = np.asarray(A.matvec(x))
        assert got.shape == (n_r,)
        assert got[rows].sum() == pytest.approx(20_000)
        assert got.sum() == pytest.approx(20_000)

    def test_empty_matrix(self):
        A = COOMatrix.from_edges([], [], shape=(10, 10))
        np.testing.assert_array_equal(np.asarray(A.matvec(np.ones(10))),
                                      np.zeros(10))


class TestShardedCOO:
    def test_sharded_matvec_matches_single(self, mesh8, rng):
        r, c, v = random_coo(rng, 6000, 4000, 50_000)
        A = COOMatrix.from_edges(r, c, v, shape=(6000, 4000))
        x = rng.standard_normal(4000).astype(np.float32)
        want = np.asarray(A.matvec(x))
        As = A.shard(mesh8)
        got = np.asarray(As.matvec(x))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)

    def test_sharded_matmat_uses_sharded_plan(self, mesh8, rng):
        r, c, v = random_coo(rng, 3000, 2000, 20_000)
        A = COOMatrix.from_edges(r, c, v, shape=(3000, 2000))
        As = A.shard(mesh8)
        X = rng.standard_normal((2000, 3)).astype(np.float32)
        got = np.asarray(As.matmat(X))
        np.testing.assert_allclose(got, np.asarray(A.matmat(X)),
                                   rtol=2e-5, atol=1e-5)
        # the sharded matrix must not have grown an unsharded plan
        assert As._plan is None and not As._plan_tried

    def test_dsl_then_eager_no_tracer_poisoning(self, rng):
        # arrays()/spmm_extra() first invoked INSIDE the executor's
        # trace must not cache tracers (regression: UnexpectedTracerError
        # on any later eager use of the same matrix)
        from matrel_tpu import execute
        r, c, v = random_coo(rng, 500, 400, 4000)
        A = COOMatrix.from_edges(r, c, v, shape=(500, 400))
        X = rng.standard_normal((400, 3)).astype(np.float32)
        from matrel_tpu.core.blockmatrix import BlockMatrix
        out = execute(A.multiply(BlockMatrix.from_numpy(X).expr()))
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ X,
                                   rtol=3e-4, atol=3e-4)
        # eager uses after the traced one must work and agree
        np.testing.assert_allclose(np.asarray(A.matmat(X)),
                                   A.to_dense() @ X, rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(np.asarray(A.matvec(X[:, 0])),
                                   A.to_dense() @ X[:, 0],
                                   rtol=3e-4, atol=3e-4)

    def test_shard_refused_graph_raises(self, mesh8):
        rows = np.arange(20_000, dtype=np.int64) * 512
        A = COOMatrix.from_edges(rows, np.zeros(20_000, np.int64),
                                 shape=(512 * 20_000, 1))
        with pytest.raises(ValueError, match="heavy-tailed"):
            A.shard(mesh8)


class TestDSLIntegration:
    """coo_leaf in the IR: SpMV lowering for matmuls, densify elsewhere."""

    def test_left_multiply_via_dsl(self, rng):
        from matrel_tpu import execute
        r, c, v = random_coo(rng, 700, 500, 6000)
        A = COOMatrix.from_edges(r, c, v, shape=(700, 500))
        x = rng.standard_normal((500, 3)).astype(np.float32)
        from matrel_tpu.core.blockmatrix import BlockMatrix
        X = BlockMatrix.from_numpy(x)
        out = execute(A.multiply(X.expr()))
        want = A.to_dense() @ x
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)

    def test_expanded_path_partially_sharded_vectors(self, rng, mesh8):
        """Regression: the expanded XLA SpMV path must REPLICATE its
        input vectors first (executor._coo_spmv_stack). A vector sliced
        from a 2D-sharded operand arrives partially sharded (P('y',) on
        the (2, 4) mesh) and jax 0.4.37's GSPMD partitioner miscompiles
        the one-hot contraction over such inputs — every entry scaled
        by exactly gx (the round-6 root cause of the 'COO DSL 2x-scale'
        pair and fuzz[49])."""
        from matrel_tpu import executor
        from matrel_tpu.config import default_config
        from matrel_tpu.core.blockmatrix import BlockMatrix
        r, c, v = random_coo(rng, 400, 600, 5000)
        S = COOMatrix.from_edges(r, c, v, shape=(400, 600))
        a = rng.standard_normal((5, 400)).astype(np.float32)
        padded = BlockMatrix.from_numpy(a, mesh=mesh8).data  # P(x, y)
        lo = executor.Lowerer(mesh8, default_config())
        plan = S._get_plan_t()
        assert plan is not None
        out = np.asarray(
            lo._coo_spmv_stack(plan, [padded[i, :400] for i in range(5)]))
        want = (a @ S.to_dense()).T
        np.testing.assert_allclose(out[:600], want, rtol=3e-4, atol=3e-4)

    def test_right_multiply_via_dsl(self, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 400, 600, 5000)
        S = COOMatrix.from_edges(r, c, v, shape=(400, 600))
        a = rng.standard_normal((5, 400)).astype(np.float32)
        A = BlockMatrix.from_numpy(a)
        out = execute(E.matmul(A.expr(), S.expr()))
        want = a @ S.to_dense()
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)

    def test_wide_rhs_takes_densify_fallback(self, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        r, c, v = random_coo(rng, 200, 150, 2000)
        A = COOMatrix.from_edges(r, c, v, shape=(200, 150))
        x = rng.standard_normal((150, 200)).astype(np.float32)  # k > 128
        out = execute(A.multiply(BlockMatrix.from_numpy(x).expr()))
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ x,
                                   rtol=2e-3, atol=2e-3)

    def test_non_matmul_use_densifies(self, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 64, 64, 500)
        A = COOMatrix.from_edges(r, c, v, shape=(64, 64))
        b = rng.standard_normal((64, 64)).astype(np.float32)
        B = BlockMatrix.from_numpy(b)
        out = execute(E.elemwise("add", A.expr(), B.expr()))
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() + b,
                                   rtol=1e-5, atol=1e-5)

    def test_chained_with_aggregation(self, rng):
        # rowSum(S·x) exercises rewrite rules over a coo_leaf tree
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 300, 250, 3000)
        S = COOMatrix.from_edges(r, c, v, shape=(300, 250))
        x = rng.standard_normal((250, 4)).astype(np.float32)
        expr = E.agg(S.multiply(BlockMatrix.from_numpy(x).expr()),
                     "sum", "row")
        out = execute(expr)
        want = (S.to_dense() @ x).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)


class TestCompactShardedExecutor:
    """DSL coo_leaf matmuls must run the compact-table Pallas path
    (13 B/slot; row-decomposed per device on a mesh) — the expanded
    ~224 B/slot XLA tables must never be built. Single-device compact
    branches are covered here too (interpret mode in CI)."""

    def _cfg(self):
        from matrel_tpu.config import MatrelConfig
        return MatrelConfig(pallas_interpret=True)

    @staticmethod
    def _forbid_expanded(plan):
        """Spy: the expanded-table path goes through plan.arrays()."""
        def _boom(*a, **k):
            raise AssertionError("expanded tables built")
        object.__setattr__(plan, "arrays", _boom)

    def test_single_device_compact_interpret(self, rng):
        # mesh.size == 1 takes the UNSHARDED compact branch
        # (compact_apply / compact_matmat_apply); regression cover for
        # the cached-tracer bug (compact_tables memoised tracers when
        # first called inside an executor trace)
        import jax
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.core import mesh as mesh_lib
        mesh1 = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
        r, c, v = random_coo(rng, 600, 500, 5000)
        A = COOMatrix.from_edges(r, c, v, shape=(600, 500))
        x = rng.standard_normal((500, 3)).astype(np.float32)
        self._forbid_expanded(A._get_plan())
        out = execute(A.multiply(BlockMatrix.from_numpy(
            x, mesh=mesh1).expr()), mesh=mesh1, config=self._cfg())
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ x,
                                   rtol=3e-4, atol=3e-4)
        # memo must hold committed arrays, not trace leftovers
        assert not isinstance(A._plan._compact_dev[0], jax.core.Tracer)
        # single vector → matvec kernel branch; plan reused across
        # compiles (the sequence the cached-tracer bug broke)
        x1 = rng.standard_normal((500, 1)).astype(np.float32)
        out1 = execute(A.multiply(BlockMatrix.from_numpy(
            x1, mesh=mesh1).expr()), mesh=mesh1, config=self._cfg())
        np.testing.assert_allclose(out1.to_numpy(), A.to_dense() @ x1,
                                   rtol=3e-4, atol=3e-4)

    def test_left_multiply_compact_on_mesh(self, mesh8, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        r, c, v = random_coo(rng, 700, 500, 6000)
        A = COOMatrix.from_edges(r, c, v, shape=(700, 500))
        x = rng.standard_normal((500, 3)).astype(np.float32)
        X = BlockMatrix.from_numpy(x, mesh=mesh8)
        # spy: the expanded-table path goes through plan.arrays(); the
        # compact path must never touch it (in-trace staging returns
        # uncached tracers, so _tables stays None on BOTH paths — state
        # alone can't discriminate)
        plan = A._get_plan()
        self._forbid_expanded(plan)
        out = execute(A.multiply(X.expr()), mesh=mesh8,
                      config=self._cfg())
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ x,
                                   rtol=3e-4, atol=3e-4)
        # compact sharded tables were built for THIS mesh, committed
        # (not tracers), block axis spread over all 8 devices
        tabs = plan._compact_sharded[mesh8]
        assert len(tabs[0].sharding.device_set) == 8
        assert plan._tables is None
        assert plan._spmm_tables is None

    def test_single_vector_compact_on_mesh(self, mesh8, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        r, c, v = random_coo(rng, 900, 400, 7000)
        A = COOMatrix.from_edges(r, c, v, shape=(900, 400))
        x = rng.standard_normal((400, 1)).astype(np.float32)
        out = execute(A.multiply(BlockMatrix.from_numpy(
            x, mesh=mesh8).expr()), mesh=mesh8, config=self._cfg())
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ x,
                                   rtol=3e-4, atol=3e-4)
        assert A._plan._tables is None

    def test_right_multiply_compact_on_mesh(self, mesh8, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 400, 600, 5000)
        S = COOMatrix.from_edges(r, c, v, shape=(400, 600))
        a = rng.standard_normal((5, 400)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        out = execute(E.matmul(A.expr(), S.expr()), mesh=mesh8,
                      config=self._cfg())
        np.testing.assert_allclose(out.to_numpy(), a @ S.to_dense(),
                                   rtol=3e-4, atol=3e-4)
        # the transpose plan drove it; expanded tables never built
        assert S._plan_t is not None
        assert S._plan_t._tables is None

    def test_compact_with_overflow_rows_on_mesh(self, mesh8, rng):
        # heavy row → plan carries overflow COO; sharded path must add
        # it after the gather
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        m = 20_000
        r = np.where(rng.random(m) < 0.3, 7,
                     rng.integers(0, 2048, m)).astype(np.int64)
        c = rng.integers(0, 512, m).astype(np.int64)
        v = rng.standard_normal(m).astype(np.float32)
        A = COOMatrix.from_edges(r, c, v, shape=(2048, 512))
        assert A._get_plan().ov_rows is not None
        x = rng.standard_normal((512, 2)).astype(np.float32)
        out = execute(A.multiply(BlockMatrix.from_numpy(
            x, mesh=mesh8).expr()), mesh=mesh8, config=self._cfg())
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ x,
                                   rtol=3e-4, atol=3e-4)


class TestCOORelational:
    """Edge-list-native σ/γ/⋈ — results must match the dense masked
    semantics (and hence the IR lowerings) exactly."""

    def _mat(self, rng, n=40, m=30, nnz=200):
        from matrel_tpu.core.coo import COOMatrix
        r = rng.integers(0, n, nnz)
        c = rng.integers(0, m, nnz)
        v = rng.standard_normal(nnz).astype(np.float32)
        return COOMatrix.from_edges(r, c, v, shape=(n, m))

    def test_select_value(self, rng):
        A = self._mat(rng)
        d = A.to_dense()
        got = A.select_value(lambda v: v > 0.3).to_dense()
        np.testing.assert_allclose(got, np.where(d > 0.3, d, 0.0),
                                   rtol=1e-6)
        with pytest.raises(ValueError, match="fill"):
            A.select_value(lambda v: v > 0, fill=1.0)

    def test_select_index(self, rng):
        A = self._mat(rng)
        d = A.to_dense()
        got = A.select_index(rows=lambda i: i % 3 == 0,
                             cols=lambda j: j < 10).to_dense()
        want = d.copy()
        want[np.arange(40) % 3 != 0, :] = 0
        want[:, 10:] = 0
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_axis_aggregates(self, rng):
        A = self._mat(rng)
        d = A.to_dense().astype(np.float64)
        np.testing.assert_allclose(A.row_sum()[:, 0], d.sum(1), rtol=1e-5)
        np.testing.assert_allclose(A.col_sum()[0], d.sum(0), rtol=1e-5)
        nz = d != 0
        np.testing.assert_allclose(A.row_count()[:, 0], nz.sum(1))
        np.testing.assert_allclose(A.col_count()[0], nz.sum(0))
        # avg/max/min over NONZERO entries (relational γ semantics)
        cnt = np.maximum(nz.sum(1), 1)
        np.testing.assert_allclose(A.row_avg()[:, 0],
                                   np.where(nz.any(1), d.sum(1) / cnt, 0),
                                   rtol=1e-5)
        # dense-lowering parity: implicit zeros participate in max/min
        np.testing.assert_allclose(A.row_max()[:, 0], d.max(1), rtol=1e-5)
        assert A.sum() == pytest.approx(d.sum(), rel=1e-5)

    def test_trace(self, rng):
        from matrel_tpu.core.coo import COOMatrix
        A = COOMatrix.from_edges([0, 1, 2, 1], [0, 1, 0, 1],
                                 [1.0, 2.0, 3.0, 4.0], shape=(3, 3))
        assert A.trace() == pytest.approx(7.0)   # dups additive on diag

    def test_join_on_index_union_semantics(self, rng):
        from matrel_tpu.core.coo import COOMatrix
        A = self._mat(rng, nnz=100)
        B = self._mat(rng, nnz=120)
        da, db = A.to_dense(), B.to_dense()
        # merge where absence reads 0 — union coordinates matter
        got = A.join_on_index(B, lambda x, y: x * y + x).to_dense()
        np.testing.assert_allclose(got, da * db + da, rtol=1e-5,
                                   atol=1e-6)
        with pytest.raises(ValueError, match="mismatch"):
            A.join_on_index(self._mat(rng, n=10, m=10), lambda x, y: x)
        # densifying merges must be rejected, not silently wrong
        with pytest.raises(ValueError, match="dense"):
            A.join_on_index(B, lambda x, y: x + y + 1.0)

    def test_all_negative_row_max_matches_dense(self, rng):
        from matrel_tpu.core.coo import COOMatrix
        A = COOMatrix.from_edges([0, 0], [1, 2], [-3.0, -5.0],
                                 shape=(2, 4))
        d = A.to_dense()
        np.testing.assert_allclose(A.row_max()[:, 0], d.max(1))   # [0, 0]
        np.testing.assert_allclose(A.row_min()[:, 0], d.min(1))   # [-5, 0]
        # a FULLY populated row keeps its true (negative) max
        B = COOMatrix.from_edges([0, 0], [0, 1], [-3.0, -5.0],
                                 shape=(1, 2))
        np.testing.assert_allclose(B.row_max()[:, 0], [-3.0])

    def test_scale_smoke_no_densify(self, rng):
        # 200k x 200k with 50k edges: any densify would be 160 GB
        from matrel_tpu.core.coo import COOMatrix
        n, nnz = 200_000, 50_000
        r = rng.integers(0, n, nnz); c = rng.integers(0, n, nnz)
        v = rng.standard_normal(nnz).astype(np.float32)
        A = COOMatrix.from_edges(r, c, v, shape=(n, n))
        pos = A.select_value(lambda x: x > 0)
        assert 0 < pos.nnz < nnz
        rs = A.row_sum()
        want = np.zeros(n); np.add.at(want, r, v)
        np.testing.assert_allclose(rs[:, 0], want, rtol=1e-4, atol=1e-5)
        j = A.join_on_index(pos, lambda x, y: x - y)   # A - positives
        neg = A.select_value(lambda x: x < 0)
        np.testing.assert_allclose(np.sort(j.vals), np.sort(neg.vals),
                                   rtol=1e-6)

    def test_norms(self, rng):
        from matrel_tpu.core.coo import COOMatrix
        A = COOMatrix.from_edges([0, 0, 1], [1, 1, 2],
                                 [3.0, -1.0, -4.0], shape=(3, 3))
        d = A.to_dense()          # dup at (0,1) sums to 2.0
        assert A.norm() == pytest.approx(np.linalg.norm(d))
        assert A.norm("l1") == pytest.approx(np.abs(d).sum())
        assert A.norm("max") == pytest.approx(np.abs(d).max())


class TestCOOValueJoin:
    """Edge-list-native ⋈ on values: nonzero entry tuples matched by
    structured (sorted) or callable (capped brute) predicates."""

    def _oracle(self, A, B, merge_np, pred_np):
        sa = A.to_dense()
        sb = B.to_dense()
        ia, ja = np.nonzero(sa)
        ib, jb = np.nonzero(sb)
        pairs = []
        for x, (i, j) in zip(sa[ia, ja], zip(ia, ja)):
            for y, (k, l) in zip(sb[ib, jb], zip(ib, jb)):
                if pred_np(x, y):
                    pairs.append((i, j, k, l, merge_np(x, y)))
        return sorted(pairs)

    def _got(self, res):
        return sorted(zip(*(a.tolist() for a in res[:4]),
                          res[4].tolist()))

    @pytest.mark.parametrize("pred", ["eq", "lt", "le", "gt", "ge"])
    def test_structured_matches_bruteforce(self, rng, pred):
        import operator
        pool = np.array([-2.0, -1.0, 1.0, 1.0, 2.0], np.float32)
        r, c = rng.integers(0, 20, 60), rng.integers(0, 15, 60)
        A = COOMatrix.from_edges(r, c, rng.choice(pool, 60),
                                 shape=(20, 15))
        r2, c2 = rng.integers(0, 10, 40), rng.integers(0, 12, 40)
        B = COOMatrix.from_edges(r2, c2, rng.choice(pool, 40),
                                 shape=(10, 12))
        ops = {"eq": operator.eq, "lt": operator.lt, "le": operator.le,
               "gt": operator.gt, "ge": operator.ge}
        got = self._got(A.join_on_value(B, merge="mul", predicate=pred))
        want = self._oracle(A, B, operator.mul, ops[pred])
        assert [g[:4] for g in got] == [w[:4] for w in want]
        np.testing.assert_allclose([g[4] for g in got],
                                   [w[4] for w in want], rtol=1e-6)

    def test_callable_pred_and_merges(self, rng):
        r, c = rng.integers(0, 8, 20), rng.integers(0, 8, 20)
        A = COOMatrix.from_edges(r, c, rng.standard_normal(20),
                                 shape=(8, 8))
        B = COOMatrix.from_edges(c, r, rng.standard_normal(20),
                                 shape=(8, 8))
        got = self._got(A.join_on_value(
            B, merge=lambda x, y: x - y,
            predicate=lambda x, y: x + y > 0.5))
        want = self._oracle(A, B, lambda x, y: x - y,
                            lambda x, y: x + y > 0.5)
        assert [g[:4] for g in got] == [w[:4] for w in want]
        # structured merges
        ia, ja, ib, jb, v = A.join_on_value(B, merge="left",
                                            predicate="ge")
        dense_a = A.to_dense()
        np.testing.assert_allclose(v, dense_a[ia, ja], rtol=1e-6)

    def test_pair_cap_refusal(self, rng):
        r = rng.integers(0, 100, 3000)
        c = rng.integers(0, 100, 3000)
        A = COOMatrix.from_edges(r, c, np.ones(3000), shape=(100, 100))
        with pytest.raises(ValueError, match="max_pairs"):
            A.join_on_value(A, merge="mul", predicate="eq",
                            max_pairs=10)
        with pytest.raises(ValueError, match="max_pairs"):
            A.join_on_value(A, merge="mul",
                            predicate=lambda x, y: x == y,
                            max_pairs=10)

    def test_zero_entries_never_join(self):
        # duplicate cancellation produces an explicit zero entry; it
        # must be absent from the join
        A = COOMatrix.from_edges([0, 0, 1], [0, 0, 1], [1.0, -1.0, 2.0],
                                 shape=(2, 2))
        B = COOMatrix.from_edges([0], [0], [0.5], shape=(1, 1))
        ia, ja, ib, jb, v = A.join_on_value(B, merge="mul",
                                            predicate="gt")
        assert list(zip(ia, ja)) == [(1, 1)]
        np.testing.assert_allclose(v, [1.0])

    def test_nan_entries_match_nothing_structured(self):
        # IEEE: NaN compares False — structured and callable paths agree
        A = COOMatrix.from_edges([0, 1], [0, 1], [1.0, np.nan],
                                 shape=(2, 2))
        B = COOMatrix.from_edges([0, 1], [0, 1], [np.nan, 2.0],
                                 shape=(2, 2))
        for pred_s, pred_f in [("lt", lambda x, y: x < y),
                               ("eq", lambda x, y: x == y),
                               ("ge", lambda x, y: x >= y)]:
            got_s = A.join_on_value(B, merge="left", predicate=pred_s)
            got_f = A.join_on_value(B, merge="left", predicate=pred_f)
            assert got_s[0].tolist() == got_f[0].tolist(), pred_s
            assert got_s[3].tolist() == got_f[3].tolist(), pred_s
        # only the (1.0, 2.0) pair can ever match 'lt'
        ia, ja, ib, jb, v = A.join_on_value(B, merge="right",
                                            predicate="lt")
        assert list(zip(ia, ja, ib, jb)) == [(0, 0, 1, 1)]
        np.testing.assert_allclose(v, [2.0])

    def test_coo_join_totals_match_dense_streaming(self, mesh8, rng):
        # cross-surface metamorphic check: for merge='mul' (zero
        # operands annihilate), the sum over COO matched PAIRS equals
        # the dense pair-matrix aggregate of the same logical matrices
        from matrel_tpu import execute
        from matrel_tpu.relational import ops as R
        from matrel_tpu.core.blockmatrix import BlockMatrix
        r, c, v = random_coo(rng, 40, 30, 200)
        r2, c2, v2 = random_coo(rng, 20, 25, 150)
        A = COOMatrix.from_edges(r, c, v, shape=(40, 30))
        B = COOMatrix.from_edges(r2, c2, v2, shape=(20, 25))
        for pred in ("lt", "gt", "eq"):
            pairs = A.join_on_value(B, merge="mul", predicate=pred)
            coo_total = float(pairs[4].astype(np.float64).sum())
            j = R.join_on_values(
                BlockMatrix.from_numpy(A.to_dense(), mesh=mesh8),
                BlockMatrix.from_numpy(B.to_dense(), mesh=mesh8),
                merge="mul", predicate=pred)
            dense_total = float(R.aggregate(j, "sum", "all")
                                .compute().to_numpy()[0, 0])
            assert abs(coo_total - dense_total) <= 1e-3 * max(
                1.0, abs(dense_total)), (pred, coo_total, dense_total)


def test_infer_dtype_asserts_coo_payload_f32(mesh8):
    # VERDICT r4 "what's weak" #4: a dtype-bearing COOMatrix must fail
    # loudly at the infer_dtype boundary instead of silently keying the
    # wrong autotune table row
    import numpy as np
    import pytest
    from matrel_tpu.core.coo import COOMatrix
    from matrel_tpu.parallel.planner import infer_dtype
    rng = np.random.default_rng(0)
    A = COOMatrix.from_edges(rng.integers(0, 32, 50),
                             rng.integers(0, 32, 50), shape=(32, 32))
    x = np.random.default_rng(1).standard_normal((32, 2)).astype(
        np.float32)
    from matrel_tpu.core.blockmatrix import BlockMatrix
    e = A.multiply(BlockMatrix.from_numpy(x, mesh=mesh8).expr())
    assert infer_dtype(e) == np.dtype("float32")
    A.vals = A.vals.astype(np.float64)          # forge a future dtype
    with pytest.raises(TypeError, match="float32"):
        infer_dtype(A.multiply(
            BlockMatrix.from_numpy(x, mesh=mesh8).expr()))
