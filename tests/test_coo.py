"""core/coo.py — element-sparse COOMatrix over the one-hot SpMV plans."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from matrel_tpu import COOMatrix


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def random_coo(rng, n_r, n_c, m):
    return (rng.integers(0, n_r, m), rng.integers(0, n_c, m),
            rng.standard_normal(m).astype(np.float32))


class TestConstruction:
    def test_from_edges_and_scipy_agree(self, rng):
        r, c, v = random_coo(rng, 500, 300, 4000)
        a = COOMatrix.from_edges(r, c, v, shape=(500, 300))
        b = COOMatrix.from_scipy(
            sp.coo_matrix((v, (r, c)), shape=(500, 300)))
        np.testing.assert_allclose(a.to_dense(), b.to_dense())
        assert a.shape == b.shape == (500, 300)
        assert a.nnz == 4000

    def test_default_values_and_shape_inference(self):
        a = COOMatrix.from_edges([0, 2], [1, 3])
        assert a.shape == (3, 4)
        assert a.to_dense()[2, 3] == 1.0

    def test_bounds_and_length_validation(self):
        with pytest.raises(ValueError, match="out of bounds"):
            COOMatrix.from_edges([5], [0], shape=(3, 3))
        with pytest.raises(ValueError, match="mismatch"):
            COOMatrix.from_edges([1, 2], [0])
        with pytest.raises(ValueError, match="vals"):
            COOMatrix.from_edges([1], [0], vals=[1.0, 2.0])


class TestOps:
    def test_matvec_vs_scipy(self, rng):
        r, c, v = random_coo(rng, 2000, 1500, 30_000)
        A = COOMatrix.from_edges(r, c, v, shape=(2000, 1500))
        S = sp.coo_matrix((v, (r, c)), shape=(2000, 1500)).tocsr()
        x = rng.standard_normal(1500).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.matvec(x)), S @ x,
                                   rtol=3e-4, atol=3e-4)

    def test_rmatvec_and_T_vs_scipy(self, rng):
        r, c, v = random_coo(rng, 800, 1200, 10_000)
        A = COOMatrix.from_edges(r, c, v, shape=(800, 1200))
        S = sp.coo_matrix((v, (r, c)), shape=(800, 1200)).tocsr()
        y = rng.standard_normal(800).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.rmatvec(y)), S.T @ y,
                                   rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(np.asarray(A.T.matvec(y)), S.T @ y,
                                   rtol=3e-4, atol=3e-4)

    def test_matmat_vs_scipy(self, rng):
        r, c, v = random_coo(rng, 600, 400, 5_000)
        A = COOMatrix.from_edges(r, c, v, shape=(600, 400))
        S = sp.coo_matrix((v, (r, c)), shape=(600, 400)).tocsr()
        X = rng.standard_normal((400, 5)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.matmat(X)), S @ X,
                                   rtol=3e-4, atol=3e-4)

    def test_matvec_shape_errors(self, rng):
        A = COOMatrix.from_edges([0], [0], shape=(4, 6))
        with pytest.raises(ValueError, match="columns"):
            A.matvec(np.ones(4))
        with pytest.raises(ValueError, match="rows"):
            A.rmatvec(np.ones(6))
        with pytest.raises(ValueError, match="k"):
            A.matmat(np.ones((4, 2)))

    def test_duplicate_coordinates_accumulate(self):
        A = COOMatrix.from_edges([1, 1, 1], [2, 2, 0],
                                 vals=[1.0, 2.0, 5.0], shape=(3, 3))
        x = np.array([1.0, 0.0, 10.0], np.float32)
        got = np.asarray(A.matvec(x))
        np.testing.assert_allclose(got, [0.0, 35.0, 0.0])

    def test_segment_fallback_on_refused_plan(self):
        # one edge per 512-block over a huge row space -> plan refused;
        # matvec must still be correct through the segment path
        n_r = 512 * 20_000
        rows = np.arange(20_000, dtype=np.int64) * 512
        cols = np.arange(20_000, dtype=np.int64) % 64
        A = COOMatrix.from_edges(rows, cols, shape=(n_r, 64))
        assert A._get_plan() is None
        x = np.ones(64, np.float32)
        got = np.asarray(A.matvec(x))
        assert got.shape == (n_r,)
        assert got[rows].sum() == pytest.approx(20_000)
        assert got.sum() == pytest.approx(20_000)

    def test_empty_matrix(self):
        A = COOMatrix.from_edges([], [], shape=(10, 10))
        np.testing.assert_array_equal(np.asarray(A.matvec(np.ones(10))),
                                      np.zeros(10))


class TestShardedCOO:
    def test_sharded_matvec_matches_single(self, mesh8, rng):
        r, c, v = random_coo(rng, 6000, 4000, 50_000)
        A = COOMatrix.from_edges(r, c, v, shape=(6000, 4000))
        x = rng.standard_normal(4000).astype(np.float32)
        want = np.asarray(A.matvec(x))
        As = A.shard(mesh8)
        got = np.asarray(As.matvec(x))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)

    def test_sharded_matmat_uses_sharded_plan(self, mesh8, rng):
        r, c, v = random_coo(rng, 3000, 2000, 20_000)
        A = COOMatrix.from_edges(r, c, v, shape=(3000, 2000))
        As = A.shard(mesh8)
        X = rng.standard_normal((2000, 3)).astype(np.float32)
        got = np.asarray(As.matmat(X))
        np.testing.assert_allclose(got, np.asarray(A.matmat(X)),
                                   rtol=2e-5, atol=1e-5)
        # the sharded matrix must not have grown an unsharded plan
        assert As._plan is None and not As._plan_tried

    def test_dsl_then_eager_no_tracer_poisoning(self, rng):
        # arrays()/spmm_extra() first invoked INSIDE the executor's
        # trace must not cache tracers (regression: UnexpectedTracerError
        # on any later eager use of the same matrix)
        from matrel_tpu import execute
        r, c, v = random_coo(rng, 500, 400, 4000)
        A = COOMatrix.from_edges(r, c, v, shape=(500, 400))
        X = rng.standard_normal((400, 3)).astype(np.float32)
        from matrel_tpu.core.blockmatrix import BlockMatrix
        out = execute(A.multiply(BlockMatrix.from_numpy(X).expr()))
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ X,
                                   rtol=3e-4, atol=3e-4)
        # eager uses after the traced one must work and agree
        np.testing.assert_allclose(np.asarray(A.matmat(X)),
                                   A.to_dense() @ X, rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(np.asarray(A.matvec(X[:, 0])),
                                   A.to_dense() @ X[:, 0],
                                   rtol=3e-4, atol=3e-4)

    def test_shard_refused_graph_raises(self, mesh8):
        rows = np.arange(20_000, dtype=np.int64) * 512
        A = COOMatrix.from_edges(rows, np.zeros(20_000, np.int64),
                                 shape=(512 * 20_000, 1))
        with pytest.raises(ValueError, match="heavy-tailed"):
            A.shard(mesh8)


class TestDSLIntegration:
    """coo_leaf in the IR: SpMV lowering for matmuls, densify elsewhere."""

    def test_left_multiply_via_dsl(self, rng):
        from matrel_tpu import execute
        r, c, v = random_coo(rng, 700, 500, 6000)
        A = COOMatrix.from_edges(r, c, v, shape=(700, 500))
        x = rng.standard_normal((500, 3)).astype(np.float32)
        from matrel_tpu.core.blockmatrix import BlockMatrix
        X = BlockMatrix.from_numpy(x)
        out = execute(A.multiply(X.expr()))
        want = A.to_dense() @ x
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)

    def test_right_multiply_via_dsl(self, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 400, 600, 5000)
        S = COOMatrix.from_edges(r, c, v, shape=(400, 600))
        a = rng.standard_normal((5, 400)).astype(np.float32)
        A = BlockMatrix.from_numpy(a)
        out = execute(E.matmul(A.expr(), S.expr()))
        want = a @ S.to_dense()
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)

    def test_wide_rhs_takes_densify_fallback(self, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        r, c, v = random_coo(rng, 200, 150, 2000)
        A = COOMatrix.from_edges(r, c, v, shape=(200, 150))
        x = rng.standard_normal((150, 200)).astype(np.float32)  # k > 128
        out = execute(A.multiply(BlockMatrix.from_numpy(x).expr()))
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() @ x,
                                   rtol=2e-3, atol=2e-3)

    def test_non_matmul_use_densifies(self, rng):
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 64, 64, 500)
        A = COOMatrix.from_edges(r, c, v, shape=(64, 64))
        b = rng.standard_normal((64, 64)).astype(np.float32)
        B = BlockMatrix.from_numpy(b)
        out = execute(E.elemwise("add", A.expr(), B.expr()))
        np.testing.assert_allclose(out.to_numpy(), A.to_dense() + b,
                                   rtol=1e-5, atol=1e-5)

    def test_chained_with_aggregation(self, rng):
        # rowSum(S·x) exercises rewrite rules over a coo_leaf tree
        from matrel_tpu import execute
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as E
        r, c, v = random_coo(rng, 300, 250, 3000)
        S = COOMatrix.from_edges(r, c, v, shape=(300, 250))
        x = rng.standard_normal((250, 4)).astype(np.float32)
        expr = E.agg(S.multiply(BlockMatrix.from_numpy(x).expr()),
                     "sum", "row")
        out = execute(expr)
        want = (S.to_dense() @ x).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)
