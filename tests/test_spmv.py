"""ops/spmv.py — blocked one-hot SpMV and the width-row gather.

Oracle: scipy-style COO accumulation in numpy f64. The plan layouts are
data-dependent (per-graph static shapes), so the suite sweeps shapes,
duplicates, weights, skew (overflow path) and the refusal fallback.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from matrel_tpu.ops import spmv as spmv_lib


def coo_oracle(rows, cols, vals, x, n_rows):
    y = np.zeros((n_rows,), np.float64)
    np.add.at(y, rows, vals * x[cols])
    return y


class TestGather1D:
    def test_matches_plain_indexing(self):
        rng = np.random.default_rng(0)
        for n, m in [(17, 5), (1000, 2048), (8, 8), (4096, 100_000)]:
            table = rng.standard_normal(n).astype(np.float32)
            idx = rng.integers(0, n, m).astype(np.int32)
            got = np.asarray(spmv_lib.gather_1d(jnp.asarray(table),
                                                jnp.asarray(idx)))
            np.testing.assert_array_equal(got, table[idx])

    def test_sentinel_reads_zero(self):
        table = jnp.arange(1, 11, dtype=jnp.float32)
        idx = jnp.asarray([0, 10, 5], jnp.int32)   # 10 == len(table)
        got = np.asarray(spmv_lib.gather_1d(table, idx))
        np.testing.assert_array_equal(got, [1.0, 0.0, 6.0])

    def test_2d_index_shape(self):
        rng = np.random.default_rng(1)
        table = rng.standard_normal(97).astype(np.float32)
        idx = rng.integers(0, 97, (13, 29)).astype(np.int32)
        got = np.asarray(spmv_lib.gather_1d(jnp.asarray(table),
                                            jnp.asarray(idx)))
        np.testing.assert_array_equal(got, table[idx])


def random_coo(rng, n_rows, n_cols, m, weighted=True):
    rows = rng.integers(0, n_rows, m).astype(np.int64)
    cols = rng.integers(0, n_cols, m).astype(np.int64)
    vals = (rng.standard_normal(m).astype(np.float32) if weighted
            else np.ones(m, np.float32))
    return rows, cols, vals


class TestSpMVPlan:
    @pytest.mark.parametrize("n_rows,n_cols,m", [
        (1000, 1000, 10_000),     # square, multi-block
        (300, 700, 5_000),        # rectangular, n_rows not /512
        (512, 512, 512),          # exactly one block
        (100, 100, 3),            # nearly empty
        (2000, 50, 20_000),       # many duplicate cols
    ])
    def test_matches_oracle(self, n_rows, n_cols, m):
        rng = np.random.default_rng(n_rows + m)
        rows, cols, vals = random_coo(rng, n_rows, n_cols, m)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_rows, n_cols=n_cols)
        assert plan is not None
        x = rng.standard_normal(n_cols).astype(np.float32)
        got = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))
        want = coo_oracle(rows, cols, vals, x, n_rows)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)

    def test_unsorted_input_and_duplicate_edges(self):
        rng = np.random.default_rng(7)
        rows = np.array([5, 5, 5, 0, 999, 0, 5], np.int64)
        cols = np.array([1, 1, 2, 3, 4, 3, 1], np.int64)
        vals = rng.standard_normal(7).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=1000, n_cols=10)
        x = rng.standard_normal(10).astype(np.float32)
        got = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))
        want = coo_oracle(rows, cols, vals, x, 1000)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_empty_edge_list(self):
        plan = spmv_lib.build_spmv_plan(np.zeros(0), np.zeros(0),
                                        n_rows=100, n_cols=100)
        got = np.asarray(spmv_lib.spmv(plan, jnp.ones((100,), jnp.float32)))
        np.testing.assert_array_equal(got, np.zeros(100))

    def test_skewed_degrees_use_overflow(self):
        # one hub row receives most edges -> quantile capacity forces an
        # overflow COO; numerics must still match
        rng = np.random.default_rng(3)
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=4096, n_cols=512)
        assert plan is not None and plan.ov_rows is not None
        x = rng.standard_normal(512).astype(np.float32)
        got = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))
        want = coo_oracle(rows, cols, vals, x, 4096)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_refuses_pathological_padding(self):
        # edges spread one-per-block over a huge row space: capacity 128
        # per block pads >4x the edge count (and >1M slots absolute)
        # -> build returns None
        n_rows = 512 * 20_000
        rows = (np.arange(20_000, dtype=np.int64) * 512)
        cols = np.zeros(20_000, np.int64)
        plan = spmv_lib.build_spmv_plan(rows, cols, n_rows=n_rows,
                                        n_cols=1, max_padding=4.0)
        assert plan is None

    def test_out_of_bounds_indices_raise(self):
        # both fill paths must fail loudly — a C++ truncating-division
        # guard once let rows in (-block, 0) through silently (regression)
        with pytest.raises(ValueError, match="out of bounds"):
            spmv_lib.build_spmv_plan(np.array([-1, 3]), np.array([0, 1]),
                                     n_rows=16, n_cols=4)
        with pytest.raises(ValueError, match="out of bounds"):
            spmv_lib.build_spmv_plan(np.array([1, 3]), np.array([0, -2]),
                                     n_rows=16, n_cols=4)
        with pytest.raises(ValueError, match="out of bounds"):
            spmv_lib.build_spmv_plan(np.array([16]), np.array([0]),
                                     n_rows=16, n_cols=4)

    def test_padding_ratio_reported(self):
        rng = np.random.default_rng(11)
        rows, cols, vals = random_coo(rng, 1024, 1024, 50_000)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=1024, n_cols=1024)
        assert 1.0 <= plan.padding_ratio < 2.0


class TestShardedSpMV:
    def test_spmv_sharded_matches_single(self, mesh8):
        import jax.numpy as jnp
        rng = np.random.default_rng(8)
        n_r, n_c, m = 8192, 4000, 60_000
        rows = rng.integers(0, n_r, m)
        cols = rng.integers(0, n_c, m)
        vals = rng.standard_normal(m).astype(np.float32)
        x = rng.standard_normal(n_c).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        want = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))
        plan_s = spmv_lib.shard_plan(
            spmv_lib.build_spmv_plan(rows, cols, vals,
                                     n_rows=n_r, n_cols=n_c), mesh8)
        got = np.asarray(spmv_lib.spmv_sharded(plan_s, x, mesh8))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)

    def test_shard_plan_shards_block_axis(self, mesh8):
        rng = np.random.default_rng(9)
        rows = rng.integers(0, 8192, 10_000)
        cols = rng.integers(0, 512, 10_000)
        plan = spmv_lib.shard_plan(
            spmv_lib.build_spmv_plan(rows, cols, n_rows=8192, n_cols=512),
            mesh8)
        assert plan.src8.shape[0] % 8 == 0
        assert len(plan.src8.sharding.device_set) == 8

    def test_shard_plan_rejects_expanded(self, mesh8):
        rng = np.random.default_rng(10)
        plan = spmv_lib.build_spmv_plan(rng.integers(0, 1024, 1000),
                                        rng.integers(0, 64, 1000),
                                        n_rows=1024, n_cols=64)
        plan.arrays()   # expand
        with pytest.raises(ValueError, match="before table expansion"):
            spmv_lib.shard_plan(plan, mesh8)

    def test_sharded_with_overflow(self, mesh8):
        import jax.numpy as jnp
        rng = np.random.default_rng(11)
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        x = rng.standard_normal(512).astype(np.float32)
        plan = spmv_lib.shard_plan(
            spmv_lib.build_spmv_plan(rows, cols, vals,
                                     n_rows=4096, n_cols=512), mesh8)
        assert plan.ov_rows is not None
        got = np.asarray(spmv_lib.spmv_sharded(plan, x, mesh8))
        want = coo_oracle(rows, cols, vals, x, 4096)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_sharded_hlo_contains_all_gather(self, mesh8):
        # plan-shape assertion (the Catalyst comparePlans analogue): the
        # sharded matvec's only collective is one tiled all-gather
        import jax
        rng = np.random.default_rng(14)
        rows = rng.integers(0, 8192, 20_000)
        cols = rng.integers(0, 1024, 20_000)
        plan = spmv_lib.shard_plan(
            spmv_lib.build_spmv_plan(rows, cols, n_rows=8192,
                                     n_cols=1024), mesh8)
        arrays = plan.arrays()
        run = spmv_lib._sharded_spmv_runner(
            (plan.n_rows, plan.n_cols, plan.block), mesh8,
            len(arrays) > 4)
        x = np.zeros(1024, np.float32)
        hlo = run.lower(*arrays[:4], x, *arrays[4:]).compile().as_text()
        assert "all-gather" in hlo
        assert "reduce-scatter" not in hlo and "all-to-all" not in hlo

    @pytest.mark.parametrize("k", [1, 3, 70])
    def test_spmm_sharded_matches_single(self, mesh8, k):
        import jax.numpy as jnp
        rng = np.random.default_rng(15 + k)
        n_r, n_c, m = 6000, 3000, 40_000
        rows = rng.integers(0, n_r, m)
        cols = rng.integers(0, n_c, m)
        vals = rng.standard_normal(m).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        X = rng.standard_normal((n_c, k)).astype(np.float32)
        want = np.asarray(spmv_lib.spmm(plan, jnp.asarray(X)))
        plan_s = spmv_lib.shard_plan(
            spmv_lib.build_spmv_plan(rows, cols, vals,
                                     n_rows=n_r, n_cols=n_c), mesh8)
        got = np.asarray(spmv_lib.spmm_sharded(plan_s, X, mesh8))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)

    def test_pagerank_sharded_matches_single(self, mesh8):
        from matrel_tpu.workloads import pagerank as pr
        rng = np.random.default_rng(12)
        n, m = 4000, 30_000
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        # impl='onehot' + mesh = the sharded variant, on any backend
        got = np.asarray(pr.pagerank_edges(src, dst, n, rounds=10,
                                           mesh=mesh8, impl="onehot"))
        want = np.asarray(pr.pagerank_edges(src, dst, n, rounds=10,
                                            impl="onehot"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-10)


class TestSpMM:
    @pytest.mark.parametrize("k", [1, 2, 7, 64, 100])
    def test_matches_scipy(self, k):
        import scipy.sparse as sp
        import jax.numpy as jnp
        rng = np.random.default_rng(k)
        n_r, n_c, m = 2500, 1800, 30_000
        rows = rng.integers(0, n_r, m)
        cols = rng.integers(0, n_c, m)
        vals = rng.standard_normal(m).astype(np.float32)
        S = sp.coo_matrix((vals, (rows, cols)), shape=(n_r, n_c)).tocsr()
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        X = rng.standard_normal((n_c, k)).astype(np.float32)
        got = np.asarray(spmv_lib.spmm(plan, jnp.asarray(X)))
        np.testing.assert_allclose(got, S @ X, rtol=3e-4, atol=3e-4)

    def test_overflow_and_column_chunking(self):
        import scipy.sparse as sp
        import jax.numpy as jnp
        rng = np.random.default_rng(3)
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        S = sp.coo_matrix((vals, (rows, cols)), shape=(4096, 512)).tocsr()
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=4096, n_cols=512)
        assert plan.ov_rows is not None
        X = rng.standard_normal((512, 9)).astype(np.float32)
        got = np.asarray(spmv_lib.spmm(plan, jnp.asarray(X), col_chunk=4))
        np.testing.assert_allclose(got, S @ X, rtol=3e-4, atol=3e-4)

    def test_consistent_with_spmv_columns(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(5)
        rows = rng.integers(0, 1500, 10_000)
        cols = rng.integers(0, 1000, 10_000)
        vals = rng.standard_normal(10_000).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=1500, n_cols=1000)
        X = rng.standard_normal((1000, 3)).astype(np.float32)
        via_spmm = np.asarray(spmv_lib.spmm(plan, jnp.asarray(X)))
        via_spmv = np.stack(
            [np.asarray(spmv_lib.spmv(plan, jnp.asarray(X[:, j])))
             for j in range(3)], axis=1)
        np.testing.assert_allclose(via_spmm, via_spmv, rtol=2e-5,
                                   atol=1e-5)


class TestPlanPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        import jax.numpy as jnp
        rng = np.random.default_rng(13)
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=4096, n_cols=512)
        p = str(tmp_path / "plan.npz")
        spmv_lib.save_plan(p, plan)
        loaded = spmv_lib.load_plan(p)
        assert loaded.capacity == plan.capacity
        assert loaded.padding_ratio == plan.padding_ratio
        x = rng.standard_normal(512).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(spmv_lib.spmv(loaded, jnp.asarray(x))),
            np.asarray(spmv_lib.spmv(plan, jnp.asarray(x))))

    def test_save_after_expansion_works(self, tmp_path):
        # behavior change (2026-07-30): compact tables are kept for the
        # plan's life, so saving after expanded-path use round-trips
        import jax.numpy as jnp
        plan = spmv_lib.build_spmv_plan(np.array([1, 2]), np.array([0, 1]),
                                        n_rows=8, n_cols=4)
        x = jnp.ones(4, jnp.float32)
        y1 = np.asarray(spmv_lib.spmv(plan, x))   # expands
        spmv_lib.save_plan(str(tmp_path / "x.npz"), plan)
        plan2 = spmv_lib.load_plan(str(tmp_path / "x.npz"))
        np.testing.assert_allclose(np.asarray(spmv_lib.spmv(plan2, x)),
                                   y1, rtol=1e-6)


class TestPageRankOneHot:
    def test_matches_segment_impl_and_oracle(self):
        from matrel_tpu.workloads.pagerank import (
            pagerank_edges, pagerank_numpy_oracle)
        rng = np.random.default_rng(5)
        n, m = 2000, 16_000
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        r_hot = np.asarray(pagerank_edges(src, dst, n, rounds=15,
                                          impl="onehot"))
        r_seg = np.asarray(pagerank_edges(src, dst, n, rounds=15,
                                          impl="segment"))
        np.testing.assert_allclose(r_hot, r_seg, rtol=5e-4, atol=1e-9)
        a = np.zeros((n, n), np.float32)
        a[src, dst] = 1.0   # duplicates collapse; rebuild edges to match
        s2, d2 = np.nonzero(a)
        r_hot2 = np.asarray(pagerank_edges(s2, d2, n, rounds=15,
                                           impl="onehot"))
        want = pagerank_numpy_oracle(a, rounds=15).ravel()
        np.testing.assert_allclose(r_hot2, want, rtol=1e-3, atol=1e-10)

    def test_explicit_onehot_raises_on_refused_graph(self):
        # same pathological spread as the plan-refusal test: explicit
        # impl='onehot' must raise, not silently run the segment path
        from matrel_tpu.workloads.pagerank import pagerank_edges
        n = 512 * 20_000
        src = np.zeros(20_000, np.int64)
        dst = np.arange(20_000, dtype=np.int64) * 512
        with pytest.raises(ValueError, match="heavy-tailed"):
            pagerank_edges(src, dst, n, rounds=2, impl="onehot")

    def test_weighted_edges_match_oracle(self):
        from matrel_tpu.workloads.pagerank import (
            pagerank_edges, pagerank_numpy_oracle)
        rng = np.random.default_rng(21)
        n, m = 800, 6000
        a = np.zeros((n, n), np.float32)
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        w = rng.random(m).astype(np.float32) + 0.1
        np.add.at(a, (src, dst), w)
        s2, d2 = np.nonzero(a)
        w2 = a[s2, d2]
        want = pagerank_numpy_oracle(a, rounds=15).ravel()
        for impl in ("onehot", "segment"):
            got = np.asarray(pagerank_edges(s2, d2, n, rounds=15,
                                            impl=impl, weights=w2))
            np.testing.assert_allclose(got, want, rtol=2e-3, atol=1e-9,
                                       err_msg=impl)

    def test_dangling_nodes(self):
        # node 3 has no out-edges; its mass must redistribute
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 3, 3])
        n = 4
        from matrel_tpu.workloads.pagerank import (
            pagerank_edges, pagerank_numpy_oracle)
        a = np.zeros((n, n), np.float32)
        a[src, dst] = 1.0
        got = np.asarray(pagerank_edges(src, dst, n, rounds=25,
                                        impl="onehot"))
        want = pagerank_numpy_oracle(a, rounds=25).ravel()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-8)
        assert abs(got.sum() - 1.0) < 1e-3


class TestRoutedSpMV:
    """ops/spmv_routed.py — the matmul-only (gather-free) formulation,
    exercised in pallas interpret mode on the CPU mesh."""

    def test_matches_oracle_two_groups(self, rng):
        from matrel_tpu.ops import spmv_routed as rt
        n, m = 40_000, 20_000          # spans 3 groups of 16384
        rows, cols, vals = random_coo(rng, n, n, m)
        plan = rt.build_routed_plan(rows, cols, vals, n, n)
        assert plan is not None
        x = rng.standard_normal(n).astype(np.float32)
        y = np.asarray(rt.routed_spmv(plan, jnp.asarray(x),
                                      interpret=True))
        oracle = coo_oracle(rows, cols, vals, x, n)
        scale = np.abs(oracle).max()
        assert np.abs(y - oracle).max() / scale < 5e-4   # passes=2

    def test_three_passes_f32_faithful(self, rng):
        from matrel_tpu.ops import spmv_routed as rt
        n, m = 20_000, 5_000
        rows, cols, vals = random_coo(rng, n, n, m)
        plan = rt.build_routed_plan(rows, cols, vals, n, n)
        x = rng.standard_normal(n).astype(np.float32)
        y = np.asarray(rt.routed_spmv(plan, jnp.asarray(x), passes=3,
                                      interpret=True))
        oracle = coo_oracle(rows, cols, vals, x, n)
        assert np.abs(y - oracle).max() / np.abs(oracle).max() < 1e-6

    def test_rectangular_and_empty_groups(self, rng):
        from matrel_tpu.ops import spmv_routed as rt
        n_rows, n_cols, m = 5_000, 33_000, 8_000
        rows, cols, vals = random_coo(rng, n_rows, n_cols, m)
        plan = rt.build_routed_plan(rows, cols, vals, n_rows, n_cols)
        x = rng.standard_normal(n_cols).astype(np.float32)
        y = np.asarray(rt.routed_spmv(plan, jnp.asarray(x),
                                      interpret=True))
        oracle = coo_oracle(rows, cols, vals, x, n_rows)
        scale = max(np.abs(oracle).max(), 1e-9)
        assert np.abs(y - oracle).max() / scale < 5e-4

    def test_overflow_coo(self, rng):
        from matrel_tpu.ops import spmv_routed as rt
        # multiple cells with one hot cell: the 0-quantile capacity
        # binds at the coolest cell's count, overflowing the hot one
        # into the COO fallback
        n = 40_000               # 3x3 groups
        m = 3_000
        rows, cols, vals = random_coo(rng, n, n, m)
        rows[:1500] = 7          # hot cell: half the edges in cell (0,0)
        cols[:1500] = 11
        plan = rt.build_routed_plan(rows, cols, vals, n, n,
                                    capacity_quantile=0.0,
                                    max_padding=1000.0)
        assert plan.ov_rows is not None and plan.ov_rows.shape[0] > 0
        x = rng.standard_normal(n).astype(np.float32)
        y = np.asarray(rt.routed_spmv(plan, jnp.asarray(x),
                                      interpret=True))
        oracle = coo_oracle(rows, cols, vals, x, n)
        scale = np.abs(oracle).max()
        assert np.abs(y - oracle).max() / scale < 5e-4

    def test_build_gates(self, rng):
        from matrel_tpu.ops import spmv_routed as rt
        rows, cols, vals = random_coo(rng, 100, 100, 20)
        # tiny graph: one cell of cap>=128 pads >3x the edge count
        assert rt.build_routed_plan(rows, cols, vals, 100, 100) is None
        # explicit slot cap
        assert rt.build_routed_plan(rows, cols, vals, 100, 100,
                                    max_padding=100.0,
                                    max_slots=10) is None

    def test_bf16_split_reconstructs(self):
        from matrel_tpu.ops.spmv_routed import _bf16_split
        v = jnp.asarray(np.random.default_rng(0)
                        .standard_normal(4096).astype(np.float32))
        # truncation-based split: one-sided error, bound ~2^(-7·passes)
        for passes, tol in ((2, 1e-4), (3, 1e-6)):
            parts = _bf16_split(v, passes)
            # parts sit exactly on the bf16 grid (lossless astype)
            for p in parts[:-1]:
                assert np.array_equal(
                    np.asarray(p),
                    np.asarray(p.astype(jnp.bfloat16).astype(jnp.float32)))
            back = np.sum([np.asarray(p, np.float64) for p in parts],
                          axis=0)
            rel = np.abs(back - np.asarray(v, np.float64))
            rel = rel / np.maximum(np.abs(np.asarray(v)), 1e-30)
            assert rel.max() < tol

    def test_cap_ceiling_gates(self, rng):
        from matrel_tpu.ops import spmv_routed as rt
        # one edge-dense cell: capacity would exceed the VMEM-safe
        # ceiling, so the build must refuse (fallback contract), not
        # fail at kernel compile time
        n, m = 16_000, 300_000
        rows, cols, vals = random_coo(rng, n, n, m)
        assert rt.build_routed_plan(rows, cols, vals, n, n,
                                    max_padding=100.0) is None


class TestCompactSpMV:
    """ops/pallas_spmv.py — the compact-table Pallas scatter (interpret
    mode on CPU; on-chip numbers in BASELINE.md row 5)."""

    def test_matches_oracle(self, rng):
        from matrel_tpu.ops import pallas_spmv as pc
        n_r, n_c, m = 3000, 2500, 30_000
        rows, cols, vals = random_coo(rng, n_r, n_c, m)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        x = rng.standard_normal(n_c).astype(np.float32)
        y = np.asarray(pc.spmv_compact(plan, jnp.asarray(x),
                                       interpret=True))
        want = coo_oracle(rows, cols, vals, x, n_r)
        scale = np.abs(want).max()
        assert np.abs(y - want).max() / scale < 1e-6   # passes=3

    def test_two_pass_split(self, rng):
        from matrel_tpu.ops import pallas_spmv as pc
        n, m = 2000, 20_000
        rows, cols, vals = random_coo(rng, n, n, m)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals, n_rows=n,
                                        n_cols=n)
        x = rng.standard_normal(n).astype(np.float32)
        y = np.asarray(pc.spmv_compact(plan, jnp.asarray(x), passes=2,
                                       interpret=True))
        want = coo_oracle(rows, cols, vals, x, n)
        assert np.abs(y - want).max() / np.abs(want).max() < 1e-4

    def test_chunked_pipeline_matches_baseline(self, rng):
        # compact_apply_chunked (VERDICT r3 #6 overlap experiment) must
        # be bit-identical in result to compact_apply: same kernel, same
        # tables, block stripes are independent
        from matrel_tpu.ops import pallas_spmv as pc
        n_r, n_c, m = 3000, 3000, 25_000
        rows, cols, vals = random_coo(rng, n_r, n_c, m)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        static = (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO)
        tables = pc.compact_tables(plan)
        x = jnp.asarray(rng.standard_normal(n_c).astype(np.float32))
        base = np.asarray(pc.compact_apply(static, tables, plan.overflow,
                                           x, interpret=True))
        for k in (2, 3, 8):
            got = np.asarray(pc.compact_apply_chunked(
                static, tables, plan.overflow, x, chunks=k,
                interpret=True))
            np.testing.assert_array_equal(got, base)

    def test_overflow_coo_included(self, rng):
        from matrel_tpu.ops import pallas_spmv as pc
        # hub row forces quantile-capacity overflow
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=4096, n_cols=512)
        assert plan.ov_rows is not None
        x = rng.standard_normal(512).astype(np.float32)
        y = np.asarray(pc.spmv_compact(plan, jnp.asarray(x),
                                       interpret=True))
        want = coo_oracle(rows, cols, vals, x, 4096)
        scale = np.abs(want).max()
        assert np.abs(y - want).max() / scale < 1e-5

    def test_works_after_expanded_path(self, rng):
        # compact hosts are kept past expansion, so the two executors
        # can be mixed on one plan in any order
        from matrel_tpu.ops import pallas_spmv as pc
        rows, cols, vals = random_coo(rng, 1000, 1000, 5_000)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=1000, n_cols=1000)
        x = rng.standard_normal(1000).astype(np.float32)
        y1 = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))  # expands
        y2 = np.asarray(pc.spmv_compact(plan, jnp.asarray(x),
                                        interpret=True))
        np.testing.assert_allclose(y2, y1, rtol=1e-5, atol=1e-6)

    def test_pagerank_compact_matches_onehot(self, rng):
        from matrel_tpu.workloads import pagerank as pr
        n, m = 3000, 30_000
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        r1 = np.asarray(pr.run_pagerank_compact(
            pr.prepare_pagerank_onehot(src, dst, n), rounds=10,
            interpret=True))
        r2 = np.asarray(pr.run_pagerank_onehot(
            pr.prepare_pagerank_onehot(src, dst, n), rounds=10))
        assert np.abs(r1 - r2).max() / np.abs(r2).max() < 5e-4
        assert abs(r1.sum() - 1.0) < 1e-3

    def test_spmm_compact_matches_oracle(self, rng):
        from matrel_tpu.ops import pallas_spmv as pc
        for n_r, n_c, m, k in [(3000, 2500, 25_000, 16),
                               (1000, 1500, 8_000, 5)]:
            rows, cols, vals = random_coo(rng, n_r, n_c, m)
            plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                            n_rows=n_r, n_cols=n_c)
            X = rng.standard_normal((n_c, k)).astype(np.float32)
            Y = np.asarray(pc.spmm_compact(plan, jnp.asarray(X),
                                           interpret=True))
            want = np.zeros((n_r, k))
            np.add.at(want, rows, vals[:, None] * X[cols])
            scale = np.abs(want).max()
            assert np.abs(Y - want).max() / scale < 1e-4

    def test_spmm_compact_overflow_and_single_col(self, rng):
        from matrel_tpu.ops import pallas_spmv as pc
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=4096, n_cols=512)
        assert plan.ov_rows is not None
        X = rng.standard_normal((512, 3)).astype(np.float32)
        Y = np.asarray(pc.spmm_compact(plan, jnp.asarray(X),
                                       interpret=True))
        want = np.zeros((4096, 3))
        np.add.at(want, rows, vals[:, None] * X[cols])
        scale = np.abs(want).max()
        assert np.abs(Y - want).max() / scale < 1e-4
        # k == 1 takes the matvec kernel
        y1 = np.asarray(pc.spmm_compact(plan, jnp.asarray(X[:, :1]),
                                        interpret=True))
        assert np.abs(y1[:, 0] - want[:, 0]).max() / scale < 1e-5

    def test_sharded_compact_matches_oracle(self, mesh8, rng):
        # compact tables row-decomposed over the 8-device mesh; pallas
        # runs per device inside shard_map (interpret on CPU)
        from matrel_tpu.ops import pallas_spmv as pc
        n_r, n_c, m = 8192, 4000, 60_000
        rows, cols, vals = random_coo(rng, n_r, n_c, m)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        x = rng.standard_normal(n_c).astype(np.float32)
        y = np.asarray(pc.spmv_compact_sharded(plan, x, mesh8,
                                               interpret=True))
        want = coo_oracle(rows, cols, vals, x, n_r)
        scale = np.abs(want).max()
        assert np.abs(y - want).max() / scale < 1e-5
        # tables are actually sharded: block axis spread over 8 devices
        tabs = plan._compact_sharded[mesh8]
        assert len(tabs[0].sharding.device_set) == 8

    def test_sharded_compact_with_overflow(self, mesh8, rng):
        from matrel_tpu.ops import pallas_spmv as pc
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=4096, n_cols=512)
        assert plan.ov_rows is not None
        x = rng.standard_normal(512).astype(np.float32)
        y = np.asarray(pc.spmv_compact_sharded(plan, x, mesh8,
                                               interpret=True))
        want = coo_oracle(rows, cols, vals, x, 4096)
        assert np.abs(y - want).max() / np.abs(want).max() < 1e-5

    def test_pagerank_compact_sharded_matches_segment(self, mesh8, rng):
        from matrel_tpu.workloads import pagerank as pr
        n, m = 3000, 30_000
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        r1 = np.asarray(pr._pagerank_compact_sharded(
            src, dst, n, 8, 0.85, mesh8, interpret=True))
        r2 = np.asarray(pr.pagerank_edges(src, dst, n, rounds=8,
                                          impl="segment"))
        assert np.abs(r1 - r2).max() / np.abs(r2).max() < 5e-4
        assert abs(r1.sum() - 1.0) < 1e-3

    def test_compact_edge_cases(self, mesh8, rng):
        # empty plans, single partial block, fewer blocks than devices,
        # zero-column X — none may crash or densify
        from matrel_tpu.ops import pallas_spmv as pc
        empty = spmv_lib.build_spmv_plan(np.zeros(0), np.zeros(0),
                                         n_rows=100, n_cols=100)
        y = np.asarray(pc.spmv_compact(empty, jnp.ones(100, jnp.float32),
                                       interpret=True))
        assert (y == 0).all()
        rows, cols, vals = random_coo(rng, 100, 80, 500)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=100, n_cols=80)
        x = rng.standard_normal(80).astype(np.float32)
        want = coo_oracle(rows, cols, vals, x, 100)
        y = np.asarray(pc.spmv_compact(plan, jnp.asarray(x),
                                       interpret=True))
        assert np.abs(y - want).max() / np.abs(want).max() < 1e-6
        # one block over eight devices: sentinel-padded to the mesh
        y = np.asarray(pc.spmv_compact_sharded(plan, x, mesh8,
                                               interpret=True))
        assert np.abs(y - want).max() / np.abs(want).max() < 1e-6
        assert pc.spmm_compact(plan, jnp.zeros((80, 0), jnp.float32),
                               interpret=True).shape == (100, 0)

    def test_save_after_use_roundtrip(self, tmp_path, rng):
        # compact tables survive expanded-path use, so persistence works
        # at any point in a plan's life
        rows, cols, vals = random_coo(rng, 2000, 1500, 20_000)
        plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=2000, n_cols=1500)
        x = rng.standard_normal(1500).astype(np.float32)
        y1 = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))  # expands
        path = str(tmp_path / "plan.npz")
        spmv_lib.save_plan(path, plan)                        # after use
        plan2 = spmv_lib.load_plan(path)
        y2 = np.asarray(spmv_lib.spmv(plan2, jnp.asarray(x)))
        np.testing.assert_allclose(y2, y1, rtol=1e-6, atol=1e-7)
        from matrel_tpu.ops import pallas_spmv as pc
        y3 = np.asarray(pc.spmv_compact(plan2, jnp.asarray(x),
                                        interpret=True))
        np.testing.assert_allclose(y3, y1, rtol=1e-5, atol=1e-6)


class TestSpmvChoiceIdentity:
    """VERDICT r4 "what's weak" #3: the forced-variant mapping is
    validated by plan identity, so a recycled id can never misroute a
    different plan onto a measured choice."""

    def test_identity_checked(self, mesh8):
        from matrel_tpu import executor as ex
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.coo import COOMatrix
        import numpy as np
        rng = np.random.default_rng(0)
        A = COOMatrix.from_edges(rng.integers(0, 64, 200),
                                 rng.integers(0, 64, 200),
                                 shape=(64, 64))
        B = COOMatrix.from_edges(rng.integers(0, 64, 200),
                                 rng.integers(0, 64, 200),
                                 shape=(64, 64))
        pa, pb = A._get_plan(), B._get_plan()
        low = ex.Lowerer(mesh8, MatrelConfig())
        low.spmv_choice = {id(pa): (pa, "expanded"),
                           # forged stale entry: pb's id mapped to pa
                           id(pb): (pa, "expanded")}
        assert low._spmv_forced(pa) == "expanded"
        assert low._spmv_forced(pb) is None     # identity mismatch
