"""Multi-slice serving fleet (serve/fleet.py, serve/placement.py,
core/mesh slice views, MV114 — docs/FLEET.md).

Covers the acceptance battery: placement decisions flip with axis
weights, directory hit-anywhere vs slice-local miss, hot-entry
migration under the reshard peak budget, dead-slice failover with
deadlines/tenant attribution intact, and default-config zero-slice
bit-identity with the poisoned-init guard.
"""

import dataclasses
import json
import time
from concurrent.futures import Future

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.resilience.errors import (DeadlineExceeded,
                                          FleetSliceLost)
from matrel_tpu.resilience.retry import Deadline
from matrel_tpu.serve import placement as placement_lib
from matrel_tpu.serve.fleet import (DirectoryRecord, FleetController,
                                    FleetDirectory)
from matrel_tpu.session import MatrelSession


def _mk(sess, rng, n=64, names=("A", "B")):
    mats = {}
    for nm in names:
        arr = rng.standard_normal((n, n)).astype(np.float32)
        sess.register(nm, sess.from_numpy(arr))
        mats[nm] = arr
    return mats


def _fleet_session(mesh8, rng, n=64, **kw):
    cfg = MatrelConfig(fleet_slices=2,
                       result_cache_max_bytes=1 << 28, **kw)
    sess = MatrelSession(mesh=mesh8, config=cfg)
    mats = _mk(sess, rng, n=n)
    return sess, mats


def _q(sess):
    return sess.table("A").expr().multiply(sess.table("B").expr())


# ---------------------------------------------------------------------------
# core/mesh slice views
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _FakeDev:
    id: int
    slice_index: int


class _FakeMesh:
    def __init__(self, rows):
        self.devices = np.asarray(rows, dtype=object)


class TestSliceViews:
    def test_virtual_partition_splits_contiguously(self, mesh8):
        groups, source = mesh_lib.slice_device_groups(mesh8, 2)
        assert source == "virtual"
        assert [len(g) for g in groups] == [4, 4]
        assert len({d.id for g in groups for d in g}) == 8

    def test_virtual_meshes_near_square(self, mesh8):
        meshes, source = mesh_lib.slice_meshes(mesh8, 2)
        assert source == "virtual"
        for m in meshes:
            assert mesh_lib.mesh_grid_shape(m) == (2, 2)
            assert m.axis_names == mesh8.axis_names

    def test_shared_when_indivisible(self, mesh8):
        groups, source = mesh_lib.slice_device_groups(mesh8, 3)
        assert source == "shared"
        assert all(len(g) == 8 for g in groups)

    def test_detected_from_slice_index(self):
        rows = [[_FakeDev(0, 0), _FakeDev(1, 0)],
                [_FakeDev(2, 1), _FakeDev(3, 1)]]
        groups, source = mesh_lib.slice_device_groups(
            _FakeMesh(rows), 2)
        assert source == "detected"
        assert {d.id for d in groups[0]} == {0, 1}
        assert {d.id for d in groups[1]} == {2, 3}

    def test_slice_index_mismatch_falls_back_virtual(self):
        rows = [[_FakeDev(0, 0), _FakeDev(1, 0)],
                [_FakeDev(2, 1), _FakeDev(3, 1)]]
        groups, source = mesh_lib.slice_device_groups(
            _FakeMesh(rows), 4)
        assert source == "virtual"
        assert [len(g) for g in groups] == [1, 1, 1, 1]

    def test_bad_count_raises(self, mesh8):
        with pytest.raises(ValueError):
            mesh_lib.slice_device_groups(mesh8, 0)


# ---------------------------------------------------------------------------
# fleet keys
# ---------------------------------------------------------------------------


class TestFleetKey:
    def test_name_keyed_and_stable_across_replicas(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        e = _q(sess)
        k1 = placement_lib.fleet_key(e, fleet._names)
        assert k1 is not None and "@A" in k1 and "@B" in k1
        assert "id(" not in k1
        # the rebound (slice-replica) form of the SAME query keys
        # identically — that is the whole cross-slice point
        sl = fleet.slices[1]
        rebound = fleet._rebind(e, sl)
        k2 = placement_lib.fleet_key(rebound, sl.names_by_id)
        assert k1 == k2

    def test_unnamed_leaf_is_ineligible(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        adhoc = sess.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32))
        e = sess.table("A").expr().multiply(adhoc.expr())
        assert placement_lib.fleet_key(e, fleet._names) is None

    def test_prefix_isolates_slas(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        e = _q(sess)
        k_def = placement_lib.fleet_key(e, fleet._names, "")
        k_fast = placement_lib.fleet_key(e, fleet._names,
                                         "prec:fast|")
        assert k_def != k_fast and k_fast.startswith("prec:fast|")


# ---------------------------------------------------------------------------
# placement decisions
# ---------------------------------------------------------------------------


def _big_expr(mesh8, n=1024):
    from matrel_tpu.core.blockmatrix import BlockMatrix
    A = BlockMatrix.random((n, n), mesh=mesh8, seed=0)
    B = BlockMatrix.random((n, n), mesh=mesh8, seed=1)
    return A.expr().multiply(B.expr())


class TestPlacement:
    def test_effective_dcn_weight(self):
        assert placement_lib.effective_dcn_weight((1.0, 1.0)) \
            == mesh_lib.DCN_AXIS_WEIGHT
        assert placement_lib.effective_dcn_weight((1.0, 1.5)) == 1.5
        assert placement_lib.effective_dcn_weight((8.0, 1.0)) == 8.0
        # a calibrated fast-DCN fabric (weights <= 1.0) is still a
        # calibration — the config contract says anything != (1.0,
        # 1.0) overrides detection, so the cut bills at the measured
        # weight, not the 8x default
        assert placement_lib.effective_dcn_weight((1.0, 0.9)) == 1.0
        assert placement_lib.effective_dcn_weight((0.5, 0.5)) == 0.5

    def test_decision_flips_with_axis_weights(self, mesh8):
        """The acceptance flip: a compute-heavy query SPANS when the
        calibrated weights say the cut is cheap, and stays
        slice-local when the DCN weight makes crossing expensive."""
        cfg = MatrelConfig(fleet_slices=2)
        e = _big_expr(mesh8)
        kw = dict(total_devices=8, slice_devices=4,
                  slice_loads={0: 0, 1: 0}, backend="cpu",
                  eligible=True)
        cheap = placement_lib.decide(e, cfg, (1.0, 1.5), **kw)
        dear = placement_lib.decide(e, cfg, (1.0, 8.0), **kw)
        assert cheap.mode == "span" and cheap.reason == "cost"
        assert dear.mode == "slice" and dear.reason == "cost"

    def test_uniform_weights_price_virtual_cut_as_dcn(self, mesh8):
        # no calibration, no detected boundary: the fleet partition
        # still IS a boundary — small queries stay slice-local
        cfg = MatrelConfig(fleet_slices=2)
        sess = MatrelSession(mesh=mesh8, config=cfg)
        e = sess.from_numpy(np.eye(64, dtype=np.float32)).expr() \
            .multiply(sess.from_numpy(
                np.eye(64, dtype=np.float32)).expr())
        dec = placement_lib.decide(
            e, cfg, (1.0, 1.0), total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, eligible=True)
        assert dec.mode == "slice"

    def test_pinned_when_ineligible(self, mesh8):
        cfg = MatrelConfig(fleet_slices=2)
        e = _big_expr(mesh8, n=64)
        dec = placement_lib.decide(
            e, cfg, (1.0, 8.0), total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, eligible=False)
        assert dec.mode == "span" and dec.reason == "pinned"

    def test_least_loaded_slice_wins(self, mesh8):
        cfg = MatrelConfig(fleet_slices=2)
        e = _big_expr(mesh8, n=64)
        dec = placement_lib.decide(
            e, cfg, (1.0, 1.0), total_devices=8, slice_devices=4,
            slice_loads={0: 5, 1: 0}, eligible=True)
        assert dec.slice_id == 1

    def test_round_robin_tie_break(self, mesh8):
        cfg = MatrelConfig(fleet_slices=2)
        e = _big_expr(mesh8, n=64)
        kw = dict(total_devices=8, slice_devices=4,
                  slice_loads={0: 0, 1: 0}, eligible=True)
        ids = [placement_lib.decide(e, cfg, (1.0, 1.0), rr_tick=t,
                                    **kw).slice_id
               for t in range(4)]
        assert ids == [0, 1, 0, 1]

    def test_stamp_carries_the_billed_dcn_weight(self, mesh8):
        cfg = MatrelConfig(fleet_slices=2)
        e = _big_expr(mesh8, n=64)
        dec = placement_lib.decide(
            e, cfg, (1.0, 1.5), total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, eligible=True)
        st = dec.stamp()
        assert st["dcn_weight"] == 1.5
        assert st["weights"] == [1.0, 1.5]
        # KEY-STABLE fields only: the stamp feeds the plan/result
        # cache structural keys, so drift-sensitive fields (the
        # estimates, coeff_source) must never ride it — they would
        # shatter every span query's cache keys on a drift-table
        # update (the brownout-rung plan-key-shatter class)
        assert set(st) == {"mode", "weights", "dcn_axis",
                           "dcn_weight"}

    def test_span_margin_biases_toward_slices(self, mesh8):
        e = _big_expr(mesh8)
        kw = dict(total_devices=8, slice_devices=4,
                  slice_loads={0: 0, 1: 0}, eligible=True)
        neutral = placement_lib.decide(
            e, MatrelConfig(fleet_slices=2), (1.0, 1.5), **kw)
        strict = placement_lib.decide(
            e, MatrelConfig(fleet_slices=2, fleet_span_margin=0.1),
            (1.0, 1.5), **kw)
        assert neutral.mode == "span" and strict.mode == "slice"


# ---------------------------------------------------------------------------
# drift-calibrated coefficients (the feedback-loop satellite)
# ---------------------------------------------------------------------------


def _seed_drift_table(path, cls="<=1024", backend="cpu",
                      strategy="rmm", gflop=50.0, mib=2.0, count=4):
    table = {"schema": 1, "entries": {
        f"{strategy}|{cls}|{backend}": {
            "strategy": strategy, "class": cls, "backend": backend,
            "count": count, "ms_median": 1.0,
            "ms_per_gflop": gflop, "ms_per_est_mib": mib}}}
    with open(path, "w") as f:
        json.dump(table, f)


class TestPlacementCalibration:
    @pytest.fixture(autouse=True)
    def _fresh_cache(self):
        placement_lib.reset_coefficient_cache()
        yield
        placement_lib.reset_coefficient_cache()

    def test_promotes_rows_per_class_backend_tier(self, tmp_path):
        p = str(tmp_path / "drift.json")
        table = {"schema": 1, "entries": {
            "rmm|<=1024|cpu": {
                "strategy": "rmm", "class": "<=1024",
                "backend": "cpu", "count": 3, "ms_median": 1.0,
                "ms_per_gflop": 10.0, "ms_per_est_mib": 1.0},
            "cpmm|<=1024|cpu": {
                "strategy": "cpmm", "class": "<=1024",
                "backend": "cpu", "count": 1, "ms_median": 1.0,
                "ms_per_gflop": 50.0, "ms_per_est_mib": 5.0},
            "rmm@bf16x1|<=1024|cpu": {
                "strategy": "rmm@bf16x1", "class": "<=1024",
                "backend": "cpu", "count": 2, "ms_median": 1.0,
                "ms_per_gflop": 4.0, "ms_per_est_mib": 0.5},
        }}
        with open(p, "w") as f:
            json.dump(table, f)
        coeffs = placement_lib.placement_coefficients(p)
        # untier rows blend count-weighted: (10*3 + 50*1) / 4 = 20
        row = coeffs[("<=1024", "cpu", "")]
        assert row["ms_per_gflop"] == pytest.approx(20.0)
        assert row["ms_per_mib"] == pytest.approx(2.0)
        assert row["source"] == "measured"
        # tiered rows promote under their own tier key
        tier = coeffs[("<=1024", "cpu", "bf16x1")]
        assert tier["ms_per_gflop"] == pytest.approx(4.0)

    def test_decide_consults_measured_ahead_of_closed_forms(
            self, mesh8, tmp_path):
        p = str(tmp_path / "drift.json")
        _seed_drift_table(p, cls="<=1024")
        cfg = MatrelConfig(fleet_slices=2, drift_table_path=p)
        e = _big_expr(mesh8)         # max dim 1024 -> class <=1024
        dec = placement_lib.decide(
            e, cfg, (1.0, 1.5), total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, backend="cpu", eligible=True)
        assert dec.coeff_source == "measured"
        # the measured ms/GFLOP (50x the analytic 1.0) scales the
        # compute term: the estimates must reflect it
        assert dec.est_slice_ms > 10.0

    def test_cold_class_falls_back_to_analytic(self, mesh8,
                                               tmp_path):
        p = str(tmp_path / "drift.json")
        _seed_drift_table(p, cls="<=64")      # wrong shape class
        cfg = MatrelConfig(fleet_slices=2, drift_table_path=p)
        e = _big_expr(mesh8)
        dec = placement_lib.decide(
            e, cfg, (1.0, 1.5), total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, backend="cpu", eligible=True)
        assert dec.coeff_source == "analytic"

    def test_calibration_gate_off(self, mesh8, tmp_path):
        p = str(tmp_path / "drift.json")
        _seed_drift_table(p, cls="<=1024")
        cfg = MatrelConfig(fleet_slices=2, drift_table_path=p,
                           fleet_placement_calibration=False)
        e = _big_expr(mesh8)
        dec = placement_lib.decide(
            e, cfg, (1.0, 1.5), total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, backend="cpu", eligible=True)
        assert dec.coeff_source == "analytic"

    def test_absent_table_reads_empty(self, tmp_path):
        assert placement_lib.placement_coefficients(
            str(tmp_path / "nope.json")) == {}


# ---------------------------------------------------------------------------
# the fleet serve plane, end to end
# ---------------------------------------------------------------------------


class TestFleetServe:
    def test_submit_routes_to_slices_and_answers_correctly(
            self, mesh8, rng):
        sess, mats = _fleet_session(mesh8, rng)
        futs = [sess.submit(_q(sess).multiply_scalar(float(i + 1)))
                for i in range(4)]
        outs = [f.result(timeout=60) for f in futs]
        oracle = mats["A"] @ mats["B"]
        for i, o in enumerate(outs):
            np.testing.assert_allclose(np.asarray(o.to_numpy()),
                                       oracle * (i + 1), rtol=2e-4,
                                       atol=2e-4)
        info = sess.fleet_info()
        assert info["placed"]["slice"] == 4
        assert {sl["id"] for sl in info["slices"]} == {0, 1}
        sess.serve_close()

    def test_directory_hit_anywhere_answers_without_recompute(
            self, mesh8, rng):
        sess, mats = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        out1 = sess.submit(q).result(timeout=60)
        sess.serve_drain()
        assert fleet.directory.info()["entries"] == 1
        before = {sl.slice_id: sl.submitted for sl in fleet.slices}
        # the second submission's placement (round-robin) prefers the
        # NON-owning slice — the directory answers from the owner's
        # cache anyway, and no slice pipeline sees the query at all
        out2 = sess.submit(q).result(timeout=60)
        np.testing.assert_allclose(np.asarray(out2.to_numpy()),
                                   np.asarray(out1.to_numpy()))
        after = {sl.slice_id: sl.submitted for sl in fleet.slices}
        assert after == before          # zero recompute, zero routing
        d = fleet.directory.info()
        assert d["hits"] == 1 and d["remote_hits"] == 1
        sess.serve_close()

    def test_slice_local_miss_recomputes_and_records_ownership(
            self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q1 = _q(sess)
        q2 = _q(sess).multiply_scalar(2.0)
        sess.submit(q1).result(timeout=60)
        sess.serve_drain()
        # a DIFFERENT query misses the directory and recomputes on
        # its placed slice, recording new ownership
        sess.submit(q2).result(timeout=60)
        sess.serve_drain()
        d = fleet.directory.info()
        assert d["entries"] == 2 and d["misses"] >= 2
        sess.serve_close()

    def test_migration_replicates_hot_entry_under_budget(
            self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng, fleet_replicate_hits=1)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        owner = fleet.directory.lookup(
            placement_lib.fleet_key(q, fleet._names)).owner
        # remote hit crosses the replication threshold -> the entry
        # replicates into the demanding slice (off-thread, so the hit
        # fast path never pays the copy — quiesce before asserting)
        sess.submit(q).result(timeout=60)
        fleet.quiesce_replication(timeout=30)
        assert fleet.migrations == 1
        rec = fleet.directory.lookup(
            placement_lib.fleet_key(q, fleet._names))
        other = 1 - owner
        assert other in rec.replicas
        repl_sess = fleet.slice_by_id(other).session
        assert repl_sess._result_cache.info()["entries"] >= 1
        # replica-side provenance: the entry carries the fleet stamp
        ent = repl_sess._result_cache.lookup(rec.replicas[other])
        assert ent is not None and ent.fleet["owner"] == owner
        # the NEXT remote ask is served by the replica, locally
        sess.submit(q).result(timeout=60)
        sess.submit(q).result(timeout=60)
        fleet.quiesce_replication(timeout=30)
        assert fleet.migrations == 1      # no re-migration
        sess.serve_close()

    def test_migration_priced_out_by_peak_budget(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng, fleet_replicate_hits=1,
                                 reshard_peak_budget_bytes=64)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        fkey = placement_lib.fleet_key(q, fleet._names)
        rec = fleet.directory.lookup(fkey)
        owner_sess = fleet.slice_by_id(rec.owner).session
        ent = owner_sess._result_cache.lookup(rec.owner_key)
        # a sharded 1 GiB entry cannot gather under a 64-byte peak
        # budget: the migration prices out and nothing is inserted
        big = dataclasses.replace(rec, nbytes=1 << 30, layout="2d")
        target = fleet.slice_by_id(1 - rec.owner)
        fleet._replicate_entry(q, fkey, big, ent, "default", target)
        assert fleet.migrations == 0
        assert fleet.migrations_priced_out == 1
        # review-round regression: the verdict memoizes on the live
        # record — later remote hits must not re-run the reshard
        # pricing (and emit one priced-out event each) forever on
        # exactly the hottest keys
        live_rec = fleet.directory.lookup(fkey)
        assert target.slice_id in live_rec.priced_out
        live_rec.hits[target.slice_id] = 99
        fleet._maybe_replicate(q, fkey, live_rec, ent, "default",
                               target)
        fleet.quiesce_replication(timeout=30)
        assert fleet.migrations_priced_out == 1
        sess.serve_close()

    def test_replication_disabled_at_zero(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng, fleet_replicate_hits=0)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        for _ in range(4):
            sess.submit(q).result(timeout=60)
            sess.serve_drain()
        assert fleet.migrations == 0
        sess.serve_close()


class TestFailover:
    def test_kill_slice_requeues_with_futures_intact(self, mesh8,
                                                     rng):
        sess, mats = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        sl = fleet.slices[0]
        pipe = sl.session._ensure_serve()
        oracle = mats["A"] @ mats["B"]
        # queue entries directly (worker not started — exactly the
        # wedged-slice shape), then kill: every future must resolve
        # through a SURVIVOR
        futs = []
        for i in range(3):
            fut = Future()
            e = fleet._rebind(
                _q(sess).multiply_scalar(float(i + 1)), sl)
            pipe._q.put((e, fut, time.perf_counter(), "default",
                         None, "tenantA", None), "tenantA")
            futs.append(fut)
        requeued = fleet.kill_slice(0)
        assert requeued == 3
        assert not fleet.slices[0].alive
        sess.serve_drain()
        for i, f in enumerate(futs):
            out = f.result(timeout=60)
            np.testing.assert_allclose(np.asarray(out.to_numpy()),
                                       oracle * (i + 1), rtol=2e-4,
                                       atol=2e-4)
        assert fleet.failovers == 1 and fleet.requeued == 3
        sess.serve_close()

    def test_failover_preserves_tenant_attribution(self, mesh8, rng):
        sess, _ = _fleet_session(
            mesh8, rng, serve_tenant_weights="tenantA:2,tenantB:1")
        fleet = sess._ensure_fleet()
        sl = fleet.slices[0]
        pipe = sl.session._ensure_serve()
        fut = Future()
        e = fleet._rebind(_q(sess), sl)
        pipe._q.put((e, fut, time.perf_counter(), "default", None,
                     "tenantA", None), "tenantA")
        # hold the survivor's worker so the requeued entry is
        # observable in its queue (the worker would otherwise pop it
        # before the assert). NOT by flipping _closed — readmission
        # now refuses typed on a closed pipeline (the stranding fix);
        # stub the worker-ensure instead.
        target = fleet.slices[1].session._ensure_serve()
        target._ensure_worker = lambda: None
        fleet.kill_slice(0)
        # the survivor's queue sees the entry under the SAME tenant
        assert target._q.tenant_depths().get("tenantA", 0) == 1
        del target._ensure_worker
        target._ensure_worker()
        sess.serve_drain()
        assert fut.result(timeout=60) is not None
        sess.serve_close()

    def test_expired_entry_fails_typed_on_failover(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        sl = fleet.slices[0]
        pipe = sl.session._ensure_serve()
        fut = Future()
        dl = Deadline(0.01)
        time.sleep(0.005)
        e = fleet._rebind(_q(sess), sl)
        pipe._q.put((e, fut, time.perf_counter(), "default", dl, "",
                     None), "")
        time.sleep(0.02)            # expire while queued
        fleet.kill_slice(0)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=10)
        sess.serve_close()

    def test_failover_disabled_fails_typed(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng, fleet_failover=False)
        fleet = sess._ensure_fleet()
        sl = fleet.slices[0]
        pipe = sl.session._ensure_serve()
        fut = Future()
        e = fleet._rebind(_q(sess), sl)
        pipe._q.put((e, fut, time.perf_counter(), "default", None,
                     "", None), "")
        fleet.kill_slice(0)
        with pytest.raises(FleetSliceLost):
            fut.result(timeout=10)
        sess.serve_close()

    def test_no_survivors_is_typed(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        fleet.kill_slice(0)
        fleet.kill_slice(1)
        fut = sess.submit(_q(sess))
        with pytest.raises(FleetSliceLost):
            fut.result(timeout=10)

    def test_wedged_worker_detected_on_submit(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        sl = fleet.slices[0]
        # start, then stop, the worker — and erase the stop flag so
        # the dead thread looks like a crash, not a shutdown
        sess.submit(_q(sess)).result(timeout=60)
        sess.serve_drain()
        pipe = sl.session._serve
        if pipe is None:        # placement sent it to slice 1
            sl = fleet.slices[1]
            pipe = sl.session._serve
        pipe._stop.set()
        pipe._worker.join(timeout=10)
        assert not pipe._worker.is_alive()
        pipe._stop.clear()
        fut = Future()
        e = fleet._rebind(_q(sess).multiply_scalar(3.0), sl)
        pipe._q.put((e, fut, time.perf_counter(), "default", None,
                     "", None), "")
        fleet.check_health()
        assert not sl.alive and fleet.failovers == 1
        sess.serve_drain()
        assert fut.result(timeout=60) is not None
        sess.serve_close()

    def test_dead_slice_directory_records_drop(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        fkey = placement_lib.fleet_key(q, fleet._names)
        rec = fleet.directory.lookup(fkey)
        fleet.kill_slice(rec.owner)
        assert fleet.directory.lookup(fkey) is None
        # the query still answers — recomputed on the survivor
        out = sess.submit(q).result(timeout=60)
        assert out is not None
        sess.serve_close()

    def test_readmit_into_closed_survivor_fails_typed(self, mesh8,
                                                      rng):
        # review-round regression: re-admission must go through the
        # pipeline's atomic closed-check + enqueue + worker-ensure
        # seam — a survivor whose pipeline a concurrent close() just
        # flipped refuses TYPED instead of stranding the stolen
        # future in a closed, workerless queue
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        dead = fleet.slice_by_id(0)
        dead.alive = False
        fleet.slice_by_id(1).session._ensure_serve().close(timeout=30)
        fut = Future()
        rebound = fleet._rebind(q, dead)
        entry = (rebound, fut, time.perf_counter(), "default", None,
                 "", None)
        assert fleet._readmit([(entry, "")], dead) == 0
        with pytest.raises(FleetSliceLost):
            fut.result(timeout=5)
        sess.serve_close()

    def test_replica_eviction_falls_back_to_owner(self, mesh8, rng):
        # review-round regression: an evicted REPLICA only loses its
        # own claim — the owner's still-valid copy keeps answering
        # and the directory record survives (no evict/recompute/
        # re-replicate churn on exactly the hottest entries)
        sess, _ = _fleet_session(mesh8, rng, fleet_replicate_hits=1)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        fkey = placement_lib.fleet_key(q, fleet._names)
        sess.submit(q).result(timeout=60)    # remote hit -> replicate
        fleet.quiesce_replication(timeout=30)
        rec = fleet.directory.lookup(fkey)
        (repl_id, repl_key), = list(rec.replicas.items())
        fleet.slice_by_id(repl_id).session._result_cache.drop(repl_key)
        # the probe below is itself a remote hit: with replication
        # still armed it would spawn a re-replication that races the
        # claim-dropped assertion (re-claiming is CORRECT sustained-
        # demand behavior — just not what this test measures)
        fleet.config = dataclasses.replace(fleet.config,
                                           fleet_replicate_hits=0)
        before = fleet.directory.info()["invalidated"]
        hit = fleet._directory_answer(q, fkey, "default", repl_id)
        assert hit is not None          # served by the OWNER's copy
        rec2 = fleet.directory.lookup(fkey)
        assert rec2 is not None         # record kept
        assert repl_id not in rec2.replicas   # claim dropped
        assert fleet.directory.info()["invalidated"] == before
        sess.serve_close()


class TestCatalogWriteThrough:
    def test_idempotent_reregister_is_a_fleet_noop(self, mesh8, rng):
        # review-round regression: re-registering the SAME object is
        # a no-op on the single-controller path (the `old is not
        # matrix` guard) and must be one on the fleet path too — the
        # unconditional hook wiped the directory and every slice
        # cache and re-replicated the table on every no-op call
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        d0 = fleet.directory.info()
        assert d0["entries"] >= 1
        gen0 = fleet.directory.reg_gen
        sess.register("A", sess.catalog["A"])     # same object
        assert fleet.directory.reg_gen == gen0
        d1 = fleet.directory.info()
        assert d1["entries"] == d0["entries"]
        assert d1["invalidated"] == d0["invalidated"]
        sess.serve_close()

    def test_unreplicable_table_pins_up_front(self, mesh8, rng):
        # review-round regression: a table NO slice can replicate
        # (sparse/COO on real sub-meshes, failed host stage) must not
        # stay in the fleet's name map — name-mapped, every query
        # over it was fleet-ELIGIBLE, routed to a slice, and bounced
        # through the KeyError fallback per submit forever (recorded
        # as the transient "fallback" reason, never in the pinned
        # census). Unmapped, fleet_key is None and placement pins to
        # the full mesh before any routing.
        from matrel_tpu.core.coo import COOMatrix
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        coo = COOMatrix.from_edges(
            np.array([0, 1, 2]), np.array([1, 2, 0]),
            np.ones(3, dtype=np.float32), shape=(64, 64))
        sess.register("S", coo)
        assert id(coo) not in fleet._names
        e = coo.expr().multiply(sess.table("B").expr())
        assert placement_lib.fleet_key(e, fleet._names) is None
        pinned0 = fleet.pinned
        out = sess.submit(e).result(timeout=60)
        assert fleet.pinned == pinned0 + 1
        assert np.asarray(out.to_numpy()).shape == (64, 64)
        sess.serve_close()

    def test_register_replicates_and_invalidates(self, mesh8, rng):
        sess, mats = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        out1 = sess.submit(q).result(timeout=60)
        sess.serve_drain()
        assert fleet.directory.info()["entries"] == 1
        # rebind A: slice replicas refresh, directory records naming
        # A drop, and the SAME query recomputes against the new value
        newA = rng.standard_normal((64, 64)).astype(np.float32)
        sess.register("A", sess.from_numpy(newA))
        assert fleet.directory.info()["entries"] == 0
        for sl in fleet.slices:
            assert "A" in sl.session.catalog
        q2 = _q(sess)
        out2 = sess.submit(q2).result(timeout=60)
        np.testing.assert_allclose(np.asarray(out2.to_numpy()),
                                   newA @ mats["B"], rtol=2e-4,
                                   atol=2e-4)
        assert not np.allclose(np.asarray(out1.to_numpy()),
                               np.asarray(out2.to_numpy()))
        sess.serve_close()

    def test_rebind_invalidates_directory_before_replication(
            self, mesh8, rng):
        # review-round regression: on_register must drop the stale
        # directory records BEFORE _replicate maps the new matrix id
        # to the name — from that mapping onward a concurrent submit
        # built from the new binding resolves the old record's fleet
        # key, and a still-live record would answer it with the OLD
        # value (lookups don't take the controller lock)
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        assert fleet.directory.info()["entries"] == 1
        seen = {}
        orig = fleet._replicate

        def spy(name, matrix):
            seen["entries"] = fleet.directory.info()["entries"]
            seen["gen"] = fleet.directory.reg_gen
            return orig(name, matrix)

        gen0 = fleet.directory.reg_gen
        fleet._replicate = spy
        try:
            newA = rng.standard_normal((64, 64)).astype(np.float32)
            sess.register("A", sess.from_numpy(newA))
        finally:
            fleet._replicate = orig
        assert seen == {"entries": 0, "gen": gen0 + 1}
        sess.serve_close()


class TestDirectoryHygiene:
    def test_no_ownership_record_when_slice_insert_declined(
            self, mesh8, rng):
        # review-round regression: when the slice did NOT cache under
        # the routing-time key (budget-declined insert here; brownout
        # downshift re-keying in production) the fleet must not
        # record ownership — a dead record would churn
        # (lookup-miss -> drop -> recompute -> re-insert) on every
        # repeat
        cfg = MatrelConfig(fleet_slices=2,
                           result_cache_max_bytes=1024)  # < one result
        sess = MatrelSession(mesh=mesh8, config=cfg)
        _mk(sess, np.random.default_rng(0))
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        fleet = sess._ensure_fleet()
        assert fleet.directory.info()["inserts"] == 0
        sess.serve_close()

    def test_close_tears_down_killed_slices(self, mesh8, rng):
        # review-round regression: serve_close must close EVERY
        # slice — a killed slice's session (stopped worker, stolen
        # queue) was skipped, leaving its pipeline/inflight state
        # held for the life of the parent
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        fleet.kill_slice(0)
        sess.serve_close(timeout=30)
        for sl in fleet.slices:
            pipe = sl.session._serve
            if pipe is not None:
                assert pipe.closed
                assert pipe._stop.is_set()
                if pipe._worker is not None:
                    # close() signals the worker and returns; the
                    # daemon exits on its next poll tick — join
                    # bounded before asserting it is gone
                    pipe._worker.join(timeout=10)
                    assert not pipe._worker.is_alive()

    def test_close_sweeps_past_a_wedged_slice(self, mesh8, rng):
        # review-round regression: one wedged live slice's
        # DrainTimeout aborted the teardown loop — later slices'
        # workers and the parent pipeline stayed open and the metrics
        # exporter was never stopped (the EADDRINUSE class the
        # exporter-lifecycle fix exists for). Every slice must be
        # closed, then the first live failure propagates.
        from matrel_tpu.resilience.errors import DrainTimeout
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        sess.submit(_q(sess)).result(timeout=60)
        sess.serve_drain()
        boom = DrainTimeout(0.0, 1)

        def wedge(timeout=None):
            raise boom

        fleet.slices[0].session.serve_close = wedge
        stopped = []
        if sess._exporter is None:
            class _Exp:
                def stop(self):
                    stopped.append(True)
            sess._exporter = _Exp()
        with pytest.raises(DrainTimeout):
            sess.serve_close(timeout=30)
        assert stopped == [True]          # exporter stopped anyway
        other = fleet.slices[1].session._serve
        assert other is None or other.closed   # sweep continued
        parent = sess._serve
        assert parent is None or parent.closed
        sess._exporter = None

    def test_drain_covers_killed_slices(self, mesh8, rng):
        # review-round regression: kill_slice steals only QUEUED
        # entries — a batch the worker already pulled keeps executing,
        # and serve_drain's "every in-flight batch has materialised"
        # contract must wait for it. drain skipped dead slices, so
        # those futures could still be unresolved when it returned.
        sess, _ = _fleet_session(mesh8, rng)
        fleet = sess._ensure_fleet()
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        fleet.kill_slice(0)
        drained = []
        for sl in fleet.slices:
            orig = sl.session.serve_drain
            sl.session.serve_drain = (
                lambda timeout=None, _i=sl.slice_id, _o=orig:
                (drained.append(_i), _o(timeout=timeout))[1])
        sess.serve_drain(timeout=30)
        assert set(drained) == {sl.slice_id for sl in fleet.slices}
        # live slices drain first: a wedged corpse must not eat the
        # shared budget before the live fleet has drained
        dead = {sl.slice_id for sl in fleet.slices if not sl.alive}
        assert all(i in dead for i in drained[-len(dead):])
        sess.serve_close()


class TestDirectoryBounds:
    def test_lru_eviction_at_max(self):
        d = FleetDirectory(2)
        for i in range(3):
            d.record_insert(f"k{i}", DirectoryRecord(
                owner=0, owner_key=f"lk{i}", nbytes=8,
                layout="rep", dtype="float32",
                dep_names=frozenset({"A"})))
        assert d.info()["entries"] == 2
        assert d.info()["evicted"] == 1
        assert d.lookup("k0") is None        # oldest evicted

    def test_invalidate_by_name(self):
        d = FleetDirectory(8)
        d.record_insert("k1", DirectoryRecord(
            owner=0, owner_key="a", nbytes=8, layout="rep",
            dtype="float32", dep_names=frozenset({"A"})))
        d.record_insert("k2", DirectoryRecord(
            owner=1, owner_key="b", nbytes=8, layout="rep",
            dtype="float32", dep_names=frozenset({"B"})))
        assert d.invalidate_name("A") == 1
        assert d.lookup("k1") is None and d.lookup("k2") is not None

    def test_claim_replica_refuses_across_generations(self):
        # review-round regression: a migration staged against an
        # old-binding record must not attach its (old-value) replica
        # to a record re-created for the NEW binding after a rebind
        # — the claim carries the staged generation and refuses on a
        # bump (the record_insert expected_gen idiom)
        d = FleetDirectory(8)
        rec = DirectoryRecord(
            owner=0, owner_key="k0", nbytes=8, layout="rep",
            dtype="float32", dep_names=frozenset({"A"}))
        d.record_insert("K", rec)
        staged_gen = d.reg_gen
        d.invalidate_name("A")               # rebind in flight
        d.record_insert("K", DirectoryRecord(
            owner=0, owner_key="k0b", nbytes=8, layout="rep",
            dtype="float32", dep_names=frozenset({"A"})))
        assert not d.claim_replica("K", 1, "k1",
                                   expected_gen=staged_gen)
        assert 1 not in d.lookup("K").replicas
        assert d.claim_replica("K", 1, "k1", expected_gen=d.reg_gen)

    def test_drop_replica_keeps_owner_record(self):
        d = FleetDirectory(8)
        rec = DirectoryRecord(
            owner=0, owner_key="k0", nbytes=8, layout="rep",
            dtype="float32", dep_names=frozenset({"A"}))
        rec.replicas[1] = "k1"
        d.record_insert("K", rec)
        d.drop_replica("K", 1)
        kept = d.lookup("K")
        assert kept is not None and 1 not in kept.replicas
        assert d.info()["invalidated"] == 0


# ---------------------------------------------------------------------------
# MV114 fixtures
# ---------------------------------------------------------------------------


class TestMV114:
    def _leaf_pair(self, mesh8):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        A = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        B = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
        return A.expr().multiply(B.expr())

    def _run(self, root, mesh8, cfg=None):
        from matrel_tpu.analysis.placement_pass import (
            check_placement_stamps)
        return list(check_placement_stamps(
            root, mesh8, cfg or MatrelConfig()))

    def test_registered_in_pipeline(self):
        from matrel_tpu import analysis
        assert any(name == "placement" for name, _ in analysis.PASSES)

    def test_stale_weights_flagged(self, mesh8):
        e = self._leaf_pair(mesh8).with_attrs(placement={
            "mode": "span", "weights": [1.0, 2.0], "dcn_axis": 1,
            "dcn_weight": 2.0})
        got = self._run(e, mesh8)
        assert any(d.code == "MV114" and "topology" in d.message
                   for d in got)

    def test_unpriced_cut_flagged(self, mesh8):
        # the stamp's own weights derive an effective DCN weight of
        # 1.5 — billing the cut at 1.0 means the dominant collective
        # was NOT priced on the DCN axis weight
        cfg = MatrelConfig(axis_cost_weights=(1.0, 1.5))
        e = self._leaf_pair(mesh8).with_attrs(placement={
            "mode": "span", "weights": [1.0, 1.5], "dcn_axis": 1,
            "dcn_weight": 1.0})
        got = self._run(e, mesh8, cfg)
        assert any(d.code == "MV114" and "DCN axis weight"
                   in d.message for d in got)

    def test_fresh_span_stamp_quiet(self, mesh8):
        cfg = MatrelConfig(fleet_slices=2,
                           axis_cost_weights=(1.0, 1.5))
        e = self._leaf_pair(mesh8)
        dec = placement_lib.decide(
            e, cfg, mesh_lib.axis_weights(mesh8, cfg),
            total_devices=8, slice_devices=4,
            slice_loads={0: 0, 1: 0}, eligible=True)
        stamped = e.with_attrs(placement=dec.stamp())
        assert self._run(stamped, mesh8, cfg) == []

    def test_slice_mode_stamp_not_checked(self, mesh8):
        e = self._leaf_pair(mesh8).with_attrs(placement={
            "mode": "slice", "weights": [9.0, 9.0]})
        assert self._run(e, mesh8) == []

    def test_replica_dtype_divergence_flagged(self, mesh8):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as expr_mod
        M = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        leaf = expr_mod.leaf(M).with_attrs(result_cache={
            "key_hash": "x", "layout": "rep", "dtype": "float32",
            "deps": [],
            "fleet": {"owner": 0, "layout": "rep",
                      "dtype": "float64"}})
        got = self._run(leaf.t(), mesh8)
        assert any(d.code == "MV114" and "dtype" in d.message
                   for d in got)

    def test_replica_coherent_stamp_quiet(self, mesh8):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as expr_mod
        M = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
        leaf = expr_mod.leaf(M).with_attrs(result_cache={
            "key_hash": "x", "layout": "rep", "dtype": "float32",
            "deps": [],
            "fleet": {"owner": 0, "layout": "rep",
                      "dtype": "float32"}})
        assert self._run(leaf.t(), mesh8) == []

    def test_end_to_end_span_plan_verifies_clean(self, mesh8, rng):
        # a REAL fleet span submission compiles under
        # verify_plans="error" with MV114 in the pipeline: the stamp
        # the placer writes must satisfy its own verifier
        cfg = MatrelConfig(fleet_slices=2, verify_plans="error",
                           result_cache_max_bytes=1 << 28)
        sess = MatrelSession(mesh=mesh8, config=cfg)
        _mk(sess, rng, n=64)
        adhoc = sess.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32))
        # an ad-hoc leaf pins the query to the span path
        e = sess.table("A").expr().multiply(adhoc.expr())
        out = sess.submit(e).result(timeout=60)
        assert out is not None
        assert sess.fleet_info()["placed"]["span"] >= 1
        sess.serve_close()


# ---------------------------------------------------------------------------
# default-config bit-identity
# ---------------------------------------------------------------------------


class TestFleetOffBitIdentity:
    def test_zero_fleet_objects_poisoned_init(self, mesh8, rng,
                                              monkeypatch):
        def poisoned(self, *a, **k):
            raise AssertionError(
                "fleet object constructed with fleet_slices=0")
        monkeypatch.setattr(FleetController, "__init__", poisoned)
        monkeypatch.setattr(FleetDirectory, "__init__", poisoned)
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        mats = _mk(sess, rng, n=32)
        out = sess.run(_q(sess))
        np.testing.assert_allclose(np.asarray(out.to_numpy()),
                                   mats["A"] @ mats["B"], rtol=2e-4,
                                   atol=2e-4)
        fut = sess.submit(_q(sess).multiply_scalar(2.0))
        assert fut.result(timeout=60) is not None
        sess.serve_drain()
        assert sess._fleet is None
        assert sess.fleet_info() is None
        sess.serve_close()

    def test_fleet_lazy_until_first_submit(self, mesh8, rng):
        sess, _ = _fleet_session(mesh8, rng)
        assert sess._fleet is None        # construction is lazy
        sess.run(_q(sess))                # run() never builds it
        assert sess._fleet is None
        sess.submit(_q(sess)).result(timeout=60)
        assert sess._fleet is not None
        sess.serve_close()

    def test_config_validation(self):
        with pytest.raises(ValueError):
            MatrelConfig(fleet_slices=-1)
        with pytest.raises(ValueError):
            MatrelConfig(fleet_span_margin=0)
        with pytest.raises(ValueError):
            MatrelConfig(fleet_directory_max=0)
        with pytest.raises(ValueError):
            MatrelConfig(fleet_replicate_hits=-1)


# ---------------------------------------------------------------------------
# obs surfaces
# ---------------------------------------------------------------------------


class TestFleetObs:
    def test_placement_events_and_summary(self, mesh8, rng,
                                          tmp_path):
        log = str(tmp_path / "events.jsonl")
        sess, _ = _fleet_session(mesh8, rng, obs_level="on",
                                 obs_event_log=log)
        q = _q(sess)
        sess.submit(q).result(timeout=60)
        sess.serve_drain()
        sess.submit(q).result(timeout=60)     # directory hit
        sess.serve_drain()
        from matrel_tpu.obs.events import read_events
        from matrel_tpu.obs.history import render_summary, summarize
        events = read_events(log)
        placements = [e for e in events
                      if e.get("kind") == "placement"]
        assert len(placements) == 2
        assert placements[0]["routed"] == "slice"
        assert placements[1]["routed"] in ("directory",
                                           "directory_remote")
        assert placements[0]["coeff_source"] in ("analytic",
                                                 "measured")
        # slice sessions tag their own query events
        tagged = [e for e in events if e.get("kind") == "query"
                  and e.get("slice") is not None]
        assert tagged
        s = summarize(events)
        assert s["fleet"]["placements"] == 2
        assert s["fleet"]["slices"]
        text = render_summary(events)
        assert "fleet:" in text
        sess.serve_close()

    def test_fleet_event_on_kill(self, mesh8, rng, tmp_path):
        log = str(tmp_path / "events.jsonl")
        sess, _ = _fleet_session(mesh8, rng, obs_level="on",
                                 obs_event_log=log)
        sess.submit(_q(sess)).result(timeout=60)
        sess.serve_drain()
        sess._fleet.kill_slice(0)
        from matrel_tpu.obs.events import read_events
        evs = [e for e in read_events(log) if e.get("kind") == "fleet"]
        assert any(e.get("event") == "slice_kill" for e in evs)
        sess.serve_close()

    def test_export_snapshot_and_top_show_fleet(self, mesh8, rng):
        from matrel_tpu.obs import export as export_lib
        from matrel_tpu.obs import top as top_lib
        sess, _ = _fleet_session(mesh8, rng)
        sess.submit(_q(sess)).result(timeout=60)
        sess.serve_drain()
        snap = export_lib.snapshot(sess)
        assert snap["fleet"] is not None
        assert len(snap["fleet"]["slices"]) == 2
        text = top_lib.render(snap)
        assert "fleet: 2 slice(s)" in text
        assert "slice 0:" in text and "slice 1:" in text
        sess.serve_close()

    def test_no_fleet_snapshot_is_none(self, mesh8):
        from matrel_tpu.obs import export as export_lib
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        assert export_lib.snapshot(sess)["fleet"] is None


# ---------------------------------------------------------------------------
# registration-plane locking (the LK102 fix: tools/lockcheck.py)
# ---------------------------------------------------------------------------


class TestRegistrationPlaneLocking:
    def test_replicate_runs_outside_controller_lock(self, mesh8, rng):
        """on_register's re-replication (device->host staging per
        table) must NOT run under the controller lock — that hold
        span wedged kill_slice/failover behind a host transfer — but
        MUST still be serialized by the registration lock (two
        rebinds of one name never interleave)."""
        import threading

        sess, mats = _fleet_session(mesh8, rng, n=32)
        try:
            # the fleet builds lazily on first submit
            sess.submit(_q(sess)).result(timeout=60)
            fc = sess._fleet
            orig = fc._replicate
            seen = {}

            def spy(name, matrix):
                # probe from ANOTHER thread: a nonblocking acquire
                # succeeds iff no thread holds the lock
                def probe():
                    free = fc._lock.acquire(blocking=False)
                    if free:
                        fc._lock.release()
                    seen["controller_free"] = free
                    reg_free = fc._reg_lock.acquire(blocking=False)
                    if reg_free:
                        fc._reg_lock.release()
                    seen["reg_held"] = not reg_free

                t = threading.Thread(target=probe, daemon=True)
                t.start()
                t.join(timeout=30)
                return orig(name, matrix)

            fc._replicate = spy
            sess.register("A", sess.from_numpy(mats["A"]))  # rebind
            assert seen == {"controller_free": True,
                            "reg_held": True}
        finally:
            sess.serve_close(timeout=30)

    def test_rebind_storm_with_concurrent_kill(self, mesh8, rng):
        """The schedule the old hold span wedged: kill_slice (takes
        the controller lock) must complete while a rebind's
        replication is in flight, and answers stay right."""
        import threading

        sess, mats = _fleet_session(mesh8, rng, n=32)
        try:
            sess.submit(_q(sess)).result(timeout=60)  # builds the fleet
            oracle = mats["A"] @ mats["B"]
            done = threading.Event()

            def rebinder():
                for _ in range(4):
                    sess.register("A", sess.from_numpy(mats["A"]))
                done.set()

            t = threading.Thread(target=rebinder, daemon=True)
            t.start()
            sess._fleet.kill_slice(0)
            out = sess.submit(_q(sess)).result(timeout=60)
            t.join(timeout=60)
            assert done.is_set(), "rebind storm wedged"
            np.testing.assert_allclose(np.asarray(out.to_numpy()),
                                       oracle, rtol=3e-3, atol=3e-3)
        finally:
            sess.serve_close(timeout=30)
