"""Static plan verifier (matrel_tpu/analysis/): one seeded-violation
fixture per pass proving the exact diagnostic code fires, the clean-
plan contract at verify_plans="error", the HBM-hardened admissible()
routing (VERDICT r5 Weak #3 / Next #6), and the session/executor/obs
wiring."""

import types

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from matrel_tpu import analysis
from matrel_tpu.analysis import padding_pass
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.ir import expr as E, rules
from matrel_tpu.parallel import planner


def _annotated(e, mesh, cfg=None):
    cfg = cfg or MatrelConfig()
    grid = (mesh.shape[mesh.axis_names[0]], mesh.shape[mesh.axis_names[1]])
    return planner.annotate_strategies(
        rules.optimize(e, cfg, grid=grid, mesh=mesh), mesh, cfg)


def _codes(diags):
    return sorted({d.code for d in diags})


def _dense(rng, n, m, mesh, spec=None):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh,
        spec=spec)


#: Planner-level stand-in for a matrix too large to materialise: the
#: planner/verifier only read shape/nnz/spec/dtype off a leaf.
def _phantom_leaf(shape, spec, dtype="float32"):
    m = types.SimpleNamespace(shape=shape, nnz=None, spec=spec,
                              dtype=np.dtype(dtype))
    return E.leaf(m)


class TestCleanPlans:
    """A healthy representative plan set produces ZERO diagnostics at
    verify_plans='error' — the all-clear half of the acceptance
    criteria (the corpus-scale version lives in tools/plan_verify.py,
    run by `make lint`)."""

    def test_dense_pipeline_clean(self, rng, mesh8):
        X = _dense(rng, 256, 64, mesh8)
        y = _dense(rng, 256, 1, mesh8)
        e = X.expr().t().multiply(X.expr()).solve(
            X.expr().t().multiply(y.expr()))
        diags = analysis.verify_plan(_annotated(e, mesh8), mesh8)
        assert diags == []

    def test_spgemm_and_masking_ops_clean(self, rng, mesh8):
        S1 = BlockSparseMatrix.random((256, 256), block_density=0.05,
                                      block_size=64, mesh=mesh8, seed=0)
        S2 = BlockSparseMatrix.random((256, 256), block_density=0.05,
                                      block_size=64, mesh=mesh8, seed=1)
        e = S1.multiply(S2).add_scalar(1.0).power(-1.0).row_sum()
        diags = analysis.verify_plan(_annotated(e, mesh8), mesh8)
        assert diags == []

    def test_compile_under_error_mode(self, rng, mesh8):
        from matrel_tpu import executor
        cfg = MatrelConfig(verify_plans="error")
        A = _dense(rng, 64, 32, mesh8)
        B = _dense(rng, 32, 48, mesh8)
        plan = executor.compile_expr(A.expr().multiply(B.expr()), mesh8,
                                     cfg)
        assert plan.meta["diagnostics"] == []
        got = plan.run().to_numpy()
        np.testing.assert_allclose(got, A.to_numpy() @ B.to_numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_off_mode_pays_nothing(self, rng, mesh8):
        from matrel_tpu import executor
        A = _dense(rng, 64, 32, mesh8)
        plan = executor.compile_expr(A.expr().t().multiply(A.expr()),
                                     mesh8, MatrelConfig())
        assert "diagnostics" not in plan.meta


class TestStrategyPass:
    def test_mv101_inadmissible_stamp(self, rng, mesh8):
        # summa needs a square grid; mesh8 is (2, 4) — a summa stamp
        # can only come from a plan annotated for a different mesh
        A = _dense(rng, 64, 64, mesh8)
        B = _dense(rng, 64, 64, mesh8)
        bad = E.matmul(A.expr(), B.expr()).with_attrs(
            strategy="summa", strategy_source="model")
        diags = analysis.verify_plan(bad, mesh8)
        assert _codes(diags) == ["MV101"]
        assert diags[0].severity == "error"

    def test_mv101_unknown_strategy(self, rng, mesh8):
        A = _dense(rng, 64, 64, mesh8)
        bad = E.matmul(A.expr(), A.expr()).with_attrs(strategy="zmm")
        diags = analysis.verify_plan(bad, mesh8)
        assert _codes(diags) == ["MV101"]
        assert "vocabulary" in diags[0].message


class TestSpgemmPass:
    def _pair(self, mesh):
        S1 = BlockSparseMatrix.random((256, 256), block_density=0.02,
                                      block_size=64, mesh=mesh, seed=2)
        S2 = BlockSparseMatrix.random((256, 256), block_density=0.02,
                                      block_size=64, mesh=mesh, seed=3)
        return S1, S2

    def test_mv104_stale_stamp_config_drift(self, mesh8):
        # annotated with SpGEMM on, verified under a config that
        # disables the dispatch (threshold 0 = the documented kill
        # switch): the stamp now promises a path that will not run
        S1, S2 = self._pair(mesh8)
        opt = _annotated(S1.multiply(S2), mesh8, MatrelConfig())
        assert opt.attrs["strategy"] == "spgemm"
        diags = analysis.verify_plan(
            opt, mesh8, MatrelConfig(spgemm_density_threshold=0.0))
        assert "MV104" in _codes(diags)

    def test_mv104_unstamped_dispatch(self, mesh8):
        S1, S2 = self._pair(mesh8)
        bad = S1.multiply(S2).with_attrs(strategy="rmm",
                                         strategy_source="model")
        diags = analysis.verify_plan(bad, mesh8)
        assert "MV104" in _codes(diags)
        assert "misreport" in [d for d in diags
                               if d.code == "MV104"][0].message


class TestLayoutPass:
    def test_mv102_unearned_credit(self, rng, mesh8, monkeypatch):
        # simulate the ADVICE r5 bug class: infer_layout hands a
        # sparse_leaf matmul the stamped strategy's layout although the
        # SpMM lowering ignores the stamp — the verifier must catch the
        # two modules disagreeing
        S = BlockSparseMatrix.random((256, 256), block_density=0.05,
                                     block_size=64, mesh=mesh8, seed=4)
        D = _dense(rng, 256, 128, mesh8)
        opt = _annotated(S.multiply(D), mesh8)
        real = planner.infer_layout

        def unearned(node, mesh, memo=None, config=None):
            if node.kind == "matmul":
                return "row"          # the pre-fix free-consume claim
            return real(node, mesh, memo, config)

        monkeypatch.setattr(planner, "infer_layout", unearned)
        diags = analysis.verify_plan(opt, mesh8)
        mv102 = [d for d in diags if d.code == "MV102"]
        assert mv102 and mv102[0].severity == "warning"

    def test_mixed_coo_sparse_takes_coo_path(self, rng, mesh8):
        """Review r6: a mixed coo×sparse matmul above the SpGEMM
        threshold runs the COO SpMV path (Lowerer._matmul checks
        coo_leaf before sparse_leaf) — infer_layout, matmul_decisions
        and both verifier mirrors must all read that branch order, so
        the compact path's replicated-output credit is claimed exactly
        where it is pinned and MV102 stays quiet."""
        from matrel_tpu.analysis import layout_pass
        from matrel_tpu.core.coo import COOMatrix
        # dense-ish operands: estimated output block density ~1.0 keeps
        # the SpGEMM dispatch out of the way
        n_edges = 40_000
        A = COOMatrix.from_edges(rng.integers(0, 256, n_edges),
                                 rng.integers(0, 256, n_edges),
                                 shape=(256, 256))
        S = BlockSparseMatrix.random((256, 64), block_density=1.0,
                                     block_size=64, mesh=mesh8, seed=6)
        cfg = MatrelConfig(pallas_interpret=True)  # compact path pinned
        opt = _annotated(A.multiply(S.expr()), mesh8, cfg)
        decs = planner.matmul_decisions(opt, mesh8, cfg)
        assert [d["dispatch"] for d in decs] == ["coo_spmv"]
        assert planner.infer_layout(opt, mesh8, {}, cfg) == "rep"
        assert layout_pass.pinned_matmul_layout(opt, mesh8, cfg) == "rep"
        assert [d for d in analysis.verify_plan(opt, mesh8, cfg)
                if d.code == "MV102"] == []

    def test_clean_claims_match_pins(self, rng, mesh8):
        # the real infer_layout and the executor mirror agree across a
        # mixed plan (dense strategies + SpMM + SpGEMM dispatches)
        S = BlockSparseMatrix.random((256, 256), block_density=0.05,
                                     block_size=64, mesh=mesh8, seed=5)
        D = _dense(rng, 256, 256, mesh8)
        e = S.multiply(D).multiply(_dense(rng, 256, 64, mesh8).expr())
        assert [d for d in analysis.verify_plan(_annotated(e, mesh8),
                                                mesh8)
                if d.code == "MV102"] == []


class TestPaddingPass:
    def test_mv103_missing_remask_seeded(self, rng, mesh8):
        # simulate an executor that forgot _mask_to_logical on
        # scalar-add: the contract entry flips to BREAKS and the
        # checker must flag the node
        A = _dense(rng, 60, 60, mesh8)   # 60 pads to 64: real padding
        e = _annotated(A.expr().add_scalar(1.0), mesh8)
        broken = dict(padding_pass.PADDING_CONTRACT,
                      scalar=lambda n: padding_pass.BREAKS)
        diags = list(padding_pass.check_padding_flow(
            e, mesh8, MatrelConfig(), contract=broken))
        assert _codes(diags) == ["MV103"]
        assert diags[0].severity == "error"
        assert "scalar" in diags[0].message

    def test_mv103_unknown_kind_warns(self, rng, mesh8):
        A = _dense(rng, 32, 32, mesh8)
        e = _annotated(A.expr().row_sum(), mesh8)
        partial = {k: v for k, v in
                   padding_pass.PADDING_CONTRACT.items() if k != "agg"}
        diags = list(padding_pass.check_padding_flow(
            e, mesh8, MatrelConfig(), contract=partial))
        assert _codes(diags) == ["MV103"]
        assert diags[0].severity == "warning"
        assert "no entry" in diags[0].message

    def test_real_contract_clean_on_breakers(self, rng, mesh8):
        # every invariant-breaking op the executor re-masks verifies
        # clean under the REAL contract
        A = _dense(rng, 60, 60, mesh8)
        B = _dense(rng, 1, 60, mesh8)
        e = _annotated(A.expr().add(B.expr()).add_scalar(2.0)
                       .power(-1.0), mesh8)
        assert list(padding_pass.check_padding_flow(
            e, mesh8, MatrelConfig())) == []


class TestHBMFeasibility:
    """The acceptance criterion: a plan that over-replicates under RMM
    on a 16 GB HBM budget is rejected by admissible(), flagged by the
    verifier, and routed to cpmm."""

    # A replicated (4096 x 2M) f32, B canonically 2D (2M x 4096): with
    # A's gather free, RMM wins the byte model — but needs a/gx + b/gy
    # = 16 + 8 = ~24 GiB per device on the (2, 4) grid, while CPMM's
    # outer-product working set is ~12 GiB.
    N, K, M = 4096, 1 << 21, 4096

    def _matmul(self, mesh):
        axes = tuple(mesh.axis_names)
        A = _phantom_leaf((self.N, self.K), P(None, None))
        B = _phantom_leaf((self.K, self.M), P(axes[0], axes[1]))
        return E.matmul(A, B)

    def test_hbm_bytes_closed_forms(self):
        gib = 2.0 ** 30
        rmm = planner.strategy_hbm_bytes("rmm", self.N, self.K, self.M,
                                         2, 4)
        cpmm = planner.strategy_hbm_bytes("cpmm", self.N, self.K,
                                          self.M, 2, 4)
        # a = b = 32 GiB, c = 64 MiB: rmm = a/2 + b/4 + c/8,
        # cpmm = a/8 + b/4 + c/2
        assert rmm == pytest.approx(24.008 * gib, rel=0.001)
        assert cpmm == pytest.approx(12.031 * gib, rel=0.001)
        assert planner.strategy_hbm_bytes("xla", self.N, self.K,
                                          self.M, 2, 4) == 0.0

    def test_admissible_gate(self):
        kw = dict(hbm_budget_bytes=16 << 30)
        assert not planner.admissible("rmm", self.N, self.K, self.M,
                                      2, 4, **kw)
        assert planner.admissible("cpmm", self.N, self.K, self.M,
                                  2, 4, **kw)
        assert planner.admissible("xla", self.N, self.K, self.M,
                                  2, 4, **kw)          # never gated
        # budget 0 = the pre-round-6 divisibility-only behaviour
        assert planner.admissible("rmm", self.N, self.K, self.M, 2, 4,
                                  hbm_budget_bytes=0)

    def test_planner_routes_rmm_to_cpmm(self, mesh8):
        node = self._matmul(mesh8)
        free = MatrelConfig(hbm_budget_bytes=0)
        s0, src0 = planner.choose_strategy_ex(node, mesh8, free)
        assert (s0, src0) == ("rmm", "model")   # the over-replicator wins
        capped = MatrelConfig()                 # default: 16 GiB budget
        s1, src1 = planner.choose_strategy_ex(node, mesh8, capped)
        assert (s1, src1) == ("cpmm", "model")  # routed, not refused

    def test_mv105_flags_overbudget_stamp(self, mesh8):
        bad = self._matmul(mesh8).with_attrs(strategy="rmm",
                                             strategy_source="model")
        diags = analysis.verify_plan(bad, mesh8, MatrelConfig())
        mv105 = [d for d in diags if d.code == "MV105"]
        assert mv105 and mv105[0].severity == "error"
        assert "GiB per device" in mv105[0].message
        # budget 0 disables the pass
        assert [d for d in analysis.verify_plan(
            bad, mesh8, MatrelConfig(hbm_budget_bytes=0))
            if d.code == "MV105"] == []


class TestResultCachePass:
    """MV107: a plan consuming a materialized-result-cache entry must
    agree with what the cache recorded at substitution (serve/)."""

    def test_mv107_stale_layout_and_dtype_stamp(self, rng, mesh8):
        B = _dense(rng, 32, 32, mesh8)
        cached = _dense(rng, 32, 32, mesh8)
        # a stamp surviving past invalidation: claims a replicated f64
        # result while the leaf really lies canonically-sharded f32
        stale = E.leaf(cached).with_attrs(result_cache={
            "key_hash": "deadbeef", "layout": "rep",
            "dtype": "float64", "deps": []})
        diags = analysis.verify_plan(
            _annotated(stale.multiply(B.expr()), mesh8), mesh8)
        mv107 = [d for d in diags if d.code == "MV107"]
        assert len(mv107) == 2          # one layout, one dtype finding
        assert all(d.severity == "warning" for d in mv107)
        assert any("layout" in d.message for d in mv107)
        assert any("dtype" in d.message for d in mv107)

    def test_mv107_quiet_on_live_substitution(self, rng, mesh8):
        # the session's own substitution stamps truthfully — clean
        from matrel_tpu.session import MatrelSession
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig(
            result_cache_max_bytes=64 << 20))
        X = _dense(rng, 64, 16, mesh8)
        gram = X.expr().t().multiply(X.expr())
        sess.run(gram)
        B = _dense(rng, 16, 16, mesh8)
        substituted = sess._rc_substitute(gram.multiply(B.expr()))
        assert any(c.attrs.get("result_cache")
                   for c in substituted.children)
        diags = analysis.verify_plan(_annotated(substituted, mesh8),
                                     mesh8)
        assert [d for d in diags if d.code == "MV107"] == []

    def test_mv107_unstamped_leaves_ignored(self, rng, mesh8):
        e = _dense(rng, 32, 32, mesh8).expr().t()
        diags = analysis.verify_plan(_annotated(e, mesh8), mesh8)
        assert [d for d in diags if d.code == "MV107"] == []


class TestWiring:
    # strategy_override bypasses BOTH the cost model and the
    # admissibility gate (choose_strategy_ex returns it first), so a
    # bad override is the realistic way an inadmissible stamp reaches
    # the compile path — and the verifier is the layer that catches it.

    def test_compile_error_mode_raises_pre_trace(self, rng, mesh8):
        from matrel_tpu import executor
        A = _dense(rng, 64, 64, mesh8)
        e = E.matmul(A.expr(), A.expr())   # summa needs a square grid
        with pytest.raises(analysis.VerificationError) as ei:
            executor.compile_expr(e, mesh8, MatrelConfig(
                strategy_override="summa", verify_plans="error"))
        assert "MV101" in str(ei.value)

    def test_compile_warn_mode_records_and_runs(self, rng, mesh8):
        from matrel_tpu import executor
        A = _dense(rng, 64, 64, mesh8)
        plan = executor.compile_expr(
            E.matmul(A.expr(), A.expr()), mesh8,
            MatrelConfig(strategy_override="summa", verify_plans="warn"))
        assert [d["code"] for d in plan.meta["diagnostics"]] == ["MV101"]
        # summa's impl falls back to cpmm off square grids: still runs
        got = plan.run().to_numpy()
        a = A.to_numpy()
        np.testing.assert_allclose(got, a @ a, rtol=1e-4, atol=1e-4)

    def test_session_verify_and_explain(self, rng, mesh8):
        from matrel_tpu import session as sess_mod
        sess = sess_mod.MatrelSession(mesh8, MatrelConfig())
        A = _dense(rng, 64, 32, mesh8)
        e = A.expr().t().multiply(A.expr())
        assert sess.verify(e) == []
        txt = sess.explain(e)
        assert "== Verifier ==" in txt
        assert "clean (0 diagnostics)" in txt

    def test_obs_verify_event(self, rng, mesh8, tmp_path):
        import json
        from matrel_tpu import session as sess_mod
        log = str(tmp_path / "ev.jsonl")
        sess = sess_mod.MatrelSession(mesh8, MatrelConfig(
            obs_level="on", obs_event_log=log, verify_plans="warn"))
        A = _dense(rng, 64, 32, mesh8)
        sess.compute(A.expr().t().multiply(A.expr()))
        kinds = [json.loads(l)["kind"] for l in open(log)]
        assert kinds.count("verify") == 1
        rec = [json.loads(l) for l in open(log)
               if json.loads(l)["kind"] == "verify"][0]
        assert rec["mode"] == "warn"
        assert rec["count"] == 0 and rec["codes"] == []

    def test_config_validates_verify_plans(self):
        with pytest.raises(ValueError, match="verify_plans"):
            MatrelConfig(verify_plans="eror")
        assert MatrelConfig(verify_plans="WARN").verify_plans == "warn"


def test_plan_verify_selfcheck_green():
    """`make lint`'s second half, enforced from inside tier-1: every
    plan in the snapshot corpus (tools/plan_snapshot.py) verifies with
    zero diagnostics."""
    from tools import plan_verify
    assert plan_verify.main() == 0


class TestTopologyPass:
    """MV106 (round 7): the slow-axis collective smell on a weighted
    mesh — fires on hand-stamped plans, never on the planner's own
    output, and costs nothing on a homogeneous mesh."""

    W_CFG = None  # built per-test (fixtures need mesh8)

    def _wcfg(self):
        return MatrelConfig(axis_cost_weights=(1.0, 8.0))

    def _stamped_slow(self, mesh):
        # replicated B makes the broadcast alternative FREE, so the
        # hand-stamped rmm (whose A all-gather rides y, the slow axis)
        # is a gy-fold weighted-bytes regression; the node sits under
        # an outer matmul so no root-reshard context muddies the gap
        import dataclasses
        base = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                      mesh=mesh)
        brep = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                      mesh=mesh, spec=P(None, None))

        def fab(src, n, m):
            return E.leaf(dataclasses.replace(src, shape=(n, m)))

        inner = E.matmul(fab(base, 8192, 2048),
                         fab(brep, 2048, 4096)).with_attrs(
            strategy="rmm", strategy_source="override")
        return E.matmul(inner, fab(base, 4096, 64))

    def test_mv106_fires_on_hand_stamped_slow_axis_plan(self, mesh8):
        cfg = self._wcfg()
        ann = planner.annotate_strategies(self._stamped_slow(mesh8),
                                          mesh8, cfg)
        diags = analysis.verify_plan(ann, mesh8, cfg)
        mv106 = [d for d in diags if d.code == "MV106"]
        assert mv106 and all(d.severity == "warning" for d in mv106)
        assert "bmm_right" in mv106[0].message

    def test_mv106_quiet_on_planner_output(self, rng, mesh8):
        # the planner minimises the same weighted bill — a fresh
        # annotation can never be >=2x off its own argmin
        cfg = self._wcfg()
        X = _dense(rng, 256, 64, mesh8)
        e = X.expr().t().multiply(X.expr()).multiply(
            _dense(rng, 64, 32, mesh8).expr())
        diags = analysis.verify_plan(_annotated(e, mesh8, cfg), mesh8,
                                     cfg)
        assert "MV106" not in _codes(diags)

    def test_mv106_free_on_uniform_mesh(self, mesh8):
        # the same hand-stamped plan on a homogeneous mesh: no slow
        # axis exists, the pass yields nothing (rmm vs free-broadcast
        # bmm is a plain cost miss, not a topology smell)
        cfg = MatrelConfig()
        ann = planner.annotate_strategies(self._stamped_slow(mesh8),
                                          mesh8, cfg)
        diags = analysis.verify_plan(ann, mesh8, cfg)
        assert "MV106" not in _codes(diags)

    def test_mv106_respects_root_exposure(self, mesh8):
        # the pass mirrors the planner's root context: a bmm
        # alternative AT the plan root pays the canonical-output
        # re-lay the executor really performs there
        # (_root_reshard_cost x exposure). The SAME stamped multiply
        # is flagged as an interior node (exposure 0 — bmm_right is
        # 4x cheaper) but NOT at the root, where the big output's
        # y-axis re-lay collapses the alternative's margin below 2x —
        # context-free pricing would false-positive every root plan.
        import dataclasses
        cfg = self._wcfg()
        base = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                      mesh=mesh8)
        brep = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                      mesh=mesh8, spec=P(None, None))
        stamped = E.matmul(
            E.leaf(dataclasses.replace(base, shape=(8192, 2048))),
            E.leaf(dataclasses.replace(brep, shape=(2048, 4096)))
        ).with_attrs(strategy="rmm", strategy_source="override")
        at_root = analysis.verify_plan(stamped, mesh8, cfg)
        assert "MV106" not in _codes(at_root)
        interior = E.matmul(stamped, E.leaf(dataclasses.replace(
            base, shape=(4096, 64))))
        diags = analysis.verify_plan(
            planner.annotate_strategies(interior, mesh8, cfg), mesh8,
            cfg)
        assert "MV106" in _codes(diags)

    def test_mv106_exempts_measured_stamps(self, mesh8, tmp_path):
        # an autotune wall-clock winner legitimately overrules the
        # byte model (that is the point of measuring) — flagging it
        # would warn on every fresh annotation of an autotune-enabled
        # weighted session (review r7)
        import json
        from matrel_tpu.parallel import autotune
        cfg = self._wcfg().replace(
            autotune=True,
            autotune_table_path=str(tmp_path / "t.json"))
        key = autotune._table_key(2048, 2, 4, "float32", (1.0, 8.0))
        json.dump({key: {"best": "rmm", "times": {"rmm": 1e-6,
                                                  "cpmm": 1.0}}},
                  open(str(tmp_path / "t.json"), "w"))
        autotune._CACHE.clear()
        rng = np.random.default_rng(3)
        a = _dense(rng, 2048, 2048, mesh8)
        b = _dense(rng, 2048, 2048, mesh8)
        inner = E.matmul(a.expr(), b.expr())
        outer = E.matmul(inner, _dense(rng, 2048, 64, mesh8).expr())
        ann = planner.annotate_strategies(outer, mesh8, cfg)
        autotune._CACHE.clear()
        assert ann.children[0].attrs["strategy_source"] == "measured"
        diags = analysis.verify_plan(ann, mesh8, cfg)
        assert "MV106" not in _codes(diags)
