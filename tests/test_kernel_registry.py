"""Sparse kernel registry (ops/kernel_registry.py) — structure
classification, registry admissibility, planner stamping, the autotune
loop's key format / legacy pruning / measured-winner override, and the
default-config bit-identity contract (round 11)."""

import json
import os

import numpy as np
import pytest

from matrel_tpu import analysis
from matrel_tpu import executor as executor_lib
from matrel_tpu.config import MatrelConfig
from matrel_tpu.ir import stats
from matrel_tpu.ops import kernel_registry as kr
from matrel_tpu.ops import spgemm as spgemm_lib
from matrel_tpu.parallel import autotune, planner


def _band_pair(mesh, n=2048, bs=16, seeds=(1, 2)):
    return (kr.synthesize_structure("row_band", n, bs, mesh,
                                    seed=seeds[0]),
            kr.synthesize_structure("row_band", n, bs, mesh,
                                    seed=seeds[1]))


# ---------------------------------------------------------------------------
# Classifier: closed-form fixtures per structure class
# ---------------------------------------------------------------------------


class TestClassifier:
    def test_diagonal_is_row_band(self):
        r = np.arange(32)
        assert stats.classify_block_structure(r, r, 32, 32) \
            == "row_band"

    def test_tridiagonal_is_row_band(self):
        r = np.repeat(np.arange(16), 3)
        c = np.clip(r + np.tile([-1, 0, 1], 16), 0, 15)
        assert stats.classify_block_structure(r, c, 16, 16) \
            == "row_band"

    def test_off_diagonal_band_is_row_band(self):
        # a shifted band (constant offset) hugs ITS diagonal
        r = np.arange(24)
        c = np.clip(r + 5, 0, 31)
        assert stats.classify_block_structure(r, c, 24, 32) \
            == "row_band"

    def test_hub_rows_are_powerlaw(self):
        rows = np.concatenate([np.zeros(24, np.int64),
                               np.full(24, 7, np.int64),
                               np.arange(24)])
        cols = np.concatenate([np.arange(24), np.arange(24),
                               np.full(24, 3, np.int64)])
        assert stats.classify_block_structure(rows, cols, 24, 24) \
            == "powerlaw_coo"

    def test_dense_blobs_are_clustered(self):
        blocks = []
        for (cr, cc) in ((2, 3), (10, 12), (17, 5)):
            ii, jj = np.meshgrid(np.arange(4), np.arange(4),
                                 indexing="ij")
            blocks.append((cr + ii.ravel(), cc + jj.ravel()))
        rows = np.concatenate([b[0] for b in blocks])
        cols = np.concatenate([b[1] for b in blocks])
        assert stats.classify_block_structure(rows, cols, 24, 24) \
            == "clustered_tile"

    def test_uniform_random_is_generic(self):
        rng = np.random.default_rng(0)
        for seed in range(5):
            flat = np.random.default_rng(seed).choice(
                32 * 32, size=50, replace=False)
            assert stats.classify_block_structure(
                flat // 32, flat % 32, 32, 32) == "generic", seed
        del rng

    def test_boundary_histograms_fall_back_to_generic(self):
        # too few tiles: no evidence
        assert stats.classify_block_structure(
            np.array([0, 1]), np.array([0, 1]), 16, 16) == "generic"
        # degenerate grid
        assert stats.classify_block_structure(
            np.arange(8), np.zeros(8), 8, 1) == "generic"
        # skew just UNDER the powerlaw threshold: 8 occupied rows,
        # max 5 < 6x the median 1, nothing adjacent, nothing banded —
        # must not classify
        rows = np.concatenate([np.zeros(5, np.int64),
                               1 + np.arange(7) * 8])
        cols = np.concatenate([np.arange(5) * 9,
                               (3 + np.arange(7) * 23) % 64])
        got = stats.classify_block_structure(rows, cols, 64, 64)
        assert got == "generic"

    def test_pair_class_conservative(self):
        assert stats.pair_structure_class("row_band", "row_band") \
            == "row_band"
        assert stats.pair_structure_class("row_band", "generic") \
            == "generic"
        assert stats.pair_structure_class("powerlaw_coo",
                                          "clustered_tile") == "generic"
        assert stats.pair_structure_class("nonsense", "nonsense") \
            == "generic"

    def test_generators_classify_as_labelled(self, mesh8):
        for structure in stats.STRUCTURE_CLASSES:
            S = kr.synthesize_structure(structure, 512, 8, mesh8,
                                        seed=3)
            assert kr.structure_of_matrix(S) == structure

    def test_structure_memoised_per_matrix(self, mesh8):
        S = kr.synthesize_structure("row_band", 256, 16, mesh8, seed=0)
        assert kr.structure_of_matrix(S) == "row_band"
        S._structure_memo = "clustered_tile"      # poke the memo
        assert kr.structure_of_matrix(S) == "clustered_tile"


# ---------------------------------------------------------------------------
# Registry: vocabulary + admissibility
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_vocabulary(self):
        ids = kr.kernel_ids()
        assert set(ids) >= {"xla_gather", "pallas_generic",
                            "pallas_band", "pallas_cluster",
                            "pallas_powerlaw"}
        for kid in ids:
            spec = kr.get_kernel(kid)
            assert spec.kernel_id == kid and spec.description
            if not spec.universal:
                assert spec.structures, kid

    def test_xla_admissible_everywhere(self):
        cfg = MatrelConfig(use_pallas=False)
        assert kr.admissible("xla_gather", 3, 0, cfg)

    def test_pallas_needs_gate_and_sublane(self):
        off = MatrelConfig(use_pallas=False)
        on = MatrelConfig(pallas_interpret=True)
        for kid in ("pallas_generic", "pallas_band", "pallas_cluster",
                    "pallas_powerlaw"):
            assert not kr.admissible(kid, 16, 4, off)
            assert not kr.admissible(kid, 4, 4, on)     # sub-8 sublane
            assert not kr.admissible(kid, 16, 0, on)    # no pairs
            assert kr.admissible(kid, 16, 4, on)

    def test_unknown_kernel_inadmissible(self):
        assert not kr.admissible("gpu_warp", 16, 4,
                                 MatrelConfig(pallas_interpret=True))

    def test_legacy_default_matches_pre_registry_choice(self):
        on = MatrelConfig(pallas_interpret=True)
        off = MatrelConfig(use_pallas=False)
        assert kr.legacy_default(16, 4, on) == "pallas_generic"
        assert kr.legacy_default(4, 4, on) == "xla_gather"
        assert kr.legacy_default(16, 4, off) == "xla_gather"

    def test_select_model_picks_home_kernel(self):
        cfg = MatrelConfig(pallas_interpret=True)
        assert kr.select_kernel("row_band", 16, 10, cfg) \
            == ("pallas_band", "model")
        assert kr.select_kernel("clustered_tile", 16, 10, cfg) \
            == ("pallas_cluster", "model")
        assert kr.select_kernel("powerlaw_coo", 16, 10, cfg) \
            == ("pallas_powerlaw", "model")
        assert kr.select_kernel("generic", 16, 10, cfg) \
            == ("pallas_generic", "default")

    def test_select_override_wins_and_unknown_raises(self):
        cfg = MatrelConfig(pallas_interpret=True,
                           spgemm_kernel_override="pallas_cluster")
        assert kr.select_kernel("row_band", 16, 10, cfg) \
            == ("pallas_cluster", "override")
        # a typo'd override fails at CONSTRUCTION (the obs_level /
        # precision_sla precedent), never as a mid-traffic surprise
        with pytest.raises(ValueError, match="warp9000"):
            MatrelConfig(spgemm_kernel_override="warp9000")

    def test_config_vocabulary_matches_registry(self):
        # config.SPGEMM_KERNEL_IDS is what the override validates
        # against at construction (config cannot import the registry —
        # it needs jax); registering a new kernel must extend BOTH
        from matrel_tpu import config as config_lib
        assert set(config_lib.SPGEMM_KERNEL_IDS) == set(kr.kernel_ids())

    def test_inadmissible_override_falls_back_to_legacy(self):
        cfg = MatrelConfig(use_pallas=False,
                           spgemm_kernel_override="pallas_band")
        assert kr.select_kernel("row_band", 16, 10, cfg) \
            == ("xla_gather", "default")

    def test_all_kernels_oracle_exact(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True)
        for structure in stats.STRUCTURE_CLASSES:
            A = kr.synthesize_structure(structure, 256, 16, mesh8,
                                        seed=5)
            B = kr.synthesize_structure(structure, 256, 16, mesh8,
                                        seed=6)
            ref = A.to_numpy() @ B.to_numpy()
            for kid in kr.kernel_ids():
                got = spgemm_lib.spgemm(A, B, cfg, kernel=kid) \
                    .to_numpy()
                np.testing.assert_allclose(got, ref, rtol=1e-4,
                                           atol=1e-4)


# ---------------------------------------------------------------------------
# Planner stamping + MV110
# ---------------------------------------------------------------------------


class TestPlannerStamping:
    def test_spgemm_stamp_carries_kernel(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True)
        A, B = _band_pair(mesh8)
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        assert ann.attrs["strategy"] == "spgemm"
        assert ann.attrs["spgemm_kernel"] == "pallas_band"
        assert ann.attrs["spgemm_structure"] == "row_band"
        assert ann.attrs["spgemm_kernel_source"] == "model"
        assert not analysis.verify_plan(ann, mesh8, cfg)

    def test_cpu_default_stamps_legacy_xla(self, mesh8):
        # without pallas (the CPU default config), the stamp is the
        # legacy choice — bit-identical dispatch behavior
        cfg = MatrelConfig()
        A, B = _band_pair(mesh8, seeds=(3, 4))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        assert ann.attrs["spgemm_kernel"] == "xla_gather"
        assert ann.attrs["spgemm_kernel_source"] == "default"

    def test_decisions_record_kernel_fields(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True)
        A, B = _band_pair(mesh8, seeds=(5, 6))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        rec = planner.matmul_decisions(ann, mesh8, cfg)[0]
        assert rec["dispatch"] == "spgemm"
        assert rec["kernel_id"] == "pallas_band"
        assert rec["structure_class"] == "row_band"
        assert rec["est_vs_measured"] == "estimate"

    def test_executor_honors_stamp(self, mesh8, monkeypatch):
        cfg = MatrelConfig(pallas_interpret=True)
        A, B = _band_pair(mesh8, seeds=(7, 8))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        built = []
        orig = kr.build_runner

        def spy(kid, *a, **k):
            built.append(kid)
            return orig(kid, *a, **k)

        monkeypatch.setattr(kr, "build_runner", spy)
        spgemm_lib._RUNNER_CACHE.clear()
        out = executor_lib.execute(ann, mesh8, cfg)
        assert built == ["pallas_band"]
        n = A.shape[0]
        np.testing.assert_allclose(out.to_numpy()[:n, :n],
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_mv110_flags_unknown_and_foreign_stamps(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True)
        A, B = _band_pair(mesh8, seeds=(9, 10))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        # unknown id
        bad = ann.with_attrs(spgemm_kernel="gpu_warp")
        codes = [d.code for d in analysis.verify_plan(bad, mesh8, cfg)]
        assert "MV110" in codes
        # specialized kernel on a foreign structure class
        foreign = ann.with_attrs(spgemm_kernel="pallas_powerlaw",
                                 spgemm_structure="row_band")
        codes = [d.code for d in
                 analysis.verify_plan(foreign, mesh8, cfg)]
        assert "MV110" in codes
        # ... but the config override legitimizes the same stamp
        forced = cfg.replace(spgemm_kernel_override="pallas_powerlaw")
        assert not [d for d in
                    analysis.verify_plan(foreign, mesh8, forced)
                    if d.code == "MV110"]

    def test_mv110_flags_stamp_without_dispatch(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True)
        A, B = _band_pair(mesh8, seeds=(11, 12))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        # verify under a config that KILLS the dispatch
        off = MatrelConfig(pallas_interpret=True,
                           spgemm_density_threshold=0.0)
        codes = [d.code for d in analysis.verify_plan(ann, mesh8, off)]
        assert "MV110" in codes

    def test_mv110_flags_pallas_stamp_without_pallas(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True)
        A, B = _band_pair(mesh8, seeds=(13, 14))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        nopallas = MatrelConfig(use_pallas=False)
        diags = [d for d in analysis.verify_plan(ann, mesh8, nopallas)
                 if d.code == "MV110"]
        assert diags and "Pallas" in diags[0].message

    def test_mv110_flags_stamp_failing_the_sublane_rule(self, mesh8):
        # review finding: runnability must be the lowering's FULL
        # admissibility gate — a hand-stamped Pallas kernel at a
        # sub-8-sublane block size would silently run the legacy
        # default while obs records the stamp
        cfg = MatrelConfig(pallas_interpret=True, block_size=4)
        rng = np.random.default_rng(0)
        from matrel_tpu.core.coo import COOMatrix
        n, nnz = 256, 120
        C1 = COOMatrix.from_edges(rng.integers(0, n, nnz),
                                  rng.integers(0, n, nnz),
                                  shape=(n, n))
        C2 = COOMatrix.from_edges(rng.integers(0, n, nnz),
                                  rng.integers(0, n, nnz),
                                  shape=(n, n))
        e = C1.multiply(C2.expr())
        assert executor_lib._spgemm_dispatch(e, cfg)
        bad = e.with_attrs(strategy="spgemm",
                           strategy_source="dispatch",
                           spgemm_kernel="pallas_generic")
        diags = [d for d in analysis.verify_plan(bad, mesh8, cfg)
                 if d.code == "MV110"]
        assert diags and "not runnable" in diags[0].message


# ---------------------------------------------------------------------------
# Autotune: key format, legacy pruning, measured-winner override
# ---------------------------------------------------------------------------


class TestAutotune:
    def test_key_format(self, mesh8):
        key = autotune._spgemm_key(3000, "row_band", 512, 2, 4)
        backend = __import__("jax").default_backend()
        assert key == f"spgemm|<=4096|row_band|bs512|2x4|{backend}"
        assert autotune._current_key_format(key)
        wkey = autotune._spgemm_key(3000, "row_band", 512, 2, 4,
                                    (1.0, 8.0))
        assert wkey.endswith("|w1x8")
        assert autotune._current_key_format(wkey)

    def test_legacy_spgemm_keys_pruned_on_load(self, tmp_path):
        path = tmp_path / "table.json"
        backend = __import__("jax").default_backend()
        good = f"spgemm|<=1024|row_band|bs16|2x4|{backend}"
        table = {
            good: {"best": "pallas_band", "times": {"pallas_band": 1}},
            # un-suffixed legacy format (missing backend field)
            "spgemm|<=1024|row_band|bs16|2x4": {"best": "x",
                                                "times": {"x": 1}},
            # retired structure taxonomy
            f"spgemm|<=1024|banded|bs16|2x4|{backend}": {
                "best": "x", "times": {"x": 1}},
        }
        path.write_text(json.dumps(table))
        loaded = autotune.load_table(str(path))
        assert set(loaded) == {good}

    def test_measured_winner_overrides_estimate(self, mesh8, tmp_path):
        path = tmp_path / "table.json"
        gx, gy = 2, 4
        key = autotune._spgemm_key(1024, "row_band", 16, gx, gy)
        path.write_text(json.dumps({key: {
            "best": "xla_gather",
            "times": {"xla_gather": 0.001, "pallas_band": 0.005}}}))
        cfg = MatrelConfig(pallas_interpret=True, autotune=True,
                           autotune_table_path=str(path))
        autotune._SPGEMM_CACHE.clear()
        autotune._TABLE_CACHE.clear()
        kid, source = kr.select_kernel("row_band", 16, 10, cfg,
                                       side=1024, mesh=mesh8)
        assert (kid, source) == ("xla_gather", "measured")
        # the planner stamp carries the measured source end to end
        A, B = _band_pair(mesh8, seeds=(15, 16))
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        assert ann.attrs["spgemm_kernel"] == "xla_gather"
        assert ann.attrs["spgemm_kernel_source"] == "measured"
        rec = planner.matmul_decisions(ann, mesh8, cfg)[0]
        assert rec["est_vs_measured"] == "measured"

    def test_measure_persist_and_replay(self, mesh8, tmp_path):
        path = tmp_path / "table.json"
        cfg = MatrelConfig(pallas_interpret=True, autotune=True,
                           autotune_table_path=str(path),
                           autotune_max_dim=512)
        autotune._SPGEMM_CACHE.clear()
        autotune._TABLE_CACHE.clear()
        best = autotune.lookup_or_measure_spgemm(256, "clustered_tile",
                                                 16, mesh8, cfg)
        table = autotune.load_table(str(path))
        assert len(table) == 1
        entry = next(iter(table.values()))
        assert set(entry["times"]) >= {"xla_gather", "pallas_generic",
                                       "pallas_cluster"}
        # fresh "session": the persisted row answers without measuring
        autotune._SPGEMM_CACHE.clear()
        autotune._TABLE_CACHE.clear()
        measured = []
        orig = autotune.measure_spgemm_kernel
        autotune.measure_spgemm_kernel = \
            lambda *a, **k: measured.append(1) or orig(*a, **k)
        try:
            again = autotune.lookup_or_measure_spgemm(
                256, "clustered_tile", 16, mesh8, cfg)
        finally:
            autotune.measure_spgemm_kernel = orig
        assert again == best and not measured

    def test_oversize_shapes_never_measured_inline(self, mesh8):
        cfg = MatrelConfig(pallas_interpret=True, autotune=True,
                           autotune_max_dim=512)
        autotune._SPGEMM_CACHE.clear()
        assert autotune.lookup_or_measure_spgemm(
            100_000, "row_band", 512, mesh8, cfg) is None


# ---------------------------------------------------------------------------
# Observability surfaces: drift keying + history census
# ---------------------------------------------------------------------------


class TestObsSurfaces:
    def test_drift_keys_calibration_rows_per_kernel(self):
        from matrel_tpu.obs import drift
        d = {"dispatch": "spgemm", "kernel_id": "pallas_band",
             "dims": [64, 64, 64], "flops": 1.0}
        assert drift._sample(d, 1.0, "cpu", "query")["strategy"] \
            == "spgemm:pallas_band"
        # pre-registry logs keep the historical key
        legacy = {"dispatch": "spgemm", "dims": [64, 64, 64]}
        assert drift._sample(legacy, 1.0, "cpu", "query")["strategy"] \
            == "dispatch:spgemm"

    def test_history_summary_kernel_census(self):
        from matrel_tpu.obs import history
        events = [{"kind": "query", "matmuls": [
            {"strategy": "spgemm", "dispatch": "spgemm",
             "kernel_id": "pallas_band", "structure_class": "row_band",
             "est_vs_measured": "measured", "flops": 1.0},
            {"strategy": "spgemm", "dispatch": "spgemm",
             "kernel_id": "xla_gather", "structure_class": "generic",
             "est_vs_measured": "estimate", "flops": 1.0},
            {"strategy": "spgemm", "dispatch": "spgemm",
             "kernel_id": "pallas_band", "structure_class": "row_band",
             "est_vs_measured": "estimate", "flops": 1.0},
        ]}]
        s = history.summarize(events)
        assert s["spgemm_kernels"]["pallas_band"] == {
            "count": 2, "measured": 1, "structures": {"row_band": 2}}
        assert s["spgemm_kernels"]["xla_gather"]["count"] == 1
        assert "spgemm kernels:" in history.render_summary(events)


# ---------------------------------------------------------------------------
# Default-config bit-identity
# ---------------------------------------------------------------------------


class TestBitIdentity:
    def test_zero_threshold_means_zero_registry_lookups(self, mesh8):
        cfg = MatrelConfig(spgemm_density_threshold=0.0)
        A, B = _band_pair(mesh8, seeds=(21, 22))
        e = A.multiply(B)
        before = kr._LOOKUPS["count"]
        ann = planner.annotate_strategies(e, mesh8, cfg)
        planner.matmul_decisions(ann, mesh8, cfg)
        analysis.verify_plan(ann, mesh8, cfg)
        executor_lib.execute(ann, mesh8, cfg)
        assert kr._LOOKUPS["count"] == before
        assert "spgemm_kernel" not in ann.attrs

    def test_dense_plans_untouched(self, mesh8):
        # a dense matmul chain must gain no registry attrs and consult
        # no registry state
        from matrel_tpu.core.blockmatrix import BlockMatrix
        rng = np.random.default_rng(0)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32),
            mesh=mesh8)
        before = kr._LOOKUPS["count"]
        ann = planner.annotate_strategies(
            A.expr().multiply(A.expr()), mesh8, MatrelConfig())
        assert kr._LOOKUPS["count"] == before
        assert "spgemm_kernel" not in ann.attrs

    def test_plan_snapshots_unchanged(self):
        """The committed 10-plan corpus replans bit-identically under
        the registry — delegated to tools/plan_snapshot.py's diff
        (test_plan_snapshots runs it too; asserted here so THIS file
        fails locally if the registry moves a snapshot)."""
        import subprocess
        import sys
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__))), "tools",
                 "plan_snapshot.py")],
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "10/10 plans match" in proc.stdout, proc.stdout
