"""Whole-plan program fusion (ir/fusion.py; docs/FUSION.md).

Covers the round-12 acceptance surface: region grammar, the off-state
bit-identity contract (zero FusedRegion constructions — poisoned-init),
fused-vs-staged numerical agreement across dense/SpGEMM/COO producers
and precision tiers, the epilogue slots (strategies / spmm / spgemm →
kernel-registry hook), MV111 in both directions, the unit-program seam
dispatch counts, the autotune ``fuse|`` key family, the degradation
rung interaction, and the obs surfaces (decision fields, drift keying,
history roll-up, analyze attribution).
"""

import numpy as np
import pytest

from matrel_tpu import analysis, executor as executor_lib
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import fusion as fusion_lib
from matrel_tpu.ir.rules import optimize
from matrel_tpu.parallel import planner


@pytest.fixture(scope="module")
def mesh8():
    return mesh_lib.make_mesh()


CFG_OFF = MatrelConfig(obs_level="off")
CFG_ON = CFG_OFF.replace(fusion_enable=True)


def _chain(mesh, n=32, k=16, seed=0):
    """(expr, float64 oracle): (XᵀX)·(1/n) + λI, then row-mean."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, k)).astype(np.float32)
    eye = np.eye(k, dtype=np.float32)
    X = BlockMatrix.from_numpy(x, mesh=mesh)
    I = BlockMatrix.from_numpy(eye, mesh=mesh)
    e = X.expr().t().multiply(X.expr()).multiply_scalar(1.0 / n) \
        .add(I.expr().multiply_scalar(0.1)) \
        .row_sum().multiply_scalar(1.0 / k)
    ref = ((x.astype(np.float64).T @ x.astype(np.float64)) / n
           + 0.1 * np.eye(k)).sum(axis=1, keepdims=True) / k
    return e, ref


def _annotated(e, mesh, cfg):
    opt = planner.annotate_strategies(optimize(e, cfg), mesh, cfg)
    return fusion_lib.annotate_fusion(opt, mesh, cfg)


class TestOffStateBitIdentity:
    def test_off_constructs_no_region_objects(self, mesh8):
        e, _ = _chain(mesh8)
        before = fusion_lib._CONSTRUCTED["count"]
        plan = executor_lib.compile_expr(e, mesh8, CFG_OFF)
        assert fusion_lib._CONSTRUCTED["count"] == before
        assert not fusion_lib.collect_stamps(plan.optimized)
        assert "fusion" not in (plan.meta or {})

    def test_off_poisoned_init(self, mesh8, monkeypatch):
        """The bit-identity contract, enforced structurally: with
        fusion off the compile path must never even INSTANTIATE a
        FusedRegion (the resilience default-config zero-object
        idiom)."""
        def boom(*a, **k):
            raise AssertionError("FusedRegion constructed with "
                                 "fusion_enable off")

        monkeypatch.setattr(fusion_lib, "FusedRegion", boom)
        e, ref = _chain(mesh8)
        out = executor_lib.compile_expr(e, mesh8, CFG_OFF).run()
        np.testing.assert_allclose(out.to_numpy()[:ref.shape[0]],
                                   ref, rtol=1e-4, atol=1e-4)

    def test_segment_returns_empty_when_off(self, mesh8):
        e, _ = _chain(mesh8)
        opt = planner.annotate_strategies(optimize(e, CFG_OFF), mesh8,
                                          CFG_OFF)
        assert fusion_lib.segment(opt, CFG_OFF) == []
        assert fusion_lib.annotate_fusion(opt, mesh8, CFG_OFF) is opt


class TestRegionGrammar:
    def test_epilogue_chain_fuses_with_anchor(self, mesh8):
        e, _ = _chain(mesh8)
        opt = _annotated(e, mesh8, CFG_ON)
        stamps = fusion_lib.collect_stamps(opt)
        assert len(stamps) == 1
        s = stamps[0]
        assert s.attrs["fused_anchor"] is not None
        census = s.attrs["fused_census"]
        assert census["mm"] == 1
        assert census.get("elemwise.add") == 1
        assert s.attrs["fused_saved_dispatches"] >= 3
        assert s.attrs["fused_saved_hbm_bytes"] > 0
        # the signature embeds in '|'-separated autotune keys
        assert "|" not in s.attrs["fused_region"]

    def test_shared_node_is_a_boundary(self, mesh8):
        rng = np.random.default_rng(1)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        shared = A.expr().multiply_scalar(2.0)
        e = shared.add(shared.elem_multiply(shared))
        opt = _annotated(e, mesh8, CFG_ON)
        for s in fusion_lib.collect_stamps(opt):
            nodes = fusion_lib.region_nodes(s)
            counts = fusion_lib.consumer_counts((opt,))
            for uid, node in nodes.items():
                if uid != s.uid:
                    assert counts[uid] == 1, (
                        "shared node absorbed as a member")

    def test_at_most_one_anchor(self, mesh8):
        rng = np.random.default_rng(2)
        mats = [BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8) for _ in range(4)]
        # (A·B) + (C·D): the add can absorb only ONE producer
        e = mats[0].expr().multiply(mats[1].expr()).add(
            mats[2].expr().multiply(mats[3].expr()))
        opt = _annotated(e, mesh8, CFG_ON)
        for s in fusion_lib.collect_stamps(opt):
            nodes = fusion_lib.region_nodes(s)
            assert sum(1 for n in nodes.values()
                       if n.kind == "matmul") <= 1

    def test_lone_fusable_op_is_not_a_region(self, mesh8):
        rng = np.random.default_rng(3)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        B = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        # transpose boundary between the add and anything else:
        # the add alone (leaf operands) must not stamp
        e = A.expr().add(B.expr())
        opt = _annotated(e, mesh8, CFG_ON)
        # add + nothing fusable below = 1 member -> no region
        assert not fusion_lib.collect_stamps(opt)

    def test_remask_census_counts_breakers(self, mesh8):
        rng = np.random.default_rng(4)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        B = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        e = A.expr().multiply(B.expr()).add_scalar(1.0) \
            .multiply_scalar(2.0)
        opt = _annotated(e, mesh8, CFG_ON)
        (s,) = fusion_lib.collect_stamps(opt)
        assert s.attrs["fused_remask"] == 1   # scalar add v!=0 only


class TestFusedExecutionAgrees:
    def test_dense_chain_oracle(self, mesh8):
        e, ref = _chain(mesh8)
        out = executor_lib.compile_expr(e, mesh8, CFG_ON).run()
        np.testing.assert_allclose(out.to_numpy()[:ref.shape[0]],
                                   ref, rtol=1e-4, atol=1e-4)

    def test_fused_equals_staged_exactly(self, mesh8):
        e, _ = _chain(mesh8, seed=5)
        a = executor_lib.compile_expr(e, mesh8, CFG_OFF).run()
        b = executor_lib.compile_expr(e, mesh8, CFG_ON).run()
        np.testing.assert_allclose(a.to_numpy(), b.to_numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_spgemm_anchor_epilogue(self, mesh8):
        from matrel_tpu.ops import kernel_registry as kr
        bs = 8
        n = bs * 48
        SA = kr.synthesize_structure("row_band", n, bs, mesh8, seed=0)
        SB = kr.synthesize_structure("row_band", n, bs, mesh8, seed=1)
        ref = (SA.to_numpy().astype(np.float64)
               @ SB.to_numpy().astype(np.float64)) * 0.5
        e = SA.multiply(SB).multiply_scalar(0.5)
        # the probabilistic density lift overestimates banded output
        # density; raise the crossover so the S×S dispatch fires
        cfg = CFG_ON.replace(block_size=bs,
                             spgemm_density_threshold=0.6)
        opt = _annotated(e, mesh8, cfg)
        (s,) = fusion_lib.collect_stamps(opt)
        anchor = fusion_lib.region_nodes(s)[s.attrs["fused_anchor"]]
        assert anchor.attrs.get("strategy") == "spgemm"
        out = executor_lib.execute(e, mesh8, cfg).to_numpy()
        scale = max(float(np.abs(ref).max()), 1.0)
        np.testing.assert_allclose(out[:n, :n] / scale, ref / scale,
                                   rtol=1e-4, atol=1e-4)

    def test_precision_tier_preserved_in_region(self, mesh8):
        rng = np.random.default_rng(6)
        a = rng.random((32, 32), dtype=np.float32)
        b = rng.random((32, 32), dtype=np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        B = BlockMatrix.from_numpy(b, mesh=mesh8)
        e = A.expr().multiply(B.expr()).multiply_scalar(2.0) \
            .add_scalar(0.5)
        cfg = CFG_ON.replace(precision_sla="high")
        opt = _annotated(e, mesh8, cfg)
        (s,) = fusion_lib.collect_stamps(opt)
        anchor = fusion_lib.region_nodes(s)[s.attrs["fused_anchor"]]
        assert anchor.attrs.get("precision_tier") == "bf16x3"
        assert s.attrs["fused_tier"] == "bf16x3"
        out = executor_lib.execute(e, mesh8, cfg).to_numpy()
        ref = (a.astype(np.float64) @ b.astype(np.float64)) * 2 + 0.5
        np.testing.assert_allclose(out[:32, :32], ref, rtol=1e-3,
                                   atol=1e-3)


class TestEpilogueSlots:
    def test_run_matmul_epilogue_in_trace(self, mesh8):
        import jax.numpy as jnp
        from matrel_tpu.parallel import strategies
        rng = np.random.default_rng(7)
        a = jnp.asarray(rng.standard_normal((16, 16)).astype(
            np.float32))
        b = jnp.asarray(rng.standard_normal((16, 16)).astype(
            np.float32))
        plain = strategies.run_matmul("xla", a, b, mesh8, CFG_OFF)
        fused = strategies.run_matmul("xla", a, b, mesh8, CFG_OFF,
                                      epilogue=lambda x: x * 3.0)
        np.testing.assert_allclose(np.asarray(fused),
                                   np.asarray(plain) * 3.0,
                                   rtol=1e-6)

    def test_spmm_apply_epilogue(self, mesh8):
        from matrel_tpu.core.sparse import BlockSparseMatrix
        from matrel_tpu.ops import spmm as spmm_lib
        S = BlockSparseMatrix.random((64, 64), block_density=0.5,
                                     block_size=8, mesh=mesh8, seed=0)
        D = BlockMatrix.random((64, 8), mesh=mesh8, seed=1)
        plain = spmm_lib.apply(S, D.data, D.shape, CFG_OFF)
        fused = spmm_lib.apply(S, D.data, D.shape, CFG_OFF,
                               epilogue=lambda x: x + 1.0)
        np.testing.assert_allclose(np.asarray(fused),
                                   np.asarray(plain) + 1.0, rtol=1e-6)

    def test_spgemm_tilewise_matches_dense_hook(self, mesh8):
        """A zero-preserving scalar epilogue applied tile-wise (the
        specialized classes' registered mode) equals the dense
        post-scatter application — the hook may only change WHERE the
        chain runs, never the product."""
        from matrel_tpu.ops import kernel_registry as kr
        from matrel_tpu.ops import spgemm as spgemm_lib
        bs = 8
        n = bs * 16
        SA = kr.synthesize_structure("row_band", n, bs, mesh8, seed=2)
        SB = kr.synthesize_structure("row_band", n, bs, mesh8, seed=3)
        assert kr.pair_class_of(SA, SB) == "row_band"
        assert kr.epilogue_mode("row_band", True) == "tilewise"
        assert kr.epilogue_mode("row_band", False) == "dense"
        assert kr.epilogue_mode("generic", True) == "dense"
        cfg = CFG_OFF.replace(block_size=bs)
        epi = lambda x: x * 0.25
        tile = spgemm_lib.apply_dense(SA, SB, cfg, epilogue=epi,
                                      epilogue_elementwise=True)
        dense = spgemm_lib.apply_dense(SA, SB, cfg, epilogue=epi,
                                       epilogue_elementwise=False)
        np.testing.assert_allclose(np.asarray(tile),
                                   np.asarray(dense), rtol=1e-6)

    def test_register_epilogue_hook_validates(self):
        from matrel_tpu.ops import kernel_registry as kr
        with pytest.raises(ValueError):
            kr.register_epilogue_hook("row_band", "bogus")


class TestMV111:
    def test_quiet_on_fresh_annotation(self, mesh8):
        e, _ = _chain(mesh8, seed=8)
        opt = _annotated(e, mesh8, CFG_ON)
        assert [d for d in analysis.verify_plan(opt, mesh8, CFG_ON)
                if d.code == "MV111"] == []

    def test_stamp_with_fusion_off_is_error(self, mesh8):
        e, _ = _chain(mesh8, seed=9)
        opt = _annotated(e, mesh8, CFG_ON)
        diags = [d for d in analysis.verify_plan(opt, mesh8, CFG_OFF)
                 if d.code == "MV111"]
        assert diags and all(d.severity == "error" for d in diags)

    def test_unstamped_region_flagged_backward(self, mesh8):
        e, _ = _chain(mesh8, seed=10)
        opt = planner.annotate_strategies(optimize(e, CFG_ON), mesh8,
                                          CFG_ON)   # NOT fused
        diags = [d for d in analysis.verify_plan(opt, mesh8, CFG_ON)
                 if d.code == "MV111"]
        assert diags and diags[0].severity == "error"
        # under autotune the suppression is legitimate -> warning
        cfg_at = CFG_ON.replace(autotune=True)
        diags = [d for d in analysis.verify_plan(opt, mesh8, cfg_at)
                 if d.code == "MV111"]
        assert diags and diags[0].severity == "warning"

    def test_tampered_census_is_error(self, mesh8):
        e, _ = _chain(mesh8, seed=11)
        opt = _annotated(e, mesh8, CFG_ON)

        def tamper(n):
            if "fused_region" in n.attrs:
                return n.with_attrs(fused_census={"mm": 99})
            if not n.children:
                return n
            return n.with_children(tuple(tamper(c)
                                         for c in n.children))

        bad = tamper(opt)
        diags = [d for d in analysis.verify_plan(bad, mesh8, CFG_ON)
                 if d.code == "MV111" and d.severity == "error"]
        assert diags

    def test_tampered_tier_is_error(self, mesh8):
        e, _ = _chain(mesh8, seed=12)
        opt = _annotated(e, mesh8, CFG_ON)

        def tamper(n):
            if "fused_region" in n.attrs:
                return n.with_attrs(fused_tier="bf16x1")
            if not n.children:
                return n
            return n.with_children(tuple(tamper(c)
                                         for c in n.children))

        bad = tamper(opt)
        diags = [d for d in analysis.verify_plan(bad, mesh8, CFG_ON)
                 if d.code == "MV111" and d.severity == "error"]
        assert diags
        assert "tier" in diags[0].message

    def test_error_gate_blocks_tampered_plan(self, mesh8):
        e, _ = _chain(mesh8, seed=13)
        cfg = CFG_ON.replace(verify_plans="error")
        # a clean compile passes the gate
        executor_lib.compile_expr(e, mesh8, cfg)


class TestUnitProgramSeam:
    def test_dispatch_counts_shrink(self, mesh8):
        e, ref = _chain(mesh8, seed=14)
        staged = executor_lib.compile_staged_units(e, mesh8, CFG_OFF)
        fused = executor_lib.compile_region_units(e, mesh8, CFG_ON)
        assert fused.dispatches < staged.dispatches
        a = np.asarray(staged.run())
        b = np.asarray(fused.run())
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(b[:ref.shape[0]], ref, rtol=1e-4,
                                   atol=1e-4)

    def test_region_units_without_fusion_match_staged(self, mesh8):
        e, _ = _chain(mesh8, seed=15)
        ru = executor_lib.compile_region_units(e, mesh8, CFG_OFF)
        su = executor_lib.compile_staged_units(e, mesh8, CFG_OFF)
        assert ru.dispatches == su.dispatches


class TestAutotuneFuseFamily:
    def test_key_format_accepted(self):
        from matrel_tpu.parallel import autotune
        key = autotune._fusion_key("mmx1+scalar.mulx2", 512, 2, 4)
        assert key.startswith("fuse|")
        assert autotune._current_key_format(key)
        assert autotune._current_key_format(key + "|w1x4")
        assert not autotune._current_key_format("fuse|sig|extra|f|g|h|i")

    def test_measure_and_persist_roundtrip(self, mesh8, tmp_path):
        from matrel_tpu.parallel import autotune
        e, _ = _chain(mesh8, seed=16)
        opt = planner.annotate_strategies(optimize(e, CFG_ON), mesh8,
                                          CFG_ON)
        (region,) = fusion_lib.segment(opt, CFG_ON, mesh=mesh8)
        table = str(tmp_path / "fuse.json")
        cfg = CFG_ON.replace(autotune=True, autotune_table_path=table)
        best = autotune.lookup_or_measure_fusion(region, opt, mesh8,
                                                 cfg)
        assert best in (None, "fused", "staged")
        persisted = autotune.load_table(table)
        fuse_keys = [k for k in persisted if k.startswith("fuse|")]
        # ties (None) persist too when both variants measured
        if fuse_keys:
            entry = persisted[fuse_keys[0]]
            assert set(entry["times"]) <= {"fused", "staged"}
            # replay from the persisted table with fresh caches
            autotune._FUSION_CACHE.clear()
            autotune._TABLE_CACHE.clear()
            again = autotune.lookup_or_measure_fusion(region, opt,
                                                      mesh8, cfg)
            assert again == best

    def test_staged_winner_suppresses_stamp(self, mesh8, monkeypatch):
        from matrel_tpu.parallel import autotune
        e, _ = _chain(mesh8, seed=17)
        monkeypatch.setattr(autotune, "lookup_or_measure_fusion",
                            lambda *a, **k: "staged")
        cfg = CFG_ON.replace(autotune=True)
        opt = planner.annotate_strategies(optimize(e, cfg), mesh8, cfg)
        out = fusion_lib.annotate_fusion(opt, mesh8, cfg)
        assert not fusion_lib.collect_stamps(out)


class TestDegradeRung:
    def test_rung3_forces_staged(self):
        from matrel_tpu.resilience import degrade
        base = MatrelConfig(fusion_enable=True)
        assert degrade.apply_rung(base, 2).fusion_enable is True
        assert degrade.apply_rung(base, 3).fusion_enable is False
        assert degrade.apply_rung(base, 4).fusion_enable is False
        # rung 0 identity (bit-identity contract)
        assert degrade.apply_rung(base, 0) is base


class TestObsSurfaces:
    def test_matmul_decisions_carry_boundary(self, mesh8):
        e, _ = _chain(mesh8, seed=18)
        plan = executor_lib.compile_expr(e, mesh8, CFG_ON)
        (d,) = executor_lib.plan_matmul_decisions(plan)
        assert d["fused_region"]
        assert d["fused_census"]["mm"] == 1
        assert d["est_saved_dispatches"] >= 3
        assert d["est_saved_hbm_bytes"] > 0
        assert plan.meta["fusion"]["regions"] == 1

    def test_decisions_unchanged_when_off(self, mesh8):
        e, _ = _chain(mesh8, seed=19)
        plan = executor_lib.compile_expr(e, mesh8, CFG_OFF)
        (d,) = executor_lib.plan_matmul_decisions(plan)
        assert "fused_region" not in d
        assert "est_saved_dispatches" not in d

    def test_drift_keying(self):
        from matrel_tpu.obs import drift
        assert drift._strategy_key(
            {"strategy": "bmm_right",
             "fused_region": "mmx1+scalar.mulx2"}) \
            == "fused:mmx1+scalar.mulx2"
        assert drift._strategy_key(
            {"strategy": "bmm_right"}) == "bmm_right"
        # tier still suffixes the fused key (same-tier populations)
        assert drift._strategy_key(
            {"fused_region": "s", "precision_tier": "bf16x3"}) \
            == "fused:s@bf16x3"

    def test_drift_joins_anchor_by_membership(self):
        from matrel_tpu.obs import drift
        events = [{
            "kind": "analyze", "backend": "cpu",
            "per_op": [{"uid": 99, "label": "fused:sig", "ms": 2.0,
                        "fused_region": "sig", "members": [7]}],
            "matmuls": [{"uid": 7, "dims": [32, 32, 32],
                         "strategy": "xla", "flops": 1e6,
                         "fused_region": "sig",
                         "est_ici_bytes": 0.0}],
        }]
        samples = list(drift.iter_samples(events))
        assert len(samples) == 1
        assert samples[0]["strategy"] == "fused:sig"
        assert samples[0]["ms"] == 2.0

    def test_history_fusion_line(self):
        from matrel_tpu.obs import history
        events = [{"kind": "query", "matmuls": [],
                   "fusion": {"regions": 2,
                              "census": {"mm": 2, "scalar.mul": 3},
                              "est_saved_dispatches": 5,
                              "est_saved_hbm_bytes": 2 << 20}}]
        s = history.summarize(events)
        assert s["fusion"]["regions"] == 2
        text = history.render_summary(events)
        assert "fusion: 2 region(s)" in text
        assert "5 dispatch(es)" in text

    def test_history_no_fusion_line_when_absent(self):
        from matrel_tpu.obs import history
        events = [{"kind": "query", "matmuls": []}]
        assert history.summarize(events)["fusion"] is None
        assert "fusion:" not in history.render_summary(events)

    def test_analyze_attributes_region_not_ghosts(self, mesh8,
                                                  tmp_path):
        from matrel_tpu.obs import analyze as analyze_mod
        from matrel_tpu.session import MatrelSession
        e, _ = _chain(mesh8, seed=20)
        sess = MatrelSession(mesh=mesh8, config=CFG_ON)
        plan = sess.compile(e)
        per_op, _total = analyze_mod.measure_per_op(plan)
        stamps = analyze_mod._fusion_stamps(plan)
        assert stamps, "plan lost its fusion stamp"
        (root_uid,) = stamps
        members = set(stamps[root_uid]["fused_members"])
        # ONE row at the region root, NO rows for absorbed members
        assert root_uid in per_op
        label, seconds = per_op[root_uid]
        assert label.startswith("fused:")
        assert seconds >= 0.0
        assert not (members & set(per_op)), "ghost member rows"
        rec = analyze_mod.analyze_record(plan, per_op, 0.001)
        region_rows = [r for r in rec["per_op"]
                       if r.get("fused_region")]
        assert len(region_rows) == 1
        assert set(region_rows[0]["members"]) == members
        text = analyze_mod.render(plan, per_op, 0.001)
        assert "fused=" in text
        assert "(in fused region" in text

    def test_query_event_carries_fusion(self, mesh8, tmp_path):
        import json
        from matrel_tpu.session import MatrelSession
        log = tmp_path / "ev.jsonl"
        e, _ = _chain(mesh8, seed=21)
        sess = MatrelSession(mesh=mesh8, config=CFG_ON.replace(
            obs_level="on", obs_event_log=str(log)))
        sess.run(e)
        events = [json.loads(l) for l in log.open()]
        q = [ev for ev in events if ev.get("kind") == "query"][0]
        assert q["fusion"]["regions"] == 1
        (d,) = q["matmuls"]
        assert d["fused_region"]


class TestConfigKnob:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("MATREL_FUSION_ENABLE", "1")
        cfg = MatrelConfig.from_env()
        assert cfg.fusion_enable is True
