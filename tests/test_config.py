"""Config system: MATREL_* env overrides, dict overrides, and the
shared Pallas gates (SURVEY.md §5 "Config / flag system")."""

import numpy as np
import pytest

from matrel_tpu.config import (MatrelConfig, pallas_enabled,
                               pallas_interpret_mode, resolve_interpret)


class TestFromEnv:
    def test_typed_overrides(self, monkeypatch):
        monkeypatch.setenv("MATREL_BLOCK_SIZE", "128")
        monkeypatch.setenv("MATREL_SPARSITY_THRESHOLD", "0.25")
        monkeypatch.setenv("MATREL_USE_PALLAS", "false")
        monkeypatch.setenv("MATREL_STRATEGY_OVERRIDE", "cpmm")
        monkeypatch.setenv("MATREL_MESH_SHAPE", "2x4")
        cfg = MatrelConfig.from_env()
        assert cfg.block_size == 128
        assert cfg.sparsity_threshold == 0.25
        assert cfg.use_pallas is False
        assert cfg.strategy_override == "cpmm"
        assert cfg.mesh_shape == (2, 4)

    def test_bool_spellings(self, monkeypatch):
        for raw, want in [("1", True), ("true", True), ("YES", True),
                          ("on", True), ("0", False), ("off", False),
                          ("no", False)]:
            monkeypatch.setenv("MATREL_CHAIN_OPT", raw)
            assert MatrelConfig.from_env().chain_opt is want, raw

    def test_mesh_shape_comma_form(self, monkeypatch):
        monkeypatch.setenv("MATREL_MESH_SHAPE", "4,2")
        assert MatrelConfig.from_env().mesh_shape == (4, 2)

    def test_unset_env_keeps_base(self, monkeypatch):
        base = MatrelConfig(block_size=64)
        assert MatrelConfig.from_env(base).block_size == 64

    def test_round2_knobs_via_env(self, monkeypatch):
        monkeypatch.setenv("MATREL_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("MATREL_JOIN_PAIR_CAP_ENTRIES", "1024")
        monkeypatch.setenv("MATREL_PLAN_CACHE_MAX_PLANS", "7")
        cfg = MatrelConfig.from_env()
        assert cfg.pallas_interpret is True
        assert cfg.join_pair_cap_entries == 1024
        assert cfg.plan_cache_max_plans == 7

    def test_round3_autotune_knobs_via_env(self, monkeypatch):
        monkeypatch.setenv("MATREL_AUTOTUNE", "true")
        monkeypatch.setenv("MATREL_AUTOTUNE_TABLE_PATH", "/tmp/t.json")
        monkeypatch.setenv("MATREL_AUTOTUNE_MAX_DIM", "2048")
        cfg = MatrelConfig.from_env()
        assert cfg.autotune is True
        assert cfg.autotune_table_path == "/tmp/t.json"
        assert cfg.autotune_max_dim == 2048


class TestFromDict:
    def test_valid_and_unknown_keys(self):
        cfg = MatrelConfig.from_dict({"block_size": 256,
                                      "use_pallas": False})
        assert cfg.block_size == 256 and cfg.use_pallas is False
        with pytest.raises(KeyError, match="unknown MatrelConfig keys"):
            MatrelConfig.from_dict({"blok_size": 1})


class TestPallasGates:
    # conftest pins the cpu backend, so the gates' backend term is False
    def test_gates_on_cpu(self):
        assert pallas_enabled(MatrelConfig()) is False
        assert pallas_enabled(MatrelConfig(pallas_interpret=True)) is True
        assert pallas_enabled(MatrelConfig(use_pallas=False,
                                           pallas_interpret=True)) is False
        assert pallas_interpret_mode(
            MatrelConfig(pallas_interpret=True)) is True
        assert pallas_interpret_mode(MatrelConfig()) is False

    def test_resolve_interpret_precedence(self):
        cfg_on = MatrelConfig(pallas_interpret=True)
        assert resolve_interpret(None, cfg_on) is True
        assert resolve_interpret(None, MatrelConfig()) is False
        assert resolve_interpret(False, cfg_on) is False   # explicit wins
        assert resolve_interpret(True, MatrelConfig()) is True


class TestAxisCostWeights:
    """Round 7 topology knob: validated at construction (a zero weight
    silently makes an axis free — worse than a crash), env-parseable in
    both mesh_shape spellings, normalised to a float tuple (the form
    every cache key embeds)."""

    def test_default_and_normalisation(self):
        assert MatrelConfig().axis_cost_weights == (1.0, 1.0)
        w = MatrelConfig(axis_cost_weights=(1, 8)).axis_cost_weights
        assert w == (1.0, 8.0)
        assert all(isinstance(v, float) for v in w)

    @pytest.mark.parametrize("bad", [(0.0, 1.0), (1.0, -2.0),
                                     (1.0,), (1.0, 2.0, 3.0),
                                     ("a", 1.0)])
    def test_invalid_rejected(self, bad):
        with pytest.raises((ValueError, TypeError)):
            MatrelConfig(axis_cost_weights=bad)

    def test_env_both_spellings(self, monkeypatch):
        monkeypatch.setenv("MATREL_AXIS_COST_WEIGHTS", "1,8")
        assert MatrelConfig.from_env().axis_cost_weights == (1.0, 8.0)
        monkeypatch.setenv("MATREL_AXIS_COST_WEIGHTS", "1.5x32")
        assert MatrelConfig.from_env().axis_cost_weights == (1.5, 32.0)
