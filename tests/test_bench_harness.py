"""bench.py harness CI: the driver's capture path must emit ONE
parseable JSON line on both a healthy and a dead backend, within
bounded wall-clock, with no leaked processes (round-1 VERDICT #1)."""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _preserve_bench_caches():
    """bench.py caches (cpu_baseline.json, bench_last_good.json) live
    at the repo root and would be overwritten by the N=256 runs —
    snapshot and restore them."""
    paths = [os.path.join(REPO, f) for f in ("cpu_baseline.json",
                                             "bench_last_good.json")]
    saved = {p: (open(p).read() if os.path.exists(p) else None)
             for p in paths}
    try:
        yield
    finally:
        for p, content in saved.items():
            if content is None:
                if os.path.exists(p):
                    os.remove(p)
            else:
                with open(p, "w") as f:
                    f.write(content)


def _run_bench(env_extra, timeout):
    env = dict(os.environ)
    # children must NOT inherit the axon sitecustomize (hangs while the
    # relay is wedged); force the CPU backend end-to-end
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.update(env_extra)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO)
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    return proc.returncode, lines, time.monotonic() - t0


def test_success_path_emits_metric_json(tmp_path):
    rc, lines, _ = _run_bench({
        "MATREL_BENCH_N": "256", "MATREL_BENCH_REPEATS": "3",
        "MATREL_BENCH_BACKOFFS": "1",
    }, timeout=240)
    assert rc == 0, lines
    out = json.loads(lines[-1])
    assert out["metric"] == "dense_blockmatmul_tflops_per_chip"
    assert out["value"] is not None and out["value"] > 0
    assert out["unit"] == "TFLOPS" and out["vs_baseline"] is not None


def test_dead_backend_emits_error_json_within_deadline():
    rc, lines, dt = _run_bench({
        # unloadable platform in the CHILDREN: probe fails; tiny
        # timeouts/backoffs keep the ladder fast
        "JAX_PLATFORMS": "nosuchplatform",
        "MATREL_BENCH_PROBE_TIMEOUT": "15",
        "MATREL_BENCH_BACKOFFS": "1,1,1",
        "MATREL_BENCH_DEADLINE": "60",
    }, timeout=180)
    assert rc == 0, lines                      # structured, not a crash
    out = json.loads(lines[-1])
    assert out["value"] is None
    assert out["vs_baseline"] is None
    assert out["error"]
    assert out["last_known_good"] is not None  # seeded in the repo
    assert dt < 150
