"""matlint (tools/matlint.py): fixture-based proof that every rule
fires on its hazard, that the inline suppression syntax silences it,
and that the repo itself lints clean — the tier-1 enforcement of
`make lint`'s first half (tests cannot silently skip what they
themselves run)."""

import textwrap

import pytest

from tools import matlint


def _lint(tmp_path, source, relpath):
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent(source))
    return matlint.lint_file(str(f), relpath=relpath)


def _rules(findings):
    return sorted({f.rule for f in findings})


class TestML001HostSync:
    def test_fires_on_block_until_ready(self, tmp_path):
        src = """
            import jax
            def lower(x):
                out = x + 1
                jax.block_until_ready(out)
                return out
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/custom.py")
        assert _rules(got) == ["ML001"]

    def test_fires_on_method_attribute_form(self, tmp_path):
        src = """
            def lower(x):
                x.block_until_ready()
                return x
        """
        got = _lint(tmp_path, src, "matrel_tpu/executor.py")
        assert _rules(got) == ["ML001"]

    def test_asarray_in_lowerer_method(self, tmp_path):
        src = """
            import numpy as np
            class MyLowerer:
                def _eval(self, x):
                    return np.asarray(x)
        """
        got = _lint(tmp_path, src, "matrel_tpu/executor.py")
        assert _rules(got) == ["ML001"]

    def test_asarray_sanctioned_under_compile_time_eval(self, tmp_path):
        src = """
            import jax
            import numpy as np
            class MyLowerer:
                def _eval(self, m):
                    with jax.ensure_compile_time_eval():
                        return np.asarray(m.rows)
        """
        assert _lint(tmp_path, src, "matrel_tpu/executor.py") == []

    def test_out_of_scope_module_ignored(self, tmp_path):
        src = """
            import jax
            def wait(x):
                jax.block_until_ready(x)
        """
        # obs/ and utils/ legitimately sync (analyze mode, checkpoint)
        assert _lint(tmp_path, src, "matrel_tpu/obs/analyze.py") == []


class TestML002NoDensify:
    def test_fires_in_ops_module(self, tmp_path):
        src = """
            def apply(S, x):
                return S.to_dense() @ x
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/spgemm.py")
        assert _rules(got) == ["ML002"]

    def test_todense_variant(self, tmp_path):
        src = """
            def apply(S):
                return S.todense()
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/spmm.py")
        assert _rules(got) == ["ML002"]

    def test_executor_dispatch_is_allowed(self, tmp_path):
        # the densify FALLBACK lives in the executor where the planner
        # prices it — only ops/ kernels are no-densify territory
        src = """
            def fallback(node, cfg):
                return node.attrs["matrix"].to_dense(cfg).data
        """
        assert _lint(tmp_path, src, "matrel_tpu/executor.py") == []


class TestML003ShardMapOutSpecs:
    def test_fires_without_out_specs(self, tmp_path):
        src = """
            from matrel_tpu.utils.compat import shard_map
            def f(kernel, mesh, specs):
                return shard_map(kernel, mesh=mesh, in_specs=specs)
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/new_kernel.py")
        assert _rules(got) == ["ML003"]

    def test_keyword_out_specs_clean(self, tmp_path):
        src = """
            from matrel_tpu.utils.compat import shard_map
            def f(kernel, mesh, specs, P):
                return shard_map(kernel, mesh=mesh, in_specs=specs,
                                 out_specs=P())
        """
        assert _lint(tmp_path, src, "matrel_tpu/ops/new_kernel.py") == []

    def test_positional_form_clean(self, tmp_path):
        src = """
            def f(sm, kernel, mesh, ins, outs):
                return sm.shard_map(kernel, mesh, ins, outs)
        """
        assert _lint(tmp_path, src, "matrel_tpu/ops/new_kernel.py") == []


class TestML004ConfigFlow:
    def test_fires_in_package(self, tmp_path):
        src = """
            from matrel_tpu.config import MatrelConfig
            def plan(node):
                cfg = MatrelConfig()
                return cfg.block_size
        """
        got = _lint(tmp_path, src, "matrel_tpu/parallel/newpass.py")
        assert _rules(got) == ["ML004"]

    def test_harness_scripts_exempt(self, tmp_path):
        src = """
            from matrel_tpu.config import MatrelConfig
            cfg = MatrelConfig(obs_level="off")
        """
        assert _lint(tmp_path, src, "tools/new_bench.py") == []
        assert _lint(tmp_path, src, "bench.py") == []

    def test_config_module_itself_exempt(self, tmp_path):
        src = """
            class MatrelConfig:
                pass
            _default = MatrelConfig()
        """
        assert _lint(tmp_path, src, "matrel_tpu/config.py") == []


class TestML005SpecKeyedCache:
    def test_fires_on_spec_keyed_store(self, tmp_path):
        src = """
            _cache = {}
            def put(m, v):
                _cache[m.spec] = v
        """
        got = _lint(tmp_path, src, "matrel_tpu/core/newcache.py")
        assert _rules(got) == ["ML005"]

    def test_fires_on_sharding_ctor_get(self, tmp_path):
        src = """
            from jax.sharding import NamedSharding
            def lookup(memo_tbl, mesh, spec):
                return memo_tbl.get(NamedSharding(mesh, spec))
        """
        got = _lint(tmp_path, src, "matrel_tpu/core/newcache.py")
        assert _rules(got) == ["ML005"]

    def test_stable_tuple_keys_clean(self, tmp_path):
        src = """
            _cache = {}
            def put(n, k, gx, gy, v):
                _cache[(n, k, gx, gy)] = v
        """
        assert _lint(tmp_path, src, "matrel_tpu/core/newcache.py") == []


class TestML005ResultCacheKeying:
    """The serve/ result cache's keying contract (ISSUE 5): entries
    key by the canonical STRUCTURAL plan key. A spec- or sharding-
    keyed variant is exactly the ML005 hazard — the fixture proves the
    rule would catch that regression, and the real module must scan
    clean."""

    def test_spec_keyed_result_cache_fixture_fires(self, tmp_path):
        src = """
            class ResultCache:
                def __init__(self):
                    self._entry_cache = {}
                def put(self, out, v):
                    self._entry_cache[out.sharding] = v
        """
        got = _lint(tmp_path, src,
                    "matrel_tpu/serve/result_cache.py")
        assert _rules(got) == ["ML005"]

    def test_real_result_cache_is_ml005_clean(self):
        import os
        got = matlint.lint_file(
            os.path.join(matlint.REPO, "matrel_tpu", "serve",
                         "result_cache.py"))
        assert [f for f in got if f.rule == "ML005"] == []


class TestML006RawTiming:
    """Raw wall-clock timing in library modules (ISSUE 6): timing
    belongs in spans/StepTimer so the measurement lands in the event
    log where history / the chrome exporter / the drift auditor can
    read it — a bare perf_counter pair dies in a local variable."""

    def test_fires_on_perf_counter(self, tmp_path):
        src = """
            import time
            def run(plan):
                t0 = time.perf_counter()
                out = plan.run()
                dt = time.perf_counter() - t0
                return out, dt
        """
        got = _lint(tmp_path, src, "matrel_tpu/session.py")
        assert _rules(got) == ["ML006"]
        assert len(got) == 2                      # both call sites

    def test_fires_on_time_time_and_bare_import(self, tmp_path):
        src = """
            import time
            from time import perf_counter
            def run():
                a = time.time()
                b = perf_counter()
                return a, b
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/pipeline.py")
        assert _rules(got) == ["ML006"] and len(got) == 2

    def test_obs_and_profiling_and_autotune_exempt(self, tmp_path):
        src = """
            import time
            def measure():
                return time.perf_counter()
        """
        # the sanctioned timing homes: the obs layer itself, the
        # StepTimer module, and the autotune measurement subsystem
        for rel in ("matrel_tpu/obs/trace.py",
                    "matrel_tpu/utils/profiling.py",
                    "matrel_tpu/parallel/autotune.py"):
            assert _lint(tmp_path, src, rel) == []

    def test_out_of_package_ignored(self, tmp_path):
        src = """
            import time
            def bench():
                return time.time()
        """
        # bench harnesses / tools are entry points, not library code
        assert _lint(tmp_path, src, "bench.py") == []

    def test_suppression_with_justification(self, tmp_path):
        src = """
            import time
            def admit(q):
                q.put(time.perf_counter())  # matlint: disable=ML006 queue-wait timestamp
        """
        assert _lint(tmp_path, src, "matrel_tpu/serve/pipeline.py") == []

    def test_unrelated_time_methods_not_flagged(self, tmp_path):
        src = """
            def fmt(dt):
                return dt.time()            # datetime.time(), not timing
        """
        assert _lint(tmp_path, src, "matrel_tpu/io.py") == []


class TestML007BroadSwallow:
    def test_fires_on_except_exception_pass(self, tmp_path):
        src = """
            def load(path):
                try:
                    return open(path).read()
                except Exception:
                    pass
        """
        got = _lint(tmp_path, src, "matrel_tpu/io.py")
        assert _rules(got) == ["ML007"]

    def test_fires_on_bare_except_continue(self, tmp_path):
        src = """
            def drain(items):
                out = []
                for it in items:
                    try:
                        out.append(it())
                    except:
                        continue
                return out
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/x.py")
        assert _rules(got) == ["ML007"]

    def test_fires_on_base_exception_ellipsis(self, tmp_path):
        src = """
            def f(g):
                try:
                    g()
                except BaseException:
                    ...
        """
        got = _lint(tmp_path, src, "matrel_tpu/utils/x.py")
        assert _rules(got) == ["ML007"]

    def test_narrow_except_is_classification(self, tmp_path):
        src = """
            def load(path):
                try:
                    return open(path).read()
                except OSError:
                    pass
        """
        # naming the exception IS the taxonomy — out of scope
        assert _lint(tmp_path, src, "matrel_tpu/io.py") == []

    def test_logging_handler_not_flagged(self, tmp_path):
        src = """
            import logging
            def load(path):
                try:
                    return open(path).read()
                except Exception:
                    logging.warning("unreadable: %s", path)
        """
        assert _lint(tmp_path, src, "matrel_tpu/io.py") == []

    def test_typed_reraise_not_flagged(self, tmp_path):
        src = """
            from matrel_tpu.resilience.errors import CheckpointCorruption
            def load(path):
                try:
                    return open(path).read()
                except Exception as e:
                    raise CheckpointCorruption(str(e)) from e
        """
        assert _lint(tmp_path, src, "matrel_tpu/utils/x.py") == []

    def test_out_of_package_ignored(self, tmp_path):
        src = """
            def probe(f):
                try:
                    f()
                except Exception:
                    pass
        """
        # tools/bench harnesses collect failures their own way
        assert _lint(tmp_path, src, "tools/soak.py") == []

    def test_suppression_with_justification(self, tmp_path):
        src = """
            def emit(fn, rec):
                try:
                    fn(rec)
                except Exception:  # matlint: disable=ML007 never-fail obs sink
                    pass
        """
        assert _lint(tmp_path, src, "matrel_tpu/obs/sink.py") == []


class TestSuppression:
    def test_inline_disable_silences(self, tmp_path):
        src = """
            import jax
            def lower(x):
                jax.block_until_ready(x)  # matlint: disable=ML001 probe path
        """
        assert _lint(tmp_path, src, "matrel_tpu/ops/custom.py") == []

    def test_disable_is_per_code(self, tmp_path):
        src = """
            import jax
            def lower(x):
                jax.block_until_ready(x)  # matlint: disable=ML002 wrong code
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/custom.py")
        assert _rules(got) == ["ML001"]

    def test_unparseable_file_reports(self, tmp_path):
        got = _lint(tmp_path, "def broken(:\n", "matrel_tpu/ops/x.py")
        assert _rules(got) == ["ML000"]


class TestML008DevicePut:
    SRC = """
        import jax
        def relay(x, sh):
            return jax.device_put(x, sh)
    """

    def test_fires_in_lowering_modules(self, tmp_path):
        for rel in ("matrel_tpu/executor.py",
                    "matrel_tpu/ops/custom.py",
                    "matrel_tpu/parallel/planner.py",
                    "matrel_tpu/serve/result_cache.py"):
            got = _lint(tmp_path, self.SRC, rel)
            assert "ML008" in _rules(got), rel

    def test_reshard_module_and_core_exempt(self, tmp_path):
        for rel in ("matrel_tpu/parallel/reshard.py",
                    "matrel_tpu/core/blockmatrix.py",
                    "matrel_tpu/utils/checkpoint.py",
                    "tools/some_harness.py"):
            assert "ML008" not in _rules(_lint(tmp_path, self.SRC,
                                               rel)), rel

    def test_compile_time_eval_sanctioned(self, tmp_path):
        src = """
            import jax
            def place_tables(tables, sh):
                with jax.ensure_compile_time_eval():
                    return [jax.device_put(t, sh) for t in tables]
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/custom.py")
        assert "ML008" not in _rules(got)

    def test_replicated_destination_sanctioned(self, tmp_path):
        src = """
            import jax
            from matrel_tpu.core.mesh import replicated
            def place(x, mesh):
                rep = replicated(mesh)
                a = jax.device_put(x, rep)
                b = jax.device_put(x, replicated(mesh))
                c = jax.device_put(x, device=rep)
                return a, b, c
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/custom.py")
        assert "ML008" not in _rules(got)

    def test_suppression_with_justification(self, tmp_path):
        src = """
            import jax
            def place(x, sh):
                return jax.device_put(x, sh)  # matlint: disable=ML008 host-built kernel table placement
        """
        assert _lint(tmp_path, src, "matrel_tpu/ops/custom.py") == []


class TestML009KernelSeam:
    def test_fires_on_pallas_call_in_ops_module(self, tmp_path):
        src = """
            from jax.experimental import pallas as pl
            def build(kern, spec, shape):
                return pl.pallas_call(kern, grid_spec=spec,
                                      out_shape=shape)
        """
        got = _lint(tmp_path, src, "matrel_tpu/ops/fancy_kernel.py")
        assert _rules(got) == ["ML009"]

    def test_registry_module_is_the_sanctioned_seam(self, tmp_path):
        src = """
            from jax.experimental import pallas as pl
            def build(kern, spec, shape):
                return pl.pallas_call(kern, grid_spec=spec,
                                      out_shape=shape)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/ops/kernel_registry.py") == []

    def test_out_of_scope_modules_ignored(self, tmp_path):
        src = """
            from jax.experimental import pallas as pl
            def probe(kern, shape):
                return pl.pallas_call(kern, out_shape=shape)
        """
        # workloads/tools aren't executor dispatch surface
        assert _lint(tmp_path, src,
                     "matrel_tpu/workloads/pagerank.py") == []
        assert _lint(tmp_path, src, "tools/kernel_probe.py") == []

    def test_suppression_with_justification(self, tmp_path):
        src = """
            from jax.experimental import pallas as pl
            def build(kern, shape):
                return pl.pallas_call(kern, out_shape=shape)  # matlint: disable=ML009 legacy SpMV path unported this round
        """
        assert _lint(tmp_path, src, "matrel_tpu/ops/pallas_spmv.py") \
            == []

    def test_legacy_kernels_carry_justified_suppressions(self):
        # the porting worklist: every pre-registry kernel module lints
        # clean ONLY via its inline ML009 suppressions
        import os
        for mod in ("pallas_spmm.py", "pallas_spmv.py",
                    "spmv_routed.py"):
            path = os.path.join(matlint.REPO, "matrel_tpu", "ops", mod)
            assert "disable=ML009" in open(path).read(), mod
            got = matlint.lint_file(path)
            assert [f for f in got if f.rule == "ML009"] == []


class TestML010JitSeam:
    def test_fires_on_jit_call_in_package(self, tmp_path):
        src = """
            import jax
            def runner(f):
                return jax.jit(f)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/pipeline.py")
        assert _rules(got) == ["ML010"]

    def test_fires_on_jit_decorator(self, tmp_path):
        src = """
            import jax
            @jax.jit
            def step(x):
                return x * 2
        """
        got = _lint(tmp_path, src, "matrel_tpu/workloads/newwl.py")
        assert _rules(got) == ["ML010"]

    def test_executor_is_the_sanctioned_seam(self, tmp_path):
        src = """
            import jax
            def emit(fn):
                return jax.jit(fn)
        """
        assert _lint(tmp_path, src, "matrel_tpu/executor.py") == []

    def test_utils_and_harnesses_out_of_scope(self, tmp_path):
        src = """
            import jax
            @jax.jit
            def probe(x):
                return x + 1
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/utils/profiling.py") == []
        assert _lint(tmp_path, src, "tools/some_probe.py") == []
        assert _lint(tmp_path, src, "bench.py") == []

    def test_suppression_with_justification(self, tmp_path):
        src = """
            import jax
            @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims
            def step(x):
                return x * 2
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/workloads/newwl.py") == []

    def test_existing_sites_carry_justified_suppressions(self):
        # the porting worklist: the pre-seam jit sites lint clean ONLY
        # via their inline ML010 suppressions (the ML009 idiom)
        import os
        for mod in ("workloads/pagerank.py", "workloads/linreg.py",
                    "ops/spmv.py", "parallel/autotune.py",
                    "core/blockmatrix.py"):
            path = os.path.join(matlint.REPO, "matrel_tpu", *mod.split("/"))
            assert "disable=ML010" in open(path).read(), mod
            got = matlint.lint_file(path)
            assert [f for f in got if f.rule == "ML010"] == []


class TestML011UnboundedQueue:
    def test_fires_on_unbounded_deque_in_serve(self, tmp_path):
        src = """
            from collections import deque
            def build():
                q = deque()
                return q
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newqueue.py")
        assert _rules(got) == ["ML011"]

    def test_fires_on_deque_with_iterable_but_no_maxlen(self,
                                                        tmp_path):
        # deque(iterable)'s first positional is the ITERABLE, not a
        # bound — the exact unbounded idiom the rule exists to catch
        src = """
            from collections import deque
            def build(items):
                return deque(items)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newqueue.py")
        assert _rules(got) == ["ML011"]

    def test_fires_on_unbounded_queue_in_serve(self, tmp_path):
        src = """
            import queue
            def build():
                return queue.Queue()
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newqueue.py")
        assert _rules(got) == ["ML011"]

    def test_bounded_forms_pass(self, tmp_path):
        src = """
            import queue
            from collections import deque
            def build(n):
                a = deque(maxlen=n)
                b = deque([1, 2], n)
                c = queue.Queue(maxsize=n)
                d = queue.Queue(n)
                return a, b, c, d
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newqueue.py") == []

    def test_queues_outside_serve_out_of_scope(self, tmp_path):
        # the queue half is contextual: obs rings / host-side tooling
        # aren't on the admission path (the Thread half still applies
        # package-wide — keep the fixture thread-free)
        src = """
            from collections import deque
            def ring():
                return deque()
        """
        assert _lint(tmp_path, src, "matrel_tpu/obs/newring.py") == []
        assert _lint(tmp_path, src, "tools/newtool.py") == []

    def test_fires_on_thread_without_daemon(self, tmp_path):
        src = """
            import threading
            def start(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t
        """
        got = _lint(tmp_path, src, "matrel_tpu/utils/newhelper.py")
        assert _rules(got) == ["ML011"]

    def test_thread_with_daemon_passes(self, tmp_path):
        src = """
            import threading
            def start(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
                return t
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/utils/newhelper.py") == []

    def test_suppression_with_justification(self, tmp_path):
        src = """
            from collections import deque
            def build():
                return deque()  # matlint: disable=ML011 bounded by the typed shed checks in put()
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newqueue.py") == []

    def test_admission_queue_carries_justified_suppressions(self):
        # the sanctioned sites: the AdmissionQueue's per-tenant deques
        # (bounded by typed shed logic, not maxlen — a maxlen deque
        # DROPS silently) and the pipeline's inflight deque (bounded
        # by the serve_max_inflight sync loop)
        import os
        for mod in ("admission.py", "pipeline.py"):
            path = os.path.join(matlint.REPO, "matrel_tpu", "serve",
                                mod)
            assert "disable=ML011" in open(path).read(), mod
            got = matlint.lint_file(path)
            assert [f for f in got if f.rule == "ML011"] == []


class TestML012ResultCacheSeam:
    def test_fires_on_entry_field_store(self, tmp_path):
        src = """
            def poke(ent, bm):
                ent.result = bm
                return ent
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML012"]

    def test_fires_on_augassign_and_del(self, tmp_path):
        src = """
            def poke(ent):
                ent.err_bound += 1.0
                del ent.delta_rule
        """
        got = _lint(tmp_path, src, "matrel_tpu/session_helper.py")
        assert [f.rule for f in got] == ["ML012", "ML012"]

    def test_fires_on_internal_store_access(self, tmp_path):
        src = """
            def sneak(cache, key):
                cache._entries.pop(key, None)
                return cache._stale
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML012"]
        assert len(got) == 2

    def test_replace_and_seam_calls_pass(self, tmp_path):
        # dataclasses.replace builds a NEW entry (the seam inserts
        # it), and the sanctioned seam methods are the whole point
        src = """
            import dataclasses
            def patch(cache, key, new_key, ent, bm, nb):
                new = dataclasses.replace(ent, result=bm, nbytes=nb)
                cache.apply_patch(key, new_key, new, 1 << 20)
                cache.rekey(key, new_key)
                cache.drop(key)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_owning_module_exempt(self, tmp_path):
        src = """
            def inside(self, key):
                self._entries[key] = 1
                self._stale.clear()
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/result_cache.py") == []

    def test_suppression_with_justification(self, tmp_path):
        src = """
            def poke(cache):
                return len(cache._entries)  # matlint: disable=ML012 test-only census helper, lock held by caller
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_ivm_plane_is_seam_clean(self):
        # the delta plane is the rule's raison d'être — it must route
        # every mutation through the seam with ZERO suppressions
        import os
        path = os.path.join(matlint.REPO, "matrel_tpu", "serve",
                            "ivm.py")
        assert "disable=ML012" not in open(path).read()
        got = matlint.lint_file(path)
        assert [f for f in got if f.rule == "ML012"] == []


class TestML013TimingAccumulation:
    def test_fires_on_latency_list_append(self, tmp_path):
        src = """
            def resolve(latencies, ms):
                latencies.append(ms)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML013"]

    def test_fires_on_ms_suffix_attr_and_extend(self, tmp_path):
        src = """
            class W:
                def feed(self, more):
                    self.queue_wait_ms.extend(more)
        """
        got = _lint(tmp_path, src, "matrel_tpu/session_helper.py")
        assert _rules(got) == ["ML013"]

    def test_fires_on_string_subscript_target(self, tmp_path):
        src = """
            def tally(row, ms):
                row["waits"].append(ms)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML013"]

    def test_non_timing_names_pass(self, tmp_path):
        src = """
            def collect(entries, pulled, it):
                entries.append(it)
                pulled.extend(entries)
                items = []
                items.append(it)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_registry_api_passes(self, tmp_path):
        # the sanctioned path: record through the sketch/histogram API
        src = """
            from matrel_tpu.obs.metrics import REGISTRY
            def resolve(ms):
                REGISTRY.histogram("serve.latency_ms").observe(ms)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_obs_package_exempt(self, tmp_path):
        src = """
            def aggregate(waits, ms):
                waits.append(ms)
        """
        assert _lint(tmp_path, src, "matrel_tpu/obs/history.py") == []

    def test_tools_out_of_scope(self, tmp_path):
        # harnesses ARE measurement (the ML006 autotune precedent)
        src = """
            def tally(row, ms):
                row["latencies"].append(ms)
        """
        assert _lint(tmp_path, src, "tools/traffic.py") == []

    def test_suppression_silences(self, tmp_path):
        src = """
            def observe(self, w):
                self._waits.append(w)  # matlint: disable=ML013 bounded controller window
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/resilience/brownout.py") == []


class TestML014FleetSeam:
    def test_fires_on_cross_slice_cache_write(self, tmp_path):
        src = """
            def poke(fleet, key, ent, cfg):
                fleet.slices[0].session._result_cache.put(
                    key, ent, cfg.result_cache_max_bytes)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML014"]

    def test_fires_on_foreign_session_invalidate(self, tmp_path):
        src = """
            def drop_all(other, ids):
                other.session._result_cache.invalidate_deps(ids)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML014"]

    def test_own_cache_mutation_passes(self, tmp_path):
        src = """
            class Plane:
                def insert(self, key, ent, cfg):
                    self.session._result_cache.put(
                        key, ent, cfg.result_cache_max_bytes)
                def drop(self, key):
                    self._result_cache.drop(key)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_sess_alias_passes(self, tmp_path):
        # the IVM plane's idiom: sess = self.session; sess._result_
        # cache.apply_patch(...) — a session mutating its OWN cache
        src = """
            def patch(self, key, new_key, ent, cfg):
                sess = self.session
                ok = sess._result_cache.apply_patch(
                    key, new_key, ent, cfg.result_cache_max_bytes)
                return ok
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_reads_and_lookups_pass(self, tmp_path):
        # the rule pins MUTATION: the fleet's hit-anywhere protocol
        # reads other caches through the public lookup surface
        src = """
            def peek(other, key):
                return other.session._result_cache.lookup(key)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_fleet_module_is_the_sanctioned_seam(self, tmp_path):
        src = """
            def replicate(target, key, ent, cfg):
                target.session._result_cache.put(
                    key, ent, cfg.result_cache_max_bytes)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/fleet.py") == []

    def test_out_of_scope_modules_pass(self, tmp_path):
        src = """
            def poke(fleet, key, ent, cfg):
                fleet.slices[0].session._result_cache.put(
                    key, ent, cfg.result_cache_max_bytes)
        """
        assert _lint(tmp_path, src, "matrel_tpu/obs/whatever.py") == []


class TestML015ProvenanceSeam:
    def test_fires_on_attribute_store(self, tmp_path):
        src = """
            def stamp(ent, key_hash):
                ent.provenance = {"schema": 1, "key_hash": key_hash}
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML015"]

    def test_fires_on_subscript_store(self, tmp_path):
        # the attrs-dict route around the attribute check
        src = """
            def stamp(attrs, rec):
                attrs["provenance"] = {"query_id": rec.query_id}
        """
        got = _lint(tmp_path, src, "matrel_tpu/session.py")
        assert _rules(got) == ["ML015"]

    def test_fires_on_with_attrs_keyword(self, tmp_path):
        # the immutable-expr route: threading a hand-built stamp onto
        # a substitution leaf
        src = """
            def leaf_with_stamp(node, stamp):
                return node.with_attrs(provenance=stamp)
        """
        got = _lint(tmp_path, src, "matrel_tpu/executor.py")
        assert _rules(got) == ["ML015"]

    def test_fires_on_del(self, tmp_path):
        src = """
            def scrub(ent):
                del ent.provenance
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/fleet.py")
        assert _rules(got) == ["ML015"]

    def test_reads_and_calls_pass(self, tmp_path):
        # the sanctioned idiom: modules READ stamps and CALL the
        # ledger's writers; only the ledger builds the dict
        src = """
            def serve(sess, ent, key, parent):
                if ent.provenance is not None:
                    ancestry = ent.provenance.get("query_id")
                sess._prov.stamp_entry(ent, "fleet_replica", parent)
                return ent.provenance
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_ledger_module_is_the_sanctioned_seam(self, tmp_path):
        src = """
            def stamp_entry(ent, path, parent):
                ent.provenance = {"schema": 1, "path": path}
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/obs/provenance.py") == []

    def test_out_of_scope_modules_pass(self, tmp_path):
        # tools/ and tests build fixture stamps freely — the rule pins
        # the library's serve path, not the harnesses around it
        src = """
            def fixture(ent):
                ent.provenance = {"schema": 1}
        """
        assert _lint(tmp_path, src, "tools/some_drill.py") == []


class TestML016TemplateKeying:
    """The MQO plane's keying contract (ISSUE 17): plan-template /
    CSE caches key by the canonical leaf-abstracted structural key
    (mqo.template_key), never id()/uid/spec — the ML005 hazard class
    extended to entries that outlive the queries that built them. The
    fixtures prove the rule would catch each regression shape, and the
    real module must scan clean."""

    def test_id_keyed_template_store_fires(self, tmp_path):
        src = """
            class MqoState:
                def __init__(self):
                    self.templates = {}
                def put(self, root, plan):
                    self.templates[id(root)] = plan
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/mqo.py")
        assert _rules(got) == ["ML016"]

    def test_uid_keyed_hoist_get_fires(self, tmp_path):
        src = """
            def probe(hoist_cache, node):
                return hoist_cache.get(node.uid)
        """
        got = _lint(tmp_path, src, "matrel_tpu/session.py")
        assert _rules(got) == ["ML016"]

    def test_spec_keyed_template_fires(self, tmp_path):
        # spec objects hash by identity or not at all — the original
        # ML005 shape, caught on template-named dicts too
        src = """
            def put(tpl_entries, m, plan):
                tpl_entries[m.spec] = plan
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/mqo.py")
        assert _rules(got) == ["ML016"]

    def test_structural_key_clean(self, tmp_path):
        # the sanctioned idiom: key derived from template_key, a
        # plain string whose equality IS plan equivalence
        src = """
            def put(templates, prefix, akey, entry):
                templates[prefix + akey] = entry
            def probe(templates, key):
                return templates.get(key)
        """
        assert _lint(tmp_path, src, "matrel_tpu/serve/mqo.py") == []

    def test_local_identity_class_map_clean(self, tmp_path):
        # first-occurrence identity classes inside one template_key
        # walk die with the walk — not a cache, not template-named,
        # exactly why the rule scopes by NAME
        src = """
            def template_key(leaves):
                classes = {}
                toks = [classes.setdefault(id(m), len(classes))
                        for m in leaves]
                return toks
        """
        assert _lint(tmp_path, src, "matrel_tpu/serve/mqo.py") == []

    def test_real_mqo_module_is_ml016_clean(self):
        import os
        got = matlint.lint_file(
            os.path.join(matlint.REPO, "matrel_tpu", "serve",
                         "mqo.py"))
        assert [f for f in got if f.rule == "ML016"] == []


class TestML017LockSeam:
    def test_fires_on_bare_lock(self, tmp_path):
        src = """
            import threading
            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newplane.py")
        assert _rules(got) == ["ML017"]

    def test_fires_on_bare_rlock_module_level(self, tmp_path):
        src = """
            from threading import RLock
            _LOCK = RLock()
        """
        got = _lint(tmp_path, src, "matrel_tpu/obs/newobs.py")
        assert _rules(got) == ["ML017"]

    def test_seam_construction_passes(self, tmp_path):
        # the sanctioned idiom: named construction through the seam —
        # the lock lands in lockcheck's inventory and lockdep's graph
        src = """
            from matrel_tpu.utils import lockdep
            class Plane:
                def __init__(self):
                    self._lock = lockdep.make_lock("serve.newplane")
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_condition_and_event_pass(self, tmp_path):
        # only Lock/RLock construction is seamed: Condition wraps an
        # already-seamed lock, Event's internal lock guards no
        # package state
        src = """
            import threading
            from matrel_tpu.utils import lockdep
            class Plane:
                def __init__(self):
                    self._lock = lockdep.make_lock("serve.cvplane")
                    self._cv = threading.Condition(self._lock)
                    self._stop = threading.Event()
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newplane.py") == []

    def test_lockdep_module_is_the_sanctioned_seam(self, tmp_path):
        src = """
            import threading
            _STATE_LOCK = threading.Lock()
            def make_lock(name):
                return threading.Lock()
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/utils/lockdep.py") == []

    def test_out_of_scope_modules_pass(self, tmp_path):
        # tools/tests spin up fixture locks freely — the seam pins the
        # package's lock plane, not the harnesses around it
        src = """
            import threading
            L = threading.Lock()
        """
        assert _lint(tmp_path, src, "tools/some_drill.py") == []

    def test_suppression_silences(self, tmp_path):
        src = """
            import threading
            _LOCK = threading.Lock()  # matlint: disable=ML017 fixture: raw by necessity
        """
        assert _lint(tmp_path, src, "matrel_tpu/obs/newobs.py") == []


class TestML018CoeffSeam:
    def test_fires_on_drift_qualified_call(self, tmp_path):
        src = """
            from matrel_tpu.obs import drift
            def rank(cfg):
                table = drift.load_table(drift.table_path(cfg))
                return table
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newrank.py")
        assert _rules(got) == ["ML018"]

    def test_fires_on_import_from_drift(self, tmp_path):
        src = """
            from matrel_tpu.obs.drift import load_table, table_path
            def rank(cfg):
                return load_table(table_path(cfg))
        """
        got = _lint(tmp_path, src, "matrel_tpu/parallel/newrank.py")
        assert _rules(got) == ["ML018"]

    def test_seam_consult_passes(self, tmp_path):
        # the sanctioned idiom: memoized, epoch-stamped reads through
        # parallel/coeffs.py (table_path/shape_class stay legal — they
        # are addressing, not reads)
        src = """
            from matrel_tpu.obs import drift
            from matrel_tpu.parallel import coeffs
            def rank(cfg, strategy, dims):
                return coeffs.strategy_row(
                    strategy, drift.shape_class(dims), "cpu",
                    drift.table_path(cfg))
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newrank.py") == []

    def test_autotune_table_reader_passes(self, tmp_path):
        # parallel/autotune.py has its own same-named load_table for
        # the AUTOTUNE table — a different store with its own seam;
        # only drift-qualified consults are in ML018's domain
        src = """
            import json
            def load_table(path):
                with open(path) as f:
                    return json.load(f)
            def consult(path):
                return load_table(path)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/parallel/newtune.py") == []

    def test_obs_modules_out_of_scope(self, tmp_path):
        # the auditor/controller plane OWNS the table — obs/ reads and
        # writes it directly by design
        src = """
            from matrel_tpu.obs import drift
            def audit(cfg):
                return drift.load_table(drift.table_path(cfg))
        """
        assert _lint(tmp_path, src, "matrel_tpu/obs/newaudit.py") == []


class TestML019DurableIoSeam:
    def test_fires_on_open_in_serve(self, tmp_path):
        src = """
            def persist(path, payload):
                with open(path, "w") as f:
                    f.write(payload)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newstate.py")
        assert _rules(got) == ["ML019"]

    def test_fires_on_np_save_and_os_replace(self, tmp_path):
        src = """
            import os
            import numpy as np
            def persist(path, arr):
                np.save(path + ".tmp", arr)
                os.replace(path + ".tmp", path)
            def thaw(path):
                return np.load(path)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newcache.py")
        assert [f.rule for f in got] == ["ML019"] * 3

    def test_fires_on_json_dump(self, tmp_path):
        src = """
            import json
            def persist(f, payload):
                json.dump(payload, f)
        """
        got = _lint(tmp_path, src, "matrel_tpu/serve/newmeta.py")
        assert _rules(got) == ["ML019"]

    def test_spill_seam_exempt(self, tmp_path):
        # the sanctioned seam: serve/spill.py IS the one writer
        src = """
            import os
            import numpy as np
            def _write_artifact(path, arr):
                with open(path + ".tmp", "wb") as f:
                    np.save(f, arr)
                os.replace(path + ".tmp", path)
        """
        assert _lint(tmp_path, src, "matrel_tpu/serve/spill.py") == []

    def test_outside_serve_out_of_scope(self, tmp_path):
        # checkpoint/obs/tools keep their own IO discipline — the
        # seam rule scopes to the serving plane only
        src = """
            import json
            def persist(path, payload):
                with open(path, "w") as f:
                    json.dump(payload, f)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/utils/newstore.py") == []

    def test_in_memory_dict_ops_pass(self, tmp_path):
        # same tails, different owners: dict.pop/list ops and
        # non-IO modules' save/load verbs are not in the rule's
        # vocabulary
        src = """
            def evict(cache, key):
                return cache.pop(key, None)
            def save(state, snapshot):
                state.update(snapshot)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/serve/newpolicy.py") == []

    def test_coeffs_module_is_the_sanctioned_seam(self, tmp_path):
        src = """
            from matrel_tpu.obs import drift
            def _payload(path):
                return drift.load_table(path)
        """
        assert _lint(tmp_path, src,
                     "matrel_tpu/parallel/coeffs.py") == []


def test_repo_lints_clean():
    """`make lint`'s contract, enforced from inside tier-1: the whole
    default scan set (package, tools, examples, bench harnesses) has
    zero unsuppressed findings."""
    findings = matlint.lint_paths()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_rule_catalogue_documented():
    # every rule carries an ID and a docstring (the catalogue the docs
    # and --list-rules render); IDs are unique
    ids = [r.id for r in matlint.RULES]
    assert len(ids) == len(set(ids))
    for r in matlint.RULES:
        assert r.id.startswith("ML") and r.__doc__
        assert r.id in r.__doc__.strip().splitlines()[0]
