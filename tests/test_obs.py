"""Query-lifecycle observability (matrel_tpu/obs/) — registry, event
log, explain(analyze=True) and the obs_level="off" zero-overhead
contract the bench relies on."""

import json
import os
import threading

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.obs.events import (EventLog, SCHEMA_VERSION, iter_events,
                                   read_events)
from matrel_tpu.obs.metrics import MetricsRegistry
from matrel_tpu.session import MatrelSession


@pytest.fixture
def chain3(mesh8, rng):
    """The 3-matrix chain demo shape: (64x96)(96x128)(128x32)."""
    A = BlockMatrix.from_numpy(
        rng.standard_normal((64, 96)).astype(np.float32), mesh=mesh8)
    B = BlockMatrix.from_numpy(
        rng.standard_normal((96, 128)).astype(np.float32), mesh=mesh8)
    C = BlockMatrix.from_numpy(
        rng.standard_normal((128, 32)).astype(np.float32), mesh=mesh8)
    return A.expr() @ B.expr() @ C.expr()


def _session(mesh, tmp_path, level="on", **cfg):
    return MatrelSession(mesh=mesh, config=MatrelConfig(
        obs_level=level,
        obs_event_log=str(tmp_path / "events.jsonl"), **cfg))


class TestMetricsRegistry:
    def test_counter_semantics(self):
        reg = MetricsRegistry()
        c = reg.counter("plan_cache.hit")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        # same name → same counter; distinct names are independent
        assert reg.counter("plan_cache.hit") is c
        assert reg.counter("plan_cache.miss").value == 0.0

    def test_gauge_last_write_wins(self):
        reg = MetricsRegistry()
        g = reg.gauge("plan_cache.plans")
        g.set(3)
        g.set(1)
        assert g.value == 1.0

    def test_histogram_semantics(self):
        reg = MetricsRegistry()
        h = reg.histogram("query.execute_ms")
        for v in (4.0, 1.0, 3.0, 2.0):
            h.observe(v)
        assert h.count == 4
        assert h.total == 10.0
        assert h.mean == 2.5
        assert (h.min, h.max) == (1.0, 4.0)
        assert h.percentile(0.0) == 1.0
        assert h.percentile(1.0) == 4.0
        s = h.summary()
        assert s["count"] == 4 and s["mean"] == 2.5

    def test_histogram_sketch_bounded(self):
        # the bounded-memory contract moved from a sample reservoir
        # to the quantile sketch: bucket count stays capped no matter
        # how many observations (or how wide their range), all-time
        # count/min/max stay exact
        from matrel_tpu.obs import metrics as m
        reg = MetricsRegistry()
        h = reg.histogram("x")
        n = 3 * m._MAX_BUCKETS
        for v in range(n):
            h.observe(float(v) * 1e3 + 0.5)
        assert h.count == n                          # all-time stats kept
        assert len(h._sketch._buckets) <= m._MAX_BUCKETS
        assert h.max == float(n - 1) * 1e3 + 0.5

    def test_snapshot_and_reset(self):
        reg = MetricsRegistry()
        reg.counter("a").inc(2)
        reg.gauge("b").set(7)
        reg.histogram("c").observe(1.5)
        snap = reg.snapshot()
        assert snap["counters"]["a"] == 2.0
        assert snap["gauges"]["b"] == 7.0
        assert snap["histograms"]["c"]["count"] == 1
        json.dumps(snap)                            # JSON-ready contract
        reg.reset()
        assert reg.snapshot() == {"counters": {}, "gauges": {},
                                  "histograms": {}}

    def test_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("n")
        h = reg.histogram("h")

        def work():
            for _ in range(1000):
                c.inc()
                h.observe(1.0)

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == 8000
        assert h.count == 8000 and h.total == 8000.0


class TestEventLog:
    def test_jsonl_round_trip(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(path)
        written = log.emit("query", {"query_id": "q1", "execute_ms": 1.25,
                                     "out_shape": [4, 4]})
        assert written["schema"] == SCHEMA_VERSION
        assert written["kind"] == "query" and "ts" in written
        [back] = read_events(path)
        assert back == json.loads(json.dumps(written))

    def test_numpy_values_serialise(self, tmp_path):
        log = EventLog(str(tmp_path / "ev.jsonl"))
        log.emit("query", {"nnz": np.int64(7), "ms": np.float32(1.5),
                           "shape": np.array([2, 3])})
        [rec] = read_events(log.path)
        assert rec["nnz"] == 7 and rec["shape"] == [2, 3]

    def test_reader_skips_garbage_and_foreign_schema(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        EventLog(path).emit("query", {"query_id": "q1"})
        with open(path, "a") as f:
            f.write("{truncated mid-cra\n")               # crashed writer
            f.write(json.dumps({"schema": SCHEMA_VERSION + 99,
                                "kind": "query"}) + "\n")  # future schema
            f.write("[1, 2]\n")                            # non-record
        recs = read_events(path)
        assert len(recs) == 1 and recs[0]["query_id"] == "q1"

    def test_missing_file_reads_empty(self, tmp_path):
        assert read_events(str(tmp_path / "nope.jsonl")) == []
        assert list(iter_events(str(tmp_path / "nope.jsonl"))) == []

    def test_emit_never_raises(self, tmp_path):
        log = EventLog(str(tmp_path / "no" / "such" / "dir" / "ev.jsonl"))
        assert log.emit("query", {"query_id": "q1"}) is None   # swallowed

    def test_emit_tool_event_path_resolution(self, tmp_path,
                                             monkeypatch):
        from matrel_tpu.obs.events import emit_tool_event
        # env var wins
        envlog = str(tmp_path / "env.jsonl")
        monkeypatch.setenv("MATREL_OBS_EVENT_LOG", envlog)
        emit_tool_event("bench", {"value": 1.0},
                        anchor_dir=str(tmp_path / "anchor"))
        assert len(read_events(envlog)) == 1
        # else the default name anchored at anchor_dir
        monkeypatch.delenv("MATREL_OBS_EVENT_LOG")
        (tmp_path / "anchor").mkdir()
        emit_tool_event("soak", {"ok": True},
                        anchor_dir=str(tmp_path / "anchor"))
        [rec] = read_events(str(tmp_path / "anchor"
                                / ".matrel_events.jsonl"))
        assert rec["kind"] == "soak"


class TestEventLogRotation:
    """obs_event_log_max_bytes: single-``.1``-sibling rotation with
    transparent reader stitching; 0 (the default) keeps the historical
    unbounded append byte-for-byte."""

    def _emit_n(self, log, n, start=0):
        for i in range(start, start + n):
            log.emit("query", {"seq": i})

    def test_off_path_never_rotates(self, tmp_path):
        from matrel_tpu.obs.events import rotated_path
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(path)               # max_bytes=0: historical
        self._emit_n(log, 50)
        assert not os.path.exists(rotated_path(path))
        recs = read_events(path)
        assert [r["seq"] for r in recs] == list(range(50))
        # byte-identical off-path: exactly one line per record, no
        # truncation, no sibling — the pre-rotation file shape
        with open(path) as f:
            assert sum(1 for _ in f) == 50

    def test_rotates_to_single_sibling_and_readers_stitch(
            self, tmp_path):
        from matrel_tpu.obs.events import rotated_path
        path = str(tmp_path / "ev.jsonl")
        probe = EventLog(path)
        probe.emit("query", {"seq": -1})
        line_sz = os.path.getsize(path)
        os.remove(path)
        # threshold = ~8 lines: one crossing over a 12-record stream
        log = EventLog(path, max_bytes=8 * line_sz)
        self._emit_n(log, 12)
        assert os.path.exists(rotated_path(path))
        # the pair stitches oldest-first into one continuous history
        recs = read_events(path)
        assert [r["seq"] for r in recs] == list(range(12))
        # and iter_events yields the same order
        assert [r["seq"] for r in iter_events(path)] == list(range(12))

    def test_rotation_bounds_disk_at_two_files(self, tmp_path):
        from matrel_tpu.obs.events import rotated_path
        path = str(tmp_path / "ev.jsonl")
        probe = EventLog(path)
        probe.emit("query", {"seq": -1})
        line_sz = os.path.getsize(path)
        os.remove(path)
        log = EventLog(path, max_bytes=4 * line_sz)
        self._emit_n(log, 40)              # many crossings
        # a crossing rotates the main file away; the next emit
        # recreates it — either way disk stays ~2x the threshold
        main_sz = os.path.getsize(path) if os.path.exists(path) else 0
        assert main_sz <= 5 * line_sz
        assert os.path.getsize(rotated_path(path)) <= 5 * line_sz
        # the history window is the newest suffix, ending at the last
        # record — rotation REPLACES the sibling, never accumulates
        seqs = [r["seq"] for r in read_events(path)]
        assert seqs == list(range(seqs[0], 40))
        assert not os.path.exists(path + ".2")

    def test_tail_bytes_spans_both_files(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        probe = EventLog(path)
        probe.emit("query", {"seq": -1})
        line_sz = os.path.getsize(path)
        os.remove(path)
        log = EventLog(path, max_bytes=8 * line_sz)
        self._emit_n(log, 10)              # .1 holds 0..7, main 8..9
        # a tail budget bigger than the main file reaches into the
        # sibling's tail (its cut-off first line dropped, not corrupt)
        recs = read_events(path, tail_bytes=5 * line_sz + 10)
        seqs = [r["seq"] for r in recs]
        assert seqs == seqs and seqs[-1] == 9
        assert 2 <= len(seqs) <= 6
        assert seqs == list(range(10 - len(seqs), 10))
        # a budget inside the main file never opens the sibling
        recs = read_events(path, tail_bytes=line_sz + 5)
        assert [r["seq"] for r in recs] == [9]

    def test_rotate_mid_read_never_raises(self, tmp_path):
        # the reader's stat/open race: the main file rotates away
        # between the size probe and the open — the reader continues
        # with what it can open, never raises
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(path)
        self._emit_n(log, 6)
        real_open = open

        def racing_open(fpath, *a, **kw):
            if fpath == path and os.path.exists(path):
                os.replace(path, path + ".1")  # rotation wins the race
            return real_open(fpath, *a, **kw)

        import builtins
        orig = builtins.open
        builtins.open = racing_open
        try:
            recs = list(iter_events(path))
        finally:
            builtins.open = orig
        # .1 was read before the race hit the main file; nothing lost
        assert [r["seq"] for r in recs] == list(range(6))

    def test_many_writers_interleave_whole_lines(self, tmp_path,
                                                 caplog):
        # O_APPEND + one write() per record: 8 writers x 200 records
        # on one path produce 1600 parseable lines and ZERO corrupt-
        # line warnings from the reader
        path = str(tmp_path / "ev.jsonl")

        def work(w):
            log = EventLog(path)
            for i in range(200):
                log.emit("query", {"w": w, "i": i})

        ts = [threading.Thread(target=work, args=(w,))
              for w in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        with caplog.at_level("WARNING", logger="matrel_tpu.obs"):
            recs = read_events(path)
        assert len(recs) == 1600
        per_writer = {}
        for r in recs:
            per_writer.setdefault(r["w"], []).append(r["i"])
        # every writer's records all landed, in ITS OWN order
        assert all(v == list(range(200))
                   for v in per_writer.values())
        assert not [m for m in caplog.messages if "corrupt" in m]

    def test_torn_line_counted_and_warned(self, tmp_path, caplog):
        # a crashed writer's partial line: the reader skips it,
        # COUNTS it, and warns once (the robust-reader contract) —
        # same across the rotation pair
        from matrel_tpu.obs.events import rotated_path
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(path)
        self._emit_n(log, 2)
        with open(rotated_path(path), "w") as f:
            f.write('{"schema": 1, "kind": "query", "seq": -2}\n')
            f.write('{"torn mid-wri\n')
        with caplog.at_level("WARNING", logger="matrel_tpu.obs"):
            recs = read_events(path)
        assert [r["seq"] for r in recs] == [-2, 0, 1]
        assert any("1 corrupt line" in m for m in caplog.messages)

    def test_session_knob_flows_and_log_rebuilds(self, mesh8,
                                                 tmp_path, chain3):
        from matrel_tpu.obs.events import rotated_path
        sess = _session(mesh8, tmp_path, obs_event_log_max_bytes=600)
        for _ in range(6):
            sess.run(chain3)
        path = str(tmp_path / "events.jsonl")
        assert os.path.exists(rotated_path(path))
        # the readers (history et al. route through read_events) see
        # a continuous stitched history ending at the newest record
        recs = read_events(path)
        assert any(r["kind"] == "query" for r in recs)
        # flipping the knob rebuilds the session's writer
        sess.config = sess.config.replace(obs_event_log_max_bytes=0)
        assert sess._obs_event_log().max_bytes == 0


class TestSessionEvents:
    def test_one_record_per_run_with_cache_outcomes(self, mesh8, tmp_path,
                                                    chain3):
        sess = _session(mesh8, tmp_path)
        sess.run(chain3)
        sess.run(chain3)
        recs = read_events(sess.config.obs_event_log,
                           kinds=("query",))
        assert len(recs) == 2                  # exactly one per run
        first, second = recs
        assert first["cache"] == "miss" and second["cache"] == "hit"
        assert first["query_id"] != second["query_id"]
        for r in recs:
            # the documented schema (docs/OBSERVABILITY.md)
            assert r["schema"] == SCHEMA_VERSION and r["kind"] == "query"
            assert r["source"] == "dsl"
            assert r["out_shape"] == [64, 32]
            assert isinstance(r["execute_ms"], (int, float))
            assert isinstance(r["matmuls"], list) and len(r["matmuls"]) == 2
            for d in r["matmuls"]:
                assert {"uid", "strategy", "source", "flops",
                        "dims"} <= set(d)
            assert "plans" in r["plan_cache"]
        # compile-time fields come from the plan meta (shared by both)
        assert isinstance(first["optimize_ms"], (int, float))
        assert first["first_execution"] is True
        assert second["first_execution"] is False

    def test_metrics_registry_updated(self, mesh8, tmp_path, chain3):
        from matrel_tpu.obs.metrics import REGISTRY
        REGISTRY.reset()
        sess = _session(mesh8, tmp_path)
        sess.run(chain3)
        sess.run(chain3)
        snap = REGISTRY.snapshot()
        assert snap["counters"]["query.count"] == 2
        assert snap["counters"]["plan_cache.miss"] == 1
        assert snap["counters"]["plan_cache.hit"] == 1
        assert snap["histograms"]["query.execute_ms"]["count"] == 2
        REGISTRY.reset()

    def test_chain_dp_not_counted_for_plain_matmul(self, mesh8, rng):
        # reorder_chains rebuilds matmul nodes even when it keeps the
        # parenthesisation — a plain 2-operand matmul must not count as
        # a chain_dp restructure
        from matrel_tpu.ir import rules
        a = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        b = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        counts = {}
        rules.optimize(a.expr() @ b.expr(), counts=counts)
        assert "chain_dp" not in counts

    def test_rule_hits_compile_scoped(self, mesh8, tmp_path, chain3):
        """Hit records carry {} rule_hits (rules fired once, at
        compile), so history's roll-up counts real optimizer work."""
        from matrel_tpu.obs.metrics import REGISTRY
        REGISTRY.reset()
        sess = _session(mesh8, tmp_path)
        sess.run(chain3)
        sess.run(chain3)
        miss, hit = read_events(sess.config.obs_event_log,
                                kinds=("query",))
        assert miss["rule_hits"].get("chain_dp") == 1
        assert hit["rule_hits"] == {}
        assert REGISTRY.snapshot()["counters"]["optimizer.rule.chain_dp"] \
            == 1
        REGISTRY.reset()

    def test_scalar_sql_still_returns_plain_number(self, mesh8,
                                                   tmp_path):
        # the _sql_hash stamp must not break scalar-only queries, which
        # compile to a plain float rather than a MatExpr
        sess = _session(mesh8, tmp_path)
        assert sess.sql("2 * 3") == 6.0

    def test_sql_source_hash(self, mesh8, tmp_path, rng):
        sess = _session(mesh8, tmp_path)
        a = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
        sess.register("A", a)
        sess.run(sess.sql("SELECT A * A FROM A"))
        [rec] = read_events(sess.config.obs_event_log,
                            kinds=("query",))
        assert rec["source"] == "sql"
        assert len(rec["source_hash"]) == 16

    def test_eviction_counted(self, mesh8, tmp_path, rng):
        sess = _session(mesh8, tmp_path, plan_cache_max_plans=2)
        for _ in range(4):
            m = BlockMatrix.from_numpy(
                rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
            sess.run(m.expr().t())
        recs = read_events(sess.config.obs_event_log,
                           kinds=("query",))
        assert recs[-1]["plan_cache"]["evicted"] == 2
        assert sess.plan_cache_info()["evicted"] == 2


class TestExplainAnalyze:
    def test_one_timed_row_per_physical_op(self, mesh8, tmp_path, chain3):
        sess = _session(mesh8, tmp_path)
        text = sess.explain(chain3, analyze=True)
        assert "== Analyzed physical plan" in text
        plan = sess.compile(chain3)

        def uids(n, acc):
            acc.add(n.uid)
            for c in n.children:
                uids(c, acc)
            return acc

        n_ops = len(uids(plan.optimized, set()))
        analyzed = text.split("== Analyzed physical plan")[1]
        assert analyzed.count(" ms]") == n_ops
        # the chain demo acceptance surface: strategy + estimated bytes
        # on every matmul row, and the fused-program line
        matmul_rows = [ln for ln in analyzed.splitlines()
                       if ln.lstrip().startswith("matmul")]
        assert len(matmul_rows) == 2
        for row in matmul_rows:
            assert "strategy=" in row and "est_ici=" in row
        assert "fused program:" in analyzed

    def test_per_op_times_are_exclusive(self, mesh8, tmp_path, chain3):
        """ev() recurses through _eval, so naive timing would report
        each parent inclusive of its children (~depth x the real
        runtime when summed); the hook must subtract child frames."""
        from matrel_tpu.obs.analyze import measure_per_op
        sess = _session(mesh8, tmp_path)
        plan = sess.compile(chain3)
        per_op, eager_total = measure_per_op(plan)
        total = sum(s for _, s in per_op.values())
        # exclusive times sum to at most the whole eager run (plus a
        # little hook overhead); inclusive times would sum to ~2x+ on
        # this depth-3 tree
        assert total <= eager_total * 1.1 + 0.05

    def test_analyze_requires_physical(self, mesh8, tmp_path, chain3):
        sess = _session(mesh8, tmp_path)
        with pytest.raises(ValueError, match="physical"):
            sess.explain(chain3, physical=False, analyze=True)

    def test_explain_sql_analyze(self, mesh8, tmp_path, rng):
        sess = _session(mesh8, tmp_path)
        a = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
        sess.register("A", a)
        text = sess.explain_sql("SELECT A * A FROM A", analyze=True)
        assert "== Analyzed physical plan" in text and " ms]" in text


class TestObsOffContract:
    """obs_level="off" (the bench default): zero events, zero extra
    syncs on the query path."""

    def test_no_events_no_syncs(self, mesh8, tmp_path, chain3,
                                monkeypatch):
        import jax
        emits = []
        monkeypatch.setattr(EventLog, "emit",
                            lambda self, *a, **k: emits.append(a))
        syncs = []
        real_sync = jax.block_until_ready
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda x: (syncs.append(1), real_sync(x))[1])
        sess = _session(mesh8, tmp_path, level="off")
        out = sess.run(chain3)
        assert out.shape == (64, 32)
        assert emits == []                      # zero events
        assert syncs == []                      # zero per-op syncs
        assert not (tmp_path / "events.jsonl").exists()

    def test_default_config_is_off(self):
        assert MatrelConfig().obs_level == "off"

    def test_obs_level_validated_and_normalised(self):
        # "OFF" must not silently enable instrumentation
        assert MatrelConfig(obs_level="OFF").obs_level == "off"
        assert MatrelConfig(obs_level="Analyze").obs_level == "analyze"
        with pytest.raises(ValueError, match="obs_level"):
            MatrelConfig(obs_level="of")


class TestHistory:
    def _seed_log(self, tmp_path):
        log = EventLog(str(tmp_path / "ev.jsonl"))
        for i, cache in enumerate(["miss", "hit", "hit"]):
            log.emit("query", {
                "query_id": f"q{i}", "source": "dsl", "cache": cache,
                "optimize_ms": 4.0, "execute_ms": 10.0,
                "out_shape": [4, 4],
                "rule_hits": {"fold_transpose": 1},
                "plan_cache": {"plans": 1, "evicted": 0},
                "matmuls": [{"uid": 1, "strategy": "rmm",
                             "flops": 1e9, "est_ici_bytes": 2.0 ** 20}]})
        log.emit("bench", {"value": 100.0})
        log.emit("soak", {"ok": True})
        return log.path

    def test_summarize(self, tmp_path):
        from matrel_tpu.obs.history import summarize
        s = summarize(read_events(self._seed_log(tmp_path)))
        assert s["queries"] == 3
        assert s["cache_hit_rate"] == pytest.approx(2 / 3, abs=1e-3)
        assert s["execute_ms_total"] == 30.0
        assert s["strategies"]["rmm"]["count"] == 3
        assert s["rule_hits"]["fold_transpose"] == 3
        assert s["bench_runs"] == 1 and s["soak_runs"] == 1

    def test_render_tables(self, tmp_path):
        from matrel_tpu.obs.history import render_queries, render_summary
        events = read_events(self._seed_log(tmp_path))
        table = render_queries(events, last=2)
        assert "q1" in table and "q2" in table and "q0" not in table
        summary = render_summary(events)
        assert "cache hit rate: 0.667" in summary
        assert "rmm" in summary

    def test_cli(self, tmp_path, capsys):
        import subprocess
        import sys
        path = self._seed_log(tmp_path)
        out = subprocess.run(
            [sys.executable, "-m", "matrel_tpu", "history", "--summary",
             "--log", path],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0
        assert "cache hit rate" in out.stdout


class TestInstrumentationGuard:
    def test_every_lowering_dispatch_is_annotated(self):
        """Structural check: each `self._eval(` dispatch call site in
        executor.py sits inside a `with annotate(` block, so a new op
        path can't silently skip the per-op scope/timing hook. Two
        sanctioned exceptions, both DELIBERATELY single-frame: the
        fused-region member sites (one `with annotate("matrel.fused:…")`
        frame covers the whole member set — that per-edge frame
        collapse IS the fusion design, docs/FUSION.md) and the
        unit-program seam (jitted region emission for the bench/
        autotune measurement harness) — each must say so inline."""
        import inspect
        from matrel_tpu import executor
        lines = inspect.getsource(executor).splitlines()
        sites = [i for i, ln in enumerate(lines)
                 if "self._eval(" in ln and "def _eval" not in ln]
        assert sites, "executor lost its central _eval dispatch"
        exempt = ("fused-region member", "unit-program member")
        for i in sites:
            if any(tag in lines[i] for tag in exempt):
                continue
            window = "\n".join(lines[max(0, i - 5):i])
            assert "with annotate(" in window, (
                f"executor.py line {i + 1}: lowering dispatch not "
                f"wrapped in annotate()")

    def test_bench_emits_bench_event(self, tmp_path, monkeypatch):
        """bench.py main() appends a `bench` record to the shared log."""
        import bench
        path = str(tmp_path / "ev.jsonl")
        monkeypatch.setenv("MATREL_OBS_EVENT_LOG", path)
        bench._emit_bench_event({"value": 1.23, "phases": {"setup_s": 0.1}})
        [rec] = read_events(path)
        assert rec["kind"] == "bench" and rec["value"] == 1.23

    def test_bench_event_emission_stays_jax_free(self, tmp_path):
        """The bench parent is deliberately backend-free (relay-wedge
        safety): emitting its obs event must not import jax."""
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, MATREL_OBS_EVENT_LOG=str(
            tmp_path / "ev.jsonl"))
        out = subprocess.run(
            [sys.executable, "-c",
             "import sys, bench; "
             "bench._emit_bench_event({'value': 1.0}); "
             "print('jax' in sys.modules)"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=repo)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "False"
        [rec] = read_events(str(tmp_path / "ev.jsonl"))
        assert rec["kind"] == "bench"


class TestTracingSpans:
    """Round 9 tentpole: parent-linked span records through admission →
    plan → verify → trace → execute, in the same schema-versioned log."""

    def test_query_spans_with_parent_links(self, mesh8, tmp_path,
                                           chain3):
        sess = _session(mesh8, tmp_path)
        sess.run(chain3)
        spans = [e for e in read_events(sess.config.obs_event_log)
                 if e["kind"] == "span"]
        names = {s["name"] for s in spans}
        assert {"query", "plan", "plan.optimize", "plan.verify",
                "plan.trace", "query.execute"} <= names
        by_id = {s["span_id"]: s for s in spans}
        # every compile phase parent-links (transitively) to the query
        # root span — the chrome exporter's nesting source of truth
        root = next(s for s in spans if s["name"] == "query")
        assert root["parent_id"] is None
        for name in ("plan.optimize", "query.execute"):
            s = next(x for x in spans if x["name"] == name)
            seen = set()
            while s["parent_id"] is not None:
                assert s["parent_id"] in by_id
                assert s["span_id"] not in seen
                seen.add(s["span_id"])
                s = by_id[s["parent_id"]]
            assert s["name"] == "query"
        for s in spans:
            assert s["schema"] == SCHEMA_VERSION
            assert isinstance(s["dur_ms"], (int, float))
            assert isinstance(s["t0"], (int, float))

    def test_serve_batch_spans(self, mesh8, tmp_path, chain3, rng):
        sess = _session(mesh8, tmp_path)
        a = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        sess.run_many([chain3, a.expr().t(), a.expr()])
        spans = [e for e in read_events(sess.config.obs_event_log)
                 if e["kind"] == "span"]
        batch = next(s for s in spans if s["name"] == "serve.batch")
        assert batch["attrs"]["size"] == 3
        execute = next(s for s in spans if s["name"] == "serve.execute")
        # execute nests under the batch (possibly through "plan")
        by_id = {s["span_id"]: s for s in spans}
        p = execute
        while p["parent_id"] is not None:
            p = by_id[p["parent_id"]]
        assert p["span_id"] == batch["span_id"]

    def test_chrome_export_round_trip(self, mesh8, tmp_path, chain3):
        from matrel_tpu.obs.trace import chrome_trace
        sess = _session(mesh8, tmp_path)
        sess.run_many([chain3])
        events = read_events(sess.config.obs_event_log)
        doc = json.loads(json.dumps(chrome_trace(events)))
        assert doc["traceEvents"]
        ids = set()
        for ev in doc["traceEvents"]:
            assert ev["ph"] == "X"
            assert ev["dur"] >= 0 and ev["ts"] > 0
            assert {"pid", "tid", "name", "args"} <= set(ev)
            ids.add(ev["args"]["span_id"])
        # parent links survive the export (the Perfetto args payload)
        assert any(ev["args"].get("parent_id") in ids
                   for ev in doc["traceEvents"])

    def test_chrome_export_last_filters_roots(self, tmp_path):
        from matrel_tpu.obs.trace import chrome_trace
        log = EventLog(str(tmp_path / "sp.jsonl"))
        for root in (1, 4):
            log.emit("span", {"name": "query", "span_id": root,
                              "parent_id": None, "t0": 100.0 + root,
                              "dur_ms": 5.0, "pid": 1, "tid": 1})
            log.emit("span", {"name": "plan", "span_id": root + 1,
                              "parent_id": root, "t0": 100.0 + root,
                              "dur_ms": 2.0, "pid": 1, "tid": 1})
        doc = chrome_trace(read_events(log.path), last=1)
        got = {ev["args"]["span_id"] for ev in doc["traceEvents"]}
        assert got == {4, 5}            # last root + its child only

    def test_chrome_export_last_keys_by_pid(self, tmp_path):
        """Span-id sequences restart per PROCESS; a shared log mixes
        pids by design, so the --last closure must never pull an
        unrelated process's identically-numbered spans."""
        from matrel_tpu.obs.trace import chrome_trace
        log = EventLog(str(tmp_path / "sp.jsonl"))
        for pid, t0 in ((111, 100.0), (222, 200.0)):
            log.emit("span", {"name": "query", "span_id": 1,
                              "parent_id": None, "t0": t0,
                              "dur_ms": 5.0, "pid": pid, "tid": 1})
            log.emit("span", {"name": "plan", "span_id": 2,
                              "parent_id": 1, "t0": t0,
                              "dur_ms": 2.0, "pid": pid, "tid": 1})
        doc = chrome_trace(read_events(log.path), last=1)
        assert {ev["pid"] for ev in doc["traceEvents"]} == {222}
        assert len(doc["traceEvents"]) == 2

    def test_trace_cli(self, mesh8, tmp_path, chain3):
        import subprocess
        import sys
        sess = _session(mesh8, tmp_path)
        sess.run(chain3)
        out_path = str(tmp_path / "trace.chrome.json")
        out = subprocess.run(
            [sys.executable, "-m", "matrel_tpu", "trace", "--export",
             "chrome", "--log", sess.config.obs_event_log,
             "--out", out_path],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        status = json.loads(out.stdout.strip().splitlines()[-1])
        assert status["spans"] > 0
        with open(out_path) as f:
            doc = json.load(f)
        assert len(doc["traceEvents"]) == status["spans"]


class TestFlightRecorder:
    """The always-cheap post-mortem ring: independent of obs_level,
    dumped on failures or on demand."""

    def test_records_spans_with_obs_off(self, mesh8, tmp_path, chain3):
        sess = _session(mesh8, tmp_path, level="off",
                        obs_flight_recorder=64,
                        obs_flight_recorder_path=str(
                            tmp_path / "flight.json"))
        sess.run(chain3)
        # no event log (obs off) — but the ring holds the span trail
        assert not (tmp_path / "events.jsonl").exists()
        assert len(sess._flight) > 0
        names = {r["name"] for r in sess._flight.snapshot()
                 if r.get("kind") == "span"}
        assert {"query", "plan.optimize", "query.execute"} <= names

    def test_ring_is_bounded(self, mesh8, tmp_path, chain3):
        sess = _session(mesh8, tmp_path, level="off",
                        obs_flight_recorder=4)
        for _ in range(3):
            sess.run(chain3)
        assert len(sess._flight) == 4          # last N only

    def test_explicit_dump_round_trip(self, mesh8, tmp_path, chain3):
        sess = _session(mesh8, tmp_path, obs_flight_recorder=64,
                        obs_flight_recorder_path=str(
                            tmp_path / "flight.json"))
        sess.run(chain3)
        p = sess.dump_flight_recorder()
        assert p == str(tmp_path / "flight.json")
        with open(p) as f:
            art = json.load(f)
        assert art["schema"] == SCHEMA_VERSION
        assert art["kind"] == "flight_recorder"
        assert art["reason"] == "explicit"
        assert art["capacity"] == 64
        kinds = {r.get("kind") for r in art["records"]}
        assert "span" in kinds and "query" in kinds  # obs on: both flow

    def test_dump_disabled_returns_none(self, mesh8, tmp_path, chain3):
        sess = _session(mesh8, tmp_path)       # recorder off (default)
        sess.run(chain3)
        assert sess._flight is None
        assert sess.dump_flight_recorder() is None

    def test_dump_on_compile_failure(self, mesh8, tmp_path, chain3,
                                     monkeypatch):
        from matrel_tpu import executor as executor_lib
        sess = _session(mesh8, tmp_path, obs_flight_recorder=64,
                        obs_flight_recorder_path=str(
                            tmp_path / "flight.json"))
        sess.run(chain3)                       # populate the ring

        def boom(*a, **k):
            raise RuntimeError("lowering exploded")

        monkeypatch.setattr(executor_lib, "compile_expr", boom)
        with pytest.raises(RuntimeError, match="lowering exploded"):
            sess.run(chain3.t())               # distinct key → compile
        with open(tmp_path / "flight.json") as f:
            art = json.load(f)
        assert art["reason"] == "compile_failure"
        assert "lowering exploded" in art["error"]
        assert art["records"]                  # the trail, not a bare
                                               # error string

    def test_dump_on_verification_error(self, mesh8, tmp_path, chain3,
                                        monkeypatch):
        from matrel_tpu import executor as executor_lib
        from matrel_tpu.analysis import VerificationError
        sess = _session(mesh8, tmp_path, obs_flight_recorder=64,
                        obs_flight_recorder_path=str(
                            tmp_path / "flight.json"))

        def boom(*a, **k):
            raise VerificationError([])

        monkeypatch.setattr(executor_lib, "compile_expr", boom)
        with pytest.raises(VerificationError):
            sess.run(chain3)
        with open(tmp_path / "flight.json") as f:
            art = json.load(f)
        assert art["reason"] == "verification_error"

    def test_dump_on_serve_batch_failure(self, mesh8, tmp_path, chain3,
                                         monkeypatch):
        from matrel_tpu import executor as executor_lib
        sess = _session(mesh8, tmp_path, obs_flight_recorder=64,
                        obs_flight_recorder_path=str(
                            tmp_path / "flight.json"))

        def boom(*a, **k):
            raise RuntimeError("batch compile died")

        monkeypatch.setattr(executor_lib, "compile_exprs", boom)
        fut = sess.submit(chain3)
        with pytest.raises(RuntimeError, match="batch compile died"):
            fut.result(timeout=30)
        sess.serve_drain()
        with open(tmp_path / "flight.json") as f:
            art = json.load(f)
        assert art["reason"] == "serve_batch_failure"


class TestObsOffServePath:
    """obs_level="off" + flight recorder off on the serve repeated-
    traffic path: zero events, zero span OBJECTS (the structural twin
    of TestObsOffContract's zero-sync guard — PR 5's QPS must not pay
    for tier 2)."""

    def test_repeated_serve_path_creates_no_spans(self, mesh8, tmp_path,
                                                  chain3, rng,
                                                  monkeypatch):
        from matrel_tpu.obs import trace as trace_lib
        sess = _session(mesh8, tmp_path, level="off",
                        result_cache_max_bytes=1 << 26)
        assert sess._tracer is None and sess._flight is None
        a = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        stream = [chain3, a.expr().t()]
        sess.run_many(stream)                  # warm: compiles once
        emits = []
        monkeypatch.setattr(EventLog, "emit",
                            lambda self, *args, **kw: emits.append(args))

        def no_spans(*a, **k):
            raise AssertionError(
                "span object constructed on the off-path serve loop")

        monkeypatch.setattr(trace_lib.Span, "__init__", no_spans)
        outs = sess.run_many(stream)           # repeated traffic:
        assert len(outs) == 2                  # rc/plan-cache hits only
        assert emits == []


class TestAnalyzeEvent:
    """explain(analyze=True) with obs on emits one `analyze` record —
    the drift auditor's measured-vs-estimated feed."""

    def test_analyze_record_joins_per_op_to_decisions(self, mesh8,
                                                      tmp_path, chain3):
        sess = _session(mesh8, tmp_path)
        sess.explain(chain3, analyze=True)
        recs = [e for e in read_events(sess.config.obs_event_log)
                if e["kind"] == "analyze"]
        assert len(recs) == 1
        rec = recs[0]
        assert rec["backend"] == "cpu"
        assert rec["fused_ms"] > 0
        uids = {p["uid"] for p in rec["per_op"]}
        assert len(rec["matmuls"]) == 2
        for d in rec["matmuls"]:
            assert d["uid"] in uids            # the drift join key
        for p in rec["per_op"]:
            assert isinstance(p["ms"], (int, float))

    def test_no_analyze_event_when_obs_off(self, mesh8, tmp_path,
                                           chain3):
        sess = _session(mesh8, tmp_path, level="off")
        sess.explain(chain3, analyze=True)
        assert not (tmp_path / "events.jsonl").exists()


class TestDriftAuditor:
    """obs/drift.py: calibration ratios + the rank-order flag (the
    empirical complement of MV106) from a recorded log."""

    def _analyze_event(self, log, strategy, est_bytes, ms,
                       dims=(1024, 1024, 1024), uid=7):
        log.emit("analyze", {
            "backend": "cpu", "fused_ms": ms,
            "per_op": [{"uid": uid, "label": f"matmul:{strategy}",
                        "ms": ms}],
            "matmuls": [{"uid": uid, "strategy": strategy,
                         "dims": list(dims),
                         "flops": 2.0 * dims[0] * dims[1] * dims[2],
                         "est_ici_bytes": est_bytes}]})

    def _seed_miscalibrated(self, tmp_path):
        """cpmm estimated 4x CHEAPER than rmm but measured 3x SLOWER —
        the seeded drift the auditor must flag."""
        log = EventLog(str(tmp_path / "drift.jsonl"))
        for _ in range(3):
            self._analyze_event(log, "cpmm", est_bytes=1.0 * 2 ** 20,
                                ms=30.0)
            self._analyze_event(log, "rmm", est_bytes=4.0 * 2 ** 20,
                                ms=10.0)
        return log.path

    def test_calibration_rows(self, tmp_path):
        from matrel_tpu.obs import drift
        events = read_events(self._seed_miscalibrated(tmp_path))
        samples = list(drift.iter_samples(events))
        assert len(samples) == 6
        calib = drift.calibrate(samples)
        row = calib["cpmm|<=1024|cpu"]
        assert row["count"] == 3
        assert row["ms_median"] == 30.0
        assert row["ms_per_est_mib"] == pytest.approx(30.0)
        assert row["ms_per_gflop"] == pytest.approx(
            30.0 / (2.0 * 1024 ** 3 / 1e9))

    def test_rank_order_flag_fires_on_seeded_drift(self, tmp_path):
        from matrel_tpu.obs import drift
        events = read_events(self._seed_miscalibrated(tmp_path))
        flags = drift.rank_flags(list(drift.iter_samples(events)))
        assert len(flags) == 1
        fl = flags[0]
        assert fl["model_prefers"] == "cpmm"
        assert fl["measured_prefers"] == "rmm"
        assert fl["slowdown"] == pytest.approx(3.0)

    def test_agreeing_log_raises_no_flag(self, tmp_path):
        from matrel_tpu.obs import drift
        log = EventLog(str(tmp_path / "ok.jsonl"))
        self._analyze_event(log, "cpmm", est_bytes=1.0 * 2 ** 20,
                            ms=10.0)
        self._analyze_event(log, "rmm", est_bytes=4.0 * 2 ** 20,
                            ms=30.0)
        flags = drift.rank_flags(list(drift.iter_samples(
            read_events(log.path))))
        assert flags == []

    def test_query_samples_filtered(self, tmp_path):
        """Single-matmul query records feed the auditor; batched roots
        and rc hits (amortised / zero execute) must not."""
        from matrel_tpu.obs import drift
        log = EventLog(str(tmp_path / "q.jsonl"))
        base = {"source": "dsl", "out_shape": [4, 4], "backend": "cpu",
                "plan_cache": {},
                "matmuls": [{"uid": 1, "strategy": "rmm",
                             "dims": [64, 64, 64], "flops": 5e5,
                             "est_ici_bytes": 1024.0}]}
        log.emit("query", dict(base, cache="miss", execute_ms=5.0))
        log.emit("query", dict(base, cache="rc_hit", execute_ms=0.0))
        log.emit("query", dict(base, cache="hit", execute_ms=5.0,
                               batch={"size": 4, "index": 0}))
        samples = list(drift.iter_samples(read_events(log.path)))
        assert len(samples) == 1 and samples[0]["source"] == "query"

    def test_table_persist_and_merge(self, tmp_path):
        from matrel_tpu.obs import drift
        events = read_events(self._seed_miscalibrated(tmp_path))
        calib = drift.calibrate(list(drift.iter_samples(events)))
        path = str(tmp_path / "table.json")
        t1 = drift.update_table(path, calib)
        assert t1["entries"]["cpmm|<=1024|cpu"]["count"] == 3
        t2 = drift.update_table(path, calib)     # second session merges
        assert t2["entries"]["cpmm|<=1024|cpu"]["count"] == 6
        with open(path) as f:                    # artifact parses
            on_disk = json.load(f)
        assert on_disk["schema"] == drift.TABLE_SCHEMA
        # corrupt table reads as empty, never an error
        with open(path, "w") as f:
            f.write("{nope")
        assert drift.load_table(path)["entries"] == {}

    def test_history_drift_cli(self, tmp_path, capsys):
        from matrel_tpu.obs import history
        path = self._seed_miscalibrated(tmp_path)
        args = type("A", (), {
            "log": path, "summary": False, "last": None, "drift": True,
            "drift_table": str(tmp_path / "table.json"),
            "no_save": False})()
        assert history.main(args) == 0
        out = capsys.readouterr().out
        assert "DRIFT" in out and "model prefers cpmm" in out
        assert "calibration table" in out
        assert (tmp_path / "table.json").exists()

    def test_end_to_end_session_feeds_auditor(self, mesh8, tmp_path,
                                              chain3):
        """A recorded session (analyze + plain queries) must yield
        calibration rows through the real pipeline."""
        from matrel_tpu.obs import drift
        sess = _session(mesh8, tmp_path)
        sess.explain(chain3, analyze=True)
        events = read_events(sess.config.obs_event_log)
        report = drift.report(events, persist=False)
        assert "calibration row" in report
        assert len(drift.calibrate(
            list(drift.iter_samples(events)))) >= 1


class TestBenchErrorEvent:
    """Satellite: a failed bench probe leaves a DISTINCT bench_error
    record (error tail + last-known-good) the summary surfaces."""

    def test_emit_bench_error(self, tmp_path, monkeypatch):
        import bench
        path = str(tmp_path / "ev.jsonl")
        monkeypatch.setenv("MATREL_OBS_EVENT_LOG", path)
        bench._emit_bench_error(
            "dense_blockmatmul_tflops_per_chip",
            "probe timed out after 180s (relay wedge?)",
            extra={"attempts": 4},
            last_good={"tflops": 184.2, "when": "2026-07-30"})
        [rec] = read_events(path)
        assert rec["kind"] == "bench_error"
        assert rec["attempts"] == 4
        assert rec["last_known_good"]["tflops"] == 184.2

    def test_summary_surfaces_last_error_per_metric(self, tmp_path):
        from matrel_tpu.obs.history import render_summary, summarize
        log = EventLog(str(tmp_path / "ev.jsonl"))
        log.emit("bench", {"metric": "m1", "value": 10.0})
        log.emit("bench_error", {"metric": "m1", "error": "older"})
        log.emit("bench_error", {"metric": "m1", "error": "wedge #2",
                                 "last_known_good": {"tflops": 99.0}})
        events = read_events(log.path)
        s = summarize(events)
        assert s["bench_errors"]["m1"]["error"] == "wedge #2"  # last
        text = render_summary(events)
        assert "LAST BENCH ERROR [m1]: wedge #2" in text
        assert "99.0" in text


class TestPhaseQuantiles:
    """Satellite: history --summary p50/p95 for optimize/trace/execute
    per query kind — since round 15 through the SHARED sketch
    definition (obs/metrics.percentile), so estimates agree with the
    nearest-rank oracle within the documented relative error."""

    def _seed(self, tmp_path):
        log = EventLog(str(tmp_path / "ev.jsonl"))
        for i in range(10):
            log.emit("query", {
                "query_id": f"m{i}", "root_kind": "matmul",
                "cache": "miss", "optimize_ms": float(i + 1),
                "trace_ms": 2.0 * (i + 1),
                "execute_ms": 10.0 * (i + 1),
                "out_shape": [4, 4], "plan_cache": {}, "matmuls": []})
        log.emit("query", {
            "query_id": "a0", "root_kind": "agg", "cache": "miss",
            "optimize_ms": 7.0, "trace_ms": None, "execute_ms": 3.0,
            "out_shape": [1, 1], "plan_cache": {}, "matmuls": []})
        return log.path

    def test_quantiles_per_kind(self, tmp_path):
        from matrel_tpu.obs.history import summarize
        s = summarize(read_events(self._seed(tmp_path)))
        pq = s["phase_quantiles"]
        mm = pq["matmul"]
        assert mm["count"] == 10
        # nearest-rank (lower) oracle over [1..10]: p50 -> rank
        # floor(.5*9)=4 -> 5.0, p95 -> rank floor(.95*9)=8 -> 9.0;
        # the sketch agrees within its documented 1% relative error
        # (obs/metrics.DEFAULT_ALPHA)
        from matrel_tpu.obs.metrics import DEFAULT_ALPHA
        rel = DEFAULT_ALPHA
        assert mm["optimize_ms"]["p50"] == pytest.approx(5.0, rel=rel)
        assert mm["optimize_ms"]["p95"] == pytest.approx(9.0, rel=rel)
        assert mm["execute_ms"]["p95"] == pytest.approx(90.0, rel=rel)
        agg = pq["agg"]
        assert agg["execute_ms"]["p50"] == pytest.approx(3.0, rel=rel)
        assert agg["trace_ms"]["p50"] is None   # Nones dropped, not 0

    def test_render_shows_phase_table(self, tmp_path):
        from matrel_tpu.obs.history import render_summary
        out = render_summary(read_events(self._seed(tmp_path)))
        assert "opt p50/p95" in out
        assert "matmul" in out and "agg" in out


class TestAxisBytesRollup:
    """Round 7: per-axis comm bytes (planner.matmul_decisions'
    est_axis_bytes) roll up per strategy in history --summary, so a
    regression shifting traffic onto the slow DCN axis shows in the
    event log even when the flat total holds."""

    def _seed(self, tmp_path):
        log = EventLog(str(tmp_path / "ax.jsonl"))
        for i in range(2):
            log.emit("query", {
                "query_id": f"q{i}", "source": "dsl", "cache": "miss",
                "execute_ms": 1.0, "out_shape": [4, 4],
                "plan_cache": {"plans": 1, "evicted": 0},
                "matmuls": [
                    {"uid": 1, "strategy": "rmm", "flops": 1e9,
                     "est_ici_bytes": 3.0 * 2 ** 20,
                     "est_axis_bytes": [1.0 * 2 ** 20, 2.0 * 2 ** 20],
                     "axis_weights": [1.0, 8.0]},
                    # legacy record without the field: must not crash
                    {"uid": 2, "strategy": "cpmm", "flops": 1e9,
                     "est_ici_bytes": 2.0 ** 20}]})
        return log.path

    def test_summarize_accumulates_per_axis(self, tmp_path):
        from matrel_tpu.obs.history import summarize
        s = summarize(read_events(self._seed(tmp_path)))
        rmm = s["strategies"]["rmm"]
        assert rmm["est_axis_bytes_x"] == pytest.approx(2.0 * 2 ** 20)
        assert rmm["est_axis_bytes_y"] == pytest.approx(4.0 * 2 ** 20)
        assert "est_axis_bytes_x" not in s["strategies"]["cpmm"]

    def test_render_shows_axis_column(self, tmp_path):
        from matrel_tpu.obs.history import render_summary
        out = render_summary(read_events(self._seed(tmp_path)))
        assert "axes x/y: 2.00/4.00 MiB" in out

    def test_weighted_query_event_carries_axis_bytes(self, tmp_path,
                                                     mesh8, rng):
        # end to end: an observed weighted session writes decisions
        # with the per-axis decomposition into the event log
        from matrel_tpu.session import MatrelSession
        cfg = MatrelConfig(obs_level="on",
                           obs_event_log=str(tmp_path / "q.jsonl"),
                           axis_cost_weights=(1.0, 8.0))
        sess = MatrelSession(mesh=mesh8, config=cfg)
        a = sess.from_numpy(
            rng.standard_normal((64, 32)).astype(np.float32))
        b = sess.from_numpy(
            rng.standard_normal((32, 16)).astype(np.float32))
        sess.compute(a.expr().multiply(b.expr()))
        (ev,) = [e for e in read_events(cfg.obs_event_log)
                 if e["kind"] == "query"]
        (d,) = ev["matmuls"]
        assert len(d["est_axis_bytes"]) == 2
        assert d["axis_weights"] == [1.0, 8.0]


class TestQuantileSketch:
    """Round 15 tentpole: the DDSketch-style streaming quantile sketch
    (obs/metrics.QuantileSketch) — accuracy vs numpy oracles across
    adversarial distributions, merge associativity, and the documented
    relative-error bound asserted at every tested q."""

    QS = (0.0, 0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0)

    @staticmethod
    def _oracle(vals, q):
        # the sketch's stated definition: nearest-rank (lower) — the
        # value at 0-indexed rank floor(q*(n-1))
        return float(np.percentile(vals, q * 100.0, method="lower"))

    def _distributions(self):
        rng = np.random.default_rng(7)
        return {
            "uniform": rng.random(5000) * 100.0,
            "heavy_tail": rng.lognormal(3.0, 1.5, 5000),
            "bimodal": np.concatenate(
                [rng.normal(10.0, 1.0, 2500),
                 rng.normal(1000.0, 50.0, 2500)]).clip(0.01),
            "constant": np.full(1000, 7.5),
            "tiny": np.array([3.0, 1.0, 2.0]),
            "with_zeros": np.concatenate(
                [np.zeros(500), rng.random(1500) * 10.0]),
        }

    def test_relative_error_bound_every_q(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        for name, vals in self._distributions().items():
            sk = QuantileSketch()
            for v in vals:
                sk.add(float(v))
            for q in self.QS:
                oracle = self._oracle(vals, q)
                est = sk.quantile(q)
                if oracle <= 1e-9:
                    assert abs(est - oracle) <= 1e-9, (name, q)
                else:
                    err = abs(est - oracle) / oracle
                    assert err <= sk.alpha + 1e-12, \
                        (name, q, est, oracle, err)

    def test_extremes_exact(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        sk = QuantileSketch()
        for v in (4.0, 1.0, 3.0, 2.0):
            sk.add(v)
        assert sk.quantile(0.0) == 1.0      # exact tracked min
        assert sk.quantile(1.0) == 4.0      # exact tracked max

    def test_merge_matches_single_sketch_and_associates(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        import copy
        rng = np.random.default_rng(3)
        vals = rng.lognormal(2.0, 1.0, 3000)
        whole = QuantileSketch()
        parts = [QuantileSketch() for _ in range(3)]
        for i, v in enumerate(vals):
            whole.add(float(v))
            parts[i % 3].add(float(v))
        a, b, c = parts
        ab_c = copy.deepcopy(a).merge(b).merge(c)
        a_bc = copy.deepcopy(a).merge(copy.deepcopy(b).merge(c))
        for q in self.QS:
            # associativity is EXACT (bucket counts add); merged ==
            # single-sketch is exact too — same buckets either way
            assert ab_c.quantile(q) == a_bc.quantile(q)
            assert ab_c.quantile(q) == whole.quantile(q)
        assert ab_c.count == whole.count == 3000
        assert ab_c.sum == pytest.approx(whole.sum)

    def test_merge_rejects_mismatched_alpha(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        with pytest.raises(ValueError, match="alpha"):
            QuantileSketch(0.01).merge(QuantileSketch(0.02))

    def test_bucket_collapse_bounds_memory_keeps_high_q(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        sk = QuantileSketch(max_buckets=32)
        rng = np.random.default_rng(0)
        # dynamic range far beyond 32 buckets forces collapses
        vals = np.exp(rng.uniform(-5, 15, 4000))
        for v in vals:
            sk.add(float(v))
        assert len(sk._buckets) <= 32
        # the collapse folds LOW buckets upward: quantiles whose rank
        # lies in the SURVIVING (high) buckets keep the bound — 32
        # kept buckets over this ~1000-bucket-wide distribution cover
        # roughly the top 3% of mass, so the SLO-bearing tail is what
        # survives (the DDSketch collapse direction, by design)
        for q in (0.99, 0.999):
            oracle = self._oracle(vals, q)
            assert abs(sk.quantile(q) - oracle) / oracle \
                <= sk.alpha + 1e-12
        assert sk.quantile(1.0) == float(vals.max())

    def test_serialisation_round_trip(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        sk = QuantileSketch()
        for v in (1.0, 5.0, 0.0, 250.0):
            sk.add(v)
        back = QuantileSketch.from_dict(
            json.loads(json.dumps(sk.to_dict())))
        for q in self.QS:
            assert back.quantile(q) == sk.quantile(q)
        assert back.count == sk.count and back.zeros == sk.zeros

    def test_constructor_validation(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        with pytest.raises(ValueError):
            QuantileSketch(alpha=0.0)
        with pytest.raises(ValueError):
            QuantileSketch(alpha=1.0)
        with pytest.raises(ValueError):
            QuantileSketch(max_buckets=1)

    def test_negative_values_clamp_to_zero_bucket(self):
        from matrel_tpu.obs.metrics import QuantileSketch
        sk = QuantileSketch()
        for v in (-3.0, 0.0, 2.0, 4.0):
            sk.add(v)
        assert sk.zeros == 2
        # nearest-rank oracle at q=.99 over 4 values is rank 2 -> 2.0
        assert sk.quantile(0.99) == pytest.approx(2.0, rel=sk.alpha)
        assert sk.quantile(1.0) == 4.0


class TestHistorySketchAgreement:
    """Satellite fix regression: obs/history's percentile helper used
    to nearest-rank raw lists per invocation while the live plane
    reported sketch estimates — now BOTH flow through
    obs.metrics.percentile, pinned to agree with the nearest-rank
    oracle within the sketch bound on every tested distribution/q."""

    def test_pctile_agreement_within_bound(self):
        from matrel_tpu.obs.history import _pctile
        from matrel_tpu.obs.metrics import (DEFAULT_ALPHA,
                                            QuantileSketch,
                                            percentile)
        rng = np.random.default_rng(11)
        for vals in (rng.random(777) * 50.0,
                     rng.lognormal(1.0, 2.0, 777),
                     np.full(40, 3.25)):
            vals = [float(v) for v in vals]
            for q in (0.5, 0.9, 0.95, 0.99):
                oracle = float(np.percentile(vals, q * 100.0,
                                             method="lower"))
                hist = _pctile(sorted(vals), q)
                assert abs(hist - oracle) <= DEFAULT_ALPHA * oracle
                # history's helper IS the shared definition — exactly
                # what a live sketch over the same values reports
                sk = QuantileSketch()
                for v in vals:
                    sk.add(v)
                assert hist == sk.quantile(q)
                assert hist == percentile(vals, q)

    def test_pctile_empty_is_none(self):
        from matrel_tpu.obs.history import _pctile
        assert _pctile([], 0.5) is None
