"""Resilience layer (matrel_tpu/resilience/ + session/serve/checkpoint
integration): seeded fault-injection determinism per site, the typed
transient/deterministic taxonomy, retry/backoff schedules, per-query
deadlines + cancellation between attempts, the plan-degradation ladder
(each rung correct vs oracle), poison-query isolation by serve-batch
bisection, typed drain/close/shed errors, robust auxiliary-file
readers, checkpoint checksums, and the default-config bit-identity
contract (zero injection objects, unchanged plan keys)."""

import json
import os

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.resilience import degrade, errors, faults
from matrel_tpu.resilience.errors import (AdmissionShed,
                                          CheckpointCorruption,
                                          DeadlineExceeded,
                                          DrainTimeout, InjectedFault,
                                          PipelineClosed, QueryAborted)
from matrel_tpu.resilience.faults import FaultInjector
from matrel_tpu.resilience.retry import Deadline, RetryPolicy
from matrel_tpu.session import MatrelSession


@pytest.fixture(autouse=True)
def _fresh_faults():
    """Each test starts (and leaves) a clean process-wide injector
    registry — schedules are per-(spec, seed) and stateful."""
    faults.reset()
    yield
    faults.reset()


def _mat(rng, n, m, mesh):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh)


def _sess(mesh, **cfg):
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


def _events(path):
    return [json.loads(l) for l in open(path)] if os.path.exists(
        path) else []


# ---------------------------------------------------------------------------
# Fault injection: spec parsing + per-site deterministic schedules
# ---------------------------------------------------------------------------


class TestFaultInjection:
    def test_spec_validation_at_config_construction(self):
        with pytest.raises(ValueError, match="site"):
            MatrelConfig(fault_inject="warp_core:transient:p=0.5")
        with pytest.raises(ValueError, match="kind"):
            MatrelConfig(fault_inject="compile:sometimes:p=0.5")
        with pytest.raises(ValueError, match="exactly one"):
            MatrelConfig(fault_inject="compile:transient")
        with pytest.raises(ValueError, match="exactly one"):
            MatrelConfig(fault_inject="compile:transient:p=0.5:n=3")
        with pytest.raises(ValueError, match="p="):
            MatrelConfig(fault_inject="compile:transient:p=1.5")
        # valid specs construct fine
        MatrelConfig(fault_inject="compile:transient:p=0.5;"
                                  "execute:fatal:n=3:max=1")

    def _schedule(self, spec, seed, site, n_calls=200):
        inj = FaultInjector(spec, seed)
        fired = []
        for i in range(n_calls):
            try:
                inj.check(site)
            except InjectedFault:
                fired.append(i)
        return fired

    @pytest.mark.parametrize("site", faults.SITES)
    def test_probability_schedule_deterministic_per_site(self, site):
        spec = f"{site}:transient:p=0.1"
        a = self._schedule(spec, 42, site)
        b = self._schedule(spec, 42, site)
        assert a == b and len(a) > 0
        c = self._schedule(spec, 43, site)
        assert a != c      # the seed IS the schedule

    def test_sites_independent_streams(self):
        # one site's draws do not perturb another's schedule
        solo = self._schedule("execute:transient:p=0.1", 7, "execute")
        inj = FaultInjector(
            "execute:transient:p=0.1;compile:transient:p=0.1", 7)
        fired = []
        for i in range(200):
            try:
                inj.check("compile")
            except InjectedFault:
                pass
            try:
                inj.check("execute")
            except InjectedFault:
                fired.append(i)
        assert fired == solo

    def test_nth_call_fires_exactly_once(self):
        fired = self._schedule("compile:transient:n=5", 0, "compile",
                               n_calls=50)
        assert fired == [4]                      # 1-based call 5

    def test_max_caps_total_fires(self):
        fired = self._schedule("execute:transient:p=1.0:max=3", 0,
                               "execute", n_calls=50)
        assert fired == [0, 1, 2]

    def test_all_site_expands_to_every_site(self):
        inj = FaultInjector("all:transient:n=1", 0)
        for site in faults.SITES:
            with pytest.raises(InjectedFault):
                inj.check(site)

    def test_unlisted_site_never_fires(self):
        assert self._schedule("compile:transient:p=1.0", 0,
                              "execute") == []

    def test_sibling_rule_counters_advance_past_a_fire(self):
        # one rule firing must not skew a sibling's call count: the
        # n=3 rule fires on the site's THIRD check even though the
        # n=1 rule fired (and raised) on the first
        inj = FaultInjector(
            "execute:transient:n=1;execute:fatal:n=3", 0)
        with pytest.raises(InjectedFault) as e1:
            inj.check("execute")
        assert e1.value.transient
        inj.check("execute")                     # call 2: quiet
        with pytest.raises(InjectedFault) as e3:
            inj.check("execute")                 # call 3: the fatal
        assert not e3.value.transient
        assert e3.value.call_index == 3

    def test_injected_fault_is_typed_and_attributed(self):
        inj = FaultInjector("execute:fatal:n=1", 0)
        with pytest.raises(InjectedFault) as ei:
            inj.check("execute")
        assert ei.value.site == "execute"
        assert ei.value.transient is False
        assert ei.value.call_index == 1


# ---------------------------------------------------------------------------
# Taxonomy + retry policy units
# ---------------------------------------------------------------------------


class TestTaxonomy:
    def test_injected_faults_classify_by_kind(self):
        assert errors.classify(
            InjectedFault("execute", "transient", 1)) == "transient"
        assert errors.classify(
            InjectedFault("execute", "fatal", 1)) == "deterministic"

    def test_verification_error_never_retries(self):
        from matrel_tpu.analysis import Diagnostic, VerificationError
        d = Diagnostic(code="MV999", severity="error", node="x",
                       message="boom")
        assert errors.classify(
            VerificationError([d])) == "deterministic"

    def test_compile_class_errors_deterministic(self):
        for ex in (ValueError("bad shape"), TypeError("no"),
                   NotImplementedError("op"), KeyError("k")):
            assert errors.classify(ex) == "deterministic"

    def test_runtime_class_errors_transient(self):
        class XlaRuntimeError(Exception):
            pass
        assert errors.classify(XlaRuntimeError("dead")) == "transient"
        assert errors.classify(
            RuntimeError("RESOURCE_EXHAUSTED: oom")) == "transient"
        assert errors.classify(MemoryError()) == "transient"
        # unknown types without markers default deterministic
        assert errors.classify(
            RuntimeError("who knows")) == "deterministic"

    def test_resilience_errors_never_retry(self):
        for ex in (DeadlineExceeded(5.0, 6.0), DrainTimeout(1.0, 2),
                   AdmissionShed(4), PipelineClosed("closed")):
            assert errors.classify(ex) == "deterministic"


class TestRetryPolicy:
    def test_from_config_none_for_default(self):
        assert RetryPolicy.from_config(MatrelConfig()) is None

    def test_from_config_active_when_asked(self):
        assert RetryPolicy.from_config(
            MatrelConfig(retry_max_attempts=2)) is not None
        assert RetryPolicy.from_config(
            MatrelConfig(fault_inject="execute:transient:n=1")) \
            is not None
        assert RetryPolicy.from_config(MatrelConfig(),
                                       deadline_ms=10.0) is not None
        assert RetryPolicy.from_config(
            MatrelConfig(deadline_ms=10.0)) is not None

    def test_backoff_schedule_closed_form_without_jitter(self):
        pol = RetryPolicy(5, backoff_ms=8.0, backoff_mult=2.0,
                          jitter=0.0, seed=0)
        assert [pol.backoff_delay_s(a) for a in (1, 2, 3, 4)] == \
            [0.008, 0.016, 0.032, 0.064]

    def test_backoff_jitter_seeded_reproducible(self):
        a = RetryPolicy(5, 8.0, 2.0, jitter=0.5, seed=11, nonce=0)
        b = RetryPolicy(5, 8.0, 2.0, jitter=0.5, seed=11, nonce=0)
        da = [a.backoff_delay_s(i) for i in (1, 2, 3)]
        db = [b.backoff_delay_s(i) for i in (1, 2, 3)]
        assert da == db
        # jitter stays inside the documented symmetric band
        for i, d in enumerate(da, start=1):
            base = 0.008 * 2.0 ** (i - 1)
            assert 0.5 * base <= d <= 1.5 * base
        c = RetryPolicy(5, 8.0, 2.0, jitter=0.5, seed=12, nonce=0)
        assert [c.backoff_delay_s(i) for i in (1, 2, 3)] != da

    def test_concurrent_policies_do_not_share_jitter_stream(self):
        # the de-dogpile property: two policies from ONE config (the
        # burst-of-queries case) must draw distinct jitter sequences
        cfg = MatrelConfig(retry_max_attempts=3, retry_jitter=0.5)
        a = RetryPolicy.from_config(cfg)
        b = RetryPolicy.from_config(cfg)
        assert [a.backoff_delay_s(i) for i in (1, 2, 3)] != \
            [b.backoff_delay_s(i) for i in (1, 2, 3)]

    def test_backoff_overshooting_deadline_raises_now(self):
        pol = RetryPolicy(5, backoff_ms=500.0, backoff_mult=1.0,
                          jitter=0.0, seed=0, deadline_ms=20.0)
        dl = pol.deadline()
        with pytest.raises(DeadlineExceeded):
            pol.backoff_sleep(1, dl)     # 500 ms sleep vs 20 ms budget

    def test_cancellation_honored_between_attempts(self):
        pol = RetryPolicy(5, backoff_ms=1.0, backoff_mult=1.0,
                          jitter=0.0, seed=0)
        with pytest.raises(QueryAborted):
            pol.backoff_sleep(1, pol.deadline(),
                              should_abort=lambda: True)

    def test_should_retry_gates_on_class_and_budget(self):
        pol = RetryPolicy(2, 1.0, 2.0, 0.0, 0)
        t = InjectedFault("execute", "transient", 1)
        assert pol.should_retry(t, 0) and pol.should_retry(t, 1)
        assert not pol.should_retry(t, 2)              # budget spent
        assert not pol.should_retry(ValueError("x"), 0)  # wrong class


# ---------------------------------------------------------------------------
# Session integration: retries, ladder, deadlines, events
# ---------------------------------------------------------------------------


class TestSessionResilience:
    def test_transient_execute_fault_retries_to_correct(self, mesh8,
                                                        rng):
        sess = _sess(mesh8, fault_inject="execute:transient:n=1",
                     retry_max_attempts=2, retry_backoff_ms=1.0)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        out = sess.run(A.expr().multiply(B.expr()))
        np.testing.assert_allclose(out.to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)
        stats = faults.injector_for(sess.config).stats()
        assert stats["execute"]["fires"] == 1

    def test_fatal_fault_raises_typed_without_retry(self, mesh8, rng):
        sess = _sess(mesh8, fault_inject="compile:fatal:n=1",
                     retry_max_attempts=3, retry_backoff_ms=1.0)
        A = _mat(rng, 32, 32, mesh8)
        with pytest.raises(InjectedFault):
            sess.run(A.expr().multiply(A.expr()))
        # deterministic = ONE attempt: the compile site saw one call
        assert faults.injector_for(
            sess.config).stats()["compile"]["calls"] == 1

    def test_retries_exhausted_raises_last_fault(self, mesh8, rng):
        sess = _sess(mesh8, fault_inject="execute:transient:p=1.0",
                     retry_max_attempts=2, retry_backoff_ms=0.5)
        A = _mat(rng, 32, 32, mesh8)
        with pytest.raises(InjectedFault) as ei:
            sess.run(A.expr().multiply(A.expr()))
        assert ei.value.transient   # typed, attributable, transient

    def test_ladder_escalates_to_rung4_and_stays_correct(self, mesh8,
                                                         rng):
        # every attempt's execute faults until the cap: the query
        # climbs all four rungs and STILL answers correctly
        sess = _sess(mesh8, fault_inject="execute:transient:p=1.0:max=4",
                     retry_max_attempts=4, retry_backoff_ms=0.5)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        out = sess.run(A.expr().multiply(B.expr()))
        np.testing.assert_allclose(out.to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)
        # the degraded attempt's plan is cached under the rung prefix
        assert any(k.startswith("degr:4|")
                   for k in sess._plan_cache), list(sess._plan_cache)
        plan = sess._plan_cache[next(
            k for k in sess._plan_cache if k.startswith("degr:4|"))]
        assert plan.meta["degrade"] == {"rung": 4,
                                        "label": "no-result-cache"}

    def test_rc_bypass_rung_recovers_from_poisoned_probe(self, mesh8,
                                                         rng):
        # rc_probe faults on EVERY consult — only the ladder's rung-4
        # cache bypass can complete this query. That it does is the
        # ladder working as designed.
        sess = _sess(mesh8, fault_inject="rc_probe:transient:p=1.0",
                     retry_max_attempts=4, retry_backoff_ms=0.5,
                     result_cache_max_bytes=1 << 24)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        out = sess.run(A.expr().multiply(B.expr()))
        np.testing.assert_allclose(out.to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_deadline_expired_raises_typed(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        with pytest.raises(DeadlineExceeded):
            sess.run(A.expr().multiply(A.expr()), deadline_ms=1e-6)

    def test_config_default_deadline_applies(self, mesh8, rng):
        sess = _sess(mesh8, deadline_ms=1e-6)
        A = _mat(rng, 32, 32, mesh8)
        with pytest.raises(DeadlineExceeded):
            sess.run(A.expr().multiply(A.expr()))

    def test_deadline_enforced_on_late_success(self, mesh8, rng,
                                               monkeypatch):
        # an attempt that SUCCEEDS past the deadline still raises
        # typed — run() matches submit()'s late-batch semantics. The
        # clock is stepped: 0 s at deadline start/entry check, 10 s
        # from the post-attempt check on.
        import matrel_tpu.resilience.retry as retry_mod
        ticks = iter([0.0, 0.0, 0.0])
        monkeypatch.setattr(retry_mod.time, "monotonic",
                            lambda: next(ticks, 10.0))
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        with pytest.raises(DeadlineExceeded):
            sess.run(A.expr().multiply(A.expr()), deadline_ms=1000.0)

    def test_generous_deadline_does_not_interfere(self, mesh8, rng):
        sess = _sess(mesh8)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        out = sess.run(A.expr().multiply(B.expr()), deadline_ms=60_000)
        np.testing.assert_allclose(out.to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_cancellation_between_attempts(self, mesh8, rng):
        sess = _sess(mesh8, fault_inject="execute:transient:p=1.0",
                     retry_max_attempts=5, retry_backoff_ms=1.0)
        A = _mat(rng, 32, 32, mesh8)
        from matrel_tpu.ir.expr import as_expr
        pol = RetryPolicy.from_config(sess.config)
        with pytest.raises(QueryAborted):
            sess._compute_resilient(
                as_expr(A.expr().multiply(A.expr())), False,
                "default", pol, should_abort=lambda: True)

    def test_run_many_retries_whole_batch(self, mesh8, rng):
        sess = _sess(mesh8, fault_inject="execute:transient:n=1",
                     retry_max_attempts=2, retry_backoff_ms=1.0)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        outs = sess.run_many([A.expr().multiply(B.expr()),
                              B.expr().t().multiply(A.expr().t())])
        np.testing.assert_allclose(outs[0].to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(outs[1].to_numpy(),
                                   (A.to_numpy() @ B.to_numpy()).T,
                                   rtol=3e-4, atol=3e-4)

    def test_run_many_deadline_typed(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        with pytest.raises(DeadlineExceeded):
            sess.run_many([A.expr().multiply(A.expr())],
                          deadline_ms=1e-6)


class TestResilienceEvents:
    def test_fault_retry_degrade_events_and_rollup(self, mesh8, rng,
                                                   tmp_path):
        log = tmp_path / "events.jsonl"
        sess = _sess(mesh8, fault_inject="execute:transient:n=1",
                     retry_max_attempts=2, retry_backoff_ms=1.0,
                     obs_level="on", obs_event_log=str(log))
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        sess.run(A.expr().multiply(B.expr()))
        evs = _events(str(log))
        kinds = [e["kind"] for e in evs]
        assert "fault" in kinds and "retry" in kinds \
            and "degrade" in kinds
        fault = next(e for e in evs if e["kind"] == "fault")
        assert fault["site"] == "execute" and fault["injected"]
        assert fault["classification"] == "transient"
        retry = next(e for e in evs if e["kind"] == "retry")
        assert retry["attempt"] == 1 and retry["rung"] == 1
        deg = next(e for e in evs if e["kind"] == "degrade")
        assert deg["rung_label"] == "no-autotune"
        # the query record still landed (the retry SAVED the query)
        assert "query" in kinds
        from matrel_tpu.obs.history import render_summary, summarize
        from matrel_tpu.obs.events import read_events
        s = summarize(read_events(str(log)))
        rs = s["resilience"]
        assert rs["faults"] == 1 and rs["injected"] == 1
        assert rs["retries"] == 1 and rs["degrades"] == 1
        assert rs["rungs"] == {"no-autotune": 1}
        assert rs["fault_sites"] == {"execute": 1}
        assert "resilience: 1 fault(s)" in render_summary(
            read_events(str(log)))

    def test_obs_off_resilient_path_emits_nothing(self, mesh8, rng,
                                                  tmp_path):
        log = tmp_path / "events.jsonl"
        os.environ.pop("MATREL_OBS_EVENT_LOG", None)
        sess = _sess(mesh8, fault_inject="execute:transient:n=1",
                     retry_max_attempts=2, retry_backoff_ms=1.0,
                     obs_event_log=str(log))
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        assert not log.exists()     # obs off: recovery is silent


# ---------------------------------------------------------------------------
# Degradation ladder units + oracle equivalence per rung
# ---------------------------------------------------------------------------


class TestDegradationLadder:
    def test_rung0_is_identity(self):
        cfg = MatrelConfig()
        assert degrade.apply_rung(cfg, 0) is cfg
        assert degrade.key_prefix(0) == ""

    def test_rungs_are_cumulative(self):
        cfg = MatrelConfig(autotune=True)
        c1 = degrade.apply_rung(cfg, 1)
        assert c1.autotune is False
        assert c1.strategy_override == "auto"
        c2 = degrade.apply_rung(cfg, 2)
        assert c2.autotune is False
        assert c2.strategy_override == "xla"
        c3 = degrade.apply_rung(cfg, 3)
        assert (c3.strategy_override, c3.use_pallas,
                c3.spgemm_density_threshold) == ("xla", False, 0.0)
        assert c3.spgemm_kernel_override == "xla_gather"
        c4 = degrade.apply_rung(cfg, 4)
        assert c4 == c3      # rung 4's rc bypass is session-side

    def test_rung3_forces_registry_off_a_forced_pallas_kernel(self):
        # the regression: a base config FORCING a specialized Pallas
        # kernel (the soak/bench knob) must not survive rung 3 — the
        # rung's whole point is escaping a miscompiling kernel
        cfg = MatrelConfig(spgemm_kernel_override="pallas_band",
                           pallas_interpret=True)
        c2 = degrade.apply_rung(cfg, 2)
        assert c2.spgemm_kernel_override == "pallas_band"
        c3 = degrade.apply_rung(cfg, 3)
        assert c3.spgemm_kernel_override == "xla_gather"

    def test_rung3_escapes_miscompiling_forced_kernel(self, mesh8,
                                                      monkeypatch):
        # end to end: the forced specialized Pallas kernel's BUILDER
        # blows up with a transient-classified fault (a Mosaic
        # miscompile's shape); rungs 1–2 keep the forced kernel and
        # keep failing; rung 3 pins the registry to the XLA generic
        # entry and the query completes
        from matrel_tpu.ops import kernel_registry as kr
        from matrel_tpu.ops import spgemm as spgemm_lib
        sess = _sess(mesh8, spgemm_kernel_override="pallas_band",
                     pallas_interpret=True, retry_max_attempts=4,
                     retry_backoff_ms=0.5)
        A = kr.synthesize_structure("row_band", 2048, 16, mesh8,
                                    seed=31)
        B = kr.synthesize_structure("row_band", 2048, 16, mesh8,
                                    seed=32)
        orig = kr.build_runner
        attempts = []

        def broken(kid, *a, **k):
            if kid == "pallas_band":
                attempts.append(kid)
                raise RuntimeError(
                    "INTERNAL: injected Mosaic miscompile")
            return orig(kid, *a, **k)

        monkeypatch.setattr(kr, "build_runner", broken)
        spgemm_lib._RUNNER_CACHE.clear()
        out = sess.run(A.multiply(B))
        assert attempts, "forced kernel was never even tried"
        n = A.shape[0]
        np.testing.assert_allclose(out.to_numpy()[:n, :n],
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)
        # the completing attempt ran degraded at rung >= 3
        assert any(k.startswith("degr:3|") or k.startswith("degr:4|")
                   for k in sess._plan_cache), list(sess._plan_cache)

    @pytest.mark.parametrize("rung", [1, 2, 3, 4])
    def test_each_rung_produces_correct_results(self, mesh8, rng,
                                                rung):
        # the ladder's safety property: every rung is semantics-
        # preserving — same answers from dense, S×S AND COO matmuls
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.core.sparse import BlockSparseMatrix
        from matrel_tpu.executor import compile_expr
        cfg = degrade.apply_rung(MatrelConfig(), rung)
        A, B = _mat(rng, 48, 32, mesh8), _mat(rng, 32, 24, mesh8)
        want = A.to_numpy() @ B.to_numpy()
        got = compile_expr(A.expr().multiply(B.expr()), mesh8,
                           cfg).run()
        np.testing.assert_allclose(got.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)
        sn = rng.standard_normal((48, 48)).astype(np.float32)
        sn[rng.random((48, 48)) < 0.8] = 0.0
        S = BlockSparseMatrix.from_numpy(sn, block_size=8, mesh=mesh8,
                                         config=cfg)
        got = compile_expr(S.expr().multiply(S.expr()), mesh8,
                           cfg).run()
        np.testing.assert_allclose(got.to_numpy(), sn @ sn, rtol=3e-4,
                                   atol=3e-4)
        rows, cols = np.nonzero(sn)
        C = COOMatrix.from_edges(rows, cols, sn[rows, cols],
                                 shape=sn.shape)
        D = _mat(rng, 48, 24, mesh8)
        got = compile_expr(C.expr().multiply(D.expr()), mesh8,
                           cfg).run()
        np.testing.assert_allclose(got.to_numpy(), sn @ D.to_numpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_rung2_plan_stamps_xla_everywhere(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr, \
            plan_matmul_decisions
        cfg = degrade.apply_rung(MatrelConfig(), 2)
        A, B = _mat(rng, 64, 64, mesh8), _mat(rng, 64, 64, mesh8)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh8, cfg)
        assert all(d["strategy"] == "xla"
                   for d in plan_matmul_decisions(plan))


# ---------------------------------------------------------------------------
# Default-config bit-identity: zero resilience overhead when off
# ---------------------------------------------------------------------------


class TestDefaultConfigInert:
    def test_zero_injection_objects_constructed(self, mesh8, rng,
                                                monkeypatch):
        def poisoned(self, *a, **kw):
            raise AssertionError(
                "FaultInjector constructed under default config")
        monkeypatch.setattr(FaultInjector, "__init__", poisoned)
        sess = _sess(mesh8)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        out = sess.run(A.expr().multiply(B.expr()))
        sess.run_many([A.expr().multiply(B.expr())])
        np.testing.assert_allclose(out.to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_no_retry_policy_objects_on_default_path(self, mesh8, rng,
                                                     monkeypatch):
        calls = []
        orig = RetryPolicy.__init__

        def spy(self, *a, **kw):
            calls.append(a)
            return orig(self, *a, **kw)
        monkeypatch.setattr(RetryPolicy, "__init__", spy)
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        assert calls == []

    def test_plan_cache_keys_carry_no_resilience_prefix(self, mesh8,
                                                        rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        assert all(not k.startswith("degr:")
                   for k in sess._plan_cache)

    def test_default_plans_carry_no_degrade_meta(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        plan = sess.compile(A.expr().multiply(A.expr()))
        assert "degrade" not in plan.meta


# ---------------------------------------------------------------------------
# Serve plane: bisection, backpressure, typed drain/close, deadlines
# ---------------------------------------------------------------------------


class TestServeResilience:
    def test_one_poison_in_five_query_batch_fails_exactly_one(
            self, mesh8, rng):
        # THE regression the tentpole exists for: pre-bisection, one
        # poison failed every sibling future of its coalesced batch
        import jax
        from matrel_tpu.core import mesh as mesh_lib
        sess = _sess(mesh8, serve_max_batch=8)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        other = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
        M_other = BlockMatrix.from_numpy(
            rng.standard_normal((48, 16)).astype(np.float32),
            mesh=other)
        good = [A.expr().multiply(B.expr()).multiply_scalar(float(s))
                for s in (1, 2, 3, 4)]
        futs = [sess.submit(e) for e in good[:2]]
        futs.append(sess.submit(A.expr().multiply(M_other.expr())))
        futs += [sess.submit(e) for e in good[2:]]
        sess.serve_drain(timeout=120)
        excs = [f.exception(timeout=30) for f in futs]
        assert isinstance(excs[2], ValueError)      # the poison, typed
        assert [e is None for e in excs] == [True, True, False, True,
                                             True]
        want = A.to_numpy() @ B.to_numpy()
        for f, s in zip((futs[0], futs[1], futs[3], futs[4]),
                        (1, 2, 3, 4)):
            np.testing.assert_allclose(f.result().to_numpy(), want * s,
                                       rtol=3e-4, atol=3e-4)

    def test_serve_admit_transient_converges(self, mesh8, rng):
        sess = _sess(mesh8, fault_inject="serve_admit:transient:n=1",
                     retry_max_attempts=2, retry_backoff_ms=1.0)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        f = sess.submit(A.expr().multiply(B.expr()))
        np.testing.assert_allclose(f.result(timeout=60).to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_drain_timeout_typed_on_wedged_worker(self, mesh8, rng):
        from matrel_tpu.serve.pipeline import ServePipeline
        sess = _sess(mesh8)
        p = ServePipeline(sess)
        p._ensure_worker = lambda: None       # a wedged worker
        A = _mat(rng, 16, 16, mesh8)
        p.submit(A.expr())
        with pytest.raises(DrainTimeout) as ei:
            p.drain(timeout=0.1)
        assert ei.value.pending == 1
        # the queue was left intact: a healthy worker can still drain
        assert p._q.unfinished_tasks == 1

    def test_submit_after_close_raises_typed(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 16, 16, mesh8)
        sess.submit(A.expr()).result(timeout=60)
        sess.serve_close()
        with pytest.raises(PipelineClosed):
            sess.submit(A.expr())

    def test_bounded_queue_sheds_typed(self, mesh8, rng):
        from matrel_tpu.serve.pipeline import ServePipeline
        sess = _sess(mesh8, serve_queue_max=2)
        p = ServePipeline(sess)
        p._ensure_worker = lambda: None       # nothing drains
        A = _mat(rng, 16, 16, mesh8)
        p.submit(A.expr())
        p.submit(A.expr())
        with pytest.raises(AdmissionShed) as ei:
            p.submit(A.expr())
        assert ei.value.queue_max == 2

    def test_queued_deadline_expiry_fails_future_typed(self, mesh8,
                                                       rng):
        sess = _sess(mesh8)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        f = sess.submit(A.expr().multiply(B.expr()), deadline_ms=1e-6)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=60)
        # a generous deadline serves normally
        f2 = sess.submit(A.expr().multiply(B.expr()),
                         deadline_ms=120_000)
        np.testing.assert_allclose(f2.result(timeout=60).to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)

    def test_worker_survives_poison_and_serves_next(self, mesh8, rng):
        import jax
        from matrel_tpu.core import mesh as mesh_lib
        sess = _sess(mesh8)
        A, B = _mat(rng, 32, 48, mesh8), _mat(rng, 48, 16, mesh8)
        other = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
        M_other = BlockMatrix.from_numpy(
            rng.standard_normal((48, 16)).astype(np.float32),
            mesh=other)
        bad = sess.submit(A.expr().multiply(M_other.expr()))
        assert isinstance(bad.exception(timeout=60), ValueError)
        ok = sess.submit(A.expr().multiply(B.expr()))
        np.testing.assert_allclose(ok.result(timeout=60).to_numpy(),
                                   A.to_numpy() @ B.to_numpy(),
                                   rtol=3e-4, atol=3e-4)


# ---------------------------------------------------------------------------
# Robust readers + checkpoint checksums
# ---------------------------------------------------------------------------


class TestRobustReaders:
    def test_corrupt_drift_table_warns_and_rebuilds(self, tmp_path,
                                                    caplog):
        from matrel_tpu.obs import drift
        p = tmp_path / "drift.json"
        p.write_text('{"schema": 1, "entr')        # torn write
        with caplog.at_level("WARNING", logger="matrel_tpu.obs"):
            t = drift.load_table(str(p))
        assert t == {"schema": drift.TABLE_SCHEMA, "entries": {}}
        assert any("corrupt" in r.message for r in caplog.records)

    def test_corrupt_autotune_table_warns_and_rebuilds(self, tmp_path,
                                                       caplog):
        from matrel_tpu.parallel import autotune
        p = tmp_path / "autotune.json"
        p.write_text("NOT JSON {{{")
        with caplog.at_level("WARNING",
                             logger="matrel_tpu.autotune"):
            t = autotune.load_table(str(p))
        assert t == {}
        assert any("corrupt" in r.message for r in caplog.records)

    def test_absent_tables_read_silently_empty(self, tmp_path,
                                               caplog):
        from matrel_tpu.obs import drift
        from matrel_tpu.parallel import autotune
        with caplog.at_level("WARNING"):
            assert autotune.load_table(str(tmp_path / "nope")) == {}
            assert drift.load_table(
                str(tmp_path / "nope"))["entries"] == {}
        assert not caplog.records     # absence is normal, not corrupt

    def test_corrupt_event_log_line_skipped_with_warning(
            self, tmp_path, caplog):
        from matrel_tpu.obs.events import EventLog, read_events
        p = tmp_path / "events.jsonl"
        EventLog(str(p)).emit("query", {"n": 1})
        with open(p, "a") as f:
            f.write('{"kind": "query", "trunca\n')   # crashed writer
        EventLog(str(p)).emit("query", {"n": 2})
        with caplog.at_level("WARNING", logger="matrel_tpu.obs"):
            evs = read_events(str(p))
        assert [e["n"] for e in evs] == [1, 2]
        assert any("corrupt line" in r.message for r in caplog.records)


class TestCheckpointChecksums:
    def _save_one(self, tmp_path, mesh, rng, config=None):
        from matrel_tpu.utils.checkpoint import CheckpointManager
        mgr = CheckpointManager(str(tmp_path / "ckpt"), config=config)
        A = _mat(rng, 16, 16, mesh)
        path = mgr.save(0, matrices={"A": A}, state={"i": 1})
        return mgr, A, path

    def test_round_trip_verifies_clean(self, tmp_path, mesh8, rng):
        mgr, A, _ = self._save_one(tmp_path, mesh8, rng)
        step, mats, _, state = mgr.restore(mesh8)
        np.testing.assert_allclose(mats["A"].to_numpy(), A.to_numpy())
        assert state == {"i": 1}

    def test_seeded_corruption_raises_typed(self, tmp_path, mesh8,
                                            rng):
        mgr, _, path = self._save_one(tmp_path, mesh8, rng)
        npy = os.path.join(path, "A.npy")
        blob = bytearray(open(npy, "rb").read())
        blob[len(blob) // 2] ^= 0xFF              # one flipped byte
        open(npy, "wb").write(bytes(blob))
        with pytest.raises(CheckpointCorruption, match="checksum"):
            mgr.restore(mesh8)

    def test_truncated_artifact_raises_typed(self, tmp_path, mesh8,
                                             rng):
        mgr, _, path = self._save_one(tmp_path, mesh8, rng)
        npy = os.path.join(path, "A.npy")
        blob = open(npy, "rb").read()
        open(npy, "wb").write(blob[: len(blob) // 2])
        with pytest.raises(CheckpointCorruption, match="checksum"):
            mgr.restore(mesh8)

    def test_corrupt_meta_raises_typed(self, tmp_path, mesh8, rng):
        mgr, _, path = self._save_one(tmp_path, mesh8, rng)
        open(os.path.join(path, "meta.json"), "w").write("{torn")
        with pytest.raises(CheckpointCorruption, match="metadata"):
            mgr.restore(mesh8)

    def test_legacy_checkpoint_without_checksums_loads(self, tmp_path,
                                                       mesh8, rng):
        mgr, A, path = self._save_one(tmp_path, mesh8, rng)
        meta_p = os.path.join(path, "meta.json")
        meta = json.load(open(meta_p))
        meta.pop("checksums")                     # a pre-round-10 save
        json.dump(meta, open(meta_p, "w"))
        step, mats, _, _ = mgr.restore(mesh8)
        np.testing.assert_allclose(mats["A"].to_numpy(), A.to_numpy())

    def test_session_catalog_round_trip_still_works(self, tmp_path,
                                                    mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 16, 16, mesh8)
        sess.register("A", A)
        sess.save_catalog(str(tmp_path / "cat"))
        sess2 = _sess(mesh8)
        assert sess2.load_catalog(str(tmp_path / "cat")) == ["A"]
        np.testing.assert_allclose(sess2.table("A").to_numpy(),
                                   A.to_numpy())


# ---------------------------------------------------------------------------
# utils/resilience.py delegation
# ---------------------------------------------------------------------------


class TestRunResilientDelegation:
    def test_driver_loop_uses_shared_taxonomy(self):
        from matrel_tpu.utils.resilience import _is_retryable
        assert _is_retryable(InjectedFault("execute", "transient", 1))
        assert not _is_retryable(ValueError("x"))
        assert not _is_retryable(
            InjectedFault("execute", "fatal", 1))
