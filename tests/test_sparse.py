"""Block-sparse path tests (SURVEY.md §7.7, BASELINE row 4): representation
round-trips, XLA SpMM vs oracle, Pallas kernel in interpret mode, IR
integration."""

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.ops import spmm as spmm_lib


def random_block_sparse_np(rng, n, k, bs, density):
    """Host oracle generator: block-sparse numpy matrix."""
    gr, gc = n // bs, k // bs
    a = np.zeros((n, k), dtype=np.float32)
    nblocks = max(1, int(gr * gc * density))
    flat = rng.choice(gr * gc, size=nblocks, replace=False)
    for f in flat:
        bi, bj = f // gc, f % gc
        a[bi * bs:(bi + 1) * bs, bj * bs:(bj + 1) * bs] = rng.standard_normal((bs, bs))
    return a


class TestRepresentation:
    def test_from_numpy_roundtrip(self, mesh8, rng):
        a = random_block_sparse_np(rng, 32, 24, 8, 0.3)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        np.testing.assert_allclose(S.to_numpy(), a, rtol=1e-6)
        assert S.nnzb < (32 // 8) * (24 // 8)  # actually sparse

    def test_ragged_shape(self, mesh8, rng):
        a = np.zeros((13, 11), dtype=np.float32)
        a[0, 0] = 5.0
        a[12, 10] = 7.0
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        np.testing.assert_allclose(S.to_numpy(), a, rtol=1e-6)

    def test_to_dense(self, mesh8, rng):
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = S.to_dense()
        assert isinstance(D, BlockMatrix)
        np.testing.assert_allclose(D.to_numpy(), a, rtol=1e-6)

    def test_random_density(self, mesh8):
        S = BlockSparseMatrix.random((64, 64), block_density=0.25,
                                     block_size=8, mesh=mesh8, seed=3)
        assert S.nnzb == 16  # 64 blocks * 0.25
        assert S.density == pytest.approx(0.25)


class TestSpMM:
    def test_xla_spmm_matches_oracle(self, mesh8, rng):
        a = random_block_sparse_np(rng, 32, 24, 8, 0.3)
        d = rng.standard_normal((24, 16)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        cfg = MatrelConfig(use_pallas=False)
        out = spmm_lib.spmm(S, D, cfg)
        np.testing.assert_allclose(out.to_numpy(), a @ d, rtol=1e-4, atol=1e-4)

    def test_spmm_with_empty_rows(self, mesh8, rng):
        # entire block-rows with no tiles: output rows must be exactly zero
        a = np.zeros((32, 16), dtype=np.float32)
        a[8:16, 0:8] = rng.standard_normal((8, 8))  # only block-row 1
        d = rng.standard_normal((16, 8)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        out = spmm_lib.spmm(S, D, MatrelConfig(use_pallas=False))
        np.testing.assert_allclose(out.to_numpy(), a @ d, rtol=1e-4, atol=1e-4)

    def test_pallas_interpret_matches_oracle(self, mesh8, rng):
        a = random_block_sparse_np(rng, 32, 32, 8, 0.3)
        a[8:16, :] = 0  # leave an empty block-row for coverage-padding path
        d = rng.standard_normal((32, 16)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        out = spmm_lib.spmm(S, D, MatrelConfig(use_pallas=False),
                            interpret=True)
        np.testing.assert_allclose(out.to_numpy(), a @ d, rtol=1e-4, atol=1e-4)

    def test_pallas_interpret_bf16_payload(self, mesh8, rng):
        # bf16 payloads select DEFAULT contract precision (Mosaic rejects
        # fp32 contract on bf16 operands) and must still accumulate
        # row-runs in the f32 scratch
        import jax.numpy as jnp
        a = random_block_sparse_np(rng, 32, 32, 8, 0.5)
        d = rng.standard_normal((32, 16)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8,
                                         dtype=jnp.bfloat16)
        D = BlockMatrix.from_numpy(d.astype(jnp.bfloat16), mesh=mesh8)
        out = spmm_lib.spmm(S, D, MatrelConfig(use_pallas=False),
                            interpret=True)
        a16 = a.astype(jnp.bfloat16).astype(np.float32)
        d16 = d.astype(jnp.bfloat16).astype(np.float32)
        np.testing.assert_allclose(out.to_numpy().astype(np.float32),
                                   a16 @ d16, rtol=2e-2, atol=2e-2)

    def test_spmv(self, mesh8, rng):
        a = random_block_sparse_np(rng, 32, 32, 8, 0.4)
        v = rng.standard_normal((32, 1)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        V = BlockMatrix.from_numpy(v, mesh=mesh8)
        out = spmm_lib.spmv(S, V, MatrelConfig(use_pallas=False))
        np.testing.assert_allclose(out.to_numpy(), a @ v, rtol=1e-4, atol=1e-4)


class TestIRIntegration:
    def test_sparse_multiply_via_dsl(self, mesh8, rng):
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        d = rng.standard_normal((16, 16)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        e = S.multiply(D)
        np.testing.assert_allclose(e.compute().to_numpy(), a @ d,
                                   rtol=1e-4, atol=1e-4)

    def test_sparse_leaf_densifies_elsewhere(self, mesh8, rng):
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        e = S.expr().row_sum()
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   a.sum(1, keepdims=True), rtol=1e-4, atol=1e-4)

    def test_sparse_stats_feed_chain_dp(self, mesh8, rng):
        e = BlockSparseMatrix.random((64, 64), 0.1, block_size=8,
                                     mesh=mesh8).expr()
        assert e.nnz is not None
        assert e.density <= 0.15


class TestSparseTranspose:
    def test_transpose_roundtrip(self, mesh8, rng):
        a = random_block_sparse_np(rng, 24, 16, 8, 0.4)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        np.testing.assert_allclose(S.transpose().to_numpy(), a.T, rtol=1e-6)

    def test_dense_times_sparse_via_ir(self, mesh8, rng):
        a = rng.standard_normal((16, 24)).astype(np.float32)
        s_np = random_block_sparse_np(rng, 24, 16, 8, 0.4)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        S = BlockSparseMatrix.from_numpy(s_np, block_size=8, mesh=mesh8)
        e = A.expr().multiply(S.expr())
        np.testing.assert_allclose(e.compute().to_numpy(), a @ s_np,
                                   rtol=1e-4, atol=1e-4)


def test_session_plan_cache_distinguishes_sparse_matrices(mesh8, rng):
    # regression: two same-shaped sparse matrices must not share a cached
    # plan (tiles are captured as constants in the compiled program)
    from matrel_tpu.session import MatrelSession
    s1_np = random_block_sparse_np(rng, 16, 16, 8, 0.5)
    s2_np = -2.0 * s1_np
    d = rng.standard_normal((16, 8)).astype(np.float32)
    sess = MatrelSession(mesh=mesh8)
    D = BlockMatrix.from_numpy(d, mesh=mesh8)
    S1 = BlockSparseMatrix.from_numpy(s1_np, block_size=8, mesh=mesh8)
    S2 = BlockSparseMatrix.from_numpy(s2_np, block_size=8, mesh=mesh8)
    out1 = sess.compute(S1.multiply(D)).to_numpy()
    out2 = sess.compute(S2.multiply(D)).to_numpy()
    np.testing.assert_allclose(out1, s1_np @ d, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out2, s2_np @ d, rtol=1e-4, atol=1e-4)


class TestRightSparseMatmul:
    def test_dense_times_sparse_via_dsl(self, mesh8, rng):
        # A·S (sparse on the RIGHT) — regression: transpose() at trace
        # time turned tile metadata into tracers (found by tools/soak.py)
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.executor import compile_expr
        from matrel_tpu.ir import expr as E
        a = rng.standard_normal((5, 24)).astype(np.float32)
        sp_np = random_block_sparse_np(rng, 24, 16, 8, 0.5)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        S = BlockSparseMatrix.from_numpy(sp_np, block_size=8, mesh=mesh8)
        e = E.matmul(A.expr(), S.expr())
        out = compile_expr(e, mesh8, MatrelConfig()).run().to_numpy()
        np.testing.assert_allclose(out, a @ sp_np, rtol=1e-4, atol=1e-4)
        # the memoised transpose must hold CONCRETE arrays — the Pallas
        # builder reads its tile metadata on host (np.asarray), which is
        # exactly what crashed when transpose() ran inside the trace
        import jax
        st = S._transposed_memo
        assert st is not None
        assert not isinstance(st.block_rows, jax.core.Tracer)
        np.asarray(st.block_rows)   # host-readable
        # run twice: the memo is reused, results stay correct
        out2 = compile_expr(
            E.matmul(A.expr(), S.expr()), mesh8,
            MatrelConfig()).run().to_numpy()
        np.testing.assert_allclose(out2, a @ sp_np, rtol=1e-4, atol=1e-4)

class TestRunnerCacheHygiene:
    def test_runner_cache_purged_on_gc(self, mesh8, rng):
        # the Pallas runner bakes a permuted copy of the tile stack, so
        # cache entries must die with their matrix or HBM residency grows
        # ~2x tile stack per matrix built
        import gc
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        d = rng.standard_normal((16, 8)).astype(np.float32)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        spmm_lib.spmm(S, D, MatrelConfig(use_pallas=False), interpret=True)
        sid = id(S)
        assert any(k[0] == sid for k in spmm_lib._RUNNER_CACHE)
        del S
        gc.collect()
        assert not any(k[0] == sid for k in spmm_lib._RUNNER_CACHE)

    def test_blocks_reassignment_raises_on_pallas_path(self, mesh8, rng):
        # the baked payload cannot see a reassigned S.blocks; the XLA
        # fallback would honor it, so the Pallas runner fails loudly
        import jax.numpy as jnp
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        d = rng.standard_normal((16, 8)).astype(np.float32)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        cfg = MatrelConfig(use_pallas=False)
        spmm_lib.spmm(S, D, cfg, interpret=True)
        S.blocks = jnp.zeros_like(S.blocks)
        with pytest.raises(ValueError, match="reassigned"):
            spmm_lib.spmm(S, D, cfg, interpret=True)

    def test_runner_build_inside_trace_no_tracer_leak(self, mesh8, rng):
        # regression (2026-07-30): a runner-cache miss inside an outer
        # jit trace must not leak tracers into the cached closure —
        # the build-time payload permutation runs under
        # ensure_compile_time_eval
        import jax
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        d = rng.standard_normal((16, 8)).astype(np.float32)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        cfg = MatrelConfig(use_pallas=False)

        @jax.jit
        def f(dd):
            return spmm_lib.apply(S, dd, (16, 8), cfg, interpret=True)

        out1 = np.asarray(f(D.data))
        # fresh, independent use of the now-cached runner
        out2 = np.asarray(
            spmm_lib.apply(S, D.data, (16, 8), cfg, interpret=True))
        np.testing.assert_allclose(out1[:16, :8], a @ d, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(out2, out1, rtol=0, atol=0)

    def test_memo_rebuilt_for_runner_built_after_reassignment(self, mesh8,
                                                              rng):
        # a runner built AFTER S.blocks is reassigned must bake the NEW
        # stack, not reuse the memoised payload from the old one
        import jax.numpy as jnp
        a = random_block_sparse_np(rng, 16, 16, 8, 0.5)
        d = rng.standard_normal((16, 8)).astype(np.float32)
        d2 = rng.standard_normal((16, 16)).astype(np.float32)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        D2 = BlockMatrix.from_numpy(d2, mesh=mesh8)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        cfg = MatrelConfig(use_pallas=False)
        spmm_lib.spmm(S, D, cfg, interpret=True)     # memo built from a
        S.blocks = 2.0 * S.blocks                    # reassignment
        # different dense width -> cache miss -> fresh runner: must
        # compute with the NEW blocks
        out = spmm_lib.spmm(S, D2, cfg, interpret=True)
        np.testing.assert_allclose(out.to_numpy(), (2.0 * a) @ d2,
                                   rtol=1e-4, atol=1e-4)
        # the pre-reassignment runner still refuses loudly
        with pytest.raises(ValueError, match="reassigned"):
            spmm_lib.spmm(S, D, cfg, interpret=True)


class TestShardedSpMM:
    """ops/spmm_sharded.py — tile stack distributed over the mesh."""

    def test_matches_replicated_and_oracle(self, mesh8, rng):
        a = random_block_sparse_np(rng, 64, 48, 8, 0.4)
        d = rng.standard_normal((48, 16)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        Ssh = S.shard()
        out = Ssh.multiply(D).to_numpy()
        np.testing.assert_allclose(out, a @ d, rtol=1e-4, atol=1e-4)

    def test_stack_actually_sharded(self, mesh8, rng):
        a = random_block_sparse_np(rng, 64, 64, 8, 0.5)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        Ssh = S.shard()
        # 8 devices, each holding cap tiles of the padded stack
        assert len(Ssh.blocks.sharding.device_set) == 8
        assert Ssh.blocks.shape[0] == 8 * Ssh.cap
        shard_rows = {s.data.shape[0] for s in Ssh.blocks.addressable_shards}
        assert shard_rows == {Ssh.cap}

    def test_all_gather_in_hlo(self, mesh8, rng):
        import jax
        a = random_block_sparse_np(rng, 64, 64, 8, 0.5)
        d = rng.standard_normal((64, 8)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        Ssh = S.shard()
        from matrel_tpu.ops import spmm_sharded as sh
        from matrel_tpu.core import padding as pad_lib
        from matrel_tpu.config import default_config
        cfg = default_config()
        out_pshape = pad_lib.padded_shape((64, 8), mesh8)
        run = sh._sharded_spmm_runner(
            mesh8, 8, Ssh.grid[1], Ssh.rows_per_dev, Ssh.cap,
            BlockMatrix.from_numpy(d, mesh=mesh8).data.shape[1],
            tuple(out_pshape), jax.lax.Precision.HIGHEST)
        hlo = run.lower(Ssh.blocks, Ssh.brow_loc, Ssh.bcols,
                        BlockMatrix.from_numpy(d, mesh=mesh8).data
                        ).compile().as_text()
        assert "all-gather" in hlo

    def test_empty_and_clustered_rows(self, mesh8, rng):
        # all tiles in the top row-range: worst-case imbalance still
        # correct (padding_ratio reflects the skew)
        a = np.zeros((64, 64), np.float32)
        a[:8, :] = rng.standard_normal((8, 64))
        d = rng.standard_normal((64, 8)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        Ssh = S.shard()
        assert Ssh.padding_ratio >= 7.9     # 8 devices, 1 loaded
        np.testing.assert_allclose(Ssh.multiply(D).to_numpy(), a @ d,
                                   rtol=1e-4, atol=1e-4)

    def test_ragged_shapes(self, mesh8, rng):
        a = random_block_sparse_np(rng, 40, 24, 8, 0.5)
        a = np.pad(a, ((0, 3), (0, 5)))     # 43 x 29, ragged vs bs=8
        a[41, 27] = 2.5
        d = rng.standard_normal((29, 7)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        out = S.shard().multiply(D).to_numpy()
        np.testing.assert_allclose(out, a @ d, rtol=1e-4, atol=1e-4)

    def test_unsorted_stack_resorted(self, mesh8, rng):
        # hand-built stacks may violate the row-major invariant the
        # constructors maintain; shard() must re-sort, not corrupt
        import jax.numpy as jnp
        tiles = rng.standard_normal((4, 8, 8)).astype(np.float32)
        S = BlockSparseMatrix(
            blocks=jnp.asarray(tiles),
            block_rows=jnp.asarray([5, 0, 5, 2], jnp.int32),
            block_cols=jnp.asarray([1, 0, 0, 2], jnp.int32),
            shape=(64, 64), block_size=8, mesh=mesh8)
        a = np.zeros((64, 64), np.float32)
        for t, (br, bc) in zip(tiles, [(5, 1), (0, 0), (5, 0), (2, 2)]):
            a[br*8:(br+1)*8, bc*8:(bc+1)*8] += t
        d = rng.standard_normal((64, 8)).astype(np.float32)
        D = BlockMatrix.from_numpy(d, mesh=mesh8)
        out = S.shard().multiply(D).to_numpy()
        np.testing.assert_allclose(out, a @ d, rtol=1e-4, atol=1e-4)


def test_pallas_eligibility_gate():
    # bs=4 blocks violate Mosaic's (8, 128) block-shape rule on real TPU
    # (caught by the on-chip soak, seed 10026); such stacks must take the
    # XLA path. bs=512 at bench shapes stays eligible.
    from matrel_tpu.ops.pallas_spmm import pallas_eligible

    class FakeS:
        def __init__(self, bs, gr):
            self.block_size = bs
            self._gr = gr
        @property
        def grid(self):
            return (self._gr, self._gr)

    assert not pallas_eligible(FakeS(4, 3), 8)     # the soak failure shape
    assert pallas_eligible(FakeS(4, 1), 8)         # single row-block: equal dims
    assert pallas_eligible(FakeS(512, 196), 512)   # bench row 4 shape
    assert pallas_eligible(FakeS(8, 4), 16)        # small but 8-aligned


def test_block_sparse_norms(mesh8, rng):
    a = random_block_sparse_np(rng, 24, 24, 8, 0.4)
    S = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
    assert S.norm() == pytest.approx(np.linalg.norm(a), rel=1e-5)
    assert S.norm("l1") == pytest.approx(np.abs(a).sum(), rel=1e-5)
    assert S.norm("max") == pytest.approx(np.abs(a).max(), rel=1e-5)
    with pytest.raises(ValueError, match="norm kind"):
        S.norm("nuclear")


def test_pallas_interpret_config_routes_spmm(mesh8, rng, monkeypatch):
    """MatrelConfig(pallas_interpret=True) must route block-sparse SpMM
    through the Pallas kernel (interpret mode) on the CPU mesh — the
    same shared gate the compact SpMV paths use."""
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.ops import pallas_spmm, spmm as spmm_lib
    calls = []
    real = pallas_spmm.make_spmm
    monkeypatch.setattr(pallas_spmm, "make_spmm",
                        lambda *a, **k: calls.append(k) or real(*a, **k))
    sp = random_block_sparse_np(rng, 32, 24, 8, 0.5)
    d = rng.standard_normal((24, 8)).astype(np.float32)
    S = BlockSparseMatrix.from_numpy(sp, block_size=8, mesh=mesh8)
    D = BlockMatrix.from_numpy(d, mesh=mesh8)
    cfg = MatrelConfig(pallas_interpret=True)
    out = spmm_lib.spmm(S, D, cfg).to_numpy()
    np.testing.assert_allclose(out, sp @ d, rtol=1e-4, atol=1e-4)
    assert calls and calls[0].get("interpret") is True
    # default config on CPU keeps the XLA path (no new pallas runner)
    S2 = BlockSparseMatrix.from_numpy(sp, block_size=8, mesh=mesh8)
    n_before = len(calls)
    out2 = spmm_lib.spmm(S2, D, MatrelConfig()).to_numpy()
    np.testing.assert_allclose(out2, sp @ d, rtol=1e-4, atol=1e-4)
    assert len(calls) == n_before


def test_pallas_spmm_mesh_padding_exceeds_tile_grid(mesh8, rng):
    """Small-k sparse x dense on a big mesh: the dense operand's MESH
    padding (k→8 rows here) exceeds the tile grid extent (gc*bs = 4);
    the Pallas runner must slice the zero padding off, not crash
    (soak seed 50114 regression)."""
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.ops import spmm as spmm_lib
    sp = rng.standard_normal((4, 4)).astype(np.float32)
    sp[rng.random((4, 4)) < 0.5] = 0.0
    d = rng.standard_normal((4, 8)).astype(np.float32)
    S = BlockSparseMatrix.from_numpy(sp, block_size=4, mesh=mesh8)
    D = BlockMatrix.from_numpy(d, mesh=mesh8)
    out = spmm_lib.spmm(S, D, MatrelConfig(pallas_interpret=True)
                        ).to_numpy()
    np.testing.assert_allclose(out, sp @ d, rtol=1e-4, atol=1e-5)
