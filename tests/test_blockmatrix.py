"""BlockMatrix representation tests — the BasicMatrixOpsSuite analogue
(SURVEY.md §4): numerics vs numpy oracles on a simulated 8-device mesh."""

import numpy as np

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core import padding


def test_roundtrip_exact_shape(mesh8, rng):
    a = rng.standard_normal((16, 32)).astype(np.float32)
    bm = BlockMatrix.from_numpy(a, mesh=mesh8)
    np.testing.assert_allclose(bm.to_numpy(), a, rtol=1e-6)


def test_roundtrip_ragged_shape_pads(mesh8, rng):
    a = rng.standard_normal((13, 7)).astype(np.float32)
    bm = BlockMatrix.from_numpy(a, mesh=mesh8)
    assert bm.shape == (13, 7)
    assert bm.padded_shape[0] % 8 == 0 and bm.padded_shape[1] % 8 == 0
    np.testing.assert_allclose(bm.to_numpy(), a, rtol=1e-6)
    # padding region must be zero
    full = np.asarray(bm.data)
    assert np.all(full[13:, :] == 0) and np.all(full[:, 7:] == 0)


def test_vector_dims_not_padded(mesh8, rng):
    v = rng.standard_normal((10, 1)).astype(np.float32)
    bm = BlockMatrix.from_numpy(v, mesh=mesh8)
    assert bm.padded_shape[1] == 1  # size-1 dims stay unpadded/replicated
    np.testing.assert_allclose(bm.to_numpy(), v, rtol=1e-6)


def test_eye_and_zeros(mesh8):
    e = BlockMatrix.eye(9, mesh=mesh8)
    np.testing.assert_allclose(e.to_numpy(), np.eye(9, dtype=np.float32))
    z = BlockMatrix.zeros((5, 5), mesh=mesh8)
    assert z.nnz == 0
    np.testing.assert_allclose(z.to_numpy(), np.zeros((5, 5)))


def test_random_masks_padding(mesh8):
    bm = BlockMatrix.random((10, 10), mesh=mesh8, seed=1)
    full = np.asarray(bm.data)
    assert np.all(full[10:, :] == 0) and np.all(full[:, 10:] == 0)
    assert np.all(bm.to_numpy() >= 0) and np.all(bm.to_numpy() < 1)


def test_from_block_fn(mesh8):
    bm = BlockMatrix.from_block_fn((6, 6), lambda r, c: (r * 6 + c), mesh=mesh8)
    expect = np.arange(36, dtype=np.float32).reshape(6, 6)
    np.testing.assert_allclose(bm.to_numpy(), expect)


def test_sharding_is_distributed(mesh8, rng):
    a = rng.standard_normal((64, 64)).astype(np.float32)
    bm = BlockMatrix.from_numpy(a, mesh=mesh8)
    # data actually lives across all 8 devices
    assert len({s.device for s in bm.data.addressable_shards}) == 8


def test_with_spec_reshards(mesh8, rng):
    from jax.sharding import PartitionSpec as P
    a = rng.standard_normal((32, 32)).astype(np.float32)
    bm = BlockMatrix.from_numpy(a, mesh=mesh8)
    row = bm.with_spec(P(("x", "y"), None))
    np.testing.assert_allclose(row.to_numpy(), a, rtol=1e-6)
    assert row.spec != bm.spec


def test_padding_rules(mesh8):
    assert padding.pad_dim(1, 8) == 1
    assert padding.pad_dim(7, 8) == 8
    assert padding.pad_dim(8, 8) == 8
    assert padding.pad_dim(9, 8) == 16
    spec = padding.canonical_spec((16, 1), mesh8)
    assert spec[1] is None
