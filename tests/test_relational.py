"""Relational operator tests — σ/γ/⋈ semantics vs numpy oracles
(SURVEY.md §3.4; the MatRel-paper relational exec suite analogue)."""

import jax.numpy as jnp
import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.relational import ops as R


def bm(arr, mesh, **kw):
    return BlockMatrix.from_numpy(np.asarray(arr, dtype=np.float32), mesh=mesh, **kw)


class TestSelection:
    def test_select_entries_value_predicate(self, mesh8, rng):
        a = rng.standard_normal((9, 9)).astype(np.float32)
        A = bm(a, mesh8)
        out = R.select_entries(A, lambda v: v > 0).compute().to_numpy()
        np.testing.assert_allclose(out, np.where(a > 0, a, 0), rtol=1e-6)

    def test_select_entries_custom_fill(self, mesh8):
        a = np.array([[1.0, -2.0], [-3.0, 4.0]], dtype=np.float32)
        out = R.select_entries(bm(a, mesh8), lambda v: v > 0, fill=-1.0)
        np.testing.assert_allclose(out.compute().to_numpy(),
                                   [[1.0, -1.0], [-1.0, 4.0]])

    def test_select_rows(self, mesh8, rng):
        a = rng.standard_normal((10, 6)).astype(np.float32)
        out = R.select_rows(bm(a, mesh8), lambda i: i % 2 == 0)
        expect = a.copy()
        expect[1::2, :] = 0
        np.testing.assert_allclose(out.compute().to_numpy(), expect, rtol=1e-6)

    def test_select_cols(self, mesh8, rng):
        a = rng.standard_normal((6, 10)).astype(np.float32)
        out = R.select_cols(bm(a, mesh8), lambda j: j < 3)
        expect = a.copy()
        expect[:, 3:] = 0
        np.testing.assert_allclose(out.compute().to_numpy(), expect, rtol=1e-6)

    def test_select_blocks(self, mesh8):
        a = np.ones((8, 8), dtype=np.float32)
        # 4x4 blocks: keep only the diagonal blocks
        out = R.select_blocks(bm(a, mesh8), lambda bi, bj: bi == bj,
                              block_size=4)
        got = out.compute().to_numpy()
        assert got[:4, :4].sum() == 16 and got[4:, 4:].sum() == 16
        assert got[:4, 4:].sum() == 0 and got[4:, :4].sum() == 0

    def test_selection_composes_with_matmul(self, mesh8, rng):
        # σ then multiply: masked semantics must flow through the algebra
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((8, 8)).astype(np.float32)
        A, B = bm(a, mesh8), bm(b, mesh8)
        e = R.select_entries(A, lambda v: v > 0).multiply(B.expr())
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   np.where(a > 0, a, 0) @ b,
                                   rtol=1e-4, atol=1e-4)


class TestAggregation:
    def test_all_kinds_all_axes(self, mesh8, rng):
        a = rng.standard_normal((7, 7)).astype(np.float32)
        a[a < 0.3] = 0  # make count/avg interesting
        A = bm(a, mesh8)
        cases = {
            ("sum", "row"): a.sum(1, keepdims=True),
            ("sum", "col"): a.sum(0, keepdims=True),
            ("sum", "all"): a.sum().reshape(1, 1),
            ("sum", "diag"): np.trace(a).reshape(1, 1),
            ("count", "row"): (a != 0).sum(1, keepdims=True).astype(np.float32),
            ("count", "all"): np.asarray((a != 0).sum(), np.float32).reshape(1, 1),
            ("max", "row"): a.max(1, keepdims=True),
            ("min", "col"): a.min(0, keepdims=True),
        }
        for (kind, axis), expect in cases.items():
            got = R.aggregate(A, kind, axis).compute().to_numpy()
            np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5,
                                       err_msg=f"{kind}/{axis}")

    def test_avg_counts_nonzero_only(self, mesh8):
        a = np.array([[2.0, 0.0, 4.0]], dtype=np.float32)
        got = R.aggregate(bm(a, mesh8), "avg", "row").compute().to_numpy()
        np.testing.assert_allclose(got, [[3.0]])  # (2+4)/2 nonzero entries


class TestJoins:
    def test_join_on_index(self, mesh8, rng):
        a = rng.standard_normal((6, 6)).astype(np.float32)
        b = rng.standard_normal((6, 6)).astype(np.float32)
        e = R.join_on_index(bm(a, mesh8), bm(b, mesh8), lambda x, y: x * y + 1)
        # merge(0,0)=1 in the padded region must NOT leak (masked)
        out = e.compute()
        np.testing.assert_allclose(out.to_numpy(), a * b + 1, rtol=1e-5)
        full = np.asarray(out.data)
        assert np.all(full[6:, :] == 0)

    def test_join_on_rows(self, mesh8):
        a = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
        b = np.array([[10.0], [20.0]], dtype=np.float32)
        e = R.join_on_rows(bm(a, mesh8), bm(b, mesh8), lambda x, y: x + y)
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   [[11.0, 12.0], [23.0, 24.0]])

    def test_join_on_cols(self, mesh8):
        a = np.array([[1.0, 2.0]], dtype=np.float32)
        b = np.array([[10.0, 20.0], [30.0, 40.0]], dtype=np.float32)
        e = R.join_on_cols(bm(a, mesh8), bm(b, mesh8), lambda x, y: y - x)
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   [[9.0, 18.0], [29.0, 38.0]])

    def test_join_on_values(self, mesh8):
        a = np.array([[1.0, 2.0]], dtype=np.float32)       # entries 1,2
        b = np.array([[2.0], [3.0]], dtype=np.float32)     # entries 2,3
        e = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x * y,
                             predicate=lambda x, y: x == y)
        out = e.compute().to_numpy()
        assert out.shape == (2, 2)
        # only the pair (2,2) matches → value 4 at (entry#2 of A, entry#1 of B)
        assert out.sum() == pytest.approx(4.0)
        assert out[1, 0] == pytest.approx(4.0)

    def test_index_join_then_aggregate(self, mesh8, rng):
        # the paper's pattern: join on index, filter, then aggregate
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((8, 8)).astype(np.float32)
        e = R.join_on_index(bm(a, mesh8), bm(b, mesh8), jnp.maximum)
        s = R.aggregate(e, "sum", "all").compute().to_numpy()[0, 0]
        assert s == pytest.approx(np.maximum(a, b).sum(), rel=1e-4)
