"""Relational operator tests — σ/γ/⋈ semantics vs numpy oracles
(SURVEY.md §3.4; the MatRel-paper relational exec suite analogue)."""

import jax.numpy as jnp
import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.relational import ops as R


def bm(arr, mesh, **kw):
    return BlockMatrix.from_numpy(np.asarray(arr, dtype=np.float32), mesh=mesh, **kw)


class TestSelection:
    def test_select_entries_value_predicate(self, mesh8, rng):
        a = rng.standard_normal((9, 9)).astype(np.float32)
        A = bm(a, mesh8)
        out = R.select_entries(A, lambda v: v > 0).compute().to_numpy()
        np.testing.assert_allclose(out, np.where(a > 0, a, 0), rtol=1e-6)

    def test_select_entries_custom_fill(self, mesh8):
        a = np.array([[1.0, -2.0], [-3.0, 4.0]], dtype=np.float32)
        out = R.select_entries(bm(a, mesh8), lambda v: v > 0, fill=-1.0)
        np.testing.assert_allclose(out.compute().to_numpy(),
                                   [[1.0, -1.0], [-1.0, 4.0]])

    def test_select_rows(self, mesh8, rng):
        a = rng.standard_normal((10, 6)).astype(np.float32)
        out = R.select_rows(bm(a, mesh8), lambda i: i % 2 == 0)
        expect = a.copy()
        expect[1::2, :] = 0
        np.testing.assert_allclose(out.compute().to_numpy(), expect, rtol=1e-6)

    def test_select_cols(self, mesh8, rng):
        a = rng.standard_normal((6, 10)).astype(np.float32)
        out = R.select_cols(bm(a, mesh8), lambda j: j < 3)
        expect = a.copy()
        expect[:, 3:] = 0
        np.testing.assert_allclose(out.compute().to_numpy(), expect, rtol=1e-6)

    def test_select_blocks(self, mesh8):
        a = np.ones((8, 8), dtype=np.float32)
        # 4x4 blocks: keep only the diagonal blocks
        out = R.select_blocks(bm(a, mesh8), lambda bi, bj: bi == bj,
                              block_size=4)
        got = out.compute().to_numpy()
        assert got[:4, :4].sum() == 16 and got[4:, 4:].sum() == 16
        assert got[:4, 4:].sum() == 0 and got[4:, :4].sum() == 0

    def test_selection_composes_with_matmul(self, mesh8, rng):
        # σ then multiply: masked semantics must flow through the algebra
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((8, 8)).astype(np.float32)
        A, B = bm(a, mesh8), bm(b, mesh8)
        e = R.select_entries(A, lambda v: v > 0).multiply(B.expr())
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   np.where(a > 0, a, 0) @ b,
                                   rtol=1e-4, atol=1e-4)


class TestAggregation:
    def test_all_kinds_all_axes(self, mesh8, rng):
        a = rng.standard_normal((7, 7)).astype(np.float32)
        a[a < 0.3] = 0  # make count/avg interesting
        A = bm(a, mesh8)
        cases = {
            ("sum", "row"): a.sum(1, keepdims=True),
            ("sum", "col"): a.sum(0, keepdims=True),
            ("sum", "all"): a.sum().reshape(1, 1),
            ("sum", "diag"): np.trace(a).reshape(1, 1),
            ("count", "row"): (a != 0).sum(1, keepdims=True).astype(np.float32),
            ("count", "all"): np.asarray((a != 0).sum(), np.float32).reshape(1, 1),
            ("max", "row"): a.max(1, keepdims=True),
            ("min", "col"): a.min(0, keepdims=True),
        }
        for (kind, axis), expect in cases.items():
            got = R.aggregate(A, kind, axis).compute().to_numpy()
            np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5,
                                       err_msg=f"{kind}/{axis}")

    def test_avg_counts_nonzero_only(self, mesh8):
        a = np.array([[2.0, 0.0, 4.0]], dtype=np.float32)
        got = R.aggregate(bm(a, mesh8), "avg", "row").compute().to_numpy()
        np.testing.assert_allclose(got, [[3.0]])  # (2+4)/2 nonzero entries


class TestJoins:
    def test_join_on_index(self, mesh8, rng):
        a = rng.standard_normal((6, 6)).astype(np.float32)
        b = rng.standard_normal((6, 6)).astype(np.float32)
        e = R.join_on_index(bm(a, mesh8), bm(b, mesh8), lambda x, y: x * y + 1)
        # merge(0,0)=1 in the padded region must NOT leak (masked)
        out = e.compute()
        np.testing.assert_allclose(out.to_numpy(), a * b + 1, rtol=1e-5)
        full = np.asarray(out.data)
        assert np.all(full[6:, :] == 0)

    def test_join_on_rows(self, mesh8):
        a = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
        b = np.array([[10.0], [20.0]], dtype=np.float32)
        e = R.join_on_rows(bm(a, mesh8), bm(b, mesh8), lambda x, y: x + y)
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   [[11.0, 12.0], [23.0, 24.0]])

    def test_join_on_cols(self, mesh8):
        a = np.array([[1.0, 2.0]], dtype=np.float32)
        b = np.array([[10.0, 20.0], [30.0, 40.0]], dtype=np.float32)
        e = R.join_on_cols(bm(a, mesh8), bm(b, mesh8), lambda x, y: y - x)
        np.testing.assert_allclose(e.compute().to_numpy(),
                                   [[9.0, 18.0], [29.0, 38.0]])

    def test_join_on_values(self, mesh8):
        a = np.array([[1.0, 2.0]], dtype=np.float32)       # entries 1,2
        b = np.array([[2.0], [3.0]], dtype=np.float32)     # entries 2,3
        e = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x * y,
                             predicate=lambda x, y: x == y)
        out = e.compute().to_numpy()
        assert out.shape == (2, 2)
        # only the pair (2,2) matches → value 4 at (entry#2 of A, entry#1 of B)
        assert out.sum() == pytest.approx(4.0)
        assert out[1, 0] == pytest.approx(4.0)

    def test_index_join_then_aggregate(self, mesh8, rng):
        # the paper's pattern: join on index, filter, then aggregate
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((8, 8)).astype(np.float32)
        e = R.join_on_index(bm(a, mesh8), bm(b, mesh8), jnp.maximum)
        s = R.aggregate(e, "sum", "all").compute().to_numpy()[0, 0]
        assert s == pytest.approx(np.maximum(a, b).sum(), rel=1e-4)


def _pair_oracle(a, b, merge, pred, kind, axis):
    """Dense numpy oracle: build the full pair matrix, aggregate it with
    the dense lowering's rules (count = nonzero entries; max/min over
    merged-or-zero; avg = sum/count)."""
    va = np.asarray(a, np.float32).T.reshape(-1)
    vb = np.asarray(b, np.float32).T.reshape(-1)
    P = merge(va[:, None], vb[None, :]).astype(np.float64)
    if pred is not None:
        P = np.where(pred(va[:, None], vb[None, :]), P, 0.0)
    ax = {"row": 1, "col": 0, "all": None}[axis]
    if kind == "sum":
        return P.sum(axis=ax)
    if kind == "count":
        return (P != 0).sum(axis=ax).astype(np.float64)
    if kind == "avg":
        s = P.sum(axis=ax)
        c = (P != 0).sum(axis=ax)
        return np.where(c > 0, s / np.maximum(c, 1), 0.0)
    red = np.max if kind == "max" else np.min
    return red(P, axis=ax)


_NP_PREDS = {"eq": np.equal, "lt": np.less, "le": np.less_equal,
             "gt": np.greater, "ge": np.greater_equal}
_NP_MERGES = {"left": lambda x, y: x + 0 * y,
              "right": lambda x, y: y + 0 * x,
              "add": np.add, "mul": np.multiply}


class TestValueJoinStreaming:
    """agg(join_on_value) must stream — sort-based for structured
    forms, capped chunk enumeration for callables — and match the
    dense pair-matrix oracle bit-for-rule."""

    @pytest.mark.parametrize("pred", ["eq", "lt", "le", "gt", "ge"])
    @pytest.mark.parametrize("merge", ["left", "right", "add", "mul"])
    def test_sorted_grid_row(self, mesh8, rng, pred, merge):
        # duplicate values + zeros + sign mix stress every range rule
        pool = np.array([-2.0, -1.0, 0.0, 0.0, 1.0, 1.0, 2.0, 3.0],
                        np.float32)
        a = rng.choice(pool, size=(4, 3)).astype(np.float32)
        b = rng.choice(pool, size=(3, 4)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8), merge=merge,
                             predicate=pred)
        for kind in ("sum", "count", "avg", "max", "min"):
            got = R.aggregate(j, kind, "row").compute().to_numpy()[:, 0]
            want = _pair_oracle(a, b, _NP_MERGES[merge],
                                _NP_PREDS[pred], kind, "row")
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{pred}/{merge}/{kind}")

    @pytest.mark.parametrize("axis", ["col", "all"])
    def test_sorted_other_axes(self, mesh8, rng, axis):
        pool = np.array([-1.0, 0.0, 0.5, 1.0, 1.0], np.float32)
        a = rng.choice(pool, size=(3, 4)).astype(np.float32)
        b = rng.choice(pool, size=(5, 2)).astype(np.float32)
        for pred in ("eq", "gt"):
            for merge in ("add", "mul"):
                j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                                     merge=merge, predicate=pred)
                for kind in ("sum", "count", "max", "min"):
                    out = R.aggregate(j, kind, axis).compute().to_numpy()
                    got = out[0] if axis == "col" else out[0, 0]
                    want = _pair_oracle(a, b, _NP_MERGES[merge],
                                        _NP_PREDS[pred], kind, axis)
                    np.testing.assert_allclose(
                        got, want, rtol=1e-5, atol=1e-5,
                        err_msg=f"{axis}/{pred}/{merge}/{kind}")

    def test_no_predicate_streams(self, mesh8, rng):
        a = rng.standard_normal((4, 4)).astype(np.float32)
        b = rng.standard_normal((4, 4)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8), merge="add")
        got = R.aggregate(j, "sum", "row").compute().to_numpy()[:, 0]
        want = _pair_oracle(a, b, np.add, None, "sum", "row")
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)

    def test_callable_chunked_matches_oracle(self, mesh8, rng):
        a = rng.standard_normal((5, 3)).astype(np.float32)
        b = rng.standard_normal((4, 4)).astype(np.float32)
        merge = lambda x, y: x * x + y          # not a structured form
        pred = lambda x, y: x + y > 0.3
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8), merge=merge,
                             predicate=pred)
        for kind, axis in (("sum", "row"), ("count", "col"),
                           ("max", "all"), ("min", "row"),
                           ("avg", "col")):
            out = R.aggregate(j, kind, axis).compute().to_numpy()
            got = {"row": out[:, 0], "col": out[0],
                   "all": out[0, 0]}[axis]
            want = _pair_oracle(a, b, merge, pred, kind, axis)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{kind}/{axis}")

    def test_diag_agg_elementwise(self, mesh8, rng):
        a = rng.standard_normal((3, 3)).astype(np.float32)
        b = rng.standard_normal((3, 3)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8), merge="mul",
                             predicate="gt")
        out = R.aggregate(j, "sum", "diag").compute().to_numpy()[0, 0]
        va = a.T.reshape(-1)
        vb = b.T.reshape(-1)
        want = np.where(va > vb, va * vb, 0.0).sum()
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

    def test_4k_by_4k_streams_without_pair_alloc(self, mesh8, rng):
        # 4096² entries each side → 16.7M × 16.7M pairs (~1.1 PB f32 if
        # materialised). The sort path must aggregate it in O(n log n);
        # finishing at all IS the no-allocation proof. Constructed
        # values give a closed-form oracle.
        n = 4096
        a = np.zeros((n, n), np.float32)
        a[0, 0] = 3.0            # one positive entry; rest zeros
        b = np.full((n, n), 2.0, np.float32)
        b[0, 0] = 5.0
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8), merge="mul",
                             predicate="lt")      # va < vb
        nb = n * n
        # row entry 0 (va=3): matches only vb=5 → sum 15. Zero entries
        # of A match every vb>0 (all of them) but merge mul → 0.
        s = R.aggregate(j, "sum", "all").compute().to_numpy()[0, 0]
        np.testing.assert_allclose(s, 15.0, rtol=1e-6)
        c = R.aggregate(j, "count", "all").compute().to_numpy()[0, 0]
        np.testing.assert_allclose(c, 1.0)
        # per-row: row 0 sums 15, every other row 0
        rs = R.aggregate(j, "sum", "row").compute().to_numpy()
        assert rs.shape == (n * n, 1)
        np.testing.assert_allclose(rs[0, 0], 15.0, rtol=1e-6)
        assert float(np.abs(rs[1:]).max()) == 0.0

    def test_materialising_large_join_refused(self, mesh8, rng):
        n = 128   # 16384 entries/side → 2.7e8 pairs > default cap 6.7e7
        a = rng.standard_normal((n, n)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(a, mesh8), merge="add",
                             predicate="eq")
        from matrel_tpu.executor import execute
        with pytest.raises(ValueError, match="join_pair_cap_entries"):
            execute(j, mesh8)

    def test_blackbox_over_cap_refused(self, mesh8, rng):
        n = 192   # 36864 entries/side → 1.36e9 pairs > brute cap 2.7e8
        a = rng.standard_normal((n, n)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(a, mesh8),
                             merge=lambda x, y: x - y,
                             predicate=lambda x, y: x > y)
        from matrel_tpu.executor import execute
        with pytest.raises(ValueError,
                           match="join_bruteforce_max_pairs"):
            execute(R.aggregate(j, "sum", "row"), mesh8)

    def test_row_col_join_size_guard(self, mesh8, rng):
        a = bm(np.zeros((2, 8), np.float32), mesh8)
        # fabricate a huge logical join via expr shapes: (2, 8) rows ⋈
        # (2, m) rows gives (2, 8*m) — pick m so entries exceed the cap
        big = bm(np.zeros((2, 8), np.float32), mesh8)
        from matrel_tpu.ir import expr as E
        node = E.MatExpr("join_rows",
                         (a.expr(), big.expr()),
                         (1 << 13, 1 << 14), None,
                         {"merge": lambda x, y: x + y})
        from matrel_tpu.executor import execute
        with pytest.raises(ValueError, match="join_pair_cap_entries"):
            execute(node, mesh8)


class TestJoinSchemeSelection:
    """The planner must pick the SMALLER operand to replicate, and the
    choice must flip when the operand sizes flip (SURVEY.md §2
    relational execs: join-scheme selection to minimize replication)."""

    def _scheme(self, a, b, mesh, joiner):
        from matrel_tpu.parallel import planner as pl
        e = joiner(a, b, lambda x, y: x + y)
        ann = pl.annotate_strategies(e, mesh)
        return ann.attrs["replicate"]

    def test_row_join_replicates_smaller_and_flips(self, mesh8, rng):
        small = bm(rng.standard_normal((8, 4)), mesh8)
        big = bm(rng.standard_normal((8, 64)), mesh8)
        assert self._scheme(small, big, mesh8, R.join_on_rows) == "left"
        assert self._scheme(big, small, mesh8, R.join_on_rows) == "right"

    def test_col_join_replicates_smaller_and_flips(self, mesh8, rng):
        small = bm(rng.standard_normal((4, 8)), mesh8)
        big = bm(rng.standard_normal((64, 8)), mesh8)
        assert self._scheme(small, big, mesh8, R.join_on_cols) == "left"
        assert self._scheme(big, small, mesh8, R.join_on_cols) == "right"

    def test_scheme_annotation_runs_through_executor(self, mesh8, rng):
        # the annotated plan must still produce oracle results
        a = rng.standard_normal((6, 3)).astype(np.float32)
        b = rng.standard_normal((6, 5)).astype(np.float32)
        e = R.join_on_rows(bm(a, mesh8), bm(b, mesh8),
                           lambda x, y: x * y)
        got = e.compute().to_numpy()
        want = (a[:, :, None] * b[:, None, :]).reshape(6, 15)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_streaming_join_nan_semantics(mesh8):
    # dense lowering: pred(NaN, .) is False -> NaN rows/cols contribute
    # nothing under comparison predicates; the sorted streaming path
    # must agree (NaNs clamp out of every range)
    a = np.array([[1.0, np.nan], [0.5, 2.0]], np.float32)
    b = np.array([[np.nan, 1.5]], np.float32)
    j = R.join_on_values(bm(a, mesh8), bm(b, mesh8), merge="left",
                         predicate="lt")
    got = R.aggregate(j, "count", "row").compute().to_numpy()[:, 0]
    va = a.T.reshape(-1)
    vb = b.T.reshape(-1)
    with np.errstate(invalid="ignore"):
        P = np.where(va[:, None] < vb[None, :], va[:, None], 0.0)
    want = (np.nan_to_num(P) != 0).sum(axis=1)
    np.testing.assert_allclose(got, want)
    s = R.aggregate(j, "sum", "all").compute().to_numpy()[0, 0]
    np.testing.assert_allclose(s, np.nan_to_num(P).sum(), rtol=1e-6)


class TestValueJoinEdgeCases:
    @pytest.mark.parametrize("case", ["ones_1x1", "zeros", "identical",
                                      "extreme"])
    def test_degenerate_inputs(self, mesh8, case):
        a, b = {
            "ones_1x1": (np.ones((1, 1)), np.ones((1, 1))),
            "zeros": (np.zeros((3, 3)), np.zeros((2, 2))),
            "identical": (np.full((4, 4), 2.5), np.full((3, 3), 2.5)),
            "extreme": (np.array([[1e30, -1e30], [1e-30, 1.0]]),
                        np.array([[1e30], [-1e-30]])),
        }[case]
        a = a.astype(np.float32)
        b = b.astype(np.float32)
        for pred in ("eq", "le"):
            for kind in ("sum", "count", "max", "min"):
                j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                                     merge="add", predicate=pred)
                got = R.aggregate(j, kind, "row").compute().to_numpy()
                want = _pair_oracle(a, b, np.add, _NP_PREDS[pred],
                                    kind, "row")
                np.testing.assert_allclose(
                    got[:, 0], want, rtol=1e-4, atol=1e-6,
                    err_msg=f"{case}/{pred}/{kind}")


class TestChunkedExtremaNonFinite:
    """ADVICE r2: legitimate ±inf extrema from the callable (chunked)
    value-join path must surface, not be masked to 0 — only PADDED slots
    are sentinel-masked."""

    def test_inf_operand_max_survives(self, mesh8):
        a = np.array([[np.inf, 1.0]], dtype=np.float32)
        b = np.array([[2.0, 3.0]], dtype=np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x * y)
        got = R.aggregate(j, "max", "row").compute().to_numpy()[:, 0]
        np.testing.assert_allclose(got, [np.inf, 3.0])

    def test_neg_inf_min_survives(self, mesh8):
        a = np.array([[-np.inf, 1.0]], dtype=np.float32)
        b = np.array([[2.0, 3.0]], dtype=np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x + y)
        got = R.aggregate(j, "min", "row").compute().to_numpy()[:, 0]
        # no predicate → every pair matches → no implicit zeros: row 1's
        # min is min(1+2, 1+3) = 3
        np.testing.assert_allclose(got, [-np.inf, 3.0])

    def test_finite_inputs_unchanged(self, mesh8, rng):
        a = rng.standard_normal((3, 3)).astype(np.float32)
        b = rng.standard_normal((2, 2)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x * y,
                             predicate=lambda x, y: x < y)
        got = R.aggregate(j, "max", "row").compute().to_numpy()[:, 0]
        # pair-matrix entry order is column-major over (i, j)
        va, vb = a.ravel(order="F"), b.ravel(order="F")
        pairs = np.where(va[:, None] < vb[None, :],
                         va[:, None] * vb[None, :], 0.0)
        np.testing.assert_allclose(got, pairs.max(1), rtol=1e-5)


class TestJoinSchemeLayoutCredit:
    """Join-scheme v2 (VERDICT r2 #3): an operand ALREADY replicated on
    the mesh replicates for free — it must win even when larger; density
    still credits bytes for sharded operands."""

    def _scheme(self, a, b, mesh, joiner=None):
        from matrel_tpu.parallel import planner as pl
        joiner = joiner or R.join_on_rows
        e = joiner(a, b, lambda x, y: x + y)
        return pl.annotate_strategies(e, mesh).attrs["replicate"]

    def test_replicated_but_larger_operand_wins(self, mesh8, rng):
        from jax.sharding import PartitionSpec as P
        big_rep = BlockMatrix.from_numpy(
            rng.standard_normal((8, 64)).astype(np.float32),
            mesh=mesh8, spec=P(None, None))
        small_sharded = bm(rng.standard_normal((8, 4)), mesh8)
        assert self._scheme(big_rep, small_sharded, mesh8) == "left"
        assert self._scheme(small_sharded, big_rep, mesh8) == "right"

    def test_density_credit_flips_choice(self, mesh8, rng):
        # sparse-big has fewer credited bytes than dense-small (credited
        # ratio kept >8x so the v3 align scheme is not competitive and
        # the left-vs-right density credit itself is what's exercised)
        dense_small = bm(rng.standard_normal((8, 16)), mesh8)
        a = np.zeros((8, 256), dtype=np.float32)
        a[:, :1] = 1.0                      # ~0.4% dense
        sparse_big = BlockMatrix.from_numpy(a, mesh=mesh8, nnz=8)
        assert self._scheme(sparse_big, dense_small, mesh8) == "left"
        assert self._scheme(dense_small, sparse_big, mesh8) == "right"

    def test_size_flip_unchanged(self, mesh8, rng):
        # the v1 behaviour (smaller side replicates) still holds for
        # same-layout operands
        small = bm(rng.standard_normal((8, 4)), mesh8)
        big = bm(rng.standard_normal((8, 64)), mesh8)
        assert self._scheme(small, big, mesh8) == "left"
        assert self._scheme(big, small, mesh8) == "right"


class TestJoinSchemeV3PartialLayouts:
    """Join-scheme v3 (VERDICT r3 #5): per-layout cost terms. An operand
    whose existing row/col sharding matches the join axis is consumed IN
    PLACE (reshard term zero) via the new "align" scheme instead of
    being charged a full (p-1)/p all-gather."""

    def _scheme(self, a, b, mesh, joiner=None):
        from matrel_tpu.parallel import planner as pl
        joiner = joiner or R.join_on_rows
        e = joiner(a, b, lambda x, y: x + y)
        return pl.annotate_strategies(e, mesh).attrs["replicate"]

    def test_colsharded_larger_beats_2d_smaller_for_coljoin(self, mesh8,
                                                            rng):
        # the VERDICT flip test: v2 replicated the smaller 2D operand
        # (full all-gather); v3 keeps the col-sharded larger operand in
        # place and just re-lays the small one — "align"
        from jax.sharding import PartitionSpec as P
        big_col = BlockMatrix.from_numpy(
            rng.standard_normal((64, 8)).astype(np.float32),
            mesh=mesh8, spec=P(None, ("x", "y")))
        small_2d = bm(rng.standard_normal((4, 8)), mesh8)
        assert self._scheme(big_col, small_2d, mesh8,
                            R.join_on_cols) == "align"
        assert self._scheme(small_2d, big_col, mesh8,
                            R.join_on_cols) == "align"

    def test_rowsharded_operand_in_place_for_rowjoin(self, mesh8, rng):
        from jax.sharding import PartitionSpec as P
        big_row = BlockMatrix.from_numpy(
            rng.standard_normal((8, 64)).astype(np.float32),
            mesh=mesh8, spec=P(("x", "y"), None))
        small_2d = bm(rng.standard_normal((8, 4)), mesh8)
        assert self._scheme(big_row, small_2d, mesh8,
                            R.join_on_rows) == "align"

    def test_align_gated_when_axis_smaller_than_mesh(self, mesh8, rng):
        # review r4: with fewer join-axis rows than devices the align
        # constraint degenerates to XLA full rematerialization — the
        # planner must fall back to replicating the smaller side
        a = bm(rng.standard_normal((4, 32)), mesh8)
        b = bm(rng.standard_normal((4, 32)), mesh8)
        assert self._scheme(a, b, mesh8, R.join_on_rows) in ("left",
                                                             "right")

    def test_similar_sized_2d_operands_align(self, mesh8, rng):
        # two cheap redistributions beat one full broadcast when the
        # operands are comparable in size
        a = bm(rng.standard_normal((8, 32)), mesh8)
        b = bm(rng.standard_normal((8, 32)), mesh8)
        assert self._scheme(a, b, mesh8) == "align"

    def test_align_hlo_avoids_full_operand_allgather(self, mesh8, rng):
        # the Catalyst-plan-assertion analogue for the align scheme:
        # replicate ("left") all-gathers the ENTIRE operand; align must
        # not — it redistributes shards (all-to-all family) instead
        import re

        from matrel_tpu import executor as executor_lib
        a = bm(rng.standard_normal((32, 16)), mesh8)
        b = bm(rng.standard_normal((32, 16)), mesh8)

        def hlo(scheme):
            e = R.join_on_rows(a, b, "mul").with_attrs(replicate=scheme)
            return executor_lib.compile_expr(e, mesh8).hlo()

        full_op_ag = re.compile(r"f32\[32,16\]\{[0-9,]*\} all-gather")
        assert full_op_ag.search(hlo("left"))
        # only the ABSENCE is pinned (test_strategies.py convention):
        # which reshard collective XLA picks for the redistribution is
        # backend/version-dependent
        assert not full_op_ag.search(hlo("align"))

    def test_align_scheme_numerics_match_oracle(self, mesh8, rng):
        # the executor's align lowering (both sides constrained to the
        # join axis) must produce oracle results — row and col joins
        a = rng.standard_normal((8, 6)).astype(np.float32)
        b = rng.standard_normal((8, 6)).astype(np.float32)
        e = R.join_on_rows(bm(a, mesh8), bm(b, mesh8),
                           lambda x, y: x * y)
        from matrel_tpu.parallel import planner as pl
        ann = pl.annotate_strategies(e, mesh8)
        assert ann.attrs["replicate"] == "align"
        got = ann.compute().to_numpy()
        want = (a[:, :, None] * b[:, None, :]).reshape(8, 36)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

        ac = rng.standard_normal((6, 8)).astype(np.float32)
        bc = rng.standard_normal((6, 8)).astype(np.float32)
        ec = R.join_on_cols(bm(ac, mesh8), bm(bc, mesh8),
                            lambda x, y: x + y)
        annc = pl.annotate_strategies(ec, mesh8)
        assert annc.attrs["replicate"] == "align"
        gotc = annc.compute().to_numpy()
        wantc = (ac[:, None, :] + bc[None, :, :]).reshape(36, 8)
        np.testing.assert_allclose(gotc, wantc, rtol=1e-5, atol=1e-5)


class TestChunkedJoinShardedQuerySide:
    """round-3: the callable (chunked) aggregated value-join shards its
    query side over the mesh like the sorted path; results must match
    the oracle at sizes that cross the sharding threshold."""

    def test_row_agg_large_callable_join(self, mesh8, rng):
        # 48x48 A = 2304 entries > 128 * 8 -> query side shards
        a = rng.standard_normal((48, 48)).astype(np.float32)
        b = rng.standard_normal((8, 8)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x * y + x,
                             predicate=lambda x, y: x < y)
        got = R.aggregate(j, "sum", "row").compute().to_numpy()[:, 0]
        va = a.T.reshape(-1)
        vb = b.T.reshape(-1)
        pairs = np.where(va[:, None] < vb[None, :],
                         va[:, None] * vb[None, :] + va[:, None], 0.0)
        np.testing.assert_allclose(got, pairs.sum(1), rtol=1e-4,
                                   atol=1e-4)

    def test_col_agg_swapped_roles(self, mesh8, rng):
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((48, 48)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x + 2 * y)
        got = R.aggregate(j, "max", "col").compute().to_numpy()[0]
        va = a.T.reshape(-1)
        vb = b.T.reshape(-1)
        pairs = va[:, None] + 2 * vb[None, :]
        np.testing.assert_allclose(got, pairs.max(0), rtol=1e-4,
                                   atol=1e-4)

    def test_all_agg_reduces_across_shards(self, mesh8, rng):
        a = rng.standard_normal((64, 64)).astype(np.float32)
        b = rng.standard_normal((4, 4)).astype(np.float32)
        j = R.join_on_values(bm(a, mesh8), bm(b, mesh8),
                             merge=lambda x, y: x * y,
                             predicate=lambda x, y: x > y)
        got = R.aggregate(j, "sum", "all").compute().to_numpy()[0, 0]
        va = a.T.reshape(-1)
        vb = b.T.reshape(-1)
        pairs = np.where(va[:, None] > vb[None, :],
                         va[:, None] * vb[None, :], 0.0)
        assert got == pytest.approx(pairs.sum(), rel=1e-3)
