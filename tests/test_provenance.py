"""Answer provenance ledger (obs tier 4, matrel_tpu/obs/provenance.py)
— per-path lineage records, the `why` console, audit replay (including
a seeded-corruption catch), the obs_provenance=0 zero-overhead
contract, and MV115 stamp coherence both statically and dynamically."""

import dataclasses
import types

import numpy as np
import pytest

from matrel_tpu import analysis
from matrel_tpu.analysis import provenance_pass
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E, rules
from matrel_tpu.obs import provenance as provenance_lib
from matrel_tpu.obs.events import read_events
from matrel_tpu.parallel import planner
from matrel_tpu.session import MatrelSession


def _session(mesh, **cfg):
    cfg.setdefault("obs_provenance", 64)
    cfg.setdefault("result_cache_max_bytes", 1 << 26)
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


def _dense(rng, n, m, mesh):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh)


def _annotated(e, mesh, cfg=None):
    cfg = cfg or MatrelConfig()
    grid = (mesh.shape[mesh.axis_names[0]],
            mesh.shape[mesh.axis_names[1]])
    return planner.annotate_strategies(
        rules.optimize(e, cfg, grid=grid, mesh=mesh), mesh, cfg)


def _mv115(diags):
    return [d for d in diags if d.code == "MV115"]


def _paths(sess):
    return [r.path for r in sess._prov.records()]


class TestLedgerCapture:
    """One record per served answer, path refined by the mechanism
    stamps the entry carries."""

    def test_execute_then_hit_then_interior(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 48, 64, mesh8)
        B = _dense(rng, 64, 32, mesh8)
        q = A.expr().multiply(B.expr())
        sess.run(q)
        sess.run(A.expr().multiply(B.expr()))
        sess.run(A.expr().multiply(B.expr()).multiply_scalar(2.0))
        assert _paths(sess) == ["execute", "rc_hit", "rc_interior"]
        recs = sess._prov.records()
        # every record replayable: live expr + result references held
        assert all(r.expr is not None and r.result is not None
                   for r in recs)
        # the interior record names its substitution-leaf ancestry
        cache = recs[2].summary["cache"]
        assert cache["kind"] == "interior"
        assert len(cache["leaves"]) == 1
        assert cache["leaves"][0]["provenance"]["query_id"] == \
            recs[0].query_id
        # the whole hit carries the producing entry's stamp
        whole = recs[1].summary["cache"]
        assert whole["kind"] == "whole"
        assert whole["entry"]["provenance"]["query_id"] == \
            recs[0].query_id

    def test_execute_record_carries_strategy_stamps(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 48, 64, mesh8)
        B = _dense(rng, 64, 32, mesh8)
        sess.run(A.expr().multiply(B.expr()))
        (rec,) = sess._prov.records()
        assert rec.summary["strategies"], "execute without planner stamps"
        assert all("strategy" in s for s in rec.summary["strategies"])

    def test_ivm_patched_record_carries_chain(self, rng, mesh8):
        sess = _session(mesh8)
        adj = (rng.random((32, 32)) < 0.2).astype(np.float32)
        sess.register("A", sess.from_numpy(adj, integral=True))

        def q():
            return sess.table("A").expr().multiply(
                sess.table("A").expr())

        sess.run(q())
        for gen in range(2):
            rows = rng.integers(0, 32, 4)
            cols = rng.integers(0, 32, 4)
            sess.register_delta(
                "A", (rows, cols, np.ones(4, np.float32)), kind="coo")
        sess.run(q())
        rec = sess._prov.records()[-1]
        assert rec.path == "ivm_patched"
        ivm = rec.summary["cache"]["ivm"]
        # two composed patches in order, gen climbing
        assert [c["gen"] for c in ivm["chain"]] == \
            sorted(c["gen"] for c in ivm["chain"])
        assert len(ivm["chain"]) == 2
        # integer path counts: the composed bound stays exact
        assert rec.err_bound == 0.0

    def test_degraded_record_stamps_rung(self, rng, mesh8):
        sess = _session(
            mesh8, fault_inject="execute:transient:p=1.0:max=4",
            retry_max_attempts=4, retry_backoff_ms=0.5)
        A = _dense(rng, 32, 48, mesh8)
        B = _dense(rng, 48, 16, mesh8)
        sess.run(A.expr().multiply(B.expr()))
        rec = sess._prov.records()[-1]
        assert rec.path == "degraded"
        assert rec.rung == 4
        assert rec.summary["degrade"]["rung"] == 4

    def test_stale_capture_carries_grant(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr())
        sess.run(e)
        (_, ent), = sess._result_cache.items_snapshot()
        sess._prov_capture_stale(
            e, ent, {"sla": None, "staleness_ms": 125.0,
                     "tenant": "t0"})
        rec = sess._prov.records()[-1]
        assert rec.path == "stale"
        assert rec.summary["stale"] == {"staleness_ms": 125.0,
                                        "tenant": "t0"}

    def test_fleet_directory_hop_recorded(self, rng, mesh8):
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig(
            fleet_slices=2, obs_provenance=64,
            result_cache_max_bytes=1 << 26))
        try:
            sess.register("A", sess.from_numpy(
                rng.standard_normal((64, 64)).astype(np.float32)))
            fq = sess.table("A").expr().multiply(
                sess.table("A").expr())
            sess.submit(fq).result(timeout=120)
            sess.serve_drain()
            # repeat submits until placement prefers the non-owning
            # slice and the answer crosses the directory
            for _ in range(6):
                sess.submit(fq).result(timeout=120)
                sess.serve_drain()
                if any(p.startswith("fleet") for p in _paths(sess)):
                    break
            recs = [r for r in sess._prov.records()
                    if r.path.startswith("fleet")]
            assert recs, f"no fleet hop in {_paths(sess)}"
            hop = recs[0].summary["fleet"]
            assert {"owner", "serving"} <= set(hop)
            assert provenance_pass.verify_ledger(sess) == []
        finally:
            sess.serve_close()

    def test_bounded_ledger_evicts_oldest(self, rng, mesh8):
        sess = _session(mesh8, obs_provenance=3)
        A = _dense(rng, 16, 16, mesh8)
        for i in range(5):
            sess.run(A.expr().multiply_scalar(float(i + 1)))
        info = sess.provenance_info()
        assert info["records"] == 3 and info["cap"] == 3
        assert info["captured"] == 5

    def test_provenance_event_emitted(self, rng, mesh8, tmp_path):
        log = str(tmp_path / "events.jsonl")
        sess = _session(mesh8, obs_level="on", obs_event_log=log)
        A = _dense(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        evs = read_events(log, kinds=("provenance",))
        assert len(evs) == 1
        assert evs[0]["path"] == "execute"
        assert evs[0]["schema"] == provenance_lib.SCHEMA_VERSION


class TestWhyConsole:
    def test_why_filters_and_render(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 32, 32, mesh8)
        out1 = sess.run(A.expr().multiply(A.expr()))
        out2 = sess.run(A.expr().multiply(A.expr()))
        assert len(sess.why()) == 2
        assert sess.why(last=1)[0]["path"] == "rc_hit"
        # BlockMatrix identity: both serves returned the cached object
        assert out1 is out2
        assert {s["path"] for s in sess.why(out2)} == \
            {"execute", "rc_hit"}
        # query-id and key-hash lookup route through find()
        qid = sess.why()[0]["query_id"]
        assert sess.why(qid)[0]["query_id"] == qid
        khash = sess.why()[0]["key_hash"]
        assert len(sess.why(khash)) == 2
        text = provenance_lib.render(sess.why(last=1)[0])
        assert "path=rc_hit" in text and "cache: whole hit" in text

    def test_why_off_session_returns_empty(self, rng, mesh8):
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        A = _dense(rng, 16, 16, mesh8)
        sess.run(A.expr().t())
        assert sess.why() == []
        assert sess.provenance_info()["records"] == 0

    def test_cli_renders_from_event_log(self, rng, mesh8, tmp_path,
                                        capsys):
        log = str(tmp_path / "events.jsonl")
        sess = _session(mesh8, obs_level="on", obs_event_log=log)
        A = _dense(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        sess.run(A.expr().multiply(A.expr()))
        args = types.SimpleNamespace(audit=False, log=log, key=None,
                                     last=10)
        assert provenance_lib.main(args) == 0
        out = capsys.readouterr().out
        assert "path=execute" in out and "path=rc_hit" in out


class TestAuditReplay:
    def test_audit_proves_all_paths(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 48, 64, mesh8)
        B = _dense(rng, 64, 32, mesh8)
        sess.run(A.expr().multiply(B.expr()))
        sess.run(A.expr().multiply(B.expr()))
        sess.run(A.expr().multiply(B.expr()).multiply_scalar(2.0))
        verdict = provenance_lib.audit(sess, sample=0)
        assert verdict["ok"]
        assert verdict["sampled"] == verdict["replayable"] == 3
        assert verdict["failed"] == 0
        # f32 executes are exact-path: bit-equal, not tolerance-passed
        assert all(r["exact"] for r in verdict["results"])

    def test_audit_catches_seeded_corruption(self, rng, mesh8):
        # the tier-4 acceptance: tamper a cached answer through the
        # cache's own patch seam, re-serve it, and the audit replay
        # must catch the lie and (under --check) exit nonzero
        cfg = MatrelConfig(obs_provenance=64,
                           result_cache_max_bytes=1 << 26)
        sess = MatrelSession(mesh=mesh8, config=cfg)
        A = _dense(rng, 32, 48, mesh8)
        B = _dense(rng, 48, 16, mesh8)
        sess.run(A.expr().multiply(B.expr()))
        (key, ent), = sess._result_cache.items_snapshot()
        corrupt = BlockMatrix.from_numpy(
            ent.result.to_numpy() + 1.0, mesh=mesh8)
        tampered = dataclasses.replace(ent, result=corrupt)
        assert sess._result_cache.apply_patch(
            key, key, tampered, cfg.result_cache_max_bytes,
            cfg.result_cache_max_entries)
        served = sess.run(A.expr().multiply(B.expr()))
        np.testing.assert_array_equal(served.to_numpy(),
                                      corrupt.to_numpy())
        verdict = provenance_lib.audit(sess, sample=0)
        assert not verdict["ok"]
        bad = [r for r in verdict["results"] if not r["ok"]]
        assert bad and bad[0]["path"] == "rc_hit"
        assert bad[0]["rel_err"] > 0.0

    def test_cli_audit_check_exit_codes(self, rng, mesh8, monkeypatch,
                                        capsys):
        # cheap CLI-contract check: swap the self-contained workload
        # for small sessions (clean, then tampered) and assert the
        # --check verdict drives the exit code
        clean = _session(mesh8)
        A = _dense(rng, 24, 24, mesh8)
        clean.run(A.expr().multiply(A.expr()))
        monkeypatch.setattr(provenance_lib, "_audit_workload",
                            lambda: clean)
        args = types.SimpleNamespace(audit=True, sample=0, check=True)
        assert provenance_lib.main(args) == 0
        assert "-> OK" in capsys.readouterr().out

        cfg = MatrelConfig(obs_provenance=64,
                           result_cache_max_bytes=1 << 26)
        dirty = MatrelSession(mesh=mesh8, config=cfg)
        dirty.run(A.expr().multiply(A.expr()))
        (key, ent), = dirty._result_cache.items_snapshot()
        tampered = dataclasses.replace(
            ent, result=BlockMatrix.from_numpy(
                ent.result.to_numpy() * 1.5 + 0.25, mesh=mesh8))
        dirty._result_cache.apply_patch(
            key, key, tampered, cfg.result_cache_max_bytes,
            cfg.result_cache_max_entries)
        dirty.run(A.expr().multiply(A.expr()))
        monkeypatch.setattr(provenance_lib, "_audit_workload",
                            lambda: dirty)
        assert provenance_lib.main(args) == 1
        assert "FAILED" in capsys.readouterr().out


class TestZeroOverhead:
    def test_default_config_builds_no_ledger_objects(self, rng, mesh8,
                                                     monkeypatch):
        # the structural-zero contract, poisoned-__init__-enforced:
        # obs_provenance=0 (the default) must construct ZERO ledger
        # objects anywhere on the serve path
        def no_ledgers(self, *a, **kw):
            raise AssertionError(
                "ProvenanceLedger constructed with obs_provenance=0")

        def no_records(self, *a, **kw):
            raise AssertionError(
                "ProvenanceRecord constructed with obs_provenance=0")

        monkeypatch.setattr(provenance_lib.ProvenanceLedger,
                            "__init__", no_ledgers)
        monkeypatch.setattr(provenance_lib.ProvenanceRecord,
                            "__init__", no_records)
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig(
            result_cache_max_bytes=1 << 26))
        assert sess._prov is None
        A = _dense(rng, 48, 64, mesh8)
        B = _dense(rng, 64, 32, mesh8)
        sess.run_many([A.expr().multiply(B.expr())])
        sess.run(A.expr().multiply(B.expr()))                # hit
        sess.run(A.expr().multiply(B.expr()).multiply_scalar(2.0))
        # no stamps either: entries and leaves stay provenance-free
        for _, ent in sess._result_cache.items_snapshot():
            assert ent.provenance is None
        assert sess.why() == []


class TestMV115:
    """Stamp coherence — static (annotated-tree) and dynamic
    (ledger-record) halves, both directions each."""

    def test_live_substitution_is_clean(self, rng, mesh8):
        sess = _session(mesh8)
        X = _dense(rng, 64, 16, mesh8)
        gram = X.expr().t().multiply(X.expr())
        sess.run(gram)
        B = _dense(rng, 16, 16, mesh8)
        substituted = sess._rc_substitute(gram.multiply(B.expr()))
        leaves = [c for c in substituted.children
                  if c.attrs.get("result_cache")]
        assert leaves and all(
            isinstance(c.attrs.get("provenance"), dict)
            for c in leaves)
        diags = analysis.verify_plan(_annotated(substituted, mesh8),
                                     mesh8)
        assert _mv115(diags) == []

    def _leaf(self, rng, mesh, provenance, result_cache="default"):
        bm = _dense(rng, 32, 32, mesh)
        if result_cache == "default":
            result_cache = {"key_hash": "cafe", "layout": "row",
                            "dtype": "float32", "deps": []}
        leaf = E.leaf(bm).with_attrs(provenance=provenance)
        if result_cache is not None:
            leaf = leaf.with_attrs(result_cache=result_cache)
        return leaf

    def _diags(self, rng, mesh, provenance, result_cache="default"):
        leaf = self._leaf(rng, mesh, provenance, result_cache)
        B = _dense(rng, 32, 32, mesh)
        return _mv115(analysis.verify_plan(
            _annotated(leaf.multiply(B.expr()), mesh), mesh))

    def _stamp(self, **kw):
        s = {"schema": provenance_lib.SCHEMA_VERSION, "path": "rc_hit",
             "query_id": "p1", "key_hash": "cafe"}
        s.update(kw)
        return s

    def test_coherent_stamp_is_clean(self, rng, mesh8):
        assert self._diags(rng, mesh8, self._stamp()) == []

    def test_non_dict_stamp_warns(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8, "p1:rc_hit")
        assert d.severity == "warning" and "ML015" in d.message

    def test_schema_drift_warns(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8, self._stamp(schema=99))
        assert "schema" in d.message

    def test_unknown_path_warns_never_errors(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8,
                           self._stamp(path="teleported"))
        assert d.severity == "warning"
        assert "unknown serve path 'teleported'" in d.message

    def test_stamp_without_result_cache_warns(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8, self._stamp(),
                           result_cache=None)
        assert "without a result_cache stamp" in d.message

    def test_key_hash_disagreement_warns(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8,
                           self._stamp(key_hash="beef"))
        assert "disagree" in d.message

    def test_ivm_claim_without_delta_stamp_warns(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8,
                           self._stamp(path="ivm_patched"))
        assert "no delta stamp" in d.message

    def test_delta_stamp_without_ivm_claim_warns(self, rng, mesh8):
        rc = {"key_hash": "cafe", "layout": "row", "dtype": "float32",
              "deps": [], "delta": {"gen": 3, "rule": "coo"}}
        (d,) = self._diags(rng, mesh8, self._stamp(), rc)
        assert "claims path 'rc_hit'" in d.message

    def test_replica_claim_without_fleet_stamp_warns(self, rng, mesh8):
        (d,) = self._diags(rng, mesh8,
                           self._stamp(path="fleet_replica"))
        assert "no fleet stamp" in d.message

    def test_fleet_stamp_without_replica_claim_warns(self, rng, mesh8):
        rc = {"key_hash": "cafe", "layout": "row", "dtype": "float32",
              "deps": [], "fleet": {"owner": 0}}
        (d,) = self._diags(rng, mesh8, self._stamp(), rc)
        assert "omits the inter-slice hop" in d.message

    def test_verify_ledger_clean_and_off(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        sess.run(A.expr().multiply(A.expr()))
        assert provenance_pass.verify_ledger(sess) == []
        off = MatrelSession(mesh=mesh8, config=MatrelConfig())
        assert provenance_pass.verify_ledger(off) == []

    def test_verify_ledger_flags_incoherent_records(self, rng, mesh8):
        sess = _session(mesh8)
        A = _dense(rng, 16, 16, mesh8)
        sess.run(A.expr().t())

        def fake(path, rung=0, **summary):
            summary.setdefault("schema",
                               provenance_lib.SCHEMA_VERSION)
            return provenance_lib.ProvenanceRecord(
                query_id="px", path=path, key="k", key_hash="beef",
                sla="f32", rung=rung, err_bound=0.0, ts=0.0,
                summary=summary)

        # one incoherent record per direction the pass checks
        bad = [
            fake("teleported"),
            fake("execute", schema=99),
            fake("ivm_patched"),
            fake("rc_hit", cache={"ivm": {"gen": 2}}),
            fake("fleet_replica"),
            fake("degraded"),
            fake("execute", rung=0, degrade={"rung": 2}),
            fake("stale"),
        ]
        sess._prov._records.extend(bad)
        diags = provenance_pass.verify_ledger(sess)
        assert len(diags) == len(bad)
        assert all(d.code == "MV115" and d.severity == "warning"
                   for d in diags)
        # limit bounds the check to the newest N records
        assert provenance_pass.verify_ledger(sess, limit=1)
        assert len(provenance_pass.verify_ledger(sess)) == len(bad)
