"""IO formats, CLI, and autotune smoke tests."""

import json
import subprocess
import sys

import numpy as np
import pytest

from matrel_tpu import io as mio
from matrel_tpu.core.blockmatrix import BlockMatrix


class TestIO:
    def test_npy_roundtrip(self, mesh8, rng, tmp_path):
        a = rng.standard_normal((12, 9)).astype(np.float32)
        p = str(tmp_path / "a.npy")
        np.save(p, a)
        m = mio.load_npy(p, mesh=mesh8)
        np.testing.assert_allclose(m.to_numpy(), a, rtol=1e-6)
        p2 = str(tmp_path / "b.npy")
        mio.save_npy(p2, m)
        np.testing.assert_allclose(np.load(p2), a, rtol=1e-6)

    def test_coo_csv_dense_and_sparse(self, mesh8, tmp_path):
        p = str(tmp_path / "m.csv")
        with open(p, "w") as f:
            f.write("0,0,1.5\n2,3,-2.0\n0,0,0.5\n")  # duplicate sums
        m = mio.load_coo_csv(p, (4, 5), mesh=mesh8, dense=True)
        got = m.to_numpy()
        assert got[0, 0] == pytest.approx(2.0)
        assert got[2, 3] == pytest.approx(-2.0)
        s = mio.load_coo_csv(p, (4, 5), mesh=mesh8, block_size=2)
        np.testing.assert_allclose(s.to_numpy(), got, rtol=1e-6)

    def test_mtx(self, mesh8, tmp_path):
        import scipy.io, scipy.sparse
        dense = np.zeros((6, 6), np.float32)
        dense[1, 2] = 3.25
        dense[5, 0] = -1.0
        p = str(tmp_path / "m.mtx")
        scipy.io.mmwrite(p, scipy.sparse.coo_matrix(dense))
        s = mio.load_mtx(p, mesh=mesh8, block_size=4)
        np.testing.assert_allclose(s.to_numpy(), dense, rtol=1e-6)

    def test_tiled_roundtrip(self, mesh8, rng, tmp_path):
        a = rng.standard_normal((20, 13)).astype(np.float32)
        m = BlockMatrix.from_numpy(a, mesh=mesh8)
        d = str(tmp_path / "tiles")
        mio.save_tiled(d, m, tile=8)
        m2 = mio.load_tiled(d, mesh=mesh8)
        np.testing.assert_allclose(m2.to_numpy(), a, rtol=1e-6)


class TestAutotune:
    def test_returns_admissible_best(self, mesh8):
        from matrel_tpu.parallel.autotune import autotune_matmul
        best, table = autotune_matmul(64, 64, 64, mesh=mesh8)
        assert best in table and len(table) >= 3
        assert all(t > 0 for t in table.values())
        # cached second call
        best2, _ = autotune_matmul(64, 64, 64, mesh=mesh8)
        assert best2 == best


class TestCLI:
    def _run(self, *args):
        import os
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        return subprocess.run(
            [sys.executable, "-m", "matrel_tpu", *args],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=240)

    def test_info(self):
        r = self._run("info")
        assert r.returncode == 0, r.stderr
        out = json.loads(r.stdout)
        assert out["backend"] == "cpu" and "mesh" in out

    def test_sql_oneshot(self, tmp_path):
        p = str(tmp_path / "x.npy")
        np.save(p, np.eye(3, dtype=np.float32) * 2)
        r = self._run("sql", "trace(X)", "--table", f"X={p}")
        assert r.returncode == 0, r.stderr
        assert "6." in r.stdout
