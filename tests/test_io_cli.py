"""IO formats, CLI, and autotune smoke tests."""

import json
import subprocess
import sys

import numpy as np
import pytest

from matrel_tpu import io as mio
from matrel_tpu.core.blockmatrix import BlockMatrix


class TestIO:
    def test_npy_roundtrip(self, mesh8, rng, tmp_path):
        a = rng.standard_normal((12, 9)).astype(np.float32)
        p = str(tmp_path / "a.npy")
        np.save(p, a)
        m = mio.load_npy(p, mesh=mesh8)
        np.testing.assert_allclose(m.to_numpy(), a, rtol=1e-6)
        p2 = str(tmp_path / "b.npy")
        mio.save_npy(p2, m)
        np.testing.assert_allclose(np.load(p2), a, rtol=1e-6)

    def test_coo_csv_dense_and_sparse(self, mesh8, tmp_path):
        p = str(tmp_path / "m.csv")
        with open(p, "w") as f:
            f.write("0,0,1.5\n2,3,-2.0\n0,0,0.5\n")  # duplicate sums
        m = mio.load_coo_csv(p, (4, 5), mesh=mesh8, dense=True)
        got = m.to_numpy()
        assert got[0, 0] == pytest.approx(2.0)
        assert got[2, 3] == pytest.approx(-2.0)
        s = mio.load_coo_csv(p, (4, 5), mesh=mesh8, block_size=2)
        np.testing.assert_allclose(s.to_numpy(), got, rtol=1e-6)

    def test_mtx(self, mesh8, tmp_path):
        import scipy.io, scipy.sparse
        dense = np.zeros((6, 6), np.float32)
        dense[1, 2] = 3.25
        dense[5, 0] = -1.0
        p = str(tmp_path / "m.mtx")
        scipy.io.mmwrite(p, scipy.sparse.coo_matrix(dense))
        s = mio.load_mtx(p, mesh=mesh8, block_size=4)
        np.testing.assert_allclose(s.to_numpy(), dense, rtol=1e-6)

    def test_mtx_coo(self, mesh8, rng, tmp_path):
        import scipy.io, scipy.sparse
        r = rng.integers(0, 300, 2000)
        c = rng.integers(0, 200, 2000)
        v = rng.standard_normal(2000).astype(np.float32)
        S = scipy.sparse.coo_matrix((v, (r, c)), shape=(300, 200))
        p = str(tmp_path / "g.mtx")
        scipy.io.mmwrite(p, S)
        A = mio.load_mtx_coo(p)
        assert A.shape == (300, 200)
        x = rng.standard_normal(200).astype(np.float32)
        np.testing.assert_allclose(np.asarray(A.matvec(x)),
                                   S.tocsr() @ x, rtol=3e-4, atol=3e-4)
        # symmetric file: native reader must expand the mirror entries
        Ssym = scipy.sparse.coo_matrix(
            np.array([[2.0, 1.0, 0], [1.0, 0, 0], [0, 0, 3.0]],
                     np.float32))
        p2 = str(tmp_path / "sym.mtx")
        scipy.io.mmwrite(p2, Ssym, symmetry="symmetric")
        B = mio.load_mtx_coo(p2)
        np.testing.assert_allclose(B.to_dense(), Ssym.toarray())

    def test_tiled_roundtrip(self, mesh8, rng, tmp_path):
        a = rng.standard_normal((20, 13)).astype(np.float32)
        m = BlockMatrix.from_numpy(a, mesh=mesh8)
        d = str(tmp_path / "tiles")
        mio.save_tiled(d, m, tile=8)
        m2 = mio.load_tiled(d, mesh=mesh8)
        np.testing.assert_allclose(m2.to_numpy(), a, rtol=1e-6)


class TestAutotune:
    def test_returns_admissible_best(self, mesh8):
        from matrel_tpu.parallel.autotune import autotune_matmul
        best, table = autotune_matmul(64, 64, 64, mesh=mesh8)
        # best may be None under the tie rule (noisy host); when named
        # it must be a measured admissible strategy
        assert best is None or best in table
        assert len(table) >= 3
        assert all(t > 0 for t in table.values())
        # cached second call
        best2, _ = autotune_matmul(64, 64, 64, mesh=mesh8)
        assert best2 == best

    def test_pick_winner_tie_rule(self):
        from matrel_tpu.parallel.autotune import _pick_winner
        # clear winner (runner-up >10% slower)
        assert _pick_winner({"rmm": 1.0, "cpmm": 1.2}) == "rmm"
        # tie within 10%: no measured winner — byte model decides
        assert _pick_winner({"rmm": 1.0, "cpmm": 1.05}) is None
        assert _pick_winner({}) is None
        # one-variant "comparison" proves nothing (review r5: the gate
        # moved INSIDE _pick_winner — one policy for both loops)
        assert _pick_winner({"xla": 0.5}) is None


class TestAutotuneLoop:
    """Closed autotune loop (VERDICT r2 #4): config.autotune lets a
    MEASURED winner override the cost model's matmul pick, and the
    table persists across sessions (process-cache clears)."""

    def _choose(self, mesh, cfg, n=64, k=64, m=64, rng=None):
        import numpy as np
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel import planner
        rng = rng or np.random.default_rng(7)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((n, k)).astype(np.float32), mesh=mesh)
        B = BlockMatrix.from_numpy(
            rng.standard_normal((k, m)).astype(np.float32), mesh=mesh)
        node = A.expr().multiply(B.expr())
        return planner.choose_strategy(node, mesh, cfg)

    def test_measured_winner_overrides_model(self, mesh8, tmp_path):
        import json
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "tuned.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        base = self._choose(mesh8, MatrelConfig())
        # plant a measured table naming a DIFFERENT admissible strategy
        forced = "rmm" if base != "rmm" else "cpmm"
        json.dump({autotune._table_key(64, 2, 4, "float32"): {"best": forced,
                                      "times": {forced: 1e-6}}},
                  open(path, "w"))
        autotune._CACHE.clear()
        assert self._choose(mesh8, cfg) == forced
        assert base != forced

    def test_table_persists_measurement(self, mesh8, tmp_path,
                                        monkeypatch):
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        # deterministic timings (>10% apart) so the winner is stable
        # regardless of host noise
        fake = {"bmm_left": 5.0, "bmm_right": 4.0, "cpmm": 1.0,
                "rmm": 2.0, "summa": 3.0, "xla": 6.0}
        monkeypatch.setattr(
            autotune, "measure_strategy",
            lambda s, A, B, cfg, **kw: fake[s])
        path = str(tmp_path / "tuned.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        best = autotune.lookup_or_measure(64, 64, 64, mesh8,
                                          "float32", cfg)
        assert best == "cpmm"
        table = autotune.load_table(path)
        assert table[autotune._table_key(64, 2, 4, "float32")]["best"] == best
        # a fresh process (cache cleared) reads the file, no re-measure
        autotune._CACHE.clear()
        monkeypatch.setattr(autotune, "measure_strategy",
                            lambda *a, **kw: 1 / 0)
        assert autotune.lookup_or_measure(
            64, 64, 64, mesh8, "float32", cfg) == best

    def test_interior_chain_multiply_consults_table(self, mesh8,
                                                    tmp_path):
        # VERDICT r3 #3: the measured table must cover every matmul
        # node, not just leaf×leaf — an operand that is ITSELF a matmul
        # (the interior product of a chain) now has an inferred dtype
        # and consults the table
        import json

        import numpy as np
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel import autotune, planner
        rng = np.random.default_rng(3)

        def mk(n, m):
            return BlockMatrix.from_numpy(
                rng.standard_normal((n, m)).astype(np.float32),
                mesh=mesh8).expr()

        A, B, C = mk(64, 64), mk(64, 64), mk(64, 64)
        outer = A.multiply(B.multiply(C))
        base = planner.choose_strategy(outer, mesh8, MatrelConfig())
        forced = "rmm" if base != "rmm" else "cpmm"
        path = str(tmp_path / "tuned.json")
        json.dump({autotune._table_key(64, 2, 4, "float32"): {"best": forced,
                                      "times": {forced: 1e-6}}},
                  open(path, "w"))
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        autotune._CACHE.clear()
        annotated = planner.annotate_strategies(outer, mesh8, cfg)
        assert annotated.attrs["strategy"] == forced          # leaf×interior
        assert annotated.children[1].attrs["strategy"] == forced

    def test_infer_dtype_propagation(self, mesh8):
        import numpy as np
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel.planner import infer_dtype
        rng = np.random.default_rng(5)

        def mk(dtype):
            return BlockMatrix.from_numpy(
                rng.standard_normal((16, 16)).astype(np.float32),
                mesh=mesh8, dtype=dtype).expr()

        f32, bf16 = mk("float32"), mk("bfloat16")
        cfg = MatrelConfig()          # keep_input_dtype=True
        assert infer_dtype(bf16.multiply(bf16), cfg) == np.dtype("bfloat16")
        assert infer_dtype(bf16.t().multiply(bf16), cfg) == np.dtype(
            "bfloat16")
        # mixed-dtype multiply accumulates (and stays) f32
        assert infer_dtype(f32.multiply(bf16), cfg) == np.dtype("float32")
        # promotion through elementwise; preservation through agg/scalar
        assert infer_dtype(f32.add(bf16), cfg) == np.dtype("float32")
        assert infer_dtype(bf16.row_sum().multiply_scalar(2.0),
                           cfg) == np.dtype("bfloat16")
        # interior product feeds dtype upward
        assert infer_dtype(bf16.multiply(bf16).multiply(bf16),
                           cfg) == np.dtype("bfloat16")
        # keep_input_dtype=False: bf16 matmul accumulates f32
        nc = MatrelConfig(keep_input_dtype=False)
        assert infer_dtype(bf16.multiply(bf16), nc) == np.dtype("float32")
        # unknown: user-callable join merge; structured merges promote
        assert infer_dtype(f32.join_on_index(f32, lambda a, b: a > b),
                           cfg) is None
        assert infer_dtype(f32.join_on_index(bf16, "add"),
                           cfg) == np.dtype("float32")
        from matrel_tpu.relational import ops as R
        assert infer_dtype(R.join_on_rows(bf16, bf16, "mul"),
                           cfg) == np.dtype("bfloat16")

    def test_empty_persisted_entry_remeasures(self, mesh8, tmp_path,
                                              monkeypatch):
        # review r4: a persisted entry with EMPTY times (e.g. from a
        # transiently broken backend) must not read as a measurement —
        # the shape class is re-measured on a healthy process, and an
        # empty result set is never persisted in the first place
        import json
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "tuned.json")
        json.dump({autotune._table_key(64, 2, 4, "float32"): {"best": None, "times": {}}},
                  open(path, "w"))
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        autotune._CACHE.clear()
        called = {}

        def fake_measure(s, A, B, c, **kw):
            called[s] = True
            return {"cpmm": 1.0}.get(s, 2.0)

        monkeypatch.setattr(autotune, "measure_strategy", fake_measure)
        assert autotune.lookup_or_measure(
            64, 64, 64, mesh8, "float32", cfg) == "cpmm"
        assert called
        # the healthy measurement replaced the empty entry on disk
        assert autotune.load_table(path)[autotune._table_key(64, 2, 4, "float32")]["times"]

    def test_strategy_source_annotation(self, mesh8, tmp_path):
        # round-4 observability: EXPLAIN records WHY a strategy was
        # chosen — override / measured / model / default
        import json

        import numpy as np
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir.expr import pretty
        from matrel_tpu.parallel import autotune, planner
        rng = np.random.default_rng(21)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32), mesh=mesh8)
        e = A.expr().multiply(A.expr())
        assert planner.choose_strategy_ex(e, mesh8,
                                          MatrelConfig())[1] == "model"
        assert planner.choose_strategy_ex(
            e, mesh8, MatrelConfig(strategy_override="rmm")) == (
                "rmm", "override")
        path = str(tmp_path / "tuned.json")
        with open(path, "w") as f:
            json.dump({autotune._table_key(64, 2, 4, "float32"):
                       {"best": "cpmm", "times": {"cpmm": 1e-6}}}, f)
        autotune._CACHE.clear()
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        assert planner.choose_strategy_ex(e, mesh8, cfg) == ("cpmm",
                                                             "measured")
        ann = planner.annotate_strategies(e, mesh8, cfg)
        assert ann.attrs["strategy_source"] == "measured"
        assert "strategy=cpmm[measured]" in pretty(ann)
        autotune._CACHE.clear()

    def test_spmv_choice_measured_and_persisted(self, mesh8, tmp_path,
                                                monkeypatch):
        # VERDICT r3 #8: the SpMV executor choice (compact Pallas vs
        # expanded XLA) joins the measured-table loop — same discipline
        import numpy as np
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.parallel import autotune
        rng = np.random.default_rng(11)
        A = COOMatrix.from_edges(rng.integers(0, 300, 4000),
                                 rng.integers(0, 300, 4000),
                                 shape=(300, 300))
        plan = A._get_plan()
        assert plan is not None
        path = str(tmp_path / "tuned.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=path,
                           pallas_interpret=True)
        fake = {"compact": 2.0, "expanded": 1.0}
        monkeypatch.setattr(autotune, "measure_spmv_variant",
                            lambda v, p, m, c, **kw: fake[v])
        autotune._SPMV_CACHE.clear()
        best = autotune.lookup_or_measure_spmv(plan, mesh8, cfg)
        assert best == "expanded"
        key = autotune._spmv_key(plan, 2, 4)
        entry = autotune.load_table(path)[key]
        assert entry["best"] == "expanded" and entry["times"]
        # fresh process reads the table, no re-measure
        autotune._SPMV_CACHE.clear()
        monkeypatch.setattr(autotune, "measure_spmv_variant",
                            lambda *a, **kw: 1 / 0)
        assert autotune.lookup_or_measure_spmv(plan, mesh8,
                                               cfg) == "expanded"

    def test_spmv_single_variant_not_persisted(self, mesh8, tmp_path,
                                               monkeypatch):
        # review r4: admissibility depends on config (use_pallas) that
        # the key does not encode — a one-variant "comparison" must
        # resolve to None and never be written to a shared table
        import numpy as np
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.parallel import autotune
        rng = np.random.default_rng(13)
        A = COOMatrix.from_edges(rng.integers(0, 300, 3000),
                                 rng.integers(0, 300, 3000),
                                 shape=(300, 300))
        plan = A._get_plan()
        path = str(tmp_path / "tuned.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=path,
                           use_pallas=False)    # compact inadmissible
        monkeypatch.setattr(autotune, "measure_spmv_variant",
                            lambda v, p, m, c, **kw: 1.0)
        autotune._SPMV_CACHE.clear()
        assert autotune.lookup_or_measure_spmv(plan, mesh8, cfg) is None
        assert autotune.load_table(path) == {}

    def test_spmv_probe_does_not_pin_expanded_tables(self, mesh8,
                                                     tmp_path):
        # review r4: the expanded probe must not leave the ~224 B/slot
        # expanded tables cached on the plan when the session moves on
        import numpy as np
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.parallel import autotune
        rng = np.random.default_rng(14)
        A = COOMatrix.from_edges(rng.integers(0, 300, 3000),
                                 rng.integers(0, 300, 3000),
                                 shape=(300, 300))
        plan = A._get_plan()
        assert plan._tables is None
        cfg = MatrelConfig(autotune=True, pallas_interpret=True,
                           autotune_table_path=str(tmp_path / "t.json"))
        autotune._SPMV_CACHE.clear()
        best = autotune.lookup_or_measure_spmv(plan, mesh8, cfg)
        assert best is not None         # both variants measured
        assert plan._tables is None     # probe caches were dropped
        assert plan._spmm_tables is None

    def test_spmv_measured_choice_drives_executor(self, mesh8, tmp_path,
                                                  monkeypatch):
        # a persisted "expanded" winner must actually route the COO
        # dispatch off the compact Pallas path, with oracle numerics
        import json

        import numpy as np
        import scipy.sparse as sp
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.core.coo import COOMatrix
        from matrel_tpu.ops import pallas_spmv as pc
        from matrel_tpu.parallel import autotune
        from matrel_tpu import executor as executor_lib
        rng = np.random.default_rng(12)
        r = rng.integers(0, 300, 4000)
        c = rng.integers(0, 300, 4000)
        A = COOMatrix.from_edges(r, c, shape=(300, 300))
        plan = A._get_plan()
        path = str(tmp_path / "tuned.json")
        key = autotune._spmv_key(plan, 2, 4)
        json.dump({key: {"best": "expanded",
                         "times": {"expanded": 1.0, "compact": 2.0}}},
                  open(path, "w"))
        cfg = MatrelConfig(autotune=True, autotune_table_path=path,
                           pallas_interpret=True)
        autotune._SPMV_CACHE.clear()

        def boom(*a, **kw):
            raise AssertionError("compact path used despite measured "
                                 "expanded winner")

        for name in ("compact_apply", "compact_matmat_apply",
                     "compact_sharded_apply",
                     "compact_sharded_matmat_apply"):
            monkeypatch.setattr(pc, name, boom)
        x = BlockMatrix.from_numpy(
            rng.standard_normal((300, 2)).astype(np.float32), mesh=mesh8)
        got = executor_lib.execute(A.multiply(x.expr()), mesh8,
                                   cfg).to_numpy()
        want = sp.coo_matrix(
            (np.ones(len(r), np.float32), (r, c)),
            shape=(300, 300)).toarray() @ x.to_numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_all_strategies_failing_not_persisted(self, mesh8, tmp_path,
                                                  monkeypatch):
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "tuned.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        autotune._CACHE.clear()
        monkeypatch.setattr(
            autotune, "measure_strategy",
            lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("down")))
        best, times = autotune.autotune_matmul(64, 64, 64, mesh=mesh8,
                                               config=cfg)
        assert best is None and times == {}
        assert autotune._table_key(64, 2, 4, "float32") not in autotune.load_table(path)

    def test_persisted_tie_not_remeasured(self, mesh8, tmp_path,
                                          monkeypatch):
        # a persisted {"best": null} IS a measurement: the planner gets
        # None (model decides) and no re-measure happens on each compile
        import json
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "tuned.json")
        json.dump({autotune._table_key(64, 2, 4, "float32"):
                   {"best": None, "times": {"rmm": 1.0, "cpmm": 1.01}}},
                  open(path, "w"))
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        autotune._CACHE.clear()
        monkeypatch.setattr(autotune, "autotune_matmul",
                            lambda *a, **kw: 1 / 0)
        assert autotune.lookup_or_measure(
            64, 64, 64, mesh8, "float32", cfg) is None

    def test_rectangular_shapes_gated_out(self, mesh8, tmp_path,
                                          monkeypatch):
        # advisor r3: square-probe winners don't transfer to strongly
        # rectangular multiplies — and the probe itself would allocate
        # two side^2 operands at compile time
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        cfg = MatrelConfig(autotune=True,
                           autotune_table_path=str(tmp_path / "t.json"))
        monkeypatch.setattr(autotune, "autotune_matmul",
                            lambda *a, **kw: 1 / 0)
        assert autotune.lookup_or_measure(
            64, 64, 8192, mesh8, "float32", cfg) is None

    def test_persist_lock_skips_on_contention(self, tmp_path):
        import json
        import os
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "t.json")
        # current-format keys: load_table prunes legacy un-suffixed
        # entries (advisor r5 low), so the lock semantics under test
        # need keys that survive a round-trip
        keep = autotune._table_key(64, 2, 4, "float32")
        new = autotune._table_key(128, 2, 4, "float32")
        json.dump({keep: {"best": "rmm", "times": {}}}, open(path, "w"))
        # fresh lock held by a live writer: persist must skip, not clobber
        open(path + ".lock", "w").close()
        autotune._persist(path, new, "cpmm", {})
        assert new not in autotune.load_table(path)
        # stale lock (>60s) is broken and the merge proceeds, keeping
        # existing entries
        os.utime(path + ".lock", (0, 0))
        autotune._persist(path, new, "cpmm", {})
        t = autotune.load_table(path)
        assert t[new]["best"] == "cpmm" and keep in t
        assert not os.path.exists(path + ".lock")

    def test_inadmissible_persisted_winner_falls_back(self, mesh8,
                                                      tmp_path):
        import json
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "tuned.json")
        # summa needs a square grid: inadmissible on the 2x4 mesh, so
        # the planner must ignore the planted winner and use the model
        json.dump({autotune._table_key(64, 2, 4, "float32"): {"best": "summa", "times": {}}},
                  open(path, "w"))
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        autotune._CACHE.clear()
        got = self._choose(mesh8, cfg)
        assert got != "summa"

    def test_oversize_shapes_never_measured_inline(self, mesh8,
                                                   tmp_path):
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        cfg = MatrelConfig(autotune=True, autotune_max_dim=32,
                           autotune_table_path=str(tmp_path / "t.json"))
        assert autotune.lookup_or_measure(
            64, 64, 64, mesh8, "float32", cfg) is None


class TestCLI:
    def _run(self, *args):
        import os
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        return subprocess.run(
            [sys.executable, "-m", "matrel_tpu", *args],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=240)

    def test_info(self):
        r = self._run("info")
        assert r.returncode == 0, r.stderr
        out = json.loads(r.stdout)
        assert out["backend"] == "cpu" and "mesh" in out

    def test_pagerank_cli(self, tmp_path, capsys):
        import json
        from matrel_tpu.__main__ import main
        p = str(tmp_path / "edges.csv")
        with open(p, "w") as f:
            # star graph into node 0 + a 1->2 edge
            f.write("1,0,1\n2,0,1\n3,0,1\n1,2,1\n")
        main(["pagerank", p, "--rounds", "20", "--top", "2"])
        out = json.loads(capsys.readouterr().out)
        assert out["nodes"] == 4 and out["edges"] == 4
        assert out["top"][0]["node"] == 0          # the hub wins
        assert abs(out["rank_sum"] - 1.0) < 1e-3

    def test_sql_oneshot(self, tmp_path):
        p = str(tmp_path / "x.npy")
        np.save(p, np.eye(3, dtype=np.float32) * 2)
        r = self._run("sql", "trace(X)", "--table", f"X={p}")
        assert r.returncode == 0, r.stderr
        assert "6." in r.stdout


def test_sql_explain_flag(tmp_path, capsys):
    import numpy as np
    from matrel_tpu.__main__ import main as cli_main
    from matrel_tpu.session import reset_session
    reset_session()
    a = np.eye(6, dtype=np.float32)
    p = str(tmp_path / "a.npy")
    np.save(p, a)
    cli_main(["sql", "rowsum(A * A)", "--table", f"A={p}", "--explain"])
    out = capsys.readouterr().out
    assert "== Optimized plan ==" in out and "matmul" in out


def test_plain_autotune_call_leaves_no_table_file(mesh8, tmp_path,
                                                  monkeypatch):
    # review r3: a one-off measurement (autotune flag off, no explicit
    # path) must not drop a hidden JSON into the working directory
    import os
    from matrel_tpu.parallel import autotune
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(autotune, "_DEFAULT_TABLE",
                        str(tmp_path / ".matrel_autotune.json"))
    autotune._CACHE.clear()
    autotune.autotune_matmul(64, 64, 64, mesh=mesh8)
    assert not os.path.exists(tmp_path / ".matrel_autotune.json")


def test_cached_measurement_persists_when_loop_enabled_later(mesh8,
                                                             tmp_path):
    # review r3: shape measured with persistence OFF, then requested
    # with the closed loop ON in the same process -> table gains it
    import os
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.parallel import autotune
    autotune._CACHE.clear()
    best, _ = autotune.autotune_matmul(64, 64, 64, mesh=mesh8)  # no persist
    path = str(tmp_path / "t.json")
    assert not os.path.exists(path)
    cfg = MatrelConfig(autotune=True, autotune_table_path=path)
    got = autotune.lookup_or_measure(64, 64, 64, mesh8, "float32", cfg)
    assert got == best
    assert autotune.load_table(path)[autotune._table_key(64, 2, 4, "float32")]["best"] == best


class TestAutotuneOneVariantGate:
    def test_lone_survivor_not_a_winner(self, mesh8, monkeypatch,
                                        tmp_path):
        # advisor r4: when every strategy but one fails to compile or
        # measures as noise, the lone survivor must be recorded
        # best=None (times persisted for observability), mirroring the
        # SpMV loop's len(results) >= 2 gate
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune

        def fake(s, A, B, cfg, **kw):
            if s != "xla":
                raise RuntimeError("compile failed")
            return 1.0
        monkeypatch.setattr(autotune, "measure_strategy", fake)
        path = str(tmp_path / "tuned.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        autotune._CACHE.clear()
        best, results = autotune.autotune_matmul(32, 32, 32, mesh=mesh8,
                                                 config=cfg)
        assert best is None
        assert list(results) == ["xla"]
        entry = autotune.load_table(path)[
            autotune._table_key(32, 2, 4, "float32")]
        assert entry["best"] is None and entry["times"]


class TestWeightedTableKeys:
    """Round 7 cache-key hygiene: weighted (topology) measurements get
    their own autotune-table rows; load_table's prune keeps both the
    historical 4/7-field keys AND the new w-suffixed forms while still
    dropping true legacy entries."""

    def test_weighted_key_formats_survive_prune(self, tmp_path):
        import json
        from matrel_tpu.parallel import autotune
        uk = autotune._table_key(64, 2, 4, "float32")
        wk = autotune._table_key(64, 2, 4, "float32", (1.0, 8.0))
        assert wk == uk + "|w1x8" and wk != uk
        path = str(tmp_path / "t.json")
        legacy_mm = "64|2x4|float32"          # pre-backend-suffix
        legacy_spmv = "spmv|cpu|100x100|nb1|cap8|blk128"
        spmv_w = legacy_spmv + "|2x4|w1x8"    # current 7-field + weights
        json.dump({uk: {"best": "rmm", "times": {"rmm": 1.0}},
                   wk: {"best": "bmm_right", "times": {"bmm_right": 1.0}},
                   legacy_mm: {"best": "cpmm", "times": {}},
                   legacy_spmv: {"best": "compact", "times": {}},
                   spmv_w: {"best": "expanded", "times": {}}},
                  open(path, "w"))
        t = autotune.load_table(path)
        assert set(t) == {uk, wk, spmv_w}

    def test_weighted_mesh_reads_its_own_row(self, mesh8, tmp_path,
                                             monkeypatch):
        # a winner measured on the flat mesh must NOT serve a weighted
        # session (and vice versa): lookup under weights misses the
        # unweighted row and returns the weighted one
        import json
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "t.json")
        json.dump(
            {autotune._table_key(64, 2, 4, "float32"):
                 {"best": "rmm", "times": {"rmm": 1e-6, "cpmm": 1.0}},
             autotune._table_key(64, 2, 4, "float32", (1.0, 8.0)):
                 {"best": "cpmm",
                  "times": {"rmm": 1.0, "cpmm": 1e-6}}},
            open(path, "w"))
        autotune._CACHE.clear()
        flat = autotune.lookup_or_measure(
            64, 64, 64, mesh8, "float32",
            MatrelConfig(autotune=True, autotune_table_path=path))
        weighted = autotune.lookup_or_measure(
            64, 64, 64, mesh8, "float32",
            MatrelConfig(autotune=True, autotune_table_path=path,
                         axis_cost_weights=(1.0, 8.0)))
        autotune._CACHE.clear()
        assert (flat, weighted) == ("rmm", "cpmm")

    def test_spmv_key_weight_suffix(self, mesh8):
        import types
        from matrel_tpu.parallel import autotune
        plan = types.SimpleNamespace(
            src8=np.zeros((2, 8), np.int32), n_rows=100, n_cols=100,
            block=128)
        k0 = autotune._spmv_key(plan, 2, 4)
        kw = autotune._spmv_key(plan, 2, 4, (2.0, 1.0))
        assert kw == k0 + "|w2x1"
        assert autotune._current_key_format(k0)
        assert autotune._current_key_format(kw)
