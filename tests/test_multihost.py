"""Multi-process (multi-host analogue) validation: collectives over a
real process boundary via jax.distributed + Gloo — the DCN shape of a
TPU pod (SURVEY.md §5 "Distributed comm backend"). Heavier than the
in-process mesh tests; one spawn of tools/multihost_check.py."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_collectives():
    # ephemeral coordinator port; the tool's own --timeout (120s) fires
    # before this test's cap, and it kills its worker process group, so
    # a hang cannot orphan coordinator-holding workers on the machine
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "multihost_check.py"),
         "--nproc", "2", "--timeout", "120"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/tmp", start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        out, _ = proc.communicate()
        raise AssertionError(f"multihost check hung:\n{out}")
    assert proc.returncode == 0, out
    assert "MULTIHOST CHECK: OK" in out
