"""Multi-process (multi-host analogue) validation: collectives over a
real process boundary via jax.distributed + Gloo — the DCN shape of a
TPU pod (SURVEY.md §5 "Distributed comm backend"). Heavier than the
in-process mesh tests; one spawn of tools/multihost_check.py."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_check(nproc: int, tool_timeout: int, outer_timeout: int) -> str:
    # ephemeral coordinator port; the tool's own --timeout fires before
    # this test's cap, and it kills its worker process group, so a hang
    # cannot orphan coordinator-holding workers on the machine
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "multihost_check.py"),
         "--nproc", str(nproc), "--timeout", str(tool_timeout)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/tmp", start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=outer_timeout)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        out, _ = proc.communicate()
        raise AssertionError(f"multihost check hung:\n{out}")
    assert proc.returncode == 0, out
    assert "MULTIHOST CHECK: OK" in out
    return out


def test_two_process_collectives():
    out = _run_check(nproc=2, tool_timeout=120, outer_timeout=240)
    assert "over 8 devices" in out


def test_four_process_collectives():
    """4 processes x 4 virtual devices each — the DCN shape of a 4-host
    pod slice (docs/INTERNALS.md's manual run, folded into CI per
    round-1 VERDICT #8). Heavier than the 2-process test; its own
    generous timeout keeps a Gloo stall from wedging the suite."""
    out = _run_check(nproc=4, tool_timeout=240, outer_timeout=420)
    assert "over 16 devices" in out   # 4x4 global mesh actually formed
