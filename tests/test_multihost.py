"""Multi-process (multi-host analogue) validation: collectives over a
real process boundary via jax.distributed + Gloo — the DCN shape of a
TPU pod (SURVEY.md §5 "Distributed comm backend"). Heavier than the
in-process mesh tests; one spawn of tools/multihost_check.py."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: This container ships jax 0.4.37, whose CPU backend refuses
#: cross-process computations outright: device_put onto a
#: cross-process NamedSharding asserts spec equality via a global psum
#: that fails with "Multiprocess computations aren't implemented on the
#: CPU backend" (jax/_src/dispatch.py -> multihost_utils.assert_equal;
#: reproduced round 6 by running tools/multihost_check.py by hand —
#: every worker dies at BlockMatrix.from_numpy). The seed targeted the
#: jax 0.6 CPU Gloo collectives backend where this works; nothing in
#: this repo can add the capability to the pinned jaxlib, so the two
#: Gloo tests are expected failures HERE and real coverage on
#: containers with the newer jax (strict=False keeps them green there).
_GLOO_XFAIL = pytest.mark.xfail(
    strict=False,
    reason="jax 0.4.37 CPU backend: 'Multiprocess computations aren't "
           "implemented on the CPU backend' — cross-process Gloo "
           "collectives need the jax 0.6 CPU backend the seed "
           "targeted")


def _run_check(nproc: int, tool_timeout: int, outer_timeout: int) -> str:
    # ephemeral coordinator port; the tool's own --timeout fires before
    # this test's cap, and it kills its worker process group, so a hang
    # cannot orphan coordinator-holding workers on the machine
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "multihost_check.py"),
         "--nproc", str(nproc), "--timeout", str(tool_timeout)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/tmp", start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=outer_timeout)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        out, _ = proc.communicate()
        raise AssertionError(f"multihost check hung:\n{out}")
    assert proc.returncode == 0, out
    assert "MULTIHOST CHECK: OK" in out
    return out


@_GLOO_XFAIL
def test_two_process_collectives():
    out = _run_check(nproc=2, tool_timeout=120, outer_timeout=240)
    assert "over 8 devices" in out


@_GLOO_XFAIL
def test_four_process_collectives():
    """4 processes x 4 virtual devices each — the DCN shape of a 4-host
    pod slice (docs/INTERNALS.md's manual run, folded into CI per
    round-1 VERDICT #8). Heavier than the 2-process test; its own
    generous timeout keeps a Gloo stall from wedging the suite."""
    out = _run_check(nproc=4, tool_timeout=240, outer_timeout=420)
    assert "over 16 devices" in out   # 4x4 global mesh actually formed
