"""The cost-model closed loop (parallel/coeffs.py + serve/replan.py +
planner integration; docs/COST_MODEL.md): the coefficient seam parses,
memoises and epoch-stamps drift tables; choose_strategy_ex ranks by
calibrated milliseconds only under full row coverage (all-or-nothing,
stamped ``cost: "measured"``); the ReplanController turns a firing
DRIFT rank flag into a re-calibration + epoch bump with cooldown and
reversal-dwell hysteresis; and the default config constructs NOTHING
from the replan module (poisoned init) and keys plans without any
``coeffv:`` prefix — bit-identical to the pre-loop planner."""

import json
import os

import numpy as np
import pytest

from matrel_tpu import executor as executor_lib
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.obs import drift
from matrel_tpu.parallel import coeffs, planner
from matrel_tpu.serve import replan as replan_lib
from matrel_tpu.session import MatrelSession

CLS = "<=128"


def _row(strategy, gf, mib, count=10, cls=CLS, backend="cpu"):
    return {"strategy": strategy, "class": cls, "backend": backend,
            "count": count, "ms_median": 1.0,
            "ms_per_gflop": gf, "ms_per_est_mib": mib}


def _write(path, rows):
    entries = {f"{r['strategy']}|{r['class']}|{r['backend']}": r
               for r in rows}
    with open(path, "w") as f:
        json.dump({"schema": 1, "entries": entries}, f)
    coeffs.reset_coefficient_cache()


@pytest.fixture()
def table(tmp_path):
    return str(tmp_path / "drift.json")


class TestSeam:
    def test_cold_table(self, table):
        assert coeffs.strategy_coefficients(table) == {}
        assert coeffs.class_coefficients(table) == {}
        assert coeffs.epoch(table) == coeffs.COLD_EPOCH
        assert coeffs.strategy_row("rmm", CLS, "cpu", table) is None

    def test_rows_and_tier_keying(self, table):
        _write(table, [_row("rmm", 1.5, 0.3),
                       _row("rmm@bf16x3", 0.5, 0.3)])
        bare = coeffs.strategy_row("rmm", CLS, "cpu", table)
        tiered = coeffs.strategy_row("rmm", CLS, "cpu", table,
                                     tier="bf16x3")
        assert bare["ms_per_gflop"] == 1.5
        assert tiered["ms_per_gflop"] == 0.5
        assert bare["source"] == tiered["source"] == "measured"

    def test_nonfinite_ratios_dropped_fieldwise(self, table):
        _write(table, [_row("rmm", float("nan"), 0.3),
                       _row("cpmm", float("inf"), float("nan"))])
        row = coeffs.strategy_row("rmm", CLS, "cpu", table)
        assert row["ms_per_gflop"] is None
        assert row["ms_per_mib"] == 0.3
        # both ratios poisoned -> the whole row is unusable, absent
        assert coeffs.strategy_row("cpmm", CLS, "cpu", table) is None

    def test_zero_count_row_dropped(self, table):
        _write(table, [_row("rmm", 1.0, 0.3, count=0)])
        assert coeffs.strategy_coefficients(table) == {}
        assert coeffs.epoch(table) == coeffs.COLD_EPOCH

    def test_stat_signature_invalidation_without_reset(self, table):
        _write(table, [_row("rmm", 1.0, 0.3)])
        assert coeffs.strategy_row("rmm", CLS, "cpu",
                                   table)["ms_per_gflop"] == 1.0
        # a table rewrite (new size/mtime) must be picked up by the
        # NEXT consult with no explicit cache reset — the live re-plan
        # path depends on it
        entries = {f"rmm|{CLS}|cpu": _row("rmm", 2.25, 0.3)}
        with open(table, "w") as f:
            json.dump({"schema": 1, "entries": entries}, f)
        os.utime(table, ns=(1, 1))  # force a distinct stat signature
        assert coeffs.strategy_row("rmm", CLS, "cpu",
                                   table)["ms_per_gflop"] == 2.25

    def test_epoch_stable_across_count_only_merge(self, table):
        _write(table, [_row("rmm", 1.0, 0.3, count=10)])
        ep1 = coeffs.epoch(table)
        _write(table, [_row("rmm", 1.0, 0.3, count=20)])
        assert coeffs.epoch(table) == ep1      # values unchanged
        _write(table, [_row("rmm", 1.1, 0.3, count=20)])
        ep2 = coeffs.epoch(table)
        assert ep2 != ep1 and ep2 != coeffs.COLD_EPOCH

    def test_predict_ms_and_cold_term_fallbacks(self):
        full = {"ms_per_gflop": 2.0, "ms_per_mib": 0.5}
        assert coeffs.predict_ms(full, 3.0, 4 << 20) == \
            pytest.approx(2.0 * 3.0 + 0.5 * 4.0)
        no_mib = {"ms_per_gflop": 2.0, "ms_per_mib": None}
        assert coeffs.predict_ms(no_mib, 3.0, 4 << 20) == \
            pytest.approx(6.0 + coeffs.ANALYTIC_MS_PER_MIB * 4.0)
        no_gf = {"ms_per_gflop": None, "ms_per_mib": 0.5}
        assert coeffs.predict_ms(no_gf, 3.0, 4 << 20) == \
            pytest.approx(coeffs.ANALYTIC_MS_PER_GFLOP * 3.0 + 2.0)

    def test_class_blend_is_count_weighted(self, table):
        _write(table, [_row("rmm", 1.0, 0.2, count=1),
                       _row("cpmm", 3.0, 0.6, count=3)])
        blend = coeffs.class_coefficients(table)[(CLS, "cpu", "")]
        assert blend["ms_per_gflop"] == pytest.approx(2.5)
        assert blend["ms_per_mib"] == pytest.approx(0.5)
        assert blend["count"] == 4

    def test_chain_comm_weights(self, table):
        _write(table, [_row("rmm", 1.0, 0.4, count=5),
                       _row("rmm@bf16x3", 9.0, 9.0, count=50,
                            cls="<=256"),
                       _row("cpmm", 1.0, 0.4, count=5, cls="<=512",
                            backend="tpu")])
        w = coeffs.chain_comm_weights(table, "cpu")
        # FLOP-equivalents per byte: (mib/2^20) / (gf/1e9)
        assert w == {CLS: pytest.approx((0.4 / 2 ** 20) / (1.0 / 1e9))}
        # tiered blends and foreign backends never reach the DP
        assert "<=256" not in w and "<=512" not in w
        assert coeffs.chain_comm_weights(table, "cpu",
                                         min_samples=6) == {}


CANDS = ("bmm_right", "bmm_left", "cpmm", "rmm", "xla")


def _decisions(mesh, cfg, n=128, seed=7):
    A = BlockMatrix.random((n, n), mesh=mesh, seed=seed)
    B = BlockMatrix.random((n, n), mesh=mesh, seed=seed + 1)
    plan = executor_lib.compile_expr(A.expr().multiply(B.expr()),
                                     mesh, cfg)
    return executor_lib.plan_matmul_decisions(plan)


class TestMeasuredRanking:
    def _cfg(self, table, **kw):
        kw.setdefault("coeff_planner_enable", True)
        kw.setdefault("coeff_min_samples", 2)
        return MatrelConfig(obs_level="off", drift_table_path=table,
                            **kw)

    def test_poisoned_table_flips_pick_and_stamps_measured(
            self, mesh8, table):
        analytic = _decisions(
            mesh8, MatrelConfig(obs_level="off",
                                drift_table_path=table))[0]["strategy"]
        decoy = next(s for s in CANDS if s != analytic)
        _write(table, [_row(s, 0.01 if s == decoy else 1.0,
                            0.0001 if s == decoy else 0.5)
                       for s in CANDS])
        d = _decisions(mesh8, self._cfg(table))[0]
        assert d["strategy"] == decoy
        assert d["cost"] == "measured"

    def test_partial_coverage_stays_analytic(self, mesh8, table):
        # all-or-nothing: one cold candidate means ranking measured
        # milliseconds against raw byte-equivalents — a units error
        _write(table, [_row(s, 1.0, 0.5) for s in CANDS
                       if s != "rmm"])
        d = _decisions(mesh8, self._cfg(table))[0]
        assert d["cost"] == "analytic"

    def test_below_min_samples_stays_analytic(self, mesh8, table):
        _write(table, [_row(s, 1.0, 0.5, count=1) for s in CANDS])
        d = _decisions(mesh8, self._cfg(table,
                                        coeff_min_samples=3))[0]
        assert d["cost"] == "analytic"

    def test_default_config_emits_no_cost_stamp(self, mesh8, table):
        _write(table, [_row(s, 1.0, 0.5) for s in CANDS])
        for d in _decisions(mesh8, MatrelConfig(
                obs_level="off", drift_table_path=table)):
            assert "cost" not in d

    def test_comm_cost_coeff_scales_to_ms(self):
        raw = planner.comm_cost("cpmm", 128, 128, 128, 1.0, 1.0, 2, 4)
        ms = planner.comm_cost("cpmm", 128, 128, 128, 1.0, 1.0, 2, 4,
                               coeff={"ms_per_mib": 2.0})
        assert ms == pytest.approx(2.0 * raw / (1 << 20))
        cold = planner.comm_cost("cpmm", 128, 128, 128, 1.0, 1.0,
                                 2, 4, coeff={})
        assert cold == pytest.approx(
            coeffs.ANALYTIC_MS_PER_MIB * raw / (1 << 20))

    def test_comm_cost_axes_coeff_scales_both_axes(self):
        bx, by = planner.comm_cost_axes("cpmm", 128, 128, 128,
                                        1.0, 1.0, 2, 4)
        mx, my = planner.comm_cost_axes("cpmm", 128, 128, 128,
                                        1.0, 1.0, 2, 4,
                                        coeff={"ms_per_mib": 2.0})
        scale = 2.0 / (1 << 20)
        assert mx == pytest.approx(bx * scale)
        assert my == pytest.approx(by * scale)


def _query(strategy, ms, est, dims=(64, 64, 64)):
    return {"kind": "query", "backend": "cpu", "cache": "miss",
            "execute_ms": ms,
            "matmuls": [{"strategy": strategy, "dims": list(dims),
                         "flops": 2.0 * dims[0] * dims[1] * dims[2],
                         "est_ici_bytes": est}]}


class TestReplanController:
    def _cfg(self, table, **kw):
        kw.setdefault("coeff_replan_cooldown", 2)
        return MatrelConfig(obs_level="off", drift_table_path=table,
                            coeff_planner_enable=True,
                            coeff_replan_enable=True,
                            coeff_replan_interval=10 ** 6, **kw)

    def _feed(self, ctl, strategy, ms, est, k=3):
        for _ in range(k):
            ctl.observe(_query(strategy, ms, est))

    def test_from_config_default_is_structural_zero(self):
        before = replan_lib._CONSTRUCTED["count"]
        assert replan_lib.from_config(MatrelConfig()) is None
        assert replan_lib._CONSTRUCTED["count"] == before

    def test_flag_fires_recalibrates_and_bumps_epoch(self, table):
        ctl = replan_lib.from_config(self._cfg(table))
        assert isinstance(ctl, replan_lib.ReplanController)
        # the model prefers cpmm by bytes; measurement says rmm is
        # 10x faster — the canonical DRIFT inversion
        self._feed(ctl, "cpmm", ms=10.0, est=1000.0)
        self._feed(ctl, "rmm", ms=1.0, est=2000.0)
        rec = ctl.check()
        assert rec is not None and ctl.replans == 1
        assert rec["classes"] == ["<=64"]
        assert rec["old_epoch"] == coeffs.COLD_EPOCH
        assert rec["epoch"] != coeffs.COLD_EPOCH
        assert rec["flags"][0]["model_prefers"] == "cpmm"
        assert rec["flags"][0]["measured_prefers"] == "rmm"
        assert rec["replanned"] == 0          # no session attached
        row = coeffs.strategy_row("cpmm", "<=64", "cpu", table)
        assert row is not None and row["source"] == "measured"
        # actioned samples dropped: the window holds fresh-only
        assert ctl.info()["window"] == 0

    def test_cooldown_suppresses_immediate_refire(self, table):
        ctl = replan_lib.from_config(self._cfg(table))
        self._feed(ctl, "cpmm", ms=10.0, est=1000.0)
        self._feed(ctl, "rmm", ms=1.0, est=2000.0)
        assert ctl.check() is not None
        # same stale inversion refed immediately: the population is
        # cooling, the loop must wait for post-re-plan evidence
        self._feed(ctl, "cpmm", ms=10.0, est=1000.0)
        self._feed(ctl, "rmm", ms=1.0, est=2000.0)
        assert ctl.check() is None
        assert ctl.replans == 1

    def test_reversal_needs_two_consecutive_checks(self, table):
        ctl = replan_lib.from_config(
            self._cfg(table, coeff_replan_cooldown=0))
        self._feed(ctl, "cpmm", ms=10.0, est=1000.0)
        self._feed(ctl, "rmm", ms=1.0, est=2000.0)
        assert ctl.check() is not None
        # the EXACT reversal of the action just taken: one window is
        # noise, two consecutive windows are a real regression
        self._feed(ctl, "rmm", ms=10.0, est=1000.0)
        self._feed(ctl, "cpmm", ms=1.0, est=2000.0)
        assert ctl.check() is None
        assert ctl.check() is not None
        assert ctl.replans == 2

    def test_interval_triggers_check_from_observe(self, table):
        ctl = replan_lib.from_config(
            self._cfg(table).replace(coeff_replan_interval=2))
        ctl.observe(_query("rmm", 1.0, 1000.0))
        assert ctl.checks == 0
        ctl.observe(_query("rmm", 1.0, 1000.0))
        assert ctl.checks == 1

    def test_observe_never_raises(self, table):
        ctl = replan_lib.from_config(self._cfg(table))
        ctl.observe({"kind": "query", "matmuls": 5,
                     "execute_ms": "garbage"})
        ctl.observe({})
        assert ctl.info()["window"] == 0

    def test_replan_config_requires_planner(self):
        with pytest.raises(ValueError):
            MatrelConfig(coeff_replan_enable=True)


class TestDriftEdgeCases:
    def test_empty_inputs(self):
        assert drift.calibrate([]) == {}
        assert drift.rank_flags([]) == []

    def test_single_strategy_population_never_flags(self):
        samples = list(drift.iter_samples(
            [_query("rmm", 10.0, 1000.0)] * 4))
        assert drift.rank_flags(samples) == []

    def test_rank_flag_margin_boundary(self):
        def flags(ms_a):
            samples = list(drift.iter_samples(
                [_query("a", ms_a, 1000.0),
                 _query("b", 1.0, 2000.0)]))
            return drift.rank_flags(samples)
        assert flags(drift.RANK_FLAG_MARGIN * 1.0)      # >= fires
        assert not flags(drift.RANK_FLAG_MARGIN * 0.99)

    def test_iter_samples_exclusions(self):
        good = _query("rmm", 1.0, 1000.0)
        zero_ms = dict(good, execute_ms=0.0)
        rc_hit = dict(good, cache="rc_hit")
        batched = dict(good, batch=3)
        multi = dict(good, matmuls=good["matmuls"] * 2)
        assert len(list(drift.iter_samples(
            [good, zero_ms, rc_hit, batched, multi]))) == 1

    def test_calibrate_single_sample_and_zero_bytes(self):
        s = {"strategy": "rmm", "class": CLS, "backend": "cpu",
             "tier": "", "flops": 2e9, "est_bytes": 0.0, "ms": 3.0,
             "source": "query"}
        row = drift.calibrate([s])[f"rmm|{CLS}|cpu"]
        assert row["count"] == 1
        assert row["ms_per_gflop"] == pytest.approx(1.5)
        assert row["ms_per_est_mib"] is None   # model said zero bytes

    def test_update_table_blend_is_count_weighted(self, tmp_path):
        path = str(tmp_path / "t.json")
        key = f"rmm|{CLS}|cpu"
        base = {"strategy": "rmm", "class": CLS, "backend": "cpu"}
        drift.update_table(path, {key: dict(base, count=10,
                                            ms_median=1.0,
                                            ms_per_gflop=1.0,
                                            ms_per_est_mib=0.2)})
        out = drift.update_table(path, {key: dict(base, count=10,
                                                  ms_median=3.0,
                                                  ms_per_gflop=3.0,
                                                  ms_per_est_mib=0.6)})
        row = out["entries"][key]
        assert row["count"] == 20
        assert row["ms_per_gflop"] == pytest.approx(2.0)
        assert row["ms_per_est_mib"] == pytest.approx(0.4)


class TestZeroOverheadDefault:
    def test_default_session_constructs_no_replan_state(self, mesh8,
                                                        rng):
        before = replan_lib._CONSTRUCTED["count"]
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        X = BlockMatrix.from_numpy(
            rng.standard_normal((48, 16)).astype(np.float32),
            mesh=mesh8)
        out = sess.run(X.expr().t().multiply(X.expr()))
        assert replan_lib._CONSTRUCTED["count"] == before
        assert sess._replan is None
        assert sess._coeff_epoch() is None
        assert sess._coeff_prefix() == ""
        xn = X.to_numpy()
        np.testing.assert_allclose(out.to_numpy(), xn.T @ xn,
                                   rtol=3e-4, atol=3e-4)

    def test_enabled_session_prefixes_plan_keys(self, mesh8, tmp_path):
        table = str(tmp_path / "drift.json")
        _write(table, [_row("rmm", 1.0, 0.3)])
        sess = MatrelSession(
            mesh=mesh8,
            config=MatrelConfig(obs_level="off",
                                drift_table_path=table,
                                coeff_planner_enable=True))
        ep = coeffs.epoch(table)
        assert ep != coeffs.COLD_EPOCH
        assert sess._coeff_epoch() == ep
        assert sess._coeff_prefix() == f"coeffv:{ep}|"

    def test_cold_prefix_is_self_describing(self, mesh8, tmp_path):
        sess = MatrelSession(
            mesh=mesh8,
            config=MatrelConfig(
                obs_level="off",
                drift_table_path=str(tmp_path / "none.json"),
                coeff_planner_enable=True))
        assert sess._coeff_prefix() == "coeffv:cold|"

    def test_defaults_are_off(self):
        cfg = MatrelConfig()
        assert cfg.coeff_planner_enable is False
        assert cfg.coeff_replan_enable is False
