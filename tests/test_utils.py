"""Aux subsystem tests: checkpoint round-trip + GC, resilient driver loop
with injected failure, step timer (SURVEY.md §5)."""

import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.utils.checkpoint import CheckpointManager
from matrel_tpu.utils.profiling import StepTimer
from matrel_tpu.utils import resilience


class TestCheckpoint:
    def test_roundtrip(self, mesh8, rng, tmp_path):
        a = rng.standard_normal((12, 10)).astype(np.float32)
        bm = BlockMatrix.from_numpy(a, mesh=mesh8, nnz=37)
        cm = CheckpointManager(str(tmp_path))
        cm.save(3, matrices={"A": bm}, state={"alpha": 0.85})
        step, mats, arrs, state = cm.restore(mesh8)
        assert step == 3 and state == {"alpha": 0.85}
        got = mats["A"]
        assert got.shape == (12, 10) and got.nnz == 37 and got.spec == bm.spec
        np.testing.assert_allclose(got.to_numpy(), a, rtol=1e-6)

    def test_gc_keeps_last_k(self, mesh8, rng, tmp_path):
        bm = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        cm = CheckpointManager(str(tmp_path), keep=2)
        for s in (1, 2, 3, 4):
            cm.save(s, matrices={"A": bm})
        assert cm._steps() == [3, 4]
        assert cm.latest_step() == 4

    def test_restore_empty_returns_none(self, mesh8, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        assert cm.restore(mesh8) is None


class TestResilience:
    def test_loop_completes_and_checkpoints(self, mesh8, rng, tmp_path):
        a = rng.standard_normal((8, 8)).astype(np.float32)
        bm = BlockMatrix.from_numpy(a, mesh=mesh8)
        cm = CheckpointManager(str(tmp_path))

        def body(step, mats, state):
            state = dict(state, last=step)
            return mats, state

        mats, state = resilience.run_resilient(
            body, cm, mesh8, {"A": bm}, num_steps=5, checkpoint_interval=2)
        assert state["last"] == 4
        assert cm.latest_step() == 4

    def test_restart_from_checkpoint_after_failure(self, mesh8, rng, tmp_path):
        a = np.ones((8, 8), dtype=np.float32)
        bm = BlockMatrix.from_numpy(a, mesh=mesh8)
        cm = CheckpointManager(str(tmp_path))
        calls = {"failed": False}

        class FakeXlaRuntimeError(Exception):
            pass

        FakeXlaRuntimeError.__name__ = "XlaRuntimeError"

        def body(step, mats, state):
            if step == 3 and not calls["failed"]:
                calls["failed"] = True
                raise FakeXlaRuntimeError("device lost")
            # matrix accumulates step index so we can check resume point
            new = BlockMatrix.from_numpy(
                mats["A"].to_numpy() + 1.0, mesh=mesh8)
            return {"A": new}, dict(state, last=step)

        mats, state = resilience.run_resilient(
            body, cm, mesh8, {"A": bm}, num_steps=5, checkpoint_interval=2)
        assert calls["failed"] and state["last"] == 4
        # A incremented exactly once per completed step (no double-apply
        # for steps made durable before the crash)
        np.testing.assert_allclose(mats["A"].to_numpy(), a + 5.0)

    def test_nonretryable_raises(self, mesh8, rng, tmp_path):
        bm = BlockMatrix.from_numpy(np.ones((8, 8), np.float32), mesh=mesh8)
        cm = CheckpointManager(str(tmp_path))

        def body(step, mats, state):
            raise ValueError("programming error")

        with pytest.raises(ValueError):
            resilience.run_resilient(body, cm, mesh8, {"A": bm}, num_steps=2)


def test_step_timer():
    t = StepTimer()
    with t.step("work"):
        sum(range(1000))
    t.count("nnz", 42)
    t.count("nnz", 8)
    out = t.table()
    assert "work" in out and "nnz" in out and "50" in out
