"""Precision-tiered execution (round 8; docs/PRECISION.md).

Covers the full SLA surface: threading through run/run_many/submit/SQL,
the tier chooser and its closed-form cost model, infer_dtype/integral
propagation, the multi-pass lowerings vs f64 oracles, MV108 fixtures,
result-cache tier-key isolation, drift-auditor tier keying, and the
default-config bit-identity contract (no stamps, no behaviour change —
the plan-snapshot corpus is asserted separately by
test_plan_snapshots)."""

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig, normalize_sla
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.executor import compile_expr
from matrel_tpu.ir import stats
from matrel_tpu.ir import expr as E
from matrel_tpu.parallel import planner


def _float_pair(mesh, rng, n=48, k=40, m=32):
    a = rng.uniform(-1.0, 1.0, (n, k)).astype(np.float32)
    b = rng.uniform(-1.0, 1.0, (k, m)).astype(np.float32)
    return (a, b, BlockMatrix.from_numpy(a, mesh=mesh),
            BlockMatrix.from_numpy(b, mesh=mesh))


def _int_pair(mesh, rng, n=48, k=40, m=32):
    a = rng.integers(-3, 4, (n, k))
    b = rng.integers(-3, 4, (k, m))
    return (a, b, BlockMatrix.from_numpy(a, mesh=mesh),
            BlockMatrix.from_numpy(b, mesh=mesh))


def _stamped_tier(plan):
    tiers = set()

    def walk(n):
        t = n.attrs.get("precision_tier")
        if n.kind == "matmul" and t is not None:
            tiers.add(t)
        for c in n.children:
            walk(c)

    roots = (plan.optimized if isinstance(plan.optimized, tuple)
             else (plan.optimized,))
    for r in roots:
        walk(r)
    return tiers


# ---------------------------------------------------------------------------
# SLA vocabulary + config
# ---------------------------------------------------------------------------


def test_normalize_sla_vocabulary():
    assert normalize_sla(None) == "default"
    assert normalize_sla("Fast") == "fast"
    assert normalize_sla("bf16") == "bfloat16"
    assert normalize_sla("f32") == "float32"
    with pytest.raises(ValueError):
        normalize_sla("fasst")


def test_config_rejects_bad_sla():
    with pytest.raises(ValueError):
        MatrelConfig(precision_sla="speedy")
    assert MatrelConfig(precision_sla="FAST").precision_sla == "fast"


# ---------------------------------------------------------------------------
# Cost model — exact closed-form unit checks
# ---------------------------------------------------------------------------


def test_tier_cost_closed_forms():
    n, k, m = 64, 128, 32
    macs = 2.0 * n * k * m
    for tier in planner.PRECISION_TIERS:
        units = planner.TIER_COMPUTE_UNITS[tier]
        isz = planner.TIER_ITEMSIZE[tier]
        want = (macs * units
                + stats.HBM_FLOPS_PER_BYTE
                * ((n * k + k * m) * isz + n * m * 4.0))
        assert planner.tier_matmul_cost(tier, n, k, m) == want

    # density credit rides the MAC term AND the operand bytes
    want = (macs * 0.5 * 0.25 * planner.TIER_COMPUTE_UNITS["bf16x1"]
            + stats.HBM_FLOPS_PER_BYTE
            * ((n * k * 0.5 + k * m * 0.25) * 2 + n * m * 4.0))
    assert planner.tier_matmul_cost("bf16x1", n, k, m, 0.5,
                                    0.25) == want


def test_pass_count_billing():
    # the billing the ISSUE names: 3 passes at 2x the MXU rate = 1.5x
    # the single-pass f32-rate MAC time; the 6-pass f32 emulation = 3x
    assert planner.TIER_PASSES["bf16x3"] == 3
    assert planner.TIER_COMPUTE_UNITS["bf16x3"] == pytest.approx(
        planner.TIER_PASSES["bf16x3"] / 2.0)
    assert planner.TIER_COMPUTE_UNITS["f32"] == pytest.approx(
        planner.TIER_PASSES["f32"] / 2.0)
    # per-tier HBM bytes: bf16x1 streams half-width operands
    assert planner.TIER_ITEMSIZE["bf16x1"] == 2
    assert planner.TIER_ITEMSIZE["int8"] == 1


def test_sla_allowed_tiers_and_chooser(mesh8, rng):
    cfg = lambda **kw: MatrelConfig(**kw)
    assert planner.sla_allowed_tiers("default", False) == ()
    assert planner.sla_allowed_tiers("exact", False) == ("f32",)
    assert set(planner.sla_allowed_tiers("exact", True)) == {"f32",
                                                             "int32"}
    assert "bf16x3" in planner.sla_allowed_tiers("high", False)
    assert "bf16x1" not in planner.sla_allowed_tiers("high", False)
    assert "bf16x1" in planner.sla_allowed_tiers("fast", False)
    assert planner.sla_allowed_tiers("bfloat16", False) == ("bf16x1",)
    # enable flags prune the named levels
    off = cfg(precision_enable_bf16=False, precision_sla="fast")
    assert planner.sla_allowed_tiers("fast", False, off) == ("f32",)
    # ...but an explicit dtype ask bypasses them
    assert planner.sla_allowed_tiers("bfloat16", False,
                                     off) == ("bf16x1",)

    _, _, A, B = _float_pair(mesh8, rng)
    e = A.expr().multiply(B.expr())
    assert planner.choose_precision_tier(
        e, cfg(precision_sla="fast")) == "bf16x1"
    assert planner.choose_precision_tier(
        e, cfg(precision_sla="high")) == "bf16x3"
    assert planner.choose_precision_tier(
        e, cfg(precision_sla="exact")) == "f32"
    assert planner.choose_precision_tier(e, cfg()) is None
    _, _, Ai, Bi = _int_pair(mesh8, rng)
    ei = Ai.expr().multiply(Bi.expr())
    assert planner.choose_precision_tier(
        ei, cfg(precision_sla="exact")) == "int32"
    assert planner.choose_precision_tier(
        ei, cfg(precision_sla="exact",
                precision_enable_int=False)) == "f32"


def test_sla_compute_factor():
    assert planner.sla_compute_factor(MatrelConfig()) == 1.0
    fast = planner.sla_compute_factor(
        MatrelConfig(precision_sla="fast"))
    assert fast == pytest.approx(0.5 / 3.0)
    high = planner.sla_compute_factor(
        MatrelConfig(precision_sla="high"))
    assert high == pytest.approx(1.5 / 3.0)


def test_chain_step_flop_scale_closed_form():
    base, lay = stats.chain_step_cost_layout(8, 8, 8, 1.0, 1.0, 2, 4,
                                             "2d", "2d")
    scaled, lay2 = stats.chain_step_cost_layout(
        8, 8, 8, 1.0, 1.0, 2, 4, "2d", "2d", flop_scale=0.5)
    comm = base - stats.matmul_cost(8, 8, 8)
    assert lay == lay2
    assert scaled == pytest.approx(stats.matmul_cost(8, 8, 8) * 0.5
                                   + comm)


# ---------------------------------------------------------------------------
# Integral inference + dtype threading
# ---------------------------------------------------------------------------


def test_infer_integral_rules(mesh8, rng):
    _, _, Ai, Bi = _int_pair(mesh8, rng, n=16, k=16, m=16)
    _, _, A, _ = _float_pair(mesh8, rng, n=16, k=16, m=16)
    ei = Ai.expr().multiply(Bi.expr())
    assert stats.infer_integral(ei)
    assert stats.infer_integral(ei.t())
    assert stats.infer_integral(ei.add(Bi.expr().t().t()))  # shapes ok
    assert stats.infer_integral(ei.multiply_scalar(3.0))
    assert not stats.infer_integral(ei.multiply_scalar(0.5))
    assert not stats.infer_integral(ei.divide(Bi.expr()))
    assert stats.infer_integral(E.agg(ei, "sum", "row"))
    assert stats.infer_integral(E.agg(A.expr(), "count", "row"))
    assert not stats.infer_integral(E.agg(ei, "avg", "row"))
    assert not stats.infer_integral(A.expr().multiply(Bi.expr()))
    # declared integral float data counts
    Af = BlockMatrix.from_numpy(
        np.ones((16, 16), np.float32), mesh=mesh8, integral=True)
    assert stats.infer_integral(Af.expr())


def test_from_numpy_integral_detection(mesh8):
    assert BlockMatrix.from_numpy(np.ones((8, 8), np.int64),
                                  mesh=mesh8).integral
    assert BlockMatrix.from_numpy(np.ones((8, 8), bool),
                                  mesh=mesh8).integral
    assert not BlockMatrix.from_numpy(np.ones((8, 8), np.float32),
                                      mesh=mesh8).integral


def test_infer_dtype_threads_int_tier(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng)
    cfg = MatrelConfig(precision_sla="exact")
    ann = planner.annotate_strategies(
        Ai.expr().multiply(Bi.expr()), mesh8, cfg)
    assert ann.attrs["precision_tier"] == "int32"
    assert planner.infer_dtype(ann, cfg) == np.dtype("int32")
    # the int32 result dtype flows through a consuming aggregate
    agg = E.agg(ann, "sum", "all")
    assert planner.infer_dtype(agg, cfg) == np.dtype("int32")


def test_integral_abs_bound_rules(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng, n=16, k=16, m=16)
    ba = float(np.abs(ai).max())
    bb = float(np.abs(bi).max())
    assert Ai.int_abs_max == ba                 # recorded by from_numpy
    assert stats.integral_abs_bound(Ai.expr()) == ba
    ei = Ai.expr().multiply(Bi.expr())
    assert stats.integral_abs_bound(ei) == 16 * ba * bb
    assert stats.integral_abs_bound(ei.add(Ai.expr())) == \
        16 * ba * bb + ba
    assert stats.integral_abs_bound(ei.multiply_scalar(2.0)) == \
        2 * 16 * ba * bb
    assert stats.integral_abs_bound(E.agg(ei, "sum", "row")) == \
        16 * (16 * ba * bb)
    # a declared-integral matrix WITHOUT a recorded magnitude: no bound
    Af = BlockMatrix(data=Ai.data, shape=Ai.shape, mesh=mesh8,
                     spec=Ai.spec, integral=True)
    assert stats.integral_abs_bound(Af.expr()) is None


def test_int_tier_overflow_gate(mesh8):
    """Auto int32 only when the accumulated product provably fits the
    int32 accumulator — "exact" must never silently wrap."""
    from matrel_tpu import analysis
    big = np.full((64, 64), 100_000, dtype=np.int64)
    A = BlockMatrix.from_numpy(big, mesh=mesh8)   # 64*1e10 >> 2^31
    cfg = MatrelConfig(precision_sla="exact")
    e = A.expr().multiply(A.expr())
    assert not planner.int_tier_fits(e, "int32")
    assert planner.choose_precision_tier(e, cfg) == "f32"   # not int32
    ann = _annotated(A.expr().multiply(A.expr()), mesh8, cfg)
    assert ann.attrs["precision_tier"] == "f32"
    # a hand-stamped int32 with PROVABLE overflow is an MV108 error,
    # even under the explicit int SLA (provably wrong is wrong)
    for sla in ("exact", "int32"):
        c = MatrelConfig(precision_sla=sla)
        bad = ann.with_attrs(precision_tier="int32")
        diags = [d for d in analysis.verify_plan(bad, mesh8, c)
                 if d.code == "MV108"]
        assert diags and diags[0].severity == "error", sla
        assert "accumulator" in diags[0].message
    # int8 additionally needs the CAST to fit: entries of 200 overflow
    # int8 even though 64*200*200 fits int32
    mid = np.full((64, 64), 200, dtype=np.int64)
    M = BlockMatrix.from_numpy(mid, mesh=mesh8)
    em = M.expr().multiply(M.expr())
    assert planner.int_tier_fits(em, "int32")
    assert not planner.int_tier_fits(em, "int8")


def test_pinned_sla_honored_on_integer_operands(mesh8, rng):
    """An inner int-tier product (int32 dtype) feeding another matmul:
    explicit int pins are honored, float pins stamp nothing, and the
    named SLAs continue the exact int32 algebra (closure) — including
    the mixed int32 × integral-f32-leaf case."""
    ai, bi, Ai, Bi = _int_pair(mesh8, rng, n=16, k=16, m=16)
    ci = rng.integers(-2, 3, (16, 16))
    Ci = BlockMatrix.from_numpy(ci, mesh=mesh8)
    for sla, want in (("exact", "int32"), ("high", "int32"),
                      ("fast", "bf16x1"),    # "fast" prefers bf16x1
                      ("int8", "int8"), ("int32", "int32")):
        cfg = MatrelConfig(precision_sla=sla)
        ann = _annotated(
            Ai.expr().multiply(Bi.expr()).multiply(Ci.expr()),
            mesh8, cfg)
        inner = next(c for c in ann.children if c.kind == "matmul")
        # under the int SLAs the inner product is int-tiered; its
        # int32 dtype flows to the outer matmul, whose other operand
        # is an integral f32 LEAF — the mixed case the closure rule
        # exists for. Under "fast" the chooser legitimately prefers
        # bf16x1 (cheapest satisfying tier) — and the bf16-tiered
        # inner product is then NOT integral, so the outer must not
        # claim int exactness either
        assert inner.attrs.get("precision_tier") == want, sla
        assert ann.attrs.get("precision_tier") == want, sla
        if want == "bf16x1":
            assert not stats.infer_integral(inner)
    # a float pin on INTEGER-dtype data stamps nothing (untier
    # promotion runs) — reachable via a hand-stamped int inner
    inner = _annotated(Ai.expr().multiply(Bi.expr()), mesh8,
                       MatrelConfig(precision_sla="exact"))
    mixed = E.matmul(inner, Ci.expr())
    for pin in ("float32", "bfloat16", "bf16x3"):
        assert planner.choose_precision_tier(
            mixed, MatrelConfig(precision_sla=pin)) is None, pin
    assert planner.choose_precision_tier(
        mixed, MatrelConfig(precision_sla="int8")) == "int8"
    assert planner.choose_precision_tier(
        mixed, MatrelConfig(precision_sla="exact")) == "int32"
    # end to end: the whole integral chain is EXACT under "exact"
    plan = compile_expr(
        Ai.expr().multiply(Bi.expr()).multiply(Ci.expr()), mesh8,
        MatrelConfig(precision_sla="exact"))
    got = plan.run().to_numpy()
    assert got.dtype == np.int32
    assert np.array_equal(got, ai @ bi @ ci)


# ---------------------------------------------------------------------------
# Lowering numerics — tiers vs f64 oracles
# ---------------------------------------------------------------------------


def test_tier_numerics_vs_oracle(mesh8, rng):
    a, b, A, B = _float_pair(mesh8, rng)
    want = a.astype(np.float64) @ b.astype(np.float64)
    k = a.shape[1]
    errs = {}
    for sla, tier in (("exact", "f32"), ("high", "bf16x3"),
                      ("fast", "bf16x1")):
        cfg = MatrelConfig(precision_sla=sla)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh8, cfg)
        assert _stamped_tier(plan) == {tier}
        got = plan.run().to_numpy().astype(np.float64)
        err = float(np.abs(got - want).max())
        assert err <= planner.tier_error_bound(tier, k, 1.0, 1.0), \
            (tier, err)
        errs[tier] = err
    # the tiers are really different numerics: bf16x1 is coarser than
    # bf16x3 is coarser than f32 (strict on random data)
    assert errs["bf16x1"] > errs["bf16x3"] >= errs["f32"]


def test_int_tier_exact_and_int8(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng)
    for sla in ("exact", "int32"):
        plan = compile_expr(Ai.expr().multiply(Bi.expr()), mesh8,
                            MatrelConfig(precision_sla=sla))
        got = plan.run().to_numpy()
        assert got.dtype == np.int32
        assert np.array_equal(got, ai @ bi)
    # explicit int8: inputs fit int8, accumulation is int32 (a k-deep
    # product of ±3 entries overflows int8 immediately — _acc_dtype's
    # integer contract)
    plan8 = compile_expr(Ai.expr().multiply(Bi.expr()), mesh8,
                         MatrelConfig(precision_sla="int8"))
    assert _stamped_tier(plan8) == {"int8"}
    got8 = plan8.run().to_numpy()
    assert np.array_equal(got8, ai @ bi)


def test_tier_composes_with_strategies(mesh_square, rng):
    """Tiered passes run through the stamped shard_map recipe — force
    each strategy and check the bf16x3 result still meets its bound."""
    a, b, A, B = _float_pair(mesh_square, rng, n=32, k=32, m=32)
    want = a.astype(np.float64) @ b.astype(np.float64)
    for strat in ("bmm_right", "cpmm", "rmm", "summa", "xla"):
        cfg = MatrelConfig(precision_sla="bf16x3",
                           strategy_override=strat)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh_square,
                            cfg)
        got = plan.run().to_numpy().astype(np.float64)
        err = float(np.abs(got - want).max())
        assert err <= planner.tier_error_bound("bf16x3", 32, 1.0, 1.0), \
            (strat, err)


def test_gram_shortcut_defers_to_tier(mesh8, rng):
    """matmul_precision="high" triggers the symmetric-gram shortcut;
    a stamped tier owns the numerics instead — the composition must
    still satisfy the tier bound."""
    a, _, A, _ = _float_pair(mesh8, rng, n=40, k=24, m=24)
    want = a.T.astype(np.float64) @ a.astype(np.float64)
    cfg = MatrelConfig(precision_sla="bf16x3",
                       matmul_precision="high")
    plan = compile_expr(A.expr().t().multiply(A.expr()), mesh8, cfg)
    got = plan.run().to_numpy().astype(np.float64)
    err = float(np.abs(got - want).max())
    assert err <= planner.tier_error_bound("bf16x3", a.shape[0],
                                           1.0, 1.0), err


# ---------------------------------------------------------------------------
# SLA threading — run / run_many / submit / SQL
# ---------------------------------------------------------------------------


def _session(mesh, **cfg_kw):
    from matrel_tpu.session import MatrelSession
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg_kw))


def test_run_threads_precision(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng)
    sess = _session(mesh8)
    q = Ai.expr().multiply(Bi.expr())
    out_default = sess.run(q)
    assert out_default.dtype == np.float32       # untier lowering
    out_exact = sess.run(q, precision="exact")
    assert out_exact.dtype == np.int32           # int tier executed
    assert np.array_equal(out_exact.to_numpy(), ai @ bi)
    # the two SLAs compiled under DIFFERENT plan-cache keys
    assert sess.plan_cache_info()["plans"] == 2


def test_run_many_and_submit_thread_precision(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng)
    sess = _session(mesh8)
    q = Ai.expr().multiply(Bi.expr())
    outs = sess.run_many([q, q], precision="exact")
    for o in outs:
        assert o.dtype == np.int32
        assert np.array_equal(o.to_numpy(), ai @ bi)
    # submit: mixed SLAs in one pipeline — per-query numerics hold
    # (the worker groups same-SLA queries into separate MultiPlans)
    f_exact = sess.submit(q, precision="exact")
    f_default = sess.submit(q)
    exact = f_exact.result(timeout=60)
    default = f_default.result(timeout=60)
    sess.serve_drain()
    assert exact.dtype == np.int32
    assert default.dtype == np.float32
    assert np.array_equal(exact.to_numpy(), ai @ bi)


def test_sql_precision_clause(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng)
    sess = _session(mesh8)
    sess.register("a", Ai)
    sess.register("b", Bi)
    e = sess.sql("SELECT a * b FROM a, b PRECISION 'exact'")
    assert getattr(e, "_sql_precision") == "exact"
    out = sess.run(e)
    assert out.dtype == np.int32
    assert np.array_equal(out.to_numpy(), ai @ bi)
    # explicit run argument beats the clause
    out2 = sess.run(e, precision="default")
    assert out2.dtype == np.float32
    # bad SLA raises SqlError at parse time
    from matrel_tpu.sql import SqlError
    with pytest.raises(SqlError):
        sess.sql("SELECT a * b FROM a, b PRECISION 'warp'")


# ---------------------------------------------------------------------------
# Result-cache tier isolation
# ---------------------------------------------------------------------------


def test_result_cache_tier_key_isolation(mesh8, rng):
    ai, bi, Ai, Bi = _int_pair(mesh8, rng)
    sess = _session(mesh8, result_cache_max_bytes=32 << 20)
    q = Ai.expr().multiply(Bi.expr())
    fast = sess.run(q, precision="fast")
    assert fast.dtype == np.float32             # bf16x1 ran
    info0 = sess.result_cache_info()
    assert info0["entries"] == 1
    # an "exact" probe of the SAME structural query must MISS the
    # "fast" entry and recompute exactly
    exact = sess.run(q, precision="exact")
    assert exact.dtype == np.int32
    assert np.array_equal(exact.to_numpy(), ai @ bi)
    info1 = sess.result_cache_info()
    assert info1["entries"] == 2                # separate entries
    # repeated same-SLA queries DO hit their own entries
    hits_before = sess.result_cache_info()["hits"]
    again = sess.run(q, precision="exact")
    assert sess.result_cache_info()["hits"] == hits_before + 1
    assert np.array_equal(again.to_numpy(), ai @ bi)


# ---------------------------------------------------------------------------
# MV108 verifier fixtures
# ---------------------------------------------------------------------------


def _annotated(e, mesh, cfg):
    from matrel_tpu.ir import rules
    return planner.annotate_strategies(rules.optimize(e, cfg), mesh,
                                       cfg)


def test_mv108_flags_violating_stamp(mesh8, rng):
    from matrel_tpu import analysis
    _, _, A, B = _float_pair(mesh8, rng)
    cfg = MatrelConfig(precision_sla="exact")
    ann = _annotated(A.expr().multiply(B.expr()), mesh8, cfg)
    assert ann.attrs["precision_tier"] == "f32"
    # hand-stamp a tier the SLA forbids — the wrong-answer class
    bad = ann.with_attrs(precision_tier="bf16x1")
    diags = [d for d in analysis.verify_plan(bad, mesh8, cfg)
             if d.code == "MV108"]
    assert diags and diags[0].severity == "error"
    assert "bf16x1" in diags[0].message


def test_mv108_flags_int_on_nonintegral(mesh8, rng):
    from matrel_tpu import analysis
    _, _, A, B = _float_pair(mesh8, rng)
    cfg = MatrelConfig(precision_sla="fast")
    ann = _annotated(A.expr().multiply(B.expr()), mesh8, cfg)
    bad = ann.with_attrs(precision_tier="int32")
    diags = [d for d in analysis.verify_plan(bad, mesh8, cfg)
             if d.code == "MV108"]
    assert diags and diags[0].severity == "error"
    assert "truncate" in diags[0].message
    # explicit int SLA downgrades the unprovable cast to a warning
    cfg_i = MatrelConfig(precision_sla="int32")
    ann_i = _annotated(A.expr().multiply(B.expr()), mesh8, cfg_i)
    diags_i = [d for d in analysis.verify_plan(ann_i, mesh8, cfg_i)
               if d.code == "MV108"]
    assert diags_i and diags_i[0].severity == "warning"


def test_mv108_clean_plans_quiet(mesh8, rng):
    from matrel_tpu import analysis
    a, b, A, B = _float_pair(mesh8, rng)
    _, _, Ai, Bi = _int_pair(mesh8, rng)
    for sla, e in (("exact", A.expr().multiply(B.expr())),
                   ("high", A.expr().multiply(B.expr())),
                   ("fast", A.expr().multiply(B.expr())),
                   ("exact", Ai.expr().multiply(Bi.expr())),
                   ("default", A.expr().multiply(B.expr()))):
        cfg = MatrelConfig(precision_sla=sla)
        ann = _annotated(e, mesh8, cfg)
        assert not [d for d in analysis.verify_plan(ann, mesh8, cfg)
                    if d.code == "MV108"], sla


def test_mv108_error_escalates(mesh8, rng):
    """MV108 findings are error-severity: the "error" policy raises
    VerificationError (the executor's pre-trace gate wiring is shared
    with every other pass and covered by test_analysis)."""
    from matrel_tpu import analysis
    from matrel_tpu.analysis import VerificationError
    _, _, A, B = _float_pair(mesh8, rng)
    cfg = MatrelConfig(precision_sla="exact", verify_plans="error")
    ann = _annotated(A.expr().multiply(B.expr()), mesh8, cfg)
    bad = ann.with_attrs(precision_tier="bf16x1")
    diags = analysis.verify_plan(bad, mesh8, cfg)
    assert any(d.code == "MV108" for d in diags)
    with pytest.raises(VerificationError):
        analysis.enforce(diags, "error")


def test_mv108_off_mode_free(mesh8, rng, monkeypatch):
    """verify_plans="off" (the default): the verifier (and with it
    MV108) never runs on the compile path — not merely quiet, absent."""
    from matrel_tpu import analysis
    called = []
    monkeypatch.setattr(analysis, "verify_plan",
                        lambda *a, **k: called.append(1) or [])
    _, _, A, B = _float_pair(mesh8, rng)
    compile_expr(A.expr().multiply(B.expr()), mesh8,
                 MatrelConfig(precision_sla="fast"))
    assert not called


# ---------------------------------------------------------------------------
# Default-config bit-identity
# ---------------------------------------------------------------------------


def test_default_config_stamps_nothing(mesh8, rng):
    from matrel_tpu import executor as executor_lib
    a, b, A, B = _float_pair(mesh8, rng)
    _, _, Ai, Bi = _int_pair(mesh8, rng)
    for e in (A.expr().multiply(B.expr()).multiply(B.expr().t()),
              Ai.expr().multiply(Bi.expr())):
        plan = compile_expr(e, mesh8, MatrelConfig())
        assert _stamped_tier(plan) == set()
        assert "precision" not in (plan.meta or {})
        for d in executor_lib.plan_matmul_decisions(plan):
            assert "precision_tier" not in d
        assert plan.run().dtype == np.float32


def test_default_sla_key_format_unchanged(mesh8, rng):
    """The default SLA keeps the historical cache-key format (empty
    prefix), so existing sessions/entries are untouched."""
    from matrel_tpu import session as session_mod
    assert session_mod._prec_prefix("default") == ""
    assert session_mod._prec_prefix("fast") == "prec:fast|"


# ---------------------------------------------------------------------------
# Drift auditor tier keying
# ---------------------------------------------------------------------------


def test_drift_tier_keying_and_rank_isolation():
    from matrel_tpu.obs import drift
    mk = lambda tier, ms, est: {
        "kind": "query", "backend": "cpu", "execute_ms": ms,
        "matmuls": [{"uid": 1, "dims": [512, 512, 512],
                     "strategy": "rmm", "flops": 2.0 * 512 ** 3,
                     "est_ici_bytes": est,
                     **({"precision_tier": tier} if tier else {})}]}
    # a miscalibrated bf16 population: cheaper est bytes, slower ms —
    # would flag against the f32 rows if blended into one group
    events = [mk(None, 2.0, 1e6)] * 3 + [mk("bf16x1", 9.0, 5e5)] * 3
    samples = list(drift.iter_samples(events))
    assert {s["strategy"] for s in samples} == {"rmm", "rmm@bf16x1"}
    calib = drift.calibrate(samples)
    assert any("rmm@bf16x1|" in k for k in calib)
    assert any(k.startswith("rmm|") for k in calib)
    # rank flags group per tier: the cross-tier inversion is NOT a flag
    assert drift.rank_flags(samples) == []
    # ...but a genuine same-tier inversion still is
    events2 = [mk("bf16x1", 9.0, 5e5) for _ in range(3)]
    for _ in range(3):
        ev = mk("bf16x1", 1.0, 9e5)
        ev["matmuls"][0]["strategy"] = "cpmm"
        events2.append(ev)
    flags = drift.rank_flags(list(drift.iter_samples(events2)))
    assert flags and flags[0]["model_prefers"] == "rmm@bf16x1"


# ---------------------------------------------------------------------------
# Obs surfaces
# ---------------------------------------------------------------------------


def test_decisions_and_meta_carry_tier(mesh8, rng):
    _, _, A, B = _float_pair(mesh8, rng)
    from matrel_tpu import executor as executor_lib
    cfg = MatrelConfig(precision_sla="high")
    plan = compile_expr(A.expr().multiply(B.expr()), mesh8, cfg)
    (d,) = executor_lib.plan_matmul_decisions(plan)
    assert d["precision_tier"] == "bf16x3"
    assert d["est_passes"] == 3
    assert d["est_rel_err"] == planner.TIER_EPS["bf16x3"]
    assert d["est_tier_cost"] == pytest.approx(planner.tier_matmul_cost(
        "bf16x3", *d["dims"]))
    meta = plan.meta["precision"]
    assert meta["sla"] == "high"
    assert meta["tiers"] == {"bf16x3": 1}
    assert meta["est_rel_err_bound"] == pytest.approx(
        planner.TIER_EPS["bf16x3"] * A.shape[1])
    # pretty/explain render the tier
    from matrel_tpu.ir.expr import pretty
    assert "precision=bf16x3" in pretty(plan.optimized)


def test_history_summary_rolls_up_tiers():
    from matrel_tpu.obs import history
    events = [{"kind": "query", "matmuls": [
        {"strategy": "rmm", "flops": 1.0, "precision_tier": "bf16x3",
         "est_passes": 3},
        {"strategy": "rmm", "flops": 1.0}]}]
    s = history.summarize(events)
    assert s["precision_tiers"] == {"bf16x3": {"count": 1,
                                               "passes": 3}}
    assert "precision tiers: bf16x3=1 (3 passes)" in \
        history.render_summary(events)
