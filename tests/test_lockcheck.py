"""lockcheck (tools/lockcheck.py): fixture-based proof that every
LK1xx rule fires on its hazard, that suppressions silence it, and
that the repo's own lock plane analyzes clean — the tier-1
enforcement of `make lint`'s lockcheck half (docs/CONCURRENCY.md)."""

import textwrap

from tools import lockcheck


def _analyze(tmp_path, sources, thread_roots=None):
    """Write a mini-package {relpath: source} and analyze it with the
    fixture's own thread-roots table (empty aliases)."""
    for rel, src in sources.items():
        f = tmp_path / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(src))
    return lockcheck.analyze_paths(
        sorted(sources), root=str(tmp_path),
        thread_roots=thread_roots or {}, aliases={})


def _rules(findings):
    return sorted({f.rule for f in findings})


class TestLK101Cycle:
    def test_fires_on_opposite_nesting(self, tmp_path):
        src = """
            import threading
            A = threading.Lock()
            B = threading.Lock()
            def f():
                with A:
                    with B:
                        pass
            def g():
                with B:
                    with A:
                        pass
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert _rules(got) == ["LK101"]
        assert "pkg/m.py:A" in got[0].message
        assert "pkg/m.py:B" in got[0].message

    def test_fires_through_calls(self, tmp_path):
        # the INTERPROCEDURAL half: f holds A and calls h (which
        # takes B); g nests them directly in the other order
        src = """
            import threading
            A = threading.Lock()
            B = threading.Lock()
            def h():
                with B:
                    pass
            def f():
                with A:
                    h()
            def g():
                with B:
                    with A:
                        pass
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert "LK101" in _rules(got)

    def test_consistent_order_clean(self, tmp_path):
        src = """
            import threading
            A = threading.Lock()
            B = threading.Lock()
            def f():
                with A:
                    with B:
                        pass
            def g():
                with A:
                    with B:
                        pass
        """
        assert _analyze(tmp_path, {"pkg/m.py": src}) == []


class TestLK102Blocking:
    def test_fires_on_sleep_under_lock(self, tmp_path):
        src = """
            import threading, time
            L = threading.Lock()
            def f():
                with L:
                    time.sleep(1)
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert _rules(got) == ["LK102"]

    def test_fires_transitively(self, tmp_path):
        src = """
            import threading
            L = threading.Lock()
            def helper(x):
                x.block_until_ready()
            def f(x):
                with L:
                    helper(x)
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert _rules(got) == ["LK102"]
        assert "helper" in got[0].message

    def test_thread_join_and_future_result_fire(self, tmp_path):
        src = """
            import threading
            L = threading.Lock()
            def f(t, fut):
                with L:
                    t.join(timeout=5)
                    fut.result(5)
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert [f.rule for f in got] == ["LK102", "LK102"]

    def test_str_and_path_join_do_not_fire(self, tmp_path):
        src = """
            import os, threading
            L = threading.Lock()
            def f(parts, d):
                with L:
                    a = ", ".join(parts)
                    b = os.path.join(d, "x")
                    return a + b
        """
        assert _analyze(tmp_path, {"pkg/m.py": src}) == []

    def test_dispatch_ok_lock_exempt(self, tmp_path):
        # the declared sanction: a lock constructed for dispatch-to-
        # completion arbitration may be held across device waits
        src = """
            from matrel_tpu.utils import lockdep
            L = lockdep.make_lock("fix.exec", dispatch_ok=True)
            def f(x):
                with L:
                    x.block_until_ready()
        """
        assert _analyze(tmp_path, {"pkg/m.py": src}) == []

    def test_suppression_silences(self, tmp_path):
        src = """
            import threading, time
            L = threading.Lock()
            def f():
                with L:
                    time.sleep(1)  # lockcheck: disable=LK102 fixture: deliberate hold
        """
        assert _analyze(tmp_path, {"pkg/m.py": src}) == []


class TestLK103SharedWrites:
    ROOTS = {"worker": (("pkg/m.py", "C.run"),),
             "daemon": (("pkg/m.py", "C.tick"),)}

    def test_fires_on_unguarded_two_root_writes(self, tmp_path):
        src = """
            class C:
                def __init__(self):
                    self.count = 0
                def run(self):
                    self.count += 1
                def tick(self):
                    self.count = 0
        """
        got = _analyze(tmp_path, {"pkg/m.py": src},
                       thread_roots=self.ROOTS)
        assert _rules(got) == ["LK103"]
        assert "C.count" in got[0].message

    def test_common_guard_clean(self, tmp_path):
        src = """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0
                def run(self):
                    with self._lock:
                        self.count += 1
                def tick(self):
                    with self._lock:
                        self.count = 0
        """
        assert _analyze(tmp_path, {"pkg/m.py": src},
                        thread_roots=self.ROOTS) == []

    def test_single_root_clean(self, tmp_path):
        src = """
            class C:
                def run(self):
                    self.count = 1
                def other(self):
                    self.count = 2
        """
        roots = {"worker": (("pkg/m.py", "C.run"),)}
        assert _analyze(tmp_path, {"pkg/m.py": src},
                        thread_roots=roots) == []


class TestLK104DoubleAcquire:
    def test_fires_on_direct_nesting(self, tmp_path):
        src = """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self):
                    with self._lock:
                        with self._lock:
                            pass
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert _rules(got) == ["LK104"]

    def test_fires_through_self_call(self, tmp_path):
        src = """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def inner(self):
                    with self._lock:
                        pass
                def outer(self):
                    with self._lock:
                        self.inner()
        """
        got = _analyze(tmp_path, {"pkg/m.py": src})
        assert "LK104" in _rules(got)

    def test_rlock_reentry_clean(self, tmp_path):
        src = """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.RLock()
                def inner(self):
                    with self._lock:
                        pass
                def outer(self):
                    with self._lock:
                        self.inner()
        """
        assert _analyze(tmp_path, {"pkg/m.py": src}) == []


class TestRepoClean:
    def test_repo_lock_plane_analyzes_clean(self):
        # mirrors `make lint`: the shipped tree carries no unsuppressed
        # LK1xx finding — new hazards fail HERE, in tier 1
        assert lockcheck.analyze_paths() == []

    def test_inventory_covers_the_seam(self):
        # every lockdep.make_lock/make_rlock name lands in the
        # inventory, and the known arbitration locks carry their
        # dispatch_ok sanction
        ana = lockcheck.analyzer_for()
        assert "fleet.controller" in ana.locks
        assert "serve.pipeline" in ana.locks
        assert ana.locks["fleet.exec"].dispatch_ok
        assert ana.locks["fleet.registration"].dispatch_ok
        assert not ana.locks["fleet.directory"].dispatch_ok

    def test_rule_catalogue_documented(self):
        doc = lockcheck.__doc__
        for rid, _ in lockcheck._RULES:
            assert rid in doc, rid
