"""SpGEMM (S×S tile-intersection) tests — ISSUE 2 tentpole.

Covers: pair-structure host math, kernel equivalence vs dense oracles
across densities/dtypes/grids (incl. fuzz seeds), the Pallas interpret
variant, the sharded wrapper, the executor's density-crossover dispatch
(structurally asserting NO densify below the threshold), COO-leaf
combinations, planner stamping/pricing/layout, the α-step comm term and
the two ADVICE r5 planner fixes that ride along this PR.
"""

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.coo import COOMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.ops import spgemm as spgemm_lib


def random_block_sparse_np(rng, n, k, bs, density):
    """Host oracle generator (shared idiom with test_sparse.py)."""
    import math
    gr, gc = math.ceil(n / bs), math.ceil(k / bs)
    a = np.zeros((n, k), dtype=np.float32)
    nblocks = max(1, int(gr * gc * density))
    flat = rng.choice(gr * gc, size=nblocks, replace=False)
    for f in flat:
        bi, bj = f // gc, f % gc
        blk = rng.standard_normal((bs, bs)).astype(np.float32)
        a[bi * bs:(bi + 1) * bs, bj * bs:(bj + 1) * bs] = \
            blk[: n - bi * bs, : k - bj * bs]
    return a


class TestPairStructure:
    def test_hand_case(self):
        # A tiles: (0,0), (0,1), (1,1); B tiles: (0,0), (1,0), (1,1)
        pa, pb, slot, orows, ocols = spgemm_lib.pair_structure(
            np.array([0, 0, 1]), np.array([0, 1, 1]),
            np.array([0, 1, 1]), np.array([0, 0, 1]), gc_out=2)
        # pairs: A0·B0→(0,0), A1·B1→(0,0), A1·B2→(0,1), A2·B1→(1,0),
        # A2·B2→(1,1); sorted by output slot
        assert pa.size == 5
        got = sorted(zip(pa.tolist(), pb.tolist(), slot.tolist()))
        assert got == [(0, 0, 0), (1, 1, 0), (1, 2, 1), (2, 1, 2),
                       (2, 2, 3)]
        assert orows.tolist() == [0, 0, 1, 1]
        assert ocols.tolist() == [0, 1, 0, 1]
        # pairs sorted by slot (the accumulate invariant)
        assert (np.diff(slot) >= 0).all()

    def test_unsorted_b_rows(self):
        # a hand-built B whose tile list is NOT row-major sorted must
        # still intersect correctly (pair_structure sorts defensively)
        pa, pb, slot, orows, ocols = spgemm_lib.pair_structure(
            np.array([0]), np.array([1]),
            np.array([2, 1, 0]), np.array([0, 1, 0]), gc_out=2)
        assert pa.tolist() == [0]
        assert pb.tolist() == [1]          # the block-row-1 B tile
        assert orows.tolist() == [0] and ocols.tolist() == [1]

    def test_empty_intersection(self):
        pa, pb, slot, orows, ocols = spgemm_lib.pair_structure(
            np.array([0]), np.array([0]),
            np.array([1]), np.array([0]), gc_out=1)
        assert pa.size == 0 and orows.size == 0


class TestKernelEquivalence:
    @pytest.mark.parametrize("n,k,m,bs,density", [
        (32, 32, 32, 8, 0.3),
        (64, 32, 48, 8, 0.2),        # rectangular, distinct grids
        (48, 48, 48, 16, 0.5),       # denser than the dispatch takes
        (40, 24, 56, 8, 0.15),
    ])
    def test_matches_dense_oracle(self, mesh8, rng, n, k, m, bs,
                                  density):
        a = random_block_sparse_np(rng, n, k, bs, density)
        b = random_block_sparse_np(rng, k, m, bs, density)
        A = BlockSparseMatrix.from_numpy(a, block_size=bs, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(b, block_size=bs, mesh=mesh8)
        C = spgemm_lib.spgemm(A, B, MatrelConfig())
        np.testing.assert_allclose(C.to_numpy(), a @ b, rtol=1e-5,
                                   atol=1e-5)
        assert C.shape == (n, m)

    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz_random_patterns(self, mesh8, seed):
        rng = np.random.default_rng(1000 + seed)
        bs = int(rng.choice([8, 16]))
        gr, gk, gm = rng.integers(1, 6, 3)
        n, k, m = int(gr) * bs, int(gk) * bs, int(gm) * bs
        a = random_block_sparse_np(rng, n, k, bs,
                                   float(rng.uniform(0.05, 0.6)))
        b = random_block_sparse_np(rng, k, m, bs,
                                   float(rng.uniform(0.05, 0.6)))
        A = BlockSparseMatrix.from_numpy(a, block_size=bs, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(b, block_size=bs, mesh=mesh8)
        C = spgemm_lib.spgemm(A, B, MatrelConfig())
        np.testing.assert_allclose(C.to_numpy(), a @ b, rtol=1e-4,
                                   atol=1e-4)

    def test_bfloat16_payloads(self, mesh8, rng):
        import jax.numpy as jnp
        a = random_block_sparse_np(rng, 32, 32, 8, 0.3)
        b = random_block_sparse_np(rng, 32, 32, 8, 0.3)
        A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8,
                                         dtype="bfloat16")
        B = BlockSparseMatrix.from_numpy(b, block_size=8, mesh=mesh8,
                                         dtype="bfloat16")
        C = spgemm_lib.spgemm(A, B, MatrelConfig())
        assert C.dtype == jnp.bfloat16     # keep_input_dtype policy
        ref = (np.asarray(A.to_numpy(), np.float32)
               @ np.asarray(B.to_numpy(), np.float32))
        np.testing.assert_allclose(
            np.asarray(C.to_numpy(), np.float32), ref,
            rtol=5e-2, atol=5e-2)          # bf16 storage tolerance

    def test_empty_product(self, mesh8):
        # disjoint contraction structure → the zero-tile convention
        a = np.zeros((16, 16), np.float32)
        a[0, 0] = 1.0                      # tile (0, 0) only
        b = np.zeros((16, 16), np.float32)
        b[8, 8] = 1.0                      # tile (1, 1) only
        A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(b, block_size=8, mesh=mesh8)
        C = spgemm_lib.spgemm(A, B, MatrelConfig())
        assert C.nnzb == 1
        np.testing.assert_allclose(C.to_numpy(), np.zeros((16, 16)))

    @pytest.mark.parametrize("shapes", [
        ((100, 100), (100, 100)),     # ragged everywhere (bs=16)
        ((96, 90), (90, 96)),         # ragged contraction dim only
    ])
    def test_ragged_random_operands(self, mesh8, shapes):
        """Regression (ragged verify probe): BlockSparseMatrix.random
        fills WHOLE tiles, so edge tiles carry nonzeros beyond the
        logical region — in S×S both operands overhang the contraction
        edge and garbage×garbage landed in kept entries until
        _edge_masked. The executor path must also keep the padded
        region exactly zero (the zero-padding invariant)."""
        from matrel_tpu import executor as executor_lib
        sa, sb = shapes
        A = BlockSparseMatrix.random(sa, 0.3, 16, mesh8, seed=31)
        B = BlockSparseMatrix.random(sb, 0.3, 16, mesh8, seed=32)
        ref = A.to_numpy() @ B.to_numpy()
        C = spgemm_lib.spgemm(A, B, MatrelConfig())
        np.testing.assert_allclose(C.to_numpy(), ref, rtol=1e-4,
                                   atol=1e-4)
        Cs = spgemm_lib.spgemm_sharded(A, B, MatrelConfig())
        np.testing.assert_allclose(Cs.to_numpy(), ref, rtol=1e-4,
                                   atol=1e-4)
        # executor leg: sparser pair so the estimate sits BELOW the
        # crossover (0.3-density operands estimate ~0.5 — correctly
        # routed to densify, which has its own masking)
        A2 = BlockSparseMatrix.random(sa, 0.1, 16, mesh8, seed=33)
        B2 = BlockSparseMatrix.random(sb, 0.1, 16, mesh8, seed=34)
        e = A2.multiply(B2)
        assert executor_lib._spgemm_dispatch(e, MatrelConfig())
        out = executor_lib.execute(e, mesh8, MatrelConfig())
        full = np.array(np.asarray(out.data))
        n, m = sa[0], sb[1]
        np.testing.assert_allclose(full[:n, :m],
                                   A2.to_numpy() @ B2.to_numpy(),
                                   rtol=1e-4, atol=1e-4)
        full[:n, :m] = 0
        assert not full.any(), "padded region must be exact zeros"

    def test_block_size_mismatch_raises(self, mesh8, rng):
        a = random_block_sparse_np(rng, 32, 32, 8, 0.3)
        A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(a, block_size=16, mesh=mesh8)
        with pytest.raises(ValueError, match="matching block sizes"):
            spgemm_lib.spgemm(A, B, MatrelConfig())

    def test_apply_dense_padded_canonical(self, mesh8, rng):
        from matrel_tpu.core import padding
        a = random_block_sparse_np(rng, 40, 24, 8, 0.3)
        b = random_block_sparse_np(rng, 24, 40, 8, 0.3)
        A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(b, block_size=8, mesh=mesh8)
        out = spgemm_lib.apply_dense(A, B, MatrelConfig())
        pshape = padding.padded_shape((40, 40), mesh8)
        assert tuple(out.shape) == pshape
        got = np.asarray(out)[:40, :40]
        np.testing.assert_allclose(got, a @ b, rtol=1e-5, atol=1e-5)
        # the zero-padding invariant every consumer relies on
        full = np.array(out)               # writable copy
        full[:40, :40] = 0
        assert not full.any()


def test_pallas_interpret_variant(mesh8, rng):
    """The scalar-prefetch Pallas kernel (interpret mode on CPU) must
    agree with the XLA gather/segment-sum runner bit-for-tolerance."""
    cfg = MatrelConfig(use_pallas=True, pallas_interpret=True)
    a = random_block_sparse_np(rng, 32, 32, 8, 0.4)
    b = random_block_sparse_np(rng, 32, 32, 8, 0.4)
    A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
    B = BlockSparseMatrix.from_numpy(b, block_size=8, mesh=mesh8)
    assert spgemm_lib.pallas_eligible(8, 4)
    C = spgemm_lib.spgemm(A, B, cfg)
    np.testing.assert_allclose(C.to_numpy(), a @ b, rtol=1e-5,
                               atol=1e-5)


def test_pallas_eligibility_gate():
    assert not spgemm_lib.pallas_eligible(4, 10)   # sub-8 sublane tile
    assert not spgemm_lib.pallas_eligible(8, 0)    # no pairs
    assert spgemm_lib.pallas_eligible(16, 1)


class TestShardedSpGEMM:
    @pytest.mark.parametrize("seed", range(3))
    def test_matches_oracle_on_mesh(self, mesh8, seed):
        rng = np.random.default_rng(2000 + seed)
        a = random_block_sparse_np(rng, 64, 48, 8, 0.3)
        b = random_block_sparse_np(rng, 48, 64, 8, 0.3)
        A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(b, block_size=8, mesh=mesh8)
        C = spgemm_lib.spgemm_sharded(A, B, MatrelConfig())
        np.testing.assert_allclose(C.to_numpy(), a @ b, rtol=1e-5,
                                   atol=1e-5)

    def test_empty_intersection_sharded(self, mesh8):
        a = np.zeros((16, 16), np.float32)
        a[0, 0] = 1.0
        b = np.zeros((16, 16), np.float32)
        b[8, 8] = 1.0
        A = BlockSparseMatrix.from_numpy(a, block_size=8, mesh=mesh8)
        B = BlockSparseMatrix.from_numpy(b, block_size=8, mesh=mesh8)
        C = spgemm_lib.spgemm_sharded(A, B, MatrelConfig())
        np.testing.assert_allclose(C.to_numpy(), np.zeros((16, 16)))


class TestFromCooArrays:
    def test_matches_from_scipy(self, mesh8, rng):
        import scipy.sparse as sp
        m = sp.random(40, 30, density=0.05, random_state=7,
                      format="coo", dtype=np.float32)
        S1 = BlockSparseMatrix.from_scipy(m, block_size=8, mesh=mesh8)
        S2 = BlockSparseMatrix.from_coo_arrays(
            m.row, m.col, m.data, m.shape, block_size=8, mesh=mesh8)
        np.testing.assert_allclose(S1.to_numpy(), S2.to_numpy())

    def test_duplicates_accumulate(self, mesh8):
        S = BlockSparseMatrix.from_coo_arrays(
            [0, 0, 5], [0, 0, 5], [1.0, 2.0, 4.0], (16, 16),
            block_size=8, mesh=mesh8)
        d = S.to_numpy()
        assert d[0, 0] == pytest.approx(3.0)   # scipy COO semantics
        assert d[5, 5] == pytest.approx(4.0)
        assert S.nnzb == 1                      # one touched tile


# -- executor dispatch -------------------------------------------------------


def _sparse_pair(mesh, bs=8, n=128, density=0.05, seeds=(11, 12)):
    A = BlockSparseMatrix.random((n, n), block_density=density,
                                 block_size=bs, mesh=mesh,
                                 seed=seeds[0])
    B = BlockSparseMatrix.random((n, n), block_density=density,
                                 block_size=bs, mesh=mesh,
                                 seed=seeds[1])
    return A, B


class TestExecutorDispatch:
    def test_dispatch_below_threshold_no_densify(self, mesh8,
                                                 monkeypatch):
        """The acceptance-criterion structural assert: an S×S matmul
        below the crossover must lower WITHOUT densifying either
        operand — to_dense/to_block poisoned, plan still runs."""
        from matrel_tpu import executor as executor_lib
        cfg = MatrelConfig()
        A, B = _sparse_pair(mesh8)
        e = A.multiply(B)
        assert executor_lib._spgemm_dispatch(e, cfg)
        ref = A.to_numpy() @ B.to_numpy()

        def boom(self, *a, **k):
            raise AssertionError(
                "S×S below the SpGEMM threshold densified an operand")

        monkeypatch.setattr(BlockSparseMatrix, "to_dense", boom)
        monkeypatch.setattr(COOMatrix, "to_block", boom)
        out = executor_lib.execute(e, mesh8, cfg)
        np.testing.assert_allclose(out.to_numpy()[:128, :128], ref,
                                   rtol=1e-5, atol=1e-5)

    def test_equals_densify_path(self, mesh8):
        """Equivalence across the crossover: the SpGEMM lowering and
        the densify fallback produce the same product."""
        from matrel_tpu import executor as executor_lib
        A, B = _sparse_pair(mesh8, density=0.1, seeds=(13, 14))
        sp = executor_lib.execute(A.multiply(B), mesh8, MatrelConfig())
        dn = executor_lib.execute(
            A.multiply(B), mesh8,
            MatrelConfig(spgemm_density_threshold=0.0))
        np.testing.assert_allclose(sp.to_numpy(), dn.to_numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_above_threshold_takes_densify(self, mesh8, monkeypatch):
        """Crossover: a dense-ish S×S (estimated output density ≥ the
        threshold) must route to the existing densify path."""
        from matrel_tpu import executor as executor_lib
        cfg = MatrelConfig()
        A, B = _sparse_pair(mesh8, density=0.9, seeds=(15, 16))
        e = A.multiply(B)
        assert not executor_lib._spgemm_dispatch(e, cfg)
        calls = []
        orig = BlockSparseMatrix.to_dense

        def spy(self, *a, **k):
            calls.append(1)
            return orig(self, *a, **k)

        monkeypatch.setattr(BlockSparseMatrix, "to_dense", spy)
        executor_lib.execute(e, mesh8, cfg)
        assert calls, "densify fallback did not run above the threshold"

    def test_threshold_zero_disables(self, mesh8):
        from matrel_tpu import executor as executor_lib
        A, B = _sparse_pair(mesh8)
        assert not executor_lib._spgemm_dispatch(
            A.multiply(B), MatrelConfig(spgemm_density_threshold=0.0))

    def test_coo_coo_dispatch(self, mesh8, monkeypatch):
        """Element-sparse × element-sparse: COO leaves bucket into
        block tiles (from_coo_arrays) — never through to_block."""
        from matrel_tpu import executor as executor_lib
        cfg = MatrelConfig(block_size=8)
        rng = np.random.default_rng(3)
        n, nnz = 256, 100
        C1 = COOMatrix.from_edges(rng.integers(0, n, nnz),
                                  rng.integers(0, n, nnz),
                                  shape=(n, n))
        C2 = COOMatrix.from_edges(rng.integers(0, n, nnz),
                                  rng.integers(0, n, nnz),
                                  shape=(n, n))
        e = C1.multiply(C2.expr())
        assert executor_lib._spgemm_dispatch(e, cfg)
        ref = C1.to_dense() @ C2.to_dense()
        monkeypatch.setattr(
            COOMatrix, "to_block",
            lambda self, *a, **k: (_ for _ in ()).throw(
                AssertionError("COO operand densified")))
        out = executor_lib.execute(e, mesh8, cfg)
        np.testing.assert_allclose(out.to_numpy()[:n, :n], ref,
                                   rtol=1e-5, atol=1e-5)

    def test_coo_clustered_exact_block_density(self, mesh8):
        """Review r6: COO block density is COUNTED from the edge list,
        not lifted probabilistically — clustered entries (500 nonzeros
        confined to 3 tiles) must dispatch; the uniform-independence
        lift would have saturated to ~0.86 and refused the very inputs
        tile-intersection SpGEMM exists for."""
        from matrel_tpu import executor as executor_lib
        cfg = MatrelConfig(block_size=16)
        rng = np.random.default_rng(6)
        rs, cs = [], []
        for (bi, bj) in [(0, 0), (3, 7), (9, 2)]:      # 3 tiles of 256
            rs.append(bi * 16 + rng.integers(0, 16, 170))
            cs.append(bj * 16 + rng.integers(0, 16, 170))
        C1 = COOMatrix.from_edges(np.concatenate(rs),
                                  np.concatenate(cs),
                                  shape=(256, 256))
        e = C1.multiply(C1.expr())
        (l, _) = e.children
        assert executor_lib._block_density_of(l, 16) == \
            pytest.approx(3 / 256)
        assert executor_lib._spgemm_dispatch(e, cfg)
        out = executor_lib.execute(e, mesh8, cfg)
        ref = C1.to_dense() @ C1.to_dense()
        np.testing.assert_allclose(out.to_numpy()[:256, :256], ref,
                                   rtol=1e-4, atol=1e-4)

    def test_pair_structure_cached_per_operand_pair(self, mesh8,
                                                    monkeypatch):
        """Review r6: the host intersection runs once per (A, B) pair —
        iterative reuse re-runs only device compute."""
        calls = []
        orig = spgemm_lib.pair_structure

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        monkeypatch.setattr(spgemm_lib, "pair_structure", counting)
        spgemm_lib._STRUCT_CACHE.clear()
        A, B = _sparse_pair(mesh8, seeds=(21, 22))
        spgemm_lib.spgemm(A, B, MatrelConfig())
        spgemm_lib.spgemm(A, B, MatrelConfig())
        spgemm_lib.spgemm_sharded(A, B, MatrelConfig())
        assert len(calls) == 1

    def test_mixed_bsr_coo_dispatch(self, mesh8):
        """BlockSparse × COO adopts the block-sparse partner's grid."""
        from matrel_tpu import executor as executor_lib
        cfg = MatrelConfig()
        rng = np.random.default_rng(4)
        A, _ = _sparse_pair(mesh8)
        C = COOMatrix.from_edges(rng.integers(0, 128, 60),
                                 rng.integers(0, 128, 60),
                                 shape=(128, 128))
        e = A.multiply(C.expr())
        assert executor_lib._spgemm_block_size(e, cfg) == A.block_size
        assert executor_lib._spgemm_dispatch(e, cfg)
        out = executor_lib.execute(e, mesh8, cfg)
        ref = A.to_numpy() @ C.to_dense()
        np.testing.assert_allclose(out.to_numpy()[:128, :128], ref,
                                   rtol=1e-5, atol=1e-5)

    def test_spgemm_feeds_downstream_ops(self, mesh8):
        """The scattered dense output must compose with the rest of the
        executor (scalar ops, aggregates) like any other matmul."""
        from matrel_tpu import executor as executor_lib
        A, B = _sparse_pair(mesh8, seeds=(17, 18))
        e = A.multiply(B).multiply_scalar(2.0).sum()
        out = executor_lib.execute(e, mesh8, MatrelConfig())
        ref = 2.0 * (A.to_numpy() @ B.to_numpy()).sum()
        assert np.asarray(out.to_numpy()).ravel()[0] == pytest.approx(
            ref, rel=1e-4)


# -- planner integration -----------------------------------------------------


class TestPlannerIntegration:
    def test_strategy_stamped_spgemm(self, mesh8):
        from matrel_tpu.parallel import planner
        A, B = _sparse_pair(mesh8)
        ann = planner.annotate_strategies(A.multiply(B), mesh8,
                                          MatrelConfig())
        assert ann.attrs["strategy"] == "spgemm"
        assert ann.attrs["strategy_source"] == "dispatch"

    def test_infer_layout_2d(self, mesh8):
        from matrel_tpu.parallel import planner
        A, B = _sparse_pair(mesh8)
        ann = planner.annotate_strategies(A.multiply(B), mesh8,
                                          MatrelConfig())
        assert planner.infer_layout(ann, mesh8,
                                    config=MatrelConfig()) == "2d"

    def test_comm_cost_spgemm_zero(self):
        from matrel_tpu.parallel import planner
        assert planner.comm_cost("spgemm", 128, 128, 128, 0.05, 0.05,
                                 2, 4) == 0.0

    def test_matmul_decisions_record(self, mesh8):
        from matrel_tpu.parallel import planner
        cfg = MatrelConfig()
        A, B = _sparse_pair(mesh8)
        ann = planner.annotate_strategies(A.multiply(B), mesh8, cfg)
        (rec,) = planner.matmul_decisions(ann, mesh8, cfg)
        assert rec["strategy"] == "spgemm"
        assert rec["dispatch"] == "spgemm"
        assert rec["est_saved_flops"] > 0
        assert rec["est_saved_hbm_bytes"] > 0
        assert 0.0 < rec["est_out_block_density"] < \
            cfg.spgemm_density_threshold

    def test_override_cannot_misreport_dispatch(self, mesh8):
        """strategy_override cannot reroute the S×S dispatch (the
        lowering checks _spgemm_dispatch before reading the strategy),
        so the stamp must still say spgemm — an 'rmm[override]' stamp
        would price a comm bill that never executes (review)."""
        from matrel_tpu.parallel import planner
        cfg = MatrelConfig(strategy_override="rmm")
        A, B = _sparse_pair(mesh8)
        assert planner.choose_strategy_ex(
            A.multiply(B), mesh8, cfg) == ("spgemm", "dispatch")
        # the documented way to force the densify path instead:
        cfg_off = MatrelConfig(strategy_override="rmm",
                               spgemm_density_threshold=0.0)
        assert planner.choose_strategy_ex(
            A.multiply(B), mesh8, cfg_off) == ("rmm", "override")

    def test_above_threshold_not_stamped_spgemm(self, mesh8):
        from matrel_tpu.parallel import planner
        A, B = _sparse_pair(mesh8, density=0.9, seeds=(15, 16))
        ann = planner.annotate_strategies(A.multiply(B), mesh8,
                                          MatrelConfig())
        assert ann.attrs["strategy"] != "spgemm"

    def test_query_event_carries_spgemm(self, mesh8, tmp_path):
        """End to end through the obs/ surface: the session's query
        event records the spgemm strategy + saved estimates."""
        import json
        from matrel_tpu import session as session_lib
        log = tmp_path / "events.jsonl"
        s = session_lib.MatrelSession(
            mesh=mesh8, config=MatrelConfig(obs_level="on",
                                            obs_event_log=str(log)))
        A, B = _sparse_pair(mesh8)
        s.compute(A.multiply(B))
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        (q,) = [r for r in recs if r["kind"] == "query"]
        (mm,) = q["matmuls"]
        assert mm["strategy"] == "spgemm"
        assert mm["est_saved_flops"] > 0


# -- α-step comm model + ADVICE r5 planner fixes (satellites) ---------------


class TestAlphaCommModel:
    def test_alpha_charges_per_step(self):
        """Exact step counts per strategy: cost(α) - cost(0) = steps·α."""
        from matrel_tpu.parallel import planner
        n = k = m = 1024
        al = 1e6

        def steps(strategy, gx, gy, **kw):
            c1 = planner.comm_cost(strategy, n, k, m, 1.0, 1.0, gx, gy,
                                   alpha_bytes=al, **kw)
            c0 = planner.comm_cost(strategy, n, k, m, 1.0, 1.0, gx, gy,
                                   **kw)
            return (c1 - c0) / al

        assert steps("bmm_right", 2, 4) == 2      # bcast + reshard
        assert steps("bmm_left", 2, 4) == 2
        assert steps("rmm", 2, 4) == 2            # two all-gathers
        assert steps("cpmm", 2, 4) == 2           # reshard_b + rs_c
        # SUMMA: 2·(g−1) ring ppermute steps (2d inputs: no reshard)
        assert steps("summa", 4, 4) == 2 * 3
        # replicated operands: gather terms vanish AND their steps do
        assert steps("rmm", 2, 4, a_layout="rep", b_layout="rep") == 0
        assert steps("spgemm", 2, 4) == 0

    def test_alpha_flips_latency_bound_choice(self, mesh8):
        """VERDICT r5 Missing #4: a small latency-bound multiply whose
        cheapest-β strategy needs MORE collective steps must flip to
        the fewer-step strategy once α is on — a col-sharded 16×512
        left operand gives cpmm three nonzero steps (re-lay A, gather
        B rows, reduce-scatter C) against rmm's two all-gathers."""
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.ir.expr import matmul
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel import planner
        rng = np.random.default_rng(0)
        A = BlockMatrix.from_numpy(
            rng.standard_normal((16, 512)).astype(np.float32),
            mesh=mesh8, spec=P(None, ("x", "y")))
        B = BlockMatrix.from_numpy(
            rng.standard_normal((512, 16)).astype(np.float32),
            mesh=mesh8)
        e = matmul(A.expr(), B.expr())
        beta_only, _ = planner.choose_strategy_ex(
            e, mesh8, MatrelConfig(comm_alpha_bytes=0.0),
            root_output=True)
        alpha, _ = planner.choose_strategy_ex(
            e, mesh8, MatrelConfig(), root_output=True)
        assert beta_only == "cpmm"     # β bytes alone prefer cpmm
        assert alpha == "rmm"          # α charges cpmm's third step


class TestChildRootScale:
    def test_wrappers_preserve_scale(self, mesh8, rng):
        from matrel_tpu.ir.expr import matmul
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel import planner
        A = BlockMatrix.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32),
            mesh=mesh8)
        mm = matmul(A.expr(), A.expr())
        scalar = mm.multiply_scalar(2.0)
        assert planner._child_root_scale(scalar, 0, 1.0) == 1.0
        # a matmul parent consumes the child's layout itself: no flow
        mm2 = matmul(mm, A.expr())
        assert planner._child_root_scale(mm2, 0, 1.0) == 0.0
        # non-root context: nothing flows
        assert planner._child_root_scale(scalar, 0, 0.0) == 0.0

    def test_elemwise_splits_charge(self, mesh8, rng):
        """ADVICE r5: at most ONE root re-lay occurs under a root
        elemwise — each full-shaped child carries half, and under
        broadcast only the full-shaped operand carries any."""
        from matrel_tpu.ir.expr import matmul
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel import planner
        A = BlockMatrix.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32),
            mesh=mesh8)
        v = BlockMatrix.from_numpy(
            rng.standard_normal((64, 1)).astype(np.float32),
            mesh=mesh8)
        mm = matmul(A.expr(), A.expr())
        ew = mm.add(mm)
        assert planner._child_root_scale(ew, 0, 1.0) == 0.5
        assert planner._child_root_scale(ew, 1, 1.0) == 0.5
        bc = mm.add(v.expr())          # broadcast: v is not full-shaped
        assert planner._child_root_scale(bc, 0, 1.0) == 1.0
        assert planner._child_root_scale(bc, 1, 1.0) == 0.0

    def test_rank1_layout_carrier_only(self, mesh8, rng):
        from matrel_tpu.ir.expr import matmul, rank_one_update
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.parallel import planner
        A = BlockMatrix.from_numpy(
            rng.standard_normal((64, 64)).astype(np.float32),
            mesh=mesh8)
        u = BlockMatrix.from_numpy(
            rng.standard_normal((64, 1)).astype(np.float32),
            mesh=mesh8)
        r1 = rank_one_update(matmul(A.expr(), A.expr()), u.expr(),
                             u.expr())
        assert planner._child_root_scale(r1, 0, 1.0) == 1.0
        assert planner._child_root_scale(r1, 1, 1.0) == 0.0


def test_child_layout_hints_admissibility_gate(mesh8, rng):
    """ADVICE r5: no hint toward a bmm the parent's padded dims cannot
    shard on this grid — a matvec-shaped (64,64)@(64,1) keeps its
    size-1 dim unpadded (padding.py), so bmm_left can never divide m
    across 8 devices and the 'col' hint must not be emitted."""
    from matrel_tpu.ir.expr import matmul
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.parallel import planner

    def mat(n, m):
        return BlockMatrix.from_numpy(
            rng.standard_normal((n, m)).astype(np.float32),
            mesh=mesh8).expr()

    matvec = matmul(mat(64, 64), mat(64, 1))
    assert planner._child_layout_hints(matvec, mesh8) == ("row", None)
    vecmat = matmul(mat(1, 64), mat(64, 64))
    assert planner._child_layout_hints(vecmat, mesh8) == (None, "col")
    wide = matmul(mat(64, 64), mat(64, 64))
    assert planner._child_layout_hints(wide, mesh8) == ("row", "col")
    # meshless call sites keep the threshold-only behaviour
    assert planner._child_layout_hints(matvec) == ("row", "col")
