"""Optimizer tests — plan-level assertions, the Catalyst comparePlans idiom
(SURVEY.md §4 "Optimizer tests"). Pure Python, no devices needed: rewrite
rules, chain DP decisions, statistics propagation."""

import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import chain, stats
from matrel_tpu.ir.expr import leaf, matmul, transpose
from matrel_tpu.ir.rules import apply_rewrites, optimize


def L(n, m, mesh, nnz=None, rng=None):
    a = np.zeros((n, m), dtype=np.float32)
    bm = BlockMatrix.from_numpy(a, mesh=mesh, nnz=nnz)
    return leaf(bm)


class TestRewrites:
    def test_double_transpose(self, mesh8):
        a = L(4, 6, mesh8)
        e = apply_rewrites(transpose(transpose(a)))
        assert e is a

    def test_transpose_of_matmul(self, mesh8):
        a, b = L(4, 5, mesh8), L(5, 6, mesh8)
        e = apply_rewrites(transpose(matmul(a, b)))
        # (A·B)ᵀ → Bᵀ·Aᵀ
        assert e.kind == "matmul"
        assert e.children[0].kind == "transpose"
        assert e.children[0].children[0] is b
        assert e.children[1].children[0] is a
        assert e.shape == (6, 4)

    def test_rowsum_pushdown(self, mesh8):
        a, b = L(4, 5, mesh8), L(5, 6, mesh8)
        e = apply_rewrites(matmul(a, b).row_sum())
        # rowSum(A·B) → A · rowSum(B)
        assert e.kind == "matmul"
        assert e.children[0] is a
        assert e.children[1].kind == "agg"
        assert e.children[1].attrs["axis"] == "row"
        assert e.shape == (4, 1)

    def test_colsum_pushdown(self, mesh8):
        a, b = L(4, 5, mesh8), L(5, 6, mesh8)
        e = apply_rewrites(matmul(a, b).col_sum())
        assert e.kind == "matmul"
        assert e.children[0].kind == "agg"
        assert e.children[1] is b

    def test_sum_of_matmul(self, mesh8):
        a, b = L(4, 5, mesh8), L(5, 6, mesh8)
        e = apply_rewrites(matmul(a, b).sum())
        # sum(A·B) → colSum(A)·rowSum(B): a (1,5)x(5,1) matmul
        assert e.kind == "matmul"
        assert e.shape == (1, 1)
        assert e.children[0].kind == "agg" and e.children[1].kind == "agg"

    def test_trace_of_matmul(self, mesh8):
        a, b = L(4, 5, mesh8), L(5, 4, mesh8)
        e = apply_rewrites(matmul(a, b).trace())
        # trace(A·B) → sum(A ⊙ Bᵀ): no matmul remains
        assert e.kind == "agg" and e.attrs["axis"] == "all"
        assert e.children[0].kind == "elemwise"

    def test_rowsum_of_transpose(self, mesh8):
        a = L(4, 6, mesh8)
        e = apply_rewrites(transpose(a).row_sum())
        assert e.kind == "transpose"
        assert e.children[0].attrs["axis"] == "col"

    def test_scalar_folding(self, mesh8):
        a = L(4, 4, mesh8)
        e = apply_rewrites(leaf_expr := (a.multiply_scalar(2.0).multiply_scalar(3.0)))
        assert e.kind == "scalar" and e.attrs["value"] == 6.0
        e2 = apply_rewrites(a.multiply_scalar(1.0))
        assert e2 is a

    def test_selection_pushdown_through_matmul(self, mesh8):
        a, b = L(4, 5, mesh8), L(5, 6, mesh8)
        sel = matmul(a, b).select_index(rows=lambda i: i < 2)
        e = apply_rewrites(sel)
        # σ_rows(A·B) → σ_rows(A)·B
        assert e.kind == "matmul"
        assert e.children[0].kind == "select_index"
        assert e.children[1] is b


class TestChainDP:
    def test_skewed_chain_reorders(self, mesh8):
        # A(10x1000)·B(1000x10)·C(10x1000): left-assoc is vastly cheaper
        a, b, c = L(10, 1000, mesh8), L(1000, 10, mesh8), L(10, 1000, mesh8)
        built = matmul(a, matmul(b, c))  # deliberately bad parenthesisation
        opt = chain.reorder_chains(built)
        # optimal: (A·B)·C
        assert opt.children[0].kind == "matmul"
        assert opt.children[0].children[0] is a
        assert opt.children[1] is c
        assert chain.chain_cost(opt) < chain.chain_cost(built)

    def test_chain_cost_matches_classic_dp(self, mesh8):
        # classic CLRS instance: dims 30x35, 35x15, 15x5, 5x10, 10x20, 20x25
        dims = [(30, 35), (35, 15), (15, 5), (5, 10), (10, 20), (20, 25)]
        ops = [L(n, m, mesh8) for n, m in dims]
        e = ops[0]
        for o in ops[1:]:
            e = matmul(e, o)
        opt, cost = chain.optimal_order(chain.collect_chain(e))
        # CLRS optimal scalar-mult count is 15125; our cost is 2x (FLOPs)
        assert cost == pytest.approx(2 * 15125)

    def test_sparsity_aware_ordering(self, mesh8):
        n = 100
        dense = L(n, n, mesh8)
        sp1 = L(n, n, mesh8, nnz=int(n * n * 0.01))
        sp2 = L(n, n, mesh8, nnz=int(n * n * 0.01))
        # (dense·sp1)·sp2 vs dense·(sp1·sp2): multiplying the two sparse
        # ones first is far cheaper; equal dims means only sparsity decides
        built = matmul(matmul(dense, sp1), sp2)
        opt = chain.reorder_chains(built)
        assert opt.children[0] is dense
        assert opt.children[1].kind == "matmul"

    def test_normal_equations_plan(self, mesh8):
        # linreg: Xᵀ·X and Xᵀ·y with X 10000x100 — full optimize() pass
        x = L(10000, 100, mesh8)
        y = L(10000, 1, mesh8)
        e = optimize(matmul(transpose(x), matmul(x, matmul(transpose(x), y))))
        # chain DP must avoid materialising X·Xᵀ (10000x10000)
        def max_intermediate(node):
            sizes = [node.shape[0] * node.shape[1]] if node.kind == "matmul" else []
            for ch in node.children:
                sizes.extend(max_intermediate(ch))
            return sizes
        assert max(max_intermediate(e)) <= 10000 * 1


class TestStats:
    def test_matmul_density(self):
        assert stats.matmul_density(1.0, 1.0, 100) == 1.0
        assert stats.matmul_density(0.0, 0.5, 100) == 0.0
        d = stats.matmul_density(0.01, 0.01, 1000)
        assert 0.05 < d < 0.15  # 1-(1-1e-4)^1000 ≈ 0.095

    def test_propagation_through_expr(self, mesh8):
        a = L(100, 100, mesh8, nnz=100)   # 1% dense
        b = L(100, 100, mesh8, nnz=100)
        mm = matmul(a, b)
        assert mm.nnz is not None and mm.nnz < 100 * 100 * 0.05
        add = a.add(b)
        assert add.nnz == pytest.approx(200, rel=0.01)
        em = a.elem_multiply(b)
        assert em.density == pytest.approx(0.0001, rel=0.01)


class TestRank1Rules:
    def test_rowsum_of_rank1_avoids_outer_product(self, mesh8):
        a = L(100, 80, mesh8)
        u = L(100, 1, mesh8)
        v = L(80, 1, mesh8)
        from matrel_tpu.ir.expr import rank_one_update
        e = apply_rewrites(rank_one_update(a, u, v).row_sum())
        # no rank1 node survives
        def kinds(n):
            out = {n.kind}
            for c in n.children:
                out |= kinds(c)
            return out
        assert "rank1" not in kinds(e)
        assert e.shape == (100, 1)

    def test_rank1_rule_numerics(self, mesh8, rng):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir.expr import leaf as mk_leaf, rank_one_update
        a = rng.standard_normal((9, 7)).astype(np.float32)
        u = rng.standard_normal((9, 1)).astype(np.float32)
        v = rng.standard_normal((7, 1)).astype(np.float32)
        A = mk_leaf(BlockMatrix.from_numpy(a, mesh=mesh8))
        U = mk_leaf(BlockMatrix.from_numpy(u, mesh=mesh8))
        V = mk_leaf(BlockMatrix.from_numpy(v, mesh=mesh8))
        for e, expect in [
            (rank_one_update(A, U, V).row_sum(), (a + u @ v.T).sum(1, keepdims=True)),
            (rank_one_update(A, U, V).col_sum(), (a + u @ v.T).sum(0, keepdims=True)),
            (rank_one_update(A, U, V).sum(), (a + u @ v.T).sum().reshape(1, 1)),
        ]:
            got = e.compute().to_numpy()
            np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


class TestMultiPlan:
    def test_shared_leaves_one_program(self, mesh8, rng):
        from matrel_tpu.executor import compile_exprs
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir.expr import leaf as mk_leaf, matmul, transpose
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = rng.standard_normal((32, 1)).astype(np.float32)
        X = mk_leaf(BlockMatrix.from_numpy(x, mesh=mesh8))
        Y = mk_leaf(BlockMatrix.from_numpy(y, mesh=mesh8))
        plan = compile_exprs((matmul(transpose(X), X),
                              matmul(transpose(X), Y)), mesh8)
        gram, rhs = plan.run()
        np.testing.assert_allclose(gram.to_numpy(), x.T @ x, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(rhs.to_numpy(), x.T @ y, rtol=1e-4, atol=1e-4)
        # X appears once in the shared leaf order
        assert len(plan.leaf_order) == 2


class TestCSE:
    def test_duplicate_subtrees_collapse(self, mesh8):
        from matrel_tpu.ir.rules import common_subexpressions
        from matrel_tpu.core.blockmatrix import BlockMatrix
        A = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32), mesh=mesh8)
        B = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32), mesh=mesh8)
        # A·B built twice from scratch (distinct nodes, same structure)
        e = A.multiply(B).t().add(A.multiply(B).t())
        opt = common_subexpressions(e)
        l, r = opt.children
        assert l is r  # one shared node after hash-consing

    def test_cse_numerics_via_compute(self, mesh8, rng):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((8, 8)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        B = BlockMatrix.from_numpy(b, mesh=mesh8)
        e = A.multiply(B).t().add(A.multiply(B).t())
        np.testing.assert_allclose(e.compute().to_numpy(), 2 * (a @ b).T,
                                   rtol=1e-4, atol=1e-4)


class TestSolveFusion:
    """R7: inverses never materialise when they feed a multiply."""

    def _exprs(self, mesh8, rng):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        a = rng.standard_normal((8, 8)).astype(np.float32)
        b = rng.standard_normal((8, 4)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        B = BlockMatrix.from_numpy(b, mesh=mesh8)
        return A.expr(), B.expr()

    def test_left_inverse_becomes_solve(self, mesh8, rng):
        from matrel_tpu.ir import rules
        import matrel_tpu.ir.expr as E
        A, B = self._exprs(mesh8, rng)
        e = rules.apply_rewrites(E.matmul(E.inverse(A), B))
        assert e.kind == "solve"
        assert e.children[0] is A and e.children[1] is B

    def test_right_inverse_becomes_transposed_solve(self, mesh8, rng):
        from matrel_tpu.ir import rules
        import matrel_tpu.ir.expr as E
        A, B = self._exprs(mesh8, rng)
        e = rules.apply_rewrites(E.matmul(A, E.inverse(A)))
        assert e.kind == "transpose" and e.children[0].kind == "solve"

    def test_double_inverse_cancels(self, mesh8, rng):
        from matrel_tpu.ir import rules
        import matrel_tpu.ir.expr as E
        A, _ = self._exprs(mesh8, rng)
        e = rules.apply_rewrites(E.inverse(E.inverse(A)))
        assert e is A


class TestRank1Pushdown:
    """R8: (A + u·vᵀ)·B → A·B + u·(vᵀ·B), both sides — the outer product
    is never materialised inside a multiply chain."""

    def test_left_rank1_multiply(self, mesh8):
        a, u, v = L(6, 6, mesh8), L(6, 1, mesh8), L(6, 1, mesh8)
        b = L(6, 4, mesh8)
        e = apply_rewrites(matmul(a.rank_one_update(u, v), b))
        assert e.kind == "elemwise" and e.attrs["op"] == "add"
        lhs, rhs = e.children
        assert lhs.kind == "matmul"
        assert lhs.children[0] is a and lhs.children[1] is b
        # rhs = u·(vᵀ·B): no rank1 node anywhere
        def no_rank1(n):
            assert n.kind != "rank1"
            for c in n.children:
                no_rank1(c)
        no_rank1(e)
        assert rhs.shape == (6, 4)

    def test_right_rank1_multiply(self, mesh8):
        a = L(4, 6, mesh8)
        base, u, v = L(6, 6, mesh8), L(6, 1, mesh8), L(6, 1, mesh8)
        e = apply_rewrites(matmul(a, base.rank_one_update(u, v)))
        assert e.kind == "elemwise" and e.attrs["op"] == "add"
        lhs, rhs = e.children
        assert lhs.children[0] is a and lhs.children[1] is base
        assert rhs.shape == (4, 6)

    def test_rank1_numeric_equivalence(self, mesh8, rng=None):
        # full pipeline: optimized vs unoptimized vs numpy oracle
        from matrel_tpu.executor import execute
        from matrel_tpu.config import MatrelConfig
        rng = np.random.default_rng(5)
        a = rng.standard_normal((6, 6)).astype(np.float32)
        u = rng.standard_normal((6, 1)).astype(np.float32)
        v = rng.standard_normal((6, 1)).astype(np.float32)
        b = rng.standard_normal((6, 4)).astype(np.float32)
        A = leaf(BlockMatrix.from_numpy(a, mesh=mesh8))
        U = leaf(BlockMatrix.from_numpy(u, mesh=mesh8))
        V = leaf(BlockMatrix.from_numpy(v, mesh=mesh8))
        B = leaf(BlockMatrix.from_numpy(b, mesh=mesh8))
        expr = matmul(A.rank_one_update(U, V), B)
        want = (a + u @ v.T) @ b
        got_opt = execute(expr, mesh8).to_numpy()
        got_raw = execute(expr, mesh8,
                          MatrelConfig(rewrite_rules=False)).to_numpy()
        np.testing.assert_allclose(got_opt, want, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(got_raw, want, rtol=1e-4, atol=1e-4)


class TestCommAwareChainDP:
    """The DP's step cost includes the collective bill: two
    parenthesisations with equal FLOPs but different comm bills must no
    longer tie arbitrarily (comm-aware reorder)."""

    def test_flop_tie_broken_by_comm(self, mesh8):
        # dims (16,512)(512,512)(512,16): both orders cost the same
        # FLOPs — an exact tie — but the comm proxy differs (the right
        # order's big middle operand rides a cheaper collective mix on
        # the 2x4 grid)
        n, k = 16, 512
        a, b, c = L(n, k, mesh8), L(k, k, mesh8), L(k, n, mesh8)
        ops = [a, b, c]
        flops_left = (stats.matmul_cost(n, k, k, 1, 1)
                      + stats.matmul_cost(n, k, n, 1, 1))
        flops_right = (stats.matmul_cost(k, k, n, 1, 1)
                       + stats.matmul_cost(n, k, n, 1, 1))
        assert flops_left == flops_right            # genuine FLOP tie
        opt_comm, cost_comm = chain.optimal_order(ops, grid=(2, 4))
        # the comm-aware plan must be at least as cheap (comm-inclusive)
        # as BOTH fixed parenthesisations, and strictly cheaper than one
        left = matmul(matmul(a, b), c)
        right = matmul(a, matmul(b, c))
        cl = chain.chain_cost(left, grid=(2, 4))
        cr = chain.chain_cost(right, grid=(2, 4))
        assert cl != cr                             # comm breaks the tie
        assert cost_comm == pytest.approx(min(cl, cr))

    def test_python_and_native_dp_agree_with_comm(self, mesh8,
                                                  monkeypatch):
        # run BOTH implementations on the same chain: the native comm
        # DP, and the pure-Python fallback (forced by disabling the
        # native path) — plans and costs must agree exactly
        from matrel_tpu.utils import native
        dims = [(64, 512), (512, 32), (32, 256), (256, 16)]
        ops = [L(n, m, mesh8) for n, m in dims]
        res = native.chain_dp(
            [d[0] for d in dims] + [dims[-1][1]],
            [1.0] * 4, grid=(2, 4))
        if res is None:
            pytest.skip("native comm DP unavailable")
        e_nat, c_nat = chain.optimal_order(ops, grid=(2, 4))
        assert c_nat == pytest.approx(res[1])
        monkeypatch.setattr(native, "chain_dp", lambda *a, **k: None)
        e_py, c_py = chain.optimal_order(ops, grid=(2, 4))
        assert c_py == pytest.approx(c_nat)
        from matrel_tpu.workloads.chain_bench import parenthesisation
        assert parenthesisation(e_py) == parenthesisation(e_nat)
        assert chain.chain_cost(e_py, grid=(2, 4)) == pytest.approx(c_py)

    def test_single_device_grid_unchanged(self, mesh8):
        # grid (1,1): step cost reduces exactly to FLOPs
        assert stats.chain_step_cost(50, 60, 70, 1.0, 1.0, 1, 1) == \
            stats.matmul_cost(50, 60, 70, 1.0, 1.0)
        assert stats.comm_proxy(50, 60, 70, 1.0, 1.0, 1, 1) == 0.0

    def test_comm_proxy_matches_planner_forms(self):
        # spot-check the proxy against planner.comm_cost with 2d layouts
        from matrel_tpu.parallel.planner import comm_cost
        n, k, m, gx, gy = 256, 512, 128, 2, 4
        want = min(comm_cost(s, n, k, m, 1.0, 1.0, gx, gy)
                   for s in ("bmm_right", "bmm_left", "cpmm", "rmm"))
        assert stats.comm_proxy(n, k, m, 1.0, 1.0, gx, gy) == \
            pytest.approx(want)


class TestLayoutAwareChainDP:
    """Round 5: with a mesh given, the chain DP's comm term reads
    operand layouts — a replicated operand makes the order that
    broadcasts it free strictly cheaper, breaking what the layout-blind
    DP saw as an exact tie."""

    def _chain(self, mesh, b_spec=None):
        # dims (16,512)(512,512)(512,16): exact FLOP tie between the
        # two parenthesisations (the TestCommAwareChainDP shape)
        n, k = 16, 512
        a = L(n, k, mesh)
        b = leaf(BlockMatrix.from_numpy(
            np.zeros((k, k), dtype=np.float32), mesh=mesh, spec=b_spec))
        c = L(k, n, mesh)
        return [a, b, c]

    def test_colsharded_middle_flips_to_left_assoc(self, mesh8):
        from jax.sharding import PartitionSpec as P
        # layout-blind: the comm tie-break picks RIGHT-assoc A·(B·C)
        # (B·C rides a cheap cpmm; the left order pays to re-lay the
        # 1 MB middle operand for bmm_left)
        blind, _ = chain.optimal_order(self._chain(mesh8), grid=(2, 4),
                                       mesh=mesh8)
        assert blind.children[1].kind == "matmul"       # A·(B·C)
        # with B ALREADY col-sharded, (A·B) consumes it in place as
        # bmm_left's broadcast target — the left order is now strictly
        # cheaper and the layout-aware DP flips the association
        aware, _ = chain.optimal_order(
            self._chain(mesh8, b_spec=P(None, ("x", "y"))), grid=(2, 4),
            mesh=mesh8)
        assert aware.children[0].kind == "matmul"       # (A·B)·C

    def test_python_and_native_layout_dp_agree(self, mesh8, monkeypatch):
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.utils import native
        if native.load() is None or not getattr(
                native.load(), "_matrel_has_dp_layout", False):
            pytest.skip("native layout DP unavailable")
        ops = self._chain(mesh8, b_spec=P(None, ("x", "y")))
        e_nat, c_nat = chain.optimal_order(ops, grid=(2, 4), mesh=mesh8)
        with monkeypatch.context() as mp:
            mp.setattr(native, "chain_dp", lambda *a, **k: None)
            e_py, c_py = chain.optimal_order(ops, grid=(2, 4),
                                             mesh=mesh8)
        assert c_nat == pytest.approx(c_py, rel=1e-9)
        assert e_nat.children[0].kind == e_py.children[0].kind

    def test_comm_proxy_layout_2d_matches_blind(self):
        # the layout-aware proxy at canonical layouts IS the old proxy —
        # the native matrel_chain_dp_comm semantics are unchanged
        rng = np.random.default_rng(3)
        for _ in range(50):
            n, k, m = (int(rng.integers(2, 2000)) for _ in range(3))
            da, db = rng.uniform(0.01, 1.0), rng.uniform(0.01, 1.0)
            gx, gy = int(rng.integers(1, 5)), int(rng.integers(1, 5))
            got, _lay = stats.comm_proxy_layout(n, k, m, da, db, gx, gy)
            assert got == stats.comm_proxy(n, k, m, da, db, gx, gy)
