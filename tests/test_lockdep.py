"""Runtime lockdep suite (matrel_tpu/utils/lockdep.py;
docs/CONCURRENCY.md).

Covers: each diagnostic fired on a seeded fixture (inversion,
self-deadlock, held-across-dispatch), raise vs record modes, the
dispatch_ok sanction, Condition interop, the obs-funnel emit hook,
config validation, and the structural-zero contract — the default
config constructs ZERO lockdep objects (poisoned-__init__, the
test_fleet idiom)."""

import threading

import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.utils import lockdep


@pytest.fixture()
def armed():
    """lockdep on (record mode), pristine graph, restored after."""
    lockdep.reset()
    lockdep.enable(raise_on_violation=False)
    yield
    lockdep.reset()
    lockdep.disable()


def _invert(a, b):
    """Drive a -> b on this thread and b -> a on a second one."""
    with a:
        with b:
            pass

    def other():
        with b:
            with a:
                pass

    t = threading.Thread(target=other, daemon=True)
    t.start()
    t.join(timeout=30)


class TestOrderGraph:
    def test_inversion_recorded(self, armed):
        a = lockdep.make_lock("fix.a")
        b = lockdep.make_lock("fix.b")
        _invert(a, b)
        diags = lockdep.diagnostics()
        assert any(d["diag"] == "inversion" for d in diags)
        assert not lockdep.is_acyclic()
        g = lockdep.order_graph()
        assert ("fix.a", "fix.b") in g and ("fix.b", "fix.a") in g

    def test_consistent_order_is_clean(self, armed):
        a = lockdep.make_lock("fix.c")
        b = lockdep.make_lock("fix.d")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lockdep.diagnostics() == []
        assert lockdep.is_acyclic()

    def test_inversion_raises_in_raise_mode(self, armed):
        lockdep.enable(raise_on_violation=True)
        a = lockdep.make_lock("fix.e")
        b = lockdep.make_lock("fix.f")
        with a:
            with b:
                pass
        box = []

        def other():
            try:
                with b:
                    with a:
                        pass
            except lockdep.LockOrderInversion as e:
                box.append(e)

        t = threading.Thread(target=other, daemon=True)
        t.start()
        t.join(timeout=30)
        assert box and box[0].record["diag"] == "inversion"

    def test_diag_record_shape(self, armed):
        a = lockdep.make_lock("fix.g")
        b = lockdep.make_lock("fix.h")
        _invert(a, b)
        d = next(d for d in lockdep.diagnostics()
                 if d["diag"] == "inversion")
        for key in ("kind", "lock", "held", "site", "held_site",
                    "thread", "msg"):
            assert key in d, key


class TestSelfDeadlock:
    def test_non_reentrant_double_acquire_is_fatal(self, armed):
        # fatal even in record mode: proceeding would WEDGE the
        # calling thread forever (wedge-safety beats record-only)
        a = lockdep.make_lock("fix.sd")
        with pytest.raises(lockdep.LockOrderInversion) as ei:
            with a:
                with a:
                    pass
        assert ei.value.record["diag"] == "self_deadlock"

    def test_rlock_reentry_clean(self, armed):
        r = lockdep.make_rlock("fix.re")
        with r:
            with r:
                pass
        assert lockdep.diagnostics() == []


class TestHeldAcrossDispatch:
    def test_unsanctioned_hold_fires(self, armed):
        lockdep.enable(raise_on_violation=True)
        a = lockdep.make_lock("fix.disp")
        with pytest.raises(lockdep.HeldAcrossDispatch):
            with a:
                lockdep.note_dispatch("fix.dispatch_point")

    def test_dispatch_ok_lock_sanctioned(self, armed):
        lockdep.enable(raise_on_violation=True)
        a = lockdep.make_lock("fix.disp_ok", dispatch_ok=True)
        with a:
            lockdep.note_dispatch("fix.dispatch_point")
        assert lockdep.diagnostics() == []

    def test_note_dispatch_off_is_free(self):
        lockdep.disable()
        lockdep.note_dispatch("fix.nothing")   # no state, no error


class TestInterop:
    def test_condition_wait_clean(self, armed):
        lk = lockdep.make_lock("fix.cond")
        cv = threading.Condition(lk)
        box = []

        def waiter():
            with cv:
                box.append(cv.wait(timeout=30))

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        while True:
            with cv:
                if box:
                    break
                cv.notify_all()
            if not t.is_alive():
                break
        t.join(timeout=30)
        assert box == [True]
        assert lockdep.diagnostics() == []

    def test_emit_hook_receives_records(self, armed):
        got = []
        lockdep.set_emit(got.append)
        a = lockdep.make_lock("fix.em1")
        b = lockdep.make_lock("fix.em2")
        _invert(a, b)
        assert any(r["diag"] == "inversion" for r in got)

    def test_nonblocking_acquire_skips_checks(self, armed):
        a = lockdep.make_lock("fix.nb")
        with a:
            # a try-lock that would "self-deadlock" is a legal probe:
            # it fails fast instead of wedging, so no diagnostic
            assert a.acquire(blocking=False) is False
        assert lockdep.diagnostics() == []


class TestStructuralZero:
    def test_default_off_returns_raw_primitives(self, monkeypatch):
        lockdep.disable()

        def poisoned(self, *a, **k):
            raise AssertionError(
                "lockdep object constructed while disabled")
        monkeypatch.setattr(lockdep._InstrumentedLock, "__init__",
                            poisoned)
        lk = lockdep.make_lock("fix.off")
        rl = lockdep.make_rlock("fix.off_r")
        assert type(lk) is type(threading.Lock())
        assert type(rl) is type(threading.RLock())

    def test_config_validation(self):
        with pytest.raises(ValueError, match="lockdep_raise"):
            MatrelConfig(lockdep_raise=True)
        cfg = MatrelConfig(lockdep_enable=True, lockdep_raise=True)
        assert cfg.lockdep_enable

    def test_session_emits_lockdep_into_flight_ring(self, monkeypatch):
        # the session wires lockdep diagnostics into the ONE obs
        # funnel: a violation under an armed session lands in the
        # flight-recorder ring as kind="lockdep"
        from matrel_tpu.session import MatrelSession
        sess = MatrelSession(config=MatrelConfig(
            lockdep_enable=True, obs_flight_recorder=64))
        try:
            a = lockdep.make_lock("fix.sess1")
            b = lockdep.make_lock("fix.sess2")
            _invert(a, b)
            ring = [r for r in sess._flight.snapshot()
                    if r.get("kind") == "lockdep"]
            assert ring and ring[-1]["diag"] == "inversion"
        finally:
            lockdep.reset()
            lockdep.disable()


class TestHistoryRollup:
    def _log_with_inversion(self, tmp_path):
        from matrel_tpu.session import MatrelSession
        log = str(tmp_path / "events.jsonl")
        MatrelSession(config=MatrelConfig(
            lockdep_enable=True, obs_level="on", obs_event_log=log))
        try:
            a = lockdep.make_lock("fix.hr1")
            b = lockdep.make_lock("fix.hr2")
            _invert(a, b)
        finally:
            lockdep.reset()
            lockdep.disable()
        return log

    def test_summary_line_and_check_gate(self, tmp_path):
        from matrel_tpu.obs import history
        log = self._log_with_inversion(tmp_path)
        events = history.read_events(log)
        s = history.summarize(events)
        assert s["lockdep"]["inversions"] >= 1
        assert s["lockdep"]["by_diag"].get("inversion", 0) >= 1
        text = history.render_summary(events)
        assert "lockdep:" in text and "LATENT DEADLOCK" in text

    def test_check_exits_nonzero_on_inversion(self, tmp_path):
        import argparse
        from matrel_tpu.obs import history
        log = self._log_with_inversion(tmp_path)
        args = argparse.Namespace(log=log, summary=True, check=True,
                                  drift=False, last=20)
        assert history.main(args) == 1

    def test_clean_log_summary_unchanged(self, tmp_path):
        # structural zero for the reader too: no lockdep events ->
        # None roll-up, no line — historical logs render byte-
        # identically
        from matrel_tpu.obs import history
        assert history._summarize_lockdep([]) is None
        assert "lockdep" not in history.render_summary(
            [{"kind": "query", "cache": "miss"}])
