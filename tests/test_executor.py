"""End-to-end execution tests — the MatrixOperatorSuite analogue
(SURVEY.md §4): DSL queries on the simulated 8-device mesh, numerics vs
numpy oracles, including ragged (padded) shapes."""

import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix


def bm(arr, mesh, **kw):
    return BlockMatrix.from_numpy(np.asarray(arr, dtype=np.float32), mesh=mesh, **kw)


@pytest.fixture()
def mats(mesh8, rng):
    a = rng.standard_normal((24, 16)).astype(np.float32)
    b = rng.standard_normal((16, 24)).astype(np.float32)
    return a, b, bm(a, mesh8), bm(b, mesh8)


class TestDenseOps:
    def test_matmul(self, mats):
        a, b, A, B = mats
        out = A.multiply(B).compute().to_numpy()
        np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-5)

    def test_matmul_ragged(self, mesh8, rng):
        a = rng.standard_normal((13, 9)).astype(np.float32)
        b = rng.standard_normal((9, 11)).astype(np.float32)
        out = bm(a, mesh8).multiply(bm(b, mesh8)).compute().to_numpy()
        assert out.shape == (13, 11)
        np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-5)

    def test_transpose(self, mats):
        a, _, A, _ = mats
        np.testing.assert_allclose(A.t().compute().to_numpy(), a.T, rtol=1e-6)

    def test_add_sub_elemwise(self, mesh8, rng):
        a = rng.standard_normal((10, 10)).astype(np.float32)
        b = rng.standard_normal((10, 10)).astype(np.float32)
        A, B = bm(a, mesh8), bm(b, mesh8)
        np.testing.assert_allclose(A.add(B).compute().to_numpy(), a + b, rtol=1e-5)
        np.testing.assert_allclose(A.subtract(B).compute().to_numpy(), a - b, rtol=1e-5)
        np.testing.assert_allclose(
            A.elem_multiply(B).compute().to_numpy(), a * b, rtol=1e-5)

    def test_divide_safe(self, mesh8):
        a = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
        b = np.array([[2.0, 0.0], [1.0, 4.0]], dtype=np.float32)
        out = bm(a, mesh8).divide(bm(b, mesh8)).compute().to_numpy()
        # division by zero yields 0 (sparse-relational semantics: missing)
        np.testing.assert_allclose(out, [[0.5, 0.0], [3.0, 1.0]], rtol=1e-6)

    def test_scalar_ops_mask_padding(self, mesh8, rng):
        a = rng.standard_normal((5, 5)).astype(np.float32)  # heavily padded
        A = bm(a, mesh8)
        out = A.add_scalar(3.0).compute()
        np.testing.assert_allclose(out.to_numpy(), a + 3.0, rtol=1e-5)
        # padding must remain zero after scalar add (invariant)
        full = np.asarray(out.data)
        assert np.all(full[5:, :] == 0)

    def test_power(self, mesh8):
        a = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
        out = bm(a, mesh8).power(2.0).compute().to_numpy()
        np.testing.assert_allclose(out, a ** 2, rtol=1e-5)

    def test_chained_expression(self, mats):
        a, b, A, B = mats
        # (A·B)ᵀ + (A·B)ᵀ computed via DSL; exercises rewrite + CSE by memo
        e = A.multiply(B).t().add(A.multiply(B).t())
        np.testing.assert_allclose(
            e.compute().to_numpy(), 2 * (a @ b).T, rtol=1e-4, atol=1e-5)


class TestAggregates:
    def test_row_col_sums(self, mesh8, rng):
        a = rng.standard_normal((9, 7)).astype(np.float32)
        A = bm(a, mesh8)
        np.testing.assert_allclose(
            A.row_sum().compute().to_numpy(), a.sum(1, keepdims=True),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            A.col_sum().compute().to_numpy(), a.sum(0, keepdims=True),
            rtol=1e-4, atol=1e-5)

    def test_sum_trace(self, mesh8, rng):
        a = rng.standard_normal((8, 8)).astype(np.float32)
        A = bm(a, mesh8)
        assert A.sum().compute().to_numpy()[0, 0] == pytest.approx(a.sum(), rel=1e-4)
        assert A.trace().compute().to_numpy()[0, 0] == pytest.approx(
            np.trace(a), rel=1e-4)

    def test_max_min_with_negative_entries(self, mesh8):
        # all-negative matrix, ragged: padding zeros must NOT win the max
        a = -np.abs(np.random.default_rng(0).standard_normal((5, 3))).astype(np.float32) - 1
        A = bm(a, mesh8)
        out = A.expr().row_max().compute().to_numpy()
        np.testing.assert_allclose(out, a.max(1, keepdims=True), rtol=1e-5)
        out = A.expr().col_min().compute().to_numpy()
        np.testing.assert_allclose(out, a.min(0, keepdims=True), rtol=1e-5)

    def test_count_avg(self, mesh8):
        a = np.array([[1.0, 0.0, 2.0], [0.0, 0.0, 3.0]], dtype=np.float32)
        A = bm(a, mesh8)
        np.testing.assert_allclose(
            A.expr().row_count().compute().to_numpy(), [[2.0], [1.0]])
        np.testing.assert_allclose(
            A.expr().row_avg().compute().to_numpy(), [[1.5], [3.0]])

    def test_rowsum_pushdown_numerics(self, mesh8, rng):
        # optimized plan (A·rowSum(B)) must equal unoptimized rowSum(A·B)
        a = rng.standard_normal((12, 20)).astype(np.float32)
        b = rng.standard_normal((20, 12)).astype(np.float32)
        A, B = bm(a, mesh8), bm(b, mesh8)
        out = A.multiply(B).row_sum().compute().to_numpy()
        np.testing.assert_allclose(out, (a @ b).sum(1, keepdims=True),
                                   rtol=1e-4, atol=1e-4)


class TestVecRank1:
    def test_vec_column_major(self, mesh8):
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        out = bm(a, mesh8).vec().compute().to_numpy()
        np.testing.assert_allclose(out, a.T.reshape(-1, 1))

    def test_rank_one_update(self, mesh8, rng):
        a = rng.standard_normal((6, 4)).astype(np.float32)
        u = rng.standard_normal((6, 1)).astype(np.float32)
        v = rng.standard_normal((4, 1)).astype(np.float32)
        out = bm(a, mesh8).rank_one_update(bm(u, mesh8), bm(v, mesh8))
        np.testing.assert_allclose(out.compute().to_numpy(), a + u @ v.T,
                                   rtol=1e-4, atol=1e-5)


class TestNormalEquations:
    def test_linreg_normal_equations(self, mesh8, rng):
        # the reference's flagship workload: (XᵀX)⁻¹Xᵀy pieces via the IR
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = rng.standard_normal((64, 1)).astype(np.float32)
        X, Y = bm(x, mesh8), bm(y, mesh8)
        xtx = X.t().multiply(X).compute().to_numpy()
        xty = X.t().multiply(Y).compute().to_numpy()
        np.testing.assert_allclose(xtx, x.T @ x, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(xty, x.T @ y, rtol=1e-4, atol=1e-4)
        theta = np.linalg.solve(xtx, xty)
        oracle = np.linalg.lstsq(x, y, rcond=None)[0]
        np.testing.assert_allclose(theta, oracle, rtol=1e-2, atol=1e-3)


class TestBf16Pipeline:
    def test_bf16_end_to_end_keeps_dtype(self, mesh8, rng):
        import jax.numpy as jnp
        a = rng.standard_normal((16, 16)).astype(np.float32)
        b = rng.standard_normal((16, 16)).astype(np.float32)
        A = bm(a, mesh8, dtype="bfloat16")
        B = bm(b, mesh8, dtype="bfloat16")
        out = A.multiply(B).compute()
        assert out.dtype == jnp.bfloat16  # f32 accumulate, bf16 storage
        np.testing.assert_allclose(out.to_numpy().astype(np.float32),
                                   a @ b, rtol=3e-2, atol=3e-1)

    def test_mixed_mesh_leaves_rejected(self, mesh8, mesh_square, rng):
        from matrel_tpu.executor import compile_expr
        a = bm(rng.standard_normal((8, 8)).astype(np.float32), mesh8)
        b = bm(rng.standard_normal((8, 8)).astype(np.float32), mesh_square)
        with pytest.raises(ValueError, match="mesh"):
            compile_expr(a.expr().multiply(b.expr()))


class TestBoundRunner:
    def test_matches_run_and_rebinds(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        a = rng.standard_normal((24, 24)).astype(np.float32)
        b = rng.standard_normal((24, 24)).astype(np.float32)
        A, B = bm(a, mesh8), bm(b, mesh8)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh8)
        a_leaf = plan.leaf_order[0]
        step = plan.bound_runner(rebind_uids=(a_leaf.uid,))
        cur = step(A.data)                    # A·B
        np.testing.assert_allclose(np.asarray(cur)[:24, :24], a @ b,
                                   rtol=1e-4, atol=1e-4)
        cur = step(cur)                       # (A·B)·B
        np.testing.assert_allclose(np.asarray(cur)[:24, :24], a @ b @ b,
                                   rtol=1e-4, atol=1e-3)
        # parity with the general run() path
        got = plan.run(bindings={a_leaf.uid: plan.run()}).to_numpy()
        np.testing.assert_allclose(np.asarray(cur)[:24, :24], got,
                                   rtol=1e-5, atol=1e-5)

    def test_no_rebind_closure(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        a = rng.standard_normal((16, 16)).astype(np.float32)
        A = bm(a, mesh8)
        plan = compile_expr(A.expr().multiply(A.expr().t()), mesh8)
        fixed = plan.bound_runner()
        np.testing.assert_allclose(np.asarray(fixed())[:16, :16], a @ a.T,
                                   rtol=1e-4, atol=1e-4)

    def test_unknown_uid_raises(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        A = bm(rng.standard_normal((8, 8)).astype(np.float32), mesh8)
        plan = compile_expr(A.expr().multiply(A.expr()), mesh8)
        with pytest.raises(KeyError):
            plan.bound_runner(rebind_uids=(999999,))

    def test_donate_chain(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        a = rng.standard_normal((16, 16)).astype(np.float32)
        b = rng.standard_normal((16, 16)).astype(np.float32)
        A, B = bm(a, mesh8), bm(b, mesh8)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh8)
        leaf = plan.leaf_order[0]
        step = plan.bound_runner(rebind_uids=(leaf.uid,), donate=True)
        cur = step(A.data + 0)        # fresh buffer (A.data stays live)
        cur = step(cur)
        cur = step(cur)
        np.testing.assert_allclose(np.asarray(cur)[:16, :16], a @ b @ b @ b,
                                   rtol=1e-3, atol=1e-2)

    def test_wrong_arity_raises(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        a = rng.standard_normal((8, 8)).astype(np.float32)
        A, B = bm(a, mesh8), bm(a, mesh8)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh8)
        step = plan.bound_runner(
            rebind_uids=tuple(l.uid for l in plan.leaf_order))
        with pytest.raises(ValueError, match="rebound"):
            step(A.data)


class TestSolveInverse:
    """inverse/solve nodes — the normal-equations building blocks."""

    def _spd(self, rng, n):
        m = rng.standard_normal((n, n)).astype(np.float32)
        return m @ m.T + n * np.eye(n, dtype=np.float32)

    def test_inverse_matches_numpy(self, mesh8, rng):
        a = self._spd(rng, 12)
        out = bm(a, mesh8).inverse().compute().to_numpy()
        np.testing.assert_allclose(out, np.linalg.inv(a), rtol=1e-3,
                                   atol=1e-4)

    def test_solve_matches_numpy(self, mesh8, rng):
        a = self._spd(rng, 12)
        b = rng.standard_normal((12, 5)).astype(np.float32)
        out = bm(a, mesh8).solve(bm(b, mesh8)).compute().to_numpy()
        np.testing.assert_allclose(out, np.linalg.solve(a, b), rtol=1e-3,
                                   atol=1e-4)

    def test_ragged_padding_not_singular(self, mesh8, rng):
        # 13x13 pads to a larger grid: the zero padding must be sliced
        # off before the LU factorisation or the system is singular
        a = self._spd(rng, 13)
        b = rng.standard_normal((13, 3)).astype(np.float32)
        out = bm(a, mesh8).solve(bm(b, mesh8)).compute().to_numpy()
        np.testing.assert_allclose(out, np.linalg.solve(a, b), rtol=1e-3,
                                   atol=1e-4)
        assert np.isfinite(out).all()

    def test_normal_equations_end_to_end(self, mesh8, rng):
        # the reference's flagship expression, straight from the DSL:
        # theta = (XᵀX)⁻¹ · (Xᵀy)
        x = rng.standard_normal((40, 6)).astype(np.float32)
        y = (x @ np.arange(1, 7, dtype=np.float32)[:, None]
             + 0.01 * rng.standard_normal((40, 1)).astype(np.float32))
        X, Y = bm(x, mesh8), bm(y, mesh8)
        theta = (X.t().matmul(X)).inverse().matmul(
            X.t().matmul(Y)).compute().to_numpy()
        oracle = np.linalg.solve(x.T @ x, x.T @ y)
        np.testing.assert_allclose(theta, oracle, rtol=1e-2, atol=1e-3)

    def test_shape_validation(self, mesh8, rng):
        import matrel_tpu.ir.expr as E
        A = bm(rng.standard_normal((4, 6)), mesh8)
        with pytest.raises(ValueError, match="square"):
            A.inverse()
        B = bm(rng.standard_normal((6, 6)), mesh8)
        with pytest.raises(ValueError, match="mismatch"):
            E.solve(B.expr(), bm(rng.standard_normal((4, 2)), mesh8).expr())


class TestLargeConstHoisting:
    """compile_expr hoists big sparse payloads into call-time args — the
    axon relay rejects compile requests with multi-GB embedded constants
    (the 10M-edge COO plan measured ~GBs of one-hot tables)."""

    def test_sparse_payload_hoisted_and_correct(self, mesh8, rng):
        from matrel_tpu.core.sparse import BlockSparseMatrix
        from matrel_tpu.executor import compile_expr
        from matrel_tpu.config import MatrelConfig
        # tile stack > 1 MB: 64 tiles of 64x64 f32 = 1.05 MB
        n = 512
        a = np.zeros((n, n), np.float32)
        for bi in range(8):
            for bj in range(8):
                a[bi*64:(bi+1)*64, bj*64:(bj+1)*64] = \
                    rng.standard_normal((64, 64))
        d = rng.standard_normal((n, 16)).astype(np.float32)
        S = BlockSparseMatrix.from_numpy(a, block_size=64, mesh=mesh8)
        D = bm(d, mesh8)
        plan = compile_expr(S.multiply(D), mesh8, MatrelConfig())
        assert len(plan.extra_args) >= 1        # payload rides as an arg
        assert sum(c.nbytes for c in plan.extra_args) >= 1 << 20
        np.testing.assert_allclose(plan.run().to_numpy(), a @ d,
                                   rtol=1e-4, atol=1e-4)
        # repeated runs and the iteration path both append the extras
        np.testing.assert_allclose(plan.run().to_numpy(), a @ d,
                                   rtol=1e-4, atol=1e-4)
        out = np.asarray(plan.bound_runner()())
        np.testing.assert_allclose(out[:n, :16], a @ d, rtol=1e-4,
                                   atol=1e-4)
        # donation paths must append the extras too (C <- f(C) loops)
        D2 = bm(d, plan.mesh)
        leaf_uid = plan.leaf_order[0].uid
        out2 = plan.run(bindings={leaf_uid: D2}, donate=True).to_numpy()
        np.testing.assert_allclose(out2, a @ d, rtol=1e-4, atol=1e-4)
        run3 = plan.bound_runner(rebind_uids=(leaf_uid,), donate=True)
        out3 = np.asarray(run3(bm(d, plan.mesh).data))
        np.testing.assert_allclose(out3[:n, :16], a @ d, rtol=1e-4,
                                   atol=1e-4)

    def test_small_consts_stay_embedded(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        from matrel_tpu.config import MatrelConfig
        A = bm(rng.standard_normal((16, 16)), mesh8)
        plan = compile_expr(A.expr().row_sum(), mesh8, MatrelConfig())
        assert plan.extra_args == []            # nothing above 1 MB

def test_cholesky_solve_option(mesh8, rng):
    m = rng.standard_normal((12, 12)).astype(np.float32)
    a = m @ m.T + 12 * np.eye(12, dtype=np.float32)
    b = rng.standard_normal((12, 5)).astype(np.float32)
    out = bm(a, mesh8).solve(bm(b, mesh8), assume="pos"
                             ).compute().to_numpy()
    np.testing.assert_allclose(out, np.linalg.solve(a, b), rtol=1e-3,
                               atol=1e-4)
    import matrel_tpu.ir.expr as E
    with pytest.raises(ValueError, match="assume"):
        E.solve(bm(a, mesh8).expr(), bm(b, mesh8).expr(),
                assume="banded")


def test_multiplan_hoists_and_appends_extras(mesh8, rng):
    # compile_exprs (multi-output) shares the hoisting path: sparse
    # payloads ride as args there too
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.executor import compile_exprs
    from matrel_tpu.config import MatrelConfig
    n = 1024
    a = np.zeros((n, n), np.float32)
    for bi in range(16):                 # 80 tiles of 64^2 f32 = 1.25 MB
        for bj in range(5):
            a[bi*64:(bi+1)*64, bj*64:(bj+1)*64] = \
            rng.standard_normal((64, 64))
    d = rng.standard_normal((n, 8)).astype(np.float32)
    S = BlockSparseMatrix.from_numpy(a, block_size=64, mesh=mesh8)
    D = bm(d, mesh8)
    e1 = S.multiply(D)
    e2 = e1.row_sum()
    plan = compile_exprs([e1, e2], mesh8, MatrelConfig())
    o1, o2 = plan.run()
    np.testing.assert_allclose(o1.to_numpy(), a @ d, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(o2.to_numpy(), (a @ d).sum(1, keepdims=True),
                               rtol=1e-4, atol=1e-4)
    if sum(c.nbytes for c in plan.extra_args) == 0:
        # tile stack below threshold would make this vacuous
        raise AssertionError("expected hoisted sparse payload")


def test_norms(mesh8, rng):
    a = rng.standard_normal((9, 13)).astype(np.float32)
    A = bm(a, mesh8)
    assert A.norm().compute().to_numpy()[0, 0] == pytest.approx(
        np.linalg.norm(a), rel=1e-4)
    assert A.norm("l1").compute().to_numpy()[0, 0] == pytest.approx(
        np.abs(a).sum(), rel=1e-4)
    assert A.norm("max").compute().to_numpy()[0, 0] == pytest.approx(
        np.abs(a).max(), rel=1e-4)
    with pytest.raises(ValueError, match="norm kind"):
        A.norm("spectral")
    # |a| via max(a, -a): tiny magnitudes must not underflow to 0
    tiny = bm(np.full((4, 4), -1e-30, np.float32), mesh8)
    assert tiny.norm("max").compute().to_numpy()[0, 0] == pytest.approx(
        1e-30, rel=1e-4)


class TestSymmetricGramLowering:
    """matmul(Aᵀ, A) / matmul(A, Aᵀ) under precision="high" lowers to
    the symmetric 2-pass bf16 split (round-3: 33% fewer MXU FLOPs at
    bf16x3-identical accuracy; docs/ROUND3.md)."""

    def _cfg(self):
        from matrel_tpu.config import MatrelConfig
        return MatrelConfig(matmul_precision="high")

    def test_ata_matches_oracle(self, mesh8, rng):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.executor import execute
        a = rng.standard_normal((48, 24)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        out = execute(A.expr().t().multiply(A.expr()), mesh8,
                      self._cfg()).to_numpy()
        np.testing.assert_allclose(out, a.T @ a, rtol=2e-3, atol=2e-3)

    def test_aat_matches_oracle(self, mesh8, rng):
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.executor import execute
        a = rng.standard_normal((24, 48)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        out = execute(A.expr().multiply(A.expr().t()), mesh8,
                      self._cfg()).to_numpy()
        np.testing.assert_allclose(out, a @ a.T, rtol=2e-3, atol=2e-3)

    def test_two_bf16_passes_not_one_f32(self, mesh8, rng, monkeypatch):
        # spy: the gram path must call run_matmul TWICE with bf16
        # operands (hi·hi, hi·lo) instead of once with f32
        import jax.numpy as jnp
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.executor import execute
        from matrel_tpu.parallel import strategies
        calls = []
        real = strategies.run_matmul

        def spy(strategy, x, y, mesh, config=None, **kw):
            calls.append((x.dtype, y.dtype))
            return real(strategy, x, y, mesh, config, **kw)

        monkeypatch.setattr(strategies, "run_matmul", spy)
        a = rng.standard_normal((32, 16)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        execute(A.expr().t().multiply(A.expr()), mesh8, self._cfg())
        gram_calls = [c for c in calls if c == (jnp.bfloat16, jnp.bfloat16)]
        assert len(gram_calls) == 2, calls

    def test_highest_precision_keeps_generic_path(self, mesh8, rng):
        # default "highest" must NOT take the 2-pass split (it would
        # silently downgrade accuracy): result ≈ f32-exact
        from matrel_tpu.config import MatrelConfig
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.executor import execute
        a = rng.standard_normal((32, 16)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        out = execute(A.expr().t().multiply(A.expr()), mesh8,
                      MatrelConfig(matmul_precision="highest")).to_numpy()
        np.testing.assert_allclose(out, a.T @ a, rtol=1e-5, atol=1e-5)

    def test_distinct_matrices_not_treated_as_gram(self, mesh8, rng):
        # Bᵀ·A with B ≠ A must stay on the generic path and be correct
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.executor import execute
        a = rng.standard_normal((48, 24)).astype(np.float32)
        b = rng.standard_normal((48, 24)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        B = BlockMatrix.from_numpy(b, mesh=mesh8)
        out = execute(B.expr().t().multiply(A.expr()), mesh8,
                      self._cfg()).to_numpy()
        np.testing.assert_allclose(out, b.T @ a, rtol=2e-3, atol=2e-3)


def test_rebound_leaf_with_different_layout_stays_correct(mesh8, rng):
    # round-5 net: a compiled plan is OPTIMIZED for the layouts its
    # leaves had at compile time; rebinding a matrix with a different
    # PartitionSpec may make the cached strategy suboptimal but must
    # never change the numbers (jit re-specializes on the new input
    # sharding; the strategy recipes are layout-correct for any input)
    from jax.sharding import PartitionSpec as P
    from matrel_tpu import executor
    from matrel_tpu.ir.expr import leaf, matmul
    a = rng.standard_normal((64, 32)).astype(np.float32)
    b = rng.standard_normal((32, 16)).astype(np.float32)
    a2 = rng.standard_normal((64, 32)).astype(np.float32)
    A_row = bm(a, mesh8, spec=P(("x", "y"), None))
    B = bm(b, mesh8)
    la = leaf(A_row)
    plan = executor.compile_expr(matmul(la, leaf(B)), mesh8)
    np.testing.assert_allclose(plan.run().to_numpy(), a @ b,
                               rtol=1e-4, atol=1e-4)
    # rebind with canonical-2D data of the same shape
    got = plan.run(bindings={la.uid: bm(a2, mesh8)}).to_numpy()
    np.testing.assert_allclose(got, a2 @ b, rtol=1e-4, atol=1e-4)
    # and with a replicated rebind
    A3_rep = bm(a2, mesh8, spec=P(None, None))
    got3 = plan.run(bindings={la.uid: A3_rep}).to_numpy()
    np.testing.assert_allclose(got3, a2 @ b, rtol=1e-4, atol=1e-4)
