"""Incremental view maintenance (ir/delta.py + serve/ivm.py +
session.register_delta; docs/IVM.md): per-rule patch-vs-fresh
equivalence (int paths bit-exact), eligibility fallback to the
transitive kill, patch-vs-recompute pricing with the autotune ``ivm|``
override, generation-prefix cache isolation, steady-state patch-plan
reuse, MV113 both halves, the obs ``delta`` event + history roll-up,
and the default-config bit-identity contract (register_delta unused ⇒
zero delta-plane objects, no ``delta:`` key prefixes)."""

import json
import os

import numpy as np
import pytest

from matrel_tpu import executor as executor_lib
from matrel_tpu.analysis import delta_pass, verify_plan
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.coo import COOMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.ir import delta as delta_lib
from matrel_tpu.session import MatrelSession

RC = dict(result_cache_max_bytes=256 << 20)


def _sess(mesh, **cfg):
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


def _int_adj(rng, n):
    a = (rng.random((n, n)) < 0.06).astype(np.float32)
    a = np.triu(a, 1)
    return a + a.T


def _coo_batch(rng, n, k, vals=None):
    rows = rng.integers(0, n, k)
    cols = rng.integers(0, n, k)
    v = np.ones(k, np.float32) if vals is None else vals
    return rows, cols, v


# ---------------------------------------------------------------------------
# MatrixDelta forms
# ---------------------------------------------------------------------------


class TestMatrixDelta:
    def test_coo_factors_reconstruct(self, mesh8, rng):
        old = BlockMatrix.from_numpy(_int_adj(rng, 64), mesh=mesh8,
                                     integral=True)
        rows, cols, v = _coo_batch(rng, 64, 9)
        d = delta_lib.as_delta((rows, cols, v), old, "coo")
        u, vv = d.factors(mesh8, MatrelConfig())
        got = u.to_numpy() @ vv.to_numpy().T
        np.testing.assert_array_equal(got, d.to_dense_numpy())
        assert d.rank == 9 and d.integral

    def test_lowrank_and_dense_kinds(self, mesh8, rng):
        old = BlockMatrix.from_numpy(
            rng.standard_normal((48, 32)).astype(np.float32),
            mesh=mesh8)
        U = rng.standard_normal((48, 3)).astype(np.float32)
        V = rng.standard_normal((32, 3)).astype(np.float32)
        d = delta_lib.as_delta((U, V), old, "lowrank")
        np.testing.assert_allclose(d.to_dense_numpy(), U @ V.T,
                                   rtol=1e-6)
        dd = delta_lib.as_delta(U @ V.T, old, "dense")
        assert dd.rank is None and dd.kind == "dense"

    def test_auto_disambiguation_and_validation(self, mesh8, rng):
        old = BlockMatrix.from_numpy(np.zeros((16, 16), np.float32),
                                     mesh=mesh8)
        coo = COOMatrix.from_edges([1, 2], [3, 4], shape=(16, 16))
        assert delta_lib.as_delta(coo, old).kind == "coo"
        with pytest.raises(ValueError, match="out of bounds"):
            delta_lib.as_delta(([99], [0], [1.0]), old, "coo")
        with pytest.raises(ValueError, match="shape"):
            delta_lib.as_delta(np.zeros((4, 4), np.float32), old,
                               "dense")
        with pytest.raises(ValueError, match="kind"):
            delta_lib.as_delta(np.zeros((16, 16)), old, "bogus")

    def test_apply_to_dense_and_sparse(self, mesh8, rng):
        a = _int_adj(rng, 64)
        old = BlockMatrix.from_numpy(a, mesh=mesh8, integral=True)
        rows, cols, v = _coo_batch(rng, 64, 7)
        d = delta_lib.as_delta((rows, cols, v), old, "coo")
        new = d.apply_to(old, mesh8, MatrelConfig())
        want = a.copy()
        np.add.at(want, (rows, cols), v)
        np.testing.assert_array_equal(new.to_numpy(), want)
        assert new.integral        # int + int stays provably int
        sp_old = BlockSparseMatrix.from_numpy(a, block_size=16,
                                              mesh=mesh8)
        sp_new = d.apply_to(sp_old, mesh8, MatrelConfig())
        np.testing.assert_array_equal(sp_new.to_numpy(), want)
        assert sp_new.block_size == 16

    def test_rank_above_bound_loses_factored_form(self, mesh8, rng):
        old = BlockMatrix.from_numpy(np.zeros((64, 64), np.float32),
                                     mesh=mesh8)
        rows, cols, v = _coo_batch(rng, 64, 12)
        d = delta_lib.as_delta((rows, cols, v), old, "coo")
        assert d.factors(mesh8, MatrelConfig(delta_rank_max=8)) is None
        assert d.factors(mesh8, MatrelConfig(delta_rank_max=16)) \
            is not None


# ---------------------------------------------------------------------------
# Per-rule patch-vs-fresh-execution equivalence
# ---------------------------------------------------------------------------


def _stream_check(sess, make_query, oracle_fn, name, make_delta,
                  steps, exact, tol=2e-4):
    """Run the query cold, then per step: produce one delta (the
    callable also advances the host oracle), register it, and assert
    the re-run HITS a patched entry and matches the oracle."""
    sess.run(make_query())
    for _ in range(steps):
        info0 = sess.result_cache_info()
        d_payload, kind = make_delta()
        sess.register_delta(name, d_payload, kind=kind)
        got = sess.run(make_query()).to_numpy()
        info1 = sess.result_cache_info()
        assert info1["hits"] > info0["hits"], "re-run did not hit"
        assert info1["patched"] > info0["patched"], "nothing patched"
        want = np.asarray(oracle_fn(), np.float32).reshape(got.shape)
        if exact:
            np.testing.assert_array_equal(got, want)
        else:
            scale = max(float(np.abs(want).max()), 1.0)
            np.testing.assert_allclose(got / scale, want / scale,
                                       atol=tol)


class TestRulePatchEquivalence:
    def test_matmul_left_delta(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n, k = 96, 24
        a = _int_adj(rng, n)
        f = rng.standard_normal((n, k)).astype(np.float32)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.register("F", sess.from_numpy(f))
        state = {"a": a}

        def mk():
            return sess.table("A").expr().multiply(
                sess.table("F").expr())

        def delta():
            rows, cols, v = _coo_batch(rng, n, 5)
            np.add.at(state["a"], (rows, cols), v)
            return (rows, cols, v), "coo"

        _stream_check(sess, mk, lambda: state["a"] @ f, "A",
                      delta, 3, exact=False)

    def test_matmul_right_delta(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        g = rng.standard_normal((16, n)).astype(np.float32)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.register("G", sess.from_numpy(g))
        state = {"a": a}

        def mk():
            return sess.table("G").expr().multiply(
                sess.table("A").expr())

        def delta():
            rows, cols, v = _coo_batch(rng, n, 4)
            np.add.at(state["a"], (rows, cols), v)
            return (rows, cols, v), "coo"

        _stream_check(sess, mk, lambda: g @ state["a"], "A",
                      delta, 2, exact=False)

    def test_gram_rank_k_correction_lowrank(self, mesh8, rng):
        # Δ(XᵀX) = ΔXᵀ·X + X'ᵀ·ΔX — the linreg panel-append case,
        # with an explicit low-rank (U, V) delta
        sess = _sess(mesh8, **RC)
        n, k = 128, 24
        x = rng.standard_normal((n, k)).astype(np.float32)
        sess.register("X", sess.from_numpy(x))
        state = {"x": x}

        def mk():
            return sess.table("X").expr().t().multiply(
                sess.table("X").expr())

        def delta():
            U = rng.standard_normal((n, 2)).astype(np.float32)
            V = rng.standard_normal((k, 2)).astype(np.float32)
            state["x"] = state["x"] + U @ V.T
            return (U, V), "lowrank"

        _stream_check(sess, mk, lambda: state["x"].T @ state["x"],
                      "X", delta, 2, exact=False, tol=1e-3)

    def test_elemwise_and_scalar_chain(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        b = rng.standard_normal((n, n)).astype(np.float32)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.register("B", sess.from_numpy(b))
        state = {"a": a}

        def mk():
            return sess.table("A").expr().elem_multiply(
                sess.table("B").expr()).multiply_scalar(3.0) \
                .add(sess.table("B").expr())

        def delta():
            rows, cols, v = _coo_batch(rng, n, 4)
            np.add.at(state["a"], (rows, cols), v)
            return (rows, cols, v), "coo"

        _stream_check(sess, mk, lambda: state["a"] * b * 3.0 + b,
                      "A", delta, 2, exact=False)

    def test_aggregates_exact_int(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        state = {"a": a}
        for mk, oracle in (
                (lambda: sess.table("A").expr().row_sum(),
                 lambda: state["a"].sum(1, keepdims=True)),
                (lambda: sess.table("A").expr().sum(),
                 lambda: state["a"].sum().reshape(1, 1))):
            def delta():
                rows, cols, v = _coo_batch(rng, n, 3)
                np.add.at(state["a"], (rows, cols), v)
                return (rows, cols, v), "coo"

            _stream_check(sess, mk, oracle, "A", delta, 2,
                          exact=True)

    def test_triangle_trace_exact_via_known_propagation(self, mesh8,
                                                        rng):
        # the graph-count headline: trace(A³) patched EXACTLY, with
        # the cached A·A entry's delta propagating into the trace
        # patch as a leaf (the known-map DAG propagation)
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        state = {"a": a}

        def mk_aa():
            return sess.table("A").expr().multiply(
                sess.table("A").expr())

        def mk_tri():
            return sess.table("A").expr().multiply(
                sess.table("A").expr()).multiply(
                sess.table("A").expr()).trace()

        sess.run(mk_aa())
        sess.run(mk_tri())
        for _ in range(3):
            rows, cols, v = _coo_batch(rng, n, 4)
            np.add.at(state["a"], (rows, cols), v)
            s = sess.register_delta("A", (rows, cols, v), kind="coo")
            assert s["patched"] == 2 and s["killed"] == 0
            assert s["rules"].get("known", 0) >= 1
            got_aa = sess.run(mk_aa()).to_numpy()
            got_tri = sess.run(mk_tri()).to_numpy()
            np.testing.assert_array_equal(got_aa,
                                          state["a"] @ state["a"])
            np.testing.assert_array_equal(
                got_tri,
                np.float32(np.trace(state["a"] @ state["a"]
                                    @ state["a"])).reshape(1, 1))

    def test_sparse_delta_spgemm_dispatch(self, mesh8, rng):
        # sparse ΔA against a sparse leaf partner: the emitted product
        # must route the S×S SpGEMM dispatch (the PR 10 registry path)
        # force mode for the end-to-end half: at toy scale the n²
        # combine honestly outweighs the tiny SpGEMM product, and the
        # point here is the dispatch routing, not the pricing
        cfg = MatrelConfig(delta_patch_mode="force", **RC)
        sess = _sess(mesh8, delta_patch_mode="force", **RC)
        n, bs = 128, 16
        # BLOCK-sparse operands (a few occupied tiles, not uniform
        # element sparsity — uniform 1% still touches every tile and
        # the dispatch's output-block-density gate would refuse)
        def tiles(k):
            m = np.zeros((n, n), np.float32)
            for _ in range(k):
                bi = int(rng.integers(0, n // bs))
                bj = int(rng.integers(0, n // bs))
                blk = (rng.random((bs, bs)) < 0.2).astype(np.float32)
                m[bi * bs:(bi + 1) * bs, bj * bs:(bj + 1) * bs] = blk
            return m
        a = tiles(5)
        b = tiles(5)
        sp_a = BlockSparseMatrix.from_numpy(a, block_size=bs,
                                            mesh=mesh8)
        sp_b = BlockSparseMatrix.from_numpy(b, block_size=bs,
                                            mesh=mesh8)
        sess.register("SA", sp_a)
        sess.register("SB", sp_b)
        state = {"a": a}

        def mk():
            from matrel_tpu.ir import expr as E
            return E.matmul(E.as_expr(sess.table("SA")),
                            E.as_expr(sess.table("SB")))

        sess.run(mk())
        rows, cols, v = _coo_batch(rng, n, 6)
        np.add.at(state["a"], (rows, cols), v)
        old = sess.table("SA")
        d = delta_lib.as_delta((rows, cols, v), old, "coo")
        new = d.apply_to(old, mesh8, cfg)
        ent = sess._result_cache.items_snapshot()[0][1]
        spec = delta_lib.derive_patch(ent.expr, old, new, d,
                                      ent.result, mesh8, cfg)
        assert spec is not None
        assert spec.rule == "spgemm" and not spec.rebindable
        s = sess.register_delta("SA", (rows, cols, v), kind="coo")
        assert s["patched"] == 1
        got = sess.run(mk()).to_numpy()
        np.testing.assert_allclose(got, state["a"] @ b, atol=1e-4)

    def test_refine_hook_warm_restart(self, mesh8, rng):
        # the iterative family: a stamped delta_refine callable owns
        # the patch (PageRank-style warm restart from the cached value)
        sess = _sess(mesh8, **RC)
        n = 48
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        calls = []

        def refine(old_result, new_matrix, d):
            calls.append(1)
            return new_matrix.to_numpy().sum(1, keepdims=True)

        def mk():
            return delta_lib.stamp_refine(
                sess.table("A").expr().row_sum(), refine)

        sess.run(mk())
        rows, cols, v = _coo_batch(rng, n, 3)
        np.add.at(a, (rows, cols), v)
        s = sess.register_delta("A", (rows, cols, v), kind="coo")
        assert s["patched"] == 1 and s["rules"] == {"refine": 1}
        assert calls == [1]
        got = sess.run(mk()).to_numpy()
        np.testing.assert_array_equal(got, a.sum(1, keepdims=True))

    def test_pagerank_warm_restart_converges(self, rng):
        a = _int_adj(rng, 64)
        cold = delta_lib.pagerank_warm_restart(
            a.astype(np.float64), np.full(64, 1 / 64), rounds=300)
        np.add.at(a, (rng.integers(0, 64, 4),
                      rng.integers(0, 64, 4)), 1.0)
        cold2 = delta_lib.pagerank_warm_restart(
            a.astype(np.float64), np.full(64, 1 / 64), rounds=300)
        warm = delta_lib.pagerank_warm_restart(
            a.astype(np.float64), cold, rounds=40)
        assert np.abs(warm - cold2).sum() < 1e-8
        assert np.abs(warm - cold2).sum() <= np.abs(
            delta_lib.pagerank_warm_restart(
                a.astype(np.float64), np.full(64, 1 / 64),
                rounds=5) - cold2).sum()


# ---------------------------------------------------------------------------
# Eligibility fallback + pricing
# ---------------------------------------------------------------------------


class TestEligibilityAndPricing:
    def test_ineligible_falls_back_to_kill(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        q = sess.table("A").expr().select_value(lambda v: v > 0.5)
        sess.run(q)
        rows, cols, v = _coo_batch(rng, n, 3)
        s = sess.register_delta("A", (rows, cols, v), kind="coo")
        assert s["patched"] == 0 and s["killed"] == 1
        np.add.at(a, (rows, cols), v)
        got = sess.run(sess.table("A").expr().select_value(
            lambda v: v > 0.5)).to_numpy()
        np.testing.assert_array_equal(got, a * (a > 0.5))

    def test_priced_out_falls_back_to_kill(self, mesh8, rng):
        # a fat delta (rank ~ n) makes the n×n patch cost more than
        # recompute — the pricing must kill, not patch at a loss
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().multiply(
            sess.table("A").expr()))
        rows, cols, v = _coo_batch(rng, n, n)  # rank n delta
        s = sess.register_delta("A", (rows, cols, v), kind="coo")
        assert s["patched"] == 0 and s["killed"] == 1
        assert s["priced_out"] == 1

    def test_force_mode_overrides_pricing(self, mesh8, rng):
        sess = _sess(mesh8, delta_patch_mode="force", **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().multiply(
            sess.table("A").expr()))
        rows, cols, v = _coo_batch(rng, n, n)
        np.add.at(a, (rows, cols), v)
        s = sess.register_delta("A", (rows, cols, v), kind="coo")
        assert s["patched"] == 1 and s["priced_out"] == 0
        got = sess.run(sess.table("A").expr().multiply(
            sess.table("A").expr())).to_numpy()
        np.testing.assert_array_equal(got, a @ a)

    def test_off_mode_kills_everything(self, mesh8, rng):
        sess = _sess(mesh8, delta_patch_mode="off", **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        s = sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        assert s["patched"] == 0 and s["killed"] == 1

    def test_measured_ivm_winner_overrides_estimate(self, mesh8, rng,
                                                    tmp_path):
        # a persisted ivm| "recompute" winner must veto a patch the
        # estimate likes (the fuse| measured-override precedent)
        from matrel_tpu.parallel import autotune
        table = str(tmp_path / "tab.json")
        sess = _sess(mesh8, autotune=True, autotune_table_path=table,
                     **RC)
        n = 96
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        import jax
        gx, gy = 2, 4
        key = autotune._ivm_key("rank_k", n, gx, gy)
        autotune._persist(table, key, "recompute",
                          {"patch": 2.0, "recompute": 1.0})
        autotune._IVM_CACHE.clear()
        autotune._TABLE_CACHE.clear()
        s = sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        assert s["patched"] == 0 and s["priced_out"] == 1
        assert sess._delta_plane.stats["measured_overrides"] == 1

    def test_ivm_key_format_accepted_and_pruned(self):
        from matrel_tpu.parallel import autotune
        assert autotune._current_key_format(
            "ivm|rank_k|1024|2x4|cpu")
        assert autotune._current_key_format(
            "ivm|spgemm|512|2x4|cpu|w1x8")
        assert not autotune._current_key_format(
            "ivm|retired_rule|1024|2x4|cpu")
        assert not autotune._current_key_format("ivm|rank_k|1024|2x4")

    def test_lookup_or_measure_ivm_ties_never_persist(self, mesh8,
                                                      tmp_path):
        from matrel_tpu.parallel import autotune
        cfg = MatrelConfig(autotune=True,
                           autotune_table_path=str(tmp_path / "t.json"))
        autotune._IVM_CACHE.clear()
        got = autotune.lookup_or_measure_ivm(
            "linear", 64, mesh8, cfg,
            patch_s=lambda: 1.0, full_s=lambda: 1.0)
        assert got is None
        # lookup without runners never measures, never caches negative
        autotune._IVM_CACHE.clear()
        assert autotune.lookup_or_measure_ivm("linear", 64, mesh8,
                                              cfg) is None


# ---------------------------------------------------------------------------
# Generation isolation + steady state
# ---------------------------------------------------------------------------


class TestGenerationIsolation:
    def test_keys_carry_generation_prefix(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        b = rng.standard_normal((n, n)).astype(np.float32)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.register("B", sess.from_numpy(b))
        sess.run(sess.table("A").expr().row_sum())
        sess.run(sess.table("B").expr().row_sum())   # independent
        keys0 = [k for k, _ in sess._result_cache.items_snapshot()]
        assert all(not k.startswith("delta:") for k in keys0)
        s = sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        assert s["gen"] == 1 and s["rekeyed"] == 1
        keys1 = [k for k, _ in sess._result_cache.items_snapshot()]
        assert keys1 and all(k.startswith("delta:1|") for k in keys1)
        # the independent entry was RENAMED, not killed: it still hits
        info0 = sess.result_cache_info()
        sess.run(sess.table("B").expr().row_sum())
        assert sess.result_cache_info()["hits"] == info0["hits"] + 1
        s2 = sess.register_delta("A", ([3], [4], [1.0]), kind="coo")
        assert s2["gen"] == 2
        keys2 = [k for k, _ in sess._result_cache.items_snapshot()]
        assert keys2 and all(k.startswith("delta:2|") for k in keys2)

    def test_precision_prefix_survives_patching(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        f = rng.standard_normal((n, 8)).astype(np.float32)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.register("F", sess.from_numpy(f))

        def mk():
            return sess.table("A").expr().multiply(
                sess.table("F").expr())

        sess.run(mk(), precision="fast")
        sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        keys = [k for k, _ in sess._result_cache.items_snapshot()]
        assert len(keys) == 1
        assert keys[0].startswith("delta:1|prec:fast|")
        # the patched fast entry answers a fast re-run, NOT an exact
        info0 = sess.result_cache_info()
        sess.run(mk(), precision="fast")
        assert sess.result_cache_info()["hits"] == info0["hits"] + 1
        sess.run(mk(), precision="exact")
        assert sess.result_cache_info()["misses"] > info0["misses"]

    def test_patch_plan_reuse_steady_state(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        for gen in range(1, 4):
            rows, cols, v = _coo_batch(rng, n, 3)
            np.add.at(a, (rows, cols), v)
            s = sess.register_delta("A", (rows, cols, v), kind="coo")
            assert s["patched"] == 1
            assert s["reused_plans"] == (0 if gen == 1 else 1)
        assert sess._delta_plane.stats["patch_compiles"] == 1
        assert sess._delta_plane.stats["patch_reuses"] == 2
        got = sess.run(sess.table("A").expr().row_sum()).to_numpy()
        np.testing.assert_array_equal(got, a.sum(1, keepdims=True))

    def test_signature_change_recompiles(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        sess.register_delta("A", (*_coo_batch(rng, n, 3),), kind="coo")
        # a different-capacity delta has a different signature: the
        # cached patch plan must NOT be rebound onto mismatched shapes
        s = sess.register_delta("A", (*_coo_batch(rng, n, 5),),
                                kind="coo")
        assert s["reused_plans"] == 0 and s["patched"] == 1
        assert sess._delta_plane.stats["patch_compiles"] == 2

    def test_known_propagation_is_tier_namespaced(self, mesh8, rng):
        # review r14: the same structural query cached at "fast" AND
        # "default" — the default entry's patch must never consume the
        # fast-tier sibling's (old, new) pair (bf16 error injected
        # into a bound composed from f32 units). The int query makes
        # the contamination detectable: default must stay BIT-exact.
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))

        def mk():
            return sess.table("A").expr().multiply(
                sess.table("A").expr())

        sess.run(mk(), precision="fast")     # processed first
        sess.run(mk())                       # default tier
        rows, cols, v = _coo_batch(rng, n, 4)
        np.add.at(a, (rows, cols), v)
        s = sess.register_delta("A", (rows, cols, v), kind="coo")
        assert s["patched"] == 2
        got = sess.run(mk()).to_numpy()      # the default entry
        np.testing.assert_array_equal(got, a @ a)
        assert delta_pass.verify_patched_entries(sess) == []

    def test_patch_programs_reconciled_after_kill(self, mesh8, rng):
        # review r14: a plain register() kills the entries but used to
        # leave their PatchPrograms (and the device arrays their plans
        # pin) cached forever; the next register_delta reconciles
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        assert len(sess._delta_plane._programs) == 1
        sess.register("A", sess.from_numpy(a, integral=True))  # kill
        assert sess.result_cache_info()["entries"] == 0
        sess.run(sess.table("A").expr().row_sum())
        sess.register_delta("A", ([3], [4], [1.0]), kind="coo")
        # exactly the live entry's program remains — the orphan is gone
        live = {e.ivm_id for _k, e in
                sess._result_cache.items_snapshot()}
        assert set(sess._delta_plane._programs) == live
        assert len(sess._delta_plane._programs) == 1

    def test_apply_patch_budget_failure_restores_old(self, mesh8,
                                                     rng):
        # review r14: an over-budget patched result must leave the OLD
        # entry in place so the caller's kill counts invalidation and
        # feeds the brownout graveyard — not vanish silently
        import dataclasses
        from matrel_tpu.serve.result_cache import (ResultCache,
                                                   result_nbytes)
        rc_ = ResultCache()
        bm = BlockMatrix.from_numpy(
            rng.standard_normal((32, 32)).astype(np.float32),
            mesh=mesh8)
        from matrel_tpu.serve.result_cache import CacheEntry
        ent = CacheEntry(key_hash="k", result=bm, pins=(),
                         dep_ids=frozenset({1}), layout="2d",
                         dtype="float32", nbytes=result_nbytes(bm))
        assert rc_.put("old", ent, 1 << 20)
        big = dataclasses.replace(ent, nbytes=2 << 20)
        assert not rc_.apply_patch("old", "new", big, 1 << 20)
        assert rc_.lookup("old") is ent          # restored
        assert rc_.patched == 0
        assert rc_.drop("old", keep_stale=True, stale_max=4,
                        stale_max_bytes=1 << 20)
        assert rc_.invalidated == 1
        assert rc_.info()["stale_entries"] == 1  # graveyard fed

    def test_register_delta_unbound_name_raises(self, mesh8):
        sess = _sess(mesh8, **RC)
        with pytest.raises(KeyError, match="not a bound"):
            sess.register_delta("nope", ([0], [0], [1.0]), kind="coo")

    def test_plain_register_still_invalidates(self, mesh8, rng):
        # register() keeps its historical semantics even after deltas
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        assert sess.result_cache_info()["entries"] == 1
        sess.register("A", sess.from_numpy(a, integral=True))
        assert sess.result_cache_info()["entries"] == 0


# ---------------------------------------------------------------------------
# MV113 — both halves, both directions
# ---------------------------------------------------------------------------


class TestMV113:
    def _patched_sess(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        rows, cols, v = _coo_batch(rng, n, 3)
        np.add.at(a, (rows, cols), v)
        sess.register_delta("A", (rows, cols, v), kind="coo")
        return sess

    def test_dynamic_clean_after_patch(self, mesh8, rng):
        sess = self._patched_sess(mesh8, rng)
        assert delta_pass.verify_patched_entries(sess) == []

    def test_dynamic_flags_corrupted_result(self, mesh8, rng):
        import dataclasses
        sess = self._patched_sess(mesh8, rng)
        key, ent = sess._result_cache.items_snapshot()[0]
        bad = BlockMatrix.from_numpy(
            ent.result.to_numpy() + 1.0, mesh=mesh8)
        # corrupt THROUGH the seam (a new entry object) — the dynamic
        # check must catch a wrong value whatever wrote it
        sess._result_cache.apply_patch(
            key, key, dataclasses.replace(ent, result=bad),
            RC["result_cache_max_bytes"])
        diags = delta_pass.verify_patched_entries(sess)
        assert len(diags) == 1 and diags[0].code == "MV113"
        assert "diverges" in diags[0].message

    def test_static_quiet_on_fresh_substitution(self, mesh8, rng):
        sess = self._patched_sess(mesh8, rng)
        # consume the patched entry as an interior leaf: the stamped
        # plan must verify MV113-quiet
        q = sess.table("A").expr().row_sum().multiply_scalar(2.0)
        _ent, _key, _pins, sub = sess._rc_admit(
            q, sess._rc_key_prefix("default"))
        from matrel_tpu.ir import rules
        from matrel_tpu.parallel import planner
        opt = planner.annotate_strategies(
            rules.optimize(sub, sess.config, mesh=sess.mesh),
            sess.mesh, sess.config)
        diags = [d for d in verify_plan(opt, sess.mesh, sess.config)
                 if d.code == "MV113"]
        assert diags == []
        # and the substituted leaf really carries the provenance
        stamps = []

        def walk(n):
            rc = n.attrs.get("result_cache")
            if rc and rc.get("delta"):
                stamps.append(rc["delta"])
            for c in n.children:
                walk(c)

        walk(sub)
        assert stamps and stamps[0]["gen"] == 1
        assert stamps[0]["rule"] in delta_lib.DELTA_RULES

    @pytest.mark.parametrize("tamper,needle", [
        ({"gen": 0, "rule": "rank_k", "err_bound": 0.0},
         "generation"),
        ({"gen": 1, "rule": "made_up", "err_bound": 0.0},
         "vocabulary"),
        ({"gen": 1, "rule": "rank_k", "err_bound": -1.0},
         "err_bound"),
        ("not-a-dict", "unreadable"),
    ])
    def test_static_flags_tampered_stamp(self, mesh8, rng, tamper,
                                         needle):
        from matrel_tpu.ir import expr as E
        bm = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32),
            mesh=mesh8)
        leaf = E.leaf(bm).with_attrs(result_cache={
            "key_hash": "x", "layout": "2d", "dtype": "float32",
            "deps": [], "delta": tamper})
        diags = [d for d in verify_plan(
            leaf.multiply_scalar(2.0), mesh8, MatrelConfig())
            if d.code == "MV113"]
        assert diags, "tampered stamp not flagged"
        assert any(needle in d.message for d in diags), diags


# ---------------------------------------------------------------------------
# Obs surfaces
# ---------------------------------------------------------------------------


class TestObsSurfaces:
    def test_delta_event_and_history_rollup(self, mesh8, rng,
                                            tmp_path):
        log = str(tmp_path / "events.jsonl")
        sess = _sess(mesh8, obs_level="on", obs_event_log=log, **RC)
        n = 64
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        events = [json.loads(l) for l in open(log)]
        dv = [e for e in events if e["kind"] == "delta"]
        assert len(dv) == 1
        rec = dv[0]
        assert rec["name"] == "A" and rec["gen"] == 1
        assert rec["patched"] == 1 and rec["delta_kind"] == "coo"
        assert "est_saved_flops" in rec and "rules" in rec
        assert rec["result_cache"]["patched"] == 1
        from matrel_tpu.obs import history
        s = history.summarize(events)
        assert s["ivm"]["registers"] == 1
        assert s["ivm"]["patched"] == 1
        text = history.render_summary(events)
        assert "ivm: 1 delta(s)" in text

    def test_no_delta_events_on_default_obs_off(self, mesh8, rng,
                                                tmp_path):
        log = str(tmp_path / "events.jsonl")
        os.environ.pop("MATREL_OBS_EVENT_LOG", None)
        sess = _sess(mesh8, obs_event_log=log, **RC)
        n = 32
        sess.register("A", sess.from_numpy(_int_adj(rng, n),
                                           integral=True))
        sess.run(sess.table("A").expr().row_sum())
        sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        assert not os.path.exists(log)

    def test_matmul_decisions_carry_delta_pricing(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        n = 96
        a = _int_adj(rng, n)
        f = rng.standard_normal((n, 16)).astype(np.float32)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.register("F", sess.from_numpy(f))
        sess.run(sess.table("A").expr().multiply(
            sess.table("F").expr()))
        sess.register_delta("A", ([1], [2], [1.0]), kind="coo")
        _key, ent = sess._result_cache.items_snapshot()[0]
        prog = sess._delta_plane._programs[ent.ivm_id]
        decs = executor_lib.plan_matmul_decisions(prog.plan)
        assert decs, "patch plan has no matmul decisions"
        for d in decs:
            assert d["delta_rule"] in delta_lib.DELTA_RULES
            assert isinstance(d["delta_est_saved_flops"],
                              (int, float))
        assert prog.plan.meta["ivm"]["est_saved_flops"] > 0

    def test_history_drift_check_exit_code(self, tmp_path,
                                           monkeypatch):
        # the --check gate: rc 0 with no flags, rc 1 when a seeded
        # rank-order flag fires (obs/drift.py audit())
        import argparse
        from matrel_tpu.obs import drift, history
        events = []
        args = argparse.Namespace(
            log=None, summary=False, last=None, drift=True,
            drift_table=str(tmp_path / "d.json"), no_save=False,
            check=True)
        monkeypatch.setattr(
            "matrel_tpu.obs.events.read_events",
            lambda path: events)
        monkeypatch.setenv("MATREL_OBS_EVENT_LOG",
                           str(tmp_path / "e.jsonl"))
        text, flags = drift.audit(events, persist=False)
        assert flags == []
        assert history.main(args) == 0
        monkeypatch.setattr(drift, "audit",
                            lambda *a, **k: ("boom", [{"class": "x"}]))
        assert history.main(args) == 1


# ---------------------------------------------------------------------------
# Default-config bit-identity
# ---------------------------------------------------------------------------


class TestBitIdentity:
    def test_no_delta_objects_without_register_delta(self, mesh8, rng,
                                                     monkeypatch):
        # poisoned init: rc-on traffic + rebinds must construct ZERO
        # delta-plane objects and produce no delta: prefixes
        def boom(self, *a, **k):
            raise AssertionError("MatrixDelta constructed on the "
                                 "default path")

        monkeypatch.setattr(delta_lib.MatrixDelta, "__post_init__",
                            boom)
        sess = _sess(mesh8, **RC)
        n = 48
        a = _int_adj(rng, n)
        sess.register("A", sess.from_numpy(a, integral=True))
        sess.run(sess.table("A").expr().row_sum())
        sess.run(sess.table("A").expr().row_sum())
        sess.register("A", sess.from_numpy(a, integral=True))  # rebind
        sess.run(sess.table("A").expr().row_sum())
        assert sess._delta_plane is None and sess._delta_gen == 0
        for k, ent in sess._result_cache.items_snapshot():
            assert not k.startswith("delta:")
            assert ent.delta_gen == 0 and ent.ivm_id is None

    def test_construction_counter_quiet_on_serve_traffic(self, mesh8,
                                                         rng):
        before = delta_lib._CONSTRUCTED["count"]
        sess = _sess(mesh8, **RC)
        X = BlockMatrix.from_numpy(
            rng.standard_normal((32, 8)).astype(np.float32),
            mesh=mesh8)
        outs = sess.run_many([X.expr().t().multiply(X.expr())
                              for _ in range(3)])
        assert len(outs) == 3
        assert delta_lib._CONSTRUCTED["count"] == before

    def test_config_validation(self):
        with pytest.raises(ValueError, match="delta_patch_mode"):
            MatrelConfig(delta_patch_mode="sometimes")
        with pytest.raises(ValueError, match="delta_rank_max"):
            MatrelConfig(delta_rank_max=0)
        assert MatrelConfig(delta_patch_mode="FORCE") \
            .delta_patch_mode == "force"
