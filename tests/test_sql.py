"""SQL entry-point tests: query strings over the catalog compile to the
same optimized plans as the DSL (SURVEY.md §2 'SQL entry point')."""

import numpy as np
import pytest

from matrel_tpu.session import MatrelSession
from matrel_tpu.sql import SqlError


@pytest.fixture()
def sess(mesh8, rng):
    s = MatrelSession(mesh=mesh8)
    a = rng.standard_normal((8, 6)).astype(np.float32)
    b = rng.standard_normal((6, 8)).astype(np.float32)
    s.register("A", s.from_numpy(a))
    s.register("B", s.from_numpy(b))
    return s, a, b


def test_select_multiply(sess):
    s, a, b = sess
    out = s.compute(s.sql("SELECT A * B FROM A, B")).to_numpy()
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)


def test_transpose_and_agg(sess):
    s, a, b = sess
    out = s.compute(s.sql("rowsum(transpose(A))")).to_numpy()
    np.testing.assert_allclose(out, a.T.sum(1, keepdims=True), rtol=1e-4,
                               atol=1e-4)


def test_trace_of_product(sess):
    s, a, b = sess
    got = s.compute(s.sql("trace(A * B)")).to_numpy()[0, 0]
    assert got == pytest.approx(np.trace(a @ b), rel=1e-3)


def test_scalar_and_elemwise(sess):
    s, a, b = sess
    out = s.compute(s.sql("elemmult(A, A) + 1.5")).to_numpy()
    np.testing.assert_allclose(out, a * a + 1.5, rtol=1e-4, atol=1e-4)
    out2 = s.compute(s.sql("2 * A")).to_numpy()
    np.testing.assert_allclose(out2, 2 * a, rtol=1e-5)


def test_select_predicate(sess):
    s, a, b = sess
    out = s.compute(s.sql("select(A, 'v > 0')")).to_numpy()
    np.testing.assert_allclose(out, np.where(a > 0, a, 0), rtol=1e-5)


def test_selectrows_with_arithmetic(sess):
    s, a, b = sess
    out = s.compute(s.sql("selectrows(A, 'i % 2 == 0')")).to_numpy()
    expect = a.copy()
    expect[1::2] = 0
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_joinindex(sess):
    s, a, b = sess
    s.register("C", s.from_numpy(a + 1))
    out = s.compute(s.sql("joinindex(A, C, 'x * y')")).to_numpy()
    np.testing.assert_allclose(out, a * (a + 1), rtol=1e-4, atol=1e-4)


def test_index_joins_structured_merge_keywords(sess):
    # round 4: joinindex/joinrows/joincols accept the structured merge
    # keywords (same set as joinvalue) — dtype-inference-friendly
    s, a, b = sess
    s.register("C", s.from_numpy(a + 1))
    out = s.compute(s.sql("joinindex(A, C, 'add')")).to_numpy()
    np.testing.assert_allclose(out, a + (a + 1), rtol=1e-4, atol=1e-4)
    n, m = a.shape
    out_r = s.compute(s.sql("joinrows(A, A, 'mul')")).to_numpy()
    want_r = (a[:, :, None] * a[:, None, :]).reshape(n, m * m)
    np.testing.assert_allclose(out_r, want_r, rtol=1e-4, atol=1e-4)
    out_c = s.compute(s.sql("joincols(A, A, 'left')")).to_numpy()
    want_c = np.broadcast_to(a[:, None, :], (n, n, m)).reshape(n * n, m)
    np.testing.assert_allclose(out_c, want_c, rtol=1e-4, atol=1e-4)


def test_unknown_table_raises(sess):
    s, _, _ = sess
    with pytest.raises(SqlError):
        s.sql("SELECT Zed * A")


def test_unsafe_predicate_rejected(sess):
    s, _, _ = sess
    with pytest.raises(SqlError):
        s.sql("select(A, '__import__(\"os\").system(\"true\")')")
    with pytest.raises(SqlError):
        s.sql("select(A, 'v.__class__')")


def test_solve_and_inverse(sess):
    s, a, b = sess
    # normal equations in SQL: solve(AᵀA, Aᵀb) over the 8x6 table A
    out = s.compute(
        s.sql("solve(multiply(transpose(A), A), multiply(transpose(A), transpose(B)))")
    ).to_numpy()
    oracle = np.linalg.solve(a.T @ a, a.T @ b.T)
    np.testing.assert_allclose(out, oracle, rtol=1e-2, atol=1e-3)
    gram_inv = s.compute(
        s.sql("inverse(multiply(transpose(A), A))")).to_numpy()
    np.testing.assert_allclose(gram_inv, np.linalg.inv(a.T @ a),
                               rtol=1e-2, atol=1e-3)


def test_norm_function(sess):
    s, a, b = sess
    out = s.compute(s.sql('norm(A)')).to_numpy()
    np.testing.assert_allclose(out[0, 0], np.linalg.norm(a), rtol=1e-4)
    out = s.compute(s.sql('norm(A, "l1")')).to_numpy()
    np.testing.assert_allclose(out[0, 0], np.abs(a).sum(), rtol=1e-4)


# -- round-2 grammar completion: every docstring grammar line tested ---------


def test_elemmul_dotstar_and_percent(sess):
    s, a, b = sess
    out = s.compute(s.sql("A .* A")).to_numpy()
    np.testing.assert_allclose(out, a * a, rtol=1e-5)
    out2 = s.compute(s.sql("A % A")).to_numpy()
    np.testing.assert_allclose(out2, a * a, rtol=1e-5)
    # .* inside a quoted predicate is NOT lexed: the string reaches the
    # predicate compiler untouched and is rejected there, not mangled
    with pytest.raises(SqlError):
        s.sql("select(A, 'v .* v')")
    with pytest.raises(SqlError, match="element-multiply"):
        s.sql("2 % A")


def test_elemwise_add_sub_div(sess):
    s, a, b = sess
    np.testing.assert_allclose(s.compute(s.sql("A + A")).to_numpy(),
                               a + a, rtol=1e-5)
    np.testing.assert_allclose(s.compute(s.sql("A - A")).to_numpy(),
                               np.zeros_like(a), atol=1e-6)
    d = s.compute(s.sql("A / (A + 10)")).to_numpy()
    np.testing.assert_allclose(d, a / (a + 10), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(s.compute(s.sql("A + 2")).to_numpy(),
                               a + 2, rtol=1e-5)


def test_from_validates_and_restricts(sess):
    s, a, b = sess
    # unknown table in FROM → clear error naming the catalog
    with pytest.raises(SqlError, match="unknown table.*FROM"):
        s.sql("SELECT A * B FROM A, C")
    # FROM restricts scope: B not listed → body may not use it
    with pytest.raises(SqlError, match="unknown table"):
        s.sql("SELECT A * B FROM A")
    # malformed name
    with pytest.raises(SqlError, match="bad table name"):
        s.sql("SELECT A FROM A B")
    # FROM with nothing after it
    with pytest.raises(SqlError, match="at least one table"):
        s.sql("SELECT A FROM ")


def test_where_clause(sess):
    s, a, b = sess
    out = s.compute(s.sql("SELECT A + 0 WHERE v > 0.5")).to_numpy()
    want = np.where(a + 0 > 0.5, a, 0)
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
    out2 = s.compute(
        s.sql("SELECT A * B FROM A, B WHERE v < 0")).to_numpy()
    ab = a @ b
    np.testing.assert_allclose(out2, np.where(ab < 0, ab, 0), rtol=1e-4,
                               atol=1e-4)
    with pytest.raises(SqlError, match="WHERE requires"):
        s.sql("SELECT A WHERE ")


def test_selectcols_and_selectblocks(sess):
    s, a, b = sess
    out = s.compute(s.sql("selectcols(A, 'j < 3')")).to_numpy()
    want = a.copy()
    want[:, 3:] = 0
    np.testing.assert_allclose(out, want, rtol=1e-5)
    blk = s.compute(s.sql("selectblocks(A, 'bi == bj', 4)")).to_numpy()
    bi = np.arange(8)[:, None] // 4
    bj = np.arange(6)[None, :] // 4
    np.testing.assert_allclose(blk, np.where(bi == bj, a, 0), rtol=1e-5)


def test_joinrows_and_joincols(sess):
    s, a, b = sess
    out = s.compute(s.sql("joinrows(A, A, 'x + y')")).to_numpy()
    want = (a[:, :, None] + a[:, None, :]).reshape(8, 36)
    np.testing.assert_allclose(out, want, rtol=1e-5)
    out2 = s.compute(s.sql("joincols(A, A, 'x - y')")).to_numpy()
    want2 = (a[:, None, :] - a[None, :, :]).reshape(64, 6)
    np.testing.assert_allclose(out2, want2, rtol=1e-5, atol=1e-6)


def test_joinvalue_structured_streams(sess):
    s, a, b = sess
    got = s.compute(
        s.sql("rowsum(joinvalue(A, B, 'mul', 'lt'))")).to_numpy()[:, 0]
    va = a.T.reshape(-1)
    vb = b.T.reshape(-1)
    want = np.where(va[:, None] < vb[None, :],
                    va[:, None] * vb[None, :], 0.0).sum(axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_joinvalue_expression_strings(sess):
    s, a, b = sess
    got = s.compute(
        s.sql("joinvalue(A, B, 'x + 2 * y', 'x > y and y > 0')")
    ).to_numpy()
    va = a.T.reshape(-1)
    vb = b.T.reshape(-1)
    want = np.where((va[:, None] > vb[None, :]) & (vb[None, :] > 0),
                    va[:, None] + 2 * vb[None, :], 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_power_vec_and_remaining_aggs(sess):
    s, a, b = sess
    np.testing.assert_allclose(s.compute(s.sql("power(A, 2)")).to_numpy(),
                               a ** 2, rtol=1e-4)
    v = s.compute(s.sql("vec(A)")).to_numpy()
    np.testing.assert_allclose(v, a.T.reshape(-1, 1), rtol=1e-6)
    checks = {
        "rowmax(A)": a.max(1, keepdims=True),
        "rowmin(A)": a.min(1, keepdims=True),
        "colmax(A)": a.max(0, keepdims=True),
        "colmin(A)": a.min(0, keepdims=True),
        "rowcount(A)": (a != 0).sum(1, keepdims=True).astype(np.float32),
        "colcount(A)": (a != 0).sum(0, keepdims=True).astype(np.float32),
        "rowavg(A)": a.mean(1, keepdims=True),
        "colavg(A)": a.mean(0, keepdims=True),
        "colsum(A)": a.sum(0, keepdims=True),
        "sum(A)": a.sum().reshape(1, 1),
    }
    for q, want in checks.items():
        got = s.compute(s.sql(q)).to_numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4,
                                   err_msg=q)


def test_syntax_errors_become_sql_errors(sess):
    s, a, b = sess
    for bad in ("A **", "A .* ", "((A)", "select(A, 'v >')",
                "joinvalue(A, B, 'x +', 'lt')", "A @", "FROM A"):
        with pytest.raises(SqlError):
            s.sql(bad)


def test_trailing_semicolons_and_case(sess):
    s, a, b = sess
    out = s.compute(s.sql("SeLeCt rowsum(A) FROM A;;")).to_numpy()
    np.testing.assert_allclose(out, a.sum(1, keepdims=True), rtol=1e-4)


def test_rankone(sess):
    s, a, b = sess
    u = np.random.default_rng(1).standard_normal((8, 1)).astype(np.float32)
    v = np.random.default_rng(2).standard_normal((6, 1)).astype(np.float32)
    s.register("U", s.from_numpy(u))
    s.register("V", s.from_numpy(v))
    out = s.compute(s.sql("rankone(A, U, V)")).to_numpy()
    np.testing.assert_allclose(out, a + u @ v.T, rtol=1e-5, atol=1e-5)
    # pushed through a multiply: still correct end-to-end
    out2 = s.compute(s.sql("rankone(A, U, V) * B")).to_numpy()
    np.testing.assert_allclose(out2, (a + u @ v.T) @ b, rtol=1e-4,
                               atol=1e-4)


def test_explain_sql(sess):
    s, a, b = sess
    txt = s.explain_sql("SELECT rowsum(A * B) FROM A, B")
    # aggregation pushdown: in the OPTIMIZED section the plan ROOT is
    # the matmul with the rowSum pushed beneath it (rowSum(A·B) →
    # A·rowSum(B)); the logical section above still shows agg-on-top
    opt = txt.split("== Optimized plan ==")[1]
    first, second = [ln for ln in opt.splitlines() if ln.strip()][:2]
    assert first.startswith("matmul")
    assert "agg sum/row" in opt and not second.startswith("agg")
    txt2 = s.explain_sql("rowsum(joinvalue(A, B, 'mul', 'lt'))")
    assert "join_value merge=mul pred=lt" in txt2
    txt3 = s.explain_sql("joinrows(A, A, 'x + y')")
    assert "join_rows" in txt3


def test_join_and_block_args_are_injection_safe(sess):
    s, a, b = sess
    for bad in ('joinvalue(A, B, \'__import__("os").system("x")\', "lt")',
                "joinrows(A, A, 'open(\"/etc/passwd\")')",
                "selectblocks(A, '__class__', 4)",
                "joinvalue(A, B, 'x + y', 'exec(\"1\")')"):
        with pytest.raises(SqlError):
            s.sql(bad)


class TestGlobalAndDiagAggregates:
    """Round-3 grammar closure (VERDICT r2 #2): every executor agg
    kind×axis is reachable from SQL — global max/min/count/avg and the
    diag family beyond trace."""

    def test_global_aggregates(self, sess):
        s, a, b = sess
        cases = {
            "max(A)": a.max(),
            "min(A)": a.min(),
            "count(A)": float(np.count_nonzero(a)),
            "avg(A)": a.sum() / np.count_nonzero(a),
        }
        for q, want in cases.items():
            got = s.compute(s.sql(q)).to_numpy()[0, 0]
            assert got == pytest.approx(want, rel=1e-3), q

    def test_diag_aggregates(self, sess):
        s, a, b = sess
        s.register("P", s.from_numpy(a @ b))     # square 8x8
        d = (a @ b).diagonal()
        cases = {
            "diagsum(P)": d.sum(),
            "diagmax(P)": d.max(),
            "diagmin(P)": d.min(),
            "diagcount(P)": float(np.count_nonzero(d)),
            "diagavg(P)": d.sum() / np.count_nonzero(d),
        }
        for q, want in cases.items():
            got = s.compute(s.sql(q)).to_numpy()[0, 0]
            assert got == pytest.approx(want, rel=1e-3), q

    def test_diagsum_equals_trace(self, sess):
        s, a, b = sess
        t1 = s.compute(s.sql("trace(A * B)")).to_numpy()[0, 0]
        t2 = s.compute(s.sql("diagsum(A * B)")).to_numpy()[0, 0]
        assert t1 == pytest.approx(t2, rel=1e-5)

    def test_global_agg_composes_with_expressions(self, sess):
        s, a, b = sess
        got = s.compute(s.sql("max(A * B)")).to_numpy()[0, 0]
        assert got == pytest.approx((a @ b).max(), rel=1e-3)


class TestElemmulLexerDigitIdentifiers:
    """ADVICE r2 low: '.*' after an identifier ending in a digit is the
    elemmul token, not a float literal."""

    def test_digit_suffixed_tables(self, mesh8, rng):
        s = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        c = rng.standard_normal((8, 8)).astype(np.float32)
        s.register("t1", s.from_numpy(a))
        s.register("t2", s.from_numpy(c))
        out = s.compute(s.sql("SELECT t1.*t2")).to_numpy()
        np.testing.assert_allclose(out, a * c, rtol=1e-4, atol=1e-4)

    def test_float_literal_dot_star_still_scalar(self, sess):
        s, a, b = sess
        out = s.compute(s.sql("SELECT 2.*A")).to_numpy()
        np.testing.assert_allclose(out, 2.0 * a, rtol=1e-5)


def test_elemmin_elemmax(sess):
    # round-3 grammar line: elementwise min/max reachable from SQL
    s, a, b = sess
    s.register("C", s.from_numpy(a + 0.5))
    got_min = s.compute(s.sql("elemmin(A, C)")).to_numpy()
    got_max = s.compute(s.sql("elemmax(A, C)")).to_numpy()
    np.testing.assert_allclose(got_min, np.minimum(a, a + 0.5), rtol=1e-5)
    np.testing.assert_allclose(got_max, np.maximum(a, a + 0.5), rtol=1e-5)
