"""SQL entry-point tests: query strings over the catalog compile to the
same optimized plans as the DSL (SURVEY.md §2 'SQL entry point')."""

import numpy as np
import pytest

from matrel_tpu.session import MatrelSession
from matrel_tpu.sql import SqlError


@pytest.fixture()
def sess(mesh8, rng):
    s = MatrelSession(mesh=mesh8)
    a = rng.standard_normal((8, 6)).astype(np.float32)
    b = rng.standard_normal((6, 8)).astype(np.float32)
    s.register("A", s.from_numpy(a))
    s.register("B", s.from_numpy(b))
    return s, a, b


def test_select_multiply(sess):
    s, a, b = sess
    out = s.compute(s.sql("SELECT A * B FROM A, B")).to_numpy()
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)


def test_transpose_and_agg(sess):
    s, a, b = sess
    out = s.compute(s.sql("rowsum(transpose(A))")).to_numpy()
    np.testing.assert_allclose(out, a.T.sum(1, keepdims=True), rtol=1e-4,
                               atol=1e-4)


def test_trace_of_product(sess):
    s, a, b = sess
    got = s.compute(s.sql("trace(A * B)")).to_numpy()[0, 0]
    assert got == pytest.approx(np.trace(a @ b), rel=1e-3)


def test_scalar_and_elemwise(sess):
    s, a, b = sess
    out = s.compute(s.sql("elemmult(A, A) + 1.5")).to_numpy()
    np.testing.assert_allclose(out, a * a + 1.5, rtol=1e-4, atol=1e-4)
    out2 = s.compute(s.sql("2 * A")).to_numpy()
    np.testing.assert_allclose(out2, 2 * a, rtol=1e-5)


def test_select_predicate(sess):
    s, a, b = sess
    out = s.compute(s.sql("select(A, 'v > 0')")).to_numpy()
    np.testing.assert_allclose(out, np.where(a > 0, a, 0), rtol=1e-5)


def test_selectrows_with_arithmetic(sess):
    s, a, b = sess
    out = s.compute(s.sql("selectrows(A, 'i % 2 == 0')")).to_numpy()
    expect = a.copy()
    expect[1::2] = 0
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_joinindex(sess):
    s, a, b = sess
    s.register("C", s.from_numpy(a + 1))
    out = s.compute(s.sql("joinindex(A, C, 'x * y')")).to_numpy()
    np.testing.assert_allclose(out, a * (a + 1), rtol=1e-4, atol=1e-4)


def test_unknown_table_raises(sess):
    s, _, _ = sess
    with pytest.raises(SqlError):
        s.sql("SELECT Zed * A")


def test_unsafe_predicate_rejected(sess):
    s, _, _ = sess
    with pytest.raises(SqlError):
        s.sql("select(A, '__import__(\"os\").system(\"true\")')")
    with pytest.raises(SqlError):
        s.sql("select(A, 'v.__class__')")


def test_solve_and_inverse(sess):
    s, a, b = sess
    # normal equations in SQL: solve(AᵀA, Aᵀb) over the 8x6 table A
    out = s.compute(
        s.sql("solve(multiply(transpose(A), A), multiply(transpose(A), transpose(B)))")
    ).to_numpy()
    oracle = np.linalg.solve(a.T @ a, a.T @ b.T)
    np.testing.assert_allclose(out, oracle, rtol=1e-2, atol=1e-3)
    gram_inv = s.compute(
        s.sql("inverse(multiply(transpose(A), A))")).to_numpy()
    np.testing.assert_allclose(gram_inv, np.linalg.inv(a.T @ a),
                               rtol=1e-2, atol=1e-3)


def test_norm_function(sess):
    s, a, b = sess
    out = s.compute(s.sql('norm(A)')).to_numpy()
    np.testing.assert_allclose(out[0, 0], np.linalg.norm(a), rtol=1e-4)
    out = s.compute(s.sql('norm(A, "l1")')).to_numpy()
    np.testing.assert_allclose(out[0, 0], np.abs(a).sum(), rtol=1e-4)
