"""Multi-query optimization at admission (serve/mqo.py + session
integration): cross-query CSE — shared interiors of one run_many batch
compute ONCE (dispatch-counted) and feed consumers as cse-stamped
leaves the planner prices (cse_operands) — and plan-template reuse —
structurally-identical-modulo-leaves queries rebind into the cached
program with ZERO optimize/trace (event-verified), isolated by SLA
prefix and by leaf identity pattern. MV116 proves substitution
transparent (static stamps + dynamic substituted ≡ unshared), and the
default config constructs NOTHING from the mqo module (poisoned init)."""

import numpy as np
import pytest
import scipy.sparse

from matrel_tpu import executor as executor_lib
from matrel_tpu.analysis import cse_pass
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.serve import mqo as mqo_lib
from matrel_tpu.session import MatrelSession

CSE = dict(cse_enable=True)


def _mat(rng, n, m, mesh):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh)


def _sess(mesh, **cfg):
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


def _gram_batch(X, k=4):
    """k structurally distinct consumers over ONE shared Gram interior
    (t(X) @ X is a matmul — a fused-region boundary, so it is a hoist
    candidate; the scalar epilogues stay with their consumers)."""
    g = X.expr().t().multiply(X.expr())
    return [g.multiply_scalar(1.0 + i) for i in range(k)]


def _gram_oracles(X, k=4):
    xn = X.to_numpy()
    g = xn.T @ xn
    return [g * (1.0 + i) for i in range(k)]


def _dispatch_spy(monkeypatch):
    """Count matmul dispatches per executed plan — the compute-once
    proof reads total matmuls across every program the batch ran."""
    counts = []
    orig = MatrelSession._arbitrated_run

    def spy(self, plan, bindings=None):
        counts.append(sum(
            len(d) for d in executor_lib.multiplan_root_decisions(plan)))
        return orig(self, plan, bindings=bindings)

    monkeypatch.setattr(MatrelSession, "_arbitrated_run", spy)
    return counts


def _find_cse_leaf(e):
    if e.attrs.get("cse") is not None:
        return e
    for c in e.children:
        hit = _find_cse_leaf(c)
        if hit is not None:
            return hit
    return None


class TestCrossQueryCSE:
    def test_shared_interior_computes_once_dispatch_counted(
            self, mesh8, rng, monkeypatch):
        X = _mat(rng, 48, 16, mesh8)
        counts = _dispatch_spy(monkeypatch)
        off = _sess(mesh8).run_many(_gram_batch(X))
        matmuls_off = sum(counts)
        counts.clear()
        sess = _sess(mesh8, **CSE)
        on = sess.run_many(_gram_batch(X))
        matmuls_on = sum(counts)
        # unshared: the Gram matmul dispatches once PER consumer;
        # hoisted: once total (the compute-once micro-batch), and the
        # consumers' programs hold zero matmuls
        assert matmuls_off == 4
        assert matmuls_on == 1
        info = sess.mqo_info()
        assert info["cse_hoisted"] == 1
        assert info["cse_batches"] == 1
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a.to_numpy(), b.to_numpy())

    def test_batch_answers_match_oracle(self, mesh8, rng):
        sess = _sess(mesh8, **CSE)
        X = _mat(rng, 64, 24, mesh8)
        outs = sess.run_many(_gram_batch(X, k=5))
        for out, want in zip(outs, _gram_oracles(X, k=5)):
            np.testing.assert_allclose(out.to_numpy(), want,
                                       rtol=3e-4, atol=3e-4)

    def test_consumer_plan_carries_cse_stamp_and_pricing(
            self, mesh8, rng):
        sess = _sess(mesh8, **CSE)
        X = _mat(rng, 48, 16, mesh8)
        Bs = [_mat(rng, 16, 16, mesh8) for _ in range(3)]
        g = X.expr().t().multiply(X.expr())
        sess.run_many([g.multiply(B.expr()) for B in Bs])
        assert sess.mqo_info()["cse_hoisted"] == 1
        # the consumers' substituted trees (MV116's ring) feed on a
        # cse-stamped leaf carrying what the hoist recorded
        _orig, sub = sess._mqo.recent[-1]
        leaf = _find_cse_leaf(sub)
        assert leaf is not None
        stamp = leaf.attrs["cse"]
        assert stamp["uses"] == 3
        assert len(stamp["key_hash"]) == 16
        assert stamp["layout"] in ("2d", "row", "col", "rep", "other")
        # and the consumer plan's matmul decisions price the hoist-fed
        # operand (the rc_operands analogue)
        plan = list(sess._plan_cache.values())[-1]
        decs = executor_lib.plan_matmul_decisions(plan)
        assert any(d.get("cse_operands") == [True, False]
                   for d in decs)

    def test_matmul_free_share_is_not_hoisted(self, mesh8, rng):
        # a shared transpose-of-a-leaf is not worth its own dispatch:
        # candidates must carry a matmul under the boundary
        sess = _sess(mesh8, **CSE)
        X = _mat(rng, 32, 32, mesh8)
        t = X.expr().t()
        outs = sess.run_many([t.multiply_scalar(2.0),
                              t.multiply_scalar(3.0)])
        assert sess.mqo_info()["cse_hoisted"] == 0
        xn = X.to_numpy()
        np.testing.assert_allclose(outs[0].to_numpy(), xn.T * 2.0,
                                   rtol=1e-6, atol=1e-6)

    def test_rebind_invalidates_hoisted_interior(self, mesh8, rng):
        # with the result cache on, the hoisted interior inserts under
        # its structural key with the source's dep ids — a catalog
        # rebind must cascade, never serve the stale Gram
        sess = _sess(mesh8, **CSE, result_cache_max_bytes=64 << 20)
        A = _mat(rng, 48, 16, mesh8)
        B = _mat(rng, 48, 16, mesh8)
        sess.register("src", A)
        src = sess.table("src")
        batch = _gram_batch(src, k=3)
        sess.run_many(batch)
        assert sess.mqo_info()["cse_hoisted"] == 1
        sess.register("src", B)
        src2 = sess.table("src")
        outs = sess.run_many(_gram_batch(src2, k=3))
        for out, want in zip(outs, _gram_oracles(B, k=3)):
            np.testing.assert_allclose(out.to_numpy(), want,
                                       rtol=3e-4, atol=3e-4)


class TestPlanTemplates:
    def test_template_hit_pays_zero_optimize_event_verified(
            self, mesh8, rng, tmp_path):
        from matrel_tpu.obs.events import read_events
        log = str(tmp_path / "events.jsonl")
        sess = _sess(mesh8, **CSE, obs_level="on", obs_event_log=log)
        A = _mat(rng, 48, 16, mesh8)
        B = _mat(rng, 48, 16, mesh8)
        sess.run(A.expr().t().multiply(A.expr()))
        out = sess.run(B.expr().t().multiply(B.expr()))
        bn = B.to_numpy()
        np.testing.assert_allclose(out.to_numpy(), bn.T @ bn,
                                   rtol=3e-4, atol=3e-4)
        info = sess.mqo_info()
        assert info["template_inserts"] == 1
        assert info["template_hits"] == 1
        q = [e for e in read_events(log) if e.get("kind") == "query"]
        assert [e["cache"] for e in q] == ["miss", "template_hit"]
        # the template contract: steady state pays ZERO optimize/trace
        # this query — the event is the proof
        assert q[1]["optimize_ms"] == 0.0
        assert q[1]["trace_ms"] == 0.0
        assert q[0]["optimize_ms"] > 0.0

    def test_multiplan_template_rebinds_whole_batch(self, mesh8, rng):
        sess = _sess(mesh8, **CSE)
        A = _mat(rng, 48, 16, mesh8)
        B = _mat(rng, 48, 16, mesh8)
        sess.run_many(_gram_batch(A, k=3))
        outs = sess.run_many(_gram_batch(B, k=3))
        info = sess.mqo_info()
        assert info["template_hits"] >= 3
        for out, want in zip(outs, _gram_oracles(B, k=3)):
            np.testing.assert_allclose(out.to_numpy(), want,
                                       rtol=3e-4, atol=3e-4)

    def test_identity_pattern_never_aliases(self, mesh8, rng):
        # t(A) @ A dedupes its two leaves into one Gram operand;
        # t(B) @ C cannot — the abstract key's identity classes
        # (#0/#0 vs #0/#1) must keep them apart
        sess = _sess(mesh8, **CSE)
        A = _mat(rng, 32, 32, mesh8)
        B = _mat(rng, 32, 32, mesh8)
        C = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().t().multiply(A.expr()))
        out = sess.run(B.expr().t().multiply(C.expr()))
        assert sess.mqo_info()["template_hits"] == 0
        np.testing.assert_allclose(
            out.to_numpy(), B.to_numpy().T @ C.to_numpy(),
            rtol=3e-4, atol=3e-4)
        # the matching pattern DOES share: a fresh Gram rebinds
        D = _mat(rng, 32, 32, mesh8)
        out2 = sess.run(D.expr().t().multiply(D.expr()))
        assert sess.mqo_info()["template_hits"] == 1
        np.testing.assert_allclose(
            out2.to_numpy(), D.to_numpy().T @ D.to_numpy(),
            rtol=3e-4, atol=3e-4)

    def test_sla_prefix_isolates_templates(self, mesh8, rng):
        sess = _sess(mesh8, **CSE)
        A = _mat(rng, 48, 16, mesh8)
        B = _mat(rng, 48, 16, mesh8)
        sess.run(A.expr().t().multiply(A.expr()))
        # same structure, different SLA: the prec: prefix must miss
        sess.run(B.expr().t().multiply(B.expr()), precision="high")
        assert sess.mqo_info()["template_hits"] == 0

    def test_sparse_leaves_keep_identity_tokens(self, mesh8, rng):
        # sparse payloads are trace CONSTANTS in the compiled program —
        # a different sparse matrix must never rebind into the template
        sess = _sess(mesh8, **CSE)
        sp1 = scipy.sparse.random(64, 64, density=0.3, format="csr",
                                  random_state=1, dtype=np.float32)
        sp2 = scipy.sparse.random(64, 64, density=0.3, format="csr",
                                  random_state=2, dtype=np.float32)
        S1 = BlockSparseMatrix.from_scipy(sp1, block_size=16,
                                          mesh=mesh8)
        S2 = BlockSparseMatrix.from_scipy(sp2, block_size=16,
                                          mesh=mesh8)
        D = _mat(rng, 64, 8, mesh8)
        o1 = sess.run(S1.expr().multiply(D.expr()))
        o2 = sess.run(S2.expr().multiply(D.expr()))
        assert sess.mqo_info()["template_hits"] == 0
        dn = D.to_numpy()
        np.testing.assert_allclose(o1.to_numpy(), sp1.toarray() @ dn,
                                   rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(o2.to_numpy(), sp2.toarray() @ dn,
                                   rtol=3e-4, atol=3e-4)


class TestMV116:
    def test_dynamic_verify_clean_over_traffic(self, mesh8, rng):
        sess = _sess(mesh8, **CSE, result_cache_max_bytes=64 << 20)
        for _ in range(3):
            X = _mat(rng, 48, 16, mesh8)
            sess.run_many(_gram_batch(X, k=3))
        assert len(sess._mqo.recent) > 0
        assert cse_pass.verify_cse_executions(sess) == []

    def test_static_stamps_clean_then_tampered(self, mesh8, rng):
        sess = _sess(mesh8, **CSE)
        X = _mat(rng, 48, 16, mesh8)
        sess.run_many(_gram_batch(X, k=3))
        _orig, sub = sess._mqo.recent[-1]
        assert list(cse_pass.check_cse_stamps(
            sub, mesh8, sess.config)) == []
        # a stamp whose dtype no longer agrees with the leaf's matrix
        # is a mispriced plan — warning severity, the MV107 class
        leaf = _find_cse_leaf(sub)
        bad = leaf.with_attrs(cse={**leaf.attrs["cse"],
                                   "dtype": "float64"})
        diags = list(cse_pass.check_cse_stamps(bad, mesh8,
                                               sess.config))
        assert len(diags) == 1
        assert diags[0].code == "MV116"
        assert diags[0].severity == "warning"

    def test_session_verify_includes_cse_pass(self, mesh8, rng):
        sess = _sess(mesh8, **CSE)
        X = _mat(rng, 48, 16, mesh8)
        sess.run_many(_gram_batch(X, k=3))
        _orig, sub = sess._mqo.recent[-1]
        assert sess.verify(sub) == []


class TestZeroOverheadDefault:
    def test_default_config_constructs_nothing(self, mesh8, rng):
        # the poisoned-init proof: cse_enable off (the default) must
        # never touch serve/mqo.py — no state, no hoist, no template
        before = mqo_lib._CONSTRUCTED["count"]
        sess = _sess(mesh8)
        X = _mat(rng, 48, 16, mesh8)
        outs = sess.run_many(_gram_batch(X, k=4))
        sess.run(X.expr().t().multiply(X.expr()))
        assert mqo_lib._CONSTRUCTED["count"] == before
        assert sess._mqo is None
        assert sess.mqo_info() == {
            "templates": 0, "template_hits": 0, "template_inserts": 0,
            "cse_hoisted": 0, "cse_batches": 0}
        for out, want in zip(outs, _gram_oracles(X, k=4)):
            np.testing.assert_allclose(out.to_numpy(), want,
                                       rtol=3e-4, atol=3e-4)

    def test_default_is_off(self):
        assert MatrelConfig().cse_enable is False
