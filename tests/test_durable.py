"""Durable fleet (docs/DURABILITY.md): the HBM -> host -> disk result-
cache spill hierarchy, warm restarts across a process-equivalent
session boundary, the corruption discipline (typed SnapshotCorruption
handled as a miss, corrupt snapshots cold-start), the zero-object
default, and the MV117 spill-provenance pass.

The kill-and-restore battery with a REAL process boundary lives in
``tools/soak.py --battery durable``; these are the deterministic unit
tiers under it.
"""

import logging
import os
import types

import numpy as np
import pytest

from matrel_tpu.analysis import spill_pass
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E
from matrel_tpu.resilience.errors import (CheckpointCorruption,
                                          SnapshotCorruption)
from matrel_tpu.serve import fleet as fleet_lib
from matrel_tpu.serve import mqo as mqo_lib
from matrel_tpu.serve import result_cache as rc_lib
from matrel_tpu.serve import spill as spill_lib
from matrel_tpu.session import MatrelSession

N = 64
ENTRY = N * N * 4               # one 64x64 f32 gram result's device bytes


def _spill_cfg(tmp_path, **over):
    """A config whose HBM budget holds ~1.5 entries, so the second
    insert demotes the first — the hierarchy exercises on two
    queries."""
    cfg = dict(spill_enable=True,
               result_cache_max_bytes=int(1.5 * ENTRY),
               result_cache_max_entries=8,
               spill_host_max_bytes=8 * ENTRY,
               spill_disk_hits=0,
               state_dir=str(tmp_path))
    cfg.update(over)
    return MatrelConfig(**cfg)


def _register(sess, rng, names, integral=False):
    """name -> (BlockMatrix, numpy gram oracle) for registered mats."""
    out = {}
    for nm in names:
        if integral:
            arr = rng.integers(-4, 5, size=(N, N)).astype(np.float32)
        else:
            arr = rng.standard_normal((N, N)).astype(np.float32)
        m = sess.from_numpy(arr)
        sess.register(nm, m)
        out[nm] = (m, arr.T @ arr)
    return out


def _gram(m):
    return m.expr().t().multiply(m.expr())


def _check(sess, mats, name, **tol):
    got = np.asarray(sess.run(_gram(mats[name][0])).data)
    np.testing.assert_allclose(got, mats[name][1],
                               **(tol or dict(rtol=1e-5, atol=1e-4)))


# ---------------------------------------------------------------------------
# Satellite 1 — result_nbytes must never silently size an entry as 0
# ---------------------------------------------------------------------------


class TestResultNbytes:

    def test_foreign_array_falls_back_to_shape_estimate(self, caplog):
        rc_lib._NBYTES_WARNED[0] = False
        bm = types.SimpleNamespace(data=object(), shape=(64, 16))
        with caplog.at_level(logging.WARNING, "matrel_tpu.serve"):
            assert rc_lib.result_nbytes(bm) == 64 * 16 * 4
        assert any("result_nbytes" in r.message for r in caplog.records)

    def test_warns_once_per_process(self, caplog):
        rc_lib._NBYTES_WARNED[0] = False
        bm = types.SimpleNamespace(data=object(), shape=(8, 8))
        with caplog.at_level(logging.WARNING, "matrel_tpu.serve"):
            rc_lib.result_nbytes(bm)
            caplog.clear()
            assert rc_lib.result_nbytes(bm) == 8 * 8 * 4
        assert not any("result_nbytes" in r.message
                       for r in caplog.records)

    def test_dtype_survives_when_only_shape_is_missing(self):
        rc_lib._NBYTES_WARNED[0] = True      # silence; latch unit above
        data = types.SimpleNamespace(dtype=np.dtype("float64"))
        bm = types.SimpleNamespace(data=data, shape=(8, 8))
        assert rc_lib.result_nbytes(bm) == 8 * 8 * 8

    def test_real_blockmatrix_uses_padded_array(self, mesh8, rng):
        arr = rng.standard_normal((N, N)).astype(np.float32)
        bm = BlockMatrix.from_numpy(arr, mesh=mesh8)
        assert rc_lib.result_nbytes(bm) == int(
            np.prod(bm.data.shape)) * 4

    def test_not_a_blockmatrix_at_all_is_zero(self):
        rc_lib._NBYTES_WARNED[0] = True
        bm = types.SimpleNamespace(data=object(), shape=None)
        assert rc_lib.result_nbytes(bm) == 0


# ---------------------------------------------------------------------------
# Tentpole — tier round-trips, demotion order, the expected-reuse gate
# ---------------------------------------------------------------------------


class TestSpillTiers:

    def test_host_round_trip_recomputes_nothing_wrong(
            self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(tmp_path))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")          # evicts a -> host tier
        sp = sess.result_cache_info()["spill"]
        assert sp["demoted_host"] >= 1 and sp["host_entries"] >= 1
        _check(sess, mats, "a")          # promote, not recompute
        sp = sess.result_cache_info()["spill"]
        assert sp["promoted"] >= 1

    def test_disk_round_trip_writes_and_thaws_artifact(
            self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(
            tmp_path, spill_host_max_bytes=1))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")          # a: HBM -> host -> ages to disk
        sp = sess.result_cache_info()["spill"]
        assert sp["demoted_disk"] == 1 and sp["disk_entries"] == 1
        files = os.listdir(os.path.join(str(tmp_path), "spill"))
        assert [f for f in files if f.endswith(".npy")]
        _check(sess, mats, "a")          # disk_read + h2d thaw
        sp = sess.result_cache_info()["spill"]
        assert sp["promoted"] == 1 and sp["corrupt"] == 0
        # re-inserting a evicted b, which cascaded down to disk in
        # a's old slot — the hierarchy stays full, nothing recomputes
        assert sp["demoted_disk"] == 2 and sp["disk_entries"] == 1

    def test_lru_pressure_ages_oldest_entry_deepest(
            self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(
            tmp_path, spill_host_max_bytes=int(1.5 * ENTRY)))
        events = []
        sess._spill.emit = events.append
        mats = _register(sess, rng, ["a", "b", "c"])
        for nm in ("a", "b", "c"):
            _check(sess, mats, nm)
        # a was evicted first, so host pressure aged it to disk; b
        # stayed host-resident
        sp = sess.result_cache_info()["spill"]
        assert sp["disk_entries"] == 1 and sp["host_entries"] == 1
        # a — evicted first — is the one that went deepest: its
        # repeat promotes from DISK (b's would have come from host)
        _check(sess, mats, "a")
        _check(sess, mats, "b")
        tiers = [e["tier"] for e in events if e["op"] == "promote"]
        assert len(tiers) == 2 and tiers[0] == "disk"
        for e in events:
            for leg in e["legs"]:
                assert leg["leg"] in ("d2h", "h2d", "disk_write",
                                      "disk_read")
                assert leg["bytes"] > 0 and leg["ms"] >= 0

    def test_expected_reuse_gate_drops_cold_entries(
            self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(
            tmp_path, spill_host_max_bytes=1, spill_disk_hits=5))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")          # a evicted cold: hits 0 < 5
        sp = sess.result_cache_info()["spill"]
        assert sp["dropped"] >= 1 and sp["disk_entries"] == 0
        assert not os.path.exists(os.path.join(str(tmp_path), "spill"))
        _check(sess, mats, "a")          # recompute stays correct
        assert sess.result_cache_info()["spill"]["promoted"] == 0

    def test_no_state_dir_means_host_only_tiering(
            self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(
            tmp_path, state_dir="", spill_host_max_bytes=1))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")
        sp = sess.result_cache_info()["spill"]
        assert sp["disk_entries"] == 0 and sp["dropped"] >= 1
        with pytest.raises(ValueError):
            sess.save_state()            # nowhere durable to write


# ---------------------------------------------------------------------------
# Tentpole — rebind invalidation cascades into every lower tier
# ---------------------------------------------------------------------------


class TestInvalidation:

    def test_rebind_kills_host_tier_entries(self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(tmp_path))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")          # a's gram now host-resident
        assert sess.result_cache_info()["spill"]["host_entries"] == 1
        arr2 = rng.standard_normal((N, N)).astype(np.float32)
        sess.register("a", sess.from_numpy(arr2))
        assert sess.result_cache_info()["spill"]["host_entries"] == 0

    def test_rebind_kills_disk_tier_and_unlinks_artifact(
            self, mesh8, rng, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(
            tmp_path, spill_host_max_bytes=1))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")
        spill_dir = os.path.join(str(tmp_path), "spill")
        assert len(os.listdir(spill_dir)) == 1
        sess.register("a", sess.from_numpy(
            rng.standard_normal((N, N)).astype(np.float32)))
        assert sess.result_cache_info()["spill"]["disk_entries"] == 0
        assert os.listdir(spill_dir) == []

    def test_rebind_kills_restored_entries_by_name(
            self, mesh8, rng, tmp_path):
        cfg = _spill_cfg(tmp_path, result_cache_max_bytes=64 << 20)
        sess1 = MatrelSession(mesh=mesh8, config=cfg)
        mats = _register(sess1, rng, ["a", "b"])
        _check(sess1, mats, "a")
        _check(sess1, mats, "b")
        sess1.save_state()
        sess2 = MatrelSession(mesh=mesh8, config=cfg)
        assert sess2.restore()["restored"]
        assert sess2.result_cache_info()["spill"][
            "restored_entries"] == 2
        arr2 = rng.standard_normal((N, N)).astype(np.float32)
        sess2.register("a", sess2.from_numpy(arr2))
        assert sess2.result_cache_info()["spill"][
            "restored_entries"] == 1
        # the rebound name recomputes against the NEW binding...
        got = np.asarray(sess2.run(_gram(sess2.catalog["a"])).data)
        np.testing.assert_allclose(got, arr2.T @ arr2,
                                   rtol=1e-5, atol=1e-4)
        # ...while the untouched name still thaws from the snapshot
        got = np.asarray(sess2.run(_gram(sess2.catalog["b"])).data)
        np.testing.assert_allclose(got, mats["b"][1],
                                   rtol=1e-5, atol=1e-4)
        assert sess2.result_cache_info()["spill"][
            "thawed_restored"] == 1


# ---------------------------------------------------------------------------
# Structural zero — the default config constructs NO spill objects
# ---------------------------------------------------------------------------


class TestDefaultZeroObjects:

    def test_default_config_never_constructs_spill(
            self, mesh8, monkeypatch):
        def _boom(self, session):
            raise AssertionError(
                "SpillManager constructed under a spill-off config")
        monkeypatch.setattr(spill_lib.SpillManager, "__init__", _boom)
        base = spill_lib._CONSTRUCTED["count"]
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig())
        assert sess._spill is None
        cache_only = MatrelSession(mesh=mesh8, config=MatrelConfig(
            result_cache_max_bytes=64 << 20))
        assert cache_only._spill is None
        assert "spill" not in cache_only.result_cache_info()
        assert spill_lib._CONSTRUCTED["count"] == base


# ---------------------------------------------------------------------------
# Tentpole + satellites 2/3 — save_state / restore and corruption
# ---------------------------------------------------------------------------


class TestSaveRestore:

    def test_warm_restart_serves_from_snapshot(
            self, mesh8, rng, tmp_path):
        cfg = _spill_cfg(tmp_path, result_cache_max_bytes=64 << 20)
        sess1 = MatrelSession(mesh=mesh8, config=cfg)
        mats = _register(sess1, rng, ["a", "b"])
        _check(sess1, mats, "a")
        _check(sess1, mats, "b")
        summary = sess1.save_state()
        assert summary["rc_entries"] == 2 and summary["catalog"] == 2
        sess2 = MatrelSession(mesh=mesh8, config=cfg)
        out = sess2.restore()
        assert out["restored"] and out["rc_entries"] == 2
        assert out["catalog"] == 2
        for nm in ("a", "b"):
            got = np.asarray(
                sess2.run(_gram(sess2.catalog[nm])).data)
            np.testing.assert_allclose(got, mats[nm][1],
                                       rtol=1e-5, atol=1e-4)
        info = sess2.result_cache_info()
        assert info["spill"]["thawed_restored"] == 2
        # a thawed answer reads as the hit it was, never a miss
        assert info["hits"] == 2 and info["misses"] == 0
        # the re-inserted entries answer the next repeat from HBM
        _ = sess2.run(_gram(sess2.catalog["a"]))
        assert sess2.result_cache_info()["hits"] == 3

    def test_integer_results_restore_bit_exact(
            self, mesh8, rng, tmp_path):
        cfg = _spill_cfg(tmp_path, result_cache_max_bytes=64 << 20)
        sess1 = MatrelSession(mesh=mesh8, config=cfg)
        mats = _register(sess1, rng, ["ints"], integral=True)
        _check(sess1, mats, "ints", rtol=0, atol=0)
        sess1.save_state()
        sess2 = MatrelSession(mesh=mesh8, config=cfg)
        assert sess2.restore()["restored"]
        got = np.asarray(sess2.run(_gram(sess2.catalog["ints"])).data)
        assert np.array_equal(got, mats["ints"][1])
        assert sess2.result_cache_info()["spill"][
            "thawed_restored"] == 1

    def test_corrupt_snapshot_warns_and_cold_starts(
            self, mesh8, rng, tmp_path, caplog):
        cfg = _spill_cfg(tmp_path, result_cache_max_bytes=64 << 20)
        sess1 = MatrelSession(mesh=mesh8, config=cfg)
        mats = _register(sess1, rng, ["a"])
        _check(sess1, mats, "a")
        sess1.save_state()
        state = os.path.join(str(tmp_path), "state")
        for dirpath, _dirs, files in os.walk(state):
            for f in files:
                with open(os.path.join(dirpath, f), "wb") as fh:
                    fh.write(b"not a snapshot")
        sess2 = MatrelSession(mesh=mesh8, config=cfg)
        with caplog.at_level(logging.WARNING):
            out = sess2.restore()        # never raises
        assert out["restored"] is False and out.get("reason")
        # the cold session still answers correctly
        mats2 = _register(sess2, rng, ["a"])
        _check(sess2, mats2, "a")

    def test_missing_snapshot_is_a_clean_cold_start(
            self, mesh8, tmp_path):
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(tmp_path))
        out = sess.restore()
        assert out["restored"] is False
        assert out["reason"] == "no snapshot"

    def test_sha1_tampered_artifact_is_a_miss_not_a_wrong_answer(
            self, mesh8, rng, tmp_path):
        cfg = _spill_cfg(tmp_path, result_cache_max_bytes=64 << 20)
        sess1 = MatrelSession(mesh=mesh8, config=cfg)
        mats = _register(sess1, rng, ["a", "b"])
        _check(sess1, mats, "a")
        _check(sess1, mats, "b")
        sess1.save_state()
        spill_dir = os.path.join(str(tmp_path), "spill")
        victim = sorted(f for f in os.listdir(spill_dir)
                        if f.endswith(".npy"))[0]
        with open(os.path.join(spill_dir, victim), "r+b") as fh:
            fh.seek(0, os.SEEK_END)
            fh.write(b"\x00tampered")
        sess2 = MatrelSession(mesh=mesh8, config=cfg)
        assert sess2.restore()["rc_entries"] == 2
        for nm in ("a", "b"):            # one thaws, one recomputes
            got = np.asarray(
                sess2.run(_gram(sess2.catalog[nm])).data)
            np.testing.assert_allclose(got, mats[nm][1],
                                       rtol=1e-5, atol=1e-4)
        sp = sess2.result_cache_info()["spill"]
        assert sp["corrupt"] == 1 and sp["thawed_restored"] == 1

    def test_read_artifact_raises_typed_snapshot_corruption(
            self, mesh8, tmp_path):
        assert issubclass(SnapshotCorruption, CheckpointCorruption)
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(tmp_path))
        mgr = sess._spill
        arr = np.arange(16, dtype=np.float32).reshape(4, 4)
        file, sha1 = mgr._write_artifact("cafe0001", arr)
        te = spill_lib.TierEntry(tier="disk", meta={"key_hash": "x"},
                                 nbytes=64, file=file, sha1=sha1)
        np.testing.assert_array_equal(mgr._read_artifact(te), arr)
        with open(file, "ab") as fh:
            fh.write(b"garbage")
        with pytest.raises(SnapshotCorruption, match="sha1 mismatch"):
            mgr._read_artifact(te)
        os.remove(file)
        with pytest.raises(SnapshotCorruption):
            mgr._read_artifact(te)

    def test_spill_off_restore_keeps_catalog_skips_entries(
            self, mesh8, rng, tmp_path, caplog):
        on = _spill_cfg(tmp_path, result_cache_max_bytes=64 << 20)
        sess1 = MatrelSession(mesh=mesh8, config=on)
        mats = _register(sess1, rng, ["a"])
        _check(sess1, mats, "a")
        sess1.save_state()
        off = MatrelConfig(result_cache_max_bytes=64 << 20,
                           state_dir=str(tmp_path))
        sess2 = MatrelSession(mesh=mesh8, config=off)
        assert sess2._spill is None
        with caplog.at_level(logging.WARNING):
            out = sess2.restore()
        assert out["restored"] and out["catalog"] == 1
        assert out["rc_entries"] == 0    # no thaw path without spill
        assert any("spill_enable is off" in r.message
                   for r in caplog.records)
        _check(sess2, {"a": (sess2.catalog["a"], mats["a"][1])}, "a")

    def test_save_state_without_any_directory_raises(self, mesh8):
        sess = MatrelSession(mesh=mesh8, config=MatrelConfig(
            spill_enable=True, result_cache_max_bytes=64 << 20))
        with pytest.raises(ValueError, match="state_dir"):
            sess.save_state()


# ---------------------------------------------------------------------------
# Tentpole — fleet demand hints and MQO template keys across a restart
# ---------------------------------------------------------------------------


class TestWarmSeeds:

    def test_fleet_seed_hints_merge_into_first_fresh_insert(self):
        d = fleet_lib.FleetDirectory(max_entries=4)
        n = d.seed_hints([{"key": "k1", "hits": {"0": 3, "1": 2}},
                          "junk", {"key": 7}, {"key": "k2",
                                               "hits": {"0": 1}}])
        assert n == 2 and d.info()["seed_hints"] == 2
        rec = fleet_lib.DirectoryRecord(
            owner=0, owner_key="local", nbytes=64, layout="2d",
            dtype="float32", dep_names=frozenset({"a"}),
            hits={0: 1})
        d.record_insert("k1", rec)
        got = d.lookup("k1")
        assert got.hits == {0: 4, 1: 2}  # pre-restart demand re-armed
        assert d.info()["seed_hints"] == 1

    def test_fleet_export_state_carries_unconsumed_hints(self):
        d = fleet_lib.FleetDirectory(max_entries=4)
        d.seed_hints([{"key": "k2", "hits": {"1": 5}}])
        d.record_insert("k1", fleet_lib.DirectoryRecord(
            owner=0, owner_key="local", nbytes=64, layout="2d",
            dtype="float32", dep_names=frozenset({"a"}), hits={0: 2}))
        out = d.export_state()
        by_key = {r["key"]: r for r in out}
        assert by_key["k1"]["hits"] == {"0": 2}
        assert by_key["k1"]["dep_names"] == ["a"]
        assert "owner_key" not in by_key["k1"]   # id-based, never exported
        assert by_key["k2"]["hits"] == {"1": 5}  # restart-of-a-restart

    def test_mqo_template_keys_seed_and_rewarm(self):
        st = mqo_lib.MqoState(MatrelConfig(cse_enable=True))
        assert st.seed_templates(["t1", "t2", 3]) == 2
        assert st.info()["seeded_templates"] == 2
        assert st.template_keys() == ["t1", "t2"]
        ent = mqo_lib.TemplateEntry(plan=object(), slots=(), pins=())
        st.put_template("t1", ent)
        assert st.info()["templates_rewarmed"] == 1
        assert st.info()["seeded_templates"] == 1
        # a still-unrewarmed seed survives into the next snapshot
        assert st.template_keys() == ["t2", "t1"]

    def test_mqo_seed_respects_template_bound(self):
        st = mqo_lib.MqoState(MatrelConfig(cse_enable=True,
                                           cse_template_max=1))
        assert st.seed_templates(["t1", "t2", "t3"]) == 1


# ---------------------------------------------------------------------------
# MV117 — spill-thaw provenance stamps cohere with the tier hierarchy
# ---------------------------------------------------------------------------


def _stamped_leaf(mesh8, rng, spill):
    A = BlockMatrix.from_numpy(
        rng.standard_normal((32, 32)).astype(np.float32), mesh=mesh8)
    return E.leaf(A).with_attrs(result_cache={
        "key_hash": "cafe", "layout": "2d", "dtype": "float32",
        "deps": [], "spill": spill})


def _mv117(e, cfg=None):
    return [d for d in spill_pass.check_spill_stamps(
        e, None, cfg or MatrelConfig())]


class TestMV117:

    def test_truthful_stamp_is_clean(self, mesh8, rng):
        from matrel_tpu.parallel import reshard
        cfg = MatrelConfig()
        nbytes = 32 * 32 * 4
        plan = reshard.spill_plan("host", "hbm", nbytes)
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "host", "legs": ["h2d"], "cost": "measured",
            "fits": plan.fits(float(cfg.reshard_peak_budget_bytes))})
        assert _mv117(leaf, cfg) == []

    def test_hbm_tier_claim_fires(self, mesh8, rng):
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "hbm", "legs": [], "cost": "measured"})
        diags = _mv117(leaf)
        assert len(diags) == 1 and diags[0].code == "MV117"
        assert "an HBM hit never stamps" in diags[0].message
        assert diags[0].severity == "warning"

    def test_unknown_leg_fires(self, mesh8, rng):
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "host", "legs": ["dma"], "cost": "measured"})
        diags = _mv117(leaf)
        assert len(diags) == 1
        assert "transfer vocabulary" in diags[0].message

    def test_wrong_legs_for_tier_fire(self, mesh8, rng):
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "host", "legs": ["disk_read", "h2d"],
            "cost": "measured"})
        diags = _mv117(leaf)
        assert any("priced on transfers that did not run"
                   in d.message for d in diags)

    def test_restored_tier_prices_the_disk_legs(self, mesh8, rng):
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "restored", "legs": ["disk_read", "h2d"],
            "cost": "measured"})
        assert _mv117(leaf) == []

    def test_stale_fits_verdict_fires(self, mesh8, rng):
        # default budget 0 always fits — a stamp claiming False lies
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "host", "legs": ["h2d"], "cost": "measured",
            "fits": False})
        diags = _mv117(leaf)
        assert any("budget story" in d.message for d in diags)

    def test_unclassifiable_cost_provenance_fires(self, mesh8, rng):
        leaf = _stamped_leaf(mesh8, rng, {
            "tier": "host", "legs": ["h2d"], "cost": "guessed"})
        diags = _mv117(leaf)
        assert any("cannot classify" in d.message for d in diags)

    def test_live_promotion_stamp_passes_verify_plan(
            self, mesh8, rng, tmp_path):
        from matrel_tpu import analysis
        from matrel_tpu.ir import rules
        from matrel_tpu.parallel import planner
        sess = MatrelSession(mesh=mesh8, config=_spill_cfg(tmp_path))
        mats = _register(sess, rng, ["a", "b"])
        _check(sess, mats, "a")
        _check(sess, mats, "b")
        _check(sess, mats, "a")          # promoted: entry now stamped
        B = sess.from_numpy(
            rng.standard_normal((N, N)).astype(np.float32))
        substituted = sess._rc_substitute(
            _gram(mats["a"][0]).multiply(B.expr()))
        stamps = [c.attrs["result_cache"] for c in substituted.children
                  if c.attrs.get("result_cache")]
        assert stamps and stamps[0].get("spill", {}).get(
            "tier") == "host"
        cfg = sess.config
        grid = (2, 4)
        annotated = planner.annotate_strategies(
            rules.optimize(substituted, cfg, grid=grid, mesh=mesh8),
            mesh8, cfg)
        diags = analysis.verify_plan(annotated, mesh8, config=cfg)
        assert [d for d in diags if d.code == "MV117"] == []


# ---------------------------------------------------------------------------
# Config validation — the durability knobs reject broken combinations
# ---------------------------------------------------------------------------


class TestConfigValidation:

    def test_spill_requires_a_result_cache(self):
        with pytest.raises(ValueError, match="result_cache_max_bytes"):
            MatrelConfig(spill_enable=True)

    def test_host_budget_must_be_positive(self):
        with pytest.raises(ValueError, match="spill_host_max_bytes"):
            MatrelConfig(spill_host_max_bytes=0)

    def test_disk_hits_gate_must_be_nonnegative(self):
        with pytest.raises(ValueError, match="spill_disk_hits"):
            MatrelConfig(spill_disk_hits=-1)
