"""Fire-drill for the staged relay-recovery batch (VERDICT r5 Next #2).

`tools/tpu_batch.sh --dry` must run the WHOLE staged capture sequence
end-to-end on the CPU backend with rc 0, each step emitting its
expected parseable artifact, and every write redirected away from the
repo's committed capture history. The round-6 introduction of this
drill immediately caught two staged tools that would have crashed in a
real relay window (gram_sym_full / autotune_capture missing their
sys.path setup) — which is precisely the failure mode the VERDICT said
the first relay window must not be spent debugging.

One subprocess run shared by every assertion: the batch takes ~30 s on
the CI host and the point is the INTEGRATED sequence.
"""

import json
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def dry_batch(tmp_path_factory):
    art = tmp_path_factory.mktemp("batch_dry")
    env = dict(os.environ)
    env["MATREL_BATCH_DRY_DIR"] = str(art)
    proc = subprocess.run(
        ["sh", os.path.join(REPO, "tools", "tpu_batch.sh"), "--dry"],
        capture_output=True, text=True, timeout=560, env=env)
    records = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                pytest.fail(f"unparseable artifact line: {line[:200]}")
    return proc, records, art


def test_batch_exits_zero(dry_batch):
    proc, _, _ = dry_batch
    assert proc.returncode == 0, (proc.stdout[-1500:]
                                  + proc.stderr[-1500:])


def _one(records, pred, what):
    got = [r for r in records if pred(r)]
    assert got, f"no {what} artifact in batch stdout"
    return got[0]


def test_headline_bench_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric")
               == "dense_blockmatmul_tflops_per_chip"
               and "vs_baseline" in r, "bench.py headline")
    assert rec["value"] is not None and rec["value"] > 0
    # Weak #5 closure rides along: the interval is recorded, and on a
    # sub-5-ms row the escalation loop must have brought the band
    # half-width inside the target (or exhausted its doublings)
    iv = rec["interval"]
    assert set(iv) >= {"median_ms", "half_width_ms", "half_width_frac",
                       "reps", "escalations", "band_target"}
    if iv["median_ms"] < 5.0 and iv["escalations"] < 4:
        assert iv["half_width_frac"] <= iv["band_target"]


def test_soak_guard_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records, lambda r: r.get("event") == "soak_tpu",
               "soak_guard")
    assert rec["ok"] is True, rec
    assert rec["stage"] == "soak"


def test_spgemm_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "blocksparse_spgemm_100k_1pct"
               and "cmp_speedup" in r, "bench.py --spgemm")
    assert rec["spgemm_full_ms"] > 0
    assert rec["cmp_densify_ms"] > 0


def test_sparse_kernels_row_artifact(dry_batch):
    _, records, _ = dry_batch
    # twice in the dry batch, like its sibling rows: the wedge-safe
    # bench.py --sparse-kernels step AND bench_all's dry-enabled row
    recs = [r for r in records
            if r.get("metric") == "sparse_kernel_sweep"
            and "rows" in r]
    assert len(recs) == 2, f"expected 2 sweep artifacts, got {recs}"
    rec = recs[0]
    # the round-11 acceptance on the dry mesh: every structure class
    # classified as generated, every relevant registered kernel
    # measured with its interval, at least one specialized variant
    # >= 1.3x over the fixed pre-registry Pallas kernel on its home
    # class, and the autotuned winner persisted + replayed from the
    # (redirected) table
    assert rec["ok"] is True, rec
    assert rec["baseline_kernel"] == "pallas_generic"
    structures = [r["structure"] for r in rec["rows"]]
    assert structures == ["row_band", "clustered_tile",
                          "powerlaw_coo"], structures
    for row in rec["rows"]:
        assert row["classified"] == row["structure"], row
        assert row["pairs"] > 0
        assert {"xla_gather", "pallas_generic"} <= set(row["kernels"])
        assert row["specialized"] in row["kernels"], row
        for t in row["kernels"].values():
            assert t["ms"] > 0 and "half_width_ms" in t
    assert rec["best_speedup"] >= 1.3, rec["best_speedup"]
    at = rec["autotune"]
    assert at["persisted"] is True and at["replayed"] is True
    assert at["key"].startswith("spgemm|")


def test_fusion_row_artifact(dry_batch):
    _, records, _ = dry_batch
    # twice in the dry batch, like its sibling rows: the wedge-safe
    # bench.py --fusion step AND bench_all's dry-enabled row
    recs = [r for r in records
            if r.get("metric") == "fusion_region_sweep"
            and "rows" in r]
    assert len(recs) == 2, f"expected 2 fusion artifacts, got {recs}"
    rec = recs[0]
    # the round-12 acceptance on the dry mesh: both chains measured
    # both ways with intervals, fused >= 1.3x over staged with the
    # dispatch count reduced and recorded, outputs identical, the
    # default (fusion off) path constructing zero region objects, and
    # MV111 quiet on a fresh fused annotation
    assert rec["ok"] is True, rec
    chains = [r["chain"] for r in rec["rows"]]
    assert chains == ["pagerank_step", "linreg_epilogue"], chains
    for row in rec["rows"]:
        assert row["staged_ms"] > 0 and row["fused_ms"] > 0
        assert "staged_half_width_ms" in row \
            and "fused_half_width_ms" in row
        assert row["fused_dispatches"] < row["staged_dispatches"], row
        assert row["regions"] >= 1
        assert row["speedup"] >= 1.3, row
        assert row["outputs_agree"] is True
    assert rec["off_constructs_nothing"] is True
    assert rec["mv111_quiet"] is True, rec["mv111"]


def test_traffic_row_artifact(dry_batch):
    _, records, _ = dry_batch
    # twice in the dry batch, like its sibling rows: the wedge-safe
    # tools/traffic.py step AND bench_all's dry-enabled row
    recs = [r for r in records
            if r.get("metric") == "traffic_overload_harness"
            and "tenants" in r]
    assert len(recs) == 2, f"expected 2 traffic artifacts, got {recs}"
    rec = recs[0]
    # the round-13 acceptance at ~2x sustained overload over 3
    # weighted tenants (docs/OVERLOAD.md): goodput holds >= 80% of
    # measured closed-loop capacity, every refusal typed, zero wrong
    # answers, admitted-and-met p99 inside the declared deadline,
    # weighted fairness strict (gold misses less than bronze), and
    # brownout provably enters AND exits
    assert rec["ok"] is True, rec
    assert rec["wrong_answers"] == 0
    assert rec["untyped_errors"] == 0
    assert rec["goodput_ratio"] >= 0.8, rec["goodput_ratio"]
    assert rec["p99_within_deadline"] is True
    assert 0.0 < rec["fairness_jain"] <= 1.0
    tenants = rec["tenants"]
    assert set(tenants) == {"gold", "silver", "bronze"}
    for t, row in tenants.items():
        assert row["arrivals"] > 0
        # per-tenant percentile columns present (p50/p95/p99)
        assert {"p50_ms", "p95_ms", "p99_ms"} <= set(row)
        # typed-shed counts present
        assert row["sheds"] >= 0 and row["deadline_misses"] >= 0
    assert tenants["gold"]["miss_rate"] < tenants["bronze"]["miss_rate"]
    assert rec["brownout"]["entered"] is True
    assert rec["brownout"]["exited"] is True
    # overload plus sheds means the typed counts actually fired
    assert sum(t["sheds"] for t in tenants.values()) > 0


def test_traffic_slo_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "traffic_slo_harness"
               and "prometheus" in r, "tools/traffic.py --slo")
    # the round-15 acceptance (docs/OBSERVABILITY.md tier 3): at ~2x
    # sustained overload under declared per-tenant objectives, the
    # violated (lowest-weight) tenant's fast-window burn-rate alert
    # FIRES during saturation and every alert CLEARS after the load
    # drops, with the live Prometheus endpoint strict-parsing clean on
    # every poll throughout and still zero wrong answers
    assert rec["ok"] is True, rec
    assert rec["violated_tenant_fired_in_window"] is True
    assert rec["alerts_fired"] >= 1
    assert rec["uncleared"] == []
    assert rec["alerts_active_final"] == 0
    assert rec["prometheus"]["ok"] is True
    assert rec["prometheus"]["polls"] > 0
    assert rec["prometheus"]["parse_failures"] == 0
    assert rec["wrong_answers"] == 0
    assert rec["untyped_errors"] == 0
    assert "bronze:avail" in rec["fired_objectives"]


def test_serve_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "serve_repeated_traffic_qps"
               and "speedup" in r, "bench.py --serve")
    # the acceptance number: result cache + micro-batched admission
    # must run the repeated-traffic stream at >= 2x the QPS of today's
    # sequential uncached session.run loop, on the CPU backend
    assert rec["speedup"] is not None and rec["speedup"] >= 2.0, rec
    assert rec["seq_uncached_qps"] > 0
    assert rec["batched_cached_qps"] > rec["seq_uncached_qps"]
    for name in ("seq_uncached", "seq_cached", "batched_uncached",
                 "batched_cached"):
        cfg = rec["configs"][name]
        assert cfg["qps"] > 0
        assert set(cfg) >= {"median_ms", "half_width_ms",
                            "half_width_frac", "replays"}


def test_cse_row_artifact(dry_batch):
    _, records, _ = dry_batch
    # twice in the dry batch, like its sibling rows: the wedge-safe
    # bench.py --cse step AND bench_all's dry-enabled row
    recs = [r for r in records
            if r.get("metric") == "cse_shared_interior_batch"
            and "speedup" in r]
    assert len(recs) == 2, f"expected 2 cse artifacts, got {recs}"
    rec = recs[0]
    # the round-17 acceptance (docs/SERVING.md): >= 1.5x first-contact
    # wall at k variants over one shared interior, CSE on vs off, with
    # bit-identical answers and exactly one hoisted interior per batch
    assert rec["speedup"] is not None and rec["speedup"] >= 1.5, rec
    assert rec["exact"] is True
    assert rec["hoisted_per_batch"] == 1
    for name in ("cse_off", "cse_on"):
        cfg = rec["configs"][name]
        assert cfg["median_ms"] > 0
        assert set(cfg) >= {"median_ms", "half_width_ms", "trials"}
    # the steady-state coda: a structurally-identical batch over a
    # REBOUND leaf answers through the plan-template path (hoist +
    # consumer probes both hit) with correct answers
    st = rec["steady"]
    assert st["template_hits_delta"] >= 1, st
    assert st["exact"] is True
    assert st["rebind_ms"] < rec["cse_on_ms"]


def test_fleet_row_artifact(dry_batch):
    _, records, _ = dry_batch
    # twice in the dry batch, like its sibling rows: the wedge-safe
    # bench.py --fleet step AND bench_all's dry-enabled row
    recs = [r for r in records
            if r.get("metric") == "fleet_scaleout_qps"
            and "speedup" in r]
    assert len(recs) == 2, f"expected 2 fleet artifacts, got {recs}"
    rec = recs[0]
    # the round-16 acceptance (docs/FLEET.md): >= 1.5x aggregate QPS
    # going 1 -> 2 virtual slices on the repeated-traffic stream
    # whose working set only fits the fleet's AGGREGATE cache, with a
    # directory hit on a NON-owning slice answering without recompute
    assert rec["speedup"] is not None and rec["speedup"] >= 1.5, rec
    assert rec["slices1_qps"] > 0
    assert rec["slices2_qps"] > rec["slices1_qps"]
    assert rec["remote_hit_no_recompute"] is True
    s2 = rec["configs"]["slices2"]
    assert s2["directory"]["remote_hits"] >= 1
    assert s2["recompute_free_replays"] is True
    for name in ("slices1", "slices2"):
        cfg = rec["configs"][name]
        assert cfg["qps"] > 0
        assert set(cfg) >= {"median_ms", "half_width_ms", "replays",
                            "directory", "placed"}
    # the mid-stream slice-kill drill: the stream completes with
    # ZERO wrong answers and only typed failures
    kill = rec["kill"]
    assert kill["wrong"] == 0
    assert kill["untyped_failures"] == 0
    assert kill["completed"] + kill["typed_failures"] \
        == kill["submitted"]
    assert kill["completed"] > 0
    assert kill["failovers"] == 1


def test_traffic_slices_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "traffic_fleet_harness"
               and "directory" in r, "tools/traffic.py --slices")
    # the open-loop fleet drill (docs/FLEET.md): placement spreads
    # the stream over both slices, the directory answers repeats,
    # span-pinned pool entries exercise the full-mesh path, and the
    # mid-stream kill completes the stream with zero wrong answers
    # and only typed failures
    assert rec["ok"] is True, rec
    assert rec["wrong_answers"] == 0
    assert rec["untyped_errors"] == 0
    assert rec["failovers"] == 1
    assert rec["completed"] > 0
    assert len(rec["slices_served_before_kill"]) >= 2
    assert rec["directory"]["hits"] >= 1
    assert rec["placed"]["slice"] > 0 and rec["placed"]["span"] > 0


def test_stream_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "stream_update_latency"
               and "speedup" in r, "bench.py --stream")
    # the round-14 acceptance number (docs/IVM.md): delta-patch
    # steady-state update latency >= 3x faster than full recompute on
    # the small-delta stream, CPU backend, with MV113 proving every
    # surviving patched entry and zero wrong answers (the measurement
    # child bit-exact-asserts the integer queries itself — rec["ok"]
    # carries that verdict)
    assert rec["speedup"] is not None and rec["speedup"] >= 3.0, rec
    assert rec["ok"] is True, rec
    assert rec["patch"]["mv113"] == [], rec["patch"]["mv113"]
    assert rec["patch"]["patched_per_update"] > 0
    assert rec["patch"]["reused_plans"] > 0
    assert rec["patch"]["median_ms"] > 0
    assert rec["recompute"]["median_ms"] > rec["patch"]["median_ms"]
    for side in ("patch", "recompute"):
        assert set(rec[side]) >= {"median_ms", "half_width_ms",
                                  "updates"}


def test_precision_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "precision_tier_sweep"
               and "rows" in r, "bench.py --precision")
    # all four tier rows, each with its TFLOPS and max-abs-error
    # columns, every measured error inside its documented bound, and
    # the SLA chooser routing each named level to the tier the cost
    # model's pass/byte billing says it should
    tiers = [row["tier"] for row in rec["rows"]]
    assert tiers == ["f32", "bf16x1", "bf16x3", "int32"], tiers
    for row in rec["rows"]:
        assert row["stamped_tier"] == row["tier"], row
        assert row["tflops_per_chip"] > 0
        assert "max_abs_err" in row and "err_bound" in row
        assert row["within_bound"] is True, row
    int_row = rec["rows"][-1]
    assert int_row["max_abs_err"] == 0.0          # int path is EXACT
    assert rec["chooser_ok"] is True, rec["sla_choices"]
    assert rec["all_within_bound"] is True


def test_reshard_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "reshard_sweep"
               and "rows" in r, "bench.py --reshard")
    # the reshard-planner acceptance on the dry mesh: every move
    # measured both ways with its modelled bytes/peaks, and the staged
    # CROSS plans peak-bounded below the one-shot full-gather model
    assert rec["ok"] is True, rec
    pairs = [row["pair"] for row in rec["rows"]]
    assert pairs == ["row->col", "col->row", "row->2d", "2d->rep"], pairs
    for row in rec["rows"]:
        assert row["staged_ms"] > 0 and row["naive_ms"] > 0, row
        assert row["staged_bytes"] >= 0 and row["peak_bytes"] > 0
        if row["cross"]:
            assert row["steps"] == ["all_to_all", "all_to_all"], row
            assert row["peak_bytes"] < row["naive_peak_bytes"], row


def test_coeffs_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "coeff_planner_sweep"
               and "rows" in r, "bench.py --coeffs")
    # the cost-model-loop acceptance on the dry mesh: every workload
    # class fully covered by calibrated rows (all decisions stamped
    # measured), answers bit-close to the analytic path, and the
    # calibrated ranking never slower beyond the documented guard band
    # (identical picks = identical plans, exempt from the jitter gate)
    assert rec["ok"] is True, rec
    names = [row["workload"] for row in rec["rows"]]
    assert names == ["chain", "pagerank_step", "linreg_epilogue"], names
    assert len(rec["classes"]) == 3, rec["classes"]  # distinct buckets
    for row in rec["rows"]:
        assert row["ok"] is True, row
        assert row["covered"] is True, row
        assert row["outputs_agree"] is True, row
        assert all(c == "measured" for c in row["cost_sources"]), row
        assert row["speedup"] is not None, row


def test_spill_row_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "spill_sweep"
               and "restart" in r, "bench.py --spill")
    # the durability acceptance on the dry mesh: working set larger
    # than the HBM budget sustained by lower-tier promotions with
    # zero wrong answers, and the thawed restart's first hit served
    # from the snapshot (not recomputed)
    assert rec["working_set_over_budget"] is True, rec
    assert rec["wrong"] == 0, rec
    assert rec["sustained"]["promoted"] > 0, rec["sustained"]
    rs = rec["restart"]
    assert rs["restored_entries"] > 0, rs
    assert rs["thawed_served_from_snapshot"] is True, rs
    assert rs["cold_first_hit_ms"] > 0, rs
    assert rs["thawed_first_hit_ms"] > 0, rs
    # per-leg transfer rows (the drift calibration feed): every leg
    # in the reshard vocabulary with positive measured bytes/ms
    assert rec["rows"], rec
    for row in rec["rows"]:
        assert row["leg"] in ("d2h", "h2d", "disk_write",
                              "disk_read"), row
        assert row["bytes"] > 0 and row["ms"] > 0, row


def test_bench_all_rows_artifacts(dry_batch):
    _, records, _ = dry_batch
    # every heavy row emits an explicit, parseable skip record — a
    # silently-missing row would hide a crashed step
    for name in ("bench_linreg", "bench_spmm", "bench_pagerank",
                 "bench_pagerank_10x", "bench_cg", "bench_eigen",
                 "bench_triangles", "bench_north_star"):
        rec = _one(records, lambda r, n=name: r.get("metric") == n,
                   f"bench_all {name}")
        assert rec.get("skipped") == "dry", rec
    chain = _one(records,
                 lambda r: r.get("metric")
                 == "chain_abc_10k_skewed_wallclock", "bench_all chain")
    assert chain["value"] > 0 and "plan" in chain


def test_topology_flip_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "topology_strategy_flip",
               "topology_flip")
    # the weighted-mesh planner provably flips off the slow axis
    # (VERDICT Next #4 "done when"), MV106 flags the hand-stamped
    # slow-axis plan, and the planner's own weighted output is clean
    assert rec["ok"] is True, rec
    assert rec["unweighted"] != rec["weighted"]
    assert rec["mv106_flagged"] is True
    assert rec["clean_plan_quiet"] is True
    assert rec["slow_axis_bytes"] > rec["fast_axis_bytes"]


def test_flight_drill_artifact(dry_batch):
    _, records, art = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "flight_recorder_drill",
               "flight_drill")
    # the obs tier-2 acceptance, end to end on the dry log: the serve
    # batch ran, the compile failure left a parseable flight artifact,
    # the chrome export has parent-linked admission/compile/execute
    # spans, and the drift audit produced calibration rows
    assert rec["ok"] is True, rec
    assert rec["batch_ok"] is True
    assert rec["compile_failure_dumped"] is True
    assert rec["chrome_events"] > 0 and rec["parent_linked"] > 0
    assert {"serve.admit", "serve.batch", "plan.optimize",
            "serve.execute"} <= set(rec["span_names"])
    assert rec["drift_rows"] >= 1
    # the flight-recorder artifact itself parses and carries records
    flight = json.loads((art / "flight.json").read_text())
    assert flight["kind"] == "flight_recorder"
    assert flight["reason"] == "compile_failure"
    assert flight["records"]
    # the drift calibration table parses too
    table = json.loads((art / "drift.json").read_text())
    assert table["schema"] == 1 and table["entries"]


def test_chaos_drill_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records, lambda r: r.get("metric") == "chaos_drill",
               "chaos_drill")
    # the resilience acceptance: >= 50 queries under a seeded fault
    # schedule with every instrumented site firing, 0 wrong answers,
    # 0 unclassified failures, only the deterministic-fault queries
    # failing (typed), the poison batch isolating exactly one future,
    # and zero hangs (the drill itself drains under a timeout)
    assert rec["ok"] is True, rec
    assert rec["queries"] >= 50
    assert rec["wrong_answers"] == 0
    assert rec["untyped_failures"] == 0
    assert rec["poison_isolated"] is True
    assert rec["deadline_typed"] is True
    assert rec["checkpoint_ok"] is True
    assert set(rec["sites_fired"]) == {
        "compile", "lower", "strategy", "execute", "rc_probe",
        "serve_admit", "checkpoint"}
    assert rec["retries"] > 0 and rec["degrades"] > 0


def test_provenance_drill_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records,
               lambda r: r.get("metric") == "provenance_drill",
               "provenance_drill")
    # the obs tier-4 acceptance: every provenance-bearing serve path
    # yields a lineage record (execute / whole hit / interior / IVM
    # patch / fleet directory + replica / rung-4 degrade), the MV115
    # dynamic ledger check is clean, and FULL audit replay proves
    # every served answer against fresh execution
    assert rec["ok"] is True, rec
    assert rec["missing_paths"] == []
    assert 4 in rec["degrade_rungs"]
    assert rec["mv115_findings"] == 0
    for name in ("serve", "fleet", "degrade"):
        verdict = rec["audit"][name]
        assert verdict["ok"] is True, (name, verdict)
        assert verdict["failed"] == 0
        assert verdict["sampled"] == verdict["replayable"] >= 1


def test_race_drill_artifact(dry_batch):
    _, records, _ = dry_batch
    rec = _one(records, lambda r: r.get("metric") == "race_drill",
               "race_drill")
    # the concurrency-sanitizer acceptance (docs/CONCURRENCY.md):
    # every seeded interleaving of the four hairy schedules resolves
    # right-or-typed with runtime lockdep armed, and the observed
    # lock-order graph stays acyclic
    assert rec["ok"] is True, rec
    assert rec["wrong"] == 0
    assert rec["untyped"] == 0
    assert rec["inversions"] == 0
    assert rec["acyclic"] is True
    assert rec["resolved"] >= 1
    assert set(rec["schedules"]) == {
        "submit_close_drain", "kill_replication",
        "rebind_probes", "delta_serve"}


def test_sweep_and_gram_artifacts(dry_batch):
    _, records, _ = dry_batch
    verdict = _one(records, lambda r: "results" in r and "ok" in r,
                   "north_star_sweep verdict")
    assert verdict["ok"] is True
    gram3 = _one(records, lambda r: "manual3_sym_s" in r,
                 "gram_manual3")
    assert gram3["rel_diff_vs_high"] < 1e-4   # numeric sanity intact
    full = _one(records,
                lambda r: r.get("metric") == "linreg_sym2pass_10Mx1k_s",
                "gram_sym_full")
    # theta of the synthetic y = X·1 fit must come back ~1 even dry
    assert all(abs(t - 1.0) < 0.05 for t in full["theta_head"])
    _one(records, lambda r: "side" in r and "best" in r,
         "autotune_capture")


def test_artifacts_redirected_out_of_repo(dry_batch):
    _, _, art = dry_batch
    # every side-effect landed in the dry dir, not the capture history
    for name in ("events.jsonl", "progress.jsonl", "soaklog.jsonl",
                 "bench_last_good.json", "cpu_baseline.json",
                 "autotune_dry.json", "spk_autotune.json",
                 "flight.json", "drift.json"):
        assert (art / name).exists(), f"{name} not redirected"
    events = [json.loads(l) for l in (art / "events.jsonl").open()]
    assert any(e.get("kind") == "bench" for e in events)
    progress = [json.loads(l) for l in (art / "progress.jsonl").open()]
    assert any(e.get("event") == "soak_tpu" for e in progress)
    assert any(e.get("event") == "north_star_sweep" for e in progress)
