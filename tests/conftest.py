"""Test harness: simulate an 8-device mesh on CPU.

The reference tests on Spark's ``local[*]`` — an in-process cluster that
exercises the real shuffle/partitioner code paths in one JVM (SURVEY.md §4).
The JAX analogue: 8 virtual CPU devices via
``--xla_force_host_platform_device_count``, so every sharding, shard_map and
collective in the framework runs for real, just without ICI.

Must run before jax is imported anywhere — hence module level, in conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon sitecustomize registers the TPU PJRT plugin at interpreter start,
# which pins the platform before this conftest runs; the config API still
# overrides it (env vars alone do not).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from matrel_tpu.core import mesh as mesh_lib
    return mesh_lib.make_mesh((2, 4))


@pytest.fixture(scope="session")
def mesh4x2():
    from matrel_tpu.core import mesh as mesh_lib
    return mesh_lib.make_mesh((4, 2))


@pytest.fixture(scope="session")
def mesh_square():
    """2x2 square mesh (SUMMA/Cannon needs gx == gy)."""
    import jax
    from matrel_tpu.core import mesh as mesh_lib
    return mesh_lib.make_mesh((2, 2), devices=jax.devices()[:4])


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _fresh_session():
    from matrel_tpu import session
    session.reset_session()
    yield
    session.reset_session()


@pytest.fixture(autouse=True)
def _autotune_table_tmp(tmp_path, monkeypatch):
    """Keep the persisted autotune table out of the repo root and out of
    cross-test state: each test gets a fresh table path + empty cache."""
    from matrel_tpu.parallel import autotune
    monkeypatch.setattr(autotune, "_DEFAULT_TABLE",
                        str(tmp_path / "autotune.json"))
    autotune._CACHE.clear()
    yield
