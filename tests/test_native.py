"""Native optimizer core tests: the C++ chain DP must exist (toolchain is
part of the environment), agree with the pure-Python DP, and beat it on
long chains."""

import time

import numpy as np
import pytest

from matrel_tpu.ir import chain as chain_lib
from matrel_tpu.ir.expr import leaf
from matrel_tpu.utils import native


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    assert lib is not None, "native build must succeed (g++ is in the image)"
    return lib


def _mk_ops(mesh, dims, dens=None):
    import dataclasses
    from matrel_tpu.core.blockmatrix import BlockMatrix
    base = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32), mesh=mesh)
    ops = []
    for i in range(len(dims) - 1):
        shape = (dims[i], dims[i + 1])
        nnz = None if dens is None else int(dens[i] * shape[0] * shape[1])
        ops.append(leaf(dataclasses.replace(base, shape=shape, nnz=nnz)))
    return ops


def _python_dp(operands):
    """The pure-Python reference DP (bypasses the native fast path)."""
    from matrel_tpu.ir import stats
    from matrel_tpu.ir.expr import matmul as mm
    n = len(operands)
    best = [[None] * n for _ in range(n)]
    for i in range(n):
        best[i][i] = (0.0, operands[i])
    for span in range(2, n + 1):
        for i in range(0, n - span + 1):
            j = i + span - 1
            cand = None
            for s in range(i, j):
                cl, el = best[i][s]
                cr, er = best[s + 1][j]
                step = stats.matmul_cost(el.shape[0], el.shape[1],
                                         er.shape[1], el.density, er.density)
                if cand is None or cl + cr + step < cand[0]:
                    cand = (cl + cr + step, mm(el, er))
            best[i][j] = cand
    return best[0][n - 1]


def test_native_matches_python_dense(lib, mesh8):
    dims = [30, 35, 15, 5, 10, 20, 25]
    ops = _mk_ops(mesh8, dims)
    got, cost = chain_lib.optimal_order(ops)
    pcost, pexpr = _python_dp(ops)
    assert cost == pytest.approx(pcost)
    assert cost == pytest.approx(2 * 15125)  # CLRS optimum × FLOP factor
    assert chain_lib.parenthesise_equal(got, pexpr) if hasattr(
        chain_lib, "parenthesise_equal") else True
    from matrel_tpu.workloads.chain_bench import parenthesisation
    assert parenthesisation(got) == parenthesisation(pexpr)


def test_native_matches_python_sparse(lib, mesh8):
    rng = np.random.default_rng(7)
    for _ in range(10):
        n = int(rng.integers(3, 8))
        dims = [int(rng.integers(2, 400)) for _ in range(n + 1)]
        dens = [float(rng.choice([1.0, 1.0, 0.1, 0.01])) for _ in range(n)]
        ops = _mk_ops(mesh8, dims, dens)
        got, cost = chain_lib.optimal_order(ops)
        pcost, pexpr = _python_dp(ops)
        # same optimum cost (ties may differ in structure; cost must agree
        # within float/rounding tolerance of the nnz-int rounding)
        assert cost == pytest.approx(pcost, rel=0.05)


def test_comm_dp_native_matches_python(lib, mesh8, monkeypatch):
    """The comm term's C++ implementation must track ir/stats.py exactly
    (the C++ comment in native/chain_dp.cc points here): fuzz random
    chains/grids through native chain_dp vs the forced-Python DP."""
    rng = np.random.default_rng(9)
    for _ in range(10):
        n = int(rng.integers(3, 7))
        dims = [int(rng.integers(2, 600)) for _ in range(n + 1)]
        dens = [float(rng.choice([1.0, 1.0, 0.2, 0.02]))
                for _ in range(n)]
        grid = tuple(rng.choice([(1, 2), (2, 2), (2, 4), (4, 2)]))
        ops = _mk_ops(mesh8, dims, dens)
        e_nat, c_nat = chain_lib.optimal_order(ops, grid=grid)
        with monkeypatch.context() as mp:
            mp.setattr(native, "chain_dp", lambda *a, **k: None)
            e_py, c_py = chain_lib.optimal_order(ops, grid=grid)
        # density propagation rounds differently (nnz ints in expr
        # nodes vs float densities in C++) — same tolerance as
        # test_native_matches_python_sparse; equal-cost ties may pick
        # different structures
        assert c_nat == pytest.approx(c_py, rel=0.05), (dims, dens, grid)


def test_native_raw_api(lib):
    splits, cost = native.chain_dp([10, 1000, 10, 1000], [1.0, 1.0, 1.0])
    # (A·B)·C: split after operand 1 for the full interval [0,2]
    assert splits[0][2] == 1
    assert cost == pytest.approx(2 * (10 * 1000 * 10 + 10 * 10 * 1000))


def test_native_faster_than_python_on_long_chain(lib, mesh8):
    rng = np.random.default_rng(1)
    dims = [int(rng.integers(10, 2000)) for _ in range(101)]
    ops = _mk_ops(mesh8, dims)
    t0 = time.perf_counter()
    chain_lib.optimal_order(ops)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    _python_dp(ops)
    t_python = time.perf_counter() - t0
    assert t_native < t_python


# -- native text ingestion (mtx_reader.cc) -----------------------------------


class TestNativeMtxReader:
    """The C++ MatrixMarket/COO parser must agree with the scipy oracle on
    every format variant and feed io.load_mtx / io.load_coo_csv."""

    def _roundtrip(self, tmp_path, sp, **mmwrite_kw):
        import scipy.io
        import scipy.sparse as sps
        p = str(tmp_path / "m.mtx")
        scipy.io.mmwrite(p, sp, **mmwrite_kw)
        parsed = native.mtx_read(p)
        assert parsed is not None
        shape, ri, ci, vals = parsed
        got = sps.coo_matrix((vals, (ri, ci)), shape=shape).toarray()
        want = scipy.io.mmread(p)
        want = want.toarray() if hasattr(want, "toarray") else np.asarray(want)
        np.testing.assert_allclose(got.astype(np.float32),
                                   want.astype(np.float32), rtol=0, atol=0)
        return shape

    def test_general(self, lib, tmp_path):
        import scipy.sparse as sps
        sp = sps.random(97, 61, density=0.07, random_state=1, format="coo")
        assert self._roundtrip(tmp_path, sp) == (97, 61)

    def test_symmetric(self, lib, tmp_path):
        import scipy.sparse as sps
        a = sps.random(80, 80, density=0.05, random_state=2, format="coo")
        self._roundtrip(tmp_path, (a + a.T).tocoo(), symmetry="symmetric")

    def test_skew_symmetric(self, lib, tmp_path):
        import scipy.sparse as sps
        b = np.triu(np.random.default_rng(3).standard_normal((40, 40)), 1)
        self._roundtrip(tmp_path, sps.coo_matrix(b - b.T),
                        symmetry="skew-symmetric")

    def test_dense_array_format(self, lib, tmp_path):
        dm = np.random.default_rng(4).standard_normal((13, 7))
        self._roundtrip(tmp_path, dm)

    def test_pattern(self, lib, tmp_path):
        import scipy.sparse as sps
        p = str(tmp_path / "pat.mtx")
        rng = np.random.default_rng(5)
        rows = rng.integers(0, 30, 50)
        cols = rng.integers(0, 20, 50)
        with open(p, "w") as f:
            f.write("%%MatrixMarket matrix coordinate pattern general\n")
            f.write("% a comment line\n30 20 50\n")
            for i, j in zip(rows, cols):
                f.write(f"{i + 1} {j + 1}\n")
        shape, ri, ci, vals = native.mtx_read(p)
        assert shape == (30, 20)
        assert len(ri) == 50 and np.all(vals == 1.0)
        got = sps.coo_matrix((vals, (ri, ci)), shape=shape).toarray()
        want = np.zeros((30, 20))
        np.add.at(want, (rows, cols), 1.0)
        np.testing.assert_allclose(got, want)

    def test_complex_falls_back(self, lib, tmp_path):
        p = str(tmp_path / "c.mtx")
        with open(p, "w") as f:
            f.write("%%MatrixMarket matrix coordinate complex general\n")
            f.write("2 2 1\n1 1 3.0 4.0\n")
        assert native.mtx_read(p) is None  # scipy fallback territory

    def test_malformed_returns_none(self, lib, tmp_path):
        p = str(tmp_path / "bad.mtx")
        with open(p, "w") as f:
            f.write("%%MatrixMarket matrix coordinate real general\n")
            f.write("2 2 3\n1 1 1.0\n")  # claims 3 entries, has 1
        assert native.mtx_read(p) is None

    def test_coo_csv_mixed_separators(self, lib, tmp_path):
        p = str(tmp_path / "t.csv")
        with open(p, "w") as f:
            f.write("# comment\n0,1,2.5\n3, 4 ,-1.0\n5\t6\t7e-3\n\n")
        ri, ci, vals = native.coo_csv_read(p)
        assert list(ri) == [0, 3, 5] and list(ci) == [1, 4, 6]
        np.testing.assert_allclose(vals, [2.5, -1.0, 7e-3])

    def test_value_precision_matches_strtod(self, lib, tmp_path):
        # 17-significant-digit values (scipy mmwrite default) must parse
        # to the same float32 as the strtod oracle.
        vals = np.random.default_rng(6).standard_normal(2000)
        vals = np.concatenate([vals, vals * 1e-20, vals * 1e17,
                               [0.0, 1.0, -1.0, 1e-300, 1e300]])
        p = str(tmp_path / "prec.csv")
        with open(p, "w") as f:
            for k, v in enumerate(vals):
                f.write(f"{k},0,{v:.17g}\n")
        _, _, got = native.coo_csv_read(p)
        want = np.array([float(f"{v:.17g}") for v in vals])
        with np.errstate(over="ignore"):   # 1e300 → inf is the point
            got32 = got.astype(np.float32)
            want32 = want.astype(np.float32)
        np.testing.assert_array_equal(got32, want32)

    def test_io_load_mtx_uses_native(self, lib, tmp_path, mesh8):
        import scipy.sparse as sps
        from matrel_tpu import io as mio
        sp = sps.random(64, 64, density=0.2, random_state=7, format="coo")
        p = str(tmp_path / "m.mtx")
        import scipy.io
        scipy.io.mmwrite(p, sp)
        bsm = mio.load_mtx(p, mesh=mesh8, block_size=16)
        np.testing.assert_allclose(bsm.to_numpy(), sp.toarray(), rtol=1e-6)

    def test_io_load_coo_csv_native(self, lib, tmp_path, mesh8):
        from matrel_tpu import io as mio
        p = str(tmp_path / "m.csv")
        with open(p, "w") as f:
            f.write("0,0,1.5\n2,3,-2.0\n7,7,4.0\n")
        bm = mio.load_coo_csv(p, shape=(8, 8), mesh=mesh8, dense=True)
        want = np.zeros((8, 8), np.float32)
        want[0, 0], want[2, 3], want[7, 7] = 1.5, -2.0, 4.0
        np.testing.assert_allclose(bm.to_numpy(), want)

    def test_array_format_blank_line_before_size(self, lib, tmp_path):
        # strtoll skips blank lines; data_off must follow the parsed
        # numbers, not the pre-skip line pointer (regression).
        p = str(tmp_path / "blank.mtx")
        with open(p, "w") as f:
            f.write("%%MatrixMarket matrix array real general\n"
                    "% comment\n\n2 2\n1.0\n2.0\n3.0\n4.0\n")
        shape, ri, ci, vals = native.mtx_read(p)
        got = np.zeros(shape)
        got[ri, ci] = vals
        np.testing.assert_allclose(got, [[1.0, 3.0], [2.0, 4.0]])

    def test_stale_lib_keeps_working_symbols(self, lib):
        # Partial symbol sets must degrade per-feature, not disable the
        # whole library.
        assert getattr(lib, "_matrel_has_dp", False)
        assert getattr(lib, "_matrel_has_ingest", False)


class TestNativeSpMVPlan:
    """spmv_plan.cc — counting-sort plan fill vs the numpy fallback.

    Layouts may differ (slot order within a block), so the contract is
    equal spmv RESULTS plus equal capacity/padding decisions.
    """

    @pytest.fixture(autouse=True)
    def _need_spmv(self, lib):
        if not getattr(lib, "_matrel_has_spmv", False):
            pytest.skip("native spmv symbols unavailable")

    def _both_plans(self, monkeypatch, rows, cols, vals, n_r, n_c):
        from matrel_tpu.ops import spmv as spmv_lib
        p_nat = spmv_lib.build_spmv_plan(rows, cols, vals,
                                         n_rows=n_r, n_cols=n_c)
        monkeypatch.setattr(native, "spmv_counts", lambda *a, **k: None)
        p_np = spmv_lib.build_spmv_plan(rows, cols, vals,
                                        n_rows=n_r, n_cols=n_c)
        monkeypatch.undo()
        return p_nat, p_np

    def test_counts_match_bincount(self, lib):
        rng = np.random.default_rng(0)
        rows = rng.integers(0, 5000, 20_000).astype(np.int64)
        got = native.spmv_counts(rows, 512, 10)
        np.testing.assert_array_equal(got, np.bincount(rows // 512,
                                                       minlength=10))

    def test_results_match_numpy_path(self, lib, monkeypatch):
        import jax.numpy as jnp
        from matrel_tpu.ops import spmv as spmv_lib
        rng = np.random.default_rng(1)
        for n_r, n_c, m in [(2000, 1500, 25_000), (512, 512, 100),
                            (100, 100, 0)]:
            rows = rng.integers(0, n_r, m)
            cols = rng.integers(0, n_c, m)
            vals = rng.standard_normal(m).astype(np.float32)
            x = rng.standard_normal(n_c).astype(np.float32)
            p_nat, p_np = self._both_plans(monkeypatch, rows, cols, vals,
                                           n_r, n_c)
            assert p_nat.capacity == p_np.capacity
            assert p_nat.padding_ratio == p_np.padding_ratio
            np.testing.assert_allclose(
                np.asarray(spmv_lib.spmv(p_nat, jnp.asarray(x))),
                np.asarray(spmv_lib.spmv(p_np, jnp.asarray(x))),
                rtol=2e-5, atol=1e-5)

    def test_overflow_path_matches(self, lib, monkeypatch):
        import jax.numpy as jnp
        from matrel_tpu.ops import spmv as spmv_lib
        rng = np.random.default_rng(2)
        m = 20_000
        rows = np.where(rng.random(m) < 0.3, 7,
                        rng.integers(0, 4096, m)).astype(np.int64)
        cols = rng.integers(0, 512, m).astype(np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        x = rng.standard_normal(512).astype(np.float32)
        p_nat, p_np = self._both_plans(monkeypatch, rows, cols, vals,
                                       4096, 512)
        assert p_nat.ov_rows is not None and p_np.ov_rows is not None
        assert p_nat.ov_rows.shape == p_np.ov_rows.shape
        np.testing.assert_allclose(
            np.asarray(spmv_lib.spmv(p_nat, jnp.asarray(x))),
            np.asarray(spmv_lib.spmv(p_np, jnp.asarray(x))),
            rtol=2e-4, atol=2e-4)

    def test_none_vals_default_to_one(self, lib):
        from matrel_tpu.ops import spmv as spmv_lib
        import jax.numpy as jnp
        plan = spmv_lib.build_spmv_plan(np.array([3, 3, 9]),
                                        np.array([0, 1, 2]),
                                        n_rows=16, n_cols=4)
        y = np.asarray(spmv_lib.spmv(plan, jnp.ones(4, jnp.float32)))
        assert y[3] == 2.0 and y[9] == 1.0


def test_makefile_sources_match_lazy_builder():
    """native/Makefile and utils/native.py build the SAME source list —
    a Makefile-built .so missing a source loads fine but silently
    drops its symbols (numpy fallback; caught round 3 with
    spmv_plan.cc)."""
    import os
    from matrel_tpu.utils import native
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mk = open(os.path.join(repo, "native", "Makefile")).read()
    srcs_line = next(l for l in mk.splitlines()
                     if l.replace(" ", "").startswith("SRCS:="))
    mk_srcs = sorted(
        tok for tok in srcs_line.split(":=", 1)[1].split()
        if tok.endswith(".cc"))
    assert mk_srcs == sorted(native._SOURCES), (mk_srcs,
                                                native._SOURCES)


def test_layout_dp_native_matches_python(lib, mesh8, monkeypatch):
    """Layout-aware DP equivalence fuzz: random chains, grids AND
    operand layout codes through native matrel_chain_dp_layout vs the
    forced-Python DP — costs must agree (native/chain_dp.cc
    comm_proxy_layout mirrors ir/stats.py exactly)."""
    if not getattr(lib, "_matrel_has_dp_layout", False):
        pytest.skip("native layout DP unavailable")
    import dataclasses
    from jax.sharding import PartitionSpec as P
    from matrel_tpu.core.blockmatrix import BlockMatrix
    specs = {"2d": None, "row": P(("x", "y"), None),
             "col": P(None, ("x", "y")), "rep": P(None, None)}
    base = {name: BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                         mesh=mesh8, spec=sp)
            for name, sp in specs.items()}
    rng = np.random.default_rng(17)
    for _ in range(10):
        n = int(rng.integers(3, 7))
        dims = [int(rng.integers(2, 600)) for _ in range(n + 1)]
        dens = [float(rng.choice([1.0, 1.0, 0.2, 0.02]))
                for _ in range(n)]
        lays = [str(rng.choice(list(specs))) for _ in range(n)]
        grid = tuple(rng.choice([(2, 2), (2, 4), (4, 2)]))
        ops = []
        for i in range(n):
            shape = (dims[i], dims[i + 1])
            nnz = int(dens[i] * shape[0] * shape[1])
            ops.append(leaf(dataclasses.replace(
                base[lays[i]], shape=shape, nnz=nnz)))
        e_nat, c_nat = chain_lib.optimal_order(ops, grid=grid,
                                               mesh=mesh8)
        with monkeypatch.context() as mp:
            mp.setattr(native, "chain_dp", lambda *a, **k: None)
            e_py, c_py = chain_lib.optimal_order(ops, grid=grid,
                                                 mesh=mesh8)
        assert c_nat == pytest.approx(c_py, rel=0.05), (dims, dens,
                                                        lays, grid)


def test_topo_dp_native_matches_python(lib, mesh8, monkeypatch):
    """Topology-weighted DP equivalence fuzz (round 7): random chains,
    grids — INCLUDING the degenerate 1×g / g×1 grids — operand layouts
    AND per-axis weights through native matrel_chain_dp_topo vs the
    forced-Python DP (native/chain_dp.cc split_full_mesh + weighted
    per-axis legs mirror planner._comm_detail exactly)."""
    if not getattr(lib, "_matrel_has_dp_topo", False):
        pytest.skip("native topology DP unavailable")
    import dataclasses
    from jax.sharding import PartitionSpec as P
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core.blockmatrix import BlockMatrix
    specs = {"2d": None, "row": P(("x", "y"), None),
             "col": P(None, ("x", "y")), "rep": P(None, None)}
    base = {name: BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                         mesh=mesh8, spec=sp)
            for name, sp in specs.items()}
    rng = np.random.default_rng(41)
    for _ in range(12):
        n = int(rng.integers(3, 7))
        dims = [int(rng.integers(2, 600)) for _ in range(n + 1)]
        dens = [float(rng.choice([1.0, 1.0, 0.2, 0.02]))
                for _ in range(n)]
        lays = [str(rng.choice(list(specs))) for _ in range(n)]
        grid = tuple(int(v) for v in
                     rng.choice([(2, 2), (2, 4), (4, 2),
                                 (1, 8), (8, 1)]))
        wts = (float(rng.choice([1.0, 2.0, 8.0, 31.5])),
               float(rng.choice([1.0, 4.0, 16.0])))
        cfg = MatrelConfig(axis_cost_weights=wts)
        ops = []
        for i in range(n):
            shape = (dims[i], dims[i + 1])
            nnz = int(dens[i] * shape[0] * shape[1])
            ops.append(leaf(dataclasses.replace(
                base[lays[i]], shape=shape, nnz=nnz)))
        e_nat, c_nat = chain_lib.optimal_order(ops, grid=grid,
                                               mesh=mesh8, config=cfg)
        with monkeypatch.context() as mp:
            mp.setattr(native, "chain_dp", lambda *a, **k: None)
            e_py, c_py = chain_lib.optimal_order(ops, grid=grid,
                                                 mesh=mesh8, config=cfg)
        assert c_nat == pytest.approx(c_py, rel=0.05), (dims, dens,
                                                        lays, grid, wts)


def test_weighted_reshard_closed_forms():
    """Exact closed-form unit checks — one weighted reshard per
    strategy at weights (3, 5) on the (2,4) grid (summa on (2,2)),
    dense 2d operands, alpha 0. Hand-derived from docs/TOPOLOGY.md's
    leg table; any drift in either mirror shows up here first."""
    from matrel_tpu.parallel import planner
    n, k, m = 512, 128, 256
    a = 512 * 128 * 4.0
    b = 128 * 256 * 4.0
    c = 512 * 256 * 4.0
    wts = (3.0, 5.0)
    # bmm_right: B broadcast split min(y-first, x-first) + A reshard
    # along y. y-first: 5*(3b/8) + 3*(b/2); x-first: 3*(b/8) + 5*(3b/4)
    bcast = min(5 * (3 * b / 8) + 3 * (b / 2),
                3 * (b / 8) + 5 * (3 * b / 4))
    want_bmm_r = bcast + 5 * (a / 8) * (3 / 4)
    assert planner.comm_cost("bmm_right", n, k, m, 1.0, 1.0, 2, 4,
                             weights=wts) == pytest.approx(want_bmm_r)
    # bmm_left: A broadcast split + B reshard along x
    bcast_a = min(5 * (3 * a / 8) + 3 * (a / 2),
                  3 * (a / 8) + 5 * (3 * a / 4))
    want_bmm_l = bcast_a + 3 * (b / 8) * (1 / 2)
    assert planner.comm_cost("bmm_left", n, k, m, 1.0, 1.0, 2, 4,
                             weights=wts) == pytest.approx(want_bmm_l)
    # cpmm: B gather along x + C reduce-scatter along y
    want_cpmm = 3 * (b / 4) * (1 / 2) + 5 * (c / 2) * (3 / 4)
    assert planner.comm_cost("cpmm", n, k, m, 1.0, 1.0, 2, 4,
                             weights=wts) == pytest.approx(want_cpmm)
    # rmm: A all-gather along y + B all-gather along x
    want_rmm = 5 * (a / 2) * (3 / 4) + 3 * (b / 4) * (1 / 2)
    assert planner.comm_cost("rmm", n, k, m, 1.0, 1.0, 2, 4,
                             weights=wts) == pytest.approx(want_rmm)
    # summa (2,2): ring of g-1=1 step — A tiles ppermute along y, B
    # tiles along x; 2d inputs re-lay free
    want_summa = 5 * (a / 4) + 3 * (b / 4)
    assert planner.comm_cost("summa", n, k, m, 1.0, 1.0, 2, 2,
                             weights=wts) == pytest.approx(want_summa)
    # row-sharded A re-lay to P(x,y) inside cpmm rides y at wy
    got = planner.comm_cost("cpmm", n, k, m, 1.0, 1.0, 2, 4,
                            a_layout="row", weights=wts)
    assert got == pytest.approx(want_cpmm + 5 * (a / 8) * (3 / 4))
    # opposite-1D join reshard = weighted full-mesh all-to-all split
    want_a2a = min(5 * ((a / 8) * 3 / 8) + 3 * ((a / 8) / 2),
                   3 * ((a / 8) * 1 / 8) + 5 * ((a / 8) * 3 / 4))
    assert planner._reshard_to_axis(a, "col", "row", 2, 4,
                                    weights=wts) == pytest.approx(
        want_a2a)
