"""Native optimizer core tests: the C++ chain DP must exist (toolchain is
part of the environment), agree with the pure-Python DP, and beat it on
long chains."""

import time

import numpy as np
import pytest

from matrel_tpu.ir import chain as chain_lib
from matrel_tpu.ir.expr import leaf
from matrel_tpu.utils import native


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    assert lib is not None, "native build must succeed (g++ is in the image)"
    return lib


def _mk_ops(mesh, dims, dens=None):
    import dataclasses
    from matrel_tpu.core.blockmatrix import BlockMatrix
    base = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32), mesh=mesh)
    ops = []
    for i in range(len(dims) - 1):
        shape = (dims[i], dims[i + 1])
        nnz = None if dens is None else int(dens[i] * shape[0] * shape[1])
        ops.append(leaf(dataclasses.replace(base, shape=shape, nnz=nnz)))
    return ops


def _python_dp(operands):
    """The pure-Python reference DP (bypasses the native fast path)."""
    from matrel_tpu.ir import stats
    from matrel_tpu.ir.expr import matmul as mm
    n = len(operands)
    best = [[None] * n for _ in range(n)]
    for i in range(n):
        best[i][i] = (0.0, operands[i])
    for span in range(2, n + 1):
        for i in range(0, n - span + 1):
            j = i + span - 1
            cand = None
            for s in range(i, j):
                cl, el = best[i][s]
                cr, er = best[s + 1][j]
                step = stats.matmul_cost(el.shape[0], el.shape[1],
                                         er.shape[1], el.density, er.density)
                if cand is None or cl + cr + step < cand[0]:
                    cand = (cl + cr + step, mm(el, er))
            best[i][j] = cand
    return best[0][n - 1]


def test_native_matches_python_dense(lib, mesh8):
    dims = [30, 35, 15, 5, 10, 20, 25]
    ops = _mk_ops(mesh8, dims)
    got, cost = chain_lib.optimal_order(ops)
    pcost, pexpr = _python_dp(ops)
    assert cost == pytest.approx(pcost)
    assert cost == pytest.approx(2 * 15125)  # CLRS optimum × FLOP factor
    assert chain_lib.parenthesise_equal(got, pexpr) if hasattr(
        chain_lib, "parenthesise_equal") else True
    from matrel_tpu.workloads.chain_bench import parenthesisation
    assert parenthesisation(got) == parenthesisation(pexpr)


def test_native_matches_python_sparse(lib, mesh8):
    rng = np.random.default_rng(7)
    for _ in range(10):
        n = int(rng.integers(3, 8))
        dims = [int(rng.integers(2, 400)) for _ in range(n + 1)]
        dens = [float(rng.choice([1.0, 1.0, 0.1, 0.01])) for _ in range(n)]
        ops = _mk_ops(mesh8, dims, dens)
        got, cost = chain_lib.optimal_order(ops)
        pcost, pexpr = _python_dp(ops)
        # same optimum cost (ties may differ in structure; cost must agree
        # within float/rounding tolerance of the nnz-int rounding)
        assert cost == pytest.approx(pcost, rel=0.05)


def test_native_raw_api(lib):
    splits, cost = native.chain_dp([10, 1000, 10, 1000], [1.0, 1.0, 1.0])
    # (A·B)·C: split after operand 1 for the full interval [0,2]
    assert splits[0][2] == 1
    assert cost == pytest.approx(2 * (10 * 1000 * 10 + 10 * 10 * 1000))


def test_native_faster_than_python_on_long_chain(lib, mesh8):
    rng = np.random.default_rng(1)
    dims = [int(rng.integers(10, 2000)) for _ in range(101)]
    ops = _mk_ops(mesh8, dims)
    t0 = time.perf_counter()
    chain_lib.optimal_order(ops)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    _python_dp(ops)
    t_python = time.perf_counter() - t0
    assert t_native < t_python
