"""Bridge server, numerical guards, donation, sparse checkpoint, scipy
ingestion — the remaining SURVEY.md §2/§5 inventory items."""

import numpy as np
import pytest

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix


class TestBridge:
    @pytest.fixture()
    def server(self, mesh8):
        from matrel_tpu.bridge import BridgeServer, BridgeClient
        from matrel_tpu.session import MatrelSession
        srv = BridgeServer(MatrelSession(mesh=mesh8))
        srv.serve_background()
        client = BridgeClient("127.0.0.1", srv.port)
        yield client
        try:
            client.call("shutdown")
        except Exception:
            pass
        client.close()
        srv.server_close()

    def test_upload_query_fetch(self, server):
        server.call("upload", name="A", data=[[1.0, 2.0], [3.0, 4.0]])
        res = server.call("sql", query="transpose(A)", store="B")
        assert res["stored"] == "B" and res["shape"] == [2, 2]
        got = server.call("fetch", name="B")
        np.testing.assert_allclose(got["data"], [[1.0, 3.0], [2.0, 4.0]])

    def test_random_and_tables(self, server):
        server.call("create_random", name="R", shape=[8, 8], seed=1)
        tabs = server.call("tables")["tables"]
        assert tabs["R"] == [8, 8]

    def test_sql_inline_result(self, server):
        server.call("upload", name="X", data=[[2.0, 0.0], [0.0, 2.0]])
        res = server.call("sql", query="trace(X)")
        assert res["data"][0][0] == pytest.approx(4.0)

    def test_error_reported(self, server):
        with pytest.raises(RuntimeError, match="unknown"):
            server.call("sql", query="Nope * X")

    def test_round3_aggregates_and_explain(self, server):
        # round-3 SQL spellings + the physical EXPLAIN over the wire
        server.call("upload", name="M",
                    data=[[1.0, -2.0], [3.0, 4.0]])
        assert server.call("sql", query="max(M)")["data"][0][0] == 4.0
        assert server.call(
            "sql", query="diagmin(M)")["data"][0][0] == 1.0
        plan = server.call("explain", query="rowsum(M * M)")["plan"]
        assert "Optimized plan" in plan and "strategy=" in plan

    def test_joinvalue_streaming_over_bridge(self, server):
        server.call("upload", name="U", data=[[1.0, 2.0]])
        server.call("upload", name="V", data=[[1.5]])
        got = server.call(
            "sql", query="sum(joinvalue(U, V, 'add', 'lt'))")
        # pairs with u < 1.5: (1, 1.5) -> 2.5
        assert got["data"][0][0] == pytest.approx(2.5)



class TestDebugGuards:
    def test_checked_raises_on_nan(self):
        import jax.numpy as jnp
        from matrel_tpu.utils.debug import checked

        f = checked(lambda x: jnp.log(x) * 2.0)
        f(jnp.ones((4,)))  # fine
        with pytest.raises(Exception, match="nan|NaN|inf"):
            f(-jnp.ones((4,)))

    def test_assert_finite(self, mesh8):
        from matrel_tpu.utils.debug import assert_finite
        good = BlockMatrix.from_numpy(np.ones((4, 4), np.float32), mesh=mesh8)
        assert_finite(good)
        bad = BlockMatrix.from_numpy(
            np.array([[1.0, np.inf], [0.0, 1.0]], np.float32), mesh=mesh8)
        with pytest.raises(FloatingPointError):
            assert_finite(bad, "bad")


class TestDonation:
    def test_donated_rerun_matches(self, mesh8, rng):
        from matrel_tpu.executor import compile_expr
        a = rng.standard_normal((16, 16)).astype(np.float32)
        b = rng.standard_normal((16, 16)).astype(np.float32)
        A = BlockMatrix.from_numpy(a, mesh=mesh8)
        B = BlockMatrix.from_numpy(b, mesh=mesh8)
        plan = compile_expr(A.expr().multiply(B.expr()), mesh8)
        a_leaf = plan.leaf_order[0]
        cur = plan.run()
        expect = a @ b
        for _ in range(3):
            cur = plan.run(bindings={a_leaf.uid: cur}, donate=True)
            expect = expect @ b
        np.testing.assert_allclose(cur.to_numpy(), expect, rtol=1e-3,
                                   atol=1e-2)


class TestSparseCheckpointScipy:
    def test_sparse_checkpoint_roundtrip(self, mesh8, tmp_path, rng):
        from matrel_tpu.utils.checkpoint import CheckpointManager
        S = BlockSparseMatrix.random((32, 32), 0.25, block_size=8,
                                     mesh=mesh8, seed=2)
        cm = CheckpointManager(str(tmp_path))
        cm.save(1, sparse={"S": S})
        got = cm.restore_sparse(mesh8)["S"]
        np.testing.assert_allclose(got.to_numpy(), S.to_numpy(), rtol=1e-6)
        assert got.block_size == 8 and got.shape == (32, 32)

    def test_from_scipy(self, mesh8, rng):
        import scipy.sparse as sps
        dense = np.zeros((40, 24), np.float32)
        idx = rng.integers(0, 40, 50), rng.integers(0, 24, 50)
        dense[idx] = rng.standard_normal(50)
        sp = sps.csr_matrix(dense)
        S = BlockSparseMatrix.from_scipy(sp, block_size=8, mesh=mesh8)
        np.testing.assert_allclose(S.to_numpy(), dense, rtol=1e-6)
        # duplicate entries must sum (COO semantics)
        coo = sps.coo_matrix((np.array([1.0, 2.0], np.float32),
                              (np.array([0, 0]), np.array([0, 0]))),
                             shape=(8, 8))
        S2 = BlockSparseMatrix.from_scipy(coo, block_size=8, mesh=mesh8)
        assert S2.to_numpy()[0, 0] == pytest.approx(3.0)
