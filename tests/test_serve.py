"""Serving layer (matrel_tpu/serve/ + session integration): the
cross-query materialized-result cache (structural keying, byte-budgeted
LRU, catalog-rebind invalidation, planner substitution), micro-batched
admission through session.run_many (MultiPlan in the session plan
cache, input-order results, duplicate dedup), the async submit
pipeline's future API, and the off-by-default contracts — cache off
must be bit-identical to the pre-serve behaviour and obs off must emit
nothing."""

import json
import os

import numpy as np
import pytest

from matrel_tpu import executor as executor_lib
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.serve.result_cache import ResultCache
from matrel_tpu.session import MatrelSession, _plan_key

RC = dict(result_cache_max_bytes=64 << 20)


def _mat(rng, n, m, mesh):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh)


def _sess(mesh, **cfg):
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


class TestResultCacheHits:
    def test_repeated_query_answers_from_cache(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        X = _mat(rng, 64, 16, mesh8)
        gram = X.expr().t().multiply(X.expr())
        r1 = sess.run(gram)
        r2 = sess.run(gram)
        # the SAME device-resident result comes back — no compile, no
        # execute (the repeated-dashboard-query fast path)
        assert r2 is r1
        info = sess.result_cache_info()
        assert info["entries"] == 1
        assert info["hits"] == 1

    def test_structurally_identical_fresh_expr_hits(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        X = _mat(rng, 64, 16, mesh8)
        r1 = sess.run(X.expr().t().multiply(X.expr()))
        # a NEW expression tree over the same matrix keys identically
        r2 = sess.run(X.expr().t().multiply(X.expr()))
        assert r2 is r1

    def test_interior_subplan_enters_planning_as_leaf(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        X = _mat(rng, 64, 16, mesh8)
        y = _mat(rng, 64, 1, mesh8)
        gram = X.expr().t().multiply(X.expr())
        sess.run(gram)
        out = sess.run(gram.multiply(X.expr().t().multiply(y.expr())))
        # the compiled plan consumed the cached Gram as a stamped leaf
        plan = list(sess._plan_cache.values())[-1]
        stamps = [l.attrs.get("result_cache")
                  for l in plan.leaf_order
                  if l.attrs.get("result_cache")]
        assert len(stamps) == 1
        assert stamps[0]["layout"] in ("2d", "row", "col", "rep",
                                       "other")
        xn, yn = X.to_numpy(), y.to_numpy()
        want = xn.T @ xn @ (xn.T @ yn)
        np.testing.assert_allclose(out.to_numpy(), want, rtol=3e-4,
                                   atol=3e-4)

    def test_matmul_decisions_record_rc_operands(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        X = _mat(rng, 64, 16, mesh8)
        B = _mat(rng, 16, 16, mesh8)
        gram = X.expr().t().multiply(X.expr())
        sess.run(gram)
        sess.run(gram.multiply(B.expr()))
        plan = list(sess._plan_cache.values())[-1]
        decs = executor_lib.plan_matmul_decisions(plan)
        assert any(d.get("rc_operands") == [True, False] for d in decs)


class TestInvalidation:
    def test_catalog_rebind_invalidates_dependents(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 32, mesh8)
        B = _mat(rng, 32, 32, mesh8)
        sess.register("A", A)
        sess.run(sess.table("A").expr().t().multiply(
            sess.table("A").expr()))
        assert sess.result_cache_info()["entries"] == 1
        sess.register("A", B)          # rebind — old results are stale
        info = sess.result_cache_info()
        assert info["entries"] == 0
        assert info["invalidated"] == 1

    def test_invalidation_cascades_through_derived_entries(self, mesh8,
                                                           rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 16, mesh8)
        C = _mat(rng, 16, 16, mesh8)
        sess.register("A", A)
        gram = A.expr().t().multiply(A.expr())
        sess.run(gram)
        # second query CONSUMES the cached gram (substituted leaf) —
        # its entry's deps must reach back to A, not stop at the
        # cached intermediate
        sess.run(gram.multiply(C.expr()))
        assert sess.result_cache_info()["entries"] == 2
        sess.register("A", C)
        assert sess.result_cache_info()["entries"] == 0

    def test_unrelated_rebind_keeps_entries(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 32, mesh8)
        B = _mat(rng, 32, 32, mesh8)
        sess.register("A", A)
        sess.register("B", B)
        sess.run(A.expr().t().multiply(A.expr()))
        sess.register("B", _mat(rng, 32, 32, mesh8))
        assert sess.result_cache_info()["entries"] == 1

    def test_load_catalog_rebind_invalidates(self, mesh8, rng,
                                             tmp_path):
        # load_catalog overwrites existing names with freshly-restored
        # matrix objects — that is a rebind and must invalidate like
        # register() does
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 32, mesh8)
        sess.register("A", A)
        sess.save_catalog(str(tmp_path))
        sess.run(A.expr().t().multiply(A.expr()))
        assert sess.result_cache_info()["entries"] == 1
        sess.load_catalog(str(tmp_path))
        info = sess.result_cache_info()
        assert info["entries"] == 0
        assert info["invalidated"] == 1

    def test_register_same_object_is_not_a_rebind(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 32, mesh8)
        sess.register("A", A)
        sess.run(A.expr().t().multiply(A.expr()))
        sess.register("A", A)
        assert sess.result_cache_info()["invalidated"] == 0


class TestEviction:
    def test_byte_budget_evicts_lru_order(self, mesh8, rng):
        # each 32x32 f32 result pins 4096 bytes padded; budget holds 2
        sess = _sess(mesh8, result_cache_max_bytes=2 * 32 * 32 * 4)
        mats = [_mat(rng, 32, 32, mesh8) for _ in range(3)]
        qs = [m.expr().t().multiply(m.expr()) for m in mats]
        sess.run(qs[0])
        sess.run(qs[1])
        assert sess.result_cache_info()["entries"] == 2
        sess.run(qs[2])                # evicts qs[0] (LRU)
        info = sess.result_cache_info()
        assert info["entries"] == 2
        assert info["evicted"] == 1
        # qs[0] misses (recomputes; re-inserted, evicting qs[1]);
        # qs[2] — touched most recently before it — still hits
        hits_before = info["hits"]
        sess.run(qs[0])
        assert sess.result_cache_info()["hits"] == hits_before
        sess.run(qs[2])
        assert sess.result_cache_info()["hits"] == hits_before + 1

    def test_hit_refreshes_lru_position(self, mesh8, rng):
        sess = _sess(mesh8, result_cache_max_bytes=2 * 32 * 32 * 4)
        mats = [_mat(rng, 32, 32, mesh8) for _ in range(3)]
        qs = [m.expr().t().multiply(m.expr()) for m in mats]
        r0 = sess.run(qs[0])
        sess.run(qs[1])
        assert sess.run(qs[0]) is r0   # refresh qs[0]
        sess.run(qs[2])                # evicts qs[1], NOT qs[0]
        assert sess.run(qs[0]) is r0   # still cached

    def test_entry_count_bound_caps_pin_retention(self, mesh8, rng):
        # the byte budget counts RESULT bytes only — pins keep the
        # query's inputs alive, so the count bound is what stops tiny
        # results over many ad-hoc inputs retaining unbounded memory
        sess = _sess(mesh8, result_cache_max_bytes=64 << 20,
                     result_cache_max_entries=2)
        mats = [_mat(rng, 32, 32, mesh8) for _ in range(3)]
        for m in mats:
            sess.run(m.expr().t().multiply(m.expr()))
        info = sess.result_cache_info()
        assert info["entries"] == 2
        assert info["evicted"] == 1

    def test_oversized_result_never_inserted(self, mesh8, rng):
        sess = _sess(mesh8, result_cache_max_bytes=64)
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().t().multiply(A.expr()))
        assert sess.result_cache_info()["entries"] == 0


class TestCacheOffBitIdentical:
    def test_default_is_off(self):
        assert MatrelConfig().result_cache_max_bytes == 0

    def test_off_path_never_touches_the_cache(self, mesh8, rng,
                                              monkeypatch):
        # structural guard, the obs-off idiom: with the cache off, the
        # query path may not even CONSULT it
        def boom(*a, **k):
            raise AssertionError("result cache consulted while off")
        monkeypatch.setattr(ResultCache, "lookup", boom)
        monkeypatch.setattr(ResultCache, "probe", boom)
        monkeypatch.setattr(ResultCache, "put", boom)
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().t().multiply(A.expr()))
        sess.run_many([A.expr().t()])

    def test_off_plans_and_results_unchanged(self, mesh8, rng):
        # the compiled plan for a query must be the SAME cache entry /
        # key with the serve layer present-but-off as the pre-serve
        # session produced: no substitution, no key prefix, no extra
        # leaves
        sess = _sess(mesh8)
        X = _mat(rng, 64, 16, mesh8)
        e = X.expr().t().multiply(X.expr())
        key, _ = _plan_key(e)
        plan, hit, got_key = sess._compile_entry(e)
        assert got_key == key
        assert all(l.attrs.get("result_cache") is None
                   for l in plan.leaf_order)
        out = sess.run(e)
        xn = X.to_numpy()
        np.testing.assert_allclose(out.to_numpy(), xn.T @ xn,
                                   rtol=3e-4, atol=3e-4)

    def test_cached_results_match_uncached(self, mesh8, rng):
        X = _mat(rng, 64, 16, mesh8)
        y = _mat(rng, 64, 1, mesh8)
        gram = X.expr().t().multiply(X.expr())
        q2 = gram.multiply(X.expr().t().multiply(y.expr()))
        on = _sess(mesh8, **RC)
        off = _sess(mesh8)
        for q in (gram, q2, gram, q2):
            a = on.run(q).to_numpy()
            b = off.run(q).to_numpy()
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestRunMany:
    def test_matches_sequential(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 16, mesh8)
        B = _mat(rng, 16, 32, mesh8)
        qs = [A.expr().multiply(B.expr()),
              A.expr().t(),
              B.expr().multiply(A.expr()).multiply_scalar(2.0)]
        batch = sess.run_many(qs)
        seq = [_sess(mesh8).run(q) for q in qs]
        for got, want in zip(batch, seq):
            np.testing.assert_allclose(got.to_numpy(), want.to_numpy(),
                                       rtol=1e-5, atol=1e-5)

    def test_duplicate_roots_dedupe_into_one_program(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        q = A.expr().t().multiply(A.expr())
        outs = sess.run_many([q, q, q])
        assert sess.plan_cache_info()["plans"] == 1
        for o in outs[1:]:
            np.testing.assert_array_equal(o.to_numpy(),
                                          outs[0].to_numpy())

    def test_multiplan_participates_in_plan_cache(self, mesh8, rng,
                                                  monkeypatch):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 16, mesh8)
        B = _mat(rng, 16, 32, mesh8)
        qs = [A.expr().multiply(B.expr()), A.expr().t()]
        sess.run_many(qs)
        assert sess.plan_cache_info()["plans"] == 1
        calls = []
        orig = executor_lib.compile_exprs
        monkeypatch.setattr(executor_lib, "compile_exprs",
                            lambda *a, **k: calls.append(1)
                            or orig(*a, **k))
        sess.run_many(qs)                  # same batch: pure hit
        sess.run_many(list(reversed(qs)))  # permuted: still a hit
        assert calls == []
        assert sess.plan_cache_info()["plans"] == 1

    def test_permuted_batch_results_keep_input_order(self, mesh8, rng):
        sess = _sess(mesh8)
        A = _mat(rng, 32, 16, mesh8)
        B = _mat(rng, 16, 32, mesh8)
        q1 = A.expr().multiply(B.expr())        # 32x32
        q2 = B.expr().multiply(A.expr())        # 16x16
        o1, o2 = sess.run_many([q1, q2])
        p2, p1 = sess.run_many([q2, q1])
        assert o1.shape == (32, 32) and o2.shape == (16, 16)
        np.testing.assert_array_equal(o1.to_numpy(), p1.to_numpy())
        np.testing.assert_array_equal(o2.to_numpy(), p2.to_numpy())

    def test_batch_with_result_cache(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 32, mesh8)
        q = A.expr().t().multiply(A.expr())
        first = sess.run_many([q, q.multiply_scalar(2.0)])
        again = sess.run_many([q, q.multiply_scalar(2.0)])
        assert again[0] is first[0]
        assert again[1] is first[1]

    def test_empty_batch(self, mesh8):
        assert _sess(mesh8).run_many([]) == []


class TestMultiPlanParity:
    def test_donate_rebound_leaves(self, mesh8, rng):
        # MultiPlan.run(donate=True) — the CompiledPlan parity fix
        A = _mat(rng, 32, 32, mesh8)
        B = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(B.expr())
        plan = executor_lib.compile_exprs([e], mesh8,
                                          MatrelConfig())
        a_leaf = plan.leaf_order[0]
        fresh = _mat(rng, 32, 32, mesh8)
        # read the donated operand BEFORE running: donation hands its
        # buffer to XLA (that being impossible afterwards is the point)
        want = fresh.to_numpy() @ B.to_numpy()
        (out,) = plan.run(bindings={a_leaf.uid: fresh}, donate=True)
        np.testing.assert_allclose(out.to_numpy(), want, rtol=1e-5,
                                   atol=1e-5)

    def test_multiplan_byte_accounting_in_session_cache(self, mesh8,
                                                        rng):
        # a MultiPlan with hoisted sparse payloads must be accounted
        # (and evictable) by the session byte budget like single plans
        from matrel_tpu.core.coo import COOMatrix
        sess = _sess(mesh8, plan_cache_max_bytes=1,
                     plan_cache_max_plans=64)
        x = _mat(rng, 2000, 2, mesh8)
        rows = rng.integers(0, 2000, 600_000)
        cols = rng.integers(0, 2000, 600_000)
        S = COOMatrix.from_edges(rows, cols, shape=(2000, 2000))
        sess.run_many([S.expr().multiply(x.expr())])
        assert sess.plan_cache_info()["plans"] == 1  # sole-plan guard
        sess.run_many([S.expr().multiply(x.expr()).multiply_scalar(2.0)])
        # over the 1-byte budget: the older MultiPlan evicted
        assert sess.plan_cache_info()["plans"] == 1
        assert sess.plan_cache_info()["evicted"] >= 1


class TestFutures:
    def test_submit_result_matches_compute(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 16, mesh8)
        fut = sess.submit(A.expr().t().multiply(A.expr()))
        out = fut.result(timeout=120)
        an = A.to_numpy()
        np.testing.assert_allclose(out.to_numpy(), an.T @ an,
                                   rtol=3e-4, atol=3e-4)
        sess.serve_drain()

    def test_submit_many_all_resolve(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        A = _mat(rng, 32, 32, mesh8)
        qs = [A.expr().multiply_scalar(float(s)) for s in range(6)]
        futs = [sess.submit(q) for q in qs]
        sess.serve_drain()
        an = A.to_numpy()
        for s, f in enumerate(futs):
            np.testing.assert_allclose(f.result(timeout=120).to_numpy(),
                                       an * s, rtol=1e-5, atol=1e-5)

    def test_cancelled_future_does_not_kill_worker(self, mesh8, rng):
        # a future cancelled while queued must drop out of its batch;
        # set_result on it would raise InvalidStateError, kill the
        # admission worker, and strand every sibling future
        import time as time_mod
        from matrel_tpu.serve.pipeline import ServePipeline
        sess = _sess(mesh8, **RC)
        pl = ServePipeline(sess)
        A = _mat(rng, 32, 32, mesh8)
        from concurrent.futures import Future
        f_cancel, f_ok = Future(), Future()
        # enqueue BOTH before the worker exists, so the cancel is
        # deterministic (still pending when the batch is admitted)
        pl._q.put((A.expr().t(), f_cancel, time_mod.perf_counter()))
        pl._q.put((A.expr().multiply_scalar(2.0), f_ok,
                   time_mod.perf_counter()))
        assert f_cancel.cancel()
        pl._ensure_worker()
        out = f_ok.result(timeout=120)
        np.testing.assert_allclose(out.to_numpy(), 2 * A.to_numpy(),
                                   rtol=1e-6, atol=1e-6)
        assert f_cancel.cancelled()
        pl.drain()
        assert pl._worker.is_alive()

    def test_submit_exception_propagates(self, mesh8, rng):
        # a query whose lowering REFUSES (join pair cap) must fail its
        # future with the original error, not hang or kill the worker
        sess = _sess(mesh8, join_pair_cap_entries=4)
        A = _mat(rng, 32, 1, mesh8)
        B = _mat(rng, 32, 1, mesh8)
        bad = A.expr().join_on_value(B.expr(), merge="add")
        fut = sess.submit(bad)
        with pytest.raises(ValueError, match="join_pair_cap_entries"):
            fut.result(timeout=120)
        # the worker survived: a healthy query still serves
        ok = sess.submit(A.expr().t())
        np.testing.assert_allclose(ok.result(timeout=120).to_numpy(),
                                   A.to_numpy().T, rtol=1e-6,
                                   atol=1e-6)
        sess.serve_drain()


class TestServeObservability:
    def _events(self, path):
        with open(path) as f:
            return [json.loads(l) for l in f if l.strip()]

    def test_run_many_emits_per_root_query_and_serve_events(
            self, mesh8, rng, tmp_path):
        log = str(tmp_path / "events.jsonl")
        sess = _sess(mesh8, obs_level="on", obs_event_log=log, **RC)
        A = _mat(rng, 32, 16, mesh8)
        B = _mat(rng, 16, 32, mesh8)
        qs = [A.expr().multiply(B.expr()), A.expr().t(),
              B.expr().t()]
        sess.run_many(qs)
        events = self._events(log)
        queries = [e for e in events if e["kind"] == "query"]
        serves = [e for e in events if e["kind"] == "serve"]
        assert len(queries) == 3           # one per ROOT — the
        assert len(serves) == 1            # MultiPlan obs parity fix
        assert serves[0]["batch_size"] == 3
        assert serves[0]["executed"] == 3
        assert serves[0]["rc_hits"] == 0
        assert "result_cache" in serves[0]
        assert serves[0]["result_cache"]["entries"] == 3
        for q in queries:
            assert q["batch"]["size"] == 3
            assert isinstance(q["matmuls"], list)
        # matmul decisions are PER ROOT, not the batch aggregate
        assert sum(len(q["matmuls"]) for q in queries) == 1
        # rewrite-rule hits attributed once, not once per root
        assert sum(1 for q in queries if q["rule_hits"]) <= 1

    def test_rc_hit_emits_query_event(self, mesh8, rng, tmp_path):
        log = str(tmp_path / "events.jsonl")
        sess = _sess(mesh8, obs_level="on", obs_event_log=log, **RC)
        A = _mat(rng, 32, 32, mesh8)
        q = A.expr().t().multiply(A.expr())
        sess.run(q)
        sess.run(q)
        queries = [e for e in self._events(log)
                   if e["kind"] == "query"]
        assert [e["cache"] for e in queries] == ["miss", "rc_hit"]
        assert queries[1]["matmuls"] == []

    def test_serve_events_roll_up_in_history_summary(self, mesh8, rng,
                                                     tmp_path):
        from matrel_tpu.obs import history
        from matrel_tpu.obs.events import read_events
        log = str(tmp_path / "events.jsonl")
        sess = _sess(mesh8, obs_level="on", obs_event_log=log, **RC)
        A = _mat(rng, 32, 32, mesh8)
        q = A.expr().t().multiply(A.expr())
        sess.run_many([q, q.multiply_scalar(2.0)])
        sess.run_many([q, q.multiply_scalar(2.0)])
        events = read_events(log)
        s = history.summarize(events)
        assert s["serve"]["batches"] == 2
        assert s["serve"]["queries"] == 4
        assert s["serve"]["qps"] is not None and s["serve"]["qps"] > 0
        assert s["serve"]["rc_hit_ratio"] == 0.5
        text = history.render_summary(events)
        assert "serve:" in text and "QPS" in text

    def test_summary_hit_ratio_sums_per_record_deltas(self):
        # the ratio must come from each record's OWN rc_hits/batch_size,
        # not the last record's cumulative session-lifetime counters —
        # a multi-session log would otherwise report only the final
        # session's cache behaviour
        from matrel_tpu.obs import history
        events = [
            {"kind": "serve", "batch_size": 10, "rc_hits": 9,
             "wall_ms": 5.0, "result_cache": {"hits": 900,
                                              "misses": 100}},
            {"kind": "serve", "batch_size": 10, "rc_hits": 0,
             "wall_ms": 5.0, "result_cache": {"hits": 0,
                                              "misses": 10}},
        ]
        s = history.summarize(events)
        assert s["serve"]["rc_hit_ratio"] == 0.45

    def test_obs_off_emits_nothing(self, mesh8, rng, tmp_path):
        log = str(tmp_path / "events.jsonl")
        os.environ.pop("MATREL_OBS_EVENT_LOG", None)
        sess = _sess(mesh8, obs_event_log=log, **RC)
        A = _mat(rng, 32, 32, mesh8)
        q = A.expr().t().multiply(A.expr())
        sess.run_many([q, q])
        sess.run(q)
        fut = sess.submit(q.multiply_scalar(2.0))
        fut.result(timeout=120)
        sess.serve_drain()
        assert not os.path.exists(log)


class TestResultCacheInfoSurface:
    def test_info_fields(self, mesh8, rng):
        sess = _sess(mesh8, **RC)
        info = sess.result_cache_info()
        assert set(info) == {"entries", "bytes", "hits", "misses",
                             "interior_hits", "evicted", "invalidated",
                             "stale_entries", "stale_bytes",
                             "stale_hits", "max_bytes", "max_entries",
                             "patched", "rekeyed"}
        assert info["max_bytes"] == RC["result_cache_max_bytes"]
        assert info["max_entries"] == 256

    def test_config_validates_serve_knobs(self):
        with pytest.raises(ValueError, match="serve_max_batch"):
            MatrelConfig(serve_max_batch=0)
        with pytest.raises(ValueError, match="serve_max_inflight"):
            MatrelConfig(serve_max_inflight=0)
